"""Synthetic tile specs for the serving layer.

The daemon's chaos/bench harness needs tiles that are deterministic,
raster-free and CPU-cheap — the serving twin of
``cli.run_synthetic``.  Observation draws are seeded per (tile, date)
by ``SyntheticObservations``, so an incremental serve, a cold rerun and
a crash-replayed serve all see identical inputs.

Numerics are chosen for EXACT warm-resume parity on CPU: the diagonal
information propagator (``propagate_information_filter_approx``) keeps
the per-pixel information matrix exactly diagonal, and the identity /
two-stream operators add exactly-symmetric ``J^T R^-1 J`` terms — so
the packed-triangle checkpoint roundtrip is bit-exact and the
incremental serve path reproduces a cold full-series rerun to the bit
(the tier-1 warm-parity acceptance test pins this).
"""

from __future__ import annotations

import datetime
from typing import List, Optional

import numpy as np

from ..core.propagators import propagate_information_filter_approx
from ..engine import KalmanFilter
from ..testing.fixtures import make_pivot_mask
from ..testing.synthetic import MemoryOutput, SyntheticObservations
from .session import TileSpec

DEFAULT_BASE_DATE = datetime.datetime(2017, 7, 1)


def synthetic_dates(base: datetime.datetime, days: int,
                    obs_every: int) -> List[datetime.datetime]:
    """The tile's observation calendar (run_synthetic's convention)."""
    return [base + datetime.timedelta(days=d)
            for d in range(1, days, obs_every)]


def make_synthetic_tile(
    name: str,
    ckpt_dir: str,
    operator: str = "identity",
    ny: int = 20,
    nx: int = 20,
    days: int = 16,
    step_days: int = 4,
    obs_every: int = 2,
    sigma: Optional[float] = None,
    scan_window: int = 1,
    seed: int = 0,
    base_date: datetime.datetime = DEFAULT_BASE_DATE,
) -> TileSpec:
    """One deterministic synthetic tile for the serving daemon.

    ``scan_window=1`` (the default) keeps the unfused per-window path —
    the bit-exact serving configuration; higher values opt into temporal
    scan fusion (parity within the established fused budget).
    """
    from ..cli.run_synthetic import build_operator

    op, params, prior, truth_val, aux_fn, op_sigma = build_operator(
        operator, None
    )
    sigma = op_sigma if sigma is None else sigma
    mask = make_pivot_mask(ny, nx, seed=seed)
    truth = np.broadcast_to(
        truth_val, mask.shape + (len(truth_val),)
    ).astype(np.float32)
    dates = synthetic_dates(base_date, days, obs_every)

    def make_filter():
        obs = SyntheticObservations(
            dates=dates, operator=op,
            truth_fn=lambda date: truth, sigma=sigma, aux_fn=aux_fn,
            mask_prob=0.1, seed=seed,
        )
        output = MemoryOutput()
        kf = KalmanFilter(
            obs, output, mask, params,
            state_propagation=propagate_information_filter_approx,
            prior=None,
            solver_options={"relaxation": 0.5},
            scan_window=scan_window,
        )
        kf.set_trajectory_model()
        kf.set_trajectory_uncertainty(
            np.full(len(params), 1e-3, np.float32)
        )
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        return kf, x0, p_inv0, output

    return TileSpec(
        name=name, make_filter=make_filter, base_date=base_date,
        step_days=step_days, ckpt_dir=ckpt_dir,
    )
