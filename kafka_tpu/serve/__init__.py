"""Assimilation-as-a-service: the crash-safe warm-state serving layer.

All six CLI drivers are batch one-shots; this package is the resident
front end the ROADMAP's "millions of users" item calls for — request
queue -> admission control -> incremental warm-state solve -> result
cache, exposed by the ``kafka-serve`` daemon (``cli.kafka_serve``) and
measured by ``tools/loadgen.py``.  See BASELINE.md "Serving".
"""

from .admission import AdmissionController, AdmissionPolicy
from .daemon import ServeDaemon, read_response, submit_request
from .journal import RequestJournal
from .request import BadRequest, ServeRequest, parse_request
from .router import HashRing, RoutePolicy, TileRouter, stable_hash
from .service import AssimilationService
from .session import TileSession, TileSpec, UnknownDateError
from .synthetic import make_synthetic_tile, synthetic_dates

__all__ = [
    "AdmissionController",
    "AdmissionPolicy",
    "AssimilationService",
    "BadRequest",
    "HashRing",
    "RequestJournal",
    "RoutePolicy",
    "ServeDaemon",
    "ServeRequest",
    "TileRouter",
    "TileSession",
    "TileSpec",
    "UnknownDateError",
    "make_synthetic_tile",
    "parse_request",
    "read_response",
    "stable_hash",
    "submit_request",
    "synthetic_dates",
]
