"""Admission control and load shedding for the serving daemon.

Overload must degrade to FAST REJECTION, not queue collapse: a queue
that admits everything turns a 2x overload into unbounded latency for
every request (and unbounded host memory), while a bounded queue plus
cheap up-front rejection keeps the admitted requests' latency flat and
gives the shed requests an immediate, explicit answer they can retry
against another replica.

The controller reads the PR 2/3 telemetry gauges as its load signals —
the SAME single-source-of-truth registry the bench health layer and the
Prometheus export read:

=============================== =====================================
``kafka_serve_queue_depth``     requests admitted but not yet served
                                (the primary signal; compared against
                                ``max_queue_depth``)
``kafka_prefetch_queue_depth``  prefetched-but-unconsumed observation
                                dates (host memory held by the input
                                pipeline)
``kafka_io_writer_backlog``     queued async GeoTIFF writes (host
                                memory + disk pressure on the output
                                side)
``kafka_health_unhealthy``      the latest ``probe_health`` verdict —
                                an off-band host serves garbage
                                latency, so shedding beats queueing
``kafka_fleet_dead_hosts``      dead workers in the fleet view (the
                                daemon refreshes it from the live
                                snapshots, ``telemetry.aggregate``) —
                                a degraded fleet sheds load instead of
                                queueing work the dead capacity was
                                meant to absorb
``kafka_quality_drift_active``  per-(tile, band) chi^2-ratio series in
                                a drift-sentinel alarm
                                (``telemetry.quality``) — a
                                statistically inconsistent filter is
                                serving wrong uncertainties, and an
                                operator may prefer explicit rejection
                                (reason ``quality_degraded``) over
                                quietly shipping them
``kafka_slo_alerts_firing``     PAGE-severity SLO alerts currently
                                firing (``telemetry.slo`` burn-rate
                                rules) — a service burning its error
                                budget catastrophically can shed
                                (reason ``slo_burn``) to stop the
                                burn at the front door
=============================== =====================================

Every decision is explicit: admitted requests count into
``kafka_serve_admitted_total``, shed requests into
``kafka_serve_rejected_total`` labelled by reason — overload is an
operator-visible number, never a silent drop.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..telemetry import get_registry


@dataclasses.dataclass(frozen=True)
class AdmissionPolicy:
    """The rejected-vs-queued contract, as data.

    ``max_queue_depth`` bounds the service's own request queue (the
    explicit queue-or-reject line).  The two pipeline bounds shed load
    when the engine's host-side buffers back up; ``None`` disables a
    signal.  ``shed_when_unhealthy`` rejects while the latest health
    probe verdict is off-band.
    """

    max_queue_depth: int = 16
    max_prefetch_queue_depth: Optional[int] = 256
    max_writer_backlog: Optional[int] = 256
    shed_when_unhealthy: bool = True
    #: shed (reason ``fleet_degraded``) while the fleet view counts more
    #: dead hosts than this; None disables the signal (the default — it
    #: only means something when the daemon refreshes the fleet gauge).
    max_dead_hosts: Optional[int] = None
    #: shed (reason ``quality_degraded``) while any quality drift
    #: sentinel is alarming (``kafka_quality_drift_active`` > 0).  Off
    #: by default: most operators want degraded answers SERVED and
    #: labelled (the response's ``quality`` field), not refused.
    shed_on_quality_drift: bool = False
    #: shed (reason ``slo_burn``) while any PAGE-severity SLO alert is
    #: firing (``kafka_slo_alerts_firing{severity="page"}`` > 0,
    #: ``telemetry.slo``).  Off by default (opt in via
    #: ``kafka-serve --shed-slo``): shedding on an availability burn
    #: is itself more rejections, so the operator chooses whether the
    #: front door amplifies or absorbs.
    shed_on_slo: bool = False
    #: backoff hint attached to LOAD-STATE rejections (queue_full,
    #: draining, fleet_degraded, ...): clients that honor it
    #: (tools/loadgen, the kafka-route front door) wait instead of
    #: hammering a shedding replica.  Request-shaped rejections
    #: (bad_request, unknown_tile) never carry it — retrying cannot
    #: make a bad request good.
    retry_after_s: float = 0.5


#: rejection reasons that describe the SERVER's state, not the
#: request's — the ones a client should back off and retry (possibly
#: against another replica).
RETRYABLE_REASONS = frozenset({
    "queue_full", "prefetch_backlog", "writer_backlog", "unhealthy",
    "fleet_degraded", "quality_degraded", "slo_burn", "draining",
})


class AdmissionController:
    """Decides admit-vs-shed for one request; stateless between calls
    (all state lives in the telemetry registry it reads)."""

    def __init__(self, policy: Optional[AdmissionPolicy] = None):
        self.policy = policy or AdmissionPolicy()

    def retry_after(self, reason: str) -> Optional[float]:
        """The backoff hint for one rejection reason — the policy's
        ``retry_after_s`` for load-state rejections, None for
        request-shaped ones."""
        if reason in RETRYABLE_REASONS:
            return self.policy.retry_after_s
        return None

    def decide(self, queue_depth: int) -> Optional[str]:
        """``None`` to admit, else the rejection reason (a short token
        that labels ``kafka_serve_rejected_total``)."""
        pol = self.policy
        if queue_depth >= pol.max_queue_depth:
            return "queue_full"
        reg = get_registry()
        if pol.max_prefetch_queue_depth is not None:
            depth = reg.value("kafka_prefetch_queue_depth")
            if depth is not None and depth > pol.max_prefetch_queue_depth:
                return "prefetch_backlog"
        if pol.max_writer_backlog is not None:
            backlog = reg.value("kafka_io_writer_backlog")
            if backlog is not None and backlog > pol.max_writer_backlog:
                return "writer_backlog"
        if pol.shed_when_unhealthy:
            # The shared sampling path (telemetry.health.latest_verdict):
            # the gauges probe_health maintains, no probing here.
            from ..telemetry.health import latest_verdict

            if latest_verdict(reg)["unhealthy"]:
                return "unhealthy"
        if pol.max_dead_hosts is not None:
            dead = reg.value("kafka_fleet_dead_hosts")
            if dead is not None and dead > pol.max_dead_hosts:
                return "fleet_degraded"
        if pol.shed_on_quality_drift:
            drifting = reg.value("kafka_quality_drift_active")
            if drifting:
                return "quality_degraded"
        if pol.shed_on_slo:
            firing = reg.value(
                "kafka_slo_alerts_firing", severity="page"
            )
            if firing:
                return "slo_burn"
        return None
