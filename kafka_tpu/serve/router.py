"""Consistent-hash tile routing across an elastic kafka-serve fleet.

One ``kafka-serve`` daemon (PR 8) is a single host's throughput and a
single point of failure.  This module is the front door that turns N
daemons into ONE serving surface:

- :func:`stable_hash` / :class:`HashRing` — the tile keyspace is
  partitioned by a consistent-hash ring over STABLE digests
  (``hashlib.blake2b``), never Python's builtin ``hash()`` (per-process
  salted: two routers would disagree about every tile) and never
  ``random`` (kafkalint rule 16 ``nondeterministic-placement`` bans
  both outside this module).  Each replica owns ``vnodes`` points on
  the ring; a tile belongs to the first replica point at or clockwise
  of its digest.  Adding or removing a replica moves ONLY the ring
  segments adjacent to its points — the minimal-movement property the
  rebalance test pins.

- :class:`TileRouter` — the routing daemon.  Same wire as the replicas
  (the shared filesystem): clients drop requests into the ROUTER's
  ``inbox/`` and read the ROUTER's ``responses/``; the router journals
  every admitted request (``requests.jsonl``, the PR 8 discipline:
  durable before forward, so a router crash replays un-answered
  requests on restart), forwards it into the owning replica's inbox,
  and relays the replica's response back.  Because every replica
  resumes tiles from the SHARED checkpoint set, re-routing a tile is
  warm-state migration for free: the new owner picks up from the bytes
  the old owner checkpointed.

- **Fleet-aware failover** — the router watches the PR 10 live
  snapshots under ``fleet_dir``: a replica whose heartbeat went stale
  without a ``final`` marker is DEAD (flagged within one heartbeat
  TTL); a replica whose ``kafka_serve_rejected_total{reason=
  "queue_full"}`` counter is climbing or whose queue-depth gauge is
  over the policy bound is SHEDDING (deprioritised, not excluded).
  Dead replicas are dropped from the ring view (ownership rebalances
  to the survivors), their in-flight requests are re-forwarded to the
  next owner, and a replica answering ``rejected: queue_full`` gets
  the same treatment reactively even with no fleet dir at all.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import json
import logging
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..resilience import faults
from ..telemetry import get_registry, live, request_log, tracing
from ..telemetry.tracing import trace_span
from .daemon import INBOX_DIR, _install_drain, _restore_drain, \
    read_response, submit_request
from .journal import RequestJournal
from .request import BadRequest, parse_request

LOG = logging.getLogger(__name__)

#: ring points per replica — enough that ownership splits evenly across
#: a handful of replicas without making ring rebuilds expensive.
DEFAULT_VNODES = 64

#: replica-side rejection reasons worth retrying SOMEWHERE ELSE — they
#: describe the replica's state, not the request's.  Anything else
#: (bad_request, unknown_tile, ...) is terminal and relayed as-is.
RETRYABLE_REJECTIONS = frozenset({
    "queue_full", "prefetch_backlog", "writer_backlog", "unhealthy",
    "fleet_degraded", "quality_degraded", "slo_burn", "draining",
})


def stable_hash(text: str) -> int:
    """64-bit digest of ``text``, identical in every process on every
    host — the ONE sanctioned hash for placement decisions (builtin
    ``hash()`` is salted per process and would shred ring agreement)."""
    digest = hashlib.blake2b(text.encode("utf-8"), digest_size=8)
    return int.from_bytes(digest.digest(), "big")


class HashRing:
    """Consistent-hash ring: ``vnodes`` points per replica, tiles owned
    by the first point at or clockwise of their digest."""

    def __init__(self, replicas: Iterable[str] = (),
                 vnodes: int = DEFAULT_VNODES):
        self.vnodes = int(vnodes)
        self._replicas: List[str] = []
        #: sorted parallel arrays: point digest -> owning replica.
        self._points: List[int] = []
        self._owners: List[str] = []
        for rid in replicas:
            self.add(rid)

    @property
    def replicas(self) -> List[str]:
        return sorted(self._replicas)

    def __len__(self) -> int:
        return len(self._replicas)

    def __contains__(self, replica_id: str) -> bool:
        return replica_id in self._replicas

    def _rebuild(self) -> None:
        pts: List[Tuple[int, str]] = []
        for rid in self._replicas:
            for v in range(self.vnodes):
                pts.append((stable_hash(f"{rid}#{v}"), rid))
        pts.sort()
        self._points = [p for p, _ in pts]
        self._owners = [r for _, r in pts]

    def add(self, replica_id: str) -> None:
        if replica_id in self._replicas:
            return
        self._replicas.append(replica_id)
        self._rebuild()

    def remove(self, replica_id: str) -> None:
        if replica_id not in self._replicas:
            return
        self._replicas.remove(replica_id)
        self._rebuild()

    def preference(self, tile: str) -> List[str]:
        """Every replica in ring-walk order from the tile's digest —
        element 0 is the owner, the rest are the failover order."""
        if not self._points:
            return []
        start = bisect.bisect_left(self._points, stable_hash(tile))
        seen: List[str] = []
        n = len(self._points)
        for i in range(n):
            rid = self._owners[(start + i) % n]
            if rid not in seen:
                seen.append(rid)
                if len(seen) == len(self._replicas):
                    break
        return seen

    def owner(self, tile: str,
              exclude: Iterable[str] = ()) -> Optional[str]:
        """The tile's owner, skipping ``exclude`` along the ring walk."""
        excluded = set(exclude)
        for rid in self.preference(tile):
            if rid not in excluded:
                return rid
        return None

    def assignments(self, tiles: Iterable[str]) -> Dict[str, List[str]]:
        """``replica -> sorted tiles owned`` over the given tile set."""
        out: Dict[str, List[str]] = {rid: [] for rid in self._replicas}
        for tile in tiles:
            rid = self.owner(tile)
            if rid is not None:
                out[rid].append(tile)
        return {rid: sorted(ts) for rid, ts in out.items()}


@dataclasses.dataclass(frozen=True)
class RoutePolicy:
    """The routing contract, as data.

    ``ttl_s`` overrides the dead-replica heartbeat TTL (default: 3x
    each snapshot's own publish interval, the fleet-view convention);
    ``refresh_s`` throttles fleet-view reads; ``max_queue_depth``
    deprioritises replicas whose live queue-depth gauge is at or past
    the bound; ``shed_backoff_s`` is how long a replica observed
    shedding (counter climb or an actual ``queue_full`` answer) stays
    deprioritised; ``retry_after_s`` rides router-level rejections as
    the client backoff hint.
    """

    vnodes: int = DEFAULT_VNODES
    refresh_s: float = 1.0
    ttl_s: Optional[float] = None
    max_queue_depth: Optional[int] = None
    shed_backoff_s: float = 2.0
    retry_after_s: float = 0.5
    #: shed new submissions (reason ``slo_burn``) while any
    #: PAGE-severity SLO alert fires on the ROUTER's own registry
    #: (``kafka_slo_alerts_firing{severity="page"}``,
    #: ``telemetry.slo``) — the fleet front door's opt-in version of
    #: ``AdmissionPolicy.shed_on_slo`` (``kafka-route --shed-slo``).
    shed_on_slo: bool = False


class FleetWatch:
    """Per-replica liveness/load view derived from the PR 10 live
    snapshots (``live_<host>_<pid>.json`` under ``fleet_dir``), matched
    to replicas by the ``serve_root`` status fact every kafka-serve
    publishes.  With no fleet dir every replica reads as routable —
    the reactive rejection path still covers shedding."""

    #: the live-snapshot counter tag of queue_full shed rejections.
    SHED_TAG = 'kafka_serve_rejected_total{reason="queue_full"}'
    DEPTH_TAG = "kafka_serve_queue_depth"

    def __init__(self, fleet_dir: Optional[str],
                 replica_roots: Dict[str, str],
                 policy: RoutePolicy):
        self.fleet_dir = fleet_dir
        self.policy = policy
        self._root_to_rid = {
            os.path.abspath(root): rid
            for rid, root in replica_roots.items()
        }
        self._shed_seen: Dict[str, float] = {}
        self._shed_until: Dict[str, float] = {}

    def note_shedding(self, replica_id: str,
                      now: Optional[float] = None) -> None:
        """Reactive signal: the replica just ANSWERED a retryable
        rejection — deprioritise it for ``shed_backoff_s``."""
        now = time.monotonic() if now is None else now
        self._shed_until[replica_id] = now + self.policy.shed_backoff_s

    def shedding(self, replica_id: str,
                 now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        return now < self._shed_until.get(replica_id, 0.0)

    def refresh(self) -> Dict[str, dict]:
        """``replica_id -> {"dead", "final", "queue_depth"}`` for every
        replica a snapshot was found for (absent = no signal yet)."""
        if not self.fleet_dir:
            return {}
        from ..telemetry.aggregate import load_live_snapshots

        now_wall = time.time()
        now_mono = time.monotonic()
        newest: Dict[str, dict] = {}
        for snap in load_live_snapshots(self.fleet_dir):
            root = (snap.get("status") or {}).get("serve_root")
            rid = self._root_to_rid.get(os.path.abspath(root)) \
                if root else None
            if rid is None:
                continue
            if rid not in newest or snap.get("ts", 0) > \
                    newest[rid].get("ts", 0):
                newest[rid] = snap
        view: Dict[str, dict] = {}
        for rid, snap in newest.items():
            ttl = self.policy.ttl_s if self.policy.ttl_s is not None \
                else 3.0 * float(snap.get("interval_s") or 2.0)
            stale = (now_wall - float(snap.get("ts", 0))) > ttl
            final = bool(snap.get("final"))
            shed_count = float(
                (snap.get("counters") or {}).get(self.SHED_TAG, 0.0)
            )
            if rid in self._shed_seen and \
                    shed_count > self._shed_seen[rid]:
                # The replica shed queue_full load since the last look:
                # deprioritise it for a backoff window.  (The first
                # sighting is the baseline, not a climb — a counter's
                # absolute value is history, its delta is load.)
                self.note_shedding(rid, now=now_mono)
            self._shed_seen[rid] = shed_count
            view[rid] = {
                "dead": stale and not final,
                "final": final,
                "queue_depth": (snap.get("gauges") or {}).get(
                    self.DEPTH_TAG
                ),
            }
        return view


def _route_metrics(reg):
    """Single registration site for the router's metric vocabulary."""
    return {
        "forwarded": reg.counter(
            "kafka_route_forwarded_total",
            "requests forwarded into a replica inbox, labelled by "
            "replica",
        ),
        "relayed": reg.counter(
            "kafka_route_relayed_total",
            "replica responses relayed back to the router's response "
            "store",
        ),
        "rerouted": reg.counter(
            "kafka_route_rerouted_total",
            "in-flight requests re-forwarded to another replica, "
            "labelled by reason (dead / rejected)",
        ),
        "rejected": reg.counter(
            "kafka_route_rejected_total",
            "requests the router itself rejected, labelled by reason "
            "(bad_request / fleet_degraded / draining)",
        ),
        "rebalanced": reg.counter(
            "kafka_route_rebalanced_total",
            "ring-ownership rebalances (the routable replica set "
            "changed: a replica joined, left, died or recovered)",
        ),
        "replayed": reg.counter(
            "kafka_route_replayed_total",
            "journaled requests re-forwarded by router crash-recovery "
            "replay",
        ),
        "inflight": reg.gauge(
            "kafka_route_inflight",
            "requests forwarded but not yet relayed",
        ),
        "routable": reg.gauge(
            "kafka_route_replicas_routable",
            "replicas currently routable (configured minus dead)",
        ),
        "latency": reg.histogram(
            "kafka_route_latency_seconds",
            "router-admission to relayed-response seconds per request",
        ),
    }


@dataclasses.dataclass
class _InFlight:
    payload: dict
    tile: str
    replica: str
    admitted_ts: float
    tried: List[str]
    #: wall-clock stamp of the LAST forward (this attempt) and of the
    #: FIRST — their difference is the failover_ms phase: the time the
    #: request lost to dead/shedding replicas before landing.
    forwarded_ts: float = 0.0
    first_forward_ts: float = 0.0
    #: reroute history ({"reason", "replica", "held_ms"} per hop) —
    #: rides the response trace and the request_log row (failover
    #: forensics).
    reroutes: List[dict] = dataclasses.field(default_factory=list)


class TileRouter:
    """The ``kafka-route`` front door: one inbox/responses surface over
    N ``kafka-serve`` replica roots (see module docstring)."""

    def __init__(
        self,
        replicas: Dict[str, str],
        root: str,
        fleet_dir: Optional[str] = None,
        policy: Optional[RoutePolicy] = None,
        poll_interval_s: float = 0.05,
        exit_when_idle: bool = False,
        idle_grace_s: float = 1.0,
        replicas_file: Optional[str] = None,
    ):
        self.policy = policy or RoutePolicy()
        self.replica_roots = {
            rid: os.path.abspath(r) for rid, r in replicas.items()
        }
        self.ring = HashRing(self.replica_roots,
                             vnodes=self.policy.vnodes)
        self.root = root
        self.inbox = os.path.join(root, INBOX_DIR)
        os.makedirs(self.inbox, exist_ok=True)
        self.journal = RequestJournal(root)
        self.watch = FleetWatch(fleet_dir, self.replica_roots,
                                self.policy)
        self.poll_interval_s = float(poll_interval_s)
        self.exit_when_idle = bool(exit_when_idle)
        self.idle_grace_s = float(idle_grace_s)
        #: optional elastic-membership file ({"rid": "root"}); re-read
        #: when its mtime changes, so replicas join/leave a RUNNING
        #: router without a restart.
        self.replicas_file = replicas_file
        self._replicas_file_mtime: Optional[float] = None
        self._inflight: Dict[str, _InFlight] = {}
        self._view: Dict[str, dict] = {}
        self._routable: List[str] = sorted(self.replica_roots)
        self._tiles_seen: set = set()
        self._last_failover_ts: Optional[float] = None
        self._refresh_next = 0.0
        self._drain = threading.Event()
        self._m = _route_metrics(get_registry())
        self._m["routable"].set(len(self._routable))

    # -- status ---------------------------------------------------------

    def drain(self) -> None:
        """Programmatic SIGTERM equivalent."""
        self._drain.set()

    def pending(self) -> int:
        return len(self._inflight)

    def status(self) -> dict:
        """Router facts for ``/statusz`` and the live snapshots — the
        ``tools/fleet_status.py`` router view renders these."""
        reg = get_registry()
        flat = reg.flat()
        return {
            "router_root": os.path.abspath(self.root),
            "router_replicas": dict(self.replica_roots),
            "router_routable": list(self._routable),
            "router_dead": sorted(
                rid for rid, v in self._view.items() if v["dead"]
            ),
            "router_ring": self.ring.assignments(
                sorted(self._tiles_seen)
            ),
            "router_inflight": len(self._inflight),
            "router_rerouted_total": int(sum(
                v for k, v in flat.items()
                if k.startswith("kafka_route_rerouted_total")
            )),
            "router_rebalanced_total": int(
                flat.get("kafka_route_rebalanced_total", 0)
            ),
            "router_last_failover_ts": self._last_failover_ts,
        }

    # -- fleet view / rebalance ----------------------------------------

    def _dead(self, replica_id: str) -> bool:
        view = self._view.get(replica_id)
        return bool(view and view["dead"])

    def _deprioritised(self, replica_id: str) -> bool:
        if self.watch.shedding(replica_id):
            return True
        view = self._view.get(replica_id)
        bound = self.policy.max_queue_depth
        if view and bound is not None:
            depth = view.get("queue_depth")
            if depth is not None and depth >= bound:
                return True
        return False

    def _refresh(self) -> None:
        now = time.monotonic()
        if now < self._refresh_next:
            return
        self._refresh_next = now + self.policy.refresh_s
        self._reload_replicas_file()
        self._view = self.watch.refresh()
        routable = sorted(
            rid for rid in self.replica_roots if not self._dead(rid)
        )
        if routable != self._routable:
            joined = sorted(set(routable) - set(self._routable))
            left = sorted(set(self._routable) - set(routable))
            self._routable = routable
            self._m["rebalanced"].inc()
            self._m["routable"].set(len(routable))
            get_registry().emit(
                "route_rebalanced", routable=routable, joined=joined,
                left=left,
            )
            self._failover(left)
        self._publish_status()

    def _reload_replicas_file(self) -> None:
        """Elastic membership: pick up replica joins/leaves from the
        config file without restarting the router."""
        path = self.replicas_file
        if not path:
            return
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return
        if mtime == self._replicas_file_mtime:
            return
        self._replicas_file_mtime = mtime
        try:
            with open(path) as f:
                desired = json.load(f)
        except (OSError, ValueError) as exc:
            get_registry().emit(
                "route_replicas_file_unreadable", path=path,
                error=repr(exc)[:200],
            )
            return
        if not isinstance(desired, dict):
            return
        desired = {str(k): os.path.abspath(str(v))
                   for k, v in desired.items()}
        added = sorted(set(desired) - set(self.replica_roots))
        removed = sorted(set(self.replica_roots) - set(desired))
        if not added and not removed:
            return
        self.replica_roots = desired
        for rid in added:
            self.ring.add(rid)
        for rid in removed:
            self.ring.remove(rid)
        self.watch = FleetWatch(self.watch.fleet_dir,
                                self.replica_roots, self.policy)
        get_registry().emit(
            "route_membership_changed", added=added, removed=removed,
        )
        if removed:
            self._failover(removed)
        # Force the routable set to be recomputed against the new
        # membership on this same refresh pass.
        self._view = self.watch.refresh()

    def _failover(self, lost: Sequence[str]) -> None:
        """Re-forward every in-flight request assigned to a lost
        replica — warm-state migration by checkpoint resume on the new
        owner."""
        if not lost:
            return
        lost_set = set(lost)
        victims = [rid for rid, inf in self._inflight.items()
                   if inf.replica in lost_set]
        if not victims:
            return
        self._last_failover_ts = time.time()
        get_registry().emit(
            "route_failover", lost=sorted(lost_set),
            rerouted=len(victims),
        )
        for rid in victims:
            inf = self._inflight.pop(rid)
            self._m["rerouted"].inc(reason="dead")
            held_ms = max(0.0, time.time() - inf.forwarded_ts) * 1e3
            reroutes = inf.reroutes + [{
                "reason": "dead", "replica": inf.replica,
                "held_ms": round(held_ms, 3),
            }]
            # The failover is a named span ON the request's trace: the
            # stitched waterfall shows router-side re-forwarding, not a
            # gap, and trace_report attributes the added tail latency
            # to the failover phase.
            with tracing.push(request_id=rid), \
                    trace_span("route_failover", tile=inf.tile,
                               replica=inf.replica):
                self._forward(inf.payload, inf.tile, inf.admitted_ts,
                              tried=inf.tried + [inf.replica],
                              reroutes=reroutes,
                              first_forward_ts=inf.first_forward_ts)
        self._set_inflight()

    def _publish_status(self) -> None:
        st = self.status()
        live.update_status(**{k: v for k, v in st.items()
                              if k.startswith("router_")})

    # -- admission / forwarding ----------------------------------------

    def submit(self, payload: dict) -> dict:
        """Admit-or-reject one raw payload (the inbox scanner and
        in-process callers both land here)."""
        rid = payload.get("request_id") if isinstance(payload, dict) \
            else None
        try:
            req = parse_request(payload)
        except BadRequest as exc:
            return self._reject(rid, "bad_request",
                               detail=repr(exc)[:200])
        if self._drain.is_set():
            return self._reject(req.request_id, "draining")
        if self.policy.shed_on_slo and get_registry().value(
            "kafka_slo_alerts_firing", severity="page"
        ):
            return self._reject(req.request_id, "slo_burn")
        if req.request_id in self._inflight:
            # Duplicate submission of an in-flight id: the original
            # forward already covers it.
            return {"request_id": req.request_id, "status": "queued"}
        req.admitted_ts = time.time()
        with tracing.push(request_id=req.request_id), \
                trace_span("route_admit", tile=req.tile):
            self.journal.record(req.payload())
        get_registry().emit(
            "route_admitted", request_id=req.request_id, tile=req.tile,
        )
        request_log.note_inflight(
            req.request_id, tile=req.tile, date=req.date.isoformat(),
            stage="routing", submitted_ts=req.submitted_ts,
        )
        self._tiles_seen.add(req.tile)
        return self._forward(req.payload(), req.tile, req.admitted_ts)

    def _candidates(self, tile: str,
                    exclude: Iterable[str]) -> List[str]:
        """Failover-ordered forward targets: ring preference, minus
        dead and already-tried replicas, shedding/overloaded ones
        deprioritised to the back."""
        excluded = set(exclude)
        alive = [rid for rid in self.ring.preference(tile)
                 if rid not in excluded and not self._dead(rid)
                 and rid in self.replica_roots]
        good = [rid for rid in alive if not self._deprioritised(rid)]
        return good + [rid for rid in alive if rid not in good]

    def _forward(self, payload: dict, tile: str, admitted_ts: float,
                 tried: Optional[List[str]] = None,
                 reroutes: Optional[List[dict]] = None,
                 first_forward_ts: Optional[float] = None) -> dict:
        tried = list(tried or ())
        rid = payload["request_id"]
        candidates = self._candidates(tile, tried)
        if not candidates:
            return self._reject(rid, "fleet_degraded", admitted=True,
                                tile=tile)
        target = candidates[0]
        faults.fault_point("route.forward", request=rid, replica=target)
        now = time.time()
        with tracing.push(request_id=rid), \
                trace_span("route_forward", tile=tile, replica=target,
                           attempt=len(tried) + 1):
            submit_request(self.replica_roots[target], payload)
        self._inflight[rid] = _InFlight(
            payload=payload, tile=tile, replica=target,
            admitted_ts=admitted_ts, tried=tried,
            forwarded_ts=now,
            first_forward_ts=(first_forward_ts if first_forward_ts
                              is not None else now),
            reroutes=list(reroutes or ()),
        )
        request_log.note_inflight(rid, stage="forwarded",
                                  replica=target)
        self._m["forwarded"].inc(replica=target)
        self._set_inflight()
        get_registry().emit(
            "route_forwarded", request_id=rid, tile=tile,
            replica=target, attempt=len(tried) + 1,
        )
        return {"request_id": rid, "status": "queued",
                "replica": target}

    def _reject(self, request_id: Optional[str], reason: str,
                detail: Optional[str] = None, admitted: bool = False,
                tile: Optional[str] = None) -> dict:
        self._m["rejected"].inc(reason=reason)
        get_registry().emit(
            "route_rejected", request_id=str(request_id), reason=reason,
        )
        ack = {"request_id": request_id, "status": "rejected",
               "reason": reason}
        if reason in RETRYABLE_REJECTIONS:
            ack["retry_after_s"] = self.policy.retry_after_s
        if detail:
            ack["detail"] = detail
        if request_id and isinstance(request_id, str):
            try:
                self.journal.respond(request_id, ack)
            except OSError as exc:
                LOG.warning("could not write router rejection for %s: "
                            "%r", request_id, exc)
            if admitted:
                # An ADMITTED request that ends rejected (fleet fully
                # degraded) still gets its wide event — 100% of
                # admitted requests leave a request_log row.
                request_log.record(request_log.build_record(
                    "route", request_id, status="rejected",
                    e2e_ms=None, tile=tile, reason=reason,
                ))
        return ack

    # -- relay ----------------------------------------------------------

    def _poll_inflight(self) -> int:
        """Relay every in-flight response that arrived; re-route
        replica-state rejections.  Returns how many were settled."""
        settled = 0
        for rid in list(self._inflight):
            inf = self._inflight.get(rid)
            if inf is None:
                continue
            got = read_response(self.replica_roots[inf.replica], rid)
            if got is None:
                continue
            reason = got.get("reason")
            if got.get("status") == "rejected" and \
                    reason in RETRYABLE_REJECTIONS:
                # The replica's state, not the request's: try the next
                # replica on the ring (it resumes the tile warm from
                # the shared checkpoints).
                self.watch.note_shedding(inf.replica)
                del self._inflight[rid]
                self._m["rerouted"].inc(reason="rejected")
                get_registry().emit(
                    "route_rerouted", request_id=rid,
                    replica=inf.replica, reason=reason,
                )
                held_ms = max(0.0,
                              time.time() - inf.forwarded_ts) * 1e3
                ack = self._forward(
                    inf.payload, inf.tile, inf.admitted_ts,
                    tried=inf.tried + [inf.replica],
                    reroutes=inf.reroutes + [{
                        "reason": reason, "replica": inf.replica,
                        "held_ms": round(held_ms, 3),
                    }],
                    first_forward_ts=inf.first_forward_ts,
                )
                if ack["status"] == "rejected":
                    settled += 1
                continue
            body = dict(got)
            body["replica"] = inf.replica
            body["trace"] = self._merged_trace(rid, inf, got)
            with tracing.push(request_id=rid), \
                    trace_span("route_relay", replica=inf.replica):
                self.journal.respond(rid, body)
            del self._inflight[rid]
            self._m["relayed"].inc()
            if got.get("status") == "ok":
                self._m["latency"].observe(
                    max(0.0, time.time() - inf.admitted_ts)
                )
            self._record_request(rid, inf, body)
            settled += 1
        if settled:
            self._set_inflight()
        return settled

    def _merged_trace(self, rid: str, inf: _InFlight, got: dict) -> dict:
        """The client-visible per-request attribution, end to end: the
        router's waits composed with the replica's phases into ONE
        non-overlapping breakdown of submit -> relay.

        The replica's own ``admission_wait_ms`` is dropped (it spans the
        ORIGINAL client submit, which overlaps the router's admission
        and forward phases); ``forward_ms`` (last forward -> replica
        admission, the filesystem-wire hop) and ``relay_ms`` (replica
        publish -> this relay) replace it, and ``failover_ms`` (first
        forward -> last forward) accounts for every dead/shedding hop —
        the phase a SIGKILL's added tail latency lands in.
        """
        t_relay = time.time()
        rep = got.get("trace") if isinstance(got.get("trace"), dict) \
            else {}
        rep_phases = rep.get("phases") or {}
        submitted = float(inf.payload.get("submitted_ts")
                          or inf.admitted_ts)
        phases = {
            # Everything before the FIRST forward: client inbox wait,
            # parse, journal fsync — admission seen from the client.
            "admission_wait_ms":
                max(0.0, inf.first_forward_ts - submitted) * 1e3,
        }
        if inf.reroutes:
            phases["failover_ms"] = max(
                0.0, inf.forwarded_ts - inf.first_forward_ts,
            ) * 1e3
        rep_admitted = rep.get("admitted_ts")
        if isinstance(rep_admitted, (int, float)):
            phases["forward_ms"] = \
                max(0.0, rep_admitted - inf.forwarded_ts) * 1e3
        for key in ("queue_wait_ms", "resume_ms", "solve_ms",
                    "dump_ms"):
            if isinstance(rep_phases.get(key), (int, float)):
                phases[key] = rep_phases[key]
        responded = rep.get("responded_ts")
        if isinstance(responded, (int, float)):
            phases["relay_ms"] = max(0.0, t_relay - responded) * 1e3
        trace = {
            "request_id": rid,
            "phases": {k: round(v, 3) for k, v in phases.items()},
            "e2e_ms": round(max(0.0, t_relay - submitted) * 1e3, 3),
        }
        if inf.reroutes:
            trace["reroutes"] = list(inf.reroutes)
        if rep.get("replayed"):
            trace["replayed"] = True
        return trace

    def _record_request(self, rid: str, inf: _InFlight,
                        body: dict) -> None:
        """The router half of request_log.jsonl: one wide event per
        relayed request, with the merged end-to-end phases and the
        reroute history attached."""
        trace = body.get("trace") or {}
        request_log.record(request_log.build_record(
            "route", rid, status=body.get("status", "?"),
            e2e_ms=trace.get("e2e_ms"), phases=trace.get("phases"),
            tile=inf.tile, date=body.get("date"),
            served_from=body.get("served_from"),
            replica=inf.replica,
            reroutes=trace.get("reroutes"),
            solver_health=body.get("solver_health"),
            quality=body.get("quality"),
        ))

    def requestz(self, n: int = 32) -> dict:
        """The ``/requestz`` payload: in-flight + last-N relayed."""
        return request_log.requestz(n)

    def _set_inflight(self) -> None:
        self._m["inflight"].set(len(self._inflight))

    # -- the loop --------------------------------------------------------

    def _scan_inbox(self) -> int:
        try:
            names = sorted(
                n for n in os.listdir(self.inbox) if n.endswith(".json")
            )
        except OSError:
            return 0
        consumed = 0
        for name in names:
            path = os.path.join(self.inbox, name)
            try:
                with open(path) as f:
                    payload = json.load(f)
            except FileNotFoundError:
                continue
            except (OSError, ValueError) as exc:
                get_registry().emit(
                    "request_unparseable", file=name,
                    error=repr(exc)[:200],
                )
                self._unlink(path)
                consumed += 1
                continue
            self.submit(payload)
            self._unlink(path)
            consumed += 1
        return consumed

    @staticmethod
    def _unlink(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:  # raced another consumer — outcome identical
            pass

    def _replay(self) -> None:
        """Router crash recovery: every journaled request with no
        relayed response is re-forwarded — zero admitted requests lost
        across a router restart."""
        for payload in self.journal.replay():
            try:
                req = parse_request(payload, replayed=True)
            except BadRequest:
                get_registry().emit(
                    "request_unreplayable",
                    request_id=str(payload.get("request_id")),
                )
                continue
            self._m["replayed"].inc()
            self._tiles_seen.add(req.tile)
            get_registry().emit(
                "route_replayed", request_id=req.request_id,
                tile=req.tile,
            )
            self._forward(req.payload(), req.tile, time.time())

    def run(self) -> dict:
        """The routing loop; returns the run summary."""
        reg = get_registry()
        prev_handler = _install_drain(self._drain)
        self._refresh()
        self._replay()
        reg.emit("route_started", root=self.root,
                 replicas=sorted(self.replica_roots))
        t0 = time.time()
        idle_since: Optional[float] = None
        try:
            while not self._drain.is_set():
                self._refresh()
                consumed = self._scan_inbox()
                self._poll_inflight()
                if consumed == 0 and not self._inflight:
                    if self.exit_when_idle:
                        now = time.monotonic()
                        if idle_since is None:
                            idle_since = now
                        elif now - idle_since >= self.idle_grace_s:
                            break
                else:
                    idle_since = None
                self._drain.wait(self.poll_interval_s)
            if self._drain.is_set():
                # Graceful drain: latecomer inbox files are answered
                # ``rejected: draining`` (submit() checks the flag),
                # in-flight requests finish relaying.
                while self._inflight:
                    self._refresh()
                    self._scan_inbox()
                    self._poll_inflight()
                    if self._inflight:
                        self._drain.wait(
                            max(self.poll_interval_s, 0.02)
                        )
                self._scan_inbox()
        finally:
            self._publish_status()
            self.journal.close()
            _restore_drain(prev_handler)
        flat = reg.flat()
        summary = {
            "mode": "route",
            "root": self.root,
            "drained": self._drain.is_set(),
            "wall_s": round(time.time() - t0, 3),
            "replicas": sorted(self.replica_roots),
            "forwarded": int(sum(
                v for k, v in flat.items()
                if k.startswith("kafka_route_forwarded_total")
            )),
            "relayed": int(flat.get("kafka_route_relayed_total", 0)),
            "rerouted": int(sum(
                v for k, v in flat.items()
                if k.startswith("kafka_route_rerouted_total")
            )),
            "rebalanced": int(
                flat.get("kafka_route_rebalanced_total", 0)
            ),
            "replayed": int(
                flat.get("kafka_route_replayed_total", 0)
            ),
            "rejected": int(sum(
                v for k, v in flat.items()
                if k.startswith("kafka_route_rejected_total")
            )),
        }
        reg.emit("route_stopped", **summary)
        return summary
