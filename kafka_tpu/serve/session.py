"""Warm per-tile filter state and the incremental serve path.

The Kalman structure (PAPER.md §propagation) makes serving a NEW
observation date from warm state near-free: the analysis at the last
grid step is a sufficient statistic for everything before it, so a
request only needs the predict/correct steps AFTER the newest
checkpoint — not a full-series rerun.  A :class:`TileSession` holds one
tile's serving state with the CHECKPOINT SET as the canonical store
(``engine.checkpoint.Checkpointer``): every serve resumes from
``load_latest`` + ``resume_time_grid`` and re-checkpoints at its end.
Routing state through the checkpoint (rather than a process-local
array) is what makes a SIGKILLed daemon and an uninterrupted one
indistinguishable — both read the same durable bytes — and it is why
the warm-path parity test can demand the incremental result be
identical to a cold full-series rerun.

Serve outcomes (the response's ``served_from`` field):

``cold``
    no usable checkpoint — full-series run from the tile prior,
    checkpointing as it goes (the first request pays this once).
``warm``
    resumed from the newest intact checkpoint; only the grid windows
    after it ran.
``warm_noop``
    the newest checkpoint already sits AT the requested grid step —
    the state is read back and answered with zero solve work (the
    ``resume_time_grid`` empty-remainder invariant).
``cold_replay``
    the request is BEHIND the warm state (a date the warm chain has
    passed).  Served by a throwaway full run up to that date with NO
    checkpointing, so historical reads never rewind the warm chain.
``smoothed_chain``
    a ``smoothed=true`` (reanalysis) request: the RTS backward pass
    over the tile's whole checkpoint chain (``kafka_tpu.smoother``),
    answered read-only — zero forward windows run, the chain is never
    rewritten.  The response's ``x_sha256`` matches what the offline
    ``kafka-smooth`` driver reports for the same chain bit-for-bit.
"""

from __future__ import annotations

import dataclasses
import datetime
import hashlib
import logging
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..engine.checkpoint import Checkpointer
from ..telemetry import get_registry, quality, span, tracing

LOG = logging.getLogger(__name__)


class UnknownDateError(ValueError):
    """A requested date the tile's observation source does not carry.
    Poison-classed: retrying cannot make the date exist."""

    kafka_failure_class = "poison"


@dataclasses.dataclass
class TileSpec:
    """Everything needed to (re)build one tile's filter.

    ``make_filter()`` returns ``(kf, x0, p_inv0, output)`` — a FRESH
    ``KalmanFilter`` with its observation source and output writer, plus
    the tile prior's initial state.  It is called once per serve: filter
    objects are cheap, the expensive jitted programs are cached
    process-wide by operator identity, and a fresh prefetcher per run is
    the engine's existing lifecycle.
    """

    name: str
    make_filter: Callable[[], tuple]
    base_date: datetime.datetime
    step_days: int
    ckpt_dir: str
    n_shards: int = 1

    def grid_through(self, date: datetime.datetime) -> List[datetime.datetime]:
        """The tile's canonical time grid extended just past ``date``
        (windows are half-open ``[t_{k-1}, t_k)``, so the last grid
        point must be strictly after the requested observation)."""
        if date < self.base_date:
            raise UnknownDateError(
                f"{date} predates tile base {self.base_date}"
            )
        grid = [self.base_date]
        step = datetime.timedelta(days=self.step_days)
        while grid[-1] <= date:
            grid.append(grid[-1] + step)
        return grid


class TileSession:
    """One tile's serving state; NOT thread-safe (the service serializes
    serves on its worker thread)."""

    def __init__(self, spec: TileSpec):
        self.spec = spec
        self.name = spec.name
        self.checkpointer = Checkpointer(
            spec.ckpt_dir, n_shards=spec.n_shards
        )
        #: the last serve's final (x, p_inv) as host arrays — test and
        #: diagnostics access; the durable state is the checkpoint set.
        self.last_state: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self.serves = 0
        self._bucket = None
        self._bucket_built = False

    # -- the serve path -------------------------------------------------

    def serve_bucket(self):
        """The tile's serve shape bucket (``serve.batch.ShapeBucket``) —
        the coarse compatibility fingerprint the admission micro-window
        groups on, plus the representative pieces AOT lowering needs.
        Built once from a throwaway filter; ``None`` when the tile's
        configuration cannot coalesce (fused scans, band-sequential
        loops, filters the probe cannot build)."""
        if not self._bucket_built:
            self._bucket_built = True
            from .batch import probe_bucket

            try:
                self._bucket = probe_bucket(self)
            except Exception:
                LOG.warning(
                    "tile %s: serve-bucket probe failed; the tile will "
                    "serve unbatched", self.name, exc_info=True,
                )
                self._bucket = None
        return self._bucket

    def serve(self, date: datetime.datetime,
              smoothed: bool = False, dispatcher=None) -> dict:
        """Answer one observation-date request; returns the response
        body (status/served_from/summary fields, JSON-serialisable).
        ``smoothed=True`` answers with the RTS reanalysis from the
        checkpoint chain instead of running the forward filter.
        ``dispatcher`` (coalesced serving) replaces the engine's per-date
        solve dispatch — same signature and bit-identical results as
        ``assimilate_date_jit`` from this session's point of view."""
        t0 = time.perf_counter()
        kf, x0, p_inv0, output = self.spec.make_filter()
        if dispatcher is not None:
            kf.date_dispatcher = dispatcher
        # Tile-scoped trace/quality context: the quality ledger keys its
        # sentinel streams by chunk_id, so each tile keeps its own
        # per-band chi^2 series (the serving analogue of a chunk).
        with tracing.push(chunk_id=f"tile:{self.name}"):
            if smoothed:
                return self._serve_smoothed_in_context(
                    kf, output, date, t0,
                )
            return self._serve_in_context(
                kf, x0, p_inv0, output, date, t0,
            )

    def _serve_in_context(self, kf, x0, p_inv0, output, date, t0) -> dict:
        phases = {}
        try:
            if date not in set(kf.observations.dates):
                raise UnknownDateError(
                    f"tile {self.name} has no observation on {date}"
                )
            with span("serve_resume"):
                grid = self.spec.grid_through(date)
                resumed, seed = self.checkpointer.resume_time_grid(grid)
            phases["resume_ms"] = (time.perf_counter() - t0) * 1e3
            t_solve = time.perf_counter()
            if seed is None:
                served_from = "cold"
                windows_run = len(grid) - 1
                with span("serve_solve"):
                    x, _, p_inv = kf.run(
                        grid, x0, None, p_inv0,
                        checkpointer=self.checkpointer,
                    )
            elif len(resumed) == 1 and resumed[0] == grid[-1]:
                # Empty remainder: the checkpoint IS the answer.
                served_from = "warm_noop"
                windows_run = 0
                x, p_inv = seed
            elif resumed[0] > grid[-1]:
                # The warm chain moved past this date; replay history
                # without touching the chain's checkpoints.
                served_from = "cold_replay"
                windows_run = len(grid) - 1
                with span("serve_solve"):
                    x, _, p_inv = kf.run(
                        grid, x0, None, p_inv0, checkpointer=None,
                    )
            else:
                served_from = "warm"
                windows_run = len(resumed) - 1
                x_r, p_inv_r = seed
                with span("serve_solve"):
                    x, _, p_inv = kf.run(
                        resumed, x_r, None, p_inv_r,
                        checkpointer=self.checkpointer,
                        advance_first=True,
                    )
            phases["solve_ms"] = (time.perf_counter() - t_solve) * 1e3
        finally:
            close = getattr(output, "close", None)
            if close is not None:
                close()
        t_dump = time.perf_counter()
        x_np = np.asarray(x, np.float32)
        n_valid = kf.gather.n_valid
        x_valid = np.ascontiguousarray(x_np[:n_valid])
        if served_from in ("cold", "warm"):
            self.last_state = (x_np, None if p_inv is None
                               else np.asarray(p_inv, np.float32))
        self.serves += 1
        wall_ms = (time.perf_counter() - t0) * 1e3
        health = self._solver_health(kf)
        qual = self._quality(kf)
        self._record(served_from, windows_run, wall_ms, health)
        phases["dump_ms"] = (time.perf_counter() - t_dump) * 1e3
        return {
            # Session-local phase durations (resume / solve / dump) —
            # consumed by the service, which folds its own waits in and
            # replaces this with the response's "trace" block.
            "trace_phases": {k: round(v, 3) for k, v in phases.items()},
            "status": "ok",
            "tile": self.name,
            "date": date.isoformat(),
            "served_from": served_from,
            "windows_run": windows_run,
            "n_pixels": int(n_valid),
            "x_mean": [round(float(v), 7)
                       for v in x_valid.mean(axis=0)],
            "x_sha256": hashlib.sha256(x_valid.tobytes()).hexdigest(),
            "wall_ms": round(wall_ms, 3),
            # Result QUALITY, not just latency: the run's solve-health
            # totals (BASELINE.md "Numerical resilience") so clients —
            # and the request journal, which persists every response —
            # can see a degraded answer for what it is.  A warm_noop /
            # cache-style serve runs zero windows, so the totals are 0.
            "solver_health": health,
            # Filter-consistency verdict for the windows THIS request
            # ran (BASELINE.md "Assimilation quality"): worst verdict
            # over the run's quality-ledger records, plus whether this
            # tile's drift sentinels are currently alarming.  A
            # zero-window serve (warm_noop) has no verdict.
            "quality": qual,
        }

    def _serve_smoothed_in_context(self, kf, output, date, t0) -> dict:
        """The ``smoothed=true`` request kind: run the RTS backward pass
        over the tile's checkpoint chain and answer with the smoothed
        state at the grid step covering ``date``.  Strictly read work —
        the chain is walked, never written (kafkalint rule 19 pins the
        smoother package to that contract), so any replica sharing the
        checkpoint directory can serve it.  The fresh filter supplies
        the trajectory model / uncertainty / propagator the fallback
        re-derivation needs for pre-sidecar checkpoint sets."""
        from ..smoother import (
            QA_CLAMPED, SmootherError, smooth_checkpoints, state_sha256,
        )

        phases = {}
        try:
            target = self.spec.grid_through(date)[-1]
            t_smooth = time.perf_counter()
            # The serve_smooth phase joins the request waterfall next to
            # serve_resume/serve_solve (the smoother's own
            # smooth_rederive / smooth_sweep spans nest under it).
            with span("serve_smooth"):
                try:
                    result = smooth_checkpoints(
                        self.checkpointer,
                        m_matrix=np.asarray(
                            kf.trajectory_model, np.float32),
                        q_diag=np.asarray(
                            kf.trajectory_uncertainty, np.float32),
                        state_propagator=kf._state_propagator,
                    )
                except SmootherError as exc:
                    raise UnknownDateError(
                        f"tile {self.name} has no smoothable "
                        f"checkpoint chain: {exc}"
                    ) from exc
                try:
                    t = result.index_of(target)
                except KeyError as exc:
                    raise UnknownDateError(
                        f"tile {self.name}: grid step "
                        f"{target.date().isoformat()} is not in the "
                        "warm checkpoint chain — serve the date "
                        "forward first, then request the reanalysis"
                    ) from exc
            phases["smooth_ms"] = (time.perf_counter() - t_smooth) * 1e3
        finally:
            close = getattr(output, "close", None)
            if close is not None:
                close()
        x_t = np.asarray(result.x_smoothed[t], np.float32)
        qa_t = np.asarray(result.qa[t])
        n_valid = kf.gather.n_valid
        shrink = result.sigma_shrink(t)
        quality.get_ledger().record_smoothed(
            target.date().isoformat(), shrink, n_valid=int(n_valid),
            prefix=f"tile:{self.name}",
        )
        self.serves += 1
        wall_ms = (time.perf_counter() - t0) * 1e3
        self._record("smoothed_chain", 0, wall_ms)
        return {
            "trace_phases": {k: round(v, 3) for k, v in phases.items()},
            "status": "ok",
            "tile": self.name,
            "date": date.isoformat(),
            "smoothed": True,
            # The chain step actually answered (the grid point covering
            # the requested observation date, like the forward path).
            "timestep": target.isoformat(),
            "served_from": "smoothed_chain",
            "windows_run": 0,
            "windows_smoothed": len(result.timesteps),
            "rederived": len(result.rederived),
            "skipped": len(result.skipped),
            "n_pixels": int(n_valid),
            "x_mean": [round(float(v), 7)
                       for v in x_t[:n_valid].mean(axis=0)],
            # Digest over ALL stored rows — the same bytes the offline
            # kafka-smooth driver hashes, so served and offline
            # reanalysis compare bit-for-bit.
            "x_sha256": state_sha256(x_t),
            "wall_ms": round(wall_ms, 3),
            # The backward pass has no innovations: quality scores on
            # sigma-shrink (smoothed/filter posterior width) instead of
            # chi^2, the same verdict quality_report recomputes.
            "quality": {
                "verdict": quality.smoothed_verdict_for(shrink),
                "sigma_shrink": [
                    None if not np.isfinite(v) else round(float(v), 6)
                    for v in shrink
                ],
                "clamped_px": int(np.count_nonzero(qa_t & QA_CLAMPED)),
                "rederived_step": result.timesteps[t] in result.rederived,
            },
        }

    def _quality(self, kf) -> dict:
        """The run's quality summary from the engine's diagnostics log
        (the verdicts were computed by the quality ledger during the
        run — this reads host state only)."""
        verdicts = [r["quality_verdict"] for r in kf.diagnostics_log
                    if "quality_verdict" in r]
        windows: dict = {}
        for v in verdicts:
            windows[v] = windows.get(v, 0) + 1
        drifting = sorted(
            key for key in quality.get_ledger().summary()["drifting"]
            if key.startswith(f"tile:{self.name}:")
        )
        return {
            "verdict": quality.worst_verdict(verdicts),
            "windows": windows,
            "drift_active": bool(drifting),
        }

    @staticmethod
    def _solver_health(kf) -> dict:
        """Sum the run's per-window solve-health counts from the
        engine's diagnostics log (zeros when the run's solve mode
        tracked no health)."""
        recs = [r for r in kf.diagnostics_log if "quarantined" in r]
        return {
            "quarantined": int(sum(r["quarantined"] for r in recs)),
            "cap_bailouts": int(sum(r["cap_bailouts"] for r in recs)),
            "damped_recovered": int(
                sum(r["damped_recovered"] for r in recs)
            ),
            "nonfinite": int(sum(r["nonfinite"] for r in recs)),
        }

    def _record(self, served_from: str, windows_run: int,
                wall_ms: float, health: Optional[dict] = None) -> None:
        reg = get_registry()
        if health and health.get("quarantined"):
            reg.emit(
                "serve_degraded_result", tile=self.name,
                quarantined=health["quarantined"],
                cap_bailouts=health.get("cap_bailouts", 0),
            )
        reg.counter(
            "kafka_serve_solves_total",
            "tile serves by path (cold / warm / warm_noop / "
            "cold_replay / smoothed_chain)",
        ).inc(served_from=served_from)
        reg.counter(
            "kafka_serve_windows_run_total",
            "grid windows actually executed by serves — the warm path's "
            "win is this number staying near the per-request delta "
            "instead of the full series length",
        ).inc(windows_run)
        reg.emit(
            "serve_solved", tile=self.name, served_from=served_from,
            windows_run=windows_run, wall_ms=round(wall_ms, 3),
        )
