"""The assimilation service: queue -> admission -> solve -> respond.

The in-process heart of the serving daemon (``serve.daemon`` wraps it in
a filesystem transport; tests and ``tools/loadgen.py`` drive it
directly).  Robustness is the design surface:

- **Admission first** (``serve.admission``): every submission is decided
  admit-or-shed BEFORE any work happens, against the bounded queue and
  the engine's telemetry gauges.  Shed requests get an immediate
  ``rejected`` response and a counted reason — overload degrades to fast
  rejection, never to queue collapse.
- **Journal before queue** (``serve.journal``): an admitted request is
  durable before it is acked, so a crash at ANY later point is
  recoverable by idempotent replay.
- **Deadlines** (``resilience.policy.Deadline``): a request whose
  wall-clock budget expired before its turn is CANCELLED — counted and
  answered, never silently dropped.
- **Classified failures**: a poison solve answers an ``error`` response
  (the daemon survives bad requests); transient solve/respond failures
  retry under a ``RetryPolicy``; fatal ones kill the process into the
  flight recorder, and the journal replays the in-flight request on
  restart.
- **Chaos hooks**: ``serve.admit`` / ``serve.solve`` / ``serve.respond``
  fault points make the shed, cancel, error and crash-resume paths
  scriptable deterministically on CPU (``KAFKA_TPU_FAULTS``).
- **Drain**: ``drain()`` (the daemon's SIGTERM) finishes in-flight and
  queued work, rejects new submissions with reason ``draining``, and
  returns with every admitted request answered; tile state is already
  durable because every serve ends in a checkpoint.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Dict, Optional

from ..resilience import (
    FATAL,
    DeadlineExceeded,
    RetryPolicy,
    classify_failure,
    faults,
)
from ..telemetry import get_registry, tracing
from ..telemetry import request_log
from ..telemetry.tracing import trace_span
from . import batch as batching
from .admission import AdmissionController, AdmissionPolicy
from .journal import RequestJournal
from .request import BadRequest, ServeRequest, parse_request
from .session import TileSession

LOG = logging.getLogger(__name__)

#: solve/respond retry default: one in-place retry of transient weather,
#: short deterministic backoff — a serving worker must not sit in long
#: backoff while the queue builds behind it.
DEFAULT_SERVE_RETRY = RetryPolicy(
    max_attempts=2, base_delay=0.1, multiplier=2.0, max_delay=1.0,
    jitter=0.0,
)


def _serve_metrics(reg):
    """Single registration site for the service's metric vocabulary."""
    return {
        "admitted": reg.counter(
            "kafka_serve_admitted_total",
            "requests accepted into the serve queue",
        ),
        "rejected": reg.counter(
            "kafka_serve_rejected_total",
            "requests shed at admission, labelled by reason — overload "
            "degrades to fast rejection, never silent queue collapse",
        ),
        "cancelled": reg.counter(
            "kafka_serve_cancelled_total",
            "admitted requests cancelled because their per-request "
            "deadline expired before serving",
        ),
        "errors": reg.counter(
            "kafka_serve_errors_total",
            "admitted requests answered with an error response "
            "(poison solves; the daemon itself survives)",
        ),
        "cache_hits": reg.counter(
            "kafka_serve_cache_hits_total",
            "requests answered from the in-memory result cache",
        ),
        "replayed": reg.counter(
            "kafka_serve_replayed_total",
            "journaled requests re-enqueued by crash-recovery replay",
        ),
        "respond_errors": reg.counter(
            "kafka_serve_respond_errors_total",
            "responses that could not be written after retries (the "
            "journal replays the request on restart)",
        ),
        "depth": reg.gauge(
            "kafka_serve_queue_depth",
            "requests admitted but not yet served (the admission "
            "controller's primary load signal)",
        ),
        "latency": reg.histogram(
            "kafka_serve_latency_seconds",
            "submit-to-response seconds for OK-served requests",
        ),
        "batches": reg.counter(
            "kafka_serve_batches_total",
            "micro-window admission groups of two or more compatible "
            "requests handed to the batch executor together",
        ),
        "batch_requests": reg.counter(
            "kafka_serve_batch_requests_total",
            "requests served as members of a coalesced admission group",
        ),
    }


class AssimilationService:
    """Long-lived serving core over a set of warm tile sessions."""

    def __init__(
        self,
        sessions: Dict[str, TileSession],
        root: str,
        policy: Optional[AdmissionPolicy] = None,
        default_deadline_s: Optional[float] = None,
        retry_policy: Optional[RetryPolicy] = None,
        result_cache_size: int = 256,
        journal_rotate_bytes: Optional[int] = None,
        journal_keep: int = 3,
        batch_window_ms: float = 0.0,
        max_batch: int = 8,
    ):
        self.sessions = dict(sessions)
        self.journal = RequestJournal(
            root, rotate_bytes=journal_rotate_bytes, keep=journal_keep,
        )
        self.admission = AdmissionController(policy)
        self.default_deadline_s = default_deadline_s
        self._retry = retry_policy if retry_policy is not None \
            else DEFAULT_SERVE_RETRY
        # Coalesced serving (BASELINE.md "Coalesced serving"): 0 ms
        # keeps the classic one-at-a-time worker; a positive window
        # lets the worker hold a dequeued request up to this long while
        # compatible peers arrive, then serves the group as one batch.
        self._batch_window_s = max(0.0, float(batch_window_ms)) / 1e3
        self._max_batch = max(1, int(max_batch))
        self._executor = batching.BatchExecutor()
        self._cache: "collections.OrderedDict" = collections.OrderedDict()
        self._cache_lock = threading.Lock()
        self._cache_size = int(result_cache_size)
        self._queue: "collections.deque[ServeRequest]" = collections.deque()
        self._cond = threading.Condition()
        self._responded = threading.Condition()
        self._draining = threading.Event()
        self._stopped = threading.Event()
        self._busy = False
        reg = get_registry()
        self._m = _serve_metrics(reg)
        # PR 3 thread-tracing convention: capture the constructing
        # thread's context, re-install it on the worker.
        self._ctx = tracing.current_context()
        self._worker = threading.Thread(
            target=self._run, name="serve-worker", daemon=True,
        )
        self._started = False

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "AssimilationService":
        """Replay the journal, then start the serving worker."""
        if self._started:
            return self
        replayed = self.journal.replay()
        for payload in replayed:
            try:
                req = parse_request(payload, replayed=True)
            except BadRequest:
                # A journaled line that no longer parses is forensic
                # residue, not recoverable work.
                get_registry().emit(
                    "request_unreplayable",
                    request_id=str(payload.get("request_id")),
                )
                continue
            if req.tile not in self.sessions:
                get_registry().emit(
                    "request_unreplayable", request_id=req.request_id,
                    reason=f"unknown tile {req.tile}",
                )
                continue
            self._m["replayed"].inc()
            get_registry().emit(
                "request_replayed", request_id=req.request_id,
                tile=req.tile, date=req.date.isoformat(),
            )
            # The replay CONTINUES the journaled trace (same request
            # id, original submission/admission stamps) — it does not
            # mint a fresh one; queue_wait restarts at re-enqueue.
            req.admitted_perf = time.perf_counter()
            request_log.note_inflight(
                req.request_id, tile=req.tile,
                date=req.date.isoformat(), stage="queued",
                replayed=True,
            )
            with self._cond:
                self._queue.append(req)
        self._set_depth()
        self._started = True
        self._worker.start()
        with self._cond:
            self._cond.notify_all()
        return self

    def close(self) -> None:
        """Stop the worker (after the queue drains) and release files."""
        self._stopped.set()
        with self._cond:
            self._cond.notify_all()
        if self._started:
            self._worker.join(timeout=60.0)
        self.journal.close()

    def stop_admitting(self) -> None:
        """Flip new submissions to ``rejected: draining`` immediately
        (the drain's first half, split out so the daemon can answer
        latecomers with explicit rejections before the final wait).
        Also wakes the worker: a partially-filled batch window must
        flush NOW — no admitted request sits out the micro-window once
        the drain started."""
        self._draining.set()
        with self._cond:
            self._cond.notify_all()

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """SIGTERM semantics: reject new work, finish everything already
        admitted.  Returns True when the queue fully drained."""
        if not self._draining.is_set():
            self._draining.set()
            get_registry().emit("serve_drain")
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        with self._cond:
            while self._queue or self._busy:
                wait = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                if wait is not None and wait <= 0:
                    return False
                self._cond.wait(timeout=wait if wait is not None else 1.0)
        return True

    def set_batch_window(self, batch_window_ms: float) -> None:
        """Re-tune the admission micro-window live (0 disables
        coalescing).  Used by the bench harness to measure batched and
        unbatched serving in ONE run against the same warm sessions."""
        with self._cond:
            self._batch_window_s = max(0.0, float(batch_window_ms)) / 1e3

    def pending(self) -> int:
        with self._cond:
            return len(self._queue) + (1 if self._busy else 0)

    @property
    def draining(self) -> bool:
        """True once new submissions are being rejected (the /statusz
        surface; the internal event stays private)."""
        return self._draining.is_set()

    # -- submission -----------------------------------------------------

    def submit(self, payload: dict) -> dict:
        """Admit-or-shed one raw request payload.  Returns the ack:
        ``{"request_id", "status": "queued"|"rejected", ...}``.  Every
        rejection also lands as a response file so cross-process clients
        see it."""
        rid = payload.get("request_id") if isinstance(payload, dict) \
            else None
        try:
            faults.fault_point("serve.admit", request=str(rid))
            req = parse_request(
                payload, default_deadline_s=self.default_deadline_s,
            )
        except BaseException as exc:
            if classify_failure(exc) == FATAL:
                raise
            reason = "bad_request" if isinstance(exc, BadRequest) \
                else "admit_error"
            return self._reject(rid, reason, detail=repr(exc)[:200])
        if req.tile not in self.sessions:
            return self._reject(req.request_id, "unknown_tile")
        if self._draining.is_set() or self._stopped.is_set():
            return self._reject(req.request_id, "draining")
        with tracing.push(request_id=req.request_id), \
                trace_span("serve_admit", tile=req.tile):
            with self._cond:
                reason = self.admission.decide(
                    queue_depth=len(self._queue)
                )
                if reason is None:
                    # The admission stamp rides the journal line and the
                    # trace: admission_wait attribution survives crash
                    # replay and (via the wire) re-forwarding.
                    req.admitted_ts = time.time()
                    req.admitted_perf = time.perf_counter()
                    self.journal.record(req.payload())
                    # In-flight BEFORE the worker can dequeue it (we
                    # hold the queue lock): a request must never finish
                    # before /requestz saw it start.
                    request_log.note_inflight(
                        req.request_id, tile=req.tile,
                        date=req.date.isoformat(), stage="queued",
                        submitted_ts=req.submitted_ts,
                    )
                    self._queue.append(req)
                    self._m["admitted"].inc()
                    self._set_depth_locked()
                    self._cond.notify_all()
        if reason is not None:
            return self._reject(req.request_id, reason)
        get_registry().emit(
            "request_admitted", request_id=req.request_id,
            tile=req.tile, date=req.date.isoformat(),
        )
        return {"request_id": req.request_id, "status": "queued"}

    def _reject(self, request_id: Optional[str], reason: str,
                detail: Optional[str] = None) -> dict:
        self._m["rejected"].inc(reason=reason)
        get_registry().emit(
            "request_rejected", request_id=str(request_id), reason=reason,
        )
        ack = {"request_id": request_id, "status": "rejected",
               "reason": reason}
        # Load-state rejections carry the backoff hint so clients wait
        # out the overload instead of hammering a shedding replica.
        retry_after = self.admission.retry_after(reason)
        if retry_after is not None:
            ack["retry_after_s"] = retry_after
        if detail:
            ack["detail"] = detail
        if request_id and isinstance(request_id, str):
            # Best-effort: the rejection must reach cross-process
            # clients, but a full disk must not crash admission.
            try:
                self._publish(request_id, ack)
            except OSError as exc:
                LOG.warning("could not write rejection response for %s: "
                            "%r", request_id, exc)
        return ack

    # -- results --------------------------------------------------------

    def result(self, request_id: str,
               timeout_s: Optional[float] = None) -> Optional[dict]:
        """Block until ``request_id`` has a response (or timeout)."""
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        with self._responded:
            while True:
                got = self.journal.response(request_id)
                if got is not None:
                    return got
                wait = 1.0 if deadline is None \
                    else deadline - time.monotonic()
                if wait <= 0:
                    return None
                self._responded.wait(timeout=min(wait, 1.0))

    # -- the worker loop ------------------------------------------------

    def _run(self) -> None:
        tracing.set_context(self._ctx)
        tracing.set_lane("serve")
        while True:
            with self._cond:
                while not self._queue and not self._stopped.is_set():
                    self._cond.wait(timeout=0.5)
                if not self._queue and self._stopped.is_set():
                    return
                req = self._queue.popleft()
                self._busy = True
                self._set_depth_locked()
            try:
                group = self._collect_batch(req)
                if len(group) == 1:
                    self._process(req)
                else:
                    self._process_batch(group)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def _collect_batch(self, head: ServeRequest) -> list:
        """The admission micro-window: hold the dequeued ``head`` up to
        ``batch_window_ms`` while compatible peers arrive — same shape
        bucket, a DISTINCT tile (sessions are single-threaded), forward
        kind (smoothed never mixes), not a crash replay.  Flushes
        immediately when the window is off, the head is ineligible, or
        a drain/stop is in progress (no request waits out the window
        during SIGTERM drain or ``--exit-when-idle``)."""
        group = [head]
        if (
            self._batch_window_s <= 0.0 or self._max_batch <= 1
            or head.smoothed or head.replayed
            or self._draining.is_set() or self._stopped.is_set()
        ):
            return group
        key = batching.session_bucket_key(self.sessions[head.tile])
        if key is None:
            return group
        tiles = {head.tile}
        deadline = time.perf_counter() + self._batch_window_s
        with self._cond:
            while len(group) < self._max_batch:
                for peer in list(self._queue):
                    if (
                        peer.smoothed or peer.replayed
                        or peer.tile in tiles
                    ):
                        continue
                    session = self.sessions.get(peer.tile)
                    if session is None:
                        continue
                    if batching.session_bucket_key(session) != key:
                        continue
                    self._queue.remove(peer)
                    group.append(peer)
                    tiles.add(peer.tile)
                    if len(group) >= self._max_batch:
                        break
                if (
                    len(group) >= self._max_batch
                    or self._draining.is_set()
                    or self._stopped.is_set()
                ):
                    break
                wait = deadline - time.perf_counter()
                if wait <= 0:
                    break
                self._cond.wait(timeout=wait)
            self._set_depth_locked()
        return group

    def _process_batch(self, group: list) -> None:
        """Serve one coalesced admission group: every member runs its
        FULL request pipeline concurrently (deadline, cache, solve,
        respond — one thread per member), with the engine dispatches
        meeting in the batch executor's rendezvous.  A member that
        errors, cancels or serves from cache simply leaves the
        rendezvous; its peers batch without it."""
        batch_id = f"batch-{group[0].request_id}"
        size = len(group)
        self._m["batches"].inc()
        self._m["batch_requests"].inc(size)
        get_registry().emit(
            "serve_batch_admitted", batch_id=batch_id, size=size,
            tiles=[r.tile for r in group],
        )
        for req in group:
            req.batch_id = batch_id
            req.batch_size = size
        members = self._executor.open(size)
        ctx = tracing.current_context()
        threads = []
        for req, member in list(zip(group, members))[1:]:
            t = threading.Thread(
                target=self._process_member,
                args=(req, member, ctx),
                name=f"serve-batch-{req.request_id}", daemon=True,
            )
            t.start()
            threads.append(t)
        self._process_member(group[0], members[0], ctx)
        for t in threads:
            t.join()

    def _process_member(self, req: ServeRequest, member, ctx) -> None:
        # PR 3 thread-tracing convention: contextvars don't cross
        # thread creation — re-install the worker's context first.
        tracing.set_context(ctx)
        try:
            with tracing.push(request_id=req.request_id):
                self._process_traced(req, member=member)
        finally:
            member.close()

    def _process(self, req: ServeRequest) -> None:
        # Request-scoped trace context: every span from here down —
        # queue_wait, serve_resume, the engine's own phases, the
        # respond write — carries the request id, so the stitched
        # per-request waterfall is one filter away.
        with tracing.push(request_id=req.request_id):
            self._process_traced(req)

    def _wait_phases(self, req: ServeRequest, t_deq: float) -> Dict:
        """The two pre-solve phases: admission_wait (client submit ->
        admission decision, wall clock — cross-process on the
        filesystem transport) and queue_wait (admission -> this
        dequeue).  The queue_wait also lands as a retroactive span so
        the waterfall shows the queue, not a gap."""
        admitted = req.admitted_ts if req.admitted_ts is not None \
            else req.submitted_ts
        phases = {
            "admission_wait_ms":
                max(0.0, admitted - req.submitted_ts) * 1e3,
        }
        if req.admitted_perf is not None:
            phases["queue_wait_ms"] = \
                max(0.0, t_deq - req.admitted_perf) * 1e3
            get_registry().trace.add_span(
                "queue_wait", req.admitted_perf, t_deq, cat="phase",
                tile=req.tile,
            )
        return phases

    def _trace_block(self, req: ServeRequest, phases: Dict) -> dict:
        """The response's ``trace`` stamp (finalised in _respond: the
        dump phase and e2e close when the answer is published)."""
        out = {
            "request_id": req.request_id,
            "phases": {k: round(v, 3) for k, v in phases.items()},
            "admitted_ts": req.admitted_ts,
            "replayed": req.replayed,
            "_anchor_perf": time.perf_counter(),
        }
        if req.batch_id is not None:
            out["batch_id"] = req.batch_id
            out["batch_size"] = req.batch_size
        return out

    def _process_traced(self, req: ServeRequest, member=None) -> None:
        reg = get_registry()
        # The request KIND is part of the response identity: a smoothed
        # (reanalysis) answer and the forward analysis for the same
        # (tile, date) are different products.
        key = (req.tile, req.date.isoformat(), req.smoothed)
        t_deq = time.perf_counter()
        phases = self._wait_phases(req, t_deq)
        request_log.note_inflight(req.request_id, stage="solving")
        try:
            if req.deadline is not None:
                req.deadline.check(f"request {req.request_id}")
        except DeadlineExceeded as exc:
            if member is not None:
                # Leave the rendezvous BEFORE the respond write: batch
                # peers must never wait on a cancelled member's I/O.
                member.close()
            self._m["cancelled"].inc()
            reg.emit(
                "request_cancelled", request_id=req.request_id,
                tile=req.tile, date=req.date.isoformat(),
                waited_s=round(time.time() - req.submitted_ts, 3),
            )
            self._finish(req, {
                "status": "cancelled", "reason": "deadline",
                "detail": str(exc), "tile": req.tile,
                "date": req.date.isoformat(),
            }, phases)
            return
        # A reanalysis answer is a function of the WHOLE chain, and the
        # chain grows with every forward serve — caching one would pin a
        # stale smoothed state past the next checkpoint.  Forward
        # answers are append-only facts; only those are cacheable.
        with self._cache_lock:
            cached = None if req.smoothed else self._cache.get(key)
        if cached is not None:
            if member is not None:
                # A cache-hit member leaves immediately; its batch
                # peers rendezvous without it (mixed hit/miss groups).
                member.close()
            self._m["cache_hits"].inc()
            body = dict(cached)
            body.pop("trace", None)
            body["served_from"] = "cache"
            self._finish_ok(req, body, phases)
            return

        def solve():
            faults.fault_point(
                "serve.solve", request=req.request_id, tile=req.tile,
            )
            session = self.sessions[req.tile]
            # All solve dispatch goes through the sanctioned executor
            # module (kafkalint rule 22).  Only a batch member's FIRST
            # attempt is coalesced: whatever its outcome, the member
            # leaves the rendezvous right there (inside the finally —
            # peers never wait on this request's retry backoff or
            # response write), and any retry runs solo.
            if member is not None and not member.used:
                member.used = True
                try:
                    return batching.solve_session(
                        session, req.date, smoothed=req.smoothed,
                        dispatcher=member.dispatcher(),
                    )
                finally:
                    member.close()
            return batching.solve_session(
                session, req.date, smoothed=req.smoothed,
            )

        try:
            if req.replayed:
                # Satellite: a journal-replayed request shows a visible
                # `replayed` span continuing the original trace — not a
                # fresh waterfall under a fresh id.
                with trace_span("replayed", tile=req.tile):
                    body = self._retry.call(solve, site="serve.solve")
            else:
                body = self._retry.call(solve, site="serve.solve")
        except BaseException as exc:
            if classify_failure(exc) == FATAL:
                raise
            self._m["errors"].inc()
            reg.emit(
                "request_error", request_id=req.request_id,
                tile=req.tile, date=req.date.isoformat(),
                error=repr(exc)[:300],
            )
            self._finish(req, {
                "status": "error", "error": repr(exc)[:300],
                "tile": req.tile, "date": req.date.isoformat(),
            }, phases)
            return
        body = dict(body)
        phases.update(body.pop("trace_phases", {}))
        if member is not None and member.batch_spans:
            # Device time this request spent inside coalesced launches
            # (amortised across the members riding each launch).
            phases["serve_batch_ms"] = round(sum(
                (t1 - t0) * 1e3 for t0, t1 in member.batch_spans
            ), 3)
        if not req.smoothed:
            with self._cache_lock:
                self._cache[key] = body
                self._cache.move_to_end(key)
                while len(self._cache) > self._cache_size:
                    self._cache.popitem(last=False)
        self._finish_ok(req, body, phases)

    def _finish(self, req: ServeRequest, body: dict,
                phases: Dict) -> None:
        """Terminal path for cancelled/error responses: stamp the
        trace, publish, record the wide event."""
        body = dict(body)
        body.pop("trace_phases", None)
        body["trace"] = self._trace_block(req, phases)
        self._respond(req, body)
        self._record_request(req, body)

    def _finish_ok(self, req: ServeRequest, body: dict,
                   phases: Optional[Dict] = None) -> None:
        latency = time.time() - req.submitted_ts
        body = dict(body)
        body.pop("trace_phases", None)
        body["request_id"] = req.request_id
        body["latency_ms"] = round(latency * 1e3, 3)
        if phases is not None:
            body["trace"] = self._trace_block(req, phases)
        if not req.replayed:
            self._m["latency"].observe(latency)
        get_registry().emit(
            "request_done", request_id=req.request_id, tile=req.tile,
            date=req.date.isoformat(),
            served_from=body.get("served_from"),
            latency_ms=body["latency_ms"],
        )
        self._respond(req, body)
        self._record_request(req, body)

    def _record_request(self, req: ServeRequest, body: dict) -> None:
        """One wide event per finished admitted request — the replica
        half of request_log.jsonl (the router writes its own with the
        relay/failover phases folded in)."""
        trace = body.get("trace") or {}
        request_log.record(request_log.build_record(
            "serve", req.request_id,
            status=body.get("status", "?"),
            e2e_ms=trace.get("e2e_ms", body.get("latency_ms")),
            phases=trace.get("phases"),
            tile=req.tile, date=req.date.isoformat(),
            served_from=body.get("served_from"),
            smoothed=req.smoothed or None,
            replayed=req.replayed or None,
            solver_health=body.get("solver_health"),
            quality=body.get("quality"),
            batch_id=req.batch_id,
            batch_size=req.batch_size,
        ))

    def requestz(self, n: int = 32) -> dict:
        """The ``/requestz`` payload: in-flight + last-N completed."""
        return request_log.requestz(n)

    def _respond(self, req: ServeRequest, body: dict) -> None:
        body.setdefault("request_id", req.request_id)
        trace = body.get("trace")
        if isinstance(trace, dict):
            # Close the attribution window at publish time: dump picks
            # up everything since the solve returned (packing, cache
            # bookkeeping, serialisation prep); e2e_ms is the SERVER's
            # submit->publish wall, the denominator trace_report and
            # loadgen's serve_trace_coverage use.
            anchor = trace.pop("_anchor_perf", None)
            if anchor is not None:
                trace["phases"]["dump_ms"] = round(
                    trace["phases"].get("dump_ms", 0.0)
                    + max(0.0, time.perf_counter() - anchor) * 1e3, 3,
                )
            now = time.time()
            trace["responded_ts"] = round(now, 6)
            trace["e2e_ms"] = round(
                max(0.0, now - req.submitted_ts) * 1e3, 3,
            )

        def write():
            faults.fault_point("serve.respond", request=req.request_id)
            return self._publish(req.request_id, body)

        try:
            self._retry.call(write, site="serve.respond")
        except BaseException as exc:
            if classify_failure(exc) == FATAL:
                raise
            # The solve's effects are durable (checkpoints); only the
            # answer is lost.  Counted + logged — and because no
            # response file exists, a restart's replay re-serves it.
            self._m["respond_errors"].inc()
            get_registry().emit(
                "respond_failed", request_id=req.request_id,
                error=repr(exc)[:300],
            )
            LOG.error("response write for %s failed: %r",
                      req.request_id, exc)

    def _publish(self, request_id: str, body: dict) -> str:
        path = self.journal.respond(request_id, body)
        with self._responded:
            self._responded.notify_all()
        return path

    def _set_depth(self) -> None:
        with self._cond:
            self._set_depth_locked()

    def _set_depth_locked(self) -> None:
        self._m["depth"].set(len(self._queue))
