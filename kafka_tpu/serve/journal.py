"""Crash-safe request journal + atomic response store.

The daemon's durable memory is two filesystem structures under its
serve root:

``requests.jsonl``
    append-only journal: every ADMITTED request is recorded before it
    enters the work queue (rejected requests are answered, not
    journaled — there is nothing to recover).  One JSON object per
    line; a torn final line (crash mid-append) is skipped with an
    event, never a crashed restart.

``responses/<request_id>.json``
    one atomic file per answered request (unique tmp + ``os.replace``,
    the marker-write discipline of the chunk queue) — the client-visible
    result AND the journal's completion marker.

**Replay.**  On restart, every journaled request with no response file
is re-enqueued in submission order.  Serving is deterministic and the
response write is atomic, so replay is idempotent: a request that
crashed after its solve but before its respond simply re-runs from the
warm checkpoint and overwrites nothing (its response did not exist);
a request that crashed mid-response-write left only a tmp file, which
is ignored.  Duplicate journal lines (same id) replay once.

**Compaction.**  A long-lived daemon's journal grows without bound,
so ``rotate_bytes`` caps it (mirroring the events.jsonl rotation):
once the live journal passes the cap, every ANSWERED entry — its
response file is the completion marker, and the serve that produced it
already checkpointed — is moved into a rotated segment
(``requests.jsonl.1`` newest, shifted up to ``keep`` segments) and the
live journal is atomically rewritten with only the pending entries.
Replay scans the rotated segments too (oldest first), so an entry is
recoverable wherever the rotation boundary fell.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
from typing import Dict, List, Optional

from ..telemetry import get_registry

LOG = logging.getLogger(__name__)

JOURNAL_NAME = "requests.jsonl"
RESPONSES_DIR = "responses"

#: per-process unique response tmp names (pid + counter), same twin as
#: the scheduler/checkpoint atomic writers.
_TMP_COUNTER = itertools.count()


class RequestJournal:
    """One serve root's journal + response store.

    ``rotate_bytes=None`` (the default) disables compaction; ``keep``
    bounds the rotated answered-entry segments kept on disk.
    """

    def __init__(self, root: str, rotate_bytes: Optional[int] = None,
                 keep: int = 3):
        self.root = root
        self.journal_path = os.path.join(root, JOURNAL_NAME)
        self.responses_dir = os.path.join(root, RESPONSES_DIR)
        os.makedirs(self.responses_dir, exist_ok=True)
        self._rotate_bytes = rotate_bytes
        self._keep = int(keep)
        self._fh = open(self.journal_path, "a", buffering=1)
        try:
            self._bytes = os.path.getsize(self.journal_path)
        except OSError:
            self._bytes = 0

    # -- journal --------------------------------------------------------

    def record(self, payload: dict) -> None:
        """Append one admitted request; flushed + fsynced so an admitted
        request survives a crash that follows immediately."""
        line = json.dumps(payload, default=str) + "\n"
        self._fh.write(line)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._bytes += len(line)
        if self._rotate_bytes is not None and \
                self._bytes >= self._rotate_bytes:
            self._compact()

    def _segment_paths(self) -> List[str]:
        """Existing rotated segments, OLDEST first (.N is oldest —
        the shift direction of the events.jsonl rotation)."""
        out = []
        i = 1
        while os.path.exists(f"{self.journal_path}.{i}"):
            out.append(f"{self.journal_path}.{i}")
            i += 1
        return list(reversed(out))

    def _compact(self) -> None:
        """Rotate answered entries out of the live journal (see module
        docstring).  A compaction pass that finds nothing answered is a
        no-op — the journal cannot shrink below its pending set."""
        answered: List[str] = []
        pending: List[str] = []
        try:
            with open(self.journal_path) as f:
                lines = f.readlines()
        except OSError:
            return
        for line in lines:
            stripped = line.strip()
            if not stripped:
                continue
            try:
                payload = json.loads(stripped)
                rid = payload.get("request_id")
            except ValueError:
                rid = None
            if isinstance(rid, str) and \
                    os.path.exists(self.response_path(rid)):
                answered.append(stripped)
            else:
                # Pending work and forensic residue (torn/id-less
                # lines) stay in the live journal — compaction must
                # never make an unanswered request unreplayable.
                pending.append(stripped)
        if not answered:
            return
        # Shift the keep-window (newest rotated segment is .1), write
        # the freshly-answered batch as the new .1, then atomically
        # rewrite the live journal with only the pending lines.
        for i in range(self._keep - 1, 0, -1):
            src = f"{self.journal_path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.journal_path}.{i + 1}")
        drop = f"{self.journal_path}.{self._keep + 1}"
        if os.path.exists(drop):
            os.unlink(drop)
        if self._keep > 0:
            seg_tmp = f"{self.journal_path}.1.tmp.{os.getpid()}"
            with open(seg_tmp, "w") as f:
                f.write("".join(s + "\n" for s in answered))
                f.flush()
                os.fsync(f.fileno())
            os.replace(seg_tmp, f"{self.journal_path}.1")
        live_tmp = f"{self.journal_path}.tmp.{os.getpid()}." \
                   f"{next(_TMP_COUNTER)}"
        with open(live_tmp, "w") as f:
            f.write("".join(s + "\n" for s in pending))
            f.flush()
            os.fsync(f.fileno())
        self._fh.close()
        os.replace(live_tmp, self.journal_path)
        self._fh = open(self.journal_path, "a", buffering=1)
        try:
            self._bytes = os.path.getsize(self.journal_path)
        except OSError:
            self._bytes = 0
        reg = get_registry()
        reg.counter(
            "kafka_serve_journal_compactions_total",
            "requests.jsonl compaction passes (answered entries "
            "rotated into size-capped segments)",
        ).inc()
        reg.emit(
            "journal_compacted", rotated=len(answered),
            retained=len(pending), path=self.journal_path,
        )

    def _iter_journal_lines(self):
        """(path, lineno, raw_line) over rotated segments oldest-first,
        then the live journal — submission order across rotations."""
        for path in self._segment_paths() + [self.journal_path]:
            if not os.path.exists(path):
                continue
            try:
                with open(path) as f:
                    for lineno, line in enumerate(f, start=1):
                        yield path, lineno, line
            except OSError:
                continue

    def replay(self) -> List[dict]:
        """Journaled request payloads with no response, oldest first —
        rotated segments included, so replay is correct wherever the
        compaction boundary fell."""
        seen: Dict[str, dict] = {}
        for path, lineno, line in self._iter_journal_lines():
            line = line.strip()
            if not line:
                continue
            try:
                payload = json.loads(line)
            except ValueError:
                # A torn tail is the signature of a crash mid-append;
                # the work it described was never acked as queued.
                get_registry().emit(
                    "journal_torn_line", line_no=lineno, path=path,
                )
                LOG.warning(
                    "skipping torn journal line %d in %s", lineno, path,
                )
                continue
            rid = payload.get("request_id")
            if isinstance(rid, str) and rid not in seen:
                seen[rid] = payload
        return [p for rid, p in seen.items()
                if not os.path.exists(self.response_path(rid))]

    # -- responses ------------------------------------------------------

    def response_path(self, request_id: str) -> str:
        return os.path.join(self.responses_dir, f"{request_id}.json")

    def respond(self, request_id: str, payload: dict) -> str:
        """Atomically publish one response (unique tmp + os.replace —
        a reader can never observe a torn response)."""
        path = self.response_path(request_id)
        tmp = f"{path}.tmp.{os.getpid()}.{next(_TMP_COUNTER)}"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def response(self, request_id: str) -> Optional[dict]:
        try:
            with open(self.response_path(request_id)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            # Unreadable response = no response; replay will re-serve.
            return None

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:  # already closed / torn down — nothing held
            pass
