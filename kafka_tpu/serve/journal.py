"""Crash-safe request journal + atomic response store.

The daemon's durable memory is two filesystem structures under its
serve root:

``requests.jsonl``
    append-only journal: every ADMITTED request is recorded before it
    enters the work queue (rejected requests are answered, not
    journaled — there is nothing to recover).  One JSON object per
    line; a torn final line (crash mid-append) is skipped with an
    event, never a crashed restart.

``responses/<request_id>.json``
    one atomic file per answered request (unique tmp + ``os.replace``,
    the marker-write discipline of the chunk queue) — the client-visible
    result AND the journal's completion marker.

**Replay.**  On restart, every journaled request with no response file
is re-enqueued in submission order.  Serving is deterministic and the
response write is atomic, so replay is idempotent: a request that
crashed after its solve but before its respond simply re-runs from the
warm checkpoint and overwrites nothing (its response did not exist);
a request that crashed mid-response-write left only a tmp file, which
is ignored.  Duplicate journal lines (same id) replay once.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
from typing import Dict, List, Optional

from ..telemetry import get_registry

LOG = logging.getLogger(__name__)

JOURNAL_NAME = "requests.jsonl"
RESPONSES_DIR = "responses"

#: per-process unique response tmp names (pid + counter), same twin as
#: the scheduler/checkpoint atomic writers.
_TMP_COUNTER = itertools.count()


class RequestJournal:
    """One serve root's journal + response store."""

    def __init__(self, root: str):
        self.root = root
        self.journal_path = os.path.join(root, JOURNAL_NAME)
        self.responses_dir = os.path.join(root, RESPONSES_DIR)
        os.makedirs(self.responses_dir, exist_ok=True)
        self._fh = open(self.journal_path, "a", buffering=1)

    # -- journal --------------------------------------------------------

    def record(self, payload: dict) -> None:
        """Append one admitted request; flushed + fsynced so an admitted
        request survives a crash that follows immediately."""
        line = json.dumps(payload, default=str) + "\n"
        self._fh.write(line)
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def replay(self) -> List[dict]:
        """Journaled request payloads with no response, oldest first."""
        if not os.path.exists(self.journal_path):
            return []
        seen: Dict[str, dict] = {}
        with open(self.journal_path) as f:
            for lineno, line in enumerate(f, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    payload = json.loads(line)
                except ValueError:
                    # A torn tail is the signature of a crash mid-append;
                    # the work it described was never acked as queued.
                    get_registry().emit(
                        "journal_torn_line", line_no=lineno,
                        path=self.journal_path,
                    )
                    LOG.warning(
                        "skipping torn journal line %d in %s",
                        lineno, self.journal_path,
                    )
                    continue
                rid = payload.get("request_id")
                if isinstance(rid, str) and rid not in seen:
                    seen[rid] = payload
        return [p for rid, p in seen.items()
                if not os.path.exists(self.response_path(rid))]

    # -- responses ------------------------------------------------------

    def response_path(self, request_id: str) -> str:
        return os.path.join(self.responses_dir, f"{request_id}.json")

    def respond(self, request_id: str, payload: dict) -> str:
        """Atomically publish one response (unique tmp + os.replace —
        a reader can never observe a torn response)."""
        path = self.response_path(request_id)
        tmp = f"{path}.tmp.{os.getpid()}.{next(_TMP_COUNTER)}"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def response(self, request_id: str) -> Optional[dict]:
        try:
            with open(self.response_path(request_id)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            # Unreadable response = no response; replay will re-serve.
            return None

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:  # already closed / torn down — nothing held
            pass
