"""Serve-request vocabulary: the one wire format of the serving layer.

A request is a small JSON object::

    {"request_id": "a1b2", "tile": "tile0", "date": "2017-07-05",
     "deadline_s": 30.0, "smoothed": false}

``request_id`` must be filesystem-safe (it names the response file);
``date`` is the observation date whose analysis the client wants —
ISO ``YYYY-MM-DD`` or a full isoformat timestamp.  ``smoothed=true``
asks for the REANALYSIS estimate instead: the RTS-smoothed state for
that date, answered from the tile's checkpoint chain (read-only work —
any replica sharing the chain can serve it).  Anything malformed
raises :class:`BadRequest`, which the service converts into a counted
rejection (a bad request must never crash a daemon that other tenants
share).
"""

from __future__ import annotations

import dataclasses
import datetime
import os
import re
import time
from typing import Optional

from ..resilience import Deadline

#: response-file-safe request ids (the id becomes ``responses/<id>.json``).
_ID_RE = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")


class BadRequest(ValueError):
    """A request the daemon must reject, not die on."""

    kafka_failure_class = "poison"


def new_request_id() -> str:
    """THE sanctioned request-id origin (kafkalint rule 17
    ``request-id-origin``): a request id doubles as the per-request
    trace key, so it must be minted exactly once — here, at admission —
    and propagated on the wire.  A second minting site anywhere in
    ``serve/`` would fork the trace: the router's spans and the
    replica's spans would carry different ids for one request."""
    return os.urandom(8).hex()


@dataclasses.dataclass
class ServeRequest:
    """One admitted unit of serving work."""

    request_id: str
    tile: str
    date: datetime.datetime
    deadline_s: Optional[float]
    submitted_ts: float
    #: live wall-clock budget (resilience.Deadline); None for requests
    #: replayed from the journal — replay exists to recover work a crash
    #: interrupted, so its age must not cancel it.
    deadline: Optional[Deadline] = None
    replayed: bool = False
    #: wall-clock admission stamp (set by the admitting process, rides
    #: the journal line and the wire so admission_wait attribution and
    #: trace continuation survive crash replay and forwarding).
    admitted_ts: Optional[float] = None
    #: perf_counter reading at enqueue (process-local, NOT serialised) —
    #: the queue_wait span's start endpoint.
    admitted_perf: Optional[float] = None
    #: reanalysis request kind: answer with the RTS-smoothed state from
    #: the checkpoint chain instead of the live filter analysis.
    smoothed: bool = False
    #: coalesced-serving stamps, set by the worker when this request was
    #: served as a member of an admission micro-batch (process-local;
    #: they ride the response trace and the request_log wide event).
    batch_id: Optional[str] = None
    batch_size: Optional[int] = None

    def payload(self) -> dict:
        """The journal line (and the client-visible echo)."""
        out = {
            "request_id": self.request_id,
            "tile": self.tile,
            "date": self.date.isoformat(),
            "deadline_s": self.deadline_s,
            "submitted_ts": round(self.submitted_ts, 6),
        }
        if self.smoothed:
            out["smoothed"] = True
        if self.admitted_ts is not None:
            out["admitted_ts"] = round(self.admitted_ts, 6)
        return out


def parse_date(text) -> datetime.datetime:
    if isinstance(text, datetime.datetime):
        return text
    if not isinstance(text, str):
        raise BadRequest(f"date must be an ISO string, got {type(text)}")
    try:
        return datetime.datetime.fromisoformat(text)
    except ValueError as exc:
        raise BadRequest(f"unparseable date {text!r}") from exc


def parse_request(payload, default_tile: Optional[str] = None,
                  default_deadline_s: Optional[float] = None,
                  replayed: bool = False) -> ServeRequest:
    """Validate one raw payload into a :class:`ServeRequest`.

    ``replayed=True`` marks a journal-recovered request: the original
    ``submitted_ts`` is kept for the record but no live deadline is
    attached (see :class:`ServeRequest.deadline`).
    """
    if not isinstance(payload, dict):
        raise BadRequest(f"request must be a JSON object, got "
                         f"{type(payload).__name__}")
    request_id = payload.get("request_id") or new_request_id()
    if not isinstance(request_id, str) or not _ID_RE.match(request_id):
        raise BadRequest(f"request_id {request_id!r} is not a short "
                         "filesystem-safe token")
    tile = payload.get("tile", default_tile)
    if not isinstance(tile, str) or not tile:
        raise BadRequest("request names no tile")
    if "date" not in payload:
        raise BadRequest("request names no observation date")
    date = parse_date(payload["date"])
    deadline_s = payload.get("deadline_s", default_deadline_s)
    if deadline_s is not None:
        try:
            deadline_s = float(deadline_s)
        except (TypeError, ValueError) as exc:
            raise BadRequest(
                f"deadline_s {payload.get('deadline_s')!r} is not a "
                "number") from exc
        if deadline_s <= 0:
            raise BadRequest(f"deadline_s must be positive, got "
                             f"{deadline_s}")
    smoothed = payload.get("smoothed", False)
    if not isinstance(smoothed, bool):
        raise BadRequest(
            f"smoothed must be a JSON boolean, got {smoothed!r}"
        )
    submitted = payload.get("submitted_ts")
    if not isinstance(submitted, (int, float)):
        submitted = time.time()
    admitted = payload.get("admitted_ts")
    if not isinstance(admitted, (int, float)):
        admitted = None
    deadline = None
    if deadline_s is not None and not replayed:
        deadline = Deadline(deadline_s)
    return ServeRequest(
        request_id=request_id, tile=tile, date=date,
        deadline_s=deadline_s, submitted_ts=float(submitted),
        deadline=deadline, replayed=replayed,
        admitted_ts=None if admitted is None else float(admitted),
        smoothed=smoothed,
    )
