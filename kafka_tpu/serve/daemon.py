"""The resident serving daemon: filesystem transport + signal handling.

Transport follows the repo's coordinator-free idiom (the chunk queue,
PR 7): the SHARED FILESYSTEM is the wire.  Under one serve root:

``inbox/<name>.json``
    client-submitted requests.  Clients write a tmp file and rename it
    in (``submit_request``), so the daemon never reads a torn request.
    The daemon consumes files in name order and unlinks each after the
    submit decision (the decision itself is durable: admitted requests
    are journaled, rejections are answered).
``requests.jsonl`` / ``responses/<id>.json``
    the crash-safe journal + atomic response store (``serve.journal``).

**Signals** (the PR 7 handler-chaining convention): the FIRST SIGTERM
requests a graceful drain — the service stops admitting (new inbox
files are answered ``rejected: draining``), in-flight and queued
requests finish, tile state is already checkpointed, and the daemon
exits 0.  The handler restores the previous handler on first use, so a
second SIGTERM terminates through the normal chain (flight recorder
included).  SIGKILL is the crash path: the journal replays unanswered
requests on the next start, resuming from the warm checkpoints.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Optional

from ..telemetry import get_registry
from .journal import RESPONSES_DIR  # noqa: F401  (re-export for clients)
from .request import new_request_id
from .service import AssimilationService

LOG = logging.getLogger(__name__)

INBOX_DIR = "inbox"


# ---------------------------------------------------------------------------
# Client helpers (used by tools/loadgen.py and tests).
# ---------------------------------------------------------------------------

def submit_request(root: str, payload: dict) -> str:
    """Atomically drop one request into a daemon's inbox; returns the
    request id (generated when the payload carries none).  The client
    submission stamp makes the inbox wait attributable: without it the
    server would start the request's clock at parse time and the time
    the file sat in ``inbox/`` would be invisible to the per-request
    trace (ISSUE 14 admission_wait)."""
    payload = dict(payload)
    payload.setdefault("request_id", new_request_id())
    payload.setdefault("submitted_ts", round(time.time(), 6))
    inbox = os.path.join(root, INBOX_DIR)
    os.makedirs(inbox, exist_ok=True)
    name = f"{payload['request_id']}.json"
    tmp = os.path.join(inbox, f".{name}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(inbox, name))
    return payload["request_id"]


def read_response(root: str, request_id: str) -> Optional[dict]:
    """One response, or None while unanswered."""
    try:
        with open(os.path.join(
                root, RESPONSES_DIR, f"{request_id}.json")) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# The daemon loop.
# ---------------------------------------------------------------------------

def _install_drain(drain: threading.Event):
    """First SIGTERM sets the drain flag and restores the PREVIOUS
    handler (PR 7 convention — the second SIGTERM terminates through the
    normal chain, flight recorder included).  No-op off the main
    thread."""
    import signal

    if threading.current_thread() is not threading.main_thread():
        return None
    prev = signal.getsignal(signal.SIGTERM)

    def handler(signum, frame):
        drain.set()
        get_registry().emit("serve_drain_signal", signal="SIGTERM")
        signal.signal(signal.SIGTERM, prev or signal.SIG_DFL)

    signal.signal(signal.SIGTERM, handler)
    return prev


def _restore_drain(prev) -> None:
    import signal

    if prev is None:
        return
    try:
        signal.signal(signal.SIGTERM, prev)
    except ValueError:  # left the main thread since install — nothing held
        pass


class ServeDaemon:
    """Run an :class:`AssimilationService` against a filesystem inbox
    until drained (SIGTERM / ``drain()``) or — with
    ``exit_when_idle`` — until the queue stays empty for
    ``idle_grace_s`` (the one-shot mode crash-recovery replays and
    batch clients use)."""

    def __init__(
        self,
        service: AssimilationService,
        root: str,
        poll_interval_s: float = 0.05,
        exit_when_idle: bool = False,
        idle_grace_s: float = 1.0,
        fleet_dir: Optional[str] = None,
        fleet_refresh_s: float = 5.0,
        fleet_ttl_s: Optional[float] = None,
    ):
        self.service = service
        self.root = root
        self.inbox = os.path.join(root, INBOX_DIR)
        os.makedirs(self.inbox, exist_ok=True)
        self.poll_interval_s = float(poll_interval_s)
        self.exit_when_idle = bool(exit_when_idle)
        self.idle_grace_s = float(idle_grace_s)
        #: fleet awareness (optional): a telemetry root holding the
        #: workers' live_<host>_<pid>.json heartbeats.  The daemon
        #: refreshes kafka_fleet_dead_hosts from it so admission can
        #: shed when the fleet degrades (AdmissionPolicy.max_dead_hosts).
        self.fleet_dir = fleet_dir
        self.fleet_refresh_s = float(fleet_refresh_s)
        self.fleet_ttl_s = fleet_ttl_s
        self._fleet_next = 0.0
        self._drain = threading.Event()

    def _refresh_fleet_gauge(self) -> None:
        """Read the live snapshots under ``fleet_dir`` and publish the
        dead-host count as the admission gauge.  Runs inline on the poll
        loop (bounded: a directory walk + a few json.loads), throttled
        to ``fleet_refresh_s``."""
        if not self.fleet_dir:
            return
        now = time.monotonic()
        if now < self._fleet_next:
            return
        self._fleet_next = now + self.fleet_refresh_s
        from ..telemetry.aggregate import (
            load_live_snapshots, worker_liveness,
        )

        me = f"{socket.gethostname()}:{os.getpid()}"
        liveness = worker_liveness(
            load_live_snapshots(self.fleet_dir), ttl_s=self.fleet_ttl_s,
        )
        dead = sorted(
            key for key, w in liveness.items()
            if w["dead"] and key != me
        )
        reg = get_registry()
        gauge = reg.gauge(
            "kafka_fleet_dead_hosts",
            "workers whose live-snapshot heartbeat went stale without a "
            "clean-shutdown marker (the fleet view's dead-host count; "
            "admission sheds on it via max_dead_hosts)",
        )
        prev = gauge.value()
        gauge.set(len(dead))
        if len(dead) != (prev or 0) and (dead or prev):
            reg.emit("fleet_dead_hosts_changed", dead=dead)

    def drain(self) -> None:
        """Programmatic SIGTERM equivalent."""
        self._drain.set()

    def _scan_inbox(self) -> int:
        """Submit every parseable inbox file (name order); returns how
        many files were consumed.  Submission is the durability point,
        so each file is unlinked after its decision."""
        try:
            names = sorted(
                n for n in os.listdir(self.inbox) if n.endswith(".json")
            )
        except OSError:
            return 0
        consumed = 0
        for name in names:
            path = os.path.join(self.inbox, name)
            try:
                with open(path) as f:
                    payload = json.load(f)
            except FileNotFoundError:
                continue  # raced another consumer
            except (OSError, ValueError) as exc:
                get_registry().emit(
                    "request_unparseable", file=name,
                    error=repr(exc)[:200],
                )
                LOG.warning("dropping unparseable request file %s: %r",
                            name, exc)
                self._unlink(path)
                consumed += 1
                continue
            self.service.submit(payload)
            self._unlink(path)
            consumed += 1
        return consumed

    @staticmethod
    def _unlink(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:  # raced another consumer — outcome identical
            pass

    def run(self) -> dict:
        """The resident loop; returns the run summary."""
        reg = get_registry()
        prev_handler = _install_drain(self._drain)
        self.service.start()
        reg.emit("serve_started", root=self.root,
                 tiles=sorted(self.service.sessions))
        t0 = time.time()
        idle_since: Optional[float] = None
        try:
            while not self._drain.is_set():
                self._refresh_fleet_gauge()
                consumed = self._scan_inbox()
                if consumed == 0 and self.service.pending() == 0:
                    if self.exit_when_idle:
                        now = time.monotonic()
                        if idle_since is None:
                            idle_since = now
                        elif now - idle_since >= self.idle_grace_s:
                            break
                else:
                    idle_since = None
                # Event.wait doubles as the poll sleep so a SIGTERM
                # interrupts the wait immediately.
                self._drain.wait(self.poll_interval_s)
            drained = self._drain.is_set()
            if drained:
                # Graceful drain: stop admitting FIRST, then keep
                # answering latecomer inbox files with explicit
                # ``rejected: draining`` responses for as long as the
                # already-admitted work is finishing — new requests are
                # rejected, never silently ignored.
                self.service.stop_admitting()
                while not self.service.drain(
                        timeout_s=max(self.poll_interval_s, 0.05)):
                    self._scan_inbox()
                self._scan_inbox()
        finally:
            self.service.close()
            _restore_drain(prev_handler)
        flat = reg.flat()
        summary = {
            "mode": "serve",
            "root": self.root,
            "drained": self._drain.is_set(),
            "wall_s": round(time.time() - t0, 3),
            "admitted": int(flat.get("kafka_serve_admitted_total", 0)),
            "replayed": int(flat.get("kafka_serve_replayed_total", 0)),
            "cancelled": int(flat.get("kafka_serve_cancelled_total", 0)),
            "errors": int(flat.get("kafka_serve_errors_total", 0)),
            "rejected": int(sum(
                v for k, v in flat.items()
                if k.startswith("kafka_serve_rejected_total")
            )),
        }
        reg.emit("serve_stopped", **summary)
        return summary
