"""Coalesced serving: the sanctioned batch executor.

This module is the ONE place the serving layer may dispatch solve work
(kafkalint rule 22 ``unbatched-serve-dispatch``): every
``TileSession.serve`` call and every per-date device dispatch on the
serve path funnels through here, so batching semantics — and their
bit-identity guarantee — cannot be bypassed by a new call site.

The coalescing design (BASELINE.md "Coalesced serving"):

Admission groups compatible queued requests by COARSE shape bucket
(:func:`probe_bucket`): padded pixel-batch size ``n_pad``, parameter
count ``p``, band count, structural solver options and the operator
fingerprint.  The service then runs each member's FULL serve pipeline
concurrently (one thread per member, distinct tiles only — sessions are
not thread-safe), with the engine's per-date dispatch replaced by a
rendezvous post (:class:`_Rendezvous`).  When every live member has
posted, the last poster executes the round: posts with identical EXACT
keys (argument avals + statics) ride one stacked
``core.solvers.assimilate_date_batch_jit`` launch — a ``vmap`` over the
member axis, NOT pixel concatenation, so each member keeps its own
convergence norm and iteration count and its output slice is
bit-identical to a solo ``assimilate_date_jit`` call.  Posts that don't
group (cold/warm members mid-run on different windows, odd shapes)
execute solo through the member's own unbatched program.

Membership is dynamic: a member leaves on finish or error (a poison
request errors alone — its peers simply rendezvous without it), and a
leave triggers execution when everyone still in is already posted.
Members whose serve runs more windows than their peers' keep posting
after the others left and finish on plain solo launches.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import solver_health, solvers
from ..telemetry import get_registry

LOG = logging.getLogger(__name__)


def _batch_metrics(reg):
    """Rendezvous-level launch counters (the one owning site)."""
    return {
        "launches": reg.counter(
            "kafka_serve_batch_launches_total",
            "device launches issued by the serve batch executor's "
            "rendezvous (coalesced and solo rounds alike)",
        ),
        "launch_members": reg.counter(
            "kafka_serve_batch_launch_members_total",
            "solve members carried by rendezvous launches — divided by "
            "launches this is the mean device-level batch size",
        ),
        "coalesced": reg.counter(
            "kafka_serve_batch_coalesced_total",
            "rendezvous launches that stacked two or more members into "
            "one vmapped device program",
        ),
    }


# ---------------------------------------------------------------------------
# shape buckets
# ---------------------------------------------------------------------------

class ShapeBucket:
    """One tile's serve-compatibility fingerprint plus the
    representative pieces AOT lowering needs.  Two sessions whose
    buckets share ``key`` may coalesce; ``linearize``/``hessian_forward``
    are the bucket's canonical statics (functionally identical across
    the bucket's tiles by construction of the key), so every coalesced
    launch of the bucket compiles exactly once."""

    def __init__(self, key, n_pad, p, n_bands, linearize,
                 hessian_forward, solver_options, example):
        self.key = key
        self.n_pad = int(n_pad)
        self.p = int(p)
        self.n_bands = int(n_bands)
        self.linearize = linearize
        self.hessian_forward = hessian_forward
        #: the per-date option dict exactly as the engine dispatches it
        self.solver_options = solver_options
        #: (bands, x0, p_inv0, aux) — representative concrete arguments
        self.example = example

    def describe(self) -> dict:
        return {
            "n_pad": self.n_pad, "p": self.p, "n_bands": self.n_bands,
            "options": sorted(
                k for k in (self.solver_options or {})
            ),
        }


def _operator_fingerprint(op) -> tuple:
    """A conservative value fingerprint of an observation operator:
    equal fingerprints mean functionally identical operators (safe to
    share one compiled program); attributes the fingerprint cannot
    inspect make the operator unique — preventing coalescing rather
    than risking a wrong shared program.  Operators may override via a
    ``serve_bucket_token()`` method."""
    token = getattr(op, "serve_bucket_token", None)
    if callable(token):
        return ("token", type(op).__module__, type(op).__qualname__,
                token())
    parts: List[Any] = [type(op).__module__, type(op).__qualname__]
    for k in sorted(vars(op) or {}):
        v = vars(op)[k]
        if isinstance(v, (bool, int, float, str, bytes, type(None))):
            parts.append((k, v))
        elif isinstance(v, (tuple, list)) and all(
                isinstance(e, (bool, int, float, str)) for e in v):
            parts.append((k, tuple(v)))
        elif isinstance(v, (np.ndarray, jnp.ndarray)):
            a = np.asarray(v)
            parts.append((k, a.shape, str(a.dtype),
                          hashlib.sha256(a.tobytes()).hexdigest()))
        else:
            # Opaque attribute: fall back to instance identity — this
            # operator only ever buckets with itself.
            parts.append((k, f"id:{id(v)}"))
    return tuple(parts)


def probe_bucket(session) -> Optional[ShapeBucket]:
    """Derive a session's :class:`ShapeBucket` from one throwaway
    filter, or ``None`` when the tile cannot coalesce: fused scan
    windows and band-sequential loops keep their own launch structure,
    Pallas kernel paths are excluded (no batching rule), and duck-typed
    sessions without a real ``TileSpec`` serve unbatched."""
    spec = getattr(session, "spec", None)
    make = getattr(spec, "make_filter", None)
    if make is None:
        return None
    kf, x0, p_inv0, output = make()
    try:
        if getattr(kf, "scan_window", 1) != 1:
            return None
        if getattr(kf, "band_sequential", False):
            return None
        dates = list(kf.observations.dates)
        if not dates:
            return None
        obs = kf.observations.get_observations(dates[0], kf.gather)
        opts = kf.date_solver_options(obs.operator)
        statics = solvers.structural_options(opts)
        use_pallas = statics[1]
        if use_pallas:
            return None
        hess = None
        if kf.hessian_correction:
            hess = getattr(obs.operator, "forward_pixel", None)
        key = (
            kf.gather.n_pad, kf.n_params, obs.operator.n_bands,
            _operator_fingerprint(obs.operator), statics,
            tuple(sorted(
                k for k in opts
                if k not in solvers.STRUCTURAL_OPTION_KEYS
            )),
            bool(kf.hessian_correction),
        )
        return ShapeBucket(
            key=key, n_pad=kf.gather.n_pad, p=kf.n_params,
            n_bands=obs.operator.n_bands,
            linearize=obs.operator.linearize, hessian_forward=hess,
            solver_options=opts,
            example=(obs.bands, x0, p_inv0, obs.aux),
        )
    finally:
        close = getattr(output, "close", None)
        if close is not None:
            close()


def session_bucket_key(session):
    """The coarse compatibility key the admission micro-window groups
    on, or ``None`` when the session cannot coalesce."""
    get = getattr(session, "serve_bucket", None)
    if get is None:
        return None
    bucket = get()
    return None if bucket is None else bucket.key


# ---------------------------------------------------------------------------
# the sanctioned serve call-through
# ---------------------------------------------------------------------------

def solve_session(session, date, smoothed: bool = False,
                  dispatcher=None) -> dict:
    """THE serve-solve entry point (kafkalint rule 22): the service's
    singleton path and every batch member funnel through here.  Plain
    calls keep the duck-typed ``serve(date)`` signature stubs rely on;
    only batch members pass a dispatcher."""
    if smoothed:
        return session.serve(date, smoothed=True)
    if dispatcher is None:
        return session.serve(date)
    return session.serve(date, dispatcher=dispatcher)


# ---------------------------------------------------------------------------
# the rendezvous
# ---------------------------------------------------------------------------

class _Post:
    """One member's blocked per-date dispatch."""

    __slots__ = ("linearize", "obs", "x", "p_inv", "aux", "opts",
                 "hess", "corrupt", "done", "result", "error")

    def __init__(self, linearize, obs, x, p_inv, aux, opts, hess,
                 corrupt):
        self.linearize = linearize
        self.obs = obs
        self.x = x
        self.p_inv = p_inv
        self.aux = aux
        self.opts = opts
        self.hess = hess
        self.corrupt = corrupt
        self.done = False
        self.result = None
        self.error = None

    def exact_key(self) -> tuple:
        """Stackability: identical avals + statics + option keys."""
        def avals(tree):
            leaves, treedef = jax.tree.flatten(tree)
            return (
                str(treedef),
                tuple((tuple(np.shape(leaf)),
                       str(jnp.result_type(leaf))) for leaf in leaves),
            )

        opts = dict(self.opts or {})
        statics = solvers.structural_options(opts)
        return (
            avals(self.obs), avals(self.x), avals(self.p_inv),
            avals(self.aux), statics, avals(opts),
            self.corrupt is None,
        )


class _Rendezvous:
    """Barrier-cycle meeting point for one admitted batch: members post
    per-date dispatches; when every live member has posted, the last
    poster (or the last leaver) executes the round and wakes everyone
    with their own slice."""

    def __init__(self, executor: "BatchExecutor", size: int):
        self._executor = executor
        self._cond = threading.Condition()
        self._active = size
        self._posted: Dict[int, _Post] = {}

    def post(self, index: int, post: _Post):
        with self._cond:
            self._posted[index] = post
            if len(self._posted) >= self._active:
                self._execute_locked()
            else:
                while not post.done:
                    self._cond.wait()
        if post.error is not None:
            raise post.error
        return post.result

    def leave(self, index: int) -> None:
        with self._cond:
            self._active -= 1
            self._posted.pop(index, None)
            if self._posted and len(self._posted) >= self._active:
                self._execute_locked()

    # -- execution (condition lock held; every live member is parked) --

    def _execute_locked(self) -> None:
        posts = self._posted
        self._posted = {}
        groups: Dict[tuple, List[_Post]] = {}
        for index in sorted(posts):
            p = posts[index]
            groups.setdefault(p.exact_key(), []).append(p)
        for key, group in groups.items():
            try:
                self._launch(key, group)
            except BaseException as exc:  # noqa: B036 — delivered to members
                for p in group:
                    p.error = exc
                    p.done = True
        self._cond.notify_all()

    def _launch(self, key: tuple, group: List[_Post]) -> None:
        metrics = self._executor.metrics()
        t0 = time.perf_counter()
        if len(group) == 1:
            p = group[0]
            # Solo round: the member's own unbatched program — the
            # exact dispatch a dispatcher-less serve would have made.
            p.result = solvers.assimilate_date_jit(
                p.linearize, p.obs, p.x, p.p_inv, p.aux, p.opts,
                p.hess,
            ) + (t0, time.perf_counter(), 1)
            p.done = True
        else:
            lin, hess = self._executor.canonical_statics(key, group[0])
            bands = jax.tree.map(
                lambda *ls: jnp.stack(ls), *[p.obs for p in group]
            )
            xs = jnp.stack([p.x for p in group])
            pis = jnp.stack([p.p_inv for p in group])
            aux = None
            if group[0].aux is not None:
                aux = jax.tree.map(
                    lambda *ls: jnp.stack(ls), *[p.aux for p in group]
                )
            bopts = solvers.stack_solver_options(
                [p.opts for p in group]
            )
            corrupt = None
            if any(p.corrupt is not None for p in group):
                n_pix = group[0].x.shape[0]
                corrupt = jnp.stack([
                    jnp.zeros((n_pix,), jnp.float32) if p.corrupt is None
                    else jnp.asarray(p.corrupt, jnp.float32)
                    for p in group
                ])
            xb, pib, diagb = solvers.assimilate_date_batch_jit(
                lin, bands, xs, pis, aux, bopts, hess, corrupt,
            )
            t1 = time.perf_counter()
            for i, p in enumerate(group):
                p.result = (
                    xb[i], pib[i],
                    jax.tree.map(lambda leaf: leaf[i], diagb),
                    t0, t1, len(group),
                )
                p.done = True
            metrics["coalesced"].inc()
        metrics["launches"].inc()
        metrics["launch_members"].inc(len(group))


class _Member:
    """One request's handle on a rendezvous: provides the engine
    dispatcher and the obligatory ``close()`` (idempotent; call it in a
    ``finally`` — success, error and cache-hit paths alike)."""

    def __init__(self, rendezvous: _Rendezvous, index: int):
        self._rendezvous = rendezvous
        self._index = index
        self._closed = False
        #: set by the service on the member's first (and only) batched
        #: solve attempt — retries run solo, after the member left.
        self.used = False
        #: (t_start, t_end) of every coalesced launch this member rode
        self.batch_spans: List[tuple] = []
        #: member counts of those launches
        self.launch_sizes: List[int] = []

    def dispatcher(self):
        """An ``assimilate_date_jit``-shaped callable that posts to the
        rendezvous instead of launching directly."""

        def dispatch(linearize, obs, x, p_inv, aux, opts, hess):
            # solver.pixel chaos hook: host-side, per member, at the
            # same point the solo path evaluates it.
            corrupt = solver_health.corruption_mask(x.shape[0])
            post = _Post(linearize, obs, x, p_inv, aux,
                         dict(opts or {}), hess, corrupt)
            x_a, p_inv_a, diags, t0, t1, size = \
                self._rendezvous.post(self._index, post)
            if size > 1:
                self.batch_spans.append((t0, t1))
                self.launch_sizes.append(size)
                get_registry().trace.add_span(
                    "serve_batch", t0, t1, cat="phase", members=size,
                )
            return x_a, p_inv_a, diags

        return dispatch

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._rendezvous.leave(self._index)


class BatchExecutor:
    """Factory for rendezvous batches + the process-wide canonical
    statics per exact key (one compiled batched program per bucket and
    batch size, however the member order shook out)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._canonical: Dict[tuple, tuple] = {}
        self._metrics = None

    def metrics(self):
        with self._lock:
            if self._metrics is None:
                self._metrics = _batch_metrics(get_registry())
            return self._metrics

    def reset_metrics(self) -> None:
        """Re-bind counters after a registry swap (tests)."""
        with self._lock:
            self._metrics = None

    def canonical_statics(self, key: tuple, post: _Post) -> tuple:
        with self._lock:
            if key not in self._canonical:
                self._canonical[key] = (post.linearize, post.hess)
            return self._canonical[key]

    def open(self, size: int) -> List[_Member]:
        """A fresh rendezvous with ``size`` member handles."""
        rendezvous = _Rendezvous(self, size)
        return [_Member(rendezvous, i) for i in range(size)]


# ---------------------------------------------------------------------------
# AOT bucket compilation
# ---------------------------------------------------------------------------

def aot_compile_buckets(sessions: dict, batch_sizes=(1,)) -> dict:
    """Ahead-of-time compile every distinct shape bucket among the
    resident tiles (daemon start): for each bucket, lower + compile the
    solo per-date program and the requested batched member counts with
    representative concrete arguments, landing the executables in the
    persistent XLA compilation cache — the first live request (and the
    first coalesced launch) then pays a cache hit, not a compile.

    Returns the ``serve_aot_buckets`` status fact: one entry per
    distinct bucket with its tiles, shapes and compile wall time.
    """
    buckets: Dict[tuple, dict] = {}
    for name in sorted(sessions):
        get = getattr(sessions[name], "serve_bucket", None)
        bucket = get() if get is not None else None
        if bucket is None:
            continue
        if bucket.key in buckets:
            buckets[bucket.key]["tiles"].append(name)
            continue
        bands, x0, p_inv0, aux = bucket.example
        t0 = time.perf_counter()
        for k in sorted(set(int(k) for k in batch_sizes)):
            if k <= 0:
                continue
            if k == 1:
                solvers.lower_date_program(
                    bucket.linearize, bands, x0, p_inv0, aux,
                    dict(bucket.solver_options),
                    bucket.hessian_forward,
                )
            else:
                stack = lambda tree: jax.tree.map(  # noqa: E731
                    lambda leaf: jnp.stack([leaf] * k), tree
                )
                solvers.lower_date_program(
                    bucket.linearize, stack(bands), stack(x0),
                    stack(p_inv0),
                    None if aux is None else stack(aux),
                    solvers.stack_solver_options(
                        [dict(bucket.solver_options)] * k
                    ),
                    bucket.hessian_forward, batch_size=k,
                )
        entry = dict(bucket.describe())
        entry.update(
            tiles=[name],
            batch_sizes=sorted(
                int(k) for k in set(batch_sizes) if int(k) > 0
            ),
            compile_ms=round((time.perf_counter() - t0) * 1e3, 3),
        )
        buckets[bucket.key] = entry
    out = list(buckets.values())
    LOG.info(
        "AOT-compiled %d serve shape bucket(s) covering %d tile(s)",
        len(out), sum(len(e["tiles"]) for e in out),
    )
    return {"count": len(out), "buckets": out}
