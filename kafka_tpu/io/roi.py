"""Pixel-window ROI plumbing shared by native-grid readers.

The MODIS-family readers (BHR, MOD09, Synergy) all work on their product's
native grid and expose the chunked-driver ROI hook the reference implements
as ``apply_roi`` (``/root/reference/kafka/input_output/observations.py:
262-267``, used per chunk at ``kafka_test_Py36.py:162``).  Grid-warping
readers (Sentinel-2/-1) resample to the chunk's state grid instead and do
not use this mixin — the driver dispatches on the presence of
``apply_roi``.
"""

from __future__ import annotations

import datetime
import glob
import logging
from typing import Callable, Dict, List, Optional, Pattern, Tuple

import numpy as np

LOG = logging.getLogger(__name__)


def index_dated_paths(
    pattern: str,
    date_regex: Pattern,
    start_time: Optional[datetime.datetime] = None,
    end_time: Optional[datetime.datetime] = None,
    transform: Optional[Callable[[str], Optional[str]]] = None,
    label: str = "granule",
) -> Dict[datetime.datetime, str]:
    """Glob ``pattern``, parse an ``A%Y%j``-style date from each basename
    with ``date_regex`` (group 1 = ``%Y%j``), filter to the time window and
    return {date: transform(path)} — the discovery loop shared by the
    MODIS-family readers.  ``transform`` may reject a path by returning
    None; duplicate dates keep the first match and warn (one tile per
    folder is assumed)."""
    import os

    out: Dict[datetime.datetime, str] = {}
    for path in sorted(glob.glob(pattern)):
        m = date_regex.search(os.path.basename(path))
        if not m:
            continue
        value = transform(path) if transform is not None else path
        if value is None:
            continue
        d = datetime.datetime.strptime(m.group(1), "%Y%j")
        if start_time is not None and d < start_time:
            continue
        if end_time is not None and d > end_time:
            continue
        if d in out:
            LOG.warning(
                "multiple %ss for %s: keeping %s, ignoring %s "
                "(one tile per folder is assumed)",
                label, d.date(), out[d], value,
            )
            continue
        out[d] = value
    return out


class RoiWindowMixin:
    """``apply_roi`` + raster windowing + geotransform shifting."""

    roi: Optional[Tuple[int, int, int, int]] = None

    def apply_roi(self, ulx: int, uly: int, lrx: int, lry: int) -> None:
        """Pixel-window ROI on the reader's native grid (ul inclusive,
        lr exclusive)."""
        self.roi = (ulx, uly, lrx, lry)

    def _window(self, arr: np.ndarray) -> np.ndarray:
        if self.roi is None:
            return arr
        ulx, uly, lrx, lry = self.roi
        return arr[uly:lry, ulx:lrx]

    def _read_windowed(self, path: str) -> np.ndarray:
        """Read a raster pre-windowed to the ROI: only the intersecting
        TIFF tiles are decoded, so a chunked run over a full tile costs
        chunk-sized I/O per chunk instead of whole-raster decodes (the
        chunk-restartability I/O property of the reference's per-chunk
        ``apply_roi``, ``kafka_test_Py36.py:162``)."""
        from .geotiff import read_geotiff, read_geotiff_window

        if self.roi is None:
            return read_geotiff(path)[0]
        ulx, uly, lrx, lry = self.roi
        return read_geotiff_window(path, uly, ulx, lry - uly, lrx - ulx)[0]

    def _shift_geotransform(self, geotransform) -> List[float]:
        """Geotransform of the ROI window (origin moved by ul offsets)."""
        gt = list(geotransform)
        if self.roi is not None:
            gt[0] += self.roi[0] * gt[1]
            gt[3] += self.roi[1] * gt[5]
        return gt

    def _require_dates(self) -> None:
        dates = getattr(self, "dates", [])
        if not dates:
            raise ValueError(
                f"{type(self).__name__}: no granules indexed under "
                f"{getattr(self, 'data_dir', '?')!r} (wrong folder, naming "
                "pattern, or start/end window)"
            )
