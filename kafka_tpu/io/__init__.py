"""Host-side raster I/O: GeoTIFF codec, output writers, chunk tiling."""

from .geotiff import GeoInfo, TiffInfo, read_geotiff, read_info, write_geotiff
from .output import GeoTIFFOutput
from .tiling import Chunk, chunk_geotransform, chunk_mask, get_chunks
