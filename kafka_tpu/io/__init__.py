"""Host-side raster I/O: GeoTIFF codec, warping, sensor readers, output
writers, chunk tiling."""

from .geotiff import (
    GeoInfo,
    TiffInfo,
    TiledTiffWriter,
    read_geotiff,
    read_geotiff_window,
    read_info,
    write_geotiff,
)
from .mod09 import MOD09Observations, decode_state_qa, zoom2_nearest
from .multi import CompositeObservations
from .modis import BHRObservations, SynergyKernels
from .output import GeoTIFFOutput
from .sentinel1 import S1Observations
from .sentinel2 import (
    Sentinel2Observations,
    find_nearest_geometry,
    geometry_bank_aux_builder,
    parse_s2_xml,
)
from .tiling import Chunk, chunk_geotransform, chunk_mask, get_chunks
from .warp import (
    from_lonlat,
    grid_mapping,
    lonlat_to_utm,
    reproject_raster,
    resample,
    to_lonlat,
    utm_to_lonlat,
)
