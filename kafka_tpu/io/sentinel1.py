"""Sentinel-1 SAR backscatter reader (NetCDF4/HDF5 via h5py).

Reproduces the observation semantics of the reference's ``S1Observations``
(``/root/reference/kafka/input_output/Sentinel1_Observations.py:56-197``):

- ``*.nc`` discovery with the acquisition datetime parsed from filename
  field 5 (``S1?_.._.._YYYYMMDDTHHMMSS_...``) (``:67-80``);
- two bands: VV then VH, read from the ``sigma0_VV``/``sigma0_VH``
  variables (``:172-179``);
- -999 treated as missing (``:24,134-152``);
- uncertainty stored as inverse variance (``:182-188``).  The reference
  ships a 5% relative placeholder with ENL refinement as an open TODO
  (``:106-132``); here the TODO is implemented: with an equivalent
  number of looks ``enl`` (constructor argument, or an ``enl`` attribute
  in the file), speckle statistics give
  ``sigma = sqrt(sigma0^2 / ENL + noise_floor^2)`` per pixel (gamma-
  distributed multi-looked intensity: std = mean/sqrt(L), plus the
  instrument's noise-equivalent sigma0 floor).  Without an ENL the 5%
  placeholder is preserved;
- the per-pixel incidence angle ``theta`` warped to the state grid and
  carried to the operator (``:191-195`` — there a TODO, here implemented:
  the WCM aux takes the real angle raster instead of the hard-coded 23
  degrees of ``sar_forward_model.py:156``).

The reference reads these files through GDAL's NetCDF driver; this image
has no GDAL, and S1 preprocessing chains emit NetCDF4 (= HDF5), so h5py is
the decoder.  Georeferencing comes from a ``geotransform`` attribute
(root or per-variable) or 1-D ``lat``/``lon`` coordinate variables.
"""

from __future__ import annotations

import datetime
import glob
import logging
import os
from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.types import BandBatch
from ..engine.protocols import DateObservation
from ..engine.state import PixelGather
from ..obsops.wcm import WCMAux, WCMOperator
from .warp import grid_mapping, resample

LOG = logging.getLogger(__name__)

MISSING_VALUE = -999.0  # Sentinel1_Observations.py:24
POLARISATIONS = ("VV", "VH")


def _read_nc_var(path: str, var: str):
    """(array, geotransform, crs) for one variable of a NetCDF4 file."""
    import h5py

    with h5py.File(path, "r") as f:
        if var not in f:
            raise KeyError(f"{var} not in {path}")
        ds = f[var]
        arr = np.asarray(ds[...], np.float32)
        gt = None
        for holder in (ds, f):
            if "geotransform" in holder.attrs:
                gt = tuple(float(v) for v in holder.attrs["geotransform"])
                break
        crs = None
        for holder in (ds, f):
            if "epsg" in holder.attrs:
                crs = int(holder.attrs["epsg"])
                break
        if gt is None and "lat" in f and "lon" in f:
            lat = np.asarray(f["lat"][...], np.float64)
            lon = np.asarray(f["lon"][...], np.float64)
            dx = (lon[-1] - lon[0]) / max(len(lon) - 1, 1)
            dy = (lat[-1] - lat[0]) / max(len(lat) - 1, 1)
            gt = (lon[0] - dx / 2, dx, 0.0, lat[0] - dy / 2, 0.0, dy)
            crs = 4326
        if gt is None:
            raise ValueError(
                f"{path}: no geotransform attribute or lat/lon coords"
            )
    return arr, gt, crs


def estimate_enl(arr: np.ndarray, missing: float = MISSING_VALUE,
                 window: int = 15, quantile: float = 0.8
                 ) -> Optional[float]:
    """Equivalent number of looks from the image's own statistics.

    For multi-looked intensity over a homogeneous area the speckle is
    gamma-distributed with ``ENL = mean^2 / variance`` — the standard
    moments estimator.  Real scenes mix homogeneous and textured areas;
    texture adds variance, biasing individual windows LOW, so the
    per-window ratio is computed over non-overlapping ``window x window``
    blocks of fully-valid pixels and the scene ENL is a high quantile of
    the block ratios — blocks near the top are the homogeneous ones.
    (window=15/q=0.8 measured on synthetic gamma speckle: <~11% error on
    homogeneous scenes, <~4% with half the scene strongly textured.)
    The reference leaves this as an open TODO
    (``Sentinel1_Observations.py:106-132``).

    Returns None when fewer than 8 usable blocks exist (no reliable
    estimate; callers fall back to the relative placeholder).
    """
    a = np.asarray(arr, np.float64)
    if a.ndim == 3 and a.shape[-1] <= 4:
        a = a[..., 0]  # trailing band axis (io.warp layout)
    if a.ndim != 2:
        return None
    valid = np.isfinite(a) & (a != missing) & (a > 0)
    ny, nx = a.shape[0], a.shape[1]
    by, bx = ny // window, nx // window
    if by == 0 or bx == 0:
        return None
    crop = a[: by * window, : bx * window]
    vcrop = valid[: by * window, : bx * window]
    blocks = crop.reshape(by, window, bx, window).swapaxes(1, 2)
    vblocks = vcrop.reshape(by, window, bx, window).swapaxes(1, 2)
    full = vblocks.all(axis=(2, 3))
    if full.sum() < 8:
        return None
    m = blocks.mean(axis=(2, 3))
    v = blocks.var(axis=(2, 3), ddof=1)
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = np.where(v > 0, m * m / v, np.nan)
    ratio = ratio[full & np.isfinite(ratio)]
    if ratio.size < 8:
        return None
    return float(np.quantile(ratio, quantile))


class S1Observations:
    """ObservationSource over a folder of preprocessed S1 sigma0 NetCDFs.

    ``operator`` defaults to the analytic Water-Cloud Model on a
    (vegetation, soil-moisture) state (``obsops.wcm``), with the scene's
    per-pixel incidence angle as its aux — the reference injects emulator
    placeholders per polarisation (``:61``)."""

    def __init__(
        self,
        data_folder: str,
        state_geo,
        operator: Optional[Any] = None,
        relative_uncertainty: float = 0.05,
        enl: Optional[float] = None,
        noise_floor: float = 0.0,
    ):
        self.state_geotransform, self.state_crs = state_geo
        self.operator = operator if operator is not None else WCMOperator()
        self.relative_uncertainty = float(relative_uncertainty)
        #: equivalent number of looks for speckle-statistics uncertainty:
        #: a number uses that ENL; ``"auto"`` estimates it per scene from
        #: the image's own homogeneous-block statistics (``estimate_enl``);
        #: None = use the file's ``enl`` attribute, or fall back to the
        #: reference's relative placeholder.
        self.enl = enl if enl is None or enl == "auto" else float(enl)
        #: noise-equivalent sigma0 (linear power units) added in
        #: quadrature to the speckle term.
        self.noise_floor = float(noise_floor)
        files = sorted(glob.glob(os.path.join(data_folder, "*.nc")))
        self.dates: List[datetime.datetime] = []
        self.date_data: Dict[datetime.datetime, str] = {}
        for fich in files:
            splitter = os.path.basename(fich).split("_")
            this_date = datetime.datetime.strptime(
                splitter[5], "%Y%m%dT%H%M%S"
            )
            self.dates.append(this_date)
            self.date_data[this_date] = fich
        self.bands_per_observation = {
            d: len(POLARISATIONS) for d in self.dates
        }
        # One warp mapping per (source grid, dst shape) — shared by
        # VV/VH/theta of a scene (see sentinel2.py mapping cache).
        self._mapping_cache: Dict[tuple, tuple] = {}
        # (mapping key, gather id) -> valid-pixel fractional coordinates.
        self._gather_coord_cache: Dict[tuple, tuple] = {}
        # File-level ``enl`` attributes and per-scene auto estimates are
        # immutable: read/estimate once per path.
        self._enl_cache: Dict[Any, Optional[float]] = {}

    def define_output(self):
        return self.state_crs, list(self.state_geotransform)

    def _warp_var_gathered(self, path: str, var: str,
                           gather: PixelGather, nodata: float
                           ) -> np.ndarray:
        """Warp one variable AT the valid pixels only, padded to
        ``n_pad`` with ``nodata`` — skips the (1 - fill) fraction of the
        chunk grid a full-grid warp would resample (see the S2 reader's
        ``_gathered_coords``).  The coordinate cache holds the gather
        object so its id cannot recycle while the entry lives."""
        arr, gt, crs = _read_nc_var(path, var)
        src_crs = crs if crs is not None else self.state_crs
        dst_shape = gather.mask.shape
        key = (tuple(gt), src_crs, tuple(dst_shape))
        if key not in self._mapping_cache:
            self._mapping_cache[key] = grid_mapping(
                gt, dst_shape, self.state_geotransform,
                src_crs=src_crs, dst_crs=self.state_crs,
            )
        col_f, row_f = self._mapping_cache[key]
        gkey = (key, id(gather))
        hit = self._gather_coord_cache.get(gkey)
        if hit is None or hit[0] is not gather:
            hit = (
                gather,
                col_f[gather.rows, gather.cols],
                row_f[gather.rows, gather.cols],
            )
            self._gather_coord_cache[gkey] = hit
        vals = resample(arr, hit[1], hit[2], method="nearest",
                        nodata=nodata)
        if vals.ndim > 1:
            vals = vals[..., 0]
        out = np.full(gather.n_pad, nodata, np.float32)
        out[: gather.n_valid] = vals
        return out

    def _file_enl(self, path: str) -> Optional[float]:
        if path in self._enl_cache:
            return self._enl_cache[path]
        import h5py

        with h5py.File(path, "r") as f:
            enl = (
                float(np.asarray(f.attrs["enl"]).ravel()[0])
                if "enl" in f.attrs else None
            )
        self._enl_cache[path] = enl
        return enl

    def _auto_enl(self, path: str) -> Optional[float]:
        """Scene ENL estimated from the native-grid VV intensity (cached
        per file; estimated BEFORE warping — resampling correlates
        neighbouring pixels and would bias the moments estimator)."""
        key = ("auto", path)
        if key in self._enl_cache:
            return self._enl_cache[key]
        arr, _, _ = _read_nc_var(path, f"sigma0_{POLARISATIONS[0]}")
        enl = estimate_enl(arr)
        if enl is None:
            LOG.warning(
                "%s: too few homogeneous blocks for an ENL estimate; "
                "falling back to the %.0f%% relative placeholder",
                path, 100 * self.relative_uncertainty,
            )
        else:
            LOG.info("%s: estimated ENL %.1f", path, enl)
        self._enl_cache[key] = enl
        return enl

    def get_observations(self, date, gather: PixelGather) -> DateObservation:
        path = self.date_data[date]
        if self.enl == "auto":
            enl = self._auto_enl(path)
        else:
            enl = self.enl if self.enl is not None else self._file_enl(path)
        ys, r_invs, masks = [], [], []
        for pol in POLARISATIONS:
            pix = self._warp_var_gathered(
                path, f"sigma0_{pol}", gather, MISSING_VALUE
            )
            mask = (
                (pix != MISSING_VALUE) & np.isfinite(pix) & gather.valid
            )
            # Linear-power backscatter must be strictly positive to carry
            # information (negative values appear in noise-subtracted GRD
            # products): both uncertainty models reject y <= 0, matching
            # the relative path's implicit sigma > 0 gate.
            mask &= pix > 0
            y = np.where(mask, pix, 0.0).astype(np.float32)
            if enl is not None:
                # Multi-looked intensity speckle: std = sigma0/sqrt(L),
                # noise floor in quadrature.
                sigma = np.sqrt(
                    y * y / enl + self.noise_floor**2
                ).astype(np.float32)
            else:
                sigma = self.relative_uncertainty * y
            with np.errstate(divide="ignore", invalid="ignore"):
                r_inv = np.where(mask & (sigma > 0), 1.0 / sigma**2, 0.0)
            ys.append(y)
            r_invs.append(r_inv.astype(np.float32))
            masks.append(mask)

        # Per-pixel incidence angle if the file carries it; otherwise the
        # reference's hard-coded 23 degrees (sar_forward_model.py:156).
        try:
            theta_pix = self._warp_var_gathered(path, "theta", gather, 23.0)
        except KeyError:
            theta_pix = np.full(gather.n_pad, 23.0, np.float32)
        theta_pix = np.where(
            np.isfinite(theta_pix), theta_pix, 23.0
        ).astype(np.float32)
        aux = WCMAux(theta_deg=jnp.asarray(theta_pix))
        bands = BandBatch(
            y=jnp.asarray(np.stack(ys)),
            r_inv=jnp.asarray(np.stack(r_invs)),
            mask=jnp.asarray(np.stack(masks)),
        )
        return DateObservation(bands=bands, operator=self.operator, aux=aux)
