"""Output writers.

``GeoTIFFOutput`` matches the reference ``KafkaOutput`` contract
(``/root/reference/kafka/input_output/observations.py:338-394``): one
GeoTIFF per parameter per timestep named ``{param}_{A%Y%j}[_{prefix}].tif``
plus ``..._unc.tif`` holding ``1/sqrt(diag(P^-1))``, DEFLATE-compressed and
tiled, unmasked pixels zero.  Writes can optionally run on a background
thread so device compute never waits on disk (the reference writes
synchronously inside the time loop, ``linear_kf.py:210-212``).
"""

from __future__ import annotations

import datetime
import os
import queue
import threading
import time
from typing import Optional, Sequence

import numpy as np

from ..engine.state import PixelGather
from ..telemetry import get_registry, tracing
from .geotiff import GeoInfo, write_geotiff


class GeoTIFFOutput:
    def __init__(
        self,
        parameter_list: Sequence[str],
        geotransform,
        projection: str = "",
        folder: str = ".",
        prefix: Optional[str] = None,
        epsg: Optional[int] = None,
        async_writes: bool = False,
        predictor: int = 3,
        level: Optional[int] = None,
        wire_dtype: str = "float32",
    ):
        self.parameter_list = tuple(parameter_list)
        self.geo = GeoInfo(
            geotransform=tuple(geotransform), projection=projection,
            epsg=epsg,
        )
        self.folder = folder
        self.prefix = prefix
        # Float rasters deflate ~2.4x faster AND ~10% smaller with the
        # floating-point predictor at level 1 than raw bytes at level 6
        # (measured on real analysis outputs) — and output compression is
        # the writer-side bottleneck of a chunked run.  Level 1 is only a
        # win WITH the byte-plane predictor, so the default level follows
        # the predictor choice.
        self.predictor = int(predictor)
        self.level = int(level) if level is not None else (
            1 if self.predictor == 3 else 6
        )
        # Device->host wire format for DEVICE-array inputs.  "float32"
        # (the default) is bit-exact, matching the reference's float32
        # outputs.  "float16" is the opt-in fast wire: it halves the bytes
        # crossing the (slow) device link — the on-disk rasters stay
        # float32 — at <= 2^-11 relative quantisation, two orders of
        # magnitude below the 5% observation uncertainty every reader
        # attaches to the data.  Under float16 the device-computed sigma
        # is clamped to the float16 max (65504) before the cast, so
        # weakly-observed and unobserved pixels stay finite ("absurdly
        # large sigma", thresholdable) instead of overflowing to +inf.
        # numpy inputs are never touched either way.
        if wire_dtype not in ("float16", "float32"):
            raise ValueError(f"wire_dtype {wire_dtype!r}")
        self.wire_dtype = wire_dtype
        os.makedirs(folder, exist_ok=True)
        self._queue: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        reg = get_registry()
        self._trace = reg.trace
        # Captured for the writer thread: contextvars don't cross thread
        # creation, so the constructing (engine/chunk) context is
        # re-installed in _drain to keep the timeline correlated.
        self._trace_ctx = tracing.current_context()
        self._m_backlog = reg.gauge(
            "kafka_io_writer_backlog",
            "queued dump requests the async writer thread has not "
            "finished (0 for synchronous writers)",
        )
        self._m_writes = reg.counter(
            "kafka_io_writes_total",
            "timesteps written to GeoTIFF outputs",
        )
        self._m_write_s = reg.histogram(
            "kafka_io_write_seconds",
            "wall seconds per timestep write (scatter + encode + disk, "
            "all parameters)",
        )
        if async_writes:
            self._queue = queue.Queue(maxsize=4)
            self._worker = threading.Thread(
                target=self._drain, daemon=True
            )
            self._worker.start()

    def _fname(self, param: str, timestep: datetime.datetime,
               unc: bool) -> str:
        date = timestep.strftime("A%Y%j")
        parts = [param, date]
        if self.prefix is not None:
            parts.append(str(self.prefix))
        if unc:
            parts.append("unc")
        return os.path.join(self.folder, "_".join(parts) + ".tif")

    def _qa_fname(self, timestep: datetime.datetime) -> str:
        return self._fname("solver_qa", timestep, False)

    def _write_all(self, timestep, x, unc, gather, parameter_list,
                   unc_is_sigma=False):
        t0 = time.perf_counter()
        try:
            x = np.asarray(x)
            for ii, param in enumerate(parameter_list):
                raster = gather.scatter(x[:, ii].astype(np.float32))
                write_geotiff(self._fname(param, timestep, False), raster,
                              self.geo, predictor=self.predictor,
                              level=self.level)
            if unc is None:
                return
            unc = np.asarray(unc)
            for ii, param in enumerate(parameter_list):
                if unc_is_sigma:
                    sigma = unc[:, ii].astype(np.float32)
                else:
                    sigma = 1.0 / np.sqrt(np.maximum(
                        unc[:, ii].astype(np.float32), 1e-30
                    ))
                raster = gather.scatter(sigma)
                write_geotiff(self._fname(param, timestep, True), raster,
                              self.geo, predictor=self.predictor,
                              level=self.level)
        finally:
            t1 = time.perf_counter()
            self._m_writes.inc()
            self._m_write_s.observe(t1 - t0)
            self._trace.add_span(
                "write", t0, t1, cat="io",
                timestep=timestep.strftime("%Y-%m-%d"),
            )

    def _to_wire(self, x, p_inv_diag):
        """Device-side downcast (and sigma computation) so the link moves
        half the bytes; starts the async copy immediately so the transfer
        overlaps the rest of the time loop.  numpy inputs pass through."""
        unc, unc_is_sigma = p_inv_diag, False
        if self.wire_dtype == "float16":
            import jax.numpy as jnp

            if x is not None and not isinstance(x, np.ndarray):
                x = x.astype(jnp.float16)
            if p_inv_diag is not None and \
                    not isinstance(p_inv_diag, np.ndarray):
                sigma = 1.0 / jnp.sqrt(jnp.maximum(p_inv_diag, 1e-30))
                # Clamp at float16 max: sigma in (65504, 1e15) — weakly
                # observed pixels — must stay finite, not collapse to the
                # same +inf as truly unobserved ones.
                unc = jnp.minimum(sigma, 65504.0).astype(jnp.float16)
                unc_is_sigma = True
        for arr in (x, unc):
            if arr is not None and hasattr(arr, "copy_to_host_async"):
                arr.copy_to_host_async()
        return x, unc, unc_is_sigma

    def dump_data(self, timestep, x, p_inv_diag, gather: PixelGather,
                  parameter_list) -> None:
        self._raise_pending()
        x, unc, unc_is_sigma = self._to_wire(x, p_inv_diag)
        if self._queue is not None:
            # Device arrays are queued as-is: they are immutable, and
            # materialising them here would put the device->host transfer
            # on the critical path of the time loop — the writer thread
            # pays it instead, overlapped with the next date's work.
            # Mutable numpy inputs are snapshotted.
            self._queue.put(
                (timestep, self._snapshot(x), self._snapshot(unc),
                 gather, tuple(parameter_list), unc_is_sigma)
            )
            self._set_backlog(self._queue.qsize())
        else:
            self._write_all(timestep, x, unc, gather, parameter_list,
                            unc_is_sigma)

    def dump_block(self, timesteps, xs, p_inv_diags,
                   gather: PixelGather, parameter_list) -> None:
        """Dump K consecutive timesteps from stacked ``(K, n, p)`` arrays
        (the engine's temporal-fusion path): ONE wire conversion and one
        pair of device->host transfers covers the whole block."""
        self._raise_pending()
        xs, uncs, unc_is_sigma = self._to_wire(xs, p_inv_diags)
        item = (
            tuple(timesteps), self._snapshot(xs), self._snapshot(uncs),
            gather, tuple(parameter_list), unc_is_sigma,
        )
        if self._queue is not None:
            self._queue.put(("block",) + item)
            self._set_backlog(self._queue.qsize())
        else:
            self._write_block(*item)

    def _write_block(self, timesteps, xs, uncs, gather, parameter_list,
                     unc_is_sigma=False):
        xs = np.asarray(xs)
        uncs = None if uncs is None else np.asarray(uncs)
        for k, ts in enumerate(timesteps):
            self._write_all(
                ts, xs[k], None if uncs is None else uncs[k],
                gather, parameter_list, unc_is_sigma,
            )

    # -- per-pixel solve-health QA band ---------------------------------

    def dump_qa(self, timestep, verdicts, gather: PixelGather) -> None:
        """Write the window's per-pixel solve-health QA band
        (``core.solver_health`` bitmask: converged / cap-bailout /
        damped-recovered / quarantined / nodata; 0 outside the state
        mask) as ``solver_qa_{A%Y%j}[_{prefix}].tif`` — a uint8 raster
        alongside every parameter/unc pair, so downstream users can MASK
        non-converged values instead of trusting them blind."""
        self._raise_pending()
        if verdicts is not None and hasattr(verdicts,
                                            "copy_to_host_async"):
            verdicts.copy_to_host_async()
        if self._queue is not None:
            self._queue.put(("qa", timestep, self._snapshot(verdicts),
                             gather))
            self._set_backlog(self._queue.qsize())
        else:
            self._write_qa(timestep, verdicts, gather)

    def dump_qa_block(self, timesteps, verdicts, gather: PixelGather
                      ) -> None:
        """QA bands for K stacked windows (``verdicts`` (K, n_pad) from
        the fused scan): one device->host transfer for the block."""
        self._raise_pending()
        if verdicts is not None and hasattr(verdicts,
                                            "copy_to_host_async"):
            verdicts.copy_to_host_async()
        if self._queue is not None:
            self._queue.put(("qa_block", tuple(timesteps),
                             self._snapshot(verdicts), gather))
            self._set_backlog(self._queue.qsize())
        else:
            self._write_qa_block(timesteps, verdicts, gather)

    def _write_qa(self, timestep, verdicts, gather):
        raster = gather.scatter(
            np.asarray(verdicts).astype(np.uint8)
        )
        # uint8 bitmask: byte predictor (1), not the float predictor
        # the parameter rasters use.
        write_geotiff(self._qa_fname(timestep), raster, self.geo,
                      predictor=1)

    def _write_qa_block(self, timesteps, verdicts, gather):
        verdicts = np.asarray(verdicts)
        for k, ts in enumerate(timesteps):
            self._write_qa(ts, verdicts[k], gather)

    @staticmethod
    def _snapshot(arr):
        if arr is None or not isinstance(arr, np.ndarray):
            return arr  # None, or an immutable device array
        return np.asarray(arr).copy()

    def _set_backlog(self, n: int) -> None:
        self._m_backlog.set(n)
        self._trace.add_counter("writer_backlog", n)

    def _drain(self):
        tracing.set_context(self._trace_ctx)
        tracing.set_lane("writer")
        while True:
            item = self._queue.get()
            if item is None:
                return
            try:
                if item[0] == "block":
                    self._write_block(*item[1:])
                elif item[0] == "qa":
                    self._write_qa(*item[1:])
                elif item[0] == "qa_block":
                    self._write_qa_block(*item[1:])
                else:
                    self._write_all(*item)
            except Exception as exc:  # surfaced on next dump/flush/close
                self._error = exc
            finally:
                self._set_backlog(self._queue.qsize())
                self._queue.task_done()

    def _raise_pending(self):
        if self._error is not None:
            exc, self._error = self._error, None
            raise RuntimeError(
                "asynchronous GeoTIFF write failed"
            ) from exc

    def flush(self):
        """Block until queued writes are on disk (raises if any failed)."""
        if self._queue is not None:
            self._queue.join()
        self._raise_pending()

    def close(self):
        if self._queue is not None:
            self.flush()
            self._queue.put(None)
            self._worker.join()
            self._queue = None
