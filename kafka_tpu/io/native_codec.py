"""Dispatch layer for the raster codec hot path.

Batch DEFLATE encode/decode of TIFF tiles.  Uses the C++ thread-pooled codec
(``kafka_tpu/native/rasterkit.cpp``) when its shared library is built —
decoding a 10980x10980 tile-year means ~10^5 tile inflations, which the
native pool does in parallel without the GIL — and falls back to Python's
zlib (itself C, but serial) otherwise.

Build the native library with ``make -C kafka_tpu/native`` (done
automatically by ``kafka_tpu.native.ensure_built()``).
"""

from __future__ import annotations

import zlib
from typing import List, Sequence

_native = None


def _load_native():
    global _native
    if _native is None:
        try:
            from ..native import load_library

            _native = load_library()
        except (OSError, ImportError):
            # dlopen of a stale/foreign .so can fail even after a build
            # reported success — the serial zlib path is always correct.
            _native = False
    return _native


def inflate_many(segments: Sequence[bytes], expected_size: int) -> List[bytes]:
    lib = _load_native()
    if lib:
        return lib.inflate_many(segments, expected_size)
    return [zlib.decompress(bytes(s)) for s in segments]


def deflate_many(segments: Sequence[bytes], level: int = 6) -> List[bytes]:
    lib = _load_native()
    if lib:
        return lib.deflate_many(segments, level)
    return [zlib.compress(s, level) for s in segments]


def lzw_inflate_many(segments: Sequence[bytes], expected_size: int):
    """Batch TIFF-LZW decode on the native pool, or None when the
    library (with LZW support) is unavailable — callers fall back to the
    pure-Python decoder."""
    lib = _load_native()
    if lib and getattr(lib, "has_lzw", False):
        return lib.lzw_inflate_many(segments, expected_size)
    return None


def lzw_deflate_many(segments: Sequence[bytes]):
    """Batch TIFF-LZW encode on the native pool (bit-identical to the
    Python ``geotiff.lzw_encode``), or None when unavailable."""
    lib = _load_native()
    if lib and getattr(lib, "has_lzw_enc", False):
        return lib.lzw_deflate_many(segments)
    return None


def has_fp3() -> bool:
    """Whether the fused native predictor-3 chain is available (library
    built AND carrying the round-3 entry points)."""
    lib = _load_native()
    return bool(lib) and getattr(lib, "has_fp3", False)


def decode_fp3_many(segments: Sequence[bytes], rows: int, cols: int,
                    nb: int, compressed: bool):
    """Fused float32 predictor-3 decode (inflate + fpAcc + unshuffle) on
    the native pool; returns a (n, rows, cols, nb) float32 array, or
    None when the native library (with fp3 support) is unavailable —
    callers fall back to the numpy predictor path."""
    lib = _load_native()
    if lib and getattr(lib, "has_fp3", False):
        return lib.decode_fp3_many(segments, rows, cols, nb, compressed)
    return None


def encode_fp3_many(tiles, level: int = 1):
    """Fused float32 predictor-3 encode (fpDiff + deflate); None when
    native fp3 is unavailable."""
    lib = _load_native()
    if lib and getattr(lib, "has_fp3", False):
        return lib.encode_fp3_many(tiles, level)
    return None
