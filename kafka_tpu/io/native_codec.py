"""Dispatch layer for the raster codec hot path.

Batch DEFLATE encode/decode of TIFF tiles.  Uses the C++ thread-pooled codec
(``kafka_tpu/native/rasterkit.cpp``) when its shared library is built —
decoding a 10980x10980 tile-year means ~10^5 tile inflations, which the
native pool does in parallel without the GIL — and falls back to Python's
zlib (itself C, but serial) otherwise.

Build the native library with ``make -C kafka_tpu/native`` (done
automatically by ``kafka_tpu.native.ensure_built()``).
"""

from __future__ import annotations

import zlib
from typing import List, Sequence

_native = None


def _load_native():
    global _native
    if _native is None:
        try:
            from ..native import load_library

            _native = load_library()
        except Exception:
            _native = False
    return _native


def inflate_many(segments: Sequence[bytes], expected_size: int) -> List[bytes]:
    lib = _load_native()
    if lib:
        return lib.inflate_many(segments, expected_size)
    return [zlib.decompress(bytes(s)) for s in segments]


def deflate_many(segments: Sequence[bytes], level: int = 6) -> List[bytes]:
    lib = _load_native()
    if lib:
        return lib.deflate_many(segments, level)
    return [zlib.compress(s, level) for s in segments]
