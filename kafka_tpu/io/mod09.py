"""MOD09GA directional-reflectance reader (the kernels observation path).

Reproduces the observation semantics of the reference's
``MOD09_ObservationsKernels`` (``/root/reference/kafka/input_output/
observations.py:89-147``):

- 500 m surface reflectance bands scaled by 1e-4 (``:111-113``);
- the 1 km ``state_1km`` QA word filtered to clear-sky land observations
  (``:101-102,119`` — the reference hard-codes a whitelist of accepted QA
  values; here the *bit fields* are decoded, which accepts exactly that
  whitelist plus every other word with the same clear/land semantics);
- 1 km solar/sensor zenith/azimuth scaled by 1e-2, relative azimuth
  ``vaa - saa`` (``:123-135``);
- nearest-neighbour x2 upsample of the 1 km fields onto the 500 m grid
  (``:136-140``, ``zoom(..., 2, order=0)``);
- Ross-Li kernels from the per-pixel geometry (``:141-143``), carried as
  operator aux instead of a SIAC ``Kernels`` object;
- fixed per-band absolute uncertainties (``:103,144``).

The reference reads HDF4-EOS subdatasets through GDAL; neither exists in
this image, so the TPU-native granule contract is a directory per date
holding the same subdatasets as GeoTIFFs:

    <dir>/MOD09GA.A<%Y%j>[.*]/sur_refl_b01.tif ... sur_refl_b07.tif
                              (int16 DN = reflectance * 1e4, 500 m grid)
    <dir>/MOD09GA.A<%Y%j>[.*]/state_1km.tif     (uint16 QA, 1 km grid)
    <dir>/MOD09GA.A<%Y%j>[.*]/SolarZenith_1.tif / SolarAzimuth_1.tif /
         SensorZenith_1.tif / SensorAzimuth_1.tif
                              (int16 DN = degrees * 1e2, 1 km grid)
"""

from __future__ import annotations

import datetime
import logging
import os
import re
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.types import BandBatch
from ..engine.protocols import DateObservation
from ..engine.state import PixelGather
from ..obsops.kernels import KernelsAux, ross_li_kernels
from .geotiff import read_geotiff, read_info
from .roi import RoiWindowMixin, index_dated_paths

LOG = logging.getLogger(__name__)

#: Per-band absolute reflectance uncertainty, MODIS land bands 1-7
#: (``observations.py:103``).
BAND_UNCERTAINTY = np.array(
    [0.004, 0.015, 0.003, 0.004, 0.013, 0.010, 0.006], np.float32
)

_GRANULE_RE = re.compile(r"MOD09GA\.A(\d{7})")

# state_1km bit layout (MOD09GA product spec):
#   bits 0-1  cloud state          (00 clear)
#   bit  2    cloud shadow
#   bits 3-5  land/water           (001 land)
#   bits 6-7  aerosol quantity     (any accepted)
#   bits 8-9  cirrus               (00 none / 01 small accepted)
#   bit  10   internal cloud flag  (ignored — reference whitelist includes
#   bit  11   internal fire flag    both settings of each)
#   bit  12   snow/ice
#   bit  13   adjacent to cloud


def decode_state_qa(qa: np.ndarray) -> np.ndarray:
    """Clear-sky land mask from the MOD09GA ``state_1km`` QA word.

    Accepts: clear clouds, no shadow, land, any aerosol load, cirrus none
    or small, no snow, not cloud-adjacent.  Every value in the reference's
    accepted-QA whitelist (``observations.py:101-102``) satisfies these
    bit conditions; unlike a whitelist, words that only differ in the
    ignored internal-algorithm bits are classified consistently.
    """
    qa = np.asarray(qa).astype(np.uint16)
    cloud_clear = (qa & 0b11) == 0
    no_shadow = (qa >> 2 & 0b1) == 0
    land = (qa >> 3 & 0b111) == 0b001
    cirrus_ok = (qa >> 8 & 0b11) <= 0b01
    no_snow = (qa >> 12 & 0b1) == 0
    no_adjacent = (qa >> 13 & 0b1) == 0
    return cloud_clear & no_shadow & land & cirrus_ok & no_snow & no_adjacent


def zoom2_nearest(arr: np.ndarray) -> np.ndarray:
    """Nearest-neighbour x2 upsample, the 1 km -> 500 m regridding
    (``observations.py:136-140``)."""
    return np.repeat(np.repeat(arr, 2, axis=0), 2, axis=1)


class MOD09Observations(RoiWindowMixin):
    """ObservationSource over MOD09GA-style granule directories.

    ``get_observations`` returns the 7 directional-reflectance bands with
    per-pixel Ross-Li kernel values in the aux — the kernel-weight state is
    then retrieved by the injected (linear) ``KernelsOperator``.
    """

    def __init__(
        self,
        data_dir: str,
        operator,
        start_time: Optional[datetime.datetime] = None,
        end_time: Optional[datetime.datetime] = None,
    ):
        self.data_dir = data_dir
        self.operator = operator
        self._granules = index_dated_paths(
            os.path.join(data_dir, "MOD09GA.A*"), _GRANULE_RE,
            start_time, end_time,
            transform=lambda p: p if os.path.isdir(p) else None,
            label="MOD09GA granule",
        )
        self.dates: List[datetime.datetime] = sorted(self._granules)
        self.bands_per_observation = {d: 7 for d in self.dates}

    def _read(self, granule: str, name: str) -> np.ndarray:
        arr, _ = read_geotiff(os.path.join(granule, name + ".tif"))
        return np.asarray(arr).squeeze()

    def define_output(self):
        self._require_dates()
        granule = self._granules[self.dates[0]]
        info = read_info(os.path.join(granule, "sur_refl_b01.tif"))
        gt = self._shift_geotransform(info.geo.geotransform)
        return info.geo.epsg or info.geo.projection or "sinusoidal", gt

    def get_observations(self, date, gather: PixelGather) -> DateObservation:
        granule = self._granules[date]

        qa = decode_state_qa(self._read(granule, "state_1km"))
        sza = self._read(granule, "SolarZenith_1").astype(np.float32) / 100.0
        saa = self._read(granule, "SolarAzimuth_1").astype(np.float32) / 100.0
        vza = self._read(granule, "SensorZenith_1").astype(np.float32) / 100.0
        vaa = self._read(granule, "SensorAzimuth_1").astype(np.float32) / 100.0
        clear = self._window(zoom2_nearest(qa))
        sza = self._window(zoom2_nearest(sza))
        raa = self._window(zoom2_nearest(vaa - saa))
        vza = self._window(zoom2_nearest(vza))

        clear_pix = gather.gather(clear) & gather.valid
        k_vol, k_geo = ross_li_kernels(
            gather.gather(sza), gather.gather(vza), gather.gather(raa)
        )
        aux = KernelsAux(
            k_vol=jnp.asarray(np.asarray(k_vol), jnp.float32),
            k_geo=jnp.asarray(np.asarray(k_geo), jnp.float32),
        )

        ys, r_invs, masks = [], [], []
        for band in range(7):
            # 500 m bands are the I/O bulk: read only the ROI window.  The
            # 1 km QA/angle rasters above stay whole-raster (1/4 the pixels,
            # and the x2 zoom needs the full grid alignment).
            dn = np.asarray(
                self._read_windowed(
                    os.path.join(granule, f"sur_refl_b{band + 1:02d}.tif")
                )
            ).squeeze()
            refl = dn.astype(np.float32) / 10000.0
            refl_pix = gather.gather(refl)
            valid = clear_pix & np.isfinite(refl_pix) & (refl_pix > 0)
            sigma = BAND_UNCERTAINTY[band]
            ys.append(np.where(valid, refl_pix, 0.0).astype(np.float32))
            r_invs.append(
                np.where(valid, 1.0 / sigma**2, 0.0).astype(np.float32)
            )
            masks.append(valid)

        bands = BandBatch(
            y=jnp.asarray(np.stack(ys)),
            r_inv=jnp.asarray(np.stack(r_invs)),
            mask=jnp.asarray(np.stack(masks)),
        )
        return DateObservation(bands=bands, operator=self.operator, aux=aux)
