"""Sentinel-2 MSI surface-reflectance reader.

Reproduces the observation semantics of the reference's
``Sentinel2Observations``
(``/root/reference/kafka/input_output/Sentinel2_Observations.py:85-185``):

- granule discovery by walking the data tree for the ``*aot.tif`` marker,
  with the acquisition date encoded in the ``YYYY/MM/DD`` path components
  (``:116-130``);
- 10-band map B02..B12 (``:93-94``) reading ``B{band}_sur.tif`` per band;
- per-scene ``metadata.xml`` parse to mean SZA/SAA/VZA/VAA (``:23-53``);
- warp of every band onto the state-mask grid (``:56-79,166`` — here via
  ``io.warp`` instead of GDAL);
- reflectance scaling /10000, positivity mask, 5% relative uncertainty
  stored as inverse variance (``:167-179``).

Array-native differences: all 10 bands of a date are returned at once as a
fixed-shape ``BandBatch`` gathered to the pixel batch (the reference fetches
band-by-band and re-warps per band), and the per-geometry emulator pickle
(``:157-159``) is replaced by an injected operator + ``aux_builder`` that
maps the scene's angles to traced operator data (e.g. a ``GPParams`` bank
selected per geometry — ``obsops.gp``)."""

from __future__ import annotations

import datetime
import glob
import logging
import os
import xml.etree.ElementTree as ET
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..core.types import BandBatch
from ..engine.protocols import DateObservation
from ..engine.state import PixelGather
from .geotiff import read_geotiff_window, read_info
from .warp import grid_mapping

LOG = logging.getLogger(__name__)

#: B02..B12 band-number map (``Sentinel2_Observations.py:93-94``).
BAND_MAP = ["02", "03", "04", "05", "06", "07", "08", "8A", "09", "12"]
#: S2 MSI band indices used to key emulators (``:171-173``).
EMULATOR_BAND_MAP = [2, 3, 4, 5, 6, 7, 8, 9, 12, 13]


def _zenith_azimuth(node) -> tuple:
    """(zenith, azimuth) floats of an angle element, None where absent."""
    z = node.findtext("ZENITH_ANGLE")
    a = node.findtext("AZIMUTH_ANGLE")
    return (
        None if z is None else float(z),
        None if a is None else float(a),
    )


def parse_s2_xml(filename: str):
    """Mean solar/viewing angles ``(sza, saa, vza, vaa)`` from a granule
    metadata file.

    Semantics match the reference parser (one mean sun angle per scene;
    viewing angles averaged over all per-band/per-detector entries,
    ``Sentinel2_Observations.py:23-53``), located here by tag search from
    the document root rather than by fixed nesting, and validated: a
    metadata file without a complete sun angle or any viewing-angle entry
    raises ``ValueError`` naming the file instead of silently returning
    ``None``/NaN angles that would surface later as opaque failures in aux
    builders."""
    root = ET.parse(filename).getroot()

    sun = root.find(".//Mean_Sun_Angle")
    sza, saa = _zenith_azimuth(sun) if sun is not None else (None, None)
    if sza is None or saa is None:
        raise ValueError(
            f"{filename}: missing or incomplete Mean_Sun_Angle element"
        )

    pairs = [
        _zenith_azimuth(el)
        for el in root.iter("Mean_Viewing_Incidence_Angle")
    ]
    vzas = [z for z, _ in pairs if z is not None]
    vaas = [a for _, a in pairs if a is not None]
    if not vzas or not vaas:
        raise ValueError(
            f"{filename}: no Mean_Viewing_Incidence_Angle entries"
        )
    return sza, saa, float(np.mean(vzas)), float(np.mean(vaas))


class Sentinel2Observations:
    """ObservationSource over a tree of preprocessed S2 granules.

    Parameters
    ----------
    parent_folder : root of the granule tree (``.../YYYY/MM/DD/granule/``
        with ``B??_sur.tif`` + ``metadata.xml`` + the ``*aot.tif`` marker).
    operator : the observation model applied to every date (stable callable
        — per-date data flows through ``aux``).
    state_geo : ``(geotransform, crs)`` of the state-mask grid that every
        band is warped onto (the reference warps to the mask file's grid).
    aux_builder : optional ``(metadata, gather) -> aux`` giving the
        operator's per-date traced data from the scene geometry; defaults
        to a dict of angle scalars.
    relative_uncertainty : 5% of reflectance, the reference's choice.
    """

    def __init__(
        self,
        parent_folder: str,
        operator: Any,
        state_geo,
        aux_builder: Optional[Callable] = None,
        relative_uncertainty: float = 0.05,
        band_workers: Optional[int] = None,
    ):
        if not os.path.exists(parent_folder):
            raise IOError("S2 data folder doesn't exist")
        self.parent = parent_folder
        self.operator = operator
        self.state_geotransform, self.state_crs = state_geo
        self.aux_builder = aux_builder or (
            lambda metadata, gather: metadata
        )
        self.relative_uncertainty = float(relative_uncertainty)
        # Per-date band parallelism: the 10 read->decode->warp->gather
        # chains are independent and the tile codec's inner loops are
        # GIL-free (C++/zlib), so they thread across host cores.  Default:
        # one worker per core up to the band count; 1 = the reference's
        # serial per-band loop (linear_kf.py:225-227).
        if band_workers is None:
            band_workers = min(len(BAND_MAP), os.cpu_count() or 1)
        self.band_workers = max(1, int(band_workers))
        # ONE pool for the source's lifetime (lazily built): an annual
        # run reads hundreds of dates — spawning/joining threads per
        # date, times N prefetch workers, would put thread churn on the
        # exact host path this pool exists to speed up.  submit() is
        # thread-safe, so concurrent prefetch readers share it.
        self._band_pool = None
        self._find_granules()
        self.bands_per_observation = {d: len(BAND_MAP) for d in self.dates}
        # (src_gt, src_crs, dst_shape) -> fractional-pixel warp mapping.
        # The CRS transform over the full state grid is the expensive part
        # of a warp; all 10 bands of a granule share one source grid, so
        # the mapping is computed once and reused.
        self._mapping_cache: Dict[tuple, tuple] = {}
        # path -> parsed TiffInfo, so repeated windowed reads of one band
        # file parse its header/IFD once.
        self._info_cache: Dict[str, Any] = {}
        # (source grid, dst shape, gather id) -> valid-pixel fractional
        # coordinates (see _gathered_coords).
        self._gather_coord_cache: Dict[tuple, tuple] = {}

    def _find_granules(self) -> None:
        """Index granule directories by acquisition date.

        A granule is any directory containing an ``*aot.tif`` marker file
        under ``<parent>/YYYY/MM/DD/...`` (the marker convention and
        path-encoded date of the reference data layout,
        ``Sentinel2_Observations.py:116-130``); discovery here is by glob
        over that layout.  Directories whose date segments don't parse are
        skipped with a log message."""
        self.date_data: Dict[datetime.datetime, str] = {}
        pattern = os.path.join(
            glob.escape(self.parent), "*", "*", "*", "*", "*aot.tif"
        )
        for marker in glob.glob(pattern):
            granule_dir = os.path.dirname(marker)
            day_dir = os.path.dirname(granule_dir)
            segments = []
            for _ in range(3):  # day, month, year directories
                segments.append(os.path.basename(day_dir))
                day_dir = os.path.dirname(day_dir)
            try:
                day, month, year = (int(s) for s in segments)
                date = datetime.datetime(year, month, day)
            except ValueError:
                LOG.warning("skipping non-date granule path %s", granule_dir)
                continue
            self.date_data[date] = granule_dir
        self.dates = sorted(self.date_data)

    def define_output(self):
        """(projection, geotransform) of the output grid — the state grid
        (``Sentinel2_Observations.py:100-113``)."""
        return self.state_crs, list(self.state_geotransform)

    def _band_info(self, path: str):
        info = self._info_cache.get(path)
        if info is None:
            info = self._info_cache[path] = read_info(path)
        return info

    def _ensure_mapping(self, info, dst_shape):
        """The (cached) fractional-pixel mapping of the state grid into
        one source grid — the expensive CRS transform, no pixel I/O."""
        src_crs = info.geo.epsg if info.geo.epsg else self.state_crs
        key = (tuple(info.geo.geotransform), src_crs, tuple(dst_shape))
        if key not in self._mapping_cache:
            col_f, row_f = grid_mapping(
                info.geo.geotransform, dst_shape, self.state_geotransform,
                src_crs=src_crs, dst_crs=self.state_crs,
            )
            # Source bbox covering every mapped coordinate (+1 for the
            # bilinear neighbour), clipped to the source raster.
            c0 = int(max(0, np.floor(col_f.min()) - 1))
            r0 = int(max(0, np.floor(row_f.min()) - 1))
            c1 = int(min(info.width, np.ceil(col_f.max()) + 2))
            r1 = int(min(info.height, np.ceil(row_f.max()) + 2))
            c1, r1 = max(c1, c0 + 1), max(r1, r0 + 1)
            self._mapping_cache[key] = (
                col_f - c0, row_f - r0, r0, c0, r1 - r0, c1 - c0
            )
        return self._mapping_cache[key]

    def _gathered_coords(self, info, dst_shape, gather: PixelGather):
        """Fractional source coordinates of the VALID pixels only.

        Resampling the full chunk grid and then gathering wastes
        (1 - fill_fraction) of the warp work — the Barrax pivot mask is
        ~18% fill, so sampling at the gathered coordinates directly cuts
        the per-band warp cost ~5x.  Cached per (source grid incl. CRS,
        gather); the cache entry HOLDS the gather object, so its id can
        never be recycled while the entry lives, and an identity check
        guards against a different gather arriving under the same key."""
        col_l, row_l, r0, c0, nr, nc = self._ensure_mapping(
            info, dst_shape
        )
        src_crs = info.geo.epsg if info.geo.epsg else self.state_crs
        key = (
            tuple(info.geo.geotransform), src_crs, tuple(dst_shape),
            id(gather),
        )
        hit = self._gather_coord_cache.get(key)
        if hit is None or hit[0] is not gather:
            gcol = col_l[gather.rows, gather.cols]
            grow = row_l[gather.rows, gather.cols]
            # Precompute the nearest-neighbour integer lookup ONCE: all
            # 10 bands of every date share these coordinates, and the
            # per-band round/astype/bounds arithmetic was the warm read
            # path's single largest cost (~0.3 s/date at 1.2M px).
            ci = np.round(gcol).astype(np.int64)
            ri = np.round(grow).astype(np.int64)
            valid = (ci >= 0) & (ci < nc) & (ri >= 0) & (ri < nr)
            np.clip(ci, 0, nc - 1, out=ci)
            np.clip(ri, 0, nr - 1, out=ri)
            hit = (gather, ri, ci, valid)
            self._gather_coord_cache[key] = hit
        return hit[1], hit[2], hit[3], r0, c0, nr, nc

    def _band_arrays(self, path: str, dst_shape, gather: PixelGather):
        """One band's full host chain: read window -> decode -> nearest
        lookup AT the valid pixels -> reflectance/uncertainty arrays."""
        info = self._band_info(path)
        ri, ci, in_bounds, r0, c0, nr, nc = self._gathered_coords(
            info, dst_shape, gather
        )
        win, _ = read_geotiff_window(path, r0, c0, nr, nc, info=info)
        win2d = win if win.ndim == 2 else win[..., 0]
        vals = win2d[ri, ci].astype(np.float32, copy=False)
        if not in_bounds.all():
            vals = np.where(in_bounds, vals, np.float32(0.0))
        rho_pix = np.zeros(gather.n_pad, np.float32)
        rho_pix[: gather.n_valid] = vals
        mask = (rho_pix > 0) & gather.valid
        # DN/10000 reflectance, 5% relative sigma, inverse variance
        # (Sentinel2_Observations.py:167-179).
        refl = np.where(mask, rho_pix / 10000.0, 0.0).astype(np.float32)
        sigma = self.relative_uncertainty * refl
        with np.errstate(divide="ignore"):
            r_inv = np.where(mask, 1.0 / sigma**2, 0.0)
        return refl, r_inv.astype(np.float32), mask

    def get_observations(self, date, gather: PixelGather) -> DateObservation:
        folder = self.date_data[date]
        meta_file = os.path.join(folder, "metadata.xml")
        sza, saa, vza, vaa = parse_s2_xml(meta_file)
        metadata = {"sza": sza, "saa": saa, "vza": vza, "vaa": vaa}

        dst_shape = gather.mask.shape
        paths = [
            os.path.join(folder, f"B{band}_sur.tif") for band in BAND_MAP
        ]
        if self.band_workers > 1:
            # Warm the per-grid caches serially first: all bands of a
            # granule typically share one source grid, and N threads
            # discovering a cold mapping would each recompute the (one
            # expensive) CRS transform and the gathered-coordinate
            # selection.  Header reads are cheap; no pixel I/O happens
            # here.
            for path in paths:
                self._gathered_coords(
                    self._band_info(path), dst_shape, gather
                )
            if self._band_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._band_pool = ThreadPoolExecutor(
                    self.band_workers, thread_name_prefix="s2-band"
                )
            results = list(self._band_pool.map(
                lambda p: self._band_arrays(p, dst_shape, gather),
                paths,
            ))
        else:
            results = [
                self._band_arrays(p, dst_shape, gather) for p in paths
            ]
        ys = [r[0] for r in results]
        r_invs = [r[1] for r in results]
        masks = [r[2] for r in results]

        bands = BandBatch(
            y=jnp.asarray(np.stack(ys)),
            r_inv=jnp.asarray(np.stack(r_invs)),
            mask=jnp.asarray(np.stack(masks)),
        )
        aux = self.aux_builder(metadata, gather)
        return DateObservation(
            bands=bands, operator=self.operator, aux=aux
        )


def find_nearest_geometry(available, sza: float, vza: float, raa: float):
    """Pick the closest (sza, vza, raa) key from an emulator bank — the
    per-geometry emulator selection of the reference
    (``Sentinel2_Observations.py:133-145``), which matches each axis to
    its nearest available grid value independently.

    On a complete angular grid the per-axis match lands on an existing
    key (the reference's assumption).  On an INCOMPLETE bank the axes can
    disagree — each axis's nearest value exists, but their combination is
    no actual bank — so the fallback picks the nearest EXISTING key, with
    each axis normalised by its grid span (raw degrees would let the wide
    relative-azimuth axis, 0-180, swamp the zenith axes, 20-60)."""
    keys = list(available)
    arr = np.asarray(keys, np.float64)  # (m, 3): sza, vza, raa
    e1 = arr[:, 0] == arr[np.argmin(np.abs(arr[:, 0] - sza)), 0]
    e2 = arr[:, 1] == arr[np.argmin(np.abs(arr[:, 1] - vza)), 1]
    e3 = arr[:, 2] == arr[np.argmin(np.abs(arr[:, 2] - raa)), 2]
    hits = np.where(e1 & e2 & e3)[0]
    if hits.size:
        return keys[int(hits[0])]
    span = arr.max(axis=0) - arr.min(axis=0)
    span[span <= 0] = 1.0
    dist = (np.abs(arr - [sza, vza, raa]) / span).sum(axis=1)
    return keys[int(np.argmin(dist))]


def geometry_bank_aux_builder(banks: Dict[tuple, Any]) -> Callable:
    """``aux_builder`` selecting a per-geometry emulator bank.

    ``banks`` maps ``(sza, vza, raa)`` grid points to operator aux pytrees
    (e.g. stacked ``GPParams`` from ``obsops.gp.stack_gp_bank``).  Each
    date's scene angles pick the nearest bank — the traced-data equivalent
    of the reference unpickling an emulator file per geometry
    (``Sentinel2_Observations.py:157-159``): the jitted program is reused,
    only the aux arrays change."""

    def build(metadata, gather):
        raa = metadata["vaa"] - metadata["saa"]
        key = find_nearest_geometry(
            banks.keys(), metadata["sza"], metadata["vza"], raa
        )
        return banks[key]

    return build
