"""MODIS MCD43 broadband-albedo (BHR) reader.

Reproduces the observation semantics of the reference's ``BHRObservations``
(``/root/reference/kafka/input_output/observations.py:214-310``):

- per-date granule indexing with ``period``-day thinning of the date list
  (16-day default, ``:241-242``);
- ROI windowing via ``apply_roi`` (``:262-267``);
- two bands, VIS then NIR (``:254-255``);
- BRDF kernel weights (iso, vol, geo) integrated to bihemispherical
  reflectance with ``to_BHR = [1.0, 0.189184, -1.377622]`` (``:290-298``);
- QA-dependent relative uncertainty — 5% for full inversions (QA 0), 7%
  for magnitude inversions (QA 1), floored at 2.5e-3 — stored as inverse
  variance (``:299-307``).

The reference reads MCD43A1/A2 HDF4-EOS granules through GDAL and an
external ``BRDF_descriptors`` package; neither exists in this image.  The
TPU-native contract is preprocessed GeoTIFFs, one pair per date and band:

    <dir>/MCD43_<A%Y%j>_<vis|nir>_kernels.tif   (3 bands: iso, vol, geo)
    <dir>/MCD43_<A%Y%j>_<vis|nir>_qa.tif        (QA level, 255 = no data)

which is exactly the intermediate the reference's ``SynergyKernels`` path
consumes as "kernel weight GeoTIFF time series" (``observations.py:150-211``).
"""

from __future__ import annotations

import datetime
import glob
import logging
import os
import re
from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.types import BandBatch
from ..engine.protocols import DateObservation
from ..engine.state import PixelGather
from .geotiff import read_info
from .roi import RoiWindowMixin, index_dated_paths

LOG = logging.getLogger(__name__)

#: Kernel-weight -> white-sky-albedo integration (``observations.py:290``).
TO_BHR = np.array([1.0, 0.189184, -1.377622], np.float64)
BAND_TRANSFER = {0: "vis", 1: "nir"}  # observations.py:254-255
_FNAME_RE = re.compile(r"MCD43_A(\d{7})_(vis|nir)_kernels\.tif$")

#: MODIS narrowband -> broadband albedo integration (the published spectral
#: conversion the reference hard-codes in ``SynergyKernels.get_band_data``,
#: ``observations.py:187-192``): weights over land bands 1-7 plus intercept.
TO_VIS = np.array([0.3265, 0.0, 0.4364, 0.2366, 0.0, 0.0, 0.0], np.float64)
TO_NIR = np.array([0.0, 0.5447, 0.0, 0.0, 0.1363, 0.0469, 0.2536], np.float64)
BB_INTERCEPT = (-0.0019, -0.0068)  # (VIS, NIR)


class BHRObservations(RoiWindowMixin):
    """ObservationSource over preprocessed MCD43 kernel-weight GeoTIFFs."""

    def __init__(
        self,
        data_dir: str,
        operator: Any,
        start_time: Optional[datetime.datetime] = None,
        end_time: Optional[datetime.datetime] = None,
        period: int = 16,
        aux_builder=None,
    ):
        self.data_dir = data_dir
        self.operator = operator
        self.aux_builder = aux_builder or (lambda date, gather: None)
        self._index_granules(start_time, end_time)
        # Thin to one date per `period` days (observations.py:241-242).
        self.dates = self.dates[::period] if period > 1 else self.dates
        self.bands_per_observation = {d: 2 for d in self.dates}

    def _index_granules(self, start_time, end_time) -> None:
        dates = set()
        for path in glob.glob(
            os.path.join(self.data_dir, "MCD43_A*_kernels.tif")
        ):
            m = _FNAME_RE.search(os.path.basename(path))
            if not m:
                continue
            d = datetime.datetime.strptime(m.group(1), "%Y%j")
            if start_time is not None and d < start_time:
                continue
            if end_time is not None and d > end_time:
                continue
            dates.add(d)
        self.dates: List[datetime.datetime] = sorted(dates)

    def _paths(self, date: datetime.datetime, band: int):
        stem = f"MCD43_A{date.strftime('%Y%j')}_{BAND_TRANSFER[band]}"
        return (
            os.path.join(self.data_dir, stem + "_kernels.tif"),
            os.path.join(self.data_dir, stem + "_qa.tif"),
        )

    def define_output(self):
        self._require_dates()
        kpath, _ = self._paths(self.dates[0], 0)
        info = read_info(kpath)
        gt = self._shift_geotransform(info.geo.geotransform)
        return info.geo.epsg or "sinusoidal", gt

    def get_observations(self, date, gather: PixelGather) -> DateObservation:
        ys, r_invs, masks = [], [], []
        for band in (0, 1):
            kpath, qpath = self._paths(date, band)
            kernels = np.asarray(
                self._read_windowed(kpath), np.float64
            )  # (ny, nx, 3)
            qa = np.asarray(self._read_windowed(qpath))
            k_pix = gather.gather(kernels)       # (n_pad, 3)
            qa_pix = gather.gather(qa.astype(np.int32), fill=255)
            valid = (qa_pix <= 1) & np.isfinite(k_pix).all(axis=-1) \
                & gather.valid
            # kernels . to_BHR -> white-sky albedo (observations.py:290-298)
            bhr = np.where(valid, k_pix @ TO_BHR, 0.0).astype(np.float32)
            # QA-dependent sigma, floored (observations.py:299-303).
            sigma = np.zeros_like(bhr)
            sigma[qa_pix == 0] = np.maximum(2.5e-3, bhr[qa_pix == 0] * 0.05)
            sigma[qa_pix == 1] = np.maximum(2.5e-3, bhr[qa_pix == 1] * 0.07)
            with np.errstate(divide="ignore"):
                r_inv = np.where(valid & (sigma > 0), 1.0 / sigma**2, 0.0)
            ys.append(bhr)
            r_invs.append(r_inv.astype(np.float32))
            masks.append(valid & (sigma > 0))

        bands = BandBatch(
            y=jnp.asarray(np.stack(ys)),
            r_inv=jnp.asarray(np.stack(r_invs)),
            mask=jnp.asarray(np.stack(masks)),
        )
        return DateObservation(
            bands=bands,
            operator=self.operator,
            aux=self.aux_builder(date, gather),
        )


_SYNERGY_RE = re.compile(r"\.A(\d{7})")


class SynergyKernels(RoiWindowMixin):
    """Broadband-albedo observations from per-band kernel-weight series.

    The reference's ``SynergyKernels`` (``observations.py:150-211``) indexes
    ``*_b{band}_kernel_weights.tif`` time series, integrates the 3 kernel
    weights to white-sky albedo with ``to_BHR`` and spectrally integrates
    the 7 MODIS land bands to broadband VIS/NIR — but its ``get_band_data``
    never returns and never touches uncertainty.  This class completes the
    contract: 2-band (VIS, NIR) broadband BHR observations with variance
    propagated through both linear integrations, assuming independent
    per-kernel, per-band errors:

        var(BHR_b) = sum_k to_BHR[k]^2 * sigma_bk^2
        var(BB)    = sum_b w_b^2 * var(BHR_b)

    On-disk contract per date (3-band float GeoTIFFs, kernel order
    iso/vol/geo, matching the reference's file naming ``:155-170``):

        <dir>/<stem>.A<%Y%j>_b{0..6}_kernel_weights.tif
        <dir>/<stem>.A<%Y%j>_b{0..6}_kernel_unc.tif
        <dir>/<stem>.A<%Y%j>_mask.tif                (uint8, 1 = usable)
    """

    def __init__(
        self,
        data_dir: str,
        operator: Any,
        start_time: Optional[datetime.datetime] = None,
        end_time: Optional[datetime.datetime] = None,
    ):
        self.data_dir = data_dir
        self.operator = operator
        self._stems: Dict[datetime.datetime, str] = index_dated_paths(
            os.path.join(data_dir, "*_b0_kernel_weights.tif"), _SYNERGY_RE,
            start_time, end_time,
            transform=lambda p: p[: -len("_b0_kernel_weights.tif")],
            label="Synergy series",
        )
        self.dates: List[datetime.datetime] = sorted(self._stems)
        self.bands_per_observation = {d: 2 for d in self.dates}

    def add_observations(self, date: datetime.datetime, stem: str) -> None:
        """Append one date to the index (``observations.py:176-182``)."""
        self._stems[date] = stem
        self.dates = sorted(self._stems)
        self.bands_per_observation[date] = 2

    def define_output(self):
        self._require_dates()
        stem = self._stems[self.dates[0]]
        info = read_info(stem + "_b0_kernel_weights.tif")
        gt = self._shift_geotransform(info.geo.geotransform)
        return info.geo.epsg or info.geo.projection or "sinusoidal", gt

    def get_observations(self, date, gather: PixelGather) -> DateObservation:
        stem = self._stems[date]
        mask_r = self._read_windowed(stem + "_mask.tif")
        usable = gather.gather(
            np.asarray(mask_r).squeeze().astype(bool)
        ) & gather.valid

        bhr = np.zeros((7, gather.n_pad), np.float64)
        var = np.zeros((7, gather.n_pad), np.float64)
        for band in range(7):
            k = self._read_windowed(f"{stem}_b{band}_kernel_weights.tif")
            u = self._read_windowed(f"{stem}_b{band}_kernel_unc.tif")
            k_pix = gather.gather(
                np.asarray(k, np.float64)
            )  # (n_pad, 3)
            u_pix = gather.gather(np.asarray(u, np.float64))
            bhr[band] = k_pix @ TO_BHR
            var[band] = (u_pix**2) @ (TO_BHR**2)

        ys, r_invs, masks = [], [], []
        for bb, weights in enumerate((TO_VIS, TO_NIR)):
            y = weights @ bhr + BB_INTERCEPT[bb]
            v = (weights**2) @ var
            valid = usable & np.isfinite(y) & (v > 0)
            ys.append(np.where(valid, y, 0.0).astype(np.float32))
            with np.errstate(divide="ignore"):
                r_invs.append(
                    np.where(valid, 1.0 / v, 0.0).astype(np.float32)
                )
            masks.append(valid)

        bands = BandBatch(
            y=jnp.asarray(np.stack(ys)),
            r_inv=jnp.asarray(np.stack(r_invs)),
            mask=jnp.asarray(np.stack(masks)),
        )
        return DateObservation(bands=bands, operator=self.operator, aux=None)
