"""Host-side raster reprojection — the GDAL-warp replacement.

Every reference reader warps each acquisition to the state-mask grid with
``gdal.Warp``/``ReprojectImage``
(``/root/reference/kafka/input_output/Sentinel2_Observations.py:56-79``,
``Sentinel1_Observations.py:30-53``, ``input_output/utils.py:43-64``).  This
image has no GDAL/pyproj, and the warp is a host-side data-prep step (never
on the device hot path), so the needed projection math is implemented here
directly in vectorised NumPy:

- **WGS84 geographic** (EPSG:4326),
- **UTM** (EPSG:326xx north / 327xx south) via the Krüger/Karney
  transverse-Mercator series to n^3 (sub-mm over a UTM zone) — covers all
  Sentinel-2 MGRS tiles and the reference's EPSG:32630 Barrax fixtures,
- **MODIS sinusoidal** (the MCD43/MOD09 grid; sphere R=6371007.181 m).

Resampling is nearest or bilinear gather — the reference uses
nearest-neighbour for masks and bilinear for reflectances
(``input_output/utils.py:58-63``).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# WGS84
_A = 6378137.0
_F = 1.0 / 298.257223563
_E2 = _F * (2.0 - _F)
_N = _F / (2.0 - _F)
# Krüger series radius and coefficients (to n^3).
_ABAR = _A / (1.0 + _N) * (1.0 + _N**2 / 4.0 + _N**4 / 64.0)
_ALPHA = (
    _N / 2.0 - 2.0 * _N**2 / 3.0 + 5.0 * _N**3 / 16.0,
    13.0 * _N**2 / 48.0 - 3.0 * _N**3 / 5.0,
    61.0 * _N**3 / 240.0,
)
_BETA = (
    _N / 2.0 - 2.0 * _N**2 / 3.0 + 37.0 * _N**3 / 96.0,
    _N**2 / 48.0 + _N**3 / 15.0,
    17.0 * _N**3 / 480.0,
)
_DELTA = (
    2.0 * _N - 2.0 * _N**2 / 3.0 - 2.0 * _N**3,
    7.0 * _N**2 / 3.0 - 8.0 * _N**3 / 5.0,
    56.0 * _N**3 / 15.0,
)
_K0 = 0.9996
_E0 = 500000.0
# MODIS sinusoidal sphere radius (the SIN grid's datum).
_R_SIN = 6371007.181


def utm_zone_params(epsg: int) -> Tuple[float, float]:
    """(central meridian radians, false northing) of a UTM EPSG code."""
    if 32601 <= epsg <= 32660:
        zone, n0 = epsg - 32600, 0.0
    elif 32701 <= epsg <= 32760:
        zone, n0 = epsg - 32700, 10000000.0
    else:
        raise ValueError(f"not a UTM EPSG code: {epsg}")
    lon0 = np.deg2rad(-183.0 + 6.0 * zone)
    return lon0, n0


def lonlat_to_utm(lon, lat, epsg: int):
    """Forward transverse Mercator (degrees -> metres)."""
    lon0, n0 = utm_zone_params(epsg)
    lam = np.deg2rad(np.asarray(lon, np.float64)) - lon0
    phi = np.deg2rad(np.asarray(lat, np.float64))
    sphi = np.sin(phi)
    c = 2.0 * np.sqrt(_N) / (1.0 + _N)
    t = np.sinh(np.arctanh(sphi) - c * np.arctanh(c * sphi))
    xi = np.arctan2(t, np.cos(lam))
    eta = np.arcsinh(np.sin(lam) / np.hypot(t, np.cos(lam)))
    x, y = xi.copy(), eta.copy()
    for j, al in enumerate(_ALPHA, start=1):
        x = x + al * np.sin(2 * j * xi) * np.cosh(2 * j * eta)
        y = y + al * np.cos(2 * j * xi) * np.sinh(2 * j * eta)
    easting = _E0 + _K0 * _ABAR * y
    northing = n0 + _K0 * _ABAR * x
    return easting, northing


def utm_to_lonlat(easting, northing, epsg: int):
    """Inverse transverse Mercator (metres -> degrees)."""
    lon0, n0 = utm_zone_params(epsg)
    xi = (np.asarray(northing, np.float64) - n0) / (_K0 * _ABAR)
    eta = (np.asarray(easting, np.float64) - _E0) / (_K0 * _ABAR)
    xip, etap = xi.copy(), eta.copy()
    for j, be in enumerate(_BETA, start=1):
        xip = xip - be * np.sin(2 * j * xi) * np.cosh(2 * j * eta)
        etap = etap - be * np.cos(2 * j * xi) * np.sinh(2 * j * eta)
    chi = np.arcsin(np.sin(xip) / np.cosh(etap))
    phi = chi.copy()
    for j, de in enumerate(_DELTA, start=1):
        phi = phi + de * np.sin(2 * j * chi)
    lam = np.arctan2(np.sinh(etap), np.cos(xip))
    return np.rad2deg(lam + lon0), np.rad2deg(phi)


def lonlat_to_sinusoidal(lon, lat):
    lat_r = np.deg2rad(np.asarray(lat, np.float64))
    lon_r = np.deg2rad(np.asarray(lon, np.float64))
    return _R_SIN * lon_r * np.cos(lat_r), _R_SIN * lat_r


def sinusoidal_to_lonlat(x, y):
    lat_r = np.asarray(y, np.float64) / _R_SIN
    with np.errstate(divide="ignore", invalid="ignore"):
        lon_r = np.asarray(x, np.float64) / (_R_SIN * np.cos(lat_r))
    return np.rad2deg(lon_r), np.rad2deg(lat_r)


#: EPSG code for the MODIS sinusoidal grid as used by GDAL ("SR-ORG:6974");
#: we accept the conventional 6974 plus the magic string "sinusoidal".
SINUSOIDAL = "sinusoidal"


def to_lonlat(crs, x, y):
    """Projected coordinates -> (lon, lat) degrees for a supported CRS."""
    key = _crs_key(crs)
    if key == 4326:
        return np.asarray(x, np.float64), np.asarray(y, np.float64)
    if key == SINUSOIDAL:
        return sinusoidal_to_lonlat(x, y)
    return utm_to_lonlat(x, y, key)


def from_lonlat(crs, lon, lat):
    """(lon, lat) degrees -> projected coordinates for a supported CRS."""
    key = _crs_key(crs)
    if key == 4326:
        return np.asarray(lon, np.float64), np.asarray(lat, np.float64)
    if key == SINUSOIDAL:
        return lonlat_to_sinusoidal(lon, lat)
    return lonlat_to_utm(lon, lat, key)


def _as_epsg(crs) -> int:
    if isinstance(crs, str):
        crs = crs.upper().replace("EPSG:", "")
        return int(crs)
    return int(crs)


def _crs_key(crs):
    """Canonical comparison key for a CRS value, so equivalent spellings
    (4326 vs 'EPSG:4326' vs None, 'sinusoidal' vs 6974) compare equal."""
    if crs in (None, "", 4326, "EPSG:4326"):
        return 4326
    if crs in (SINUSOIDAL, 6974):
        return SINUSOIDAL
    return _as_epsg(crs)


def apply_geotransform(gt, col, row):
    """Pixel (col, row) -> projected (x, y); GDAL convention, pixel centre
    at (col+0.5, row+0.5)."""
    return (
        gt[0] + (col + 0.5) * gt[1] + (row + 0.5) * gt[2],
        gt[3] + (col + 0.5) * gt[4] + (row + 0.5) * gt[5],
    )


def invert_geotransform(gt, x, y):
    """Projected (x, y) -> fractional pixel (col, row)."""
    det = gt[1] * gt[5] - gt[2] * gt[4]
    dx = np.asarray(x, np.float64) - gt[0]
    dy = np.asarray(y, np.float64) - gt[3]
    col = (gt[5] * dx - gt[2] * dy) / det - 0.5
    row = (-gt[4] * dx + gt[1] * dy) / det - 0.5
    return col, row


def grid_mapping(
    src_gt,
    dst_shape: Tuple[int, int],
    dst_gt,
    src_crs=None,
    dst_crs=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Fractional source pixel coordinates ``(col_f, row_f)`` of every
    destination pixel centre.  This is the expensive part of a warp (the
    per-pixel CRS transform); compute it once per (grid, CRS) pair and
    reuse it across bands/variables via ``resample``."""
    ny, nx = dst_shape
    cols, rows = np.meshgrid(np.arange(nx), np.arange(ny))
    x, y = apply_geotransform(dst_gt, cols, rows)
    # Exact equality first: equal-but-unparseable spellings must still be
    # treated as the identity mapping, without going through _crs_key.
    if src_crs != dst_crs and _crs_key(src_crs) != _crs_key(dst_crs):
        lon, lat = to_lonlat(dst_crs, x, y)
        x, y = from_lonlat(src_crs, lon, lat)
    return invert_geotransform(src_gt, x, y)


def resample(
    src: np.ndarray,
    col_f: np.ndarray,
    row_f: np.ndarray,
    method: str = "nearest",
    nodata: float = np.nan,
) -> np.ndarray:
    """Gather ``src`` (ny, nx[, k]) at fractional pixel coordinates.

    ``nearest`` or ``bilinear``; out-of-bounds pixels get ``nodata``.
    Trailing band axes are supported by both methods.
    """
    src = np.asarray(src)
    h, w = src.shape[:2]
    dst_shape = col_f.shape
    out_dtype = src.dtype if np.issubdtype(src.dtype, np.floating) \
        else np.float32
    if method == "nearest":
        ci = np.round(col_f).astype(np.int64)
        ri = np.round(row_f).astype(np.int64)
        valid = (ci >= 0) & (ci < w) & (ri >= 0) & (ri < h)
        out = np.full(dst_shape + src.shape[2:], nodata, out_dtype)
        out[valid] = src[ri[valid], ci[valid]]
        return out
    if method != "bilinear":
        raise ValueError(f"unknown resampling method: {method}")
    # Valid anywhere within the outer pixel centres; cell indices clamped to
    # the last full cell so points exactly on the far edge interpolate with
    # fraction 1.0 instead of being dropped.
    valid = (col_f >= 0) & (col_f <= w - 1) & (row_f >= 0) & (row_f <= h - 1)
    c0 = np.clip(np.floor(col_f).astype(np.int64), 0, max(w - 2, 0))
    r0 = np.clip(np.floor(row_f).astype(np.int64), 0, max(h - 2, 0))
    fc = np.clip(col_f - c0, 0.0, 1.0)
    fr = np.clip(row_f - r0, 0.0, 1.0)
    c1 = np.minimum(c0 + 1, w - 1)
    r1 = np.minimum(r0 + 1, h - 1)
    if src.ndim > 2:
        fc = fc[..., None]
        fr = fr[..., None]
        valid = valid[..., None]
    v00 = src[r0, c0].astype(np.float64)
    v01 = src[r0, c1].astype(np.float64)
    v10 = src[r1, c0].astype(np.float64)
    v11 = src[r1, c1].astype(np.float64)
    interp = (
        v00 * (1 - fr) * (1 - fc) + v01 * (1 - fr) * fc
        + v10 * fr * (1 - fc) + v11 * fr * fc
    )
    out = np.where(valid, interp, nodata)
    return out.astype(out_dtype)


def reproject_raster(
    src: np.ndarray,
    src_gt,
    dst_shape: Tuple[int, int],
    dst_gt,
    src_crs=None,
    dst_crs=None,
    method: str = "nearest",
    nodata: float = np.nan,
) -> np.ndarray:
    """Warp ``src`` (ny, nx[, k]) onto the destination grid.

    The equivalent of the reference's ``reproject_image``
    (``input_output/utils.py:43-64``): target-driven inverse mapping — for
    each destination pixel centre, project into the source grid and gather.
    One-shot convenience around ``grid_mapping`` + ``resample``.
    """
    col_f, row_f = grid_mapping(src_gt, dst_shape, dst_gt, src_crs, dst_crs)
    return resample(src, col_f, row_f, method=method, nodata=nodata)
