"""Self-contained GeoTIFF reader/writer.

The reference delegates all raster I/O to GDAL's C++ stack (readers in
``/root/reference/kafka/input_output/``; writer ``KafkaOutput.dump_data``,
``observations.py:354-394``).  This environment has no GDAL, and the TPU
build owns its raster path anyway (SURVEY.md §2.2): this module implements
the TIFF 6.0 container (classic + BigTIFF) with striped/tiled layout,
DEFLATE (zlib) compression, horizontal-differencing predictor, and the
GeoTIFF tags needed for georeferenced outputs (pixel scale, tiepoint, geokey
directory, projection citation) plus GDAL-style nodata.

Container parsing/assembly is pure Python + NumPy; the per-tile
compress/decompress/predictor hot path is dispatched to the C++ codec in
``kafka_tpu/native`` (thread-pooled zlib) when built, else Python zlib.

Capabilities: float32/float64/uint8/int16/uint16/int32/uint32 samples,
single- or multi-band (band-interleaved-by-pixel), compression none/deflate
(8)/adobe-deflate(32946), predictor 1/2.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import native_codec

# --- TIFF constants -------------------------------------------------------

_TYPE_SIZES = {1: 1, 2: 1, 3: 2, 4: 4, 5: 8, 6: 1, 7: 1, 8: 2, 9: 4,
               10: 8, 11: 4, 12: 8, 16: 8, 17: 8, 18: 8}
_TYPE_FMT = {1: "B", 3: "H", 4: "I", 8: "h", 9: "i", 11: "f", 12: "d",
             16: "Q", 17: "q"}

T_WIDTH, T_HEIGHT = 256, 257
T_BITS, T_COMPRESSION, T_PHOTOMETRIC = 258, 259, 262
T_STRIP_OFFSETS, T_SAMPLES_PER_PIXEL, T_ROWS_PER_STRIP = 273, 277, 278
T_STRIP_BYTECOUNTS = 279
T_PLANAR = 284
T_PREDICTOR = 317
T_TILE_WIDTH, T_TILE_HEIGHT, T_TILE_OFFSETS, T_TILE_BYTECOUNTS = (
    322, 323, 324, 325
)
T_SAMPLE_FORMAT = 339
T_PIXEL_SCALE, T_TIEPOINT = 33550, 33922
T_GEO_KEYS, T_GEO_DOUBLES, T_GEO_ASCII = 34735, 34736, 34737
T_GDAL_METADATA, T_GDAL_NODATA = 42112, 42113

_SAMPLE_DTYPES = {
    (8, 1): np.uint8, (16, 1): np.uint16, (32, 1): np.uint32,
    (8, 2): np.int8, (16, 2): np.int16, (32, 2): np.int32,
    (32, 3): np.float32, (64, 3): np.float64,
}


@dataclass
class GeoInfo:
    """Georeferencing: GDAL-style geotransform + projection description.

    ``geotransform`` = (origin_x, pixel_w, 0, origin_y, 0, -pixel_h), the
    exact 6-tuple contract of the reference's ``define_output``
    (``Sentinel2_Observations.py:100-113``).  ``projection`` is stored in
    the GeoASCII tag; EPSG codes go in the geokey directory.
    """

    geotransform: Tuple[float, ...] = (0.0, 1.0, 0.0, 0.0, 0.0, -1.0)
    projection: str = ""
    epsg: Optional[int] = None
    nodata: Optional[float] = None


@dataclass
class TiffInfo:
    width: int
    height: int
    n_bands: int
    dtype: np.dtype
    compression: int
    predictor: int
    tiled: bool
    tile_shape: Optional[Tuple[int, int]]
    geo: GeoInfo
    tags: Dict[int, tuple] = field(default_factory=dict)
    #: byte order of the file ("<" or ">") — sample data in an "MM" TIFF
    #: must be decoded big-endian regardless of host order.
    byte_order: str = "<"


# --- reading --------------------------------------------------------------


def _read_ifd(buf, offset, endian, big):
    entries = {}
    if big:
        (count,) = struct.unpack_from(endian + "Q", buf, offset)
        pos = offset + 8
        entry_size, cnt_fmt = 20, "Q"
    else:
        (count,) = struct.unpack_from(endian + "H", buf, offset)
        pos = offset + 2
        entry_size, cnt_fmt = 12, "I"
    for i in range(count):
        tag, typ = struct.unpack_from(endian + "HH", buf, pos)
        (n,) = struct.unpack_from(endian + cnt_fmt, buf, pos + 4)
        val_off = pos + (12 if big else 8)
        size = _TYPE_SIZES.get(typ, 1) * n
        inline = 8 if big else 4
        if size <= inline:
            data_pos = val_off
        else:
            (data_pos,) = struct.unpack_from(
                endian + ("Q" if big else "I"), buf, val_off
            )
        if typ in (2, 7):  # ascii / undefined
            values = bytes(buf[data_pos:data_pos + n])
        elif typ == 5 or typ == 10:  # rational
            raw = struct.unpack_from(endian + ("iI"[typ == 5] * 2 * n),
                                     buf, data_pos)
            values = tuple(raw[2 * i] / max(raw[2 * i + 1], 1)
                           for i in range(n))
        else:
            fmt = _TYPE_FMT.get(typ)
            if fmt is None:
                pos += entry_size
                continue
            values = struct.unpack_from(endian + fmt * n, buf, data_pos)
        entries[tag] = values
        pos += entry_size
    (next_ifd,) = struct.unpack_from(
        endian + ("Q" if big else "I"), buf, pos
    )
    return entries, next_ifd


def _tag1(tags, tag, default=None):
    v = tags.get(tag)
    if v is None:
        return default
    return v[0] if isinstance(v, tuple) else v


def read_info(path: str) -> TiffInfo:
    with open(path, "rb") as f:
        buf = f.read()
    return _parse_info(buf)[0]


def _parse_info(buf):
    endian = {b"II": "<", b"MM": ">"}.get(bytes(buf[:2]))
    if endian is None:
        raise ValueError("not a TIFF file")
    magic = struct.unpack_from(endian + "H", buf, 2)[0]
    if magic == 42:
        big = False
        (ifd_off,) = struct.unpack_from(endian + "I", buf, 4)
    elif magic == 43:
        big = True
        (ifd_off,) = struct.unpack_from(endian + "Q", buf, 8)
    else:
        raise ValueError("bad TIFF magic %d" % magic)
    tags, _ = _read_ifd(buf, ifd_off, endian, big)

    width = _tag1(tags, T_WIDTH)
    height = _tag1(tags, T_HEIGHT)
    n_bands = _tag1(tags, T_SAMPLES_PER_PIXEL, 1)
    bits = _tag1(tags, T_BITS, 8)
    fmt = _tag1(tags, T_SAMPLE_FORMAT, 1)
    dtype = np.dtype(_SAMPLE_DTYPES.get((bits, fmt), np.uint8))
    compression = _tag1(tags, T_COMPRESSION, 1)
    predictor = _tag1(tags, T_PREDICTOR, 1)
    tiled = T_TILE_OFFSETS in tags

    geo = GeoInfo()
    if T_PIXEL_SCALE in tags and T_TIEPOINT in tags:
        sx, sy = tags[T_PIXEL_SCALE][0], tags[T_PIXEL_SCALE][1]
        tp = tags[T_TIEPOINT]
        # tiepoint: (i, j, k, x, y, z) raster->model
        ox = tp[3] - tp[0] * sx
        oy = tp[4] + tp[1] * sy
        geo.geotransform = (ox, sx, 0.0, oy, 0.0, -sy)
    if T_GEO_ASCII in tags:
        geo.projection = tags[T_GEO_ASCII].rstrip(b"\x00|").decode(
            "ascii", "replace"
        )
    if T_GEO_KEYS in tags:
        keys = tags[T_GEO_KEYS]
        for i in range(4, len(keys), 4):
            key_id, loc, cnt, val = keys[i:i + 4]
            if key_id in (3072, 2048) and loc == 0:  # Projected/Geog CS
                geo.epsg = int(val)
    if T_GDAL_NODATA in tags:
        try:
            geo.nodata = float(
                tags[T_GDAL_NODATA].rstrip(b"\x00").strip()
            )
        except ValueError:
            pass

    info = TiffInfo(
        width=int(width), height=int(height), n_bands=int(n_bands),
        dtype=dtype, compression=int(compression), predictor=int(predictor),
        tiled=tiled,
        tile_shape=(
            (int(_tag1(tags, T_TILE_HEIGHT)), int(_tag1(tags, T_TILE_WIDTH)))
            if tiled else None
        ),
        geo=geo, tags=tags, byte_order=endian,
    )
    return info, endian, big


def _decode_segments(segments, info, seg_shape):
    """Decompress + de-predict a list of raw byte segments into arrays of
    ``seg_shape`` (rows, cols, bands)."""
    rows, cols = seg_shape
    itemsize = info.dtype.itemsize
    expected = rows * cols * info.n_bands * itemsize
    if info.compression in (8, 32946):
        raw = native_codec.inflate_many(segments, expected)
    elif info.compression == 1:
        raw = [bytes(s) for s in segments]
    elif info.compression == 5:
        raw = [_lzw_decode(bytes(s)) for s in segments]
    else:
        raise NotImplementedError(
            "TIFF compression %d not supported" % info.compression
        )
    # Decode with the FILE's byte order, then return native-endian arrays.
    file_dtype = info.dtype.newbyteorder(info.byte_order)
    out = []
    for r in raw:
        arr = np.frombuffer(r[:expected].ljust(expected, b"\x00"),
                            dtype=file_dtype)
        arr = arr.reshape(rows, cols, info.n_bands).astype(info.dtype)
        if info.predictor == 2:
            np.cumsum(arr, axis=1, out=arr, dtype=arr.dtype)
        out.append(arr)
    return out


def _lzw_decode(data: bytes) -> bytes:
    """TIFF LZW (MSB-first, early-change) — needed for fixtures written by
    GDAL's default creation options."""
    CLEAR, EOI = 256, 257
    out = bytearray()
    table: List[bytes] = []

    def reset():
        nonlocal table
        table = [bytes([i]) for i in range(256)] + [b"", b""]

    reset()
    bitpos = 0
    nbits = 9
    prev = b""
    total_bits = len(data) * 8
    while bitpos + nbits <= total_bits:
        byte_idx = bitpos >> 3
        chunk = int.from_bytes(
            data[byte_idx:byte_idx + 4].ljust(4, b"\x00"), "big"
        )
        code = (chunk >> (32 - nbits - (bitpos & 7))) & ((1 << nbits) - 1)
        bitpos += nbits
        if code == EOI:
            break
        if code == CLEAR:
            reset()
            nbits = 9
            prev = b""
            continue
        if prev == b"":
            entry = table[code]
        elif code < len(table):
            entry = table[code]
            table.append(prev + entry[:1])
        else:
            entry = prev + prev[:1]
            table.append(entry)
        out += entry
        prev = entry
        if len(table) >= (1 << nbits) - 1 and nbits < 12:
            nbits += 1
    return bytes(out)


def read_geotiff(path: str) -> Tuple[np.ndarray, TiffInfo]:
    """Read a GeoTIFF.  Returns ``(array, info)`` with array shaped
    (height, width) single-band or (height, width, bands)."""
    with open(path, "rb") as f:
        buf = f.read()
    info, endian, big = _parse_info(buf)
    tags = info.tags
    h, w, nb = info.height, info.width, info.n_bands
    out = np.zeros((h, w, nb), info.dtype)
    if info.tiled:
        th, tw = info.tile_shape
        offsets = tags[T_TILE_OFFSETS]
        counts = tags[T_TILE_BYTECOUNTS]
        tiles_across = (w + tw - 1) // tw
        segs = [buf[o:o + c] for o, c in zip(offsets, counts)]
        arrays = _decode_segments(segs, info, (th, tw))
        for idx, arr in enumerate(arrays):
            ty, tx = divmod(idx, tiles_across)
            y0, x0 = ty * th, tx * tw
            ys, xs = min(th, h - y0), min(tw, w - x0)
            if ys <= 0 or xs <= 0:
                continue
            out[y0:y0 + ys, x0:x0 + xs] = arr[:ys, :xs]
    else:
        rps = int(_tag1(tags, T_ROWS_PER_STRIP, h))
        offsets = tags[T_STRIP_OFFSETS]
        counts = tags.get(
            T_STRIP_BYTECOUNTS, tuple([len(buf)] * len(offsets))
        )
        for si, (o, c) in enumerate(zip(offsets, counts)):
            y0 = si * rps
            rows = min(rps, h - y0)
            if rows <= 0:
                continue
            arr = _decode_segments([buf[o:o + c]], info, (rows, w))[0]
            out[y0:y0 + rows] = arr
    if nb == 1:
        out = out[:, :, 0]
    return out, info


# --- writing --------------------------------------------------------------


def _geo_tags(geo: GeoInfo):
    ox, sx, _, oy, _, nsy = geo.geotransform
    tags = [
        (T_PIXEL_SCALE, 12, (float(sx), float(abs(nsy)), 0.0)),
        (T_TIEPOINT, 12, (0.0, 0.0, 0.0, float(ox), float(oy), 0.0)),
    ]
    keys = [1, 1, 0, 0]  # version, rev, minor, n_keys (patched below)
    n_keys = 0
    # Geographic CRS codes (EPSG 4000-4999, e.g. 4326/WGS84) get
    # ModelTypeGeographic + GeographicTypeGeoKey; everything else is
    # treated as projected (ProjectedCSTypeGeoKey).
    geographic = geo.epsg is not None and 4000 <= geo.epsg < 5000
    keys += [1024, 0, 1, 2 if geographic else 1]
    n_keys += 1
    keys += [1025, 0, 1, 1]  # RasterPixelIsArea
    n_keys += 1
    if geo.epsg is not None:
        keys += [2048 if geographic else 3072, 0, 1, int(geo.epsg)]
        n_keys += 1
    ascii_blob = b""
    if geo.projection:
        text = geo.projection.encode("ascii", "replace") + b"|"
        keys += [1026, T_GEO_ASCII, len(text), 0]
        n_keys += 1
        ascii_blob = text
    keys[3] = n_keys
    tags.append((T_GEO_KEYS, 3, tuple(keys)))
    if ascii_blob:
        tags.append((T_GEO_ASCII, 2, ascii_blob + b"\x00"))
    if geo.nodata is not None:
        tags.append(
            (T_GDAL_NODATA, 2, (repr(float(geo.nodata)).encode() + b"\x00"))
        )
    return tags


_DTYPE_TO_TAGS = {
    np.dtype(np.uint8): (8, 1), np.dtype(np.uint16): (16, 1),
    np.dtype(np.uint32): (32, 1), np.dtype(np.int16): (16, 2),
    np.dtype(np.int32): (32, 2), np.dtype(np.float32): (32, 3),
    np.dtype(np.float64): (64, 3),
}


def write_geotiff(
    path: str,
    array: np.ndarray,
    geo: Optional[GeoInfo] = None,
    tile_size: int = 256,
    compress: bool = True,
    level: int = 6,
    predictor: int = 1,
    bigtiff: Optional[bool] = None,
) -> None:
    """Write a single/multi-band GeoTIFF: tiled, DEFLATE by default — the
    writer-side contract of the reference's ``KafkaOutput``
    (``observations.py:360-365``: COMPRESS=DEFLATE, TILED=YES, PREDICTOR=1,
    BIGTIFF=YES; BigTIFF here switches on automatically past 3.5 GB or can
    be forced)."""
    geo = geo or GeoInfo()
    arr = np.asarray(array)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    h, w, nb = arr.shape
    dtype = arr.dtype
    if dtype not in _DTYPE_TO_TAGS:
        arr = arr.astype(np.float32)
        dtype = arr.dtype
    bits, fmt = _DTYPE_TO_TAGS[dtype]
    if predictor == 2 and dtype.kind == "f":
        # TIFF predictor 2 is integer-only (floats use predictor 3); a
        # float-diff file would be unreadable by libtiff/GDAL.
        raise ValueError(
            "predictor=2 requires an integer dtype; floats must use "
            "predictor 1 (got %s)" % dtype
        )

    th = tw = tile_size
    tiles_down = (h + th - 1) // th
    tiles_across = (w + tw - 1) // tw
    segs = []
    for ty in range(tiles_down):
        for tx in range(tiles_across):
            tile = np.zeros((th, tw, nb), dtype)
            y0, x0 = ty * th, tx * tw
            ys, xs = min(th, h - y0), min(tw, w - x0)
            tile[:ys, :xs] = arr[y0:y0 + ys, x0:x0 + xs]
            if predictor == 2:
                tile = np.diff(
                    np.concatenate(
                        [np.zeros((th, 1, nb), dtype), tile], axis=1
                    ),
                    axis=1,
                ).astype(dtype)
            segs.append(tile.tobytes())
    if compress:
        segs = native_codec.deflate_many(segs, level)
        compression = 8
    else:
        compression = 1

    data_size = sum(len(s) for s in segs)
    if bigtiff is None:
        bigtiff = data_size > 3_500_000_000
    big = bool(bigtiff)

    entries = [
        (T_WIDTH, 3, (w,)), (T_HEIGHT, 3, (h,)),
        (T_BITS, 3, (bits,) * nb),
        (T_COMPRESSION, 3, (compression,)),
        (T_PHOTOMETRIC, 3, (1,)),
        (T_SAMPLES_PER_PIXEL, 3, (nb,)),
        (T_PLANAR, 3, (1,)),
        (T_PREDICTOR, 3, (predictor,)),
        (T_TILE_WIDTH, 3, (tw,)), (T_TILE_HEIGHT, 3, (th,)),
        (T_SAMPLE_FORMAT, 3, (fmt,) * nb),
    ]
    entries += _geo_tags(geo)

    off_type = 16 if big else 4  # LONG8 vs LONG
    entries.append((T_TILE_OFFSETS, off_type, None))     # patched later
    entries.append((T_TILE_BYTECOUNTS, off_type, None))
    entries.sort(key=lambda e: e[0])

    endian = "<"
    header_size = 16 if big else 8
    ifd_entry = 20 if big else 12
    ifd_header = 8 if big else 2
    ifd_tail = 8 if big else 4
    inline_max = 8 if big else 4
    n = len(entries)
    ifd_size = ifd_header + n * ifd_entry + ifd_tail

    # layout: header | IFD | overflow tag data | segment data
    overflow = []
    overflow_pos = header_size + ifd_size

    def value_bytes(typ, values):
        if typ == 2 or typ == 7:
            return bytes(values)
        fmt_ch = {3: "H", 4: "I", 12: "d", 16: "Q"}[typ]
        return struct.pack(endian + fmt_ch * len(values), *values)

    # first pass to size overflow area (tile offsets resolved after)
    seg_count = len(segs)
    placeholder = {
        T_TILE_OFFSETS: (off_type, tuple([0] * seg_count)),
        T_TILE_BYTECOUNTS: (off_type, tuple(len(s) for s in segs)),
    }
    sized = []
    for tag, typ, values in entries:
        if values is None:
            typ, values = placeholder[tag]
        raw = value_bytes(typ, values)
        count = (
            len(values) if typ in (2, 7)
            else len(values)
        )
        sized.append((tag, typ, count, raw))
        if len(raw) > inline_max:
            overflow.append(len(raw))
    data_start = overflow_pos + sum((s + 1) & ~1 for s in overflow)

    # resolve real tile offsets
    offsets = []
    pos = data_start
    for s in segs:
        offsets.append(pos)
        pos += len(s)
    final = []
    for tag, typ, count, raw in sized:
        if tag == T_TILE_OFFSETS:
            raw = value_bytes(typ, tuple(offsets))
        final.append((tag, typ, count, raw))

    with open(path, "wb") as f:
        if big:
            f.write(struct.pack(endian + "2sHHHQ", b"II", 43, 8, 0,
                                header_size))
        else:
            f.write(struct.pack(endian + "2sHI", b"II", 42, header_size))
        # IFD
        if big:
            f.write(struct.pack(endian + "Q", n))
        else:
            f.write(struct.pack(endian + "H", n))
        ov_pos = overflow_pos
        ov_chunks = []
        for tag, typ, count, raw in final:
            f.write(struct.pack(endian + "HH", tag, typ))
            f.write(struct.pack(endian + ("Q" if big else "I"), count))
            if len(raw) <= inline_max:
                f.write(raw.ljust(inline_max, b"\x00"))
            else:
                f.write(struct.pack(endian + ("Q" if big else "I"), ov_pos))
                ov_chunks.append((ov_pos, raw))
                ov_pos += (len(raw) + 1) & ~1
        f.write(struct.pack(endian + ("Q" if big else "I"), 0))  # next IFD
        for pos_, raw in ov_chunks:
            f.seek(pos_)
            f.write(raw)
        f.seek(data_start)
        for s in segs:
            f.write(s)
