"""Self-contained GeoTIFF reader/writer.

The reference delegates all raster I/O to GDAL's C++ stack (readers in
``/root/reference/kafka/input_output/``; writer ``KafkaOutput.dump_data``,
``observations.py:354-394``).  This environment has no GDAL, and the TPU
build owns its raster path anyway (SURVEY.md §2.2): this module implements
the TIFF 6.0 container (classic + BigTIFF) with striped/tiled layout,
DEFLATE (zlib) compression, horizontal-differencing predictor, and the
GeoTIFF tags needed for georeferenced outputs (pixel scale, tiepoint, geokey
directory, projection citation) plus GDAL-style nodata.

Container parsing/assembly is pure Python + NumPy; the per-tile
compress/decompress/predictor hot path is dispatched to the C++ codec in
``kafka_tpu/native`` (thread-pooled zlib, fused float32-predictor-3
chain, batch LZW) when built, else Python zlib + the reference decoders
here.

Capabilities: float32/float64/uint8/int16/uint16/int32/uint32 samples,
single- or multi-band (band-interleaved-by-pixel), compression
none/deflate(8)/adobe-deflate(32946)/LZW(5) read AND write (LZW write is
the GDAL-default-compatibility mode), predictor 1/2/3.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import native_codec
from ..resilience import faults

# --- TIFF constants -------------------------------------------------------

_TYPE_SIZES = {1: 1, 2: 1, 3: 2, 4: 4, 5: 8, 6: 1, 7: 1, 8: 2, 9: 4,
               10: 8, 11: 4, 12: 8, 16: 8, 17: 8, 18: 8}
_TYPE_FMT = {1: "B", 3: "H", 4: "I", 8: "h", 9: "i", 11: "f", 12: "d",
             16: "Q", 17: "q"}

T_WIDTH, T_HEIGHT = 256, 257
T_BITS, T_COMPRESSION, T_PHOTOMETRIC = 258, 259, 262
T_STRIP_OFFSETS, T_SAMPLES_PER_PIXEL, T_ROWS_PER_STRIP = 273, 277, 278
T_STRIP_BYTECOUNTS = 279
T_PLANAR = 284
T_PREDICTOR = 317
T_TILE_WIDTH, T_TILE_HEIGHT, T_TILE_OFFSETS, T_TILE_BYTECOUNTS = (
    322, 323, 324, 325
)
T_SAMPLE_FORMAT = 339
T_PIXEL_SCALE, T_TIEPOINT = 33550, 33922
T_GEO_KEYS, T_GEO_DOUBLES, T_GEO_ASCII = 34735, 34736, 34737
T_GDAL_METADATA, T_GDAL_NODATA = 42112, 42113

_SAMPLE_DTYPES = {
    (8, 1): np.uint8, (16, 1): np.uint16, (32, 1): np.uint32,
    (8, 2): np.int8, (16, 2): np.int16, (32, 2): np.int32,
    (32, 3): np.float32, (64, 3): np.float64,
}


@dataclass
class GeoInfo:
    """Georeferencing: GDAL-style geotransform + projection description.

    ``geotransform`` = (origin_x, pixel_w, 0, origin_y, 0, -pixel_h), the
    exact 6-tuple contract of the reference's ``define_output``
    (``Sentinel2_Observations.py:100-113``).  ``projection`` is stored in
    the GeoASCII tag; EPSG codes go in the geokey directory.
    """

    geotransform: Tuple[float, ...] = (0.0, 1.0, 0.0, 0.0, 0.0, -1.0)
    projection: str = ""
    epsg: Optional[int] = None
    nodata: Optional[float] = None


@dataclass
class TiffInfo:
    width: int
    height: int
    n_bands: int
    dtype: np.dtype
    compression: int
    predictor: int
    tiled: bool
    tile_shape: Optional[Tuple[int, int]]
    geo: GeoInfo
    tags: Dict[int, tuple] = field(default_factory=dict)
    #: byte order of the file ("<" or ">") — sample data in an "MM" TIFF
    #: must be decoded big-endian regardless of host order.
    byte_order: str = "<"


# --- reading --------------------------------------------------------------
#
# All parsing is seek-based: only the header, the IFD, and the out-of-line
# tag values are read up front, so opening a multi-GB BigTIFF costs a few KB
# of I/O and windowed reads touch only the tiles they intersect.


def _read_ifd(read, offset, endian, big):
    """Parse one IFD via ``read(offset, size) -> bytes``."""
    entries = {}
    if big:
        (count,) = struct.unpack(endian + "Q", read(offset, 8))
        pos = offset + 8
        entry_size, cnt_fmt = 20, "Q"
    else:
        (count,) = struct.unpack(endian + "H", read(offset, 2))
        pos = offset + 2
        entry_size, cnt_fmt = 12, "I"
    block = read(pos, count * entry_size + (8 if big else 4))
    for i in range(count):
        epos = i * entry_size
        tag, typ = struct.unpack_from(endian + "HH", block, epos)
        (n,) = struct.unpack_from(endian + cnt_fmt, block, epos + 4)
        val_off = epos + (12 if big else 8)
        size = _TYPE_SIZES.get(typ, 1) * n
        inline = 8 if big else 4
        if size <= inline:
            data = block[val_off:val_off + size]
        else:
            (data_pos,) = struct.unpack_from(
                endian + ("Q" if big else "I"), block, val_off
            )
            data = read(data_pos, size)
        if typ in (2, 7):  # ascii / undefined
            values = bytes(data[:n])
        elif typ == 5 or typ == 10:  # rational
            raw = struct.unpack(endian + ("iI"[typ == 5] * 2 * n), data)
            values = tuple(raw[2 * i] / max(raw[2 * i + 1], 1)
                           for i in range(n))
        else:
            fmt = _TYPE_FMT.get(typ)
            if fmt is None:
                continue
            values = struct.unpack(endian + fmt * n, data)
        entries[tag] = values
    (next_ifd,) = struct.unpack(
        endian + ("Q" if big else "I"),
        block[count * entry_size:count * entry_size + (8 if big else 4)],
    )
    return entries, next_ifd


def _tag1(tags, tag, default=None):
    v = tags.get(tag)
    if v is None:
        return default
    return v[0] if isinstance(v, tuple) else v


def read_info(path: str) -> TiffInfo:
    """Header + IFD only — cheap even for multi-GB files."""
    with open(path, "rb") as f:
        return _parse_info_f(f)[0]


def _parse_info_f(f):
    def read(off, size):
        f.seek(off)
        return f.read(size)

    head = read(0, 16)
    endian = {b"II": "<", b"MM": ">"}.get(bytes(head[:2]))
    if endian is None:
        raise ValueError("not a TIFF file")
    magic = struct.unpack_from(endian + "H", head, 2)[0]
    if magic == 42:
        big = False
        (ifd_off,) = struct.unpack_from(endian + "I", head, 4)
    elif magic == 43:
        big = True
        (ifd_off,) = struct.unpack_from(endian + "Q", head, 8)
    else:
        raise ValueError("bad TIFF magic %d" % magic)
    tags, _ = _read_ifd(read, ifd_off, endian, big)

    width = _tag1(tags, T_WIDTH)
    height = _tag1(tags, T_HEIGHT)
    n_bands = _tag1(tags, T_SAMPLES_PER_PIXEL, 1)
    bits = _tag1(tags, T_BITS, 8)
    fmt = _tag1(tags, T_SAMPLE_FORMAT, 1)
    dtype = np.dtype(_SAMPLE_DTYPES.get((bits, fmt), np.uint8))
    compression = _tag1(tags, T_COMPRESSION, 1)
    predictor = _tag1(tags, T_PREDICTOR, 1)
    tiled = T_TILE_OFFSETS in tags

    geo = GeoInfo()
    if T_PIXEL_SCALE in tags and T_TIEPOINT in tags:
        sx, sy = tags[T_PIXEL_SCALE][0], tags[T_PIXEL_SCALE][1]
        tp = tags[T_TIEPOINT]
        # tiepoint: (i, j, k, x, y, z) raster->model
        ox = tp[3] - tp[0] * sx
        oy = tp[4] + tp[1] * sy
        geo.geotransform = (ox, sx, 0.0, oy, 0.0, -sy)
    if T_GEO_ASCII in tags:
        geo.projection = tags[T_GEO_ASCII].rstrip(b"\x00|").decode(
            "ascii", "replace"
        )
    if T_GEO_KEYS in tags:
        keys = tags[T_GEO_KEYS]
        for i in range(4, len(keys), 4):
            key_id, loc, cnt, val = keys[i:i + 4]
            if key_id in (3072, 2048) and loc == 0:  # Projected/Geog CS
                geo.epsg = int(val)
    if T_GDAL_NODATA in tags:
        try:
            geo.nodata = float(
                tags[T_GDAL_NODATA].rstrip(b"\x00").strip()
            )
        except ValueError:
            pass

    info = TiffInfo(
        width=int(width), height=int(height), n_bands=int(n_bands),
        dtype=dtype, compression=int(compression), predictor=int(predictor),
        tiled=tiled,
        tile_shape=(
            (int(_tag1(tags, T_TILE_HEIGHT)), int(_tag1(tags, T_TILE_WIDTH)))
            if tiled else None
        ),
        geo=geo, tags=tags, byte_order=endian,
    )
    return info, endian, big


def _fp_predict_encode(tile: np.ndarray) -> bytes:
    """TIFF predictor 3 (floating-point horizontal differencing) encode.

    Per row, the float bytes are rearranged into byte-significance planes
    (MSB plane first) and then byte-wise horizontally differenced with a
    stride of the sample count — the libtiff ``fpDiff`` layout, so GDAL
    reads these files.  Splitting exponent and mantissa bytes into planes
    makes smooth float rasters compress several times better AND faster
    than raw bytes: the writer's dominant cost in the output path.
    """
    th, tw, nb = tile.shape
    b = tile.astype("<f4", copy=False).view(np.uint8).reshape(th, tw * nb, 4)
    planes = np.transpose(b[:, :, ::-1], (0, 2, 1))  # (th, 4, tw*nb), MSB 1st
    buf = np.ascontiguousarray(planes).reshape(th, 4 * tw * nb)
    out = buf.copy()
    out[:, nb:] -= buf[:, :-nb]  # uint8 arithmetic wraps mod 256
    return out.tobytes()


def _fp_predict_decode(raw: bytes, rows: int, cols: int, nb: int,
                       ) -> np.ndarray:
    """Inverse of :func:`_fp_predict_encode` (libtiff ``fpAcc``)."""
    buf = np.frombuffer(raw, np.uint8).reshape(rows, 4 * cols * nb).copy()
    acc = np.add.accumulate(
        buf.reshape(rows, 4 * cols, nb), axis=1, dtype=np.uint8
    ).reshape(rows, 4, cols * nb)
    b = np.transpose(acc, (0, 2, 1))[:, :, ::-1]  # back to LE byte order
    return (
        np.ascontiguousarray(b)
        .view("<f4")
        .reshape(rows, cols, nb)
        .astype(np.float32)
    )


def _decode_segments(segments, info, seg_shape):
    """Decompress + de-predict a list of raw byte segments into arrays of
    ``seg_shape`` (rows, cols, bands).  Empty segments (sparse-file tiles,
    offset/bytecount 0) decode to zeros."""
    rows, cols = seg_shape
    itemsize = info.dtype.itemsize
    expected = rows * cols * info.n_bands * itemsize
    if (
        info.predictor == 3 and itemsize == 4
        and info.compression in (1, 8, 32946)
    ):
        # Fused native chain: inflate + fpAcc + byte unshuffle in one
        # parallel C++ pass over all tiles (the per-tile numpy
        # accumulate/transpose below is the decode hot path at
        # tile-year scale).  The byte-plane layout is endian-neutral,
        # matching the numpy path exactly.
        decoded = native_codec.decode_fp3_many(
            segments, rows, cols, info.n_bands,
            compressed=info.compression != 1,
        )
        if decoded is not None:
            return [
                decoded[i].astype(info.dtype, copy=False)
                for i in range(len(segments))
            ]
    present = [(i, s) for i, s in enumerate(segments) if len(s)]
    if info.compression in (8, 32946):
        raw_present = native_codec.inflate_many(
            [s for _, s in present], expected
        )
    elif info.compression == 1:
        raw_present = [bytes(s) for _, s in present]
    elif info.compression == 5:
        raw_present = None
        try:
            raw_present = native_codec.lzw_inflate_many(
                [s for _, s in present], expected
            )
        except ValueError:
            # The native decoder hard-caps its output at expected+16;
            # a stream with trailing post-EOI bytes (foreign encoders)
            # can exceed it.  The Python reference decoder tolerates
            # and truncates — fall through to it rather than failing
            # the whole read.
            raw_present = None
        if raw_present is None:
            raw_present = [_lzw_decode(bytes(s)) for _, s in present]
    else:
        raise NotImplementedError(
            "TIFF compression %d not supported" % info.compression
        )
    raw = [b""] * len(segments)
    for (i, _), r in zip(present, raw_present):
        raw[i] = r
    # Decode with the FILE's byte order, then return native-endian arrays.
    file_dtype = info.dtype.newbyteorder(info.byte_order)
    out = []
    for r in raw:
        padded = r[:expected].ljust(expected, b"\x00")
        if info.predictor == 3:
            if itemsize != 4:
                raise NotImplementedError(
                    "TIFF predictor 3 is supported for 32-bit floats "
                    f"only (file has {itemsize * 8}-bit samples)"
                )
            out.append(
                _fp_predict_decode(padded, rows, cols, info.n_bands)
                .astype(info.dtype)
            )
            continue
        arr = np.frombuffer(padded, dtype=file_dtype)
        arr = arr.reshape(rows, cols, info.n_bands).astype(info.dtype)
        if info.predictor == 2:
            np.cumsum(arr, axis=1, out=arr, dtype=arr.dtype)
        out.append(arr)
    return out


def lzw_encode(data: bytes) -> bytes:
    """TIFF LZW encode (MSB-first, early-change) — the inverse of
    ``_lzw_decode``, used to build LZW fixtures without GDAL.  The
    encoder's width switch runs one append later than the decoder's
    (``next_code >= 1 << nbits``): the decoder's table lags the
    encoder's by exactly one entry."""
    out = bytearray()
    bitbuf = bitcnt = 0
    nbits = 9

    def put(code):
        nonlocal bitbuf, bitcnt
        bitbuf = (bitbuf << nbits) | code
        bitcnt += nbits
        while bitcnt >= 8:
            out.append((bitbuf >> (bitcnt - 8)) & 0xFF)
            bitcnt -= 8

    table = {bytes([i]): i for i in range(256)}
    next_code = 258
    put(256)
    w = b""
    for ch in data:
        wc = w + bytes([ch])
        if wc in table:
            w = wc
            continue
        put(table[w])
        table[wc] = next_code
        next_code += 1
        if next_code >= 4094:
            put(256)
            table = {bytes([i]): i for i in range(256)}
            next_code = 258
            nbits = 9
        elif next_code >= (1 << nbits) and nbits < 12:
            nbits += 1
        w = bytes([ch])
    if w:
        put(table[w])
        # The decoder appends its (lagged) table entry upon receiving
        # this final code, closing the one-entry lag — so the EOI must
        # be written at the width the decoder will READ it with
        # (libtiff's LZWPostEncode does the same final bump).  Without
        # this, streams whose final code lands the decoder's table
        # exactly on a width boundary (511/1023/2047) decode with
        # trailing garbage.
        if next_code >= (1 << nbits) - 1 and nbits < 12:
            nbits += 1
    put(257)
    if bitcnt:
        out.append((bitbuf << (8 - bitcnt)) & 0xFF)
    return bytes(out)


def _lzw_decode(data: bytes) -> bytes:
    """TIFF LZW (MSB-first, early-change) — needed for fixtures written by
    GDAL's default creation options."""
    CLEAR, EOI = 256, 257
    out = bytearray()
    table: List[bytes] = []

    def reset():
        nonlocal table
        table = [bytes([i]) for i in range(256)] + [b"", b""]

    reset()
    bitpos = 0
    nbits = 9
    prev = b""
    total_bits = len(data) * 8
    while bitpos + nbits <= total_bits:
        byte_idx = bitpos >> 3
        chunk = int.from_bytes(
            data[byte_idx:byte_idx + 4].ljust(4, b"\x00"), "big"
        )
        code = (chunk >> (32 - nbits - (bitpos & 7))) & ((1 << nbits) - 1)
        bitpos += nbits
        if code == EOI:
            break
        if code == CLEAR:
            reset()
            nbits = 9
            prev = b""
            continue
        if prev == b"":
            entry = table[code]
        elif code < len(table):
            entry = table[code]
            table.append(prev + entry[:1])
        else:
            entry = prev + prev[:1]
            table.append(entry)
        out += entry
        prev = entry
        if len(table) >= (1 << nbits) - 1 and nbits < 12:
            nbits += 1
    return bytes(out)


def read_geotiff(path: str) -> Tuple[np.ndarray, TiffInfo]:
    """Read a whole GeoTIFF.  Returns ``(array, info)`` with array shaped
    (height, width) single-band or (height, width, bands)."""
    faults.fault_point("io.read_band", path=path)
    with open(path, "rb") as f:
        info, _, _ = _parse_info_f(f)
        arr = _read_window_f(f, info, 0, 0, info.height, info.width)
    return arr, info


def read_geotiff_window(path: str, row0: int, col0: int, nrows: int,
                        ncols: int, info: Optional[TiffInfo] = None,
                        ) -> Tuple[np.ndarray, TiffInfo]:
    """Read only the pixels of a window — decodes just the tiles/strips it
    intersects, so reading a 256x256 chunk of a 10980x10980 BigTIFF costs
    window-sized I/O instead of a whole-file decode (the streaming-read
    half of the reference's ``gdal.Translate(srcWin=...)`` /
    ``gdal.Warp`` usage, ``kafka_test_S2.py:155-158``).

    The window may extend past the raster edge; out-of-raster pixels come
    back zero-filled.  Pass a previously obtained ``info`` (``read_info``)
    to skip re-parsing the header/IFD on repeated windows of one file.
    Returns ``(array, info)`` with array shaped ``(nrows, ncols[, bands])``."""
    faults.fault_point("io.read_band", path=path)
    with open(path, "rb") as f:
        if info is None:
            info, _, _ = _parse_info_f(f)
        arr = _read_window_f(f, info, row0, col0, nrows, ncols)
    return arr, info


def _read_window_f(f, info: TiffInfo, row0: int, col0: int, nrows: int,
                   ncols: int) -> np.ndarray:
    tags = info.tags
    h, w, nb = info.height, info.width, info.n_bands
    out = np.zeros((nrows, ncols, nb), info.dtype)

    def read_seg(off, cnt):
        if cnt == 0 or off == 0:
            return b""
        f.seek(off)
        return f.read(cnt)

    if info.tiled:
        th, tw = info.tile_shape
        offsets = tags[T_TILE_OFFSETS]
        counts = tags[T_TILE_BYTECOUNTS]
        tiles_across = (w + tw - 1) // tw
        tiles_down = (h + th - 1) // th
        ty0 = max(0, row0 // th)
        ty1 = min(tiles_down, (row0 + nrows + th - 1) // th)
        tx0 = max(0, col0 // tw)
        tx1 = min(tiles_across, (col0 + ncols + tw - 1) // tw)
        wanted = [
            ty * tiles_across + tx
            for ty in range(ty0, ty1) for tx in range(tx0, tx1)
        ]
        segs = [read_seg(offsets[i], counts[i]) for i in wanted]
        arrays = _decode_segments(segs, info, (th, tw))
        for idx, arr in zip(wanted, arrays):
            ty, tx = divmod(idx, tiles_across)
            y0, x0 = ty * th, tx * tw
            # overlap of this tile with the window, in window coords
            oy0 = max(y0, row0)
            oy1 = min(y0 + th, row0 + nrows, h)
            ox0 = max(x0, col0)
            ox1 = min(x0 + tw, col0 + ncols, w)
            if oy1 <= oy0 or ox1 <= ox0:
                continue
            out[oy0 - row0:oy1 - row0, ox0 - col0:ox1 - col0] = (
                arr[oy0 - y0:oy1 - y0, ox0 - x0:ox1 - x0]
            )
    else:
        rps = int(_tag1(tags, T_ROWS_PER_STRIP, h))
        offsets = tags[T_STRIP_OFFSETS]
        counts = tags.get(T_STRIP_BYTECOUNTS, (None,) * len(offsets))
        s0 = max(0, row0 // rps)
        s1 = min(len(offsets), (row0 + nrows + rps - 1) // rps)
        for si in range(s0, s1):
            o = offsets[si]
            c = counts[si]
            if c is None:
                f.seek(0, 2)
                c = f.tell() - o
            y0 = si * rps
            rows = min(rps, h - y0)
            if rows <= 0:
                continue
            arr = _decode_segments([read_seg(o, c)], info, (rows, w))[0]
            oy0 = max(y0, row0)
            oy1 = min(y0 + rows, row0 + nrows)
            ox0 = max(col0, 0)
            ox1 = min(w, col0 + ncols)
            if oy1 <= oy0 or ox1 <= ox0:
                continue
            out[oy0 - row0:oy1 - row0, ox0 - col0:ox1 - col0] = (
                arr[oy0 - y0:oy1 - y0, ox0:ox1]
            )
    if nb == 1:
        out = out[:, :, 0]
    return out


# --- writing --------------------------------------------------------------


def _geo_tags(geo: GeoInfo):
    ox, sx, _, oy, _, nsy = geo.geotransform
    tags = [
        (T_PIXEL_SCALE, 12, (float(sx), float(abs(nsy)), 0.0)),
        (T_TIEPOINT, 12, (0.0, 0.0, 0.0, float(ox), float(oy), 0.0)),
    ]
    keys = [1, 1, 0, 0]  # version, rev, minor, n_keys (patched below)
    n_keys = 0
    # Geographic CRS codes (EPSG 4000-4999, e.g. 4326/WGS84) get
    # ModelTypeGeographic + GeographicTypeGeoKey; everything else is
    # treated as projected (ProjectedCSTypeGeoKey).
    geographic = geo.epsg is not None and 4000 <= geo.epsg < 5000
    keys += [1024, 0, 1, 2 if geographic else 1]
    n_keys += 1
    keys += [1025, 0, 1, 1]  # RasterPixelIsArea
    n_keys += 1
    if geo.epsg is not None:
        keys += [2048 if geographic else 3072, 0, 1, int(geo.epsg)]
        n_keys += 1
    ascii_blob = b""
    if geo.projection:
        text = geo.projection.encode("ascii", "replace") + b"|"
        keys += [1026, T_GEO_ASCII, len(text), 0]
        n_keys += 1
        ascii_blob = text
    keys[3] = n_keys
    tags.append((T_GEO_KEYS, 3, tuple(keys)))
    if ascii_blob:
        tags.append((T_GEO_ASCII, 2, ascii_blob + b"\x00"))
    if geo.nodata is not None:
        tags.append(
            (T_GDAL_NODATA, 2, (repr(float(geo.nodata)).encode() + b"\x00"))
        )
    return tags


_DTYPE_TO_TAGS = {
    np.dtype(np.uint8): (8, 1), np.dtype(np.uint16): (16, 1),
    np.dtype(np.uint32): (32, 1), np.dtype(np.int16): (16, 2),
    np.dtype(np.int32): (32, 2), np.dtype(np.float32): (32, 3),
    np.dtype(np.float64): (64, 3),
}


class TiledTiffWriter:
    """Streaming tiled GeoTIFF writer.

    Tiles are compressed and appended to the file as they are produced —
    nothing accumulates in memory — and the IFD is written at end-of-file
    on :meth:`close` (the libtiff append layout: the header's IFD pointer
    is patched last, so a crashed write is detectable as a zero pointer).
    This is what makes multi-GB BigTIFF tile-year outputs writable from a
    host that is simultaneously holding the assimilation state.

    Tiles may be written in any order; unwritten tiles become sparse
    (offset/bytecount 0, reading as zeros — GDAL's sparse-file convention).
    """

    def __init__(
        self,
        path: str,
        height: int,
        width: int,
        n_bands: int = 1,
        dtype=np.float32,
        geo: Optional[GeoInfo] = None,
        tile_size: int = 256,
        compress="deflate",  # True|"deflate" (fast, native) | "lzw" (interop) | False
        level: int = 6,
        predictor: int = 1,
        bigtiff: Optional[bool] = None,
    ):
        self.h, self.w, self.nb = int(height), int(width), int(n_bands)
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_TO_TAGS:
            raise ValueError(f"unsupported sample dtype {self.dtype}")
        if predictor == 2 and self.dtype.kind == "f":
            # TIFF predictor 2 is integer-only (floats use predictor 3); a
            # float-diff file would be unreadable by libtiff/GDAL.
            raise ValueError(
                "predictor=2 requires an integer dtype; floats must use "
                "predictor 1 or 3 (got %s)" % self.dtype
            )
        if predictor == 3 and self.dtype != np.dtype(np.float32):
            raise ValueError(
                "predictor=3 (floating-point differencing) is implemented "
                "for float32 samples only (got %s)" % self.dtype
            )
        self.geo = geo or GeoInfo()
        self.ts = int(tile_size)
        # compress: True/"deflate" (the reference's KafkaOutput choice),
        # "lzw" (GDAL's default creation option — native pool-parallel
        # encoder when built, serial Python fallback otherwise), or
        # False.
        if compress == "lzw":
            self.codec = "lzw"
        elif compress in (True, "deflate"):
            self.codec = "deflate"
        elif not compress:
            self.codec = None
        else:
            raise ValueError(f"compress={compress!r}")
        self.level = int(level)
        self.predictor = int(predictor)
        self.tiles_down = (self.h + self.ts - 1) // self.ts
        self.tiles_across = (self.w + self.ts - 1) // self.ts
        n_tiles = self.tiles_down * self.tiles_across
        raw_size = self.h * self.w * self.nb * self.dtype.itemsize
        if bigtiff is None:
            bigtiff = raw_size > 3_500_000_000
        self.big = bool(bigtiff)
        self._offsets = [0] * n_tiles
        self._counts = [0] * n_tiles
        self._f = open(path, "wb")
        # Header with a zero IFD pointer (patched on close).
        if self.big:
            self._f.write(struct.pack("<2sHHHQ", b"II", 43, 8, 0, 0))
        else:
            self._f.write(struct.pack("<2sHI", b"II", 42, 0))
        self._pos = self._f.tell()
        self._closed = False

    def _pad_tile(self, tile: np.ndarray) -> np.ndarray:
        """Pad a (possibly clipped edge) tile to the full tile grid."""
        arr = np.asarray(tile)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        full = np.zeros((self.ts, self.ts, self.nb), self.dtype)
        full[:arr.shape[0], :arr.shape[1]] = arr.astype(self.dtype)
        return full

    def _prep_tile(self, tile: np.ndarray) -> bytes:
        """Pad to the tile grid + apply the predictor; returns raw bytes."""
        full = self._pad_tile(tile)
        if self.predictor == 3:
            return _fp_predict_encode(full)
        if self.predictor == 2:
            full = np.diff(
                np.concatenate(
                    [np.zeros((self.ts, 1, self.nb), self.dtype), full],
                    axis=1,
                ),
                axis=1,
            ).astype(self.dtype)
        return full.tobytes()

    def _append_segment(self, idx: int, seg: bytes) -> None:
        if not self.big and self._pos + len(seg) > 0xFFFFFFFF:
            raise ValueError(
                "classic TIFF offset overflow — pass bigtiff=True"
            )
        self._offsets[idx] = self._pos
        self._counts[idx] = len(seg)
        self._f.seek(self._pos)
        self._f.write(seg)
        self._pos += len(seg)

    def write_tile(self, ty: int, tx: int, tile: np.ndarray) -> None:
        """Write one tile (row ``ty``, col ``tx``).  ``tile`` may be the
        full ``tile_size`` square or the clipped edge shape; it is
        zero-padded to the tile grid."""
        if not (0 <= ty < self.tiles_down and 0 <= tx < self.tiles_across):
            raise IndexError(f"tile ({ty}, {tx}) outside grid")
        seg = self._prep_tile(tile)
        if self.codec == "lzw":
            native = native_codec.lzw_deflate_many([seg])
            seg = native[0] if native is not None else lzw_encode(seg)
        elif self.codec == "deflate":
            seg = native_codec.deflate_many([seg], self.level)[0]
        self._append_segment(ty * self.tiles_across + tx, seg)

    def write_rows(self, row0: int, rows: np.ndarray) -> None:
        """Write a horizontal band of complete tile rows starting at pixel
        row ``row0`` (must be tile-aligned and a multiple of ``tile_size``
        tall, except the last band).  All tiles of the band go through ONE
        batched deflate call so the native codec's thread pool gets the
        whole row at once."""
        if row0 % self.ts:
            raise ValueError("row0 must be tile-aligned")
        ty0 = row0 // self.ts
        arr = np.asarray(rows)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        indices, tiles = [], []
        for dy in range(0, arr.shape[0], self.ts):
            for tx in range(self.tiles_across):
                x0 = tx * self.ts
                indices.append((ty0 + dy // self.ts) * self.tiles_across + tx)
                tiles.append(arr[dy:dy + self.ts, x0:x0 + self.ts])
        if not tiles:
            return
        segs = None
        if self.codec == "deflate" and self.predictor == 3 \
                and native_codec.has_fp3():
            # Fused native chain: fpDiff + deflate in one parallel C++
            # pass over the whole tile band.  Capability is probed BEFORE
            # building the padded stack so fallback systems don't pay for
            # an allocation the native call would just discard.
            stacked = np.stack([
                self._pad_tile(t).astype(np.float32, copy=False)
                for t in tiles
            ])
            segs = native_codec.encode_fp3_many(stacked, self.level)
        if segs is None:
            raws = [self._prep_tile(t) for t in tiles]
            if self.codec == "lzw":
                segs = native_codec.lzw_deflate_many(raws)
                if segs is None:
                    segs = [lzw_encode(r) for r in raws]
            elif self.codec == "deflate":
                segs = native_codec.deflate_many(raws, self.level)
            else:
                segs = raws
        for idx, seg in zip(indices, segs):
            self._append_segment(idx, seg)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        bits, fmt = _DTYPE_TO_TAGS[self.dtype]
        off_type = 16 if self.big else 4  # LONG8 vs LONG
        entries = [
            (T_WIDTH, 3, (self.w,)), (T_HEIGHT, 3, (self.h,)),
            (T_BITS, 3, (bits,) * self.nb),
            (T_COMPRESSION, 3,
             ({"deflate": 8, "lzw": 5, None: 1}[self.codec],)),
            (T_PHOTOMETRIC, 3, (1,)),
            (T_SAMPLES_PER_PIXEL, 3, (self.nb,)),
            (T_PLANAR, 3, (1,)),
            (T_PREDICTOR, 3, (self.predictor,)),
            (T_TILE_WIDTH, 3, (self.ts,)), (T_TILE_HEIGHT, 3, (self.ts,)),
            (T_SAMPLE_FORMAT, 3, (fmt,) * self.nb),
            (T_TILE_OFFSETS, off_type, tuple(self._offsets)),
            (T_TILE_BYTECOUNTS, off_type, tuple(self._counts)),
        ]
        entries += _geo_tags(self.geo)
        entries.sort(key=lambda e: e[0])
        endian = "<"
        inline_max = 8 if self.big else 4
        ifd_entry = 20 if self.big else 12

        def value_bytes(typ, values):
            if typ == 2 or typ == 7:
                return bytes(values)
            fmt_ch = {3: "H", 4: "I", 12: "d", 16: "Q"}[typ]
            return struct.pack(endian + fmt_ch * len(values), *values)

        ifd_start = (self._pos + 1) & ~1
        n = len(entries)
        ifd_size = (8 if self.big else 2) + n * ifd_entry + \
            (8 if self.big else 4)
        ov_pos = ifd_start + ifd_size
        if not self.big and ov_pos > 0xFFFFFFFF:
            raise ValueError(
                "classic TIFF offset overflow — pass bigtiff=True"
            )
        f = self._f
        f.seek(ifd_start)
        f.write(struct.pack(endian + ("Q" if self.big else "H"), n))
        ov_chunks = []
        for tag, typ, values in entries:
            raw = value_bytes(typ, values)
            f.write(struct.pack(endian + "HH", tag, typ))
            f.write(struct.pack(endian + ("Q" if self.big else "I"),
                                len(values)))
            if len(raw) <= inline_max:
                f.write(raw.ljust(inline_max, b"\x00"))
            else:
                f.write(struct.pack(endian + ("Q" if self.big else "I"),
                                    ov_pos))
                ov_chunks.append((ov_pos, raw))
                ov_pos += (len(raw) + 1) & ~1
        f.write(struct.pack(endian + ("Q" if self.big else "I"), 0))
        for pos_, raw in ov_chunks:
            f.seek(pos_)
            f.write(raw)
        # Patch the header's IFD pointer last: a file with a zero pointer
        # is an unfinished write.
        f.seek(8 if self.big else 4)
        f.write(struct.pack(endian + ("Q" if self.big else "I"), ifd_start))
        f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_geotiff(
    path: str,
    array: np.ndarray,
    geo: Optional[GeoInfo] = None,
    tile_size: int = 256,
    compress="deflate",  # True|"deflate" (fast, native) | "lzw" (interop) | False
    level: int = 6,
    predictor: int = 1,
    bigtiff: Optional[bool] = None,
) -> None:
    """Write a single/multi-band GeoTIFF: tiled, DEFLATE by default — the
    writer-side contract of the reference's ``KafkaOutput``
    (``observations.py:360-365``: COMPRESS=DEFLATE, TILED=YES, PREDICTOR=1,
    BIGTIFF=YES; BigTIFF here switches on automatically past 3.5 GB or can
    be forced).  ``compress="lzw"`` writes GDAL's default creation option
    instead (native pool-parallel encoder when built; Python fallback
    is serial — fine for masks/fixtures).  Rasters up to 64 MB raw
    encode as ONE pool batch (peak memory ~ one padded + one compressed
    copy of the raster); larger rasters stream through
    :class:`TiledTiffWriter` tile-row by tile-row, bounding peak memory
    at one row of compressed tiles."""
    arr = np.asarray(array)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype not in _DTYPE_TO_TAGS:
        arr = arr.astype(np.float32)
    h, w, nb = arr.shape
    # Hand the codec pool as many tiles per call as memory sensibly
    # allows: per-tile-row batches of a ~1000-px-wide raster are only
    # 4-5 tiles, starving a wide native pool.  Up to ~64 MB raw, encode
    # the WHOLE raster in one batch (peak memory = one compressed copy);
    # larger rasters stream per tile row as before.
    raw_bytes = h * w * nb * arr.dtype.itemsize
    step = (h or tile_size) if raw_bytes <= (64 << 20) else tile_size
    with TiledTiffWriter(
        path, h, w, n_bands=nb, dtype=arr.dtype, geo=geo,
        tile_size=tile_size, compress=compress, level=level,
        predictor=predictor, bigtiff=bigtiff,
    ) as writer:
        for y0 in range(0, h, step):
            writer.write_rows(y0, arr[y0:y0 + step])
