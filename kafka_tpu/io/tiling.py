"""Spatial chunking — the unit of distributed parallelism.

``get_chunks`` reproduces the reference's block tiler exactly
(``/root/reference/kafka/input_output/utils.py:12-40``): column-major
blocks, 1-based chunk numbering, trailing blocks shrunk to fit.  Chunks are
the reference's only sharding axis (SURVEY.md §2.3); in this framework they
feed the multi-host tile scheduler (``kafka_tpu.shard``) while pixels within
a chunk shard over the device mesh.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Tuple


class Chunk(NamedTuple):
    x0: int
    y0: int
    nx_valid: int
    ny_valid: int
    chunk_no: int


def get_chunks(nx: int, ny: int,
               block_size: Tuple[int, int] = (256, 256)) -> Iterator[Chunk]:
    bx, by = block_size
    nx_blocks = (nx + bx - 1) // bx
    ny_blocks = (ny + by - 1) // by
    chunk_no = 0
    for ix in range(nx_blocks):
        nx_valid = bx if ix < nx_blocks - 1 else nx - ix * bx
        for iy in range(ny_blocks):
            ny_valid = by if iy < ny_blocks - 1 else ny - iy * by
            chunk_no += 1
            yield Chunk(ix * bx, iy * by, nx_valid, ny_valid, chunk_no)


def chunk_mask(state_mask, chunk: Chunk):
    """Slice a chunk's window out of the full state mask (the VRT-submask
    trick of the S2 driver, ``kafka_test_S2.py:152-158``)."""
    return state_mask[
        chunk.y0:chunk.y0 + chunk.ny_valid,
        chunk.x0:chunk.x0 + chunk.nx_valid,
    ]


def chunk_geotransform(geotransform, chunk: Chunk):
    """Shift a GDAL-style geotransform to a chunk's origin."""
    ox, sx, rx, oy, ry, sy = geotransform
    return (
        ox + chunk.x0 * sx + chunk.y0 * rx,
        sx, rx,
        oy + chunk.x0 * ry + chunk.y0 * sy,
        ry, sy,
    )
