"""Multi-sensor observation composition.

The reference assimilates one sensor per driver; combining optical and SAR
time series over one state is left undone (its SAR operator exists but no
driver wires it, ``/root/reference/kafka/observation_operators/
sar_forward_model.py``).  ``CompositeObservations`` merges any number of
``ObservationSource``s into one: the date list is the sorted union, and
each date dispatches to the source that owns it — the per-date
``DateObservation`` carries that sensor's own operator and aux, which the
engine already supports (one jitted program per operator, reused across
its dates).

Same-day acquisitions from different sensors are kept distinct by nudging
later sources' duplicate dates forward by one second per source index
(real S1/S2 acquisition timestamps differ anyway; the reference keys
observations by exact datetime too, ``linear_kf.py:225-227``).
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Sequence

from ..engine.protocols import DateObservation
from ..engine.state import PixelGather


class CompositeObservations:
    """One ObservationSource over several sensors."""

    def __init__(self, sources: Sequence[Any]):
        if not sources:
            raise ValueError("CompositeObservations needs >= 1 source")
        self.sources = list(sources)
        self._owner: Dict[datetime.datetime, Any] = {}
        self._source_date: Dict[datetime.datetime, datetime.datetime] = {}
        for si, src in enumerate(self.sources):
            for d in src.dates:
                key = d
                while key in self._owner:
                    key = key + datetime.timedelta(seconds=si + 1)
                self._owner[key] = src
                self._source_date[key] = d
        self.dates: List[datetime.datetime] = sorted(self._owner)
        self.bands_per_observation = {
            d: self._owner[d].bands_per_observation[self._source_date[d]]
            for d in self.dates
        }

    def define_output(self):
        """The first source defines the output grid (all sources must have
        been built against the same state grid)."""
        return self.sources[0].define_output()

    def get_observations(self, date, gather: PixelGather) -> DateObservation:
        return self._owner[date].get_observations(
            self._source_date[date], gather
        )
