"""kafka_tpu — a TPU-native raster data-assimilation framework.

A from-scratch JAX/XLA re-design of the capabilities of
QCDIS/KaFKA-InferenceEngine (per-pixel linearised Kalman/information
filtering of satellite raster time series): batched dense per-pixel solves on
the MXU instead of giant sparse CPU LU factorizations, `lax.while_loop`
relinearisation, mesh-sharded pixels, and a host-side streaming raster
pipeline.  See SURVEY.md for the structural map to the reference.
"""

__version__ = "0.1.0"

from . import core
from .core import (  # noqa: F401 — flat re-export API like the reference's kafka/__init__.py:1-4
    BandBatch,
    GaussianState,
    Linearization,
    PixelPrior,
    iterate_time_grid,
    tip_prior,
)
