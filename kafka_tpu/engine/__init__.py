"""Filter orchestration: the engine around the math core."""

from .checkpoint import Checkpointer
from .filter import KalmanFilter
from .prefetch import ObservationPrefetcher, planned_observation_dates
from .priors import (
    KERNEL_PARAMETER_LIST,
    PROSAIL_PARAMETER_LIST,
    TIP_PARAMETER_LIST,
    FixedGaussianPrior,
    jrc_prior,
    kernels_prior,
    sail_prior,
)
from .protocols import DateObservation, ObservationSource, OutputWriter, Prior
from .state import PixelGather, make_pixel_gather
