"""Filter orchestration: the engine around the math core."""

from .checkpoint import Checkpointer
from .filter import KalmanFilter
from .priors import (
    PROSAIL_PARAMETER_LIST,
    TIP_PARAMETER_LIST,
    FixedGaussianPrior,
    jrc_prior,
    sail_prior,
)
from .protocols import DateObservation, ObservationSource, OutputWriter, Prior
from .state import PixelGather, make_pixel_gather
