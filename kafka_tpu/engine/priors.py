"""Prior objects: batched equivalents of the drivers' prior classes.

The reference defines near-identical prior classes per driver — ``JRCPrior``
(``/root/reference/kafka_test.py:78-133``) and ``SAILPrior``
(``kafka_test_S2.py:77-118``) — each tiling a fixed per-pixel mean/inverse
covariance over the masked pixels with ``block_diag``.  Here that is one
``FixedGaussianPrior`` over any ``PixelPrior``; the published constants ship
as ready-made constructors.
"""

from __future__ import annotations

import datetime
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.propagators import PixelPrior, broadcast_prior, tip_prior
from .state import PixelGather

# The 10-parameter PROSAIL state of the S2 driver (kafka_test_S2.py:136-137).
PROSAIL_PARAMETER_LIST = (
    "n", "cab", "car", "cbrown", "cw", "cm", "lai", "ala", "bsoil", "psoil",
)

# The 7-parameter TIP state of the MODIS drivers (kafka_test.py:159-160).
TIP_PARAMETER_LIST = (
    "w_vis", "x_vis", "a_vis", "w_nir", "x_nir", "a_nir", "TeLAI",
)

def kernel_parameter_list(n_modis_bands: int) -> Tuple[str, ...]:
    """Kernel-weight parameter names: (iso, vol, geo) per MODIS band."""
    return tuple(
        f"b{b + 1}_{k}"
        for b in range(n_modis_bands)
        for k in ("iso", "vol", "geo")
    )


# The 21-parameter kernel-weight state of the MOD09 path.
KERNEL_PARAMETER_LIST = kernel_parameter_list(7)


class FixedGaussianPrior:
    """A time-invariant i.i.d.-per-pixel Gaussian prior."""

    #: safe to reuse one ``process_prior`` result across fused scan
    #: windows (engine temporal fusion)
    date_invariant = True

    def __init__(self, prior: PixelPrior,
                 parameter_list: Sequence[str]):
        self.prior = prior
        self.parameter_list = tuple(parameter_list)

    def process_prior(self, date: Optional[datetime.datetime],
                      gather: PixelGather) -> Tuple[jnp.ndarray, jnp.ndarray]:
        return broadcast_prior(self.prior, gather.n_pad)


def sail_prior() -> FixedGaussianPrior:
    """The S2/PROSAIL prior with the reference's transformed-space means and
    sigmas (``kafka_test_S2.py:84-92``): exponential transforms for the
    absorption/structure parameters, ``lai`` slot in TLAI space."""
    mean = np.array([
        2.1, np.exp(-60.0 / 100.0), np.exp(-7.0 / 100.0), 0.1,
        np.exp(-50 * 0.0176), np.exp(-100.0 * 0.002), np.exp(-4.0 / 2.0),
        70.0 / 90.0, 0.5, 0.9,
    ], np.float32)
    sigma = np.array(
        [0.01, 0.2, 0.01, 0.05, 0.01, 0.01, 0.50, 0.1, 0.1, 0.1], np.float32
    )
    cov = np.diag(sigma**2).astype(np.float32)
    inv_cov = np.diag(1.0 / sigma**2).astype(np.float32)
    prior = PixelPrior(
        mean=jnp.asarray(mean), cov=jnp.asarray(cov),
        inv_cov=jnp.asarray(inv_cov),
    )
    return FixedGaussianPrior(prior, PROSAIL_PARAMETER_LIST)


def kernels_prior(n_modis_bands: int = 7,
                  sigma: float = 0.2) -> FixedGaussianPrior:
    """A weak prior for the MOD09 kernel-weight state: plausible land-band
    magnitudes (moderate isotropic, smaller volumetric/geometric) with a
    broad diagonal covariance, so the retrieval is observation-driven the
    way the reference's MCD43-style inversion is."""
    mean = np.tile(
        np.array([0.15, 0.05, 0.02], np.float32), n_modis_bands
    )
    sig = np.full(3 * n_modis_bands, sigma, np.float32)
    prior = PixelPrior(
        mean=jnp.asarray(mean),
        cov=jnp.asarray(np.diag(sig**2), jnp.float32),
        inv_cov=jnp.asarray(np.diag(1.0 / sig**2), jnp.float32),
    )
    return FixedGaussianPrior(prior, kernel_parameter_list(n_modis_bands))


def jrc_prior() -> FixedGaussianPrior:
    """The MODIS/TIP prior (``kafka_test.py:110-125``; same constants as
    ``kf_tools.tip_prior`` but with mean LAI 2.0 in transformed space)."""
    base = tip_prior()
    mean = np.asarray(base.mean).copy()
    mean[6] = np.exp(-0.5 * 2.0)  # JRCPrior uses TLAI(2.0), kafka_test.py:113
    prior = PixelPrior(
        mean=jnp.asarray(mean), cov=base.cov, inv_cov=base.inv_cov
    )
    return FixedGaussianPrior(prior, TIP_PARAMETER_LIST)


# The 11-parameter joint optical+SAR state (obsops.joint).
JOINT_PARAMETER_LIST = PROSAIL_PARAMETER_LIST + ("sm",)


def joint_prior() -> FixedGaussianPrior:
    """Prior for the joint S2+S1 state: the SAIL prior extended with a
    broad volumetric soil-moisture marginal (mean 0.25 m^3/m^3, sigma
    0.15 — essentially uninformative over the WCM domain, so soil
    moisture is learned from the SAR signal)."""
    base = sail_prior().prior
    mean = np.concatenate(
        [np.asarray(base.mean), [0.25]]
    ).astype(np.float32)
    cov = np.zeros((11, 11), np.float32)
    cov[:10, :10] = np.asarray(base.cov)
    cov[10, 10] = 0.15**2
    inv_cov = np.zeros((11, 11), np.float32)
    inv_cov[:10, :10] = np.asarray(base.inv_cov)
    inv_cov[10, 10] = 1.0 / 0.15**2
    prior = PixelPrior(
        mean=jnp.asarray(mean), cov=jnp.asarray(cov),
        inv_cov=jnp.asarray(inv_cov),
    )
    return FixedGaussianPrior(prior, JOINT_PARAMETER_LIST)


# The 2-parameter WCM state of the SAR-only path (obsops.wcm).
WCM_PARAMETER_LIST = ("lai", "sm")


def wcm_prior() -> FixedGaussianPrior:
    """Prior for the SAR-only Water-Cloud state: broad LAI (mean 2, sigma
    2 over the (0, 10] domain) and soil moisture (mean 0.25, sigma 0.15
    over (0, 0.6]) — both essentially uninformative, so the retrieval is
    SAR-driven (the reference ships the WCM operator but no prior or
    driver for it, ``sar_forward_model.py``)."""
    mean = np.array([2.0, 0.25], np.float32)
    sigma = np.array([2.0, 0.15], np.float32)
    prior = PixelPrior(
        mean=jnp.asarray(mean),
        cov=jnp.asarray(np.diag(sigma**2), jnp.float32),
        inv_cov=jnp.asarray(np.diag(1.0 / sigma**2), jnp.float32),
    )
    return FixedGaussianPrior(prior, WCM_PARAMETER_LIST)
