"""Checkpoint / resume.

The reference has no resume mechanism — SURVEY.md §5 flags it as a cited
gap: every timestep dumps mean/sigma GeoTIFFs (``linear_kf.py:210-212``) and
keeps ``Previous_State`` in memory (``linear_kf.py:51-52,351-352``) but never
persists or reloads it.  This module closes the gap: the full analysis state
(mean + information matrix) is written per timestep as compressed ``.npz``,
and a run can resume from the latest (or any) checkpoint, which also gives
per-chunk restartability for the distributed scheduler (the reference's
cheap-rerun-by-chunk property, ``kafka_test_Py36.py:164-166``).
"""

from __future__ import annotations

import datetime
import os
import re
from typing import List, Optional, Tuple

import numpy as np

_FMT = "state_%Y%m%dT%H%M%S.npz"
_RX = re.compile(r"state_(\d{8}T\d{6})\.npz$")


class Checkpointer:
    def __init__(self, folder: str, prefix: str = ""):
        self.folder = folder
        self.prefix = prefix
        os.makedirs(folder, exist_ok=True)

    def _path(self, timestep: datetime.datetime) -> str:
        return os.path.join(
            self.folder, self.prefix + timestep.strftime(_FMT)
        )

    def save(self, timestep: datetime.datetime, x_analysis,
             p_analysis_inverse) -> str:
        path = self._path(timestep)
        np.savez_compressed(
            path,
            x_analysis=np.asarray(x_analysis),
            p_analysis_inverse=(
                np.zeros((0,)) if p_analysis_inverse is None
                else np.asarray(p_analysis_inverse)
            ),
        )
        return path

    def list_checkpoints(self) -> List[Tuple[datetime.datetime, str]]:
        out = []
        if not os.path.isdir(self.folder):
            return out
        for name in sorted(os.listdir(self.folder)):
            if not name.startswith(self.prefix):
                continue
            m = _RX.search(name)
            if m:
                ts = datetime.datetime.strptime(m.group(1), "%Y%m%dT%H%M%S")
                out.append((ts, os.path.join(self.folder, name)))
        return out

    def load_latest(self) -> Optional[Tuple[datetime.datetime, np.ndarray,
                                            Optional[np.ndarray]]]:
        """Returns (timestep, x_analysis, p_analysis_inverse) of the newest
        checkpoint, or None."""
        ckpts = self.list_checkpoints()
        if not ckpts:
            return None
        ts, path = ckpts[-1]
        data = np.load(path)
        p_inv = data["p_analysis_inverse"]
        return ts, data["x_analysis"], (None if p_inv.size == 0 else p_inv)

    def resume_time_grid(self, time_grid):
        """Trim a time grid to the steps strictly after the last checkpoint.

        The returned grid starts AT the checkpoint time and the seed state
        is an *analysis*: run the resumed filter with ``advance_first=True``
        so the propagation/prior blend into the first resumed window — which
        the original run performed — is not skipped."""
        latest = self.load_latest()
        if latest is None:
            return time_grid, None
        ts, x, p_inv = latest
        remaining = [t for t in time_grid if t > ts]
        return [ts] + remaining, (x, p_inv)
