"""Checkpoint / resume.

The reference has no resume mechanism — SURVEY.md §5 flags it as a cited
gap: every timestep dumps mean/sigma GeoTIFFs (``linear_kf.py:210-212``) and
keeps ``Previous_State`` in memory (``linear_kf.py:51-52,351-352``) but never
persists or reloads it.  This module closes the gap: the full analysis state
(mean + information matrix) is written per timestep and a run can resume
from the latest (or any) checkpoint, which also gives per-chunk
restartability for the distributed scheduler (the reference's
cheap-rerun-by-chunk property, ``kafka_test_Py36.py:164-166``).

Storage is scale-aware: the per-pixel information matrix is symmetric, so
only its lower triangle is persisted — ``p(p+1)/2`` instead of ``p**2``
floats per pixel (45% smaller at p=10 before compression) — and the pixel
axis can be split across ``n_shards`` independent ``.npz`` files so a
north-star-scale tile (10980**2 px) checkpoints as parallel-writable,
individually-rereadable pieces instead of one monolithic array.
"""

from __future__ import annotations

import datetime
import itertools
import logging
import os
import re
import zipfile
from typing import List, Optional, Tuple

import numpy as np

from ..resilience import faults
from ..telemetry import get_registry

LOG = logging.getLogger(__name__)

_FMT = "%Y%m%dT%H%M%S"
_RX = re.compile(r"state_(\d{8}T\d{6})(?:\.shard(\d+)of(\d+))?\.npz$")

#: what a truncated / empty / corrupted .npz raises out of ``np.load``
#: (zip CRC and header errors, short reads, missing keys).
_UNREADABLE_ERRORS = (
    OSError, EOFError, ValueError, KeyError, zipfile.BadZipFile,
)

#: per-process tmp-name counter: with the pid it makes every writer's tmp
#: unique, so two processes checkpointing into one folder (chunk workers,
#: queue-mode reruns of the same chunk) can never interleave open and
#: ``os.replace`` on a shared fixed-name tmp and commit a torn file.
_TMP_COUNTER = itertools.count()

#: forecast-sidecar schema version.  The sidecar rides INSIDE the same
#: per-shard .npz as the analysis (extra keys, never extra files, so the
#: shard-set completeness rules are unchanged).  Back-compat rule: a set
#: without the keys, or with a DIFFERENT schema number, simply has no
#: sidecar — readers fall back to re-deriving the forecast through the
#: propagator; they never fail the load.
SIDECAR_SCHEMA = 1


def pack_tril(a: np.ndarray) -> np.ndarray:
    """Symmetric ``(..., p, p)`` -> packed lower triangle ``(..., p(p+1)/2)``."""
    p = a.shape[-1]
    i, j = np.tril_indices(p)
    return np.ascontiguousarray(a[..., i, j])


def unpack_tril(packed: np.ndarray, p: int) -> np.ndarray:
    """Packed lower triangle -> full symmetric ``(..., p, p)``."""
    i, j = np.tril_indices(p)
    out = np.zeros(packed.shape[:-1] + (p, p), packed.dtype)
    out[..., i, j] = packed
    out[..., j, i] = packed
    return out


class Checkpointer:
    """Per-timestep state persistence.

    ``n_shards > 1`` splits the pixel axis into that many independent
    files per timestep (``state_<ts>.shard<k>of<n>.npz``); ``load_latest``
    only considers timesteps whose shard set is complete, so a crash
    mid-save resumes from the previous intact checkpoint.
    """

    def __init__(self, folder: str, prefix: str = "", n_shards: int = 1,
                 dtype=np.float32):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.folder = folder
        self.prefix = prefix
        self.n_shards = int(n_shards)
        self.dtype = np.dtype(dtype)
        os.makedirs(folder, exist_ok=True)

    def _path(self, timestep: datetime.datetime, shard: int) -> str:
        stamp = timestep.strftime(_FMT)
        name = (f"state_{stamp}.npz" if self.n_shards == 1
                else f"state_{stamp}.shard{shard}of{self.n_shards}.npz")
        return os.path.join(self.folder, self.prefix + name)

    def save(self, timestep: datetime.datetime, x_analysis,
             p_analysis_inverse, x_forecast=None,
             p_forecast_inverse=None) -> List[str]:
        """Persist one timestep's analysis (and, optionally, the forecast
        sidecar the RTS smoother consumes).

        ``x_forecast``/``p_forecast_inverse`` — when BOTH are given — are
        the window's pre-update forecast state, stored as extra keys in
        the same shard files (``SIDECAR_SCHEMA``).  The engine only
        passes them when the forecast was propagated from the PREVIOUS
        checkpointed analysis (per-window checkpointing), because that
        adjacency is exactly what the smoother gain assumes."""
        x = np.asarray(x_analysis, self.dtype)
        n_pix = x.shape[0] if x.ndim > 1 else x.size
        if p_analysis_inverse is None:
            tril = np.zeros((n_pix, 0), self.dtype)
            p = 0
        else:
            full = np.asarray(p_analysis_inverse)
            p = full.shape[-1]
            tril = pack_tril(full).astype(self.dtype, copy=False)
        sidecar = x_forecast is not None and p_forecast_inverse is not None
        if sidecar:
            xf = np.asarray(x_forecast, self.dtype)
            f_full = np.asarray(p_forecast_inverse)
            f_p = f_full.shape[-1]
            f_tril = pack_tril(f_full).astype(self.dtype, copy=False)
        paths = []
        bounds = np.linspace(0, n_pix, self.n_shards + 1).astype(int)
        for shard in range(self.n_shards):
            lo, hi = bounds[shard], bounds[shard + 1]
            path = self._path(timestep, shard)
            faults.fault_point("checkpoint.save", path=path)
            # Atomic write: a crash mid-save must never leave a
            # truncated .npz under the FINAL name (load_latest would
            # have treated it as the newest complete checkpoint).  The
            # tmp is written through a file handle so np.savez doesn't
            # append its own .npz suffix; its name is unique per writer
            # (pid + counter) so concurrent savers can't tear each
            # other's writes, and a crash-leaked tmp is removed by the
            # scheduler's startup sweep (``shard.sweep_stale_tmp``).
            tmp = f"{path}.tmp.{os.getpid()}.{next(_TMP_COUNTER)}"
            extra = {}
            if sidecar:
                extra = dict(
                    x_forecast=xf[lo:hi],
                    f_inv_tril=f_tril[lo:hi],
                    f_p=np.int64(f_p),
                    sidecar=np.int64(SIDECAR_SCHEMA),
                )
            with open(tmp, "wb") as f:
                np.savez_compressed(
                    f,
                    x_analysis=x[lo:hi],
                    p_inv_tril=tril[lo:hi],
                    p=np.int64(p),
                    **extra,
                )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            paths.append(path)
        return paths

    def _scan_sets(self) -> List[Tuple[datetime.datetime,
                                       Optional[List[str]], List[str]]]:
        """All checkpoint timesteps oldest first, complete or not:
        ``(ts, complete_paths | None, stray_paths)``.

        Shards are grouped by their ``of<total>`` declaration, so leftovers
        from a run with a different ``n_shards`` can never be mixed into a
        set (each file's shard count must agree).  If several totals have a
        complete set for one timestep (e.g. an old 2-shard and a finished
        3-shard save), the most recently written set wins.  ``stray_paths``
        are the files of that timestep's INCOMPLETE totals — evidence of a
        crash mid-save (or a concurrent save in flight) the loader must
        treat as corrupt, never as a resumable state."""
        by_ts: dict = {}
        if not os.path.isdir(self.folder):
            return []
        for name in sorted(os.listdir(self.folder)):
            if not name.startswith(self.prefix):
                continue
            m = _RX.search(name)
            if not m:
                continue
            ts = datetime.datetime.strptime(m.group(1), _FMT)
            shard = int(m.group(2)) if m.group(2) else 0
            total = int(m.group(3)) if m.group(3) else 1
            group = by_ts.setdefault(ts, {}).setdefault(total, {})
            group[shard] = os.path.join(self.folder, name)
        out = []
        for ts in sorted(by_ts):
            complete = []
            strays: List[str] = []
            for total, shards in by_ts[ts].items():
                if set(shards) == set(range(total)):
                    paths = [shards[k] for k in range(total)]
                    complete.append(
                        (max(os.path.getmtime(p) for p in paths), paths)
                    )
                else:
                    strays.extend(shards[k] for k in sorted(shards))
            out.append(
                (ts, max(complete)[1] if complete else None, strays)
            )
        return out

    def list_checkpoints(self) -> List[Tuple[datetime.datetime, List[str]]]:
        """Timesteps with a COMPLETE shard set, oldest first (see
        ``_scan_sets`` for the grouping rules)."""
        return [(ts, paths) for ts, paths, _ in self._scan_sets()
                if paths is not None]

    def load_latest(self, shard: Optional[int] = None,
                    ) -> Optional[Tuple[datetime.datetime, np.ndarray,
                                        Optional[np.ndarray]]]:
        """Returns (timestep, x_analysis, p_analysis_inverse) of the newest
        complete checkpoint, or None.

        ``shard`` restricts loading to that shard's pixel slice — the
        per-piece path for chunk-level restarts at scales where the
        assembled full matrix would not fit host RAM (the shards partition
        the pixel axis in order, ``np.linspace`` bounds as written)."""
        # Newest first; a corrupt set — an unreadable/truncated shard
        # (crash mid-save pre-dating the atomic writer, torn filesystem,
        # bit rot), a MISSING shard (crash between shard writes), or
        # shards whose shapes disagree — is skipped with a logged event
        # and the previous intact set wins: resuming slightly earlier
        # beats dying on a corrupt file.
        for ts, paths, strays in reversed(self._scan_sets()):
            if paths is None:
                self._note_unreadable(
                    ts, strays,
                    "incomplete shard set (missing shard files)",
                )
                continue
            use = [paths[shard]] if shard is not None else paths
            try:
                x, p_inv = self._load_set(use)
            except _UNREADABLE_ERRORS as exc:
                self._note_unreadable(ts, use, repr(exc)[:300])
                continue
            return ts, x, p_inv
        return None

    def _note_unreadable(self, ts, paths: List[str], error: str) -> None:
        LOG.warning(
            "checkpoint %s is unusable (%s); falling back to the "
            "previous intact checkpoint", ts, error,
        )
        get_registry().counter(
            "kafka_checkpoint_unreadable_total",
            "checkpoint sets skipped by load_latest because a file was "
            "truncated/corrupt or a shard was missing",
        ).inc()
        get_registry().emit(
            "checkpoint_unreadable", timestep=str(ts),
            paths=[os.path.basename(q) for q in paths],
            error=error,
        )

    @staticmethod
    def _load_set(paths: List[str], with_sidecar: bool = False):
        xs, trils, p = [], [], 0
        fxs, ftrils, f_p = [], [], 0
        have_sidecar = True
        for path in paths:
            data = np.load(path)
            xs.append(data["x_analysis"])
            if "p_inv_tril" in data:
                trils.append(data["p_inv_tril"])
                p = int(data["p"])
            else:  # round-1 full-matrix layout
                full = data["p_analysis_inverse"]
                if full.size:
                    p = full.shape[-1]
                    trils.append(pack_tril(full))
            # Forecast sidecar: EVERY shard must carry it under the one
            # schema this reader knows, else the set has no sidecar
            # (pre-sidecar sets and future schemas both degrade to the
            # propagator fallback, never to a load failure).
            if "sidecar" in data and int(data["sidecar"]) == SIDECAR_SCHEMA:
                fxs.append(data["x_forecast"])
                ftrils.append(data["f_inv_tril"])
                f_p = int(data["f_p"])
            else:
                have_sidecar = False
        # Cross-shard consistency: shards written by different runs (or a
        # torn rewrite under a different state layout) must read as
        # corrupt, not silently concatenate into a wrong-shaped state.
        if len({a.shape[-1] for a in xs if a.ndim > 1}) > 1 or \
                len({t.shape[-1] for t in trils}) > 1:
            raise ValueError(
                "checkpoint shards disagree on the state/information "
                f"width: {[a.shape for a in xs]} / "
                f"{[t.shape for t in trils]}"
            )
        x = np.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
        if p == 0:
            p_inv = None
        else:
            tril = (np.concatenate(trils, axis=0) if len(trils) > 1
                    else trils[0])
            p_inv = unpack_tril(tril.astype(np.float32), p)
        if not with_sidecar:
            return x, p_inv
        sidecar = None
        if have_sidecar and fxs and f_p > 0:
            if len({t.shape[-1] for t in ftrils}) > 1:
                raise ValueError(
                    "checkpoint shards disagree on the forecast-sidecar "
                    f"width: {[t.shape for t in ftrils]}"
                )
            xf = np.concatenate(fxs, axis=0) if len(fxs) > 1 else fxs[0]
            ftril = (np.concatenate(ftrils, axis=0) if len(ftrils) > 1
                     else ftrils[0])
            sidecar = (xf, unpack_tril(ftril.astype(np.float32), f_p))
        return x, p_inv, sidecar

    def resume_time_grid(self, time_grid):
        """Trim a time grid to the steps strictly after the last checkpoint.

        The returned grid starts AT the checkpoint time and the seed state
        is an *analysis*: run the resumed filter with ``advance_first=True``
        so the propagation/prior blend into the first resumed window — which
        the original run performed — is not skipped."""
        latest = self.load_latest()
        if latest is None:
            return time_grid, None
        ts, x, p_inv = latest
        remaining = [t for t in time_grid if t > ts]
        return [ts] + remaining, (x, p_inv)
