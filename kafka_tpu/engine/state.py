"""State-mask pixel gathering: rasters <-> fixed-shape pixel batches.

The reference carries boolean state masks through every layer and builds
variable-size vectors from ``mask[state_mask]`` selections (e.g.
``/root/reference/kafka/inference/utils.py:155-167``).  Variable sizes are
hostile to XLA; here the mask is resolved ONCE into a gather index list,
padded to a fixed, TPU-friendly pixel count (lane-aligned multiples), and
every raster is gathered into that layout on the host before device upload.
Padding pixels carry ``r_inv = 0`` observations and an identity-information
prior, so they ride along in the batched solves at full speed and are simply
never scattered back.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class PixelGather:
    """Precomputed mapping between a 2-D state mask and the padded flat
    pixel batch."""

    mask: np.ndarray          # (ny, nx) bool
    rows: np.ndarray          # (n_valid,) row index of each valid pixel
    cols: np.ndarray          # (n_valid,)
    n_valid: int
    n_pad: int                # padded batch size (>= n_valid)

    @property
    def valid(self) -> np.ndarray:
        """(n_pad,) bool — True for real pixels, False for padding."""
        out = np.zeros(self.n_pad, bool)
        out[: self.n_valid] = True
        return out

    def gather(self, raster: np.ndarray, fill: float = 0.0) -> np.ndarray:
        """(ny, nx [, ...]) raster -> (n_pad [, ...]) pixel batch."""
        vals = np.asarray(raster)[self.rows, self.cols]
        pad_shape = (self.n_pad,) + vals.shape[1:]
        out = np.full(pad_shape, fill, dtype=vals.dtype)
        out[: self.n_valid] = vals
        return out

    def scatter(self, pixel_values: np.ndarray,
                fill: float = 0.0) -> np.ndarray:
        """(n_pad [, ...]) batch -> (ny, nx [, ...]) raster, padding
        dropped, unmasked pixels set to ``fill`` (the reference writes 0
        outside the mask, ``observations.py:375-377``)."""
        pixel_values = np.asarray(pixel_values)
        out_shape = self.mask.shape + pixel_values.shape[1:]
        out = np.full(out_shape, fill, dtype=pixel_values.dtype)
        out[self.rows, self.cols] = pixel_values[: self.n_valid]
        return out


def make_pixel_gather(state_mask: np.ndarray,
                      pad_multiple: int = 256) -> PixelGather:
    """Build the gather for a boolean state mask.  ``pad_multiple`` keeps the
    pixel axis aligned to TPU lanes (128) with headroom for even sharding
    over 8-device meshes (hence 256 default; shards stay 128-aligned)."""
    mask = np.asarray(state_mask).astype(bool)
    rows, cols = np.nonzero(mask)
    n_valid = int(rows.size)
    n_pad = max(int(np.ceil(max(n_valid, 1) / pad_multiple)) * pad_multiple,
                pad_multiple)
    return PixelGather(
        mask=mask, rows=rows, cols=cols, n_valid=n_valid, n_pad=n_pad
    )
