"""The filter orchestrator — the TPU-native ``LinearKalman``.

Drives the time loop of ``LinearKalman.run``
(``/root/reference/kafka/linear_kf.py:171-212``): iterate the temporal grid,
advance the state between steps, assimilate every acquisition in the window
(all bands jointly, ``assimilate_multiple_bands`` semantics,
``linear_kf.py:214-242``), dump each timestep's analysis.  The host owns
dates, I/O and scheduling; each date's full multi-band, multi-iteration
solve is ONE jitted XLA program (``core.solvers.assimilate_date_jit``) keyed
on the operator's stable ``linearize`` callable — per-date data (rasters,
angles, emulator weights) flows through traced arguments, so the program
compiles once per operator and is reused for every date and every tile.
"""

from __future__ import annotations

import datetime
import logging
import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import propagators as prop
from ..core import solver_health
from ..core.linalg import spd_inverse_batched
from ..core.solvers import assimilate_date_jit
from ..core.time_grid import iterate_time_grid
from ..core.types import BandBatch
from .prefetch import ObservationPrefetcher
from .protocols import DateObservation, ObservationSource, OutputWriter, Prior
from .state import PixelGather, make_pixel_gather
from ..resilience import (
    DEFAULT_READ_POLICY,
    TRANSIENT,
    DegradedDateError,
    RetryPolicy,
    classify_failure,
    faults,
)
from ..telemetry import (
    devprof,
    fetch_scalars,
    get_registry,
    perf,
    quality,
    record_memory_watermark,
    span,
    tracing,
)
from ..utils.profiling import trace

LOG = logging.getLogger(__name__)


class KalmanFilter:
    """Raster-time-series Kalman/information filter.

    The five injection points of the reference's ``LinearKalman.__init__``
    (``linear_kf.py:59-96``), array-native:

    - ``observations``: an ``ObservationSource``
    - ``output``: an ``OutputWriter``
    - the observation operator: carried per-date inside ``DateObservation``
      (the reference's ``create_observation_operator`` factory argument)
    - ``state_propagation``: a propagator callable from ``core.propagators``
      (or ``None`` for prior-only advance, as the S2 driver uses)
    - ``prior``: a ``Prior`` (or ``None`` for propagator-only advance)
    """

    def __init__(
        self,
        observations: ObservationSource,
        output: OutputWriter,
        state_mask: np.ndarray,
        parameter_list: Sequence[str],
        state_propagation: Optional[Callable] = None,
        prior: Optional[Prior] = None,
        pad_multiple: int = 256,
        diagnostics: bool = True,
        solver_options: Optional[dict] = None,
        hessian_correction: bool = False,
        prefetch_depth: int = 2,
        prefetch_workers: int = 1,
        scan_window: int = 8,
        mesh=None,
        mesh_lane: int = 128,
        checkpoint_every_n: int = 1,
        band_sequential: bool = False,
        read_retry_policy: Optional[RetryPolicy] = None,
        max_degraded_dates: int = 8,
    ):
        self.observations = observations
        self.output = output
        self.parameter_list = tuple(parameter_list)
        self.n_params = len(self.parameter_list)
        # Multi-chip execution: with a ``jax.sharding.Mesh`` the engine
        # commits every pixel-batched array (state, band batches, per-pixel
        # aux, priors) to a pixel-axis NamedSharding, so the SAME jitted
        # per-date program partitions across all mesh devices under GSPMD —
        # the ICI half of the reference's fan-out axis
        # (``kafka_test_Py36.py:242-255`` -> SURVEY §2.3), with zero
        # collectives in the solve beyond the scalar convergence norm.
        # ``mesh_lane`` keeps every device shard a multiple of the VPU lane
        # width (128 on TPU; tests use smaller lanes on CPU meshes).
        self.mesh = mesh
        if mesh is not None:
            quantum = int(mesh.devices.size) * int(mesh_lane)
            pad_multiple = int(np.lcm(int(pad_multiple), quantum))
            # /meshz introspection (telemetry.devprof): the mesh axes
            # this engine partitions over, registered once.
            devprof.note_mesh(mesh)
        self.gather = make_pixel_gather(state_mask, pad_multiple)
        self._state_propagator = state_propagation
        self.prior = prior
        # e.g. {"relaxation": 0.7} for damped Gauss-Newton on stiff
        # operators; None reproduces the reference loop exactly.
        self.solver_options = solver_options
        # Subtract the second-order Hessian correction from the analysis
        # information matrix (linear_kf.py:412-416) when the operator
        # exposes a per-pixel forward model.
        self.hessian_correction = bool(hessian_correction)
        # Depth of the double-buffered observation prefetch (SURVEY §2.2
        # raster row); 0 reads synchronously in the loop like the reference
        # (linear_kf.py:225-227).
        self.prefetch_depth = int(prefetch_depth)
        # Concurrent prefetch reads (ordered delivery): >1 overlaps
        # multiple dates' host I/O on multi-core hosts; 1 is the
        # single-pipeline behaviour.
        self.prefetch_workers = max(1, int(prefetch_workers))
        self._prefetcher = None
        # Temporal fusion: up to this many consecutive single-observation
        # windows run as ONE lax.scan program (advance + Gauss-Newton per
        # step), with the per-window analyses returned as two stacked
        # arrays — one dispatch and one device->host round-trip per block
        # instead of per date.  1 disables fusion (the reference's
        # strictly host-driven loop).
        self.scan_window = max(1, int(scan_window))
        # Observations fetched while probing a fusion block but consumed
        # by the unfused path instead (prefetcher dates pop exactly once).
        self._pending_obs: dict = {}
        # The current window's OR-merged solve-health QA verdicts
        # (device array; written as the per-window solver_qa band).
        self._window_verdicts = None
        # Graceful degradation (BASELINE.md "Fault tolerance"): a date
        # whose read exhausts its transient-failure retries is consumed
        # as a MISSING observation — the window becomes predict-only,
        # which the Kalman structure handles natively — up to a budget
        # of ``max_degraded_dates`` per run, after which the run aborts
        # (losing more dates than that is a systemic outage, not
        # weather).  Poison/fatal read errors stay fail-fast.
        self._read_policy = read_retry_policy \
            if read_retry_policy is not None else DEFAULT_READ_POLICY
        self.max_degraded_dates = max_degraded_dates
        self._degraded_count = 0
        # Dates the fusion-probing path already consumed as degraded;
        # the unfused window path reads the degradation from here
        # (prefetcher dates pop exactly once).
        self._degraded_pending: set = set()
        # Fetch-order date counter: the ``obs.bias`` chaos site
        # addresses observation dates by this 1-based number
        # (telemetry.quality.observation_bias; degraded fetches count
        # too, so the numbering is deterministic either way).
        self._obs_date_no = 0
        # The reference's LEGACY band-sequential path
        # (``linear_kf.py:325-425``): each band assimilates alone, its
        # posterior becoming the next band's prior, with its own
        # Gauss-Newton loop (and per-band Hessian correction when on).
        # The default joint multiband update matches the reference's
        # shipped drivers (``assimilate_multiple_bands``); this mode
        # reproduces the older sequential conditioning — identical for
        # linear operators, order-dependent for nonlinear ones, exactly
        # as in the reference.
        self.band_sequential = bool(band_sequential)
        self._band_views: dict = {}
        # Checkpoint cadence: save at most every N grid windows (the last
        # window of a run always saves).  1 = the reference-faithful
        # every-window cadence; at annual-chain scale that is ~50
        # compressed writes of the full packed information matrix per
        # chunk on the critical path, so production configs raise it.
        # Fused blocks count as their window span and save at block end.
        self.checkpoint_every_n = max(1, int(checkpoint_every_n))
        self._windows_since_ckpt = 0
        # Per-date dispatch hook: the serving layer's batch executor
        # points this at its rendezvous so compatible concurrent serves
        # coalesce into one stacked launch (serve.batch).  None (the
        # default, and every non-serving path) dispatches
        # ``assimilate_date_jit`` directly — same signature, same
        # program.  Only the unfused scan_window=1 joint-band path
        # honours it; fused scans and band-sequential keep their own
        # launches.
        self.date_dispatcher = None
        self.diagnostics = diagnostics
        self.diagnostics_log: list = []
        # Identity trajectory model + zero model error by default, matching
        # set_trajectory_model / set_trajectory_uncertainty
        # (linear_kf.py:123-146).
        self.trajectory_model = jnp.eye(self.n_params, dtype=jnp.float32)
        self.trajectory_uncertainty = jnp.zeros(
            (self.n_params,), jnp.float32
        )

    # ------------------------------------------------------------------
    # configuration (reference API parity)
    # ------------------------------------------------------------------

    def set_trajectory_model(self, m: Optional[np.ndarray] = None) -> None:
        """Identity by default — 'that's how we roll' (linear_kf.py:123)."""
        self.trajectory_model = (
            jnp.eye(self.n_params, dtype=jnp.float32)
            if m is None else jnp.asarray(m, jnp.float32)
        )

    def set_trajectory_uncertainty(self, q_diag) -> None:
        """Per-parameter model-error diagonal Q (linear_kf.py:131-146)."""
        q = np.asarray(q_diag, np.float32)
        if q.ndim == 0:
            q = np.full((self.n_params,), float(q), np.float32)
        self.trajectory_uncertainty = jnp.asarray(q)

    # ------------------------------------------------------------------
    # mesh sharding
    # ------------------------------------------------------------------

    def _px_sharding(self, batch_axis: int, ndim: int):
        from ..shard.mesh import pixel_sharding

        return pixel_sharding(self.mesh, batch_axis, ndim)

    def _aux_axis_flags(self, operator, aux):
        """Flattened aux leaves + per-leaf pixel-axis flags (0 = split on
        pixels, None = replicate), deferring to the operator's own
        ``aux_in_axes`` contract; plain callables fall back to the shared
        leading-axis heuristic (``obsops.protocol._aux_in_axes``)."""
        n_pad = self.gather.n_pad
        leaves, treedef = jax.tree.flatten(aux)
        if hasattr(operator, "aux_in_axes"):
            axes_tree = operator.aux_in_axes(aux, n_pad)
        else:
            from ..obsops.protocol import _aux_in_axes

            axes_tree = _aux_in_axes(aux, n_pad)
        return leaves, treedef, treedef.flatten_up_to(axes_tree)

    def _put_pixel(self, arr):
        """Commit a pixel-leading array to the mesh (no-op without one)."""
        if self.mesh is None or arr is None:
            return arr
        return jax.device_put(arr, self._px_sharding(0, np.ndim(arr)))

    def _shard_obs(self, obs: DateObservation) -> DateObservation:
        """Commit a fetched observation to the mesh: band batches split on
        their pixel axis, aux leaves split or replicated per the operator's
        own ``aux_in_axes`` contract (a weight matrix whose leading dim
        happens to equal n_pix must be replicated, not split)."""
        if self.mesh is None:
            return obs
        bnd = self._px_sharding(1, 2)
        bands = BandBatch(
            y=jax.device_put(obs.bands.y, bnd),
            r_inv=jax.device_put(obs.bands.r_inv, bnd),
            mask=jax.device_put(obs.bands.mask, bnd),
        )
        aux = self._put_aux(obs.operator, obs.aux)
        return obs._replace(bands=bands, aux=aux)

    def _put_aux(self, operator, aux, stacked=None, batch_offset=0):
        """Commit an aux pytree to the mesh: per-pixel leaves split on
        their pixel axis, the rest replicated.  ``stacked`` (with
        ``batch_offset=1``) handles the fused path, where leaves gained a
        leading window axis but the per-pixel/broadcast decision must be
        taken from the UNstacked template ``aux``."""
        if aux is None:
            return None if stacked is None else stacked
        from ..shard.mesh import replicated

        leaves, treedef, axes = self._aux_axis_flags(operator, aux)
        if stacked is not None:
            leaves = treedef.flatten_up_to(stacked)
        rep = replicated(self.mesh)
        return jax.tree.unflatten(treedef, [
            jax.device_put(
                leaf,
                self._px_sharding(batch_offset, np.ndim(leaf))
                if ax == 0 else rep,
            )
            for leaf, ax in zip(leaves, axes)
        ])

    # ------------------------------------------------------------------
    # the time loop
    # ------------------------------------------------------------------

    def advance(self, x_analysis, p_analysis, p_analysis_inverse,
                date: datetime.datetime):
        """State propagation + prior blending (``LinearKalman.advance`` ->
        ``propagate_and_blend_prior``, linear_kf.py:99-108)."""
        prior_mean = prior_inv = None
        if self.prior is not None:
            prior_mean, prior_inv = self.prior.process_prior(
                date, self.gather
            )
            prior_mean = self._put_pixel(prior_mean)
            prior_inv = self._put_pixel(prior_inv)
        return prop.advance(
            x_analysis, p_analysis, p_analysis_inverse,
            self.trajectory_model, self.trajectory_uncertainty,
            prior_mean=prior_mean, prior_cov_inverse=prior_inv,
            state_propagator=self._state_propagator,
        )

    def _fetch(self, date) -> Optional[DateObservation]:
        """The date's observation, or None when its read DEGRADED (the
        caller must then treat the date as having no observation)."""
        if self._pending_obs:
            hit = self._pending_obs.pop(date, None)
            if hit is not None:
                return hit
        if date in self._degraded_pending:
            self._degraded_pending.discard(date)
            return None
        # One number per date, in fetch order (pending replays above
        # were numbered when first fetched) — the obs.bias address.
        self._obs_date_no += 1
        date_no = self._obs_date_no
        if self._prefetcher is not None:
            try:
                return self._apply_obs_bias(
                    self._prefetcher.get(date), date_no
                )
            except DegradedDateError as exc:
                self._note_degraded(date, exc.cause)
                return None

        def read():
            faults.fault_point("prefetch.read_date", date=str(date))
            return self.observations.get_observations(date, self.gather)

        try:
            obs = self._read_policy.call(read, site="prefetch.read_date")
        except BaseException as exc:
            if classify_failure(exc) != TRANSIENT:
                raise
            self._note_degraded(date, exc)
            return None
        return self._apply_obs_bias(self._shard_obs(obs), date_no)

    def _apply_obs_bias(self, obs: DateObservation,
                        date_no: int) -> DateObservation:
        """The ``obs.bias`` chaos site: when an armed fault spec matches
        this fetch-order date number, add the scripted bias to the
        date's VALID observations (masked entries stay untouched).  The
        bias rides the traced ``y`` data, so the compiled program is
        identical armed or not; disarmed, nothing is touched at all."""
        bias = quality.observation_bias(date_no)
        if bias is None:
            return obs
        bands = obs.bands
        y = bands.y + jnp.float32(bias) * bands.mask.astype(jnp.float32)
        return obs._replace(bands=BandBatch(
            y=y, r_inv=bands.r_inv, mask=bands.mask,
        ))

    def _note_degraded(self, date, exc: BaseException) -> None:
        """Record one degraded date (counter + event + budget check)."""
        self._degraded_count += 1
        reg = get_registry()
        reg.counter(
            "kafka_engine_dates_degraded_total",
            "observation dates whose read exhausted transient-failure "
            "retries and were assimilated as missing (predict-only)",
        ).inc()
        reg.emit(
            "date_degraded", date=str(date), error=repr(exc)[:300],
            degraded_total=self._degraded_count,
            budget=self.max_degraded_dates,
        )
        LOG.warning(
            "observation read for %s degraded after retries (%r); "
            "treating as a missing observation (%d of %s budget)",
            date, exc, self._degraded_count, self.max_degraded_dates,
        )
        # The quality ledger keeps the hole visible: a thinned series
        # is itself a quality signal (BASELINE.md "Assimilation
        # quality").
        ctx = tracing.current_context()
        quality.get_ledger(reg).record_missing(
            date, reason="degraded_read",
            prefix=None if ctx is None else ctx.chunk_id,
        )
        if self.max_degraded_dates is not None and \
                self._degraded_count > self.max_degraded_dates:
            raise RuntimeError(
                f"{self._degraded_count} degraded observation dates "
                f"exceed max_degraded_dates={self.max_degraded_dates}; "
                "aborting (systemic read outage, not transient weather)"
            ) from exc

    def date_solver_options(self, operator) -> dict:
        """The per-date solver-option dict EXACTLY as the time loop
        dispatches it — also the source of truth for serve-side AOT
        bucket lowering (``core.solvers.lower_date_program``), which must
        trace the same program the live dispatch will."""
        opts = dict(self.solver_options or {})
        if "state_bounds" not in opts and \
                getattr(operator, "state_bounds", None) is not None:
            lo, hi = operator.state_bounds
            opts["state_bounds"] = (
                jnp.asarray(lo, jnp.float32), jnp.asarray(hi, jnp.float32)
            )
        # Convergence tolerance must be measured on valid pixels only.
        opts.setdefault(
            "norm_denominator",
            float(self.gather.n_valid * self.n_params),
        )
        # Bound solver peak memory on big batches: linearise in
        # sequential 256k-pixel blocks (the batched value+Jacobian is
        # ~11 KB/px of live intermediates for deep operators — without
        # blocking, ~1.4M px exhausts a 16 GB chip).  Harmless when
        # the in-kernel-linearise path engages: that path is
        # O(kernel block) memory by construction and ignores this.
        if self.gather.n_pad > 262144:
            opts.setdefault("linearize_block", 262144)
        return opts

    def assimilate_dates(self, dates, x_forecast, p_forecast,
                         p_forecast_inverse):
        """Assimilate each acquisition in the window sequentially, posterior
        becoming the next forecast (``assimilate_multiple_bands``,
        linear_kf.py:214-242)."""
        x_a, p_a, p_inv_a = x_forecast, p_forecast, p_forecast_inverse
        if p_inv_a is None and p_a is not None:
            # Covariance-form propagators (standard Kalman) hand back P, not
            # P^-1; the solver works in information space.
            p_inv_a = spd_inverse_batched(jnp.asarray(p_a, jnp.float32))
        # Per-window solve-health QA accumulator (device array): the
        # window's QA band is the OR-merge over its acquisitions.
        self._window_verdicts = None
        for date in dates:
            obs = self._fetch(date)
            if obs is None:
                # Degraded date: no observation to assimilate — the
                # forecast passes through unchanged (predict-only), the
                # same arithmetic as a window with no acquisitions.
                LOG.info("Skipping degraded date %s (predict-only)", date)
                continue
            # The device.oom chaos site: an armed fault here stands in
            # for XLA's RESOURCE_EXHAUSTED unwinding out of the solve
            # dispatch below — the flight recorder must attach the
            # buffer census (telemetry.devprof OOM forensics).
            faults.fault_point("device.oom", date=str(date))
            t0 = time.time()
            opts = self.date_solver_options(obs.operator)
            if self.band_sequential:
                x_a, p_inv_a, diags = self._assimilate_band_sequential(
                    obs, x_a, p_inv_a, opts
                )
            else:
                hess_fwd = None
                if self.hessian_correction:
                    hess_fwd = getattr(
                        obs.operator, "forward_pixel", None
                    )
                dispatch = self.date_dispatcher or assimilate_date_jit
                x_a, p_inv_a, diags = dispatch(
                    obs.operator.linearize, obs.bands, x_a,
                    p_inv_a, obs.aux, opts or None, hess_fwd,
                )
            p_a = None
            if diags.health_verdicts is not None:
                self._window_verdicts = (
                    diags.health_verdicts
                    if self._window_verdicts is None
                    else solver_health.merge_verdicts(
                        self._window_verdicts, diags.health_verdicts
                    )
                )
            if self.diagnostics:
                # One packed read: each device->host round-trip costs
                # ~0.2 s of latency on a tunneled chip, so ALL diagnostic
                # scalars — loop counters AND the telemetry quantities
                # computed on device inside the solve — travel together
                # through the counted fetch_scalars funnel.
                n_bands = obs.bands.y.shape[0]
                parts = [
                    jnp.stack([
                        jnp.asarray(diags.n_iterations, jnp.float32),
                        jnp.asarray(diags.convergence_norm, jnp.float32),
                        jnp.asarray(diags.clipped_count, jnp.float32),
                        jnp.asarray(diags.nodata_count, jnp.float32),
                    ]),
                    jnp.asarray(diags.chi2_per_band, jnp.float32),
                ]
                if diags.converged_mask is not None:
                    parts.append(jnp.mean(
                        diags.converged_mask[: self.gather.n_valid]
                        .astype(jnp.float32)
                    )[None])
                # Solve-health scalars join the SAME packed read (zero
                # added transfers; mutually exclusive with the
                # per-pixel-convergence extra above — health only runs
                # in global-norm mode).
                if diags.health_verdicts is not None:
                    parts.append(jnp.stack([
                        jnp.asarray(diags.cap_bailout_count, jnp.float32),
                        jnp.asarray(
                            diags.damped_recovered_count, jnp.float32
                        ),
                        jnp.asarray(diags.quarantined_count, jnp.float32),
                        jnp.asarray(diags.nonfinite_count, jnp.float32),
                    ]))
                    parts.append(jnp.asarray(
                        diags.clip_saturated_count, jnp.float32
                    ))
                packed = fetch_scalars(jnp.concatenate(parts))
                rec = {
                    "date": date,
                    "n_iterations": int(packed[0]),
                    "convergence_norm": float(packed[1]),
                    "bounds_clipped": int(packed[2]),
                    "nodata": self._nodata_valid(int(packed[3]), n_bands),
                    "chi2_per_band": [
                        float(v) for v in packed[4:4 + n_bands]
                    ],
                    "wall_s": time.time() - t0,
                }
                if diags.converged_mask is not None:
                    rec["converged_frac"] = float(packed[4 + n_bands])
                if diags.health_verdicts is not None:
                    h0 = 4 + n_bands
                    rec["cap_bailouts"] = int(packed[h0])
                    rec["damped_recovered"] = int(packed[h0 + 1])
                    rec["quarantined"] = int(packed[h0 + 2])
                    rec["nonfinite"] = int(packed[h0 + 3])
                    rec["clip_saturated"] = [
                        int(v)
                        for v in packed[h0 + 4:h0 + 4 + self.n_params]
                    ]
                self.diagnostics_log.append(rec)
                self._record_window(rec)
                LOG.info(
                    "Assimilated %s: %d iterations, norm %.3g, %.2fs",
                    date, rec["n_iterations"], rec["convergence_norm"],
                    rec["wall_s"],
                )
        return x_a, p_a, p_inv_a

    def _nodata_valid(self, raw: int, n_bands: int) -> int:
        """Nodata count over REAL pixels: the device-side count includes
        the padding rows (mask False in every band there)."""
        pad = self.gather.n_pad - self.gather.n_valid
        return max(0, raw - n_bands * pad)

    def _record_window(self, rec: dict) -> None:
        """Land one window's diagnostics in the telemetry registry + event
        log.  Metric names: BASELINE.md "Observability"."""
        reg = get_registry()
        reg.counter(
            "kafka_engine_windows_total",
            "assimilated observation windows",
        ).inc(mode="fused" if "fused" in rec else "single")
        reg.counter(
            "kafka_engine_pixels_total",
            "valid pixels assimilated, summed over windows — the "
            "solver SLO objective's denominator (telemetry.slo)",
        ).inc(self.gather.n_valid)
        reg.histogram(
            "kafka_engine_gn_iterations",
            "Gauss-Newton iterations to convergence per window",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16, 25, 40),
        ).observe(rec["n_iterations"])
        reg.gauge(
            "kafka_engine_convergence_norm",
            "final Gauss-Newton step norm of the latest window",
        ).set(rec["convergence_norm"])
        chi2_hist = reg.histogram(
            "kafka_engine_innovation_chi2",
            "mean innovation chi^2 per band per window (~1 when the "
            "assumed observation uncertainty matches residuals)",
            buckets=(0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.5, 5.0, 10.0,
                     100.0),
        )
        for b, v in enumerate(rec["chi2_per_band"]):
            chi2_hist.observe(v, band=b)
        reg.counter(
            "kafka_engine_bounds_clipped_total",
            "state entries projected onto state_bounds (observed "
            "pixels only)",
        ).inc(rec["bounds_clipped"])
        reg.counter(
            "kafka_engine_nodata_pixels_total",
            "masked-out (NaN/nodata) observation entries across bands",
        ).inc(rec["nodata"])
        if "converged_frac" in rec:
            reg.gauge(
                "kafka_engine_converged_frac",
                "fraction of valid pixels frozen at convergence "
                "(per_pixel_convergence mode)",
            ).set(rec["converged_frac"])
        if "quarantined" in rec:
            self._record_solver_health(reg, rec)
        # Quality ledger: the window's consistency record, built from
        # the SAME host-side scalars (the packed read already paid) —
        # zero added device transfers.  The verdict is folded back into
        # the diagnostics record so serve responses can report it.
        ctx = tracing.current_context()
        entry = quality.get_ledger(reg).record_window(
            date=rec["date"],
            chi2_per_band=rec["chi2_per_band"],
            n_valid=self.gather.n_valid,
            solver_health=(
                {
                    "quarantined": rec["quarantined"],
                    "cap_bailouts": rec["cap_bailouts"],
                    "damped_recovered": rec["damped_recovered"],
                    "nonfinite": rec["nonfinite"],
                } if "quarantined" in rec else None
            ),
            prefix=None if ctx is None else ctx.chunk_id,
            fused=rec.get("fused"),
        )
        rec["quality_verdict"] = entry["verdict"]
        rec["quality_drift"] = entry["drift"]["active"]
        # Performance attribution (telemetry.perf): the live throughput/
        # device-fraction/roofline gauges, fed from this SAME host-side
        # record — the packed read above is still the window's only
        # device->host transfer.
        perf.record_window(
            rec,
            n_valid=self.gather.n_valid,
            n_pad=self.gather.n_pad,
            n_params=self.n_params,
            n_bands=len(rec["chi2_per_band"]),
            solver_options=self.solver_options,
            registry=reg,
        )
        reg.emit(
            "solve",
            **{k: (str(v) if k == "date" else v) for k, v in rec.items()},
        )

    def _record_solver_health(self, reg, rec: dict) -> None:
        """Solve-health counters + events for one window's record
        (BASELINE.md "Numerical resilience")."""
        reg.counter(
            "kafka_solver_cap_bailouts_total",
            "observed pixels still moving when the Gauss-Newton loop "
            "hit its iteration cap (the reference's silent bailout, "
            "counted)",
        ).inc(rec["cap_bailouts"])
        reg.counter(
            "kafka_solver_damped_recoveries_total",
            "pixels that went numerically bad mid-loop, took the "
            "Levenberg-Marquardt damping escalation and recovered",
        ).inc(rec["damped_recovered"])
        reg.counter(
            "kafka_solver_quarantined_pixels_total",
            "pixels still bad after damping escalation, served as "
            "forecast with deflated information (QA_QUARANTINED)",
        ).inc(rec["quarantined"])
        reg.counter(
            "kafka_solver_nonfinite_total",
            "observed pixels whose raw Gauss-Newton step went "
            "non-finite at least once during the loop",
        ).inc(rec["nonfinite"])
        sat = rec.get("clip_saturated") or []
        c_sat = reg.counter(
            "kafka_solver_clip_saturated_total",
            "pixels clipped to a state_bounds limit on EVERY "
            "iteration, per parameter — a pinned pixel is a masked "
            "divergence",
        )
        for name, v in zip(self.parameter_list, sat):
            if v:
                c_sat.inc(v, param=name)
        if any(sat):
            reg.emit(
                "solver_clip_saturated", date=str(rec["date"]),
                counts={
                    name: int(v)
                    for name, v in zip(self.parameter_list, sat) if v
                },
            )
        if rec["quarantined"]:
            reg.emit(
                "solver_pixels_quarantined", date=str(rec["date"]),
                count=rec["quarantined"],
            )

    def _band_view(self, operator, band: int):
        from ..obsops.protocol import BandView, ObservationModel

        # Fail HERE with a clear message, not with an opaque
        # NotImplementedError from inside a vmap trace: the sequential
        # mode slices the operator's forward_pixel per band, so a
        # linearize-only operator (plain-closure form) cannot use it.
        fwd = getattr(type(operator), "forward_pixel", None)
        if fwd is None or fwd is ObservationModel.forward_pixel:
            raise TypeError(
                "band_sequential=True requires the operator to "
                "implement forward_pixel; "
                f"{type(operator).__name__} only provides linearize"
            )
        key = (id(operator), band)
        view = self._band_views.get(key)
        if view is None or view.inner is not operator:
            view = self._band_views[key] = BandView(operator, band)
        return view

    def _assimilate_band_sequential(self, obs, x_a, p_inv_a, opts):
        """One acquisition, bands assimilated SEQUENTIALLY — the
        reference's ``assimilate``/``assimilate_band`` legacy semantics
        (``linear_kf.py:325-425``): per band, a full Gauss-Newton loop,
        posterior -> next band's prior, Hessian correction per band.

        Merged diagnostics are conservative: iterations SUM over the
        per-band loops, the convergence norm is the WORST band's (a date
        only reads as converged when every band's loop converged), the
        per-pixel converged mask is the AND over bands, and the
        innovations/forward-model residuals concatenate over bands so
        the merged record covers every band like the joint path's."""
        n_bands = obs.bands.y.shape[0]
        iters_total = 0
        norms = []
        masks = []
        innovations = []
        fwds = []
        chi2s = []
        verds = []
        nonfins = []
        nodata_total = None
        last_diags = None
        for b in range(n_bands):
            band_obs = BandBatch(
                y=obs.bands.y[b:b + 1],
                r_inv=obs.bands.r_inv[b:b + 1],
                mask=obs.bands.mask[b:b + 1],
            )
            view = self._band_view(obs.operator, b)
            hess_fwd = view.forward_pixel if self.hessian_correction \
                else None
            x_a, p_inv_a, last_diags = assimilate_date_jit(
                view.linearize, band_obs, x_a, p_inv_a, obs.aux,
                opts or None, hess_fwd,
            )
            iters_total += last_diags.n_iterations
            norms.append(last_diags.convergence_norm)
            innovations.append(last_diags.innovations)
            fwds.append(last_diags.fwd_modelled)
            chi2s.append(last_diags.chi2_per_band)
            nodata_total = last_diags.nodata_count if nodata_total is None \
                else nodata_total + last_diags.nodata_count
            if last_diags.converged_mask is not None:
                masks.append(last_diags.converged_mask)
            if last_diags.health_verdicts is not None:
                verds.append(last_diags.health_verdicts)
                nonfins.append(last_diags.nonfinite_count)
        # Telemetry merge: chi2 concatenates (each solve saw one band),
        # nodata sums over bands, clipped is the LAST band's — the final
        # state's bound projections (summing would re-count every loop).
        diags = last_diags._replace(
            n_iterations=iters_total,
            convergence_norm=jnp.max(jnp.stack(norms)),
            innovations=jnp.concatenate(innovations, axis=0),
            fwd_modelled=jnp.concatenate(fwds, axis=0),
            converged_mask=(
                jnp.all(jnp.stack(masks), axis=0) if masks else None
            ),
            chi2_per_band=jnp.concatenate(chi2s, axis=0),
            nodata_count=nodata_total,
        )
        # Solve-health merge: verdict flags OR over the per-band loops
        # (NODATA only where no band observed the pixel), scalar counts
        # recomputed from the merged bitmask; nonfinite sums over loops;
        # clip_saturated stays the LAST band's, like clipped above.
        if len(verds) == n_bands and verds:
            merged = verds[0]
            for v in verds[1:]:
                merged = solver_health.merge_verdicts(merged, v)
            cap, damped, quar = solver_health.verdict_counts(merged)
            diags = diags._replace(
                health_verdicts=merged,
                cap_bailout_count=cap,
                damped_recovered_count=damped,
                quarantined_count=quar,
                nonfinite_count=sum(nonfins),
            )
        return x_a, p_inv_a, diags

    def run(self, time_grid, x_forecast, p_forecast, p_forecast_inverse,
            checkpointer=None, advance_first=False, profile_dir=None):
        """Full assimilation run (``LinearKalman.run``,
        linear_kf.py:171-212).  ``x_forecast`` may be (n_pad, p) batched or
        the reference's flat interleaved layout.

        ``advance_first=True`` applies the state propagation/prior blend
        before the FIRST grid step too — required when resuming from a
        checkpoint, where the loaded state is an *analysis* whose advance
        into the first resumed window hasn't happened yet.

        ``profile_dir`` captures a ``jax.profiler`` trace of the whole run
        into that directory (TensorBoard/Perfetto-viewable), with engine
        phases labelled via TraceAnnotation spans."""
        x_forecast = jnp.asarray(x_forecast, jnp.float32).reshape(
            -1, self.n_params
        )
        if p_forecast_inverse is not None:
            p_forecast_inverse = jnp.asarray(
                p_forecast_inverse, jnp.float32
            )
        if x_forecast.shape[0] != self.gather.n_pad:
            # States checkpointed under a different padding (pre-mesh
            # checkpoints, or a host exposing a different device count
            # changing the mesh lcm) carry the same n_valid real pixels in
            # their leading rows — re-pad rather than fail mid-resume.
            x_forecast, p_forecast, p_forecast_inverse = self._repad(
                x_forecast, p_forecast, p_forecast_inverse
            )
        if self.mesh is not None:
            x_forecast = self._put_pixel(x_forecast)
            p_forecast_inverse = self._put_pixel(p_forecast_inverse)
            p_forecast = self._put_pixel(p_forecast)
        # Snapshot the grid windowing ONCE: the run loop and the prefetch
        # plan must see the identical date sequence even if the source's
        # `dates` property recomputes between reads (else a plan/loop
        # divergence would block forever on the prefetch queue).
        windows = list(iterate_time_grid(time_grid, self.observations.dates))
        if self.prefetch_depth > 0:
            plan = [d for _, locate_times, _ in windows
                    for d in locate_times]
            if plan:
                # Temporal fusion collects a whole block of observations
                # before dispatching the scan; a shallower prefetch would
                # serialise those reads against the device instead of
                # overlapping them with the previous block's solve.  Runs
                # that can never fuse keep the configured depth.
                depth = self.prefetch_depth
                if self._fusion_possible():
                    depth = max(depth, self.scan_window)
                self._prefetcher = ObservationPrefetcher(
                    self.observations, self.gather, plan,
                    depth=depth,
                    transform=(
                        self._shard_obs if self.mesh is not None else None
                    ),
                    workers=self.prefetch_workers,
                    retry_policy=self._read_policy,
                )
        try:
            # push() keeps the driver's run context when one is active and
            # otherwise opens a fresh run id, so even a bare engine run
            # gets one coherent timeline.
            with trace(profile_dir), tracing.push():
                return self._run_loop(
                    windows, x_forecast, p_forecast, p_forecast_inverse,
                    checkpointer, advance_first,
                )
        finally:
            if self._prefetcher is not None:
                self._prefetcher.close()
                self._prefetcher = None

    def _repad(self, x, p_f, p_inv):
        """Re-pad a pixel-state triple to this gather's ``n_pad``: the
        leading ``n_valid`` rows are the real pixels (PixelGather layout
        invariant), new padding rows get zero state and identity
        information — inert in every solve, never scattered out."""
        n_valid, n_pad, p = self.gather.n_valid, self.gather.n_pad, \
            self.n_params
        if x.shape[0] < n_valid:
            raise ValueError(
                f"state has {x.shape[0]} rows but the mask holds "
                f"{n_valid} valid pixels — not a state of this chunk"
            )
        if x.shape[0] == self.gather.mask.size and \
                self.gather.mask.size != n_valid:
            # A row per raster cell is NOT PixelGather layout — slicing
            # its first n_valid rows would silently scramble pixels.
            raise ValueError(
                f"state has one row per raster cell ({x.shape[0]}); "
                "expected PixelGather layout (valid pixels first) — "
                "gather it with PixelGather.gather before run()"
            )
        LOG.info(
            "re-padding state from %d to %d rows (%d valid pixels)",
            x.shape[0], n_pad, n_valid,
        )
        n_fill = n_pad - n_valid

        def pad2(a):
            return jnp.concatenate([
                jnp.asarray(a, jnp.float32)[:n_valid],
                jnp.zeros((n_fill, p), jnp.float32),
            ])

        def pad3(a, fill):
            return jnp.concatenate([
                jnp.asarray(a, jnp.float32)[:n_valid],
                jnp.broadcast_to(
                    fill * jnp.eye(p, dtype=jnp.float32), (n_fill, p, p)
                ),
            ])

        return (
            pad2(x),
            None if p_f is None else pad3(p_f, 1.0),
            None if p_inv is None else pad3(p_inv, 1.0),
        )

    # ------------------------------------------------------------------
    # temporal fusion (lax.scan over consecutive windows)
    # ------------------------------------------------------------------

    # Device-memory guards for a fused block: K*n*p elements for each of
    # the two stacked result arrays, K*B*n per stacked band array, and the
    # stacked aux bytes (an aux bank identical across dates would be
    # replicated K times — refuse rather than blow HBM).
    _SCAN_MAX_STATE_ELEMS = 100_000_000
    _SCAN_MAX_BAND_ELEMS = 100_000_000
    _SCAN_MAX_AUX_BYTES = 64 * 1024 * 1024

    def _fusion_possible(self) -> bool:
        """Engine-level fusability: a date-invariant (or absent) prior.
        ``use_pallas`` composes with fusion — the scan threads it through
        as a static argument, so each step's solve runs the fused
        VMEM-resident kernel (parity-tested in tests/test_fusion.py);
        operators advertising ``inkernel_linearize`` additionally run
        each step's whole Gauss-Newton loop INSIDE that kernel (the
        solver discovers the capability from the bound ``linearize``
        itself — nothing extra threads through the engine beyond the
        ``inkernel_linearize`` solver-option opt-out)."""
        if self.scan_window <= 1 or self.band_sequential:
            return False
        return self.prior is None or bool(
            getattr(self.prior, "date_invariant", False)
        )

    @staticmethod
    def _aux_leaves(aux):
        leaves, treedef = jax.tree.flatten(aux)
        return treedef, leaves

    def _stackable(self, first: DateObservation,
                   other: DateObservation) -> bool:
        if other.operator is not first.operator:
            return False
        if other.bands.y.shape != first.bands.y.shape:
            return False
        td_a, la = self._aux_leaves(first.aux)
        td_b, lb = self._aux_leaves(other.aux)
        if td_a != td_b or len(la) != len(lb):
            return False
        for a, b in zip(la, lb):
            sa = np.shape(a)
            if sa != np.shape(b):
                return False
        return True

    def _block_fits(self, obs: DateObservation, k: int) -> bool:
        n, p = self.gather.n_pad, self.n_params
        if k * n * p > self._SCAN_MAX_STATE_ELEMS:
            return False
        # Three stacked band arrays (y, r_inv, mask) are materialised.
        if 3 * k * int(np.prod(obs.bands.y.shape)) > \
                self._SCAN_MAX_BAND_ELEMS:
            return False
        _, leaves = self._aux_leaves(obs.aux)
        aux_bytes = sum(
            int(np.prod(np.shape(a)) or 1)
            * int(getattr(getattr(a, "dtype", None), "itemsize", 4))
            for a in leaves
        )
        return k * aux_bytes <= self._SCAN_MAX_AUX_BYTES

    def _maybe_checkpoint(self, checkpointer, timestep, x, p_analysis,
                          p_inv, n_windows: int, is_last: bool,
                          forecast=None) -> None:
        """Cadenced checkpoint: counts processed grid windows and saves
        every ``checkpoint_every_n`` (the run's last window always saves).
        A checkpoint asserts "everything up to this timestep is durable",
        so queued async output writes are flushed first; the state is
        persisted in information form regardless of propagator.

        ``forecast`` is the window's pre-update ``(x_f, p_f, p_f_inv)``
        triple; it is persisted as the smoother's forecast sidecar ONLY
        when exactly one window elapsed since the previous save, because
        the RTS gain pairs a checkpoint's sidecar with the PREVIOUS
        checkpoint's analysis — with a wider cadence (or a fused block)
        the smoother re-derives the forecast via the propagator instead."""
        if checkpointer is None:
            return
        self._windows_since_ckpt += n_windows
        if not is_last and \
                self._windows_since_ckpt < self.checkpoint_every_n:
            return
        adjacent = n_windows == 1 and self._windows_since_ckpt == 1
        self._windows_since_ckpt = 0
        flush = getattr(self.output, "flush", None)
        if flush is not None:
            flush()
        p_inv_ck = p_inv
        if p_inv_ck is None and p_analysis is not None:
            p_inv_ck = spd_inverse_batched(
                jnp.asarray(p_analysis, jnp.float32)
            )
        x_f = p_f_inv = None
        if forecast is not None and adjacent:
            x_f, p_f, p_f_inv = forecast
            if p_f_inv is None and p_f is not None:
                p_f_inv = spd_inverse_batched(
                    jnp.asarray(p_f, jnp.float32)
                )
            if x_f is None or p_f_inv is None:
                x_f = p_f_inv = None
        checkpointer.save(timestep, x, p_inv_ck, x_forecast=x_f,
                          p_forecast_inverse=p_f_inv)

    def _run_fused_block(self, block, x_analysis, p_analysis,
                         p_analysis_inverse, checkpointer,
                         is_last: bool = True):
        """Run K collected (timestep, obs) windows as one scan program."""
        from ..core.solvers import assimilate_windows_scan

        p_inv = p_analysis_inverse
        if p_inv is None and p_analysis is not None:
            p_inv = spd_inverse_batched(
                jnp.asarray(p_analysis, jnp.float32)
            )
        prior_mean = prior_inv = None
        if self.prior is not None:
            prior_mean, prior_inv = self.prior.process_prior(
                block[0][0], self.gather
            )
            prior_mean = self._put_pixel(prior_mean)
            prior_inv = self._put_pixel(prior_inv)
        first = block[0][1]
        opts = dict(self.solver_options or {})
        if "state_bounds" not in opts and \
                getattr(first.operator, "state_bounds", None) is not None:
            lo, hi = first.operator.state_bounds
            opts["state_bounds"] = (
                jnp.asarray(lo, jnp.float32), jnp.asarray(hi, jnp.float32)
            )
        opts.setdefault(
            "norm_denominator",
            float(self.gather.n_valid * self.n_params),
        )
        if self.gather.n_pad > 262144:
            opts.setdefault("linearize_block", 262144)
        hess_fwd = None
        if self.hessian_correction:
            hess_fwd = getattr(first.operator, "forward_pixel", None)

        # Same device.oom chaos site as the unfused path: the fused
        # scan dispatch is the block's RESOURCE_EXHAUSTED surface.
        faults.fault_point("device.oom", date=str(block[0][0]))
        t0 = time.time()
        bands = BandBatch(
            y=jnp.stack([o.bands.y for _, o in block]),
            r_inv=jnp.stack([o.bands.r_inv for _, o in block]),
            mask=jnp.stack([o.bands.mask for _, o in block]),
        )
        aux_stacked = None
        if first.aux is not None:
            aux_stacked = jax.tree.map(
                lambda *ls: jnp.stack([jnp.asarray(l) for l in ls]),
                *[o.aux for _, o in block],
            )
        if self.mesh is not None:
            # Normalise the stacked shardings: bands (K, n_bands, n_pix)
            # split on the pixel axis; aux leaves that were per-pixel
            # before stacking (axis 0 -> now axis 1) likewise, the rest
            # replicated.  The per-date inputs were already committed by
            # _shard_obs, so these puts are cheap layout confirmations.
            bnd3 = self._px_sharding(2, 3)
            bands = BandBatch(
                y=jax.device_put(bands.y, bnd3),
                r_inv=jax.device_put(bands.r_inv, bnd3),
                mask=jax.device_put(bands.mask, bnd3),
            )
            aux_stacked = self._put_aux(
                first.operator, first.aux, stacked=aux_stacked,
                batch_offset=1,
            )
        x_fin, p_inv_fin, xs, diag_s, iters, norms, converged, wstats = (
            assimilate_windows_scan(
                first.operator.linearize, bands, x_analysis, p_inv,
                aux_stacked, self.trajectory_model,
                self.trajectory_uncertainty, prior_mean, prior_inv,
                self._state_propagator, opts or None, hess_fwd,
            )
        )
        timesteps = [ts for ts, _ in block]
        with span("dump"):
            dump_block = getattr(self.output, "dump_block", None)
            if dump_block is not None:
                dump_block(timesteps, xs, diag_s, self.gather,
                           self.parameter_list)
            else:
                for k, ts in enumerate(timesteps):
                    self.output.dump_data(
                        ts, xs[k], diag_s[k], self.gather,
                        self.parameter_list,
                    )
            # Per-window solve-health QA bands from the stacked scan
            # verdicts — an output product like the states (the writer
            # pays the transfer; no diagnostic read is added).
            if wstats.health_verdicts is not None:
                qa_block = getattr(self.output, "dump_qa_block", None)
                if qa_block is not None:
                    qa_block(timesteps, wstats.health_verdicts,
                             self.gather)
                else:
                    qa_one = getattr(self.output, "dump_qa", None)
                    if qa_one is not None:
                        for k, ts in enumerate(timesteps):
                            qa_one(ts, wstats.health_verdicts[k],
                                   self.gather)
        if self.diagnostics:
            k = len(timesteps)
            n_bands = first.bands.y.shape[0]
            scalars = [
                jnp.asarray(iters, jnp.float32),
                jnp.asarray(norms, jnp.float32),
                jnp.asarray(wstats.clipped_count, jnp.float32),
                jnp.asarray(wstats.nodata_count, jnp.float32),
                jnp.asarray(
                    wstats.chi2_per_band, jnp.float32
                ).reshape(-1),
            ]
            if converged is not None:
                # Fraction of VALID pixels frozen per window, computed
                # on-device so it rides the same packed transfer.
                scalars.append(
                    jnp.mean(
                        converged[:, : self.gather.n_valid]
                        .astype(jnp.float32),
                        axis=1,
                    )
                )
            # Solve-health scalars join the block's one packed read
            # (mutually exclusive with the converged extra above —
            # health runs in global-norm mode only).
            has_health = wstats.health_verdicts is not None
            if has_health:
                scalars.extend([
                    jnp.asarray(wstats.cap_bailout_count, jnp.float32),
                    jnp.asarray(
                        wstats.damped_recovered_count, jnp.float32
                    ),
                    jnp.asarray(wstats.quarantined_count, jnp.float32),
                    jnp.asarray(wstats.nonfinite_count, jnp.float32),
                    jnp.asarray(
                        wstats.clip_saturated_count, jnp.float32
                    ).reshape(-1),
                ])
            packed = fetch_scalars(jnp.concatenate(scalars))
            wall = time.time() - t0
            chi0 = 4 * k
            h0 = chi0 + k * n_bands + (k if converged is not None else 0)
            p = self.n_params
            for j, ts in enumerate(timesteps):
                rec = {
                    "date": ts,
                    "n_iterations": int(packed[j]),
                    "convergence_norm": float(packed[k + j]),
                    "bounds_clipped": int(packed[2 * k + j]),
                    "nodata": self._nodata_valid(
                        int(packed[3 * k + j]), n_bands
                    ),
                    "chi2_per_band": [
                        float(v) for v in
                        packed[chi0 + j * n_bands:
                               chi0 + (j + 1) * n_bands]
                    ],
                    "wall_s": wall / k,
                    "fused": k,
                }
                if converged is not None:
                    rec["converged_frac"] = float(
                        packed[chi0 + k * n_bands + j]
                    )
                if has_health:
                    rec["cap_bailouts"] = int(packed[h0 + j])
                    rec["damped_recovered"] = int(packed[h0 + k + j])
                    rec["quarantined"] = int(packed[h0 + 2 * k + j])
                    rec["nonfinite"] = int(packed[h0 + 3 * k + j])
                    sat0 = h0 + 4 * k + j * p
                    rec["clip_saturated"] = [
                        int(v) for v in packed[sat0:sat0 + p]
                    ]
                self.diagnostics_log.append(rec)
                self._record_window(rec)
            LOG.info(
                "Assimilated %d fused windows ending %s in %.2fs",
                k, timesteps[-1], wall,
            )
        self._maybe_checkpoint(
            checkpointer, timesteps[-1], x_fin, None, p_inv_fin,
            n_windows=len(timesteps), is_last=is_last,
        )
        return x_fin, None, p_inv_fin

    def _run_loop(self, windows, x_forecast, p_forecast,
                  p_forecast_inverse, checkpointer, advance_first):
        x_analysis, p_analysis, p_analysis_inverse = (
            x_forecast, p_forecast, p_forecast_inverse
        )
        self._pending_obs = {}
        self._degraded_pending = set()
        self._degraded_count = 0
        self._obs_date_no = 0
        self._windows_since_ckpt = 0
        idx = 0
        while idx < len(windows):
            # window_id correlates everything recorded while processing
            # this grid window (a fused block carries its HEAD window's id;
            # the block length is in the records' "fused" field).  The
            # per-window device-memory watermark rides the same host path —
            # no device transfer (telemetry.device invariant).
            with tracing.push(window_id=idx):
                timestep, locate_times, is_first = windows[idx]
                # Try to collect a run of fusable windows: each advances, holds
                # exactly one acquisition, and stacks with the block head.
                if (
                    self._fusion_possible()
                    and ((not is_first) or advance_first)
                    and len(locate_times) == 1
                ):
                    block, block_dates = [], []
                    j = idx
                    while j < len(windows) and len(block) < self.scan_window:
                        ts_j, lt_j, _ = windows[j]
                        if len(lt_j) != 1:
                            break
                        obs_j = self._fetch(lt_j[0])
                        if obs_j is None:
                            # Degraded date: it can't join a fused block
                            # (the scan has no missing-date slot).  The
                            # degradation is already recorded; park it so
                            # the unfused window path sees None again.
                            self._degraded_pending.add(lt_j[0])
                            break
                        if (block and not self._stackable(block[0][1], obs_j)) \
                                or not self._block_fits(obs_j, len(block) + 1):
                            self._pending_obs[lt_j[0]] = obs_j
                            break
                        block.append((ts_j, obs_j))
                        block_dates.append(lt_j[0])
                        j += 1
                    # Bucket the block length to a power of two: the scan
                    # program recompiles per distinct K, so free-running block
                    # sizes (broken by sensor changes, grid gaps...) would each
                    # pay a fresh multi-second XLA compile.  Trimmed windows
                    # return their fetched observations via _pending_obs.
                    k_bucket = 1
                    while k_bucket * 2 <= len(block):
                        k_bucket *= 2
                    for (ts_j, obs_j), date_j in zip(
                        block[k_bucket:], block_dates[k_bucket:]
                    ):
                        self._pending_obs[date_j] = obs_j
                    block = block[:k_bucket]
                    if len(block) >= 2:
                        LOG.info(
                            "Advancing + assimilating %d fused windows "
                            "%s..%s", len(block), block[0][0], block[-1][0],
                        )
                        with span("fused_scan"):
                            x_analysis, p_analysis, p_analysis_inverse = (
                                self._run_fused_block(
                                    block, x_analysis, p_analysis,
                                    p_analysis_inverse, checkpointer,
                                    is_last=(idx + len(block) == len(windows)),
                                )
                            )
                        idx += len(block)
                        record_memory_watermark()
                        continue
                    if len(block) == 1:
                        # Hand the fetched observation to the unfused path.
                        self._pending_obs[locate_times[0]] = block[0][1]
                x_analysis, p_analysis, p_analysis_inverse = (
                    self._run_one_window(
                        windows[idx], x_analysis, p_analysis,
                        p_analysis_inverse, checkpointer, advance_first,
                        is_last=(idx == len(windows) - 1),
                    )
                )
                idx += 1
                record_memory_watermark()
        return x_analysis, p_analysis, p_analysis_inverse

    def _run_one_window(self, window, x_analysis, p_analysis,
                        p_analysis_inverse, checkpointer, advance_first,
                        is_last: bool = True):
        timestep, locate_times, is_first = window
        x_forecast, p_forecast, p_forecast_inverse = (
            x_analysis, p_analysis, p_analysis_inverse
        )
        if (not is_first) or advance_first:
            LOG.info("Advancing state to %s", timestep)
            with span("advance"):
                x_forecast, p_forecast, p_forecast_inverse = (
                    self.advance(
                        x_analysis, p_analysis, p_analysis_inverse,
                        timestep,
                    )
                )
        if len(locate_times) == 0:
            LOG.info("No observations in window ending %s", timestep)
            x_analysis = x_forecast
            p_analysis = p_forecast
            p_analysis_inverse = p_forecast_inverse
            self._window_verdicts = None
        else:
            with span("assimilate"):
                x_analysis, p_analysis, p_analysis_inverse = (
                    self.assimilate_dates(
                        locate_times, x_forecast, p_forecast,
                        p_forecast_inverse,
                    )
                )
        p_inv_diag = self._information_diagonal(
            p_analysis, p_analysis_inverse
        )
        with span("dump"):
            # x/diag stay device arrays: an async writer then pays the
            # device->host transfer on its own thread, off the loop.
            self.output.dump_data(
                timestep, x_analysis, p_inv_diag,
                self.gather, self.parameter_list,
            )
            # The window's solve-health QA band (an output product like
            # x itself — no diagnostic device read involved); writers
            # without a dump_qa simply don't get one.
            if self._window_verdicts is not None:
                dump_qa = getattr(self.output, "dump_qa", None)
                if dump_qa is not None:
                    dump_qa(timestep, self._window_verdicts, self.gather)
        self._maybe_checkpoint(
            checkpointer, timestep, x_analysis, p_analysis,
            p_analysis_inverse, n_windows=1, is_last=is_last,
            forecast=(x_forecast, p_forecast, p_forecast_inverse),
        )
        return x_analysis, p_analysis, p_analysis_inverse

    @staticmethod
    def _information_diagonal(p_analysis, p_analysis_inverse):
        """Per-pixel information diagonal for the sigma outputs
        (``observations.py:393``: sigma = 1/sqrt(diag(P_inv))).  Stays a
        device array — consumers materialise it when they need it."""
        if p_analysis_inverse is not None:
            return jnp.diagonal(p_analysis_inverse, axis1=-2, axis2=-1)
        if p_analysis is not None:
            return 1.0 / jnp.maximum(
                jnp.diagonal(p_analysis, axis1=-2, axis2=-1), 1e-30
            )
        return None
