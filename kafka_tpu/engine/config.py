"""Run configuration — the config layer the reference lacks.

Every reference driver hard-codes paths, dates, Q values and parameter
lists in script bodies (``/root/reference/kafka_test.py:156-217``,
``kafka_test_S2.py:135-205``; SURVEY.md §5 "Config/flag system: none").
This module gives the five injection points (observations, output,
observation operator, state propagation, prior — ``linear_kf.py:59-96``)
a declarative, serialisable home: a ``RunConfig`` dataclass loadable from
JSON, with registries resolving component names to constructors so drivers
stay thin.
"""

from __future__ import annotations

import dataclasses
import datetime
import glob
import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..core import propagators as prop

# ---------------------------------------------------------------------------
# Production solver defaults.
# ---------------------------------------------------------------------------

#: Operators whose fused-Pallas solve path (``use_pallas``) has tier-1
#: parity coverage against the XLA reference (tests/test_solvers.py,
#: tests/test_fusion.py): TIP/two-stream (p=7, incl. the in-kernel
#: Gauss-Newton path) and PROSAIL (p=10, slow-marked full loop + fast
#: single-update kernel parity).  Only these flip to the fused kernel by
#: default; everything else stays opt-in until its parity test lands.
PALLAS_PARITY_TESTED = frozenset({"twostream", "prosail"})

#: env override for where the default-flip gate looks for the bench
#: artifact (absent: the repo's archived BENCH_*.json files).
BENCH_ARTIFACT_ENV = "KAFKA_TPU_BENCH_ARTIFACT"


def _artifact_payload(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]  # driver-wrapped artifacts (BENCH_r0*.json)
    return doc if isinstance(doc, dict) else None


def _artifact_qualifies(doc: dict) -> bool:
    """The ROADMAP gate for the default flip, verbatim: a healthy-window
    artifact (``unhealthy: false`` — flagged or pre-health-layer
    artifacts never qualify) carrying BOTH device rows with the fused
    kernel measured faster."""
    xla, pallas = doc.get("device_xla_ms"), doc.get("device_pallas_ms")
    return (
        doc.get("unhealthy") is False
        and isinstance(xla, (int, float))
        and isinstance(pallas, (int, float))
        and pallas < xla
    )


def pallas_default_ready(artifact_path: Optional[str] = None) -> bool:
    """True when the bench-artifact evidence ROADMAP demands for flipping
    ``use_pallas`` to the production default exists.

    Looks at ``artifact_path``, else ``$KAFKA_TPU_BENCH_ARTIFACT``, else
    every archived ``BENCH*.json`` at the repo root (any qualifying
    artifact suffices).  The flip is therefore automatic-but-gated: the
    code path is production-ready (parity-tested), and the default
    engages the moment a healthy-window artifact carrying both device
    rows (fused faster) is archived — never on unhealthy or
    pre-health-schema artifacts.
    """
    if artifact_path is None:
        artifact_path = os.environ.get(BENCH_ARTIFACT_ENV)
    if artifact_path is not None:
        doc = _artifact_payload(artifact_path)
        return doc is not None and _artifact_qualifies(doc)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    for path in sorted(glob.glob(os.path.join(repo_root, "BENCH*.json"))):
        doc = _artifact_payload(path)
        if doc is not None and _artifact_qualifies(doc):
            return True
    return False


# ---------------------------------------------------------------------------
# Registries for the pluggable pieces.
# ---------------------------------------------------------------------------

PROPAGATORS: Dict[str, Optional[Callable]] = {
    # The five reference propagation schemes (kf_tools.py, SURVEY.md §1 L3)
    "none": None,                      # prior-only advance (S2 driver)
    "standard_kalman": prop.propagate_standard_kalman,
    "information_filter": prop.propagate_information_filter,
    "information_filter_approx": prop.propagate_information_filter_approx,
    "information_filter_lai": prop.propagate_information_filter_lai,
    "no_propagation": prop.no_propagation,
}


def _operator_registry() -> Dict[str, Callable]:
    from ..obsops import (
        IdentityOperator,
        TwoStreamOperator,
        WCMOperator,
    )

    return {
        "identity": lambda cfg: IdentityOperator(
            n_params=cfg.n_params,
            obs_indices=tuple(range(cfg.n_params)),
        ),
        "twostream": lambda cfg: TwoStreamOperator(),
        "wcm": lambda cfg: WCMOperator(),
        "prosail": lambda cfg: _make_prosail(cfg),
        "kernels": lambda cfg: _make_kernels(cfg),
        "prosail_joint": lambda cfg: _joint_op("ProsailJointOperator"),
        "wcm_joint": lambda cfg: _joint_op("WCMJointOperator"),
        # Converted gp_emulator banks (the reference's actual emulator
        # artifacts) as the S2 operator: per-date geometry selects a
        # bank through the aux builder, extra["emulator_folder"] points
        # at the pickles/.npz files.
        "gp_bank": lambda cfg: _make_gp_bank(cfg),
    }


def _make_gp_bank(cfg):
    from ..obsops.gp import GPBankOperator

    return GPBankOperator(
        n_params=cfg.n_params,
        n_bands=int(cfg.extra.get("gp_n_bands", 10)),
    )


def _joint_op(name):
    from ..obsops import joint

    return getattr(joint, name)()


def _make_kernels(cfg):
    from ..obsops.kernels import KernelsOperator

    n_bands, rem = divmod(cfg.n_params, 3)
    if rem:
        raise ValueError(
            "the kernels operator needs 3 weights per band; "
            f"parameter_list has {cfg.n_params} entries"
        )
    return KernelsOperator(n_modis_bands=n_bands)


def _make_prosail(cfg):
    from ..obsops.prosail import ProsailOperator

    return ProsailOperator()


def _named_prior(name: Optional[str], cfg: Optional["RunConfig"] = None):
    from .priors import (
        jrc_prior, joint_prior, kernels_prior, sail_prior, wcm_prior,
    )

    if name is None:
        return None
    if name == "kernels":
        # Band count follows the state size so non-7-band kernel configs
        # get a matching prior, like _make_kernels does for the operator.
        if cfg is None:
            return kernels_prior()
        n_bands, rem = divmod(cfg.n_params, 3)
        if rem:
            raise ValueError(
                "the kernels prior needs 3 weights per band; "
                f"parameter_list has {cfg.n_params} entries"
            )
        return kernels_prior(n_modis_bands=n_bands)
    return {
        "tip": jrc_prior,
        "jrc": jrc_prior,
        "sail": sail_prior,
        "joint": joint_prior,
        "wcm": wcm_prior,
    }[name]()


@dataclasses.dataclass
class RunConfig:
    """One assimilation run, declaratively.

    Mirrors the knobs the reference scatters through its drivers:
    ``time_grid`` (start/end/step days — ``kafka_test_S2.py:174-194``),
    ``q_diag`` (the trajectory uncertainty, ``kafka_test.py:207-208``),
    chunking (``kafka_test_Py36.py:241``), and the five injection points
    by name.
    """

    parameter_list: Sequence[str]
    start: datetime.datetime
    end: datetime.datetime
    step_days: int = 1
    operator: str = "identity"
    propagator: str = "none"
    prior: Optional[str] = None
    #: prior used only for the initial state when ``prior`` is None —
    #: the MODIS-serial pattern (``kafka_test.py:195-208``: JRCPrior
    #: provides x0/P0 but the filter advances by propagator alone).
    initial_prior: Optional[str] = None
    q_diag: Optional[Sequence[float]] = None
    chunk_size: Tuple[int, int] = (128, 128)
    output_folder: str = "."
    data_folder: Optional[str] = None
    state_mask: Optional[str] = None
    observations: str = "synthetic"
    pad_multiple: int = 256
    #: single-process multi-chip execution: "auto" shards every chunk's
    #: pixel batch over a mesh of this process's local devices when there
    #: is more than one (a v5e-8 host runs each chunk on all 8 chips from
    #: ONE process), "local" forces the mesh even on one device, "none"
    #: disables sharding.  The DCN/process axis stays with the chunk
    #: scheduler — together they are the reference's dask fan-out
    #: (``kafka_test_Py36.py:242-255``) mapped to ICI + DCN (SURVEY §2.3).
    device_mesh: str = "auto"
    hessian_correction: bool = False
    #: double-buffered observation prefetch depth; 0 = synchronous reads
    prefetch_depth: int = 2
    #: concurrent prefetch reader threads (ordered delivery); >1 overlaps
    #: several dates' host I/O on multi-core hosts
    prefetch_workers: int = 1
    #: device->host wire format for output rasters: "float32" (default)
    #: is bit-exact like the reference's outputs; "float16" is the opt-in
    #: fast wire (halves transfer bytes, <=2^-11 relative quantisation,
    #: sigma clamped to 65504 — see ``io.output.GeoTIFFOutput``)
    wire_dtype: str = "float32"
    #: temporal fusion: consecutive single-observation windows run as one
    #: lax.scan program in blocks of up to this many; 1 disables
    scan_window: int = 8
    #: the reference's legacy band-SEQUENTIAL assimilation
    #: (``linear_kf.py:325-425``: per-band Gauss-Newton, posterior ->
    #: next band's prior) instead of the joint multiband update its
    #: shipped drivers use; disables temporal fusion
    band_sequential: bool = False
    #: numeric/structural solver knobs (core.solvers.iterated_solve):
    #: e.g. ``{"relaxation": 0.7}`` for damped Gauss-Newton.  Drivers
    #: resolve this through :meth:`resolved_solver_options`, which
    #: applies the PRODUCTION DEFAULTS: ``use_pallas`` (the fused
    #: VMEM-resident solve kernel) defaults ON for parity-tested
    #: operators (``PALLAS_PARITY_TESTED``) once a healthy-window bench
    #: artifact carries both device rows with the fused kernel faster
    #: (``pallas_default_ready`` — the ROADMAP gate).  Explicit
    #: ``{"use_pallas": False}`` always opts out; operators advertising
    #: ``inkernel_linearize`` additionally run the whole Gauss-Newton
    #: loop inside the kernel (opt-out: ``{"inkernel_linearize":
    #: False}``).
    solver_options: Optional[dict] = None
    #: folder for per-timestep state checkpoints (packed-triangle .npz,
    #: prefixed per chunk).  A restarted run resumes each unfinished chunk
    #: from its latest complete checkpoint instead of its first date —
    #: mid-chunk crash recovery on top of the scheduler's whole-chunk
    #: ``.done`` markers.  ``extra["checkpoint_shards"]`` splits each
    #: checkpoint's pixel axis across that many files.
    checkpoint_folder: Optional[str] = None
    #: save a checkpoint at most every N grid windows (the run's last
    #: window always saves); 1 = every window (reference-faithful), larger
    #: values trade resume granularity for less write traffic on the
    #: annual-chain critical path
    checkpoint_every_n: int = 1
    #: telemetry export directory (the drivers' ``--telemetry-dir``):
    #: the structured event log streams to ``events.jsonl`` during the
    #: run, ``metrics.prom`` / ``metrics.json`` snapshots land at run
    #: end.  None = metrics stay in-memory only (zero files).
    telemetry_dir: Optional[str] = None
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def n_params(self) -> int:
        return len(self.parameter_list)

    def time_grid(self) -> List[datetime.datetime]:
        """The assimilation time grid (the reference builds these with
        explicit loops, ``kafka_test_S2.py:190-193``)."""
        out = []
        t = self.start
        while t <= self.end:
            out.append(t)
            t = t + datetime.timedelta(days=self.step_days)
        return out

    def make_operator(self):
        return _operator_registry()[self.operator](self)

    def make_propagator(self):
        return PROPAGATORS[self.propagator]

    def make_prior(self):
        return _named_prior(self.prior, self)

    def make_initial_prior(self):
        """The prior providing x0/P0^-1: ``initial_prior`` if set, else
        ``prior``."""
        return _named_prior(self.initial_prior or self.prior, self)

    def resolved_solver_options(self) -> Optional[dict]:
        """``solver_options`` with the production defaults applied.

        ``use_pallas`` defaults True for operators in
        ``PALLAS_PARITY_TESTED`` when ``pallas_default_ready()`` holds
        (a healthy-window bench artifact carries both device rows, fused
        faster — the ROADMAP gate); an explicit ``use_pallas`` value in
        ``solver_options`` — notably ``False``, the opt-out — always
        wins.  Returns None when nothing resolves (the engine treats
        None and {} identically).
        """
        opts = dict(self.solver_options or {})
        if (
            "use_pallas" not in opts
            and self.operator in PALLAS_PARITY_TESTED
            and pallas_default_ready()
        ):
            opts["use_pallas"] = True
        return opts or None

    def make_observations(self, operator, state_geo=None, aux_builder=None):
        """Build the observation source named by ``observations``.

        ``state_geo`` — ``(geotransform, crs)`` of the (chunk) state grid;
        required by grid-warping readers (sentinel2).  ``aux_builder`` is a
        runtime callable (not serialisable, so not a config field);
        serialisable reader knobs live in ``extra`` (``period``,
        ``relative_uncertainty``).
        """
        if self.observations == "sentinel2":
            from ..io.sentinel2 import Sentinel2Observations

            return Sentinel2Observations(
                self.data_folder, operator, state_geo,
                aux_builder=aux_builder,
                relative_uncertainty=self.extra.get(
                    "relative_uncertainty", 0.05
                ),
            )
        if self.observations == "bhr":
            from ..io.modis import BHRObservations

            return BHRObservations(
                self.data_folder, operator,
                start_time=self.start, end_time=self.end,
                period=self.extra.get("period", 16),
            )
        if self.observations == "mod09":
            from ..io.mod09 import MOD09Observations

            return MOD09Observations(
                self.data_folder, operator,
                start_time=self.start, end_time=self.end,
            )
        if self.observations == "synergy":
            from ..io.modis import SynergyKernels

            return SynergyKernels(
                self.data_folder, operator,
                start_time=self.start, end_time=self.end,
            )
        if self.observations == "sentinel1":
            from ..io.sentinel1 import S1Observations

            return S1Observations(
                self.data_folder, state_geo, operator=operator,
                relative_uncertainty=self.extra.get(
                    "relative_uncertainty", 0.05
                ),
                # ENL speckle statistics: a number, "auto" (per-scene
                # estimate), or None (file attribute / 5% placeholder).
                enl=self.extra.get("s1_enl"),
                noise_floor=self.extra.get("s1_noise_floor", 0.0),
            )
        if self.observations == "joint":
            # Multi-sensor S2 optical + S1 SAR on the shared 11-parameter
            # joint state: data_folder is the S2 granule tree,
            # extra["s1_folder"] the S1 NetCDF folder.  ``operator`` (the
            # config's named operator, normally "prosail_joint") serves the
            # S2 dates; the WCM joint operator serves the S1 dates.
            from ..io.multi import CompositeObservations
            from ..io.sentinel1 import S1Observations
            from ..io.sentinel2 import Sentinel2Observations
            from ..obsops.joint import WCMJointOperator

            s2 = Sentinel2Observations(
                self.data_folder, operator, state_geo,
                aux_builder=aux_builder,
                relative_uncertainty=self.extra.get(
                    "relative_uncertainty", 0.05
                ),
            )
            # ONE WCM instance per config: the jitted solver is keyed on
            # the operator's bound linearize, so a fresh instance per
            # chunk would recompile the S1 program every chunk.
            if not hasattr(self, "_wcm_joint_op"):
                self._wcm_joint_op = WCMJointOperator()
            s1 = S1Observations(
                self.extra["s1_folder"], state_geo,
                operator=self._wcm_joint_op,
                relative_uncertainty=self.extra.get(
                    "s1_relative_uncertainty", 0.05
                ),
                enl=self.extra.get("s1_enl"),
                noise_floor=self.extra.get("s1_noise_floor", 0.0),
            )
            return CompositeObservations([s2, s1])
        raise KeyError(
            f"no observation-source factory for {self.observations!r}"
        )

    # -- (de)serialisation ------------------------------------------------

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["start"] = self.start.isoformat()
        d["end"] = self.end.isoformat()
        d["parameter_list"] = list(self.parameter_list)
        d["chunk_size"] = list(self.chunk_size)
        return json.dumps(d, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "RunConfig":
        d = json.loads(text)
        d["start"] = datetime.datetime.fromisoformat(d["start"])
        d["end"] = datetime.datetime.fromisoformat(d["end"])
        d["chunk_size"] = tuple(d.get("chunk_size", (128, 128)))
        return cls(**d)

    @classmethod
    def load(cls, path: str) -> "RunConfig":
        with open(path) as f:
            return cls.from_json(f.read())

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())
