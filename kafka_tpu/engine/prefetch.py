"""Double-buffered observation prefetch.

SURVEY.md §2.2 (raster row) requires the input pipeline to feed fixed-shape
pixel blocks into device HBM ahead of the solve, the way the output side
already hides GeoTIFF encoding behind ``GeoTIFFOutput``'s writer thread.
The reference reads every band synchronously inside the time loop
(``/root/reference/kafka/linear_kf.py:225-227`` — per band *and* per date,
GDAL warp on the critical path); here a single worker thread walks the
run's observation dates in order, performs the full host-side read/decode/
warp/gather for date t+1 (including the ``jnp.asarray`` device upload the
readers already do), and parks the result in a bounded queue while the
device solves date t.

The assimilation order is fully known before the loop starts (the time
grid windows the observation dates deterministically), so prefetching is a
straight pipeline, not speculation.  Queue depth 2 = classic double
buffering; the worker blocks when the buffer is full, bounding host memory
at ``depth`` gathered dates.
"""

from __future__ import annotations

import datetime
import logging
import queue
import threading
from typing import List, Optional, Sequence

from .protocols import DateObservation, ObservationSource
from .state import PixelGather

LOG = logging.getLogger(__name__)

_SENTINEL_ERROR = object()


class ObservationPrefetcher:
    """Reads ``dates`` from ``source`` on a worker thread, in order.

    ``get(date)`` returns the prefetched ``DateObservation`` for the next
    date in sequence — callers must consume dates in the order given
    (the filter's time loop does).  Worker exceptions re-raise in the
    caller at the ``get`` for the failing date.
    """

    def __init__(
        self,
        source: ObservationSource,
        gather: PixelGather,
        dates: Sequence[datetime.datetime],
        depth: int = 2,
        transform=None,
    ):
        self._source = source
        self._gather = gather
        # Optional post-read hook run ON THE WORKER thread (e.g. the
        # engine's mesh commit, ``KalmanFilter._shard_obs``) so the
        # device upload/reshard overlaps the previous date's solve too.
        self._transform = transform
        self._dates: List[datetime.datetime] = list(dates)
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, name="obs-prefetch", daemon=True
        )
        self._thread.start()

    def _worker(self) -> None:
        for date in self._dates:
            if self._stopped.is_set():
                return
            try:
                obs = self._source.get_observations(date, self._gather)
                if self._transform is not None:
                    obs = self._transform(obs)
            except BaseException as exc:  # re-raised at the caller's get()
                self._queue.put((_SENTINEL_ERROR, exc))
                return
            self._queue.put((date, obs))

    def get(self, date: datetime.datetime) -> DateObservation:
        got, obs = self._queue.get()
        if got is _SENTINEL_ERROR:
            raise obs
        if got != date:
            # Out-of-order consumption would silently assimilate the wrong
            # acquisition; fail loudly instead.
            raise RuntimeError(
                f"prefetch order violation: requested {date}, queued {got}"
            )
        return obs

    def close(self) -> None:
        """Stop the worker; safe to call at any point (e.g. early abort)."""
        self._stopped.set()
        # Unblock a worker waiting on a full queue.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # A read longer than the join timeout is still in flight; it
            # holds file handles / host memory until it finishes.
            LOG.warning(
                "observation prefetch worker still running after close() "
                "(a read is in flight); it will exit after the current date"
            )


def planned_observation_dates(
    time_grid, observation_dates
) -> List[datetime.datetime]:
    """The exact, ordered sequence of acquisition dates ``KalmanFilter.run``
    will assimilate for this grid — the prefetcher's work list."""
    from ..core.time_grid import iterate_time_grid

    out: List[datetime.datetime] = []
    for _, locate_times, _ in iterate_time_grid(
        time_grid, observation_dates, verbose=False
    ):
        out.extend(locate_times)
    return out
