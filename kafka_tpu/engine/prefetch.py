"""Multi-worker observation prefetch with ordered delivery.

SURVEY.md §2.2 (raster row) requires the input pipeline to feed fixed-shape
pixel blocks into device HBM ahead of the solve, the way the output side
already hides GeoTIFF encoding behind ``GeoTIFFOutput``'s writer thread.
The reference reads every band synchronously inside the time loop
(``/root/reference/kafka/linear_kf.py:225-227`` — per band *and* per date,
GDAL warp on the critical path); here ``workers`` threads walk the run's
observation dates, each performing the full host-side read/decode/warp/
gather for its claimed date (plus the optional ``transform`` — e.g. the
engine's mesh commit), and results are delivered strictly IN ORDER however
the reads complete.

In-flight results are bounded by ``depth`` (a semaphore slot per undelivered
date), so host memory holds at most ``max(depth, workers)`` gathered dates.
``workers=1`` reproduces the round-2 single-worker pipeline exactly; more
workers overlap multiple dates' I/O — the win on hosts with several cores,
where decode (GIL-free C++ codec) and warp parallelise across dates on top
of the per-band pool inside each reader.
"""

from __future__ import annotations

import datetime
import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .protocols import DateObservation, ObservationSource
from .state import PixelGather
from ..resilience import (
    DEFAULT_READ_POLICY,
    TRANSIENT,
    DegradedDateError,
    RetryPolicy,
    classify_failure,
    faults,
)
from ..telemetry import get_registry, stopwatch, tracing

LOG = logging.getLogger(__name__)


class ObservationPrefetcher:
    """Reads ``dates`` from ``source`` on worker threads.

    ``get(date)`` returns the prefetched ``DateObservation`` for the next
    date in sequence — callers must consume dates in the order given
    (the filter's time loop does).

    Failure semantics (BASELINE.md "Fault tolerance"): a read that fails
    with a TRANSIENT-class error is retried on the worker thread under
    ``retry_policy``; if retries are exhausted the date is delivered
    *degraded* — ``get`` raises :class:`DegradedDateError` so the engine
    can consume it as a missing observation — and the workers keep
    claiming later dates.  A POISON/FATAL-class error keeps today's
    fail-fast behaviour: it re-raises in the caller at the ``get`` for
    the failing date, and nothing new is claimed after it (later dates
    already in flight may complete).

    With ``workers > 1`` the source's ``get_observations`` is called
    CONCURRENTLY for different dates — sources must tolerate concurrent
    pure reads (all in-repo sources do; see the threading contract on
    ``ObservationSource``).
    """

    def __init__(
        self,
        source: ObservationSource,
        gather: PixelGather,
        dates: Sequence[datetime.datetime],
        depth: int = 2,
        transform=None,
        workers: int = 1,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self._source = source
        self._gather = gather
        self._policy = retry_policy if retry_policy is not None \
            else DEFAULT_READ_POLICY
        # Optional post-read hook run ON THE WORKER thread (e.g. the
        # engine's mesh commit, ``KalmanFilter._shard_obs``) so the
        # device upload/reshard overlaps the previous date's solve too.
        self._transform = transform
        self._dates: List[datetime.datetime] = list(dates)
        self._workers = max(1, int(workers))
        self._slots = threading.Semaphore(
            max(1, int(depth), self._workers)
        )
        self._cond = threading.Condition()
        #: idx -> ("ok", obs) | ("error", exc)
        self._results: Dict[int, Tuple[str, Any]] = {}
        self._next_claim = 0
        self._next_emit = 0
        self._stopped = threading.Event()
        # Telemetry handles bound once (registry resolved at construction
        # — the engine builds prefetchers after the driver's configure()).
        reg = get_registry()
        self._trace = reg.trace
        # Cross-thread trace propagation: contextvars do NOT flow into new
        # threads, so the constructing thread's context (run/chunk ids) is
        # captured here and re-installed on every worker.
        self._trace_ctx = tracing.current_context()
        self._m_read = reg.histogram(
            "kafka_prefetch_read_seconds",
            "host-side read/decode/warp/gather seconds per date "
            "(includes the optional transform, e.g. the mesh commit)",
        )
        self._m_wait = reg.histogram(
            "kafka_prefetch_wait_seconds",
            "seconds the engine loop blocked waiting for a prefetched "
            "date (0 when the pipeline is ahead)",
        )
        self._m_reads = reg.counter(
            "kafka_prefetch_reads_total",
            "observation dates read by prefetch workers",
        )
        self._m_depth = reg.gauge(
            "kafka_prefetch_queue_depth",
            "prefetched dates buffered and not yet consumed",
        )
        self._threads = [
            threading.Thread(
                target=self._worker, args=(i,),
                name=f"obs-prefetch-{i}", daemon=True,
            )
            for i in range(self._workers)
        ]
        for t in self._threads:
            t.start()

    def _worker(self, worker_index: int) -> None:
        tracing.set_context(self._trace_ctx)
        # One timeline track per worker thread; the single-worker default
        # keeps the canonical "prefetch" lane name.
        tracing.set_lane(
            "prefetch" if worker_index == 0 else f"prefetch-{worker_index}"
        )
        while True:
            self._slots.acquire()
            if self._stopped.is_set():
                return
            with self._cond:
                idx = self._next_claim
                if idx >= len(self._dates):
                    return
                self._next_claim += 1
            date = self._dates[idx]
            sw = stopwatch()

            def read():
                faults.fault_point("prefetch.read_date", date=str(date))
                obs = self._source.get_observations(date, self._gather)
                if self._transform is not None:
                    obs = self._transform(obs)
                return obs

            try:
                item = (
                    "ok",
                    self._policy.call(read, site="prefetch.read_date"),
                )
            except BaseException as exc:  # classified + re-raised at get()
                # Exhausted-transient reads degrade (the engine treats
                # the date as a missing observation); poison/fatal stay
                # fail-fast and abort the run at this date's get().
                if classify_failure(exc) == TRANSIENT:
                    item = ("degraded", exc)
                else:
                    item = ("error", exc)
            if item[0] == "ok":
                t1 = sw.now()
                self._m_read.observe(t1 - sw.t0)
                self._m_reads.inc()
                self._trace.add_span(
                    "prefetch_read", sw.t0, t1, cat="io", date=str(date),
                )
            with self._cond:
                self._results[idx] = item
                self._m_depth.set(len(self._results))
                self._trace.add_counter(
                    "prefetch_queue_depth", len(self._results)
                )
                if item[0] == "error":
                    # Don't claim past a failure: the run is about to
                    # abort at this date's get(); reading further dates
                    # would waste I/O and hold memory.
                    self._next_claim = len(self._dates)
                self._cond.notify_all()
            if item[0] == "error":
                return

    def get(self, date: datetime.datetime) -> DateObservation:
        sw = stopwatch()
        with self._cond:
            idx = self._next_emit
            while idx not in self._results and not self._stopped.is_set():
                self._cond.wait(timeout=0.5)
                # Watchdog: if every worker thread has exited and the
                # awaited index still has no result, no notify is ever
                # coming — fail loudly instead of spinning on the 0.5s
                # wait forever (a worker killed by a fatal error, or a
                # bug that let one exit without posting, used to wedge
                # the engine here).
                if (idx not in self._results
                        and not self._stopped.is_set()
                        and not any(t.is_alive() for t in self._threads)):
                    raise RuntimeError(
                        "prefetch workers died without delivering "
                        f"{date!s}"
                    )
            if idx not in self._results:
                raise RuntimeError("prefetcher closed while waiting")
            kind, payload = self._results.pop(idx)
            self._next_emit += 1
            self._m_depth.set(len(self._results))
            self._trace.add_counter(
                "prefetch_queue_depth", len(self._results)
            )
        self._m_wait.observe(sw.elapsed())
        self._slots.release()
        if kind == "error":
            raise payload
        if self._dates[idx] != date:
            # Out-of-order consumption would silently assimilate the wrong
            # acquisition; fail loudly instead.
            raise RuntimeError(
                f"prefetch order violation: requested {date}, queued "
                f"{self._dates[idx]}"
            )
        if kind == "degraded":
            raise DegradedDateError(date, payload)
        return payload

    def close(self) -> None:
        """Stop the workers; safe to call at any point (early abort)."""
        self._stopped.set()
        with self._cond:
            self._next_claim = len(self._dates)
            self._cond.notify_all()
        # Unblock workers parked on the slot semaphore.
        for _ in self._threads:
            self._slots.release()
        for t in self._threads:
            t.join(timeout=5.0)
        if any(t.is_alive() for t in self._threads):
            # A read longer than the join timeout is still in flight; it
            # holds file handles / host memory until it finishes.
            LOG.warning(
                "observation prefetch worker still running after close() "
                "(a read is in flight); it will exit after the current date"
            )


def planned_observation_dates(
    time_grid, observation_dates
) -> List[datetime.datetime]:
    """The exact, ordered sequence of acquisition dates ``KalmanFilter.run``
    will assimilate for this grid — the prefetcher's work list."""
    from ..core.time_grid import iterate_time_grid

    out: List[datetime.datetime] = []
    for _, locate_times, _ in iterate_time_grid(
        time_grid, observation_dates, verbose=False
    ):
        out.extend(locate_times)
    return out
