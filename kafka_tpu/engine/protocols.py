"""The five injection points, as explicit protocols.

The reference wires ``LinearKalman`` with five pluggable pieces — an
observations object, an output writer, an observation-operator factory, a
state-propagation function, and a prior object
(``/root/reference/kafka/linear_kf.py:59-96``).  This module preserves
exactly those extension points with array-native signatures (SURVEY.md §1:
"the new framework should preserve exactly these five extension points").
"""

from __future__ import annotations

import datetime
from typing import Any, NamedTuple, Optional, Protocol, Sequence, Tuple,\
    runtime_checkable

import jax.numpy as jnp

from ..core.types import BandBatch
from ..obsops.protocol import ObservationModel
from .state import PixelGather


class DateObservation(NamedTuple):
    """Everything needed to assimilate one acquisition date: the stacked
    band observations gathered to the pixel batch, the operator that maps
    state to those bands, and the operator's per-date aux data (angles,
    emulator weights...).  Replaces the reference's per-band
    ``get_band_data`` tuples + pickled emulator
    (``Sentinel2_Observations.py:148-185``)."""

    bands: BandBatch
    operator: ObservationModel
    aux: Any


@runtime_checkable
class ObservationSource(Protocol):
    """Injection point 1 — the observations object.

    ``dates`` lists available acquisitions (reference: ``.dates``,
    ``observations.py:241-249``); ``get_observations`` gathers one date's
    rasters into the fixed pixel batch.

    Threading contract: the filter prefetches observations on a background
    thread by default (``KalmanFilter(prefetch_depth=2)``), so
    ``get_observations`` must be safe to call off the main thread and must
    not mutate state shared with the ``Prior`` or ``OutputWriter`` without
    its own locking.  All in-repo sources are pure reads and comply; a
    source that cannot meet this should be run with ``prefetch_depth=0``
    (synchronous reads, the reference's behaviour)."""

    @property
    def dates(self) -> Sequence[datetime.datetime]: ...

    def get_observations(self, date: datetime.datetime,
                         gather: PixelGather) -> DateObservation: ...


@runtime_checkable
class OutputWriter(Protocol):
    """Injection point 2 — the output sink.  Mirrors
    ``KafkaOutput.dump_data`` (``observations.py:354-394``) with batched
    arrays: ``x`` (n_pad, p) and ``p_inv_diag`` (n_pad, p)."""

    def dump_data(self, timestep: datetime.datetime, x, p_inv_diag,
                  gather: PixelGather, parameter_list: Sequence[str]) -> None:
        ...


@runtime_checkable
class Prior(Protocol):
    """Injection point 5 — the prior object.  Mirrors
    ``prior.process_prior(date, inv_cov=True)``
    (``kafka_test_S2.py:106-118``) in batched layout."""

    def process_prior(self, date: Optional[datetime.datetime],
                      gather: PixelGather) -> Tuple[jnp.ndarray, jnp.ndarray]:
        ...


# Injection points 3 and 4 are plain callables:
#  - the observation operator (an ObservationModel instance, carried inside
#    DateObservation so different dates/sensors can use different operators);
#  - the state propagator, any callable with the propagator contract of
#    kafka_tpu.core.propagators.
