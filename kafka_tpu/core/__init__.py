"""Pure-JAX batched kernels: the math core of the framework."""

from .linalg import (
    batched_diag,
    batched_diagonal,
    solve_batched,
    solve_spd_batched,
    spd_inverse_batched,
)
from .propagators import (
    PixelPrior,
    advance,
    blend_gaussians,
    blend_prior,
    broadcast_prior,
    make_no_propagation,
    make_prior_reset_propagator,
    no_propagation,
    propagate_information_filter,
    propagate_information_filter_approx,
    propagate_information_filter_lai,
    propagate_standard_kalman,
    tip_prior,
)
from .solvers import (
    CONVERGENCE_TOL,
    MAX_ITERATIONS,
    MIN_ITERATIONS,
    assimilate_date_jit,
    build_normal_equations,
    iterated_solve,
    kalman_update,
    linear_solve,
)
from .hessian import hessian_correction
from .time_grid import iterate_time_grid
from .types import (
    BandBatch,
    GaussianState,
    Linearization,
    SolveDiagnostics,
    block_diag_to_batched,
    flat_to_pixel_major,
    pixel_major_to_flat,
)
