"""Batched small-matrix linear algebra for the per-pixel solves.

The reference's dominant kernel is a SuperLU factorization of a sparse
block-diagonal system of ``n_pix`` independent ``p x p`` SPD blocks
(``/root/reference/kafka/inference/solvers.py:125-134``; block-diagonality is
guaranteed because every Jacobian row only touches its own pixel's parameters,
``inference/utils.py:193-215``).  On TPU this is a batched dense Cholesky
factorization + triangular solve over the pixel batch axis — no sparse
machinery, no host BLAS, fully fused by XLA and shardable over a mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Below this block size the unrolled elementwise Cholesky beats XLA's
# blocked lax.linalg.cholesky by a wide margin on TPU (the state is p=7 or
# p=10; measured ~50x on p=7, 2^19 pixels — the blocked algorithm can't
# tile tiny matrices onto the MXU, while the unrolled form is pure VPU
# work over the huge batch axis).
UNROLL_MAX_P = 16


def solve_chol_vectors(l, b_vectors):
    """Forward+back substitution against an unrolled packed factor.

    ``b_vectors`` is a list of p batch vectors (any common shape); returns
    the solution as a list of p batch vectors.  Layout-agnostic on
    purpose: the XLA path feeds ``(n,)`` batch vectors and the Pallas
    kernel feeds ``(block,)`` lane vectors — one implementation of the
    substitution for both."""
    p = len(l)
    # L y = b
    y = [None] * p
    for i in range(p):
        s = b_vectors[i]
        for k in range(i):
            s = s - l[i][k] * y[k]
        y[i] = s / l[i][i]
    # L^T x = y
    x = [None] * p
    for i in reversed(range(p)):
        s = y[i]
        for k in range(i + 1, p):
            s = s - l[k][i] * x[k]
        x[i] = s / l[i][i]
    return x


def _solve_chol_unrolled(l, b: jnp.ndarray) -> jnp.ndarray:
    """Forward+back substitution against an unrolled factor; ``b`` (..., p)."""
    p = len(l)
    x = solve_chol_vectors(l, [b[..., i] for i in range(p)])
    return jnp.stack(x, axis=-1)


def cholesky_packed(a_packed):
    """Cholesky of a batch of SPD blocks given as a packed symmetric
    list-of-lists ``a_packed[i][j]`` of (...,) batch vectors (j <= i filled;
    the representation produced by
    ``core.solvers.build_normal_equations_packed``).  Returns the lower
    factor in the same packed form."""
    p = len(a_packed)
    l = [[None] * p for _ in range(p)]
    for j in range(p):
        d = a_packed[j][j]
        for k in range(j):
            d = d - l[j][k] * l[j][k]
        ljj = jnp.sqrt(d)
        l[j][j] = ljj
        inv = 1.0 / ljj
        for i in range(j + 1, p):
            s = a_packed[i][j]
            for k in range(j):
                s = s - l[i][k] * l[j][k]
            l[i][j] = s * inv
    return l


def solve_spd_packed(a_packed, b: jnp.ndarray) -> jnp.ndarray:
    """Solve against a packed symmetric batch (``b``: (..., p)).

    The packed path never materialises the (..., p, p) tensor, so the whole
    factor+solve compiles to a few hundred fused elementwise VPU ops over
    the batch axis — ~40x faster than building the dense blocks and
    gathering their entries back out (measured on p=7, 2^19 pixels)."""
    return _solve_chol_unrolled(cholesky_packed(a_packed), b)


def pack_symmetric(a: jnp.ndarray):
    """(..., p, p) dense -> packed list-of-lists view (lower + mirrored)."""
    p = a.shape[-1]
    out = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            out[i][j] = out[j][i] = a[..., i, j]
    return out


def unpack_symmetric(a_packed) -> jnp.ndarray:
    """Packed list-of-lists -> dense (..., p, p)."""
    p = len(a_packed)
    rows = [
        jnp.stack([a_packed[i][j] for j in range(p)], axis=-1)
        for i in range(p)
    ]
    return jnp.stack(rows, axis=-2)


def solve_spd_batched(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Solve ``a[i] @ x[i] = b[i]`` for a batch of SPD matrices.

    Parameters
    ----------
    a : (..., p, p) SPD matrices (the per-pixel information matrices).
    b : (..., p) right-hand sides.

    Replaces the reference's ``sp.linalg.splu(A).solve(b)``
    (``solvers.py:133-134``) exactly on SPD input, at ~p^3/3 flops per
    pixel.  Small blocks (every real state: p=7 TIP, p=10 PROSAIL) use the
    unrolled elementwise Cholesky; large ones fall back to the blocked
    ``lax.linalg.cholesky``.
    """
    if a.shape[-1] <= UNROLL_MAX_P:
        return _solve_chol_unrolled(cholesky_packed(pack_symmetric(a)), b)
    chol = jax.lax.linalg.cholesky(a)
    y = jax.lax.linalg.triangular_solve(
        chol, b[..., None], left_side=True, lower=True
    )
    x = jax.lax.linalg.triangular_solve(
        chol, y, left_side=True, lower=True, transpose_a=True
    )
    return x[..., 0]


def solve_batched(a: jnp.ndarray, b: jnp.ndarray,
                  block: int = None) -> jnp.ndarray:
    """General batched solve (LU) for non-symmetric per-pixel systems.

    Needed by the exact information-filter propagator, which solves
    ``(I + P_inv Q) X = P_inv`` where the left side is not symmetric
    (``kf_tools.py:240-242``).

    ``block`` bounds the batch slice handed to XLA's LU custom call at a
    time (via ``lax.map``): the pivoted-LU lowering allocates HLO temps
    of several times the operand size, which at millions of pixels OOMs
    the chip — especially inside a fused temporal scan where the rest of
    the program's buffers are live too.  Padding blocks are identity
    systems, so every slice stays non-singular.
    """
    n = a.shape[0]
    if block is None or n <= block:
        return jnp.linalg.solve(a, b)
    nb = -(-n // block)
    pad = nb * block - n
    if pad:
        eye = jnp.broadcast_to(
            jnp.eye(a.shape[-1], dtype=a.dtype), (pad,) + a.shape[1:]
        )
        a = jnp.concatenate([a, eye], axis=0)
        b = jnp.concatenate(
            [b, jnp.zeros((pad,) + b.shape[1:], b.dtype)], axis=0
        )
    a = a.reshape((nb, block) + a.shape[1:])
    b = b.reshape((nb, block) + b.shape[1:])
    out = jax.lax.map(lambda ab: jnp.linalg.solve(ab[0], ab[1]), (a, b))
    return out.reshape((nb * block,) + out.shape[2:])[:n]


def spd_inverse_batched(a: jnp.ndarray) -> jnp.ndarray:
    """Batched SPD inverse via Cholesky (used to turn p_inv into p and back
    for the covariance-form propagator, ``kf_tools.py:203-205``)."""
    p = a.shape[-1]
    if p <= UNROLL_MAX_P:
        l = cholesky_packed(pack_symmetric(a))
        eye = jnp.eye(p, dtype=a.dtype)
        cols = [
            _solve_chol_unrolled(
                l, jnp.broadcast_to(eye[j], a.shape[:-2] + (p,))
            )
            for j in range(p)
        ]
        return jnp.stack(cols, axis=-1)
    chol = jax.lax.linalg.cholesky(a)
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    y = jax.lax.linalg.triangular_solve(chol, eye, left_side=True, lower=True)
    return jax.lax.linalg.triangular_solve(
        chol, y, left_side=True, lower=True, transpose_a=True
    )


def batched_diag(d: jnp.ndarray) -> jnp.ndarray:
    """``(..., p)`` diagonals -> ``(..., p, p)`` diagonal matrices."""
    return d[..., None] * jnp.eye(d.shape[-1], dtype=d.dtype)


def batched_diagonal(a: jnp.ndarray) -> jnp.ndarray:
    """``(..., p, p)`` -> ``(..., p)`` main diagonals."""
    return jnp.diagonal(a, axis1=-2, axis2=-1)
