"""Batched small-matrix linear algebra for the per-pixel solves.

The reference's dominant kernel is a SuperLU factorization of a sparse
block-diagonal system of ``n_pix`` independent ``p x p`` SPD blocks
(``/root/reference/kafka/inference/solvers.py:125-134``; block-diagonality is
guaranteed because every Jacobian row only touches its own pixel's parameters,
``inference/utils.py:193-215``).  On TPU this is a batched dense Cholesky
factorization + triangular solve over the pixel batch axis — no sparse
machinery, no host BLAS, fully fused by XLA and shardable over a mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def solve_spd_batched(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Solve ``a[i] @ x[i] = b[i]`` for a batch of SPD matrices.

    Parameters
    ----------
    a : (..., p, p) SPD matrices (the per-pixel information matrices).
    b : (..., p) right-hand sides.

    Uses batched Cholesky (``lax.linalg.cholesky``) + two triangular solves.
    Replaces the reference's ``sp.linalg.splu(A).solve(b)``
    (``solvers.py:133-134``) exactly on SPD input, at ~p^3/3 flops per pixel.
    """
    chol = jax.lax.linalg.cholesky(a)
    y = jax.lax.linalg.triangular_solve(
        chol, b[..., None], left_side=True, lower=True
    )
    x = jax.lax.linalg.triangular_solve(
        chol, y, left_side=True, lower=True, transpose_a=True
    )
    return x[..., 0]


def solve_batched(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """General batched solve (LU) for non-symmetric per-pixel systems.

    Needed by the exact information-filter propagator, which solves
    ``(I + P_inv Q) X = P_inv`` where the left side is not symmetric
    (``kf_tools.py:240-242``).
    """
    return jnp.linalg.solve(a, b)


def spd_inverse_batched(a: jnp.ndarray) -> jnp.ndarray:
    """Batched SPD inverse via Cholesky (used to turn p_inv into p and back
    for the covariance-form propagator, ``kf_tools.py:203-205``)."""
    chol = jax.lax.linalg.cholesky(a)
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    y = jax.lax.linalg.triangular_solve(chol, eye, left_side=True, lower=True)
    return jax.lax.linalg.triangular_solve(
        chol, y, left_side=True, lower=True, transpose_a=True
    )


def batched_diag(d: jnp.ndarray) -> jnp.ndarray:
    """``(..., p)`` diagonals -> ``(..., p, p)`` diagonal matrices."""
    return d[..., None] * jnp.eye(d.shape[-1], dtype=d.dtype)


def batched_diagonal(a: jnp.ndarray) -> jnp.ndarray:
    """``(..., p, p)`` -> ``(..., p)`` main diagonals."""
    return jnp.diagonal(a, axis1=-2, axis2=-1)
