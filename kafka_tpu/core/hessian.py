"""Second-order (Hessian) correction of the posterior information matrix.

The Gauss-Newton Hessian ``J^T R^-1 J + P_f^-1`` drops the term
``sum_k r_inv_k * innov_k * d2H_k/dx2``.  The reference adds it back per
pixel using the GP emulator's ``.hessian`` method scattered through the
band->state mapper (``/root/reference/kafka/inference/kf_tools.py:26-72``)
and subtracts it from the returned Hessian (``linear_kf.py:412-416``).

Here the observation operator is a differentiable JAX function, so the
second derivative comes from ``jax.hessian`` of the per-pixel forward model —
no hand-coded Hessians, and the whole correction is one vmap over pixels.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def hessian_correction(
    forward_per_pixel: Callable[[jnp.ndarray], jnp.ndarray],
    x_analysis: jnp.ndarray,
    r_inv: jnp.ndarray,
    innovations: jnp.ndarray,
    obs_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Batched second-order correction term.

    Parameters
    ----------
    forward_per_pixel : maps one pixel's state ``(p,)`` to its per-band
        forward-modelled observations ``(n_bands,)``.
    x_analysis : (n_pix, p) converged analysis state.
    r_inv : (n_bands, n_pix) inverse observation variances.
    innovations : (n_bands, n_pix) ``y - H0`` innovations
        (``solvers.py:139-142`` convention).
    obs_mask : (n_bands, n_pix) validity mask — masked pixels contribute a
        zero block, as in ``kf_tools.py:49-52``.

    Returns
    -------
    (n_pix, p, p) correction; subtract it from the analysis information
    matrix (``linear_kf.py:416``: ``P_analysis_inverse - P_correction``).
    """

    per_pixel_hessian = jax.vmap(jax.hessian(forward_per_pixel))
    ddh = per_pixel_hessian(x_analysis)  # (n_pix, n_bands, p, p)
    weight = (r_inv * innovations * obs_mask).T  # (n_pix, n_bands)
    return jnp.einsum("nb,nbpq->npq", weight, ddh)
