"""Core array containers for the TPU-native assimilation engine.

Design note
-----------
The reference engine (KaFKA) represents the state of an ``ny x nx`` raster as a
single flat, pixel-major-interleaved vector ``x = [pix0 params | pix1 params |
...]`` and carries a giant sparse block-diagonal inverse covariance (see
``/root/reference/kafka/inference/solvers.py:60-69`` and the slicing patterns
``x[ii::n_params]`` in ``observations.py:375``).  On TPU the idiomatic layout
is *batched dense*: the state is ``(n_pix, p)`` and the information matrix is
``(n_pix, p, p)`` — XLA then maps the per-pixel linear algebra onto the
MXU/VPU with the pixel axis as the (shardable) batch axis.  ``StateVector``
provides lossless converters between the two layouts so outputs match the
reference bit-for-bit in ordering.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax.numpy as jnp


class BandBatch(NamedTuple):
    """All observations of one date, batched over bands.

    Mirrors the per-band namedtuples of the reference readers
    (``S2MSIdata``/``S1data``/``BHR_data``: observations, uncertainty, mask,
    metadata, emulator — ``Sentinel2_Observations.py:80-81``) but stacked to
    fixed shapes for jit:

    - ``y``:      ``(n_bands, n_pix)`` observed values (gathered to the state
                  mask's pixel list, padded to a fixed pixel count).
    - ``r_inv``:  ``(n_bands, n_pix)`` *inverse variance* of each observation.
      The reference stores uncertainty as inverse variance everywhere
      (``Sentinel2_Observations.py:174-179``) and the solver uses it directly
      as R^-1.  Masked / missing observations carry ``r_inv == 0`` which
      removes them from the update exactly (unlike the reference's ``y=0``
      trick, ``solvers.py:53`` — same posterior, no inf rows).
    - ``mask``:   ``(n_bands, n_pix)`` bool, True where the observation is
                  valid.  Redundant with ``r_inv > 0`` but kept for
                  diagnostics and innovation reporting.
    """

    y: jnp.ndarray
    r_inv: jnp.ndarray
    mask: jnp.ndarray


class GaussianState(NamedTuple):
    """Batched per-pixel Gaussian belief in information form.

    - ``x``:     ``(n_pix, p)`` mean.
    - ``p_inv``: ``(n_pix, p, p)`` inverse covariance (information matrix).
      The reference never forms the posterior covariance; it carries the
      Hessian ``A`` as ``P_analysis_inverse`` (``solvers.py:78``) and
      consumers only read its diagonal (``observations.py:393``).  We keep
      the same contract.
    - ``p``:     optional ``(n_pix, p, p)`` covariance for the
      covariance-form Kalman propagator (``kf_tools.py:203-205``); ``None``
      in information-filter mode.
    """

    x: jnp.ndarray
    p_inv: Optional[jnp.ndarray]
    p: Optional[jnp.ndarray] = None


class Linearization(NamedTuple):
    """Observation operator linearized around a state point.

    - ``h0``:  ``(n_bands, n_pix)`` forward-modelled observation at the
               linearization point.
    - ``jac``: ``(n_bands, n_pix, p)`` Jacobian d h0 / d x.

    Equivalent of the reference's ``(H0, H_matrix)`` pair where ``H_matrix``
    is an ``(n_pix, p*n_pix)`` sparse matrix whose row i only touches pixel
    i's parameters (``inference/utils.py:193-215``) — i.e. exactly a batched
    ``(n_pix, p)`` Jacobian per band.
    """

    h0: jnp.ndarray
    jac: jnp.ndarray


class SolveDiagnostics(NamedTuple):
    """Extras returned by the iterated solve.

    ``innovations`` follows the reference multiband convention
    ``y_orig - H0`` (``solvers.py:139-142``); ``fwd_modelled`` is
    ``J (x_a - x_f) + H0`` (``solvers.py:70-71``); ``n_iterations`` and
    ``convergence_norm`` mirror the loop diagnostics of
    ``linear_kf.py:293-296``.

    The trailing telemetry scalars are computed inside the jitted solve so
    they ride the engine's one packed diagnostic device->host read per
    window (``telemetry.device.fetch_scalars``) instead of costing extra
    syncs.
    """

    innovations: jnp.ndarray
    fwd_modelled: jnp.ndarray
    n_iterations: jnp.ndarray
    convergence_norm: jnp.ndarray
    #: (n_pix,) bool — which pixels froze at a converged fixed point;
    #: only populated by ``per_pixel_convergence`` solves (else None).
    converged_mask: Any = None
    #: (n_bands,) mean innovation chi^2 per band over that band's valid
    #: pixels: sum(innov^2 * r_inv) / count(mask) — ~1 when the assumed
    #: observation uncertainty matches the residuals.
    chi2_per_band: Any = None
    #: () int32 — state entries sitting exactly at a ``state_bounds``
    #: limit on the final iterate, counted over observed pixels only
    #: (padding/unobserved pixels excluded); 0 when no bounds were given.
    clipped_count: Any = None
    #: () int32 — masked-out (NaN/nodata) observation entries across all
    #: bands, INCLUDING padding pixels (every band's mask is False there);
    #: consumers with a PixelGather subtract n_bands * (n_pad - n_valid).
    nodata_count: Any = None
    #: (n_pix,) int32 — per-pixel solve-health QA bitmask
    #: (``core.solver_health``: converged / cap-bailout / damped-recovered
    #: / quarantined / nodata).  None when the solve ran a mode without
    #: health tracking (per_pixel_convergence, the large-p dense
    #: fallback, or the single-shot linear solve).
    health_verdicts: Any = None
    #: () int32 — observed pixels still moving (per-pixel step >= tol)
    #: when the loop hit the iteration cap: the reference's silent
    #: bailout, counted.
    cap_bailout_count: Any = None
    #: () int32 — pixels that went bad mid-loop, took the LM damping
    #: escalation, and finished healthy.
    damped_recovered_count: Any = None
    #: () int32 — pixels still bad after escalation, served as forecast
    #: with deflated information (QA_QUARANTINED).
    quarantined_count: Any = None
    #: () int32 — observed pixels whose raw Gauss-Newton step went
    #: non-finite at least once (a subset of the escalated pixels; the
    #: complement broke down at the Cholesky instead).
    nonfinite_count: Any = None
    #: (p,) int32 — per-parameter count of observed pixels clipped to a
    #: ``state_bounds`` limit on EVERY iteration (bound saturation: a
    #: pinned pixel is a masked divergence).  Zeros without bounds.
    clip_saturated_count: Any = None


def flat_to_pixel_major(x_flat: jnp.ndarray, n_params: int) -> jnp.ndarray:
    """``(n_pix*p,)`` interleaved reference layout -> ``(n_pix, p)``."""
    return x_flat.reshape(-1, n_params)


def pixel_major_to_flat(x: jnp.ndarray) -> jnp.ndarray:
    """``(n_pix, p)`` -> the reference's interleaved flat layout."""
    return x.reshape(-1)


def block_diag_to_batched(p_mat: Any, n_params: int) -> jnp.ndarray:
    """Dense/scipy block-diagonal ``(n_pix*p, n_pix*p)`` -> ``(n_pix, p, p)``.

    Host-side helper for interop tests against the reference layout.
    """
    import numpy as np

    if hasattr(p_mat, "toarray"):
        p_mat = p_mat.toarray()
    p_mat = np.asarray(p_mat)
    n = p_mat.shape[0] // n_params
    out = np.empty((n, n_params, n_params), dtype=p_mat.dtype)
    for i in range(n):
        sl = slice(i * n_params, (i + 1) * n_params)
        out[i] = p_mat[sl, sl]
    return jnp.asarray(out)
