"""Pallas TPU kernel for the hot solve: fused packed Cholesky + substitution.

The per-date update factorises ``n_pix`` independent p x p SPD systems.
The default path (``linalg.solve_spd_packed``) expresses this as a few
hundred fused elementwise VPU ops that XLA schedules; this module provides
the same computation as ONE hand-written Pallas kernel: pixels ride the
lane axis, the ``p(p+1)/2`` packed coefficients ride sublanes, and the
whole factor+solve for a block of pixels happens VMEM-resident in a single
kernel launch — no intermediate HBM round-trips between the ~300 fused ops.

Opt-in via ``solver_options={"use_pallas": True}`` (structural, jit-static)
— the XLA path remains the default; a parity test pins both to the same
results.  Layout contract: coefficient ``(i, j)`` with ``j <= i`` of the
lower triangle lives at row ``i (i + 1) / 2 + j``, matching
``linalg.cholesky_packed``'s list-of-lists ordering.

Two generations of kernel live here.  ``solve_rows`` (factor+solve only)
was the first: measured 21.3 ms/solve vs 19.4 ms for the XLA path on the
full GN loop — XLA's automatic fusion already near-optimal for that
slice, so it stayed opt-in.  ``_fused_update_rows`` fuses the WHOLE
per-date update (assembly + factor + solve + innovations) into one
launch; on a real v5e (TIP, 2^19 px, full 2-iteration GN loop,
queued-slope timing) it takes the solve from 6.45 ms to 3.80 ms (~1.7x).
The single measured story lives in BASELINE.md's "Roofline" section.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .linalg import cholesky_packed, solve_chol_vectors


def tri_rows(p: int) -> int:
    return p * (p + 1) // 2


def _solve_kernel(p: int, a_ref, b_ref, x_ref):
    """One pixel block: Cholesky factor + forward/back substitution.

    Reuses the SAME unrolled helpers as the XLA path
    (``linalg.cholesky_packed`` / ``solve_chol_vectors`` — batch-axis
    agnostic jnp arithmetic, which lowers inside a Pallas kernel), so
    there is exactly one implementation of the numerically delicate
    factorisation to maintain.  Everything stays in (block,)-lane row
    vectors: no in-kernel transpose (a (block, p) relayout pads p up to
    the 128-lane tile and overflows VMEM)."""

    def idx(i, j):
        return i * (i + 1) // 2 + j

    a_pk = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            a_pk[i][j] = a_pk[j][i] = a_ref[idx(i, j), :]
    l = cholesky_packed(a_pk)
    x = solve_chol_vectors(l, [b_ref[i, :] for i in range(p)])
    for i in range(p):
        x_ref[i, :] = x[i]


@functools.partial(jax.jit, static_argnums=(2, 3))
def solve_rows(a_rows: jnp.ndarray, b_rows: jnp.ndarray,
               block: int = 1024, interpret: bool = False) -> jnp.ndarray:
    """Solve the packed batch in row layout.

    ``a_rows``: (p(p+1)/2, n) lower-triangle coefficients, ``b_rows``:
    (p, n); returns x (p, n).  ``block`` is a maximum: the actual block
    is its gcd with ``n`` so every pixel count divides cleanly (engine
    batches are multiples of 128/256, giving full-width blocks).
    """
    n_coeff, n = a_rows.shape
    p = b_rows.shape[0]
    if tri_rows(p) != n_coeff:
        raise ValueError(f"{n_coeff} coefficient rows for p={p}")
    block = math.gcd(n, min(block, n))
    return pl.pallas_call(
        functools.partial(_solve_kernel, p),
        out_shape=jax.ShapeDtypeStruct((p, n), jnp.float32),
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((n_coeff, block), lambda i: (0, i)),
            pl.BlockSpec((p, block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((p, block), lambda i: (0, i)),
        interpret=interpret,
    )(a_rows.astype(jnp.float32), b_rows.astype(jnp.float32))


def _fused_update_kernel(p: int, n_bands: int, jac_ref, h0_ref, y_ref,
                         w_ref, m_ref, xl_ref, xf_ref, pf_ref,
                         x_ref, a_ref, inn_ref):
    """One pixel block of the WHOLE per-date update, VMEM-resident:

        y~   = where(mask, y + J x_lin - H0, 0)
        A    = sum_b w_b J_b J_b^T + P_f^-1        (packed lower triangle)
        rhs  = sum_b w_b y~_b J_b + P_f^-1 x_f
        x    = A^-1 rhs                            (packed Cholesky)

    i.e. ``build_normal_equations_packed`` + ``solve_spd_packed`` as ONE
    kernel launch — the elementwise DAG XLA splits into ~40 HBM-bounded
    fusions (measured 5.5x TIP / 24x PROSAIL the fusion-perfect traffic,
    tools/roofline.py) runs entirely on block-resident lane vectors.

    Row layouts: ``jac`` (B*p, blk) with row ``b*p + k`` = J[b, :, k];
    ``h0/y/w/m`` (B, blk); ``xl/xf`` (p, blk); ``pf`` packed (tri(p), blk);
    outputs ``x`` (p, blk) and ``a`` packed (tri(p), blk).
    """

    def idx(i, j):
        return i * (i + 1) // 2 + j

    jac = [
        [jac_ref[b * p + k, :] for k in range(p)] for b in range(n_bands)
    ]
    w = [w_ref[b, :] for b in range(n_bands)]
    # y~ = where(mask, y + J x_lin - H0, 0): the reference's
    # np.where(mask, y, 0) guard (solvers.py:53) with the relinearisation
    # shift (:56,:95).  A select, NOT mask multiplication: masked-out
    # positions hold NaN nodata (io/warp.py default) and 0 * NaN = NaN
    # would poison the whole solve.
    y_t = []
    for b in range(n_bands):
        jx = jac[b][0] * xl_ref[0, :]
        for k in range(1, p):
            jx = jx + jac[b][k] * xl_ref[k, :]
        y_t.append(
            jnp.where(
                m_ref[b, :] > 0, y_ref[b, :] + jx - h0_ref[b, :], 0.0
            )
        )
    wj = [[w[b] * jac[b][i] for i in range(p)] for b in range(n_bands)]
    a_pk = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            s = pf_ref[idx(i, j), :]
            for b in range(n_bands):
                s = s + wj[b][i] * jac[b][j]
            a_pk[i][j] = a_pk[j][i] = s
    rhs = []
    for i in range(p):
        s = pf_ref[idx(i, 0), :] * xf_ref[0, :]
        for q in range(1, p):
            s = s + pf_ref[idx(max(i, q), min(i, q)), :] * xf_ref[q, :]
        for b in range(n_bands):
            s = s + wj[b][i] * y_t[b]
        rhs.append(s)
    l = cholesky_packed(a_pk)
    x = solve_chol_vectors(l, rhs)
    for i in range(p):
        x_ref[i, :] = x[i]
    for i in range(p):
        for j in range(i + 1):
            a_ref[idx(i, j), :] = a_pk[i][j]
    # Innovations are state-independent diagnostics — free while the
    # operands are block-resident: where(mask, y - H0, 0)
    # (solvers.py:139-142; select not multiplication, same NaN-nodata
    # reasoning as y~ above).
    # (fwd = J (x - x_f) + H0 is NOT computed here: it must see the
    # damped/bounds-projected iterate, which is applied outside.)
    for b in range(n_bands):
        inn_ref[b, :] = jnp.where(
            m_ref[b, :] > 0, y_ref[b, :] - h0_ref[b, :], 0.0
        )


@functools.partial(jax.jit, static_argnums=(8, 9))
def _fused_update_rows(jac_rows, h0, y, w, m, xl_rows, xf_rows, pf_rows,
                       block: int = 2048, interpret: bool = False):
    n_coeff, n = pf_rows.shape
    p = xf_rows.shape[0]
    n_bands = h0.shape[0]
    block = math.gcd(n, min(block, n))
    f32 = jnp.float32
    grid = (n // block,)

    def spec(rows):
        return pl.BlockSpec((rows, block), lambda i: (0, i))

    x_rows, a_rows, inn_rows = pl.pallas_call(
        functools.partial(_fused_update_kernel, p, n_bands),
        out_shape=(
            jax.ShapeDtypeStruct((p, n), f32),
            jax.ShapeDtypeStruct((n_coeff, n), f32),
            jax.ShapeDtypeStruct((n_bands, n), f32),
        ),
        grid=grid,
        in_specs=[
            spec(n_bands * p), spec(n_bands), spec(n_bands), spec(n_bands),
            spec(n_bands), spec(p), spec(p), spec(n_coeff),
        ],
        out_specs=(spec(p), spec(n_coeff), spec(n_bands)),
        interpret=interpret,
    )(
        jac_rows.astype(f32), h0.astype(f32), y.astype(f32),
        w.astype(f32), m.astype(f32), xl_rows.astype(f32),
        xf_rows.astype(f32), pf_rows.astype(f32),
    )
    return x_rows, a_rows, inn_rows


def fused_update_pallas(lin, obs, x_lin: jnp.ndarray,
                        x_forecast: jnp.ndarray,
                        p_inv_forecast: jnp.ndarray,
                        interpret: bool = None):
    """Whole-update drop-in for the packed XLA path of
    ``core.solvers.kalman_update``: returns ``(x, a_packed)`` with
    ``a_packed`` the list-of-lists packed information matrix.

    ``p_inv_forecast`` accepts the dense (n, p, p) batch (sliced to packed
    rows here) or a pre-packed (tri(p), n) row array.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_bands, n, p = lin.jac.shape
    # (B, n, p) -> (B*p, n): row-major lane layout for the kernel.  This
    # relayout is the one extra HBM pass the fused path pays (the dense
    # carry/fusion round-trips it replaces cost ~10x more).
    jac_rows = jnp.moveaxis(lin.jac, 2, 1).reshape(n_bands * p, n)
    if isinstance(p_inv_forecast, jnp.ndarray) and p_inv_forecast.ndim == 2:
        pf_rows = p_inv_forecast
    else:
        pf_rows = jnp.stack(
            [
                p_inv_forecast[:, i, j]
                for i in range(p)
                for j in range(i + 1)
            ]
        )
    x_rows, a_rows, _inn = _fused_update_rows(
        jac_rows, lin.h0, obs.y,
        obs.r_inv, obs.mask.astype(jnp.float32),
        x_lin.T, x_forecast.T, pf_rows,
        interpret=bool(interpret),
    )

    def idx(i, j):
        return i * (i + 1) // 2 + j

    a_packed = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            a_packed[i][j] = a_packed[j][i] = a_rows[idx(i, j)]
    return x_rows.T, a_packed


def solve_spd_packed_pallas(a_packed, b: jnp.ndarray,
                            interpret: bool = None) -> jnp.ndarray:
    """Drop-in for ``linalg.solve_spd_packed``: packed list-of-lists ``A``
    (batch-leading vectors) + ``b`` (n, p) -> x (n, p).

    ``interpret`` defaults to True off-TPU (Pallas lowering targets
    Mosaic; the interpreter keeps the kernel testable on the CPU mesh)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    p = len(a_packed)
    a_rows = jnp.stack(
        [a_packed[i][j] for i in range(p) for j in range(i + 1)]
    )
    x = solve_rows(a_rows, b.T, interpret=bool(interpret))
    return x.T
