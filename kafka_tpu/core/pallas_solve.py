"""Pallas TPU kernel for the hot solve: fused packed Cholesky + substitution.

The per-date update factorises ``n_pix`` independent p x p SPD systems.
The default path (``linalg.solve_spd_packed``) expresses this as a few
hundred fused elementwise VPU ops that XLA schedules; this module provides
the same computation as ONE hand-written Pallas kernel: pixels ride the
lane axis, the ``p(p+1)/2`` packed coefficients ride sublanes, and the
whole factor+solve for a block of pixels happens VMEM-resident in a single
kernel launch — no intermediate HBM round-trips between the ~300 fused ops.

Opt-in via ``solver_options={"use_pallas": True}`` (structural, jit-static)
— the XLA path remains the default; a parity test pins both to the same
results.  Layout contract: coefficient ``(i, j)`` with ``j <= i`` of the
lower triangle lives at row ``i (i + 1) / 2 + j``, matching
``linalg.cholesky_packed``'s list-of-lists ordering.

Measured on a real v5e chip (TIP problem, 2^19 pixels, full GN loop):
21.3 ms/solve vs 19.4 ms for the XLA-fused path — XLA's automatic fusion
is already near-optimal for this pure-VPU workload, which is why the
kernel is opt-in rather than default.  It exists as the Mosaic foothold
for work XLA cannot schedule (fusing the normal-equations assembly's
band reduction into the factorisation, block-resident multi-iteration
solves).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .linalg import cholesky_packed, solve_chol_vectors


def tri_rows(p: int) -> int:
    return p * (p + 1) // 2


def _solve_kernel(p: int, a_ref, b_ref, x_ref):
    """One pixel block: Cholesky factor + forward/back substitution.

    Reuses the SAME unrolled helpers as the XLA path
    (``linalg.cholesky_packed`` / ``solve_chol_vectors`` — batch-axis
    agnostic jnp arithmetic, which lowers inside a Pallas kernel), so
    there is exactly one implementation of the numerically delicate
    factorisation to maintain.  Everything stays in (block,)-lane row
    vectors: no in-kernel transpose (a (block, p) relayout pads p up to
    the 128-lane tile and overflows VMEM)."""

    def idx(i, j):
        return i * (i + 1) // 2 + j

    a_pk = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            a_pk[i][j] = a_pk[j][i] = a_ref[idx(i, j), :]
    l = cholesky_packed(a_pk)
    x = solve_chol_vectors(l, [b_ref[i, :] for i in range(p)])
    for i in range(p):
        x_ref[i, :] = x[i]


@functools.partial(jax.jit, static_argnums=(2, 3))
def solve_rows(a_rows: jnp.ndarray, b_rows: jnp.ndarray,
               block: int = 1024, interpret: bool = False) -> jnp.ndarray:
    """Solve the packed batch in row layout.

    ``a_rows``: (p(p+1)/2, n) lower-triangle coefficients, ``b_rows``:
    (p, n); returns x (p, n).  ``block`` is a maximum: the actual block
    is its gcd with ``n`` so every pixel count divides cleanly (engine
    batches are multiples of 128/256, giving full-width blocks).
    """
    n_coeff, n = a_rows.shape
    p = b_rows.shape[0]
    if tri_rows(p) != n_coeff:
        raise ValueError(f"{n_coeff} coefficient rows for p={p}")
    block = math.gcd(n, min(block, n))
    return pl.pallas_call(
        functools.partial(_solve_kernel, p),
        out_shape=jax.ShapeDtypeStruct((p, n), jnp.float32),
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((n_coeff, block), lambda i: (0, i)),
            pl.BlockSpec((p, block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((p, block), lambda i: (0, i)),
        interpret=interpret,
    )(a_rows.astype(jnp.float32), b_rows.astype(jnp.float32))


def solve_spd_packed_pallas(a_packed, b: jnp.ndarray,
                            interpret: bool = None) -> jnp.ndarray:
    """Drop-in for ``linalg.solve_spd_packed``: packed list-of-lists ``A``
    (batch-leading vectors) + ``b`` (n, p) -> x (n, p).

    ``interpret`` defaults to True off-TPU (Pallas lowering targets
    Mosaic; the interpreter keeps the kernel testable on the CPU mesh)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    p = len(a_packed)
    a_rows = jnp.stack(
        [a_packed[i][j] for i in range(p) for j in range(i + 1)]
    )
    x = solve_rows(a_rows, b.T, interpret=bool(interpret))
    return x.T
