"""Pallas TPU kernel for the hot solve: fused packed Cholesky + substitution.

The per-date update factorises ``n_pix`` independent p x p SPD systems.
The default path (``linalg.solve_spd_packed``) expresses this as a few
hundred fused elementwise VPU ops that XLA schedules; this module provides
the same computation as ONE hand-written Pallas kernel: pixels ride the
lane axis, the ``p(p+1)/2`` packed coefficients ride sublanes, and the
whole factor+solve for a block of pixels happens VMEM-resident in a single
kernel launch — no intermediate HBM round-trips between the ~300 fused ops.

Opt-in via ``solver_options={"use_pallas": True}`` (structural, jit-static)
— the XLA path remains the default; a parity test pins both to the same
results.  Layout contract: coefficient ``(i, j)`` with ``j <= i`` of the
lower triangle lives at row ``i (i + 1) / 2 + j``, matching
``linalg.cholesky_packed``'s list-of-lists ordering.

Three generations of kernel live here.  ``solve_rows`` (factor+solve
only) was the first: measured 21.3 ms/solve vs 19.4 ms for the XLA path
on the full GN loop — XLA's automatic fusion already near-optimal for
that slice, so it stayed opt-in.  ``_fused_update_rows`` fuses the WHOLE
per-date update (assembly + factor + solve + innovations) into one
launch; on a real v5e (TIP, 2^19 px, full 2-iteration GN loop,
queued-slope timing) it takes the solve from 6.45 ms to 3.80 ms (~1.7x).
``_fused_gn_kernel`` goes the rest of the way for operators that
advertise an in-kernel analytic linearisation
(``ObservationModel.inkernel_linearize``): the ENTIRE Gauss-Newton
iteration — linearise, assemble, factor, solve, damp, project, converge
— runs as one launch, with the state, packed information matrix and
diagnostics block-resident in VMEM across iterations.  That deletes all
three HBM round-trips BASELINE.md's "Roofline" gap attribution charges
to the 3.80 ms path: the ``(B, n, p) -> (B*p, n)`` Jacobian relayout
(the Jacobian never materialises at all), the ``lax.while_loop`` carry,
and the separate bandwidth-bound operator-linearize program.  The single
measured story lives in BASELINE.md's "Roofline" section.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import solver_health
from .linalg import cholesky_packed, solve_chol_vectors


def tri_rows(p: int) -> int:
    return p * (p + 1) // 2


def jac_to_rows(jac: jnp.ndarray) -> jnp.ndarray:
    """The SANCTIONED ``(B, n, p) -> (B*p, n)`` Jacobian relayout.

    Operators without an in-kernel linearisation (GP banks, PROSAIL, any
    plain ``linearize`` closure) still produce the dense Jacobian batch
    and pay this one extra HBM pass to reach the kernel's lane-row
    layout.  It is the ONLY place in ``core/`` allowed to relayout a
    Jacobian (kafkalint rule ``kernel-relayout`` flags any other): the
    in-kernel path (``fused_gn_rows``) exists precisely so that operators
    advertising ``inkernel_linearize`` never materialise the tensor —
    their ``jac_rows`` are born in lane layout inside the kernel.
    """
    n_bands, n, p = jac.shape
    # kafkalint: disable=kernel-relayout — this IS the sanctioned shim
    return jnp.moveaxis(jac, 2, 1).reshape(n_bands * p, n)


def _solve_kernel(p: int, a_ref, b_ref, x_ref):
    """One pixel block: Cholesky factor + forward/back substitution.

    Reuses the SAME unrolled helpers as the XLA path
    (``linalg.cholesky_packed`` / ``solve_chol_vectors`` — batch-axis
    agnostic jnp arithmetic, which lowers inside a Pallas kernel), so
    there is exactly one implementation of the numerically delicate
    factorisation to maintain.  Everything stays in (block,)-lane row
    vectors: no in-kernel transpose (a (block, p) relayout pads p up to
    the 128-lane tile and overflows VMEM)."""

    def idx(i, j):
        return i * (i + 1) // 2 + j

    a_pk = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            a_pk[i][j] = a_pk[j][i] = a_ref[idx(i, j), :]
    l = cholesky_packed(a_pk)
    x = solve_chol_vectors(l, [b_ref[i, :] for i in range(p)])
    for i in range(p):
        x_ref[i, :] = x[i]


@functools.partial(jax.jit, static_argnums=(2, 3))
def solve_rows(a_rows: jnp.ndarray, b_rows: jnp.ndarray,
               block: int = 1024, interpret: bool = False) -> jnp.ndarray:
    """Solve the packed batch in row layout.

    ``a_rows``: (p(p+1)/2, n) lower-triangle coefficients, ``b_rows``:
    (p, n); returns x (p, n).  ``block`` is a maximum: the actual block
    is its gcd with ``n`` so every pixel count divides cleanly (engine
    batches are multiples of 128/256, giving full-width blocks).
    """
    n_coeff, n = a_rows.shape
    p = b_rows.shape[0]
    if tri_rows(p) != n_coeff:
        raise ValueError(f"{n_coeff} coefficient rows for p={p}")
    block = math.gcd(n, min(block, n))
    return pl.pallas_call(
        functools.partial(_solve_kernel, p),
        out_shape=jax.ShapeDtypeStruct((p, n), jnp.float32),
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((n_coeff, block), lambda i: (0, i)),
            pl.BlockSpec((p, block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((p, block), lambda i: (0, i)),
        interpret=interpret,
    )(a_rows.astype(jnp.float32), b_rows.astype(jnp.float32))


def _fused_update_kernel(p: int, n_bands: int, jac_ref, h0_ref, y_ref,
                         w_ref, m_ref, xl_ref, xf_ref, pf_ref, esc_ref,
                         x_ref, a_ref, inn_ref, hb_ref):
    """One pixel block of the WHOLE per-date update, VMEM-resident:

        y~   = where(mask, y + J x_lin - H0, 0)
        A    = sum_b w_b J_b J_b^T + P_f^-1        (packed lower triangle)
        rhs  = sum_b w_b y~_b J_b + P_f^-1 x_f
        x    = A^-1 rhs                            (packed Cholesky)

    i.e. ``build_normal_equations_packed`` + ``solve_spd_packed`` as ONE
    kernel launch — the elementwise DAG XLA splits into ~40 HBM-bounded
    fusions (measured 5.5x TIP / 24x PROSAIL the fusion-perfect traffic,
    tools/roofline.py) runs entirely on block-resident lane vectors.

    Row layouts: ``jac`` (B*p, blk) with row ``b*p + k`` = J[b, :, k];
    ``h0/y/w/m`` (B, blk); ``xl/xf`` (p, blk); ``pf`` packed (tri(p), blk);
    ``esc`` (1, blk) 0/1 — pixels under solve-health damping escalation,
    whose FACTORED diagonal is LM-inflated (``solver_health.inflate_diag``;
    exactly ``* 1.0 + 0.0`` for healthy pixels, and the STORED ``a`` stays
    the uninflated Hessian either way); outputs ``x`` (p, blk), ``a``
    packed (tri(p), blk), and ``hb`` (2, blk) — row 0 the per-pixel
    bad-step flag (Cholesky breakdown or non-finite solve), row 1 the
    non-finite-solve subset (``kafka_solver_nonfinite_total``'s census).
    """

    def idx(i, j):
        return i * (i + 1) // 2 + j

    jac = [
        [jac_ref[b * p + k, :] for k in range(p)] for b in range(n_bands)
    ]
    w = [w_ref[b, :] for b in range(n_bands)]
    # y~ = where(mask, y + J x_lin - H0, 0): the reference's
    # np.where(mask, y, 0) guard (solvers.py:53) with the relinearisation
    # shift (:56,:95).  A select, NOT mask multiplication: masked-out
    # positions hold NaN nodata (io/warp.py default) and 0 * NaN = NaN
    # would poison the whole solve.
    y_t = []
    for b in range(n_bands):
        jx = jac[b][0] * xl_ref[0, :]
        for k in range(1, p):
            jx = jx + jac[b][k] * xl_ref[k, :]
        y_t.append(
            jnp.where(
                m_ref[b, :] > 0, y_ref[b, :] + jx - h0_ref[b, :], 0.0
            )
        )
    wj = [[w[b] * jac[b][i] for i in range(p)] for b in range(n_bands)]
    a_pk = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            s = pf_ref[idx(i, j), :]
            for b in range(n_bands):
                s = s + wj[b][i] * jac[b][j]
            a_pk[i][j] = a_pk[j][i] = s
    rhs = []
    for i in range(p):
        s = pf_ref[idx(i, 0), :] * xf_ref[0, :]
        for q in range(1, p):
            s = s + pf_ref[idx(max(i, q), min(i, q)), :] * xf_ref[q, :]
        for b in range(n_bands):
            s = s + wj[b][i] * y_t[b]
        rhs.append(s)
    # Factor the LM-inflated copy; a_ref keeps the true Hessian.
    esc = esc_ref[0, :]
    chol_in = [row[:] for row in a_pk]
    for i in range(p):
        chol_in[i][i] = solver_health.inflate_diag(a_pk[i][i], esc)
    l = cholesky_packed(chol_in)
    x = solve_chol_vectors(l, rhs)
    hb_ref[0, :] = (
        solver_health.chol_breakdown(l) | solver_health.nonfinite_any(x)
    ).astype(jnp.float32)
    hb_ref[1, :] = solver_health.nonfinite_any(x).astype(jnp.float32)
    for i in range(p):
        x_ref[i, :] = x[i]
    for i in range(p):
        for j in range(i + 1):
            a_ref[idx(i, j), :] = a_pk[i][j]
    # Innovations are state-independent diagnostics — free while the
    # operands are block-resident: where(mask, y - H0, 0)
    # (solvers.py:139-142; select not multiplication, same NaN-nodata
    # reasoning as y~ above).
    # (fwd = J (x - x_f) + H0 is NOT computed here: it must see the
    # damped/bounds-projected iterate, which is applied outside.)
    for b in range(n_bands):
        inn_ref[b, :] = jnp.where(
            m_ref[b, :] > 0, y_ref[b, :] - h0_ref[b, :], 0.0
        )


@functools.partial(jax.jit, static_argnums=(9, 10))
def _fused_update_rows(jac_rows, h0, y, w, m, xl_rows, xf_rows, pf_rows,
                       esc_row=None,
                       block: int = 2048, interpret: bool = False):
    n_coeff, n = pf_rows.shape
    p = xf_rows.shape[0]
    n_bands = h0.shape[0]
    block = math.gcd(n, min(block, n))
    f32 = jnp.float32
    grid = (n // block,)
    if esc_row is None:
        esc_row = jnp.zeros((1, n), f32)

    def spec(rows):
        return pl.BlockSpec((rows, block), lambda i: (0, i))

    x_rows, a_rows, inn_rows, hb_rows = pl.pallas_call(
        functools.partial(_fused_update_kernel, p, n_bands),
        out_shape=(
            jax.ShapeDtypeStruct((p, n), f32),
            jax.ShapeDtypeStruct((n_coeff, n), f32),
            jax.ShapeDtypeStruct((n_bands, n), f32),
            jax.ShapeDtypeStruct((2, n), f32),
        ),
        grid=grid,
        in_specs=[
            spec(n_bands * p), spec(n_bands), spec(n_bands), spec(n_bands),
            spec(n_bands), spec(p), spec(p), spec(n_coeff), spec(1),
        ],
        out_specs=(spec(p), spec(n_coeff), spec(n_bands), spec(2)),
        interpret=interpret,
    )(
        jac_rows.astype(f32), h0.astype(f32), y.astype(f32),
        w.astype(f32), m.astype(f32), xl_rows.astype(f32),
        xf_rows.astype(f32), pf_rows.astype(f32), esc_row.astype(f32),
    )
    return x_rows, a_rows, inn_rows, hb_rows


def _fused_gn_kernel(p: int, n_bands: int, min_iters: int, max_iters: int,
                     has_bounds: bool, lin_rows,
                     y_ref, w_ref, m_ref, xf_ref, pf_ref, scal_ref, bnd_ref,
                     cor_ref,
                     x_ref, a_ref, fwd_ref, inn_ref, st_ref, hl_ref):
    """One pixel block of the WHOLE per-date Gauss-Newton solve.

    Per iteration (the body of ``gn_step``, the exact math of
    ``_fused_update_kernel`` with the linearisation inlined):

        H0, J = lin_rows(x)                       (analytic, in-VMEM)
        y~    = where(mask, y + J x - H0, 0)
        A     = sum_b w_b J_b J_b^T + P_f^-1      (packed lower triangle)
        x*    = A^-1 (sum_b w_b y~_b J_b + P_f^-1 x_f)
        x     <- clip(x + relaxation (x* - x), lo, hi)

    iterated as a bounded ``fori_loop`` over ``max_iters`` whose body is
    skipped (``lax.cond``) once the block converged — the early-exit norm
    check of the reference's while loop, folded into the convergence
    diagnostics instead of a loop carrier crossing HBM.  State, packed
    ``A``, fwd/innovation diagnostics and the iteration counters all stay
    block-resident across iterations; the Jacobian lane rows are BORN in
    kernel registers and never exist in HBM at all.

    Convergence is block-local: ``||dx_block||^2 < thresh_sq`` where
    ``thresh_sq = (tol * numel * block/n)^2`` applies the caller's
    per-element normalisation to this block's share — the same test the
    global loop applies, restricted to the block (a refinement: every
    block satisfying it implies the global norm does too).  Iterations
    match the while-loop semantics exactly when the batch is one block
    (every tier-1 parity problem) and agree within the GN tolerance ball
    otherwise.

    ``lin_rows`` maps a tuple of p state lane vectors to ``(h0, jac)``
    lists with ``jac[b][k]`` already a lane row (the
    ``ObservationModel.kernel_linearize_rows`` contract).  ``scal_ref``
    (SMEM) carries [relaxation, thresh_sq, moving_sq]; ``bnd_ref``
    (SMEM, (2, p)) the per-parameter bounds; ``cor_ref`` (1, blk) the
    ``solver.pixel`` corruption row (all zeros disarmed — the selects
    below then keep every value bit-identical).  ``st_ref`` row 0
    broadcasts the block's executed iteration count, row 1 its final
    squared step norm.  ``hl_ref`` carries the per-pixel solve-health
    outputs: row 0 the QA verdict bitmask (``core.solver_health``),
    row 1 the ever-non-finite census, rows 2..2+p the per-parameter
    clipped-on-every-iteration flags (bound saturation).

    The solve-health iteration semantics (detect -> LM retreat ->
    quarantine) are WORD-FOR-WORD those of the out-of-kernel loops in
    ``core.solvers`` — the verdict parity test pins the bitmasks equal.
    """

    def idx(i, j):
        return i * (i + 1) // 2 + j

    f32 = jnp.float32
    relax = scal_ref[0]
    thresh_sq = scal_ref[1]
    moving_sq = scal_ref[2]
    xf = tuple(xf_ref[k, :] for k in range(p))
    y = tuple(y_ref[b, :] for b in range(n_bands))
    w = tuple(w_ref[b, :] for b in range(n_bands))
    msk = tuple(m_ref[b, :] > 0 for b in range(n_bands))
    pf = tuple(pf_ref[r, :] for r in range(tri_rows(p)))
    cor = cor_ref[0, :] > 0

    def gn_step(carry):
        x = carry[0]
        n_done = carry[4]
        esc = carry[6]
        nonfin = carry[7]
        clip = carry[10]
        h0, jac = lin_rows(x)
        h0 = [solver_health.corrupt_h0(h0[b], cor) for b in range(n_bands)]
        # y~ = where(mask, y + J x - H0, 0): select, NOT mask
        # multiplication — masked-out positions hold NaN nodata
        # (io/warp.py default) and 0 * NaN = NaN would poison the solve.
        y_t = []
        for b in range(n_bands):
            jx = jac[b][0] * x[0]
            for k in range(1, p):
                jx = jx + jac[b][k] * x[k]
            y_t.append(jnp.where(msk[b], y[b] + jx - h0[b], 0.0))
        wj = [[w[b] * jac[b][i] for i in range(p)] for b in range(n_bands)]
        a_pk = [[None] * p for _ in range(p)]
        for i in range(p):
            for j in range(i + 1):
                s = pf[idx(i, j)]
                for b in range(n_bands):
                    s = s + wj[b][i] * jac[b][j]
                a_pk[i][j] = a_pk[j][i] = s
        rhs = []
        for i in range(p):
            s = pf[idx(i, 0)] * xf[0]
            for q in range(1, p):
                s = s + pf[idx(max(i, q), min(i, q))] * xf[q]
            for b in range(n_bands):
                s = s + wj[b][i] * y_t[b]
            rhs.append(s)
        # Factor the LM-inflated copy (exactly * 1.0 + 0.0 for healthy
        # pixels); the stored information matrix stays the true Hessian.
        chol_in = [row[:] for row in a_pk]
        for i in range(p):
            chol_in[i][i] = solver_health.inflate_diag(a_pk[i][i], esc)
        l = cholesky_packed(chol_in)
        x_raw = solve_chol_vectors(l, rhs)
        x_nonfin = solver_health.nonfinite_any(x_raw)
        step_bad = solver_health.chol_breakdown(l) | x_nonfin
        esc_now = jnp.maximum(esc, step_bad.astype(f32))
        # LM retreat: a bad pixel discards its step and holds position;
        # escalated pixels take shrunk-relaxation steps from here on.
        # Damped step + physical-domain projection, otherwise identical
        # to the while-loop body (core/solvers.py).
        relax_eff = solver_health.damped_relaxation(relax, esc_now)
        x_tgt = [
            solver_health.retreat(x_raw[k], x[k], step_bad)
            for k in range(p)
        ]
        x_new = [x[k] + relax_eff * (x_tgt[k] - x[k]) for k in range(p)]
        if has_bounds:
            x_new = [
                jnp.clip(x_new[k], bnd_ref[0, k], bnd_ref[1, k])
                for k in range(p)
            ]
            clip = tuple(
                clip[k] * ((x_new[k] <= bnd_ref[0, k])
                           | (x_new[k] >= bnd_ref[1, k])).astype(f32)
                for k in range(p)
            )
        # fwd = J (x_new - x_f) + H0 with the damped/projected iterate
        # (reference solvers.py:70-71,135-136); innovations = y - H0
        # under the mask (:139-142).  Both from the LIVE linearisation —
        # no jac/h0 in the carry.
        fwd = []
        for b in range(n_bands):
            s = jac[b][0] * (x_new[0] - xf[0])
            for k in range(1, p):
                s = s + jac[b][k] * (x_new[k] - xf[k])
            fwd.append(s + h0[b])
        inn = [
            jnp.where(msk[b], y[b] - h0[b], 0.0) for b in range(n_bands)
        ]
        ssq = (x_new[0] - x[0]) ** 2
        for k in range(1, p):
            ssq = ssq + (x_new[k] - x[k]) ** 2
        # Same reduction order as the pre-health kernel (bit-stable
        # trip counts): per-row sums, then the row-sum total.
        normsq = sum(jnp.sum((x_new[k] - x[k]) ** 2) for k in range(p))
        a_rows = tuple(a_pk[i][j] for i in range(p) for j in range(i + 1))
        return (tuple(x_new), a_rows, tuple(fwd), tuple(inn),
                n_done + 1, normsq, esc_now,
                jnp.maximum(nonfin, x_nonfin.astype(f32)),
                step_bad.astype(f32), ssq, clip)

    def body(_i, carry):
        n_done, normsq = carry[4], carry[5]
        converged = (normsq < thresh_sq) & (n_done >= min_iters)
        return jax.lax.cond(converged, lambda c: c, gn_step, carry)

    zero = jnp.zeros_like(xf[0])
    carry0 = (
        xf,
        tuple(zero for _ in range(tri_rows(p))),
        tuple(zero for _ in range(n_bands)),
        tuple(zero for _ in range(n_bands)),
        jnp.zeros((), jnp.int32),
        jnp.full((), jnp.inf, jnp.float32),
        zero,                                  # esc: escalated pixels
        zero,                                  # ever-non-finite census
        zero,                                  # bad on the LAST step
        zero + jnp.inf,                        # last per-pixel step^2
        tuple(zero + 1.0 for _ in range(p)),   # clipped EVERY iteration
    )
    # Bound max_iters + 1 reproduces the while loop's post-increment cap
    # check (n_done > max_iterations): 26 solves at the reference's cap.
    (x, a_rows, fwd, inn, n_done, normsq, esc, nonfin, bad_now, ssq,
     clip) = jax.lax.fori_loop(0, max_iters + 1, body, carry0)
    # Quarantine with honesty: pixels still bad (or non-finite in their
    # final state/information) fall back to the forecast with deflated
    # information, and the QA verdict says so.
    observed = msk[0]
    for b in range(1, n_bands):
        observed = observed | msk[b]
    quar = (
        (bad_now > 0)
        | solver_health.nonfinite_any(list(x))
        | solver_health.nonfinite_any(list(a_rows))
    ) & observed
    x = tuple(solver_health.quarantine_select(quar, xf[k], x[k])
              for k in range(p))
    a_rows = tuple(
        solver_health.quarantine_select(
            quar, solver_health.QUARANTINE_INFO_SCALE * pf[r], a_rows[r]
        )
        for r in range(tri_rows(p))
    )
    fwd = tuple(solver_health.quarantine_select(quar, zero, fwd[b])
                for b in range(n_bands))
    inn = tuple(solver_health.quarantine_select(quar, zero, inn[b])
                for b in range(n_bands))
    verd = solver_health.assemble_verdicts(
        observed, quar, n_done > max_iters, ssq >= moving_sq, esc > 0,
    )
    for k in range(p):
        x_ref[k, :] = x[k]
    for r in range(tri_rows(p)):
        a_ref[r, :] = a_rows[r]
    for b in range(n_bands):
        fwd_ref[b, :] = fwd[b]
        inn_ref[b, :] = inn[b]
    st_ref[0, :] = zero + n_done.astype(jnp.float32)
    st_ref[1, :] = zero + normsq
    hl_ref[0, :] = verd.astype(f32)
    hl_ref[1, :] = nonfin * observed.astype(f32)
    for k in range(p):
        hl_ref[2 + k, :] = (
            (clip[k] * observed.astype(f32)) if has_bounds else zero
        )


def fused_gn_rows(lin_rows, y, r_inv, mask_f, xf_rows, pf_rows,
                  tol, min_iterations: int, max_iterations: int,
                  relaxation, state_bounds_rows, norm_denominator,
                  block: int = 2048, interpret: bool = None,
                  corrupt=None):
    """Whole Gauss-Newton solve as ONE kernel launch per block.

    Row-layout driver around :func:`_fused_gn_kernel`.  ``lin_rows`` is
    the operator's bound ``kernel_linearize_rows`` (a stable callable —
    the jit cache keys on it); ``state_bounds_rows`` is ``None`` or a
    ``(lo, hi)`` pair broadcastable to ``(p,)``; ``corrupt`` an
    optional (n,) 0/1 mask of pixels whose linearisation the
    ``solver.pixel`` chaos site corrupts (zeros when disarmed).
    Returns ``(x_rows, a_rows, fwd, inn, n_done, norm, verdicts,
    nonfinite_count, clip_saturated)`` — ``n_done`` the max executed
    iteration count over blocks, ``norm`` the global final-step norm
    assembled from the per-block diagnostics, ``verdicts`` the (n,)
    int32 solve-health QA bitmask, ``nonfinite_count`` a () int32 and
    ``clip_saturated`` a (p,) int32 census of bound-saturated pixels.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    f32 = jnp.float32
    n_coeff, n = pf_rows.shape
    p = xf_rows.shape[0]
    n_bands = y.shape[0]
    if tri_rows(p) != n_coeff:
        raise ValueError(f"{n_coeff} coefficient rows for p={p}")
    block = math.gcd(n, min(block, n))
    numel = jnp.asarray(norm_denominator, f32)
    # Block-local share of the global convergence test (see kernel doc).
    thresh = jnp.asarray(tol, f32) * numel * (block / n)
    # Per-pixel "still moving" threshold for the cap-bailout verdict:
    # the per-pixel convergence criterion ||dx_i|| / p < tol, squared.
    moving = jnp.asarray(tol, f32) * p
    scal = jnp.stack([
        jnp.asarray(relaxation, f32), thresh * thresh, moving * moving,
    ])
    has_bounds = state_bounds_rows is not None
    if has_bounds:
        lo, hi = state_bounds_rows
        bnd = jnp.stack([
            jnp.broadcast_to(jnp.asarray(lo, f32), (p,)),
            jnp.broadcast_to(jnp.asarray(hi, f32), (p,)),
        ])
    else:
        bnd = jnp.zeros((2, p), f32)
    cor_row = (
        jnp.zeros((1, n), f32) if corrupt is None
        else jnp.asarray(corrupt, f32).reshape(1, n)
    )

    def spec(rows):
        return pl.BlockSpec((rows, block), lambda i: (0, i))

    x_rows, a_rows, fwd, inn, st, hl = pl.pallas_call(
        functools.partial(
            _fused_gn_kernel, p, n_bands, int(min_iterations),
            int(max_iterations), has_bounds, lin_rows,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((p, n), f32),
            jax.ShapeDtypeStruct((n_coeff, n), f32),
            jax.ShapeDtypeStruct((n_bands, n), f32),
            jax.ShapeDtypeStruct((n_bands, n), f32),
            jax.ShapeDtypeStruct((2, n), f32),
            jax.ShapeDtypeStruct((2 + p, n), f32),
        ),
        grid=(n // block,),
        in_specs=[
            spec(n_bands), spec(n_bands), spec(n_bands),
            spec(p), spec(n_coeff),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            spec(1),
        ],
        out_specs=(
            spec(p), spec(n_coeff), spec(n_bands), spec(n_bands), spec(2),
            spec(2 + p),
        ),
        interpret=bool(interpret),
    )(
        y.astype(f32), r_inv.astype(f32), mask_f.astype(f32),
        xf_rows.astype(f32), pf_rows.astype(f32), scal, bnd, cor_row,
    )
    # Per-block diagnostics ride the st rows broadcast over their block:
    # column 0 of each block carries the block's value.
    per_block = st[:, ::block]
    n_done = jnp.max(per_block[0]).astype(jnp.int32)
    norm = jnp.sqrt(jnp.sum(per_block[1])) / numel
    verdicts = hl[0].astype(jnp.int32)
    nonfinite_count = jnp.sum(hl[1] > 0).astype(jnp.int32)
    clip_saturated = jnp.sum(hl[2:] > 0, axis=1).astype(jnp.int32)
    return (x_rows, a_rows, fwd, inn, n_done, norm,
            verdicts, nonfinite_count, clip_saturated)


def fused_update_pallas(lin, obs, x_lin: jnp.ndarray,
                        x_forecast: jnp.ndarray,
                        p_inv_forecast: jnp.ndarray,
                        interpret: bool = None):
    """Whole-update drop-in for the packed XLA path of
    ``core.solvers.kalman_update``: returns ``(x, a_packed)`` with
    ``a_packed`` the list-of-lists packed information matrix.

    ``p_inv_forecast`` accepts the dense (n, p, p) batch (sliced to packed
    rows here) or a pre-packed (tri(p), n) row array.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_bands, n, p = lin.jac.shape
    # (B, n, p) -> (B*p, n): the sanctioned compat-shim relayout — the
    # one extra HBM pass the out-of-kernel-linearise path pays (the
    # in-kernel path, fused_gn_rows, pays none).
    jac_rows = jac_to_rows(lin.jac)
    if isinstance(p_inv_forecast, jnp.ndarray) and p_inv_forecast.ndim == 2:
        pf_rows = p_inv_forecast
    else:
        pf_rows = jnp.stack(
            [
                p_inv_forecast[:, i, j]
                for i in range(p)
                for j in range(i + 1)
            ]
        )
    x_rows, a_rows, _inn, _hb = _fused_update_rows(
        jac_rows, lin.h0, obs.y,
        obs.r_inv, obs.mask.astype(jnp.float32),
        x_lin.T, x_forecast.T, pf_rows,
        interpret=bool(interpret),
    )

    def idx(i, j):
        return i * (i + 1) // 2 + j

    a_packed = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            a_packed[i][j] = a_packed[j][i] = a_rows[idx(i, j)]
    return x_rows.T, a_packed


def solve_spd_packed_pallas(a_packed, b: jnp.ndarray,
                            interpret: bool = None) -> jnp.ndarray:
    """Drop-in for ``linalg.solve_spd_packed``: packed list-of-lists ``A``
    (batch-leading vectors) + ``b`` (n, p) -> x (n, p).

    ``interpret`` defaults to True off-TPU (Pallas lowering targets
    Mosaic; the interpreter keeps the kernel testable on the CPU mesh)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    p = len(a_packed)
    a_rows = jnp.stack(
        [a_packed[i][j] for i in range(p) for j in range(i + 1)]
    )
    x = solve_rows(a_rows, b.T, interpret=bool(interpret))
    return x.T
