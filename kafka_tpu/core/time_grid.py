"""Temporal grid iteration.

Host-side (dates are Python datetimes; nothing here is traced).  Same
windowing semantics as the reference's ``iterate_time_grid``
(``/root/reference/kafka/inference/utils.py:44-65``): for each grid step
``t_k`` (skipping the first), yield the observation dates falling in
``[t_{k-1}, t_k)`` plus a first-step flag.
"""

from __future__ import annotations

import logging
from typing import Iterable, Iterator, List, Sequence, Tuple, TypeVar

LOG = logging.getLogger(__name__)

T = TypeVar("T")


def iterate_time_grid(
    time_grid: Sequence[T], the_dates: Iterable[T], verbose: bool = True
) -> Iterator[Tuple[T, List[T], bool]]:
    """Yield ``(timestep, observation_dates_in_window, is_first)``.

    The window for the step ending at ``time_grid[k]`` is
    ``time_grid[k-1] <= d < time_grid[k]`` — half-open on the right, exactly
    as the reference (``inference/utils.py:49-52``).  ``verbose=False``
    silences the per-window log line (for planning passes that re-walk the
    grid before the run loop does).
    """
    dates = sorted(the_dates)
    istart = time_grid[0]
    is_first = True
    for timestep in time_grid[1:]:
        located = [d for d in dates if istart <= d < timestep]
        if verbose:
            LOG.info(
                "Timestep %s -> %s: %d observation(s)", istart, timestep,
                len(located)
            )
        istart = timestep
        yield timestep, located, is_first
        is_first = False
