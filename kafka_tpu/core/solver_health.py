"""Per-pixel solve-health verdicts, adaptive damping, honest quarantine.

The infrastructure around the solver became fault-tolerant in PRs 6-8,
but the *math* still failed silently: the Gauss-Newton loop "bails at
the cap and silently returns the last iterate", a step outside the
operator's domain diverges without a safeguard, and one bad
linearisation makes "Cholesky then emit NaN for that pixel forever"
(``core/solvers.py``).  At tile-year scale a per-mille rate of
silently-diverged pixels is thousands of corrupt values shipped with
confident-looking uncertainties.  This module is the per-PIXEL analogue
of the resilience layer's per-DATE degradation: detect, retreat, and —
when retreat fails — fall back to the forecast and *say so* in the
product.

Semantics (implemented identically by all solve generations — the XLA
while-loop in ``core.solvers.iterated_solve``, the out-of-kernel Pallas
row loop in ``_iterated_solve_rows``, and the fully in-kernel
``pallas_solve.fused_gn_rows``; verdict bitmasks are pinned equal
across paths on the same inputs):

1. **Detection** (every iteration, per pixel): a Gauss-Newton step is
   *bad* when the packed Cholesky factor's diagonal is non-positive or
   non-finite (the information matrix left the SPD cone — the silent
   "NaN forever" failure), or when any component of the raw solve is
   non-finite (NaN nodata that leaked past a mask, an operator
   evaluated outside its domain).
2. **Adaptive damping escalation** (Levenberg-Marquardt retreat): a
   pixel flagged bad holds its position for that iteration (the bad
   step is discarded) and, for every REMAINING iteration, solves with
   its packed-``A`` diagonal inflated (``a_ii * DAMP_DIAG + DAMP_ABS``)
   and its relaxation shrunk (``relaxation * DAMP_RELAX``).  Healthy
   pixels multiply by exactly 1.0 and add exactly 0.0 — their steps are
   bit-identical to a run without the health machinery.
3. **Quarantine with honesty**: a pixel still bad on its LAST executed
   iteration (or non-finite in its final state/information rows) falls
   back to its forecast — ``x := x_forecast``, information deflated to
   ``QUARANTINE_INFO_SCALE * p_inv_forecast`` (sigma inflated 2x) — the
   pixel-level analogue of the engine's predict-only degraded dates.
   The QA verdict says so; nothing pretends the solve worked.

QA bitmask (written per pixel into every output GeoTIFF as the
``solver_qa`` band; 0 = outside the state mask):

================== === ==================================================
``QA_CONVERGED``     1 pixel ended on a healthy, converged trajectory
``QA_CAP_BAILOUT``   2 the loop hit ``max_iterations`` with this pixel
                       still moving (per-pixel step ``||dx||/p >= tol``)
                       — the reference's silent bailout, now labelled
``QA_DAMPED_RECOVERED``
                     4 the pixel was flagged bad mid-loop, took the LM
                       retreat, and finished healthy (set alongside
                       CONVERGED/CAP_BAILOUT)
``QA_QUARANTINED``   8 still bad after escalation; output is the
                       forecast with deflated information
``QA_NODATA``       16 no valid observation in any band this window
                       (predict-only by construction)
================== === ==================================================

Bound-saturation — a pixel pinned at ``state_bounds`` on EVERY
iteration is a masked divergence (the projection hides an iterate that
wants to leave the physical domain) — is tracked per parameter as
``clip_saturated_count`` and surfaced through
``kafka_solver_clip_saturated_total`` / the ``solver_clip_saturated``
event rather than a QA bit: the output value is still the (clamped)
solve, not a fabrication.

This module is also the ONE sanctioned home for non-finite select logic
in device code: kafkalint rule ``nonfinite-launder`` flags
``jnp.nan_to_num`` / ``jnp.where(jnp.isnan(...))`` anywhere else,
because laundering a NaN into a plausible number without raising a
verdict is exactly the silent failure this module exists to end.

Chaos hook — the ``solver.pixel`` fault site: arming
``KAFKA_TPU_FAULTS="solver.pixel@3-5"`` (or ``faults.script``) makes
:func:`corruption_mask` return a mask of the 0-based pixel indices
3..5, and the solvers corrupt exactly those pixels' linearisation
(``h0`` forced to NaN in every band) so the whole
detect -> escalate -> quarantine -> QA path is testable
deterministically on CPU.  The calls grammar addresses PIXELS here, not
call numbers; the failure class is irrelevant (corruption is always
non-finite).  Indices are positions in the solve's (padded) pixel
batch — under a chunked run each chunk's filter has its own gather, so
the same armed range corrupts that range in EVERY chunk.  Disarmed,
the mask is ``None`` and no corruption argument enters the compiled
program at all.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

# -- QA bitmask -------------------------------------------------------------

QA_CONVERGED = 1
QA_CAP_BAILOUT = 2
QA_DAMPED_RECOVERED = 4
QA_QUARANTINED = 8
QA_NODATA = 16

# -- escalation / quarantine constants --------------------------------------

#: multiplicative LM inflation of an escalated pixel's packed-A diagonal.
DAMP_DIAG = 10.0
#: absolute diagonal floor added under escalation — a multiplicative
#: inflation alone cannot rescue an EXACTLY zero diagonal (the singular
#: prior case: 0 * 10 is still 0).
DAMP_ABS = 1e-3
#: relaxation multiplier for escalated pixels' remaining steps.
DAMP_RELAX = 0.25
#: information deflation for quarantined pixels: the forecast is served
#: with sigma inflated by 1/sqrt(scale) = 2x, so downstream consumers
#: that ignore the QA band still see an honestly wide uncertainty.
QUARANTINE_INFO_SCALE = 0.25

#: the chaos fault site (documented in ``resilience.faults``).
FAULT_SITE = "solver.pixel"


# -- detection (layout-agnostic: (n,) batch or (block,) lane vectors) -------

def chol_breakdown(l) -> jnp.ndarray:
    """Pixels whose packed Cholesky factor broke down.

    ``l`` is the list-of-lists factor from ``linalg.cholesky_packed``.
    A non-positive pivot square-roots to 0 (division blows up) or NaN;
    either way the factor diagonal stops being a finite positive number
    — the single test covering both the indefinite-A and the
    NaN-poisoned-A failure, evaluated per batch/lane element.
    """
    p = len(l)
    bad = jnp.zeros_like(l[0][0], dtype=bool)
    for j in range(p):
        d = l[j][j]
        bad = bad | ~(d > 0) | ~jnp.isfinite(d)
    return bad


def nonfinite_any(vectors) -> jnp.ndarray:
    """Elementwise OR of non-finiteness over a list of same-shape batch
    (or lane) vectors — the per-pixel "did anything go NaN/inf" test."""
    bad = ~jnp.isfinite(vectors[0])
    for v in vectors[1:]:
        bad = bad | ~jnp.isfinite(v)
    return bad


# -- escalation arithmetic --------------------------------------------------

def inflate_diag(a_ii, esc):
    """LM diagonal inflation: ``a_ii * DAMP_DIAG + DAMP_ABS`` where
    ``esc`` (0/1 float, same shape) marks escalated pixels.  Healthy
    pixels compute ``a_ii * 1.0 + 0.0`` — bit-identical."""
    return a_ii * (1.0 + esc * (DAMP_DIAG - 1.0)) + esc * DAMP_ABS


def damped_relaxation(relaxation, esc):
    """Per-pixel effective relaxation: shrunk for escalated pixels,
    exactly ``relaxation`` otherwise."""
    return relaxation * (1.0 + esc * (DAMP_RELAX - 1.0))


def retreat(x_raw, x_prev, bad):
    """Discard a bad pixel's raw step: hold position instead.  The ONE
    sanctioned non-finite select in the solve path — the replaced value
    is never laundered into the product silently, because ``bad`` also
    drives the escalation flags and, if it persists, the quarantine
    verdict."""
    return jnp.where(bad, x_prev, x_raw)


def quarantine_select(quarantined, fallback, value):
    """Final-output select: quarantined pixels take ``fallback`` (the
    forecast / deflated forecast information), everything else keeps
    ``value`` untouched.  Sanctioned here for the same reason as
    :func:`retreat` — the replacement is always paired with the
    ``QA_QUARANTINED`` verdict bit."""
    return jnp.where(quarantined, fallback, value)


# -- verdict assembly -------------------------------------------------------

def assemble_verdicts(observed, quarantined, cap_exit, moving,
                      escalated_ever) -> jnp.ndarray:
    """Pack the per-pixel verdict bitmask (int32) from boolean vectors.

    ``observed``: any valid observation in any band; ``quarantined``:
    still-bad-after-escalation; ``cap_exit``: scalar (or broadcast) bool
    — the loop ended via the iteration cap; ``moving``: per-pixel step
    still >= tol at the last iteration; ``escalated_ever``: the pixel
    took the LM retreat at least once.
    """
    i32 = jnp.int32
    observed = observed.astype(bool)
    quarantined = quarantined.astype(bool) & observed
    bailout = (
        jnp.broadcast_to(cap_exit, moving.shape).astype(bool)
        & moving.astype(bool) & observed & ~quarantined
    )
    recovered = escalated_ever.astype(bool) & observed & ~quarantined
    converged = observed & ~quarantined & ~bailout
    return (
        converged.astype(i32) * QA_CONVERGED
        + bailout.astype(i32) * QA_CAP_BAILOUT
        + recovered.astype(i32) * QA_DAMPED_RECOVERED
        + quarantined.astype(i32) * QA_QUARANTINED
        + (~observed).astype(i32) * QA_NODATA
    )


def verdict_counts(verdicts):
    """Scalar census of a verdict vector: (cap_bailouts,
    damped_recoveries, quarantined) int32 — the telemetry counters'
    per-window increments, computed on device so they ride the packed
    diagnostic read."""
    i32 = jnp.int32
    return (
        jnp.sum((verdicts & QA_CAP_BAILOUT) > 0).astype(i32),
        jnp.sum((verdicts & QA_DAMPED_RECOVERED) > 0).astype(i32),
        jnp.sum((verdicts & QA_QUARANTINED) > 0).astype(i32),
    )


def merge_verdicts(a, b):
    """OR-combine two verdict vectors over the same pixels (multiple
    acquisitions in one window / band-sequential loops): any flag raised
    in any constituent solve survives into the window's QA band, except
    NODATA, which only holds when the pixel was unobserved in EVERY
    solve (one observed solve clears it)."""
    return (
        ((a | b) & ~QA_NODATA) | (a & b & QA_NODATA)
    ).astype(jnp.int32)


# -- the solver.pixel chaos hook --------------------------------------------

def corruption_mask(n_pix: int) -> Optional[np.ndarray]:
    """Host-side: the armed ``solver.pixel`` fault specs as a boolean
    (n_pix,) numpy mask of pixels whose linearisation must be corrupted
    (0-based index ranges through the standard calls grammar), or
    ``None`` when nothing is armed — the disarmed path adds NOTHING to
    the compiled program (the corruption argument stays a None pytree
    leaf)."""
    from ..resilience import faults

    if not faults.active():
        return None
    specs = faults.specs_for(FAULT_SITE)
    if not specs:
        return None
    mask = np.zeros((n_pix,), bool)
    for s in specs:
        first = max(0, int(s.first))
        last = n_pix - 1 if s.last is None else min(n_pix - 1, int(s.last))
        if last >= first:
            mask[first:last + 1] = True
    if not mask.any():
        return None
    faults.record_injection(
        FAULT_SITE, pixels=int(mask.sum()),
        ranges=[[int(s.first), None if s.last is None else int(s.last)]
                for s in specs],
    )
    return mask


def corrupt_h0(h0, corrupt):
    """Apply the scripted corruption: forecasted observations forced to
    NaN at armed pixels (every band), making the pixel's normal
    equations non-finite — the deterministic stand-in for an operator
    evaluated outside its domain.  ``corrupt`` is a (n,) 0/1 float (or
    bool) vector; ``h0`` has pixels on its LAST axis."""
    return jnp.where(corrupt.astype(bool), jnp.float32(jnp.nan), h0)
