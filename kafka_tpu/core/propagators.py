"""State propagation in time, batched per pixel.

The reference ships five pluggable propagators plus Gaussian prior blending
(``/root/reference/kafka/inference/kf_tools.py``); each is reproduced here on
the ``(n_pix, p)`` / ``(n_pix, p, p)`` batched layout, jit/vmap-friendly, with
the giant sparse ``block_diag`` rebuilds replaced by a leading batch axis.

Propagator contract (mirrors ``kf_tools.py``): a callable

    (x_analysis, p_analysis, p_analysis_inverse, m_matrix, q_diag) ->
        (x_forecast, p_forecast | None, p_forecast_inverse | None)

where ``m_matrix`` is the (p, p) linear trajectory model (the reference uses
identity, ``linear_kf.py:123-129``) and ``q_diag`` the per-parameter model
uncertainty diagonal (``linear_kf.py:131-146``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .linalg import (
    batched_diag,
    batched_diagonal,
    solve_batched,
    solve_spd_batched,
    spd_inverse_batched,
)

# Pixel-batch slice per LU call in the exact information propagator: the
# XLA LU custom call's HLO temps are several times the operand, so the
# full-tile batch must not hit it in one piece (OOMs a 16 GB chip at
# ~1M pixels inside a fused scan).
INFO_SOLVE_BLOCK = 131072


class PixelPrior(NamedTuple):
    """A per-pixel i.i.d. Gaussian prior: mean (p,), cov + inverse (p, p)."""

    mean: jnp.ndarray
    cov: jnp.ndarray
    inv_cov: jnp.ndarray


def tip_prior_arrays() -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side (mean, cov, inv_cov) of the JRC-TIP prior — for callers
    that must stay off the device (e.g. synthetic problem construction,
    where a device round-trip would poison benchmark dispatch latency)."""
    sigma = np.array([0.12, 0.7, 0.0959, 0.15, 1.5, 0.2, 0.5])
    x0 = np.array([0.17, 1.0, 0.1, 0.7, 2.0, 0.18, np.exp(-0.5 * 1.5)])
    little_p = np.diag(sigma**2).astype(np.float32)
    little_p[5, 2] = 0.8862 * 0.0959 * 0.2
    little_p[2, 5] = 0.8862 * 0.0959 * 0.2
    inv_p = np.linalg.inv(little_p)
    return (
        x0.astype(np.float32), little_p, inv_p.astype(np.float32)
    )


def tip_prior() -> PixelPrior:
    """The JRC-TIP prior (published two-stream inversion package prior).

    Same constants as the reference (``kf_tools.py:99-116``): per-parameter
    sigmas, transformed-space effective LAI ``TLAI = exp(-0.5 LAI)`` with
    mean LAI 1.5, and the single off-diagonal correlation between the NIR
    soil albedo and background terms.
    """
    x0, little_p, inv_p = tip_prior_arrays()
    return PixelPrior(
        mean=jnp.asarray(x0, jnp.float32),
        cov=jnp.asarray(little_p, jnp.float32),
        inv_cov=jnp.asarray(inv_p, jnp.float32),
    )


# The TIP prior's constants never change; build it once at import so the
# per-timestep propagators don't redo the NumPy inverse + device transfers.
_TIP_PRIOR: Optional[PixelPrior] = None


def _tip_prior_cached() -> PixelPrior:
    global _TIP_PRIOR
    if _TIP_PRIOR is None:
        _TIP_PRIOR = tip_prior()
    return _TIP_PRIOR


def broadcast_prior(prior: PixelPrior, n_pix: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Tile a per-pixel prior over the pixel batch: the batched equivalent of
    the reference's ``block_diag([inv_covar] * n_pixels)``
    (``kafka_test.py:124-128``)."""
    x0 = jnp.broadcast_to(prior.mean, (n_pix, prior.mean.shape[0]))
    p_inv = jnp.broadcast_to(
        prior.inv_cov, (n_pix,) + prior.inv_cov.shape
    )
    return x0, p_inv


# --------------------------------------------------------------------------
# The five propagators (kf_tools.py L3 inventory).
# --------------------------------------------------------------------------

def propagate_standard_kalman(x_analysis, p_analysis, p_analysis_inverse,
                              m_matrix, q_diag):
    """Covariance-form Kalman propagation: ``x_f = M x_a``,
    ``P_f = P_a + Q`` (``kf_tools.py:174-205``).  Returns None for the
    inverse covariance, as the reference does."""
    x_forecast = jnp.einsum("pq,nq->np", m_matrix, x_analysis)
    p_forecast = p_analysis + batched_diag(
        jnp.broadcast_to(q_diag, x_analysis.shape)
    )
    return x_forecast, p_forecast, None


def propagate_information_filter(x_analysis, p_analysis, p_analysis_inverse,
                                 m_matrix, q_diag):
    """Exact information-filter propagation: solves
    ``(I + P_inv Q) P_f_inv = P_inv`` per pixel (``kf_tools.py:208-245``,
    the ``_SLOW`` variant — a dense p x p solve per pixel is fast here, so
    the exact form is the default rather than the "SLOW" fallback)."""
    x_forecast = jnp.einsum("pq,nq->np", m_matrix, x_analysis)
    n_pix, p = x_analysis.shape
    q = jnp.broadcast_to(q_diag, (n_pix, p))
    # S = P_inv Q with diagonal Q: scale columns.
    s = p_analysis_inverse * q[:, None, :]
    a = jnp.eye(p, dtype=x_analysis.dtype) + s
    p_forecast_inverse = solve_batched(
        a, p_analysis_inverse, block=INFO_SOLVE_BLOCK
    )
    return x_forecast, None, p_forecast_inverse


def propagate_information_filter_approx(x_analysis, p_analysis,
                                        p_analysis_inverse, m_matrix, q_diag):
    """Diagonal approximation to the information propagation
    (``kf_tools.py:247-289``): keep only the main diagonal of ``P_inv`` and
    deflate it by ``D = 1 / (1 + diag(P_inv) diag(Q))``."""
    x_forecast = jnp.einsum("pq,nq->np", m_matrix, x_analysis)
    m_diag = batched_diagonal(p_analysis_inverse)
    d = 1.0 / (1.0 + m_diag * q_diag)
    p_forecast_inverse = batched_diag(m_diag * d)
    return x_forecast, None, p_forecast_inverse


def make_prior_reset_propagator(prior: PixelPrior, keep_param: int):
    """Generalisation of ``propagate_information_filter_LAI``
    (``kf_tools.py:292-314``): every parameter is reset to the prior except
    ``keep_param`` (LAI slot 6 in the TIP state), whose mean is carried over
    and whose information is deflated as ``1 / (1/p_kk + q_k)``."""

    def propagate(x_analysis, p_analysis, p_analysis_inverse, m_matrix,
                  q_diag):
        x_forecast = jnp.einsum("pq,nq->np", m_matrix, x_analysis)
        n_pix, p = x_analysis.shape
        x0, p_inv0 = broadcast_prior(prior, n_pix)
        x0 = x0.at[:, keep_param].set(x_forecast[:, keep_param])
        post_info = batched_diagonal(p_analysis_inverse)[:, keep_param]
        q_k = jnp.broadcast_to(q_diag, (n_pix, p))[:, keep_param]
        new_info = 1.0 / ((1.0 / post_info) + q_k)
        p_forecast_inverse = p_inv0.at[:, keep_param, keep_param].set(new_info)
        return x0, None, p_forecast_inverse

    return propagate


def propagate_information_filter_lai(x_analysis, p_analysis,
                                     p_analysis_inverse, m_matrix, q_diag):
    """The reference's exact TIP/LAI propagator (``kf_tools.py:292-314``)."""
    return make_prior_reset_propagator(_tip_prior_cached(), keep_param=6)(
        x_analysis, p_analysis, p_analysis_inverse, m_matrix, q_diag
    )


def make_no_propagation(prior: PixelPrior):
    """``no_propagation`` (``kf_tools.py:316-353``): discard the analysis and
    return the (tiled) prior."""

    def propagate(x_analysis, p_analysis, p_analysis_inverse, m_matrix,
                  q_diag):
        n_pix = x_analysis.shape[0]
        x0, p_inv0 = broadcast_prior(prior, n_pix)
        return x0, None, p_inv0

    return propagate


def no_propagation(x_analysis, p_analysis, p_analysis_inverse, m_matrix,
                   q_diag):
    """Reference default: reset to the TIP prior (``kf_tools.py:316-353``)."""
    return make_no_propagation(_tip_prior_cached())(
        x_analysis, p_analysis, p_analysis_inverse, m_matrix, q_diag
    )


# --------------------------------------------------------------------------
# Prior blending (product of Gaussians) and the advance dispatcher.
# --------------------------------------------------------------------------

def blend_prior(prior_mean, prior_cov_inverse, x_forecast,
                p_forecast_inverse):
    """Product-of-Gaussians combination of a (possibly time-varying) prior
    with the propagated forecast, per pixel.

    Preserves the reference's exact operand pairing
    (``kf_tools.py:89-94``): ``A = P_f_inv + C_inv``,
    ``b = P_f_inv @ prior_mean + C_inv @ x_forecast`` — note the reference
    crosses the means (forecast information weights the *prior* mean and
    vice versa); we keep that contract for parity and expose the
    conventional pairing via ``blend_gaussians``.
    The sparse-LU solve becomes a batched p x p SPD solve.
    """
    hi = jax.lax.Precision.HIGHEST
    combined_cov_inv = p_forecast_inverse + prior_cov_inverse
    b = jnp.einsum(
        "npq,nq->np", p_forecast_inverse, prior_mean, precision=hi
    ) + jnp.einsum("npq,nq->np", prior_cov_inverse, x_forecast, precision=hi)
    x_combined = solve_spd_batched(combined_cov_inv, b.astype(jnp.float32))
    return x_combined, combined_cov_inv


def blend_gaussians(mean_a, inv_cov_a, mean_b, inv_cov_b):
    """Textbook product of Gaussians: each mean weighted by its *own*
    information matrix.  (The mathematically conventional form of
    ``blend_prior``; provided for new code.)"""
    hi = jax.lax.Precision.HIGHEST
    combined = inv_cov_a + inv_cov_b
    b = jnp.einsum("npq,nq->np", inv_cov_a, mean_a, precision=hi) + jnp.einsum(
        "npq,nq->np", inv_cov_b, mean_b, precision=hi
    )
    return solve_spd_batched(combined, b.astype(jnp.float32)), combined


def advance(x_analysis, p_analysis, p_analysis_inverse, m_matrix, q_diag,
            prior_mean=None, prior_cov_inverse=None, state_propagator=None):
    """The four-way advance dispatcher (``propagate_and_blend_prior``,
    ``kf_tools.py:136-171``): propagate, blend with a prior, either, or
    neither.

    ``prior_mean`` / ``prior_cov_inverse`` are already-batched arrays
    (``(n_pix, p)`` / ``(n_pix, p, p)``) — the engine resolves the prior
    object for the current date on the host before calling in.
    """
    have_prior = prior_mean is not None
    if state_propagator is not None:
        x_f, p_f, p_f_inv = state_propagator(
            x_analysis, p_analysis, p_analysis_inverse, m_matrix, q_diag
        )
        if have_prior:
            if p_f_inv is None:
                # Covariance-form propagators (standard Kalman) return P, not
                # P^-1; blending works in information space, so invert the
                # batched p x p blocks first.  (The reference crashes here —
                # blend_prior at kf_tools.py:89 with a None — so this
                # combination is a fixed gap, not a behavior change.)
                p_f_inv = spd_inverse_batched(p_f)
            x_c, p_c_inv = blend_prior(
                prior_mean, prior_cov_inverse, x_f, p_f_inv
            )
            return x_c, None, p_c_inv
        return x_f, p_f, p_f_inv
    if have_prior:
        return prior_mean, None, prior_cov_inverse
    return None, None, None
