"""The variational-Kalman update, TPU-native.

Math (identical to the reference, re-derived in batched-dense form):

Per pixel i the analysis solves the linearised normal equations

    A_i x_i = b_i
    A_i = sum_b r_inv[b,i] * J[b,i,:] J[b,i,:]^T  +  P_f_inv[i]
    b_i = sum_b r_inv[b,i] * ytilde[b,i] * J[b,i,:]  +  P_f_inv[i] x_f[i]
    ytilde = y + J x_lin - H0          (nonlinear relinearisation shift)

which is the reference's ``A = H^T R^-1 H + P_f^-1``, ``b = H^T R^-1 y~ +
P_f^-1 x_f`` (``/root/reference/kafka/inference/solvers.py:60-61,125-127``;
relinearisation shift at ``:56`` and ``:95``) specialised to the proven
block-diagonal structure (H rows touch only their own pixel,
``inference/utils.py:193-215``).  The multi-band row-stacking
``sp.vstack``/``sp.diags`` (``solvers.py:118-122``) becomes a sum over the
band axis of rank-1 outer products — one einsum on the MXU.

The outer relinearisation loop (``linear_kf.py:245-307``: tol 1e-3 on
``||dx||_2 / len(x)``, min 2 iterations, bail after 25) becomes a
``lax.while_loop`` so the whole multi-iteration solve is one XLA program.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from . import solver_health
from .linalg import (
    UNROLL_MAX_P,
    cholesky_packed,
    solve_chol_vectors,
    solve_spd_batched,
    solve_spd_packed,
    unpack_symmetric,
)
from .types import BandBatch, Linearization, SolveDiagnostics

# Reference loop constants, linear_kf.py:246-247 and :299-302.
CONVERGENCE_TOL = 1e-3
MIN_ITERATIONS = 2
MAX_ITERATIONS = 25

# A linearize function maps (operator_params, state (n_pix, p)) to a
# Linearization.  ``operator_params`` is a traced pytree carrying the per-date
# operator data (illumination angles, emulator weights, ...) so that one
# compiled program serves every date — closing over per-date arrays instead
# would make each date a fresh jit cache miss.
LinearizeFn = Callable[[Any, jnp.ndarray], Linearization]


def build_normal_equations(
    lin: Linearization,
    obs: BandBatch,
    x_lin: jnp.ndarray,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Assemble per-pixel ``A`` (n_pix, p, p) and ``b`` (n_pix, p).

    ``x_lin`` is the linearisation point (the reference's ``x0``/``x_prev``),
    ``x_forecast`` the prior mean — they differ after the first Gauss-Newton
    iteration (``solvers.py:100-127`` passes both).
    """
    f32 = jnp.float32
    # Full float32 contraction precision is load-bearing: TPU einsum defaults
    # to bfloat16 multiplies, and with R^-1 ~ 1e5 the bf16 rounding error
    # exceeds the prior's small eigenvalues, making A numerically indefinite
    # and the Cholesky NaN.
    hi = jax.lax.Precision.HIGHEST
    jac = lin.jac.astype(f32)
    r_inv = obs.r_inv.astype(f32)
    # Relinearised pseudo-observation: y + J x_lin - H0  (solvers.py:56,95).
    # Zeroed where masked so NaN nodata in y cannot poison the 0-weighted
    # products below (the reference's guard is np.where(mask, y, 0.),
    # solvers.py:53).
    y_tilde = jnp.where(
        obs.mask,
        obs.y.astype(f32)
        + jnp.einsum("bnp,np->bn", jac, x_lin, precision=hi)
        - lin.h0,
        0.0,
    )
    # A = sum_b J^T R^-1 J + P_f^-1 : contraction over the band axis.
    a = jnp.einsum(
        "bnp,bn,bnq->npq", jac, r_inv, jac, precision=hi
    ) + p_inv_forecast
    b = jnp.einsum(
        "bnp,bn,bn->np", jac, r_inv, y_tilde, precision=hi
    ) + jnp.einsum("npq,nq->np", p_inv_forecast, x_forecast, precision=hi)
    return a.astype(f32), b.astype(f32)


def build_normal_equations_packed(
    lin: Linearization,
    obs: BandBatch,
    x_lin: jnp.ndarray,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
):
    """Packed-symmetric assembly of the normal equations.

    Same math as ``build_normal_equations``, but the p(p+1)/2 unique
    entries of each per-pixel ``A`` are built as individual (n_pix,) batch
    vectors with fully unrolled band/parameter sums — no (n_pix, p, p)
    tensor and no einsum in the hot path.  Everything is an elementwise
    float32 VPU op (nothing routes through the MXU's bf16 default), which
    XLA fuses into a handful of kernels; combined with the packed Cholesky
    this makes the whole update ~40x faster than the dense-block einsum
    form on TPU (measured at p=7, 2^19 pixels).

    Returns ``(a_packed, b)`` with ``a_packed[i][j]`` (n_pix,) for j <= i
    (mirrored) and ``b`` (n_pix, p).
    """
    f32 = jnp.float32
    jac = lin.jac.astype(f32)
    w = obs.r_inv.astype(f32)
    n_bands, _, p = jac.shape
    # Relinearised pseudo-observation y + J x_lin - H0 (solvers.py:56,95),
    # zeroed where masked (the reference's np.where(mask, y, 0), :53).
    jx = [
        sum(jac[b, :, k] * x_lin[:, k] for k in range(p))
        for b in range(n_bands)
    ]
    y_tilde = [
        jnp.where(obs.mask[b], obs.y[b].astype(f32) + jx[b] - lin.h0[b], 0.0)
        for b in range(n_bands)
    ]
    wj = [[w[b] * jac[b, :, i] for i in range(p)] for b in range(n_bands)]
    a_packed = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            s = p_inv_forecast[:, i, j].astype(f32)
            for b in range(n_bands):
                s = s + wj[b][i] * jac[b, :, j]
            a_packed[i][j] = a_packed[j][i] = s
    b_cols = []
    for i in range(p):
        s = sum(
            p_inv_forecast[:, i, q].astype(f32)
            * x_forecast[:, q].astype(f32)
            for q in range(p)
        )
        for b in range(n_bands):
            s = s + wj[b][i] * y_tilde[b]
        b_cols.append(s)
    return a_packed, jnp.stack(b_cols, axis=-1).astype(f32)


def _packed_update_health(lin, obs, x_lin, x_forecast, p_inv_forecast,
                          esc):
    """One packed update with solve-health instrumentation: same math as
    ``build_normal_equations_packed`` + ``solve_spd_packed``, but the
    FACTORED diagonal is LM-inflated for escalated pixels (``esc`` (n,)
    0/1; exactly ``* 1.0 + 0.0`` — bit-identical — for healthy ones)
    while the returned information matrix stays the true Hessian, and
    the per-pixel breakdown/non-finite flags come back alongside.

    Returns ``(x_raw, a_packed, step_bad, x_nonfin)``.
    """
    a_packed, b = build_normal_equations_packed(
        lin, obs, x_lin, x_forecast, p_inv_forecast
    )
    p = x_forecast.shape[-1]
    chol_in = [row[:] for row in a_packed]
    for i in range(p):
        chol_in[i][i] = solver_health.inflate_diag(a_packed[i][i], esc)
    l = cholesky_packed(chol_in)
    x_cols = solve_chol_vectors(l, [b[..., i] for i in range(p)])
    x_nonfin = solver_health.nonfinite_any(x_cols)
    step_bad = solver_health.chol_breakdown(l) | x_nonfin
    return jnp.stack(x_cols, axis=-1), a_packed, step_bad, x_nonfin


def kalman_update(
    lin: Linearization,
    obs: BandBatch,
    x_lin: jnp.ndarray,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
    use_pallas: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One linearised update.  Returns ``(x_analysis, A)`` where ``A`` is the
    posterior information matrix — the reference returns the Hessian as
    ``P_analysis_inverse`` (``solvers.py:78,145``).

    Small states (p=7 TIP, p=10 PROSAIL — every real config) go through the
    packed elementwise path; the dense einsum+Cholesky form is the fallback
    for large p.  The dense ``A`` is still materialised once per update for
    the information-matrix output, but nothing in the solve reads it back.
    ``use_pallas`` runs the ENTIRE update (normal-equations assembly +
    packed Cholesky factor + substitution + innovation diagnostics) as one
    VMEM-resident Pallas kernel (``core.pallas_solve.fused_update_pallas``)
    instead of XLA-fused elementwise ops; masked positions are excluded by
    ``jnp.where`` selects in both paths, so NaN nodata under a False mask
    stays inert either way.
    """
    # The unrolled assembly emits O(n_bands * p^2) traced ops; past ~32
    # bands (hyperspectral) the three-op dense einsum compiles faster.
    if x_forecast.shape[-1] <= UNROLL_MAX_P and lin.jac.shape[0] <= 32:
        if use_pallas:
            # The whole update (assembly + factor + solve) as ONE
            # VMEM-resident Pallas kernel — XLA splits the same DAG into
            # ~40 HBM-bounded fusions moving 5-24x the necessary bytes
            # (tools/roofline.py).
            from .pallas_solve import fused_update_pallas

            x, a_packed = fused_update_pallas(
                lin, obs, x_lin, x_forecast, p_inv_forecast
            )
            return x, unpack_symmetric(a_packed)
        a_packed, b = build_normal_equations_packed(
            lin, obs, x_lin, x_forecast, p_inv_forecast
        )
        x = solve_spd_packed(a_packed, b)
        return x, unpack_symmetric(a_packed)
    if use_pallas:
        raise NotImplementedError(
            "use_pallas covers the packed small-state path only "
            f"(p <= {UNROLL_MAX_P}, <= 32 bands); this problem has "
            f"p={x_forecast.shape[-1]}, {lin.jac.shape[0]} bands"
        )
    a, b = build_normal_equations(lin, obs, x_lin, x_forecast, p_inv_forecast)
    return solve_spd_batched(a, b), a


def _kernel_bounds_rows(state_bounds, p: int):
    """Classify ``state_bounds`` for the in-kernel Gauss-Newton path.

    Returns ``None`` (no bounds), the ``(lo, hi)`` pair when both sides
    broadcast to per-parameter ``(p,)`` vectors (scalars included), or
    ``False`` when the bounds need the out-of-kernel row loop (per-pixel
    ``(n_pix, p)`` arrays — the kernel keeps bounds in SMEM, one scalar
    pair per parameter)."""
    if state_bounds is None:
        return None
    for v in state_bounds:
        v = jnp.asarray(v)
        if v.ndim > 1 or (v.ndim == 1 and v.shape[0] != p):
            return False
    return state_bounds


def _iterated_solve_rows(
    linearize: LinearizeFn,
    obs: BandBatch,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
    operator_params: Any,
    tol: float,
    min_iterations: int,
    max_iterations: int,
    relaxation,
    state_bounds: Any,
    norm_denominator: Any,
    linearize_block: Any,
    inkernel_linearize: bool = True,
    corrupt: Any = None,
):
    """Row-layout Gauss-Newton loop around the fused Pallas update.

    Same math as the XLA branch of ``iterated_solve`` (global-norm mode),
    restructured so the memory-bound parts stay at the bandwidth roof:

    - ``P_f^-1`` is packed to (tri(p), n) coefficient rows ONCE per date —
      the while_loop body never re-slices the dense (n, p, p) batch;
    - the state iterate is carried as (p, n) lane rows, so the only
      relayouts per iteration are the operator-facing transposes of x and
      the Jacobian;
    - the information matrix crosses iterations as packed rows (tri(p)
      instead of p^2 carried vectors) and is unpacked to the dense batch
      once, after convergence;
    - assembly + Cholesky + substitution + innovations run as ONE
      VMEM-resident kernel (``pallas_solve._fused_update_rows``).

    Measured at p=7, 2 bands, 2^19 px on a v5e (queued-slope method):
    6.45 ms -> 3.80 ms for the full 2-iteration solve, a ~1.7x speedup
    over the XLA-fused path — still above the fusion-perfect traffic
    bound because the Jacobian relayout, the while_loop carry and the
    separate linearize program all cross HBM (BASELINE.md "Roofline").

    When the operator advertises an in-kernel analytic linearisation
    (``ObservationModel.inkernel_linearize`` + ``kernel_linearize_rows``)
    and ``inkernel_linearize`` is not opted out, the ENTIRE loop instead
    runs inside ``pallas_solve.fused_gn_rows`` — one launch, all three
    round-trips deleted.  Engagement requires structural compatibility:
    global-norm mode (checked by the caller), per-parameter bounds (see
    ``_kernel_bounds_rows``), static iteration bounds, and an empty
    operator-params pytree (the in-kernel operators are closed-form;
    per-date aux stays on the out-of-kernel path).  ``linearize_block``
    is irrelevant in-kernel — it bounds the out-of-kernel batched
    jacfwd's peak memory, while the kernel is O(block) by construction.
    """
    from .pallas_solve import _fused_update_rows, fused_gn_rows, \
        jac_to_rows, tri_rows

    interpret = jax.default_backend() != "tpu"
    f32 = jnp.float32
    n_pix, p = x_forecast.shape
    n_bands = obs.y.shape[0]
    numel = x_forecast.size if norm_denominator is None else norm_denominator

    xf_rows = x_forecast.T.astype(f32)
    pf_rows = jnp.stack(
        [
            p_inv_forecast[:, i, j].astype(f32)
            for i in range(p)
            for j in range(i + 1)
        ]
    )
    mask_f = obs.mask.astype(f32)

    owner = getattr(linearize, "__self__", None)
    kernel_bounds = _kernel_bounds_rows(state_bounds, p)
    params_empty = (
        operator_params is None or not jax.tree.leaves(operator_params)
    )
    if (
        inkernel_linearize
        and owner is not None
        and getattr(owner, "inkernel_linearize", False)
        and params_empty
        and isinstance(min_iterations, int)
        and isinstance(max_iterations, int)
        and kernel_bounds is not False
    ):
        x_rows, a_rows, fwd, inn, n_done, norm, verd, nonfin, clip_sat = \
            fused_gn_rows(
                owner.kernel_linearize_rows, obs.y, obs.r_inv, mask_f,
                xf_rows, pf_rows, tol, min_iterations, max_iterations,
                relaxation, kernel_bounds, numel, interpret=interpret,
                corrupt=corrupt,
            )
        a_packed = [[None] * p for _ in range(p)]
        for i in range(p):
            for j in range(i + 1):
                a_packed[i][j] = a_packed[j][i] = \
                    a_rows[i * (i + 1) // 2 + j]
        return (
            x_rows.T, unpack_symmetric(a_packed), fwd, inn, n_done, norm,
            (verd, nonfin, clip_sat),
        )

    use_block = (
        linearize_block is not None and 0 < linearize_block < n_pix
    )

    def body_step(x_rows, esc):
        x_cols = x_rows.T
        if use_block:
            lin = _blocked_linearize(
                linearize, operator_params, x_cols, int(linearize_block)
            )
        else:
            lin = _call_linearize(linearize, operator_params, x_cols)
        if corrupt is not None:
            lin = lin._replace(
                h0=solver_health.corrupt_h0(lin.h0, corrupt)
            )
        jac_rows = jac_to_rows(lin.jac.astype(f32))
        x_raw, a_rows, inn, hb = _fused_update_rows(
            jac_rows, lin.h0, obs.y, obs.r_inv, mask_f,
            x_rows, xf_rows, pf_rows, esc[None, :], 2048, interpret
        )
        step_bad = hb[0] > 0
        # LM retreat (solver_health semantics, identical to the other
        # generations): bad pixels hold position, escalated pixels take
        # shrunk-relaxation steps; healthy arithmetic is bit-identical.
        esc_now = jnp.maximum(esc, step_bad.astype(f32))
        x_tgt = solver_health.retreat(x_raw, x_rows, step_bad[None, :])
        relax_eff = solver_health.damped_relaxation(
            relaxation, esc_now
        )[None, :]
        x_new = x_rows + relax_eff * (x_tgt - x_rows)
        at_bound = None
        if state_bounds is not None:
            # Accept the same bound shapes the XLA branch's
            # jnp.clip(x, lo, hi) does: scalars broadcast, (p,) vectors go
            # per-parameter, (n_pix, p) arrays go per-pixel — the row
            # layout transposes the last to (p, n_pix) lane rows and adds
            # the trailing lane axis to vectors.  Anything else fails HERE
            # with a shape message, not as an opaque while_loop
            # carry-shape error three frames deeper.
            def to_rows(v):
                v = jnp.asarray(v)
                if v.ndim == 0:
                    return v
                if v.ndim == 1:
                    if v.shape[0] != p:
                        raise ValueError(
                            f"state_bounds vector has {v.shape[0]} "
                            f"entries for p={p} parameters"
                        )
                    return v[:, None]
                if v.ndim == 2:
                    if v.shape != (n_pix, p):
                        raise ValueError(
                            f"state_bounds array has shape {v.shape}; "
                            f"expected (n_pix, p) = ({n_pix}, {p})"
                        )
                    return v.T
                raise ValueError(
                    "state_bounds must be scalar, (p,) or (n_pix, p); "
                    f"got ndim={v.ndim}"
                )

            lo, hi = (to_rows(v) for v in state_bounds)
            x_new = jnp.clip(x_new, lo, hi)
            at_bound = (x_new <= lo) | (x_new >= hi)
        # fwd = J (x - x_f) + H0 with the damped/projected iterate
        # (solvers.py:70-71,135-136).
        fwd = jnp.stack([
            sum(
                jac_rows[b * p + k] * (x_new[k] - xf_rows[k])
                for k in range(p)
            ) + lin.h0[b]
            for b in range(n_bands)
        ])
        return (x_new, a_rows, fwd, inn, esc_now, step_bad, hb[1] > 0,
                at_bound)

    def cond(carry):
        n_done, norm = carry[4], carry[5]
        converged = (norm < tol) & (n_done >= min_iterations)
        return ~(converged | (n_done > max_iterations))

    def body(carry):
        (x_rows, _a, _f, _i, n_done, _norm, esc, nonfin, _bad, _ssq,
         clip) = carry
        x_new, a_rows, fwd, inn, esc_now, step_bad, x_nonfin, at_bound = \
            body_step(x_rows, esc)
        if at_bound is not None:
            clip = clip * at_bound.astype(f32)
        step = x_new - x_rows
        norm = jnp.linalg.norm(step) / numel
        return (x_new, a_rows, fwd, inn, n_done + 1, norm, esc_now,
                jnp.maximum(nonfin, x_nonfin.astype(f32)),
                step_bad.astype(f32),
                jnp.sum(step * step, axis=0), clip)

    carry0 = (
        xf_rows,
        jnp.zeros((tri_rows(p), n_pix), f32),
        jnp.zeros((n_bands, n_pix), f32),
        jnp.zeros((n_bands, n_pix), f32),
        jnp.zeros((), jnp.int32),
        jnp.full((), jnp.inf, f32),
        jnp.zeros((n_pix,), f32),            # esc
        jnp.zeros((n_pix,), f32),            # ever-non-finite census
        jnp.zeros((n_pix,), f32),            # bad on the LAST step
        jnp.full((n_pix,), jnp.inf, f32),    # last per-pixel step^2
        jnp.ones((p, n_pix), f32),           # clipped EVERY iteration
    )
    (x_rows, a_rows, fwd, inn, n_done, norm, esc, nonfin, bad_now, ssq,
     clip) = jax.lax.while_loop(cond, body, carry0)
    # Quarantine with honesty (solver_health semantics, shared with the
    # in-kernel path): still-bad pixels fall back to the forecast with
    # deflated information; fwd/innovation diagnostics zero there.
    observed = jnp.any(obs.mask, axis=0)
    quar = (
        (bad_now > 0)
        | solver_health.nonfinite_any([x_rows[k] for k in range(p)])
        | solver_health.nonfinite_any(
            [a_rows[r] for r in range(tri_rows(p))]
        )
    ) & observed
    x_rows = solver_health.quarantine_select(quar[None, :], xf_rows,
                                             x_rows)
    a_rows = solver_health.quarantine_select(
        quar[None, :], solver_health.QUARANTINE_INFO_SCALE * pf_rows,
        a_rows,
    )
    fwd = solver_health.quarantine_select(quar[None, :], 0.0, fwd)
    inn = solver_health.quarantine_select(quar[None, :], 0.0, inn)
    verd = solver_health.assemble_verdicts(
        observed, quar, n_done > max_iterations,
        ssq >= (jnp.asarray(tol, f32) * p) ** 2, esc > 0,
    )
    nonfin_count = jnp.sum((nonfin > 0) & observed).astype(jnp.int32)
    if state_bounds is not None:
        clip_sat = jnp.sum(
            (clip > 0) & observed[None, :], axis=1
        ).astype(jnp.int32)
    else:
        clip_sat = jnp.zeros((p,), jnp.int32)
    a_packed = [[None] * p for _ in range(p)]
    for i in range(p):
        for j in range(i + 1):
            a_packed[i][j] = a_packed[j][i] = a_rows[i * (i + 1) // 2 + j]
    return (x_rows.T, unpack_symmetric(a_packed), fwd, inn, n_done, norm,
            (verd, nonfin_count, clip_sat))


def _iterated_solve_health(
    one_lin, obs, x_forecast, p_inv_forecast, tol, min_iterations,
    max_iterations, relaxation, state_bounds, numel, hessian_forward,
    operator_params,
):
    """Global-norm XLA Gauss-Newton loop with per-pixel solve health.

    The while-loop body is the plain ``gn_step`` opened up one level —
    ``build_normal_equations_packed`` + factor + substitute — so the
    Cholesky factor's diagonal is inspectable per pixel, the factored
    diagonal can be LM-inflated for escalated pixels, and the raw step
    can be retreated from before damping.  Healthy pixels' floats are
    bit-identical to the pre-health loop (the escalation arithmetic is
    exactly ``* 1.0 + 0.0`` for them); the iteration-count semantics are
    unchanged (same global norm, same cond).  Shares the detect ->
    escalate -> quarantine semantics with the Pallas generations via
    ``core.solver_health`` — the verdict-parity test pins the bitmasks
    equal across all three.
    """
    f32 = jnp.float32
    n_pix, p = x_forecast.shape
    n_bands = obs.y.shape[0]

    def cond(carry):
        n_done, norm = carry[4], carry[5]
        converged = (norm < tol) & (n_done >= min_iterations)
        return ~(converged | (n_done > max_iterations))

    def body(carry):
        (x_prev, _a, _h0, _jac, n_done, _norm, esc, nonfin, _bad, _ssq,
         clip) = carry
        lin = one_lin(x_prev)
        x_raw, a_packed, step_bad, x_nonfin = _packed_update_health(
            lin, obs, x_prev, x_forecast, p_inv_forecast, esc
        )
        # LM retreat: bad pixels discard the step and hold position;
        # escalated pixels take shrunk-relaxation steps from here on.
        esc_now = jnp.maximum(esc, step_bad.astype(f32))
        x_tgt = solver_health.retreat(x_raw, x_prev, step_bad[:, None])
        relax_eff = solver_health.damped_relaxation(
            relaxation, esc_now
        )[:, None]
        x_new = x_prev + relax_eff * (x_tgt - x_prev)
        if state_bounds is not None:
            lo, hi = state_bounds
            x_new = jnp.clip(x_new, lo, hi)
            clip = clip * ((x_new <= lo) | (x_new >= hi)).astype(f32)
        step = x_new - x_prev
        norm = jnp.linalg.norm(step) / numel
        return (x_new, unpack_symmetric(a_packed), lin.h0, lin.jac,
                n_done + 1, norm, esc_now,
                jnp.maximum(nonfin, x_nonfin.astype(f32)),
                step_bad.astype(f32),
                jnp.sum(step * step, axis=-1), clip)

    carry0 = (
        x_forecast,
        jnp.zeros((n_pix, p, p), f32),
        jnp.zeros((n_bands, n_pix), f32),
        jnp.zeros((n_bands, n_pix, p), f32),
        jnp.zeros((), jnp.int32),
        jnp.full((), jnp.inf, f32),
        jnp.zeros((n_pix,), f32),            # esc
        jnp.zeros((n_pix,), f32),            # ever-non-finite census
        jnp.zeros((n_pix,), f32),            # bad on the LAST step
        jnp.full((n_pix,), jnp.inf, f32),    # last per-pixel step^2
        jnp.ones((n_pix, p), f32),           # clipped EVERY iteration
    )
    (x, a, h0, jac, n_done, norm, esc, nonfin, bad_now, ssq, clip) = \
        jax.lax.while_loop(cond, body, carry0)
    # Quarantine with honesty: still-bad pixels fall back to the
    # forecast with deflated information; their fwd/innovation
    # diagnostics are zeroed so chi^2 only reads assimilated pixels.
    observed = jnp.any(obs.mask, axis=0)
    quar = (
        (bad_now > 0)
        | solver_health.nonfinite_any([x[:, k] for k in range(p)])
        | solver_health.nonfinite_any(
            [a[:, i, j] for i in range(p) for j in range(i + 1)]
        )
    ) & observed
    x = solver_health.quarantine_select(quar[:, None], x_forecast, x)
    a = solver_health.quarantine_select(
        quar[:, None, None],
        solver_health.QUARANTINE_INFO_SCALE * p_inv_forecast, a,
    )
    fwd = jnp.einsum("bnp,np->bn", jac, x - x_forecast) + h0
    fwd = solver_health.quarantine_select(quar[None, :], 0.0, fwd)
    innovations = jnp.where(obs.mask, obs.y - h0, 0.0)
    innovations = solver_health.quarantine_select(
        quar[None, :], 0.0, innovations
    )
    verd = solver_health.assemble_verdicts(
        observed, quar, n_done > max_iterations,
        ssq >= (jnp.asarray(tol, f32) * p) ** 2, esc > 0,
    )
    nonfin_count = jnp.sum((nonfin > 0) & observed).astype(jnp.int32)
    if state_bounds is not None:
        clip_sat = jnp.sum(
            (clip > 0) & observed[:, None], axis=0
        ).astype(jnp.int32)
    else:
        clip_sat = jnp.zeros((p,), jnp.int32)
    return _finish_solve(
        x, a, fwd, innovations, n_done, norm, None, obs,
        hessian_forward, operator_params, state_bounds,
        health=(verd, nonfin_count, clip_sat),
    )


def iterated_solve(
    linearize: LinearizeFn,
    obs: BandBatch,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
    operator_params: Any = None,
    tol: float = CONVERGENCE_TOL,
    min_iterations: int = MIN_ITERATIONS,
    max_iterations: int = MAX_ITERATIONS,
    relaxation: float = 1.0,
    state_bounds: Any = None,
    norm_denominator: Any = None,
    hessian_forward: Any = None,
    linearize_block: Any = None,
    use_pallas: bool = False,
    per_pixel_convergence: bool = False,
    inkernel_linearize: bool = True,
    corrupt: Any = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, SolveDiagnostics]:
    """Gauss-Newton relinearisation loop as a single ``lax.while_loop``.

    Mirrors ``LinearKalman.do_all_bands`` (``linear_kf.py:245-307``): start at
    ``x_forecast``, relinearise the observation operator at the previous
    iterate, solve, test ``||x - x_prev||_2 / numel < tol`` with at least
    ``min_iterations`` solves and a hard cap.  All pixels iterate together
    (the norm is global, exactly like the reference's single scalar norm at
    ``linear_kf.py:293``).

    ``relaxation`` < 1 applies damped Gauss-Newton
    (``x <- x_prev + relaxation * (x_solve - x_prev)``), which stabilises
    stiff nonlinear operators the undamped reference loop oscillates on
    (it bails at the cap and silently returns the last iterate); 1.0
    reproduces the reference exactly.

    ``state_bounds`` — an optional ``(lower, upper)`` pair of per-parameter
    arrays — projects each iterate into the physical domain.  Without it a
    Gauss-Newton step can leave the region where the operator's gradients
    are meaningful (e.g. negative transformed LAI), after which the
    iteration diverges; the reference has no safeguard and silently emits
    the diverged state.  Operators declare their domains via
    ``ObservationModel.state_bounds``.

    ``norm_denominator`` — element count used to normalise the convergence
    norm.  Callers with padded pixel batches must pass the *valid* element
    count (n_valid * p): padding pixels contribute zero step, so dividing by
    the padded size would loosen the tolerance by n_pad/n_valid relative to
    the reference's ``len(x_analysis)`` (``linear_kf.py:296``).

    ``inkernel_linearize`` — with ``use_pallas``, let operators that
    advertise an analytic in-kernel linearisation
    (``ObservationModel.inkernel_linearize``) run the WHOLE Gauss-Newton
    loop inside the fused Pallas kernel (``pallas_solve.fused_gn_rows``)
    — the linearisation, the iteration carry and the packed information
    matrix all stay VMEM-resident; parity with the out-of-kernel path is
    pinned within the documented 2e-3 float32 GN tolerance.  True by
    default (it only engages when structurally possible — global-norm
    mode, per-parameter bounds, empty operator params, static iteration
    bounds); pass False to force the out-of-kernel linearise path, e.g.
    to benchmark the two generations against each other.

    ``per_pixel_convergence`` — freeze each pixel once TWO consecutive
    steps satisfy ``||dx_i||_2 / p < tol`` (instead of the reference's
    single global norm, normalised by ``n*p``, under which individual
    pixels can still be moving), iterating until every pixel froze or
    the cap (SURVEY §7(c)).  Converged pixels stop moving even when
    stiff neighbours keep oscillating to the iteration cap; their
    information matrix relinearises at the frozen point.  The criterion
    is evaluated with the loop's own arithmetic: for a rare
    non-contractive pixel (~0.05 % measured on TIP problems) a re-check
    under different op fusion can exceed tol — the same pixels the
    reference leaves oscillating at its cap.  Off by default — the
    global norm reproduces the reference exactly.

    **Solve health** (``core.solver_health``): in global-norm mode on
    the packed small-state path (p <= 16, <= 32 bands — every real
    config; both the XLA and the Pallas generations), every pixel gets a
    per-iteration health check (Cholesky breakdown, non-finite step), a
    Levenberg-Marquardt damping escalation when flagged (hold position,
    inflate the factored diagonal, shrink the relaxation — healthy
    pixels' arithmetic is bit-identical), and an end-of-loop verdict: a
    pixel still bad after escalation is QUARANTINED — its output is the
    forecast with information deflated to ``QUARANTINE_INFO_SCALE *
    p_inv_forecast`` and its fwd/innovation diagnostics zeroed — and the
    QA bitmask (``diagnostics.health_verdicts``) says so.
    ``per_pixel_convergence`` mode and the large-p dense fallback keep
    their previous semantics (``health_verdicts`` is None there).
    ``corrupt`` is the ``solver.pixel`` chaos hook: a traced (n_pix,)
    0/1 mask of pixels whose linearisation is deterministically
    NaN-corrupted (None — the production case — adds nothing to the
    compiled program).

    ``hessian_forward`` — optional per-pixel forward model ``(p,) ->
    (n_bands,)`` (or ``(operator_params, (p,)) -> (n_bands,)``).  When
    given, the second-order Hessian correction is subtracted from the
    returned information matrix after convergence, mirroring the
    reference's ``P_analysis_inverse - P_correction``
    (``linear_kf.py:412-416``, ``kf_tools.py:26-72``) with ``jax.hessian``
    of the forward model in place of the GP emulator's hand-coded
    ``.hessian``.

    Returns ``(x_analysis, p_inv_analysis, diagnostics)``.
    """
    numel = x_forecast.size if norm_denominator is None else norm_denominator
    n_pix_total = x_forecast.shape[0]
    use_block = (
        linearize_block is not None and 0 < linearize_block < n_pix_total
    )

    def one_lin(x_prev):
        if use_block:
            lin = _blocked_linearize(
                linearize, operator_params, x_prev, int(linearize_block)
            )
        else:
            lin = _call_linearize(linearize, operator_params, x_prev)
        if corrupt is not None:
            # solver.pixel chaos: deterministic NaN corruption of the
            # armed pixels' linearisation (solver_health docstring).
            lin = lin._replace(
                h0=solver_health.corrupt_h0(lin.h0, corrupt)
            )
        return lin

    def one_solve(x_prev):
        lin = one_lin(x_prev)
        x_new, a = kalman_update(
            lin, obs, x_prev, x_forecast, p_inv_forecast,
            use_pallas=use_pallas,
        )
        return x_new, a, lin

    def gn_step(x_prev):
        """One damped, bounds-projected Gauss-Newton step — shared by
        both convergence modes so they cannot drift apart."""
        x_new, a, lin = one_solve(x_prev)
        x_new = x_prev + relaxation * (x_new - x_prev)
        if state_bounds is not None:
            lo, hi = state_bounds
            x_new = jnp.clip(x_new, lo, hi)
        return x_new, a, lin

    n_pix, p = x_forecast.shape
    n_bands = obs.y.shape[0]

    if (
        use_pallas
        and not per_pixel_convergence
        and p <= UNROLL_MAX_P
        and n_bands <= 32
    ):
        # Fused-kernel fast path (global-norm mode): the whole per-date
        # loop in row layout around one VMEM-resident Pallas kernel —
        # or, for operators advertising inkernel_linearize, INSIDE it.
        x, a, fwd, innovations, n_done, norm, health = \
            _iterated_solve_rows(
                linearize, obs, x_forecast, p_inv_forecast,
                operator_params,
                tol, min_iterations, max_iterations, relaxation,
                state_bounds, norm_denominator, linearize_block,
                inkernel_linearize=inkernel_linearize, corrupt=corrupt,
            )
        return _finish_solve(
            x, a, fwd, innovations, n_done, norm, None, obs,
            hessian_forward, operator_params, state_bounds,
            health=health,
        )

    if (
        not per_pixel_convergence
        and p <= UNROLL_MAX_P
        and n_bands <= 32
    ):
        # Global-norm XLA path with solve health: the packed update is
        # opened up (factor-level breakdown detection, LM escalation)
        # but healthy pixels' arithmetic is bit-identical to the plain
        # gn_step (inflate by * 1.0 + 0.0, relax by * 1.0).
        return _iterated_solve_health(
            one_lin, obs, x_forecast, p_inv_forecast, tol,
            min_iterations, max_iterations, relaxation, state_bounds,
            numel, hessian_forward, operator_params,
        )

    # Initial carry: no solves done yet; dummy A/h0/jac of the right shapes.
    carry0 = (
        x_forecast,
        jnp.zeros((n_pix, p, p), jnp.float32),
        jnp.zeros((n_bands, n_pix), jnp.float32),
        jnp.zeros((n_bands, n_pix, p), jnp.float32),
        jnp.zeros((), jnp.int32),
        jnp.full((), jnp.inf, jnp.float32),
    )

    if per_pixel_convergence:
        # SURVEY §7(c): under the reference's single global norm, pixels
        # that converged early keep being re-solved while stiff pixels
        # oscillate — and an oscillating neighbourhood's relinearisation
        # can drag already-converged pixels back out.  This mode FREEZES
        # each pixel at its first converged iterate (per-pixel criterion
        # ||dx_i||_2 / p < tol, same min/max bounds), iterating until all
        # pixels froze or the cap.  Frozen pixels relinearise at their
        # fixed point, so their information matrix stays consistent.
        def cond(carry):
            _x, _a, _h0, _jac, n_done, _norm, frozen, _small = carry
            done = frozen.all() & (n_done >= min_iterations)
            return ~(done | (n_done > max_iterations))

        def body(carry):
            x_prev, _a, _h0, _jac, n_done, _norm, frozen, prev_small = \
                carry
            x_new, a, lin = gn_step(x_prev)
            step = x_new - x_prev
            pix_norm = jnp.sqrt(jnp.sum(step * step, axis=-1)) / p
            x_out = jnp.where(frozen[:, None], x_prev, x_new)
            small = pix_norm < tol
            # Freeze only on TWO consecutive sub-tol steps: an oscillating
            # pixel's step dips below tol at each direction change, and a
            # single small step there is not a fixed point.
            newly = small & prev_small & (n_done + 1 >= min_iterations)
            norm = jnp.sqrt(jnp.sum(jnp.where(
                frozen[:, None], 0.0, step
            ) ** 2)) / numel
            return (
                x_out, a, lin.h0, lin.jac, n_done + 1, norm,
                frozen | newly, small,
            )

        carry0 = carry0 + (
            jnp.zeros((n_pix,), bool), jnp.zeros((n_pix,), bool),
        )
        x, a, h0, jac, n_done, norm, frozen, _small = jax.lax.while_loop(
            cond, body, carry0
        )
    else:
        frozen = None
        def cond(carry):
            _x, _a, _h0, _jac, n_done, norm = carry
            converged = (norm < tol) & (n_done >= min_iterations)
            return ~(converged | (n_done > max_iterations))

        def body(carry):
            x_prev, _a, _h0, _jac, n_done, _norm = carry
            x_new, a, lin = gn_step(x_prev)
            norm = jnp.linalg.norm(x_new - x_prev) / numel
            return (x_new, a, lin.h0, lin.jac, n_done + 1, norm)

        x, a, h0, jac, n_done, norm = jax.lax.while_loop(
            cond, body, carry0
        )

    # Diagnostics follow the reference conventions: fwd = J (x_a - x_f) + H0
    # (solvers.py:70-71,135-136); multiband innovations = y_orig - H0
    # (solvers.py:139-142).
    fwd = jnp.einsum("bnp,np->bn", jac, x - x_forecast) + h0
    innovations = jnp.where(obs.mask, obs.y - h0, 0.0)
    return _finish_solve(
        x, a, fwd, innovations, n_done, norm, frozen, obs,
        hessian_forward, operator_params, state_bounds,
    )


def _window_telemetry_scalars(x, innovations, obs, state_bounds):
    """On-device per-window diagnostic scalars (telemetry subsystem).

    Computed INSIDE the jitted solve so they join the packed diagnostic
    read the engine already pays — zero additional device->host
    transfers (see ``telemetry.device.fetch_scalars``).

    - ``chi2``: (n_bands,) mean innovation chi^2 over each band's valid
      pixels — sum(innov^2 * r_inv) / count(mask); ~1 when the assumed
      observation uncertainty matches the residuals.
    - ``clipped``: state entries exactly AT a bound on the final iterate
      (the loop clips with these exact values, so equality identifies the
      projected entries), counted over observed pixels only — padding
      pixels sit at zero state and would otherwise read as clipped.
    - ``nodata``: masked-out observation entries over all bands (padding
      included; the engine subtracts its known padding).
    """
    count_b = jnp.sum(obs.mask, axis=1)
    chi2 = jnp.sum(
        innovations.astype(jnp.float32) ** 2 * obs.r_inv, axis=1
    ) / jnp.maximum(count_b, 1).astype(jnp.float32)
    nodata = jnp.sum(~obs.mask).astype(jnp.int32)
    if state_bounds is None:
        clipped = jnp.zeros((), jnp.int32)
    else:
        lo, hi = (jnp.asarray(v, jnp.float32) for v in state_bounds)
        observed = jnp.any(obs.mask, axis=0)
        at_bound = (x <= lo) | (x >= hi)
        clipped = jnp.sum(
            at_bound & observed[:, None]
        ).astype(jnp.int32)
    return chi2, clipped, nodata


def _finish_solve(
    x, a, fwd, innovations, n_done, norm, frozen, obs,
    hessian_forward, operator_params, state_bounds=None, health=None,
):
    """Shared post-loop tail: optional second-order Hessian correction
    (with the PSD guard) + diagnostics packaging.  ``health`` is the
    solve-health triple ``(verdicts, nonfinite_count,
    clip_saturated_count)`` from paths that track it (None elsewhere —
    the trailing SolveDiagnostics fields then stay None)."""
    if hessian_forward is not None:
        from .hessian import hessian_correction

        fwd_pixel = _bind_per_pixel(hessian_forward, operator_params)
        a = a - hessian_correction(
            fwd_pixel, x, obs.r_inv, innovations, obs.mask
        )
        # The second-order term is subtracted UNGUARDED in the reference
        # (``linear_kf.py:412-416``); where the linearisation is poor it
        # can push A off the positive-definite cone, and the next date's
        # Cholesky then emits NaN for that pixel forever.  Clamp the
        # per-pixel eigenvalues to a small positive floor — a no-op for
        # healthy pixels, a finite (near-zero-information) matrix for
        # the pathological ones.
        w, v = jnp.linalg.eigh(a)
        floor = 1e-6 * jnp.maximum(jnp.abs(w[..., -1:]), 1e-3)
        fixed = jnp.einsum(
            "nij,nj,nkj->nik", v, jnp.maximum(w, floor), v,
            precision=jax.lax.Precision.HIGHEST,
        )
        # Healthy pixels keep their EXACT matrix (the eigh round-trip
        # would otherwise smear ~1e-7 reconstruction error over every
        # pixel); only off-cone pixels take the clamped rebuild.
        bad = w[..., 0] < floor[..., 0]
        a = jnp.where(bad[:, None, None], fixed, a)
    chi2, clipped, nodata = _window_telemetry_scalars(
        x, innovations, obs, state_bounds
    )
    verdicts = nonfin = clip_sat = cap = damped = quar = None
    if health is not None:
        verdicts, nonfin, clip_sat = health
        cap, damped, quar = solver_health.verdict_counts(verdicts)
    diags = SolveDiagnostics(
        innovations=innovations,
        fwd_modelled=fwd,
        n_iterations=n_done,
        convergence_norm=norm,
        converged_mask=frozen,
        chi2_per_band=chi2,
        clipped_count=clipped,
        nodata_count=nodata,
        health_verdicts=verdicts,
        cap_bailout_count=cap,
        damped_recovered_count=damped,
        quarantined_count=quar,
        nonfinite_count=nonfin,
        clip_saturated_count=clip_sat,
    )
    return x, a, diags


def linear_solve(
    lin: Linearization,
    obs: BandBatch,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, SolveDiagnostics]:
    """Single-shot update for *linear* observation operators (identity H).

    Equivalent to ``variational_kalman`` with a plain H matrix
    (``solvers.py:41-78``) — no relinearisation loop needed.  Note the
    reference's linear branch has a latent NameError (``solvers.py:44-49``
    never sets ``H_matrix_``); this is the corrected semantics.
    """
    x, a = kalman_update(lin, obs, x_forecast, x_forecast, p_inv_forecast)
    fwd = jnp.einsum("bnp,np->bn", lin.jac, x - x_forecast) + lin.h0
    innovations = jnp.where(obs.mask, obs.y - fwd, 0.0)
    diags = SolveDiagnostics(
        innovations=innovations,
        fwd_modelled=fwd,
        n_iterations=jnp.ones((), jnp.int32),
        convergence_norm=jnp.zeros((), jnp.float32),
    )
    return x, a, diags


def _bind_per_pixel(fn, operator_params):
    """Close a ``(params, x_pixel)`` per-pixel forward over its per-date
    params; 1-argument callables pass through unchanged."""
    try:
        n_args = len(inspect.signature(fn).parameters)
    except (ValueError, TypeError):
        n_args = 2
    if n_args >= 2:
        return lambda x_pixel: fn(operator_params, x_pixel)
    return fn


def _call_linearize(linearize, operator_params, x):
    """Support both ``f(params, x)`` (preferred — per-date data stays a
    traced argument) and plain ``f(x)`` closures (tests, quick scripts)."""
    try:
        n_args = len(inspect.signature(linearize).parameters)
    except (ValueError, TypeError):
        n_args = 2
    if n_args >= 2:
        return linearize(operator_params, x)
    return linearize(x)


def _blocked_linearize(linearize, operator_params, x, block: int):
    """Linearize in sequential pixel blocks (``lax.map``) to bound peak
    device memory.

    The batched value+Jacobian of a deep operator (the exact-SAIL PROSAIL
    chain) is the solver's dominant memory consumer — ~11 KB/pixel of live
    intermediates at p=10, which caps a 16 GB chip near 1.4M pixels per
    solve.  Mapping the linearisation over blocks makes peak memory
    ``O(block)`` instead of ``O(n_pix)`` while the cheap normal-equations
    update still runs over the full batch; per-pixel aux leaves (leading
    ``n_pix`` axis, e.g. SAR incidence angles) are split alongside the
    pixels, broadcast leaves close over.

    ``block`` is a maximum: pixels are split into the fewest blocks that
    respect it, sized evenly, so edge-padding waste is at most one block's
    remainder instead of up to ~2x.

    Which aux leaves are per-pixel is decided by the OPERATOR when
    ``linearize`` is a bound ``ObservationModel.linearize`` (its
    ``aux_in_axes`` honours ``aux_per_pixel = False`` — weight matrices
    whose leading dim happens to equal ``n_pix`` must not be split);
    plain closures fall back to the leading-axis heuristic.
    """
    n_pix, p = x.shape
    n_blocks = -(-n_pix // block)
    block = -(-n_pix // n_blocks)  # even split under the same memory bound
    n_pad = n_blocks * block - n_pix
    x_pad = jnp.pad(x, ((0, n_pad), (0, 0)), mode="edge")

    leaves, treedef = jax.tree.flatten(operator_params)

    owner = getattr(linearize, "__self__", None)
    if owner is not None and hasattr(owner, "aux_in_axes"):
        # flatten_up_to aligns the operator's in_axes tree (0 = mapped,
        # None = broadcast) with the param leaves position by position.
        axes = treedef.flatten_up_to(
            owner.aux_in_axes(operator_params, n_pix)
        )
        per_pixel_flags = [a == 0 for a in axes]
    else:
        per_pixel_flags = [
            (hasattr(leaf, "ndim") and leaf.ndim > 0
             and leaf.shape[0] == n_pix)
            for leaf in leaves
        ]
    mapped_idx = [i for i, f in enumerate(per_pixel_flags) if f]
    mapped = [
        jnp.pad(
            jnp.asarray(leaves[i]),
            ((0, n_pad),) + ((0, 0),) * (leaves[i].ndim - 1),
            mode="edge",
        ).reshape((n_blocks, block) + leaves[i].shape[1:])
        for i in mapped_idx
    ]

    def body(xs):
        xb = xs[0]
        ls = list(leaves)
        for i, leaf_b in zip(mapped_idx, xs[1:]):
            ls[i] = leaf_b
        lin = _call_linearize(
            linearize, jax.tree.unflatten(treedef, ls), xb
        )
        return lin.h0, lin.jac

    h0s, jacs = jax.lax.map(
        body, (x_pad.reshape(n_blocks, block, p), *mapped)
    )
    n_bands = h0s.shape[1]
    h0 = jnp.moveaxis(h0s, 0, 1).reshape(n_bands, n_blocks * block)
    # kafkalint: disable=kernel-relayout — block-axis merge of the
    # lax.map outputs, not a (B, n, p) -> (B*p, n) lane relayout: the
    # Jacobian keeps its dense layout here and reaches the kernel (if at
    # all) through the jac_to_rows shim.
    jac = jnp.moveaxis(jacs, 0, 1).reshape(n_bands, n_blocks * block, p)
    return Linearization(h0=h0[:, :n_pix], jac=jac[:, :n_pix])


@functools.partial(jax.jit, static_argnums=(0, 6, 7, 8, 9, 10, 11, 12))
def _assimilate_date_impl(
    linearize: LinearizeFn,
    obs: BandBatch,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
    operator_params: Any,
    solver_options: Any,
    hessian_forward: Any,
    linearize_block: Any,
    use_pallas: bool,
    per_pixel_convergence: bool,
    inkernel_linearize: bool,
    min_iterations: Any,
    max_iterations: Any,
    corrupt: Any = None,
):
    opts = dict(solver_options or {})
    if min_iterations is not None:
        opts["min_iterations"] = min_iterations
    if max_iterations is not None:
        opts["max_iterations"] = max_iterations
    return iterated_solve(
        linearize, obs, x_forecast, p_inv_forecast, operator_params,
        hessian_forward=hessian_forward, linearize_block=linearize_block,
        use_pallas=use_pallas,
        per_pixel_convergence=per_pixel_convergence,
        inkernel_linearize=inkernel_linearize, corrupt=corrupt, **opts
    )


def assimilate_date_jit(
    linearize: LinearizeFn,
    obs: BandBatch,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
    operator_params: Any = None,
    solver_options: Any = None,
    hessian_forward: Any = None,
):
    """Jitted entry point for one date's full multi-band assimilation.

    ``linearize`` (and ``hessian_forward``, when used) are static
    arguments: pass ONE stable callable per observation-operator
    configuration and feed all per-date data through ``operator_params``
    (a traced pytree) — a fresh closure per date would recompile the whole
    multi-iteration program every timestep.

    Numeric solver options (tol, relaxation, bounds...) flow through as
    traced values; structural options (``linearize_block`` — changes the
    compiled program's shape — ``use_pallas`` / ``inkernel_linearize`` —
    swap the solve kernel — and the iteration bounds, which become the
    in-kernel loop's static trip count) are split out as static
    arguments here.
    """
    opts = dict(solver_options or {})
    statics = _split_structural_options(opts)
    # solver.pixel chaos hook (host-side check; None when disarmed — the
    # production compiled program carries no corruption argument).
    corrupt = solver_health.corruption_mask(x_forecast.shape[0])
    return _assimilate_date_impl(
        linearize, obs, x_forecast, p_inv_forecast, operator_params,
        opts or None, hessian_forward, *statics,
        None if corrupt is None else jnp.asarray(corrupt, jnp.float32),
    )


# Option keys that change the compiled program's STRUCTURE (shape, kernel
# choice, loop trip count) rather than riding it as traced data.  Batch
# members must agree on all of them — they become the bucket's statics.
STRUCTURAL_OPTION_KEYS = (
    "linearize_block", "use_pallas", "per_pixel_convergence",
    "inkernel_linearize", "min_iterations", "max_iterations",
)


def _split_structural_options(opts: dict):
    """Pop the structural options out of ``opts`` (mutated in place,
    leaving only traced numeric leaves) and return them normalised in
    ``_assimilate_date_impl`` static-argument order."""
    block = opts.pop("linearize_block", None)
    use_pallas = bool(opts.pop("use_pallas", False))
    inkernel = bool(opts.pop("inkernel_linearize", True))
    per_pixel = bool(opts.pop("per_pixel_convergence", False))
    min_it = opts.pop("min_iterations", None)
    max_it = opts.pop("max_iterations", None)
    return (
        None if block is None else int(block),
        use_pallas, per_pixel, inkernel,
        None if min_it is None else int(min_it),
        None if max_it is None else int(max_it),
    )


def structural_options(solver_options) -> tuple:
    """The structural-option fingerprint of an option dict (normalised,
    fixed order) — the piece of a serve shape bucket key that comes from
    solver options.  Does not mutate the input."""
    return _split_structural_options(dict(solver_options or {}))


def stack_solver_options(options_list):
    """Merge per-member solver-option dicts into ONE batched dict for
    ``assimilate_date_batch_jit``: structural options must agree across
    members (they shape the compiled program) and pass through as plain
    values; every numeric leaf gains a leading member axis via
    ``jnp.stack`` so each vmapped member sees exactly its own value.

    Raises ``ValueError`` when members disagree structurally or carry
    different option keys — such requests belong to different shape
    buckets and must not share a launch.
    """
    dicts = [dict(o or {}) for o in options_list]
    statics = [_split_structural_options(d) for d in dicts]
    if any(s != statics[0] for s in statics[1:]):
        raise ValueError(
            "batch members disagree on structural solver options: "
            f"{[s for s in statics]}"
        )
    keys = sorted(dicts[0])
    if any(sorted(d) != keys for d in dicts[1:]):
        raise ValueError(
            "batch members carry different solver-option keys: "
            f"{[sorted(d) for d in dicts]}"
        )
    out = {}
    for k in keys:
        out[k] = jax.tree.map(
            lambda *leaves: jnp.stack([jnp.asarray(v) for v in leaves]),
            *[d[k] for d in dicts],
        )
    for key, value in zip(STRUCTURAL_OPTION_KEYS, statics[0]):
        if value is not None:
            out[key] = value
    return out


@functools.partial(jax.jit, static_argnums=(0, 6, 7, 8, 9, 10, 11, 12))
def _assimilate_batch_impl(
    linearize: LinearizeFn,
    obs: BandBatch,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
    operator_params: Any,
    solver_options: Any,
    hessian_forward: Any,
    linearize_block: Any,
    use_pallas: bool,
    per_pixel_convergence: bool,
    inkernel_linearize: bool,
    min_iterations: Any,
    max_iterations: Any,
    corrupt: Any = None,
):
    def _member(obs_m, x_m, p_inv_m, params_m, opts_m, corrupt_m):
        opts = dict(opts_m or {})
        if min_iterations is not None:
            opts["min_iterations"] = min_iterations
        if max_iterations is not None:
            opts["max_iterations"] = max_iterations
        return iterated_solve(
            linearize, obs_m, x_m, p_inv_m, params_m,
            hessian_forward=hessian_forward,
            linearize_block=linearize_block, use_pallas=use_pallas,
            per_pixel_convergence=per_pixel_convergence,
            inkernel_linearize=inkernel_linearize, corrupt=corrupt_m,
            **opts,
        )

    in_axes = (
        0, 0, 0,
        None if operator_params is None else 0,
        None if not solver_options else 0,
        None if corrupt is None else 0,
    )
    return jax.vmap(_member, in_axes=in_axes)(
        obs, x_forecast, p_inv_forecast, operator_params,
        solver_options, corrupt,
    )


def assimilate_date_batch_jit(
    linearize: LinearizeFn,
    obs: BandBatch,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
    operator_params: Any = None,
    solver_options: Any = None,
    hessian_forward: Any = None,
    corrupt: Any = None,
):
    """Coalesced-serving twin of :func:`assimilate_date_jit`: K compatible
    members stacked on a leading axis ride ONE launch.

    Every traced argument carries a leading member axis K: ``obs`` leaves
    are (K, n_bands, n_pad), states (K, n_pad, p), information matrices
    (K, n_pad, ...), ``operator_params`` leaves stacked leaf-wise (or
    None when every member's aux is None).  ``solver_options`` is a
    *batched* dict as produced by :func:`stack_solver_options` — numeric
    leaves stacked to (K, ...), structural options plain and shared.

    The batching is ``jax.vmap`` over members, NOT pixel concatenation:
    each member keeps its own convergence norm, its own iteration count
    (the batched ``lax.while_loop`` freezes finished members via select)
    and its own ``norm_denominator`` — so each member's (n_pad, p) output
    slice is bit-identical to what a solo ``assimilate_date_jit`` call
    would have produced.  Diagnostics come back member-stacked too.

    ``corrupt``, when given, is a (K, n_pix) mask — rows of zeros leave
    their member untouched (``where`` against an all-False row is the
    identity), so a batch may mix armed and unarmed members.
    """
    opts = dict(solver_options or {})
    statics = _split_structural_options(opts)
    return _assimilate_batch_impl(
        linearize, obs, x_forecast, p_inv_forecast, operator_params,
        opts or None, hessian_forward, *statics,
        None if corrupt is None else jnp.asarray(corrupt, jnp.float32),
    )


def lower_date_program(
    linearize: LinearizeFn,
    obs: BandBatch,
    x_forecast: jnp.ndarray,
    p_inv_forecast: jnp.ndarray,
    operator_params: Any = None,
    solver_options: Any = None,
    hessian_forward: Any = None,
    batch_size: Any = None,
):
    """Ahead-of-time ``lower().compile()`` of one serve shape bucket.

    Called with *representative concrete arguments* (zeros of the
    bucket's exact shapes, the bucket's real option dict — concrete
    Python floats lower to the same weak-typed avals the live dispatch
    traces) so the compiled executable lands in the persistent XLA
    compilation cache NOW; the first live request against this bucket
    then pays a cache hit instead of a compile.  ``batch_size=None``
    lowers the solo per-date program, an integer K lowers the K-member
    batched program (arguments must already carry the leading K axis).

    Returns the ``jax.stages.Compiled`` object (useful for memory
    analysis); the side effect on the compilation cache is the point.
    """
    opts = dict(solver_options or {})
    statics = _split_structural_options(opts)
    target = (
        _assimilate_date_impl if batch_size is None
        else _assimilate_batch_impl
    )
    lowered = target.lower(
        linearize, obs, x_forecast, p_inv_forecast, operator_params,
        opts or None, hessian_forward, *statics, None,
    )
    return lowered.compile()


class ScanWindowStats(NamedTuple):
    """Per-window telemetry stacked over a fused scan block — computed
    on device inside each scan step (same quantities as the trailing
    ``SolveDiagnostics`` fields) so the whole block's telemetry rides
    the block's single packed device->host read.  The solve-health
    fields are None when the block ran a mode without health tracking
    (per_pixel_convergence, large-p dense fallback); ``health_verdicts``
    is the one per-PIXEL member (the QA band's source — an output
    product like the states, not a diagnostic scalar read)."""

    chi2_per_band: jnp.ndarray   # (K, n_bands)
    clipped_count: jnp.ndarray   # (K,) int32
    nodata_count: jnp.ndarray    # (K,) int32
    cap_bailout_count: Any = None       # (K,) int32
    damped_recovered_count: Any = None  # (K,) int32
    quarantined_count: Any = None       # (K,) int32
    nonfinite_count: Any = None         # (K,) int32
    clip_saturated_count: Any = None    # (K, p) int32
    health_verdicts: Any = None         # (K, n_pix) int32 QA bitmask


@functools.partial(jax.jit, static_argnums=(0, 9, 11, 12, 13, 14, 15, 16, 17))
def _assimilate_scan_impl(
    linearize: LinearizeFn,
    obs_stacked: BandBatch,
    x_analysis0: jnp.ndarray,
    p_inv_analysis0: jnp.ndarray,
    aux_stacked: Any,
    m_matrix: jnp.ndarray,
    q_diag: jnp.ndarray,
    prior_mean: Any,
    prior_inv: Any,
    state_propagator: Any,
    solver_options: Any,
    hessian_forward: Any,
    linearize_block: Any,
    per_pixel_convergence: bool,
    use_pallas: bool,
    inkernel_linearize: bool,
    min_iterations: Any,
    max_iterations: Any,
    corrupt: Any = None,
):
    from .linalg import batched_diagonal, spd_inverse_batched
    from .propagators import advance as advance_fn

    opts = dict(solver_options or {})
    if min_iterations is not None:
        opts["min_iterations"] = min_iterations
    if max_iterations is not None:
        opts["max_iterations"] = max_iterations
    # Structural: does this block's solve mode track health?  Mirrors
    # the iterated_solve gating exactly (trace-time constant).
    has_health = (
        not per_pixel_convergence
        and x_analysis0.shape[-1] <= UNROLL_MAX_P
        and obs_stacked.y.shape[1] <= 32
    )

    def step(carry, inp):
        x_a, p_inv_a = carry
        bands_k, aux_k = inp
        x_f, p_f, p_f_inv = advance_fn(
            x_a, None, p_inv_a, m_matrix, q_diag,
            prior_mean=prior_mean, prior_cov_inverse=prior_inv,
            state_propagator=state_propagator,
        )
        if p_f_inv is None:
            p_f_inv = spd_inverse_batched(p_f)
        x_n, p_inv_n, diags = iterated_solve(
            linearize, bands_k, x_f, p_f_inv, aux_k,
            hessian_forward=hessian_forward,
            linearize_block=linearize_block,
            use_pallas=use_pallas,
            per_pixel_convergence=per_pixel_convergence,
            inkernel_linearize=inkernel_linearize, corrupt=corrupt,
            **opts
        )
        out = (
            x_n, batched_diagonal(p_inv_n),
            diags.n_iterations, diags.convergence_norm,
            diags.chi2_per_band, diags.clipped_count,
            diags.nodata_count,
        )
        # Solve-health outputs stack along the window axis (a static
        # structural difference, like the per-pixel masks below).
        if has_health:
            out = out + (
                diags.cap_bailout_count, diags.damped_recovered_count,
                diags.quarantined_count, diags.nonfinite_count,
                diags.clip_saturated_count, diags.health_verdicts,
            )
        # Per-pixel convergence masks stack along the window axis so the
        # fused path keeps the same per-pixel diagnostics as the unfused
        # one (a static structural difference: the mode is a static arg).
        if per_pixel_convergence:
            out = out + (diags.converged_mask,)
        return (x_n, p_inv_n), out

    (x_fin, p_inv_fin), ys = jax.lax.scan(
        step, (x_analysis0, p_inv_analysis0), (obs_stacked, aux_stacked)
    )
    xs, diag_s, iters, norms = ys[:4]
    idx = 7
    health = {}
    if has_health:
        health = dict(
            cap_bailout_count=ys[7], damped_recovered_count=ys[8],
            quarantined_count=ys[9], nonfinite_count=ys[10],
            clip_saturated_count=ys[11], health_verdicts=ys[12],
        )
        idx = 13
    stats = ScanWindowStats(
        chi2_per_band=ys[4], clipped_count=ys[5], nodata_count=ys[6],
        **health,
    )
    converged = ys[idx] if per_pixel_convergence else None
    return x_fin, p_inv_fin, xs, diag_s, iters, norms, converged, stats


def assimilate_windows_scan(
    linearize: LinearizeFn,
    obs_stacked: BandBatch,
    x_analysis0: jnp.ndarray,
    p_inv_analysis0: jnp.ndarray,
    aux_stacked: Any = None,
    m_matrix: jnp.ndarray = None,
    q_diag: jnp.ndarray = None,
    prior_mean: Any = None,
    prior_inv: Any = None,
    state_propagator: Any = None,
    solver_options: Any = None,
    hessian_forward: Any = None,
):
    """K consecutive advance→assimilate windows as ONE device program.

    The temporal axis of SURVEY §2.3 mapped onto ``lax.scan``: each step
    advances the previous analysis (propagator and/or prior blend, the
    ``propagate_and_blend_prior`` semantics) and runs the full Gauss-Newton
    assimilation of that window's observations.  The host dispatches once
    per K windows instead of once per date, and the per-window analyses
    come back as two stacked arrays — on a slow device link that turns K
    round-trips into one.

    ``obs_stacked`` is a ``BandBatch`` with a leading window axis
    ``(K, n_bands, n_pix)``; ``aux_stacked`` a pytree whose array leaves
    carry the same leading axis.  The prior (if any) must be
    time-invariant across the K windows — the engine only fuses windows
    whose prior declares ``date_invariant``.

    Returns ``(x_final, p_inv_final, xs (K, n, p), p_inv_diags (K, n, p),
    n_iterations (K,), convergence_norms (K,), converged_masks,
    window_stats)`` — ``converged_masks`` a ``(K, n)`` bool array under
    ``per_pixel_convergence`` (else None), ``window_stats`` a
    :class:`ScanWindowStats` of stacked per-window telemetry scalars.
    """
    opts = dict(solver_options or {})
    block = opts.pop("linearize_block", None)
    # Structural (static) options split out exactly as in
    # assimilate_date_jit: ``use_pallas`` swaps each scan step's solve for
    # the fused VMEM-resident kernel (``inkernel_linearize`` additionally
    # moves the whole GN loop inside it for capable operators, and the
    # iteration bounds become the in-kernel static trip count) — the scan
    # carries them as static arguments, so the fused and XLA programs are
    # distinct jit entries.
    use_pallas = bool(opts.pop("use_pallas", False))
    inkernel = bool(opts.pop("inkernel_linearize", True))
    per_pixel = bool(opts.pop("per_pixel_convergence", False))
    min_it = opts.pop("min_iterations", None)
    max_it = opts.pop("max_iterations", None)
    if m_matrix is None:
        m_matrix = jnp.eye(x_analysis0.shape[-1], dtype=jnp.float32)
    if q_diag is None:
        q_diag = jnp.zeros((x_analysis0.shape[-1],), jnp.float32)
    # solver.pixel chaos hook — same mask for every window of the block
    # (the armed pixel set is positional, not temporal).
    corrupt = solver_health.corruption_mask(x_analysis0.shape[0])
    return _assimilate_scan_impl(
        linearize, obs_stacked, x_analysis0, p_inv_analysis0, aux_stacked,
        m_matrix, q_diag, prior_mean, prior_inv, state_propagator,
        opts or None, hessian_forward,
        None if block is None else int(block), per_pixel, use_pallas,
        inkernel,
        None if min_it is None else int(min_it),
        None if max_it is None else int(max_it),
        None if corrupt is None else jnp.asarray(corrupt, jnp.float32),
    )
