"""IR-level static analysis of device programs (BASELINE.md "Program
contracts").

``tools/programlint.py`` drives this package: every registered device
program is abstractly traced (CPU-only ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` specs) and verified against machine-checkable
contracts — dtype hygiene, transfer-freedom, relayout-freedom, and (for
mesh programs) a collective manifest — with checked-in fingerprint
manifests under ``contracts/`` guarding against silent drift.
"""

from .checkers import (
    AnalysisResult,
    ContractFinding,
    analyze,
    fingerprint,
    manifest_payload,
)
from .registry import (
    REGISTRY,
    BuiltProgram,
    ProgramSpec,
    get_specs,
    register_program,
)
from .trace import TracedProgram, trace_program

__all__ = [
    "AnalysisResult",
    "BuiltProgram",
    "ContractFinding",
    "ProgramSpec",
    "REGISTRY",
    "TracedProgram",
    "analyze",
    "contracts_dir",
    "contracts_snapshot",
    "fingerprint",
    "get_specs",
    "manifest_payload",
    "register_program",
    "trace_program",
]


def contracts_dir() -> str:
    """The checked-in manifest directory (next to this package)."""
    import os

    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "contracts")


_SNAPSHOT_CACHE = {}


def contracts_snapshot() -> dict:
    """Compact trace-level snapshot for ``bench.py`` artifacts: per-
    program fingerprints plus the contract-finding count.  Trace-only
    (no compile step, so no collective inventory) and cached — the
    benchmark assembles many artifacts per process and the programs
    don't change mid-run.  Never raises: an analysis failure becomes an
    ``error`` field, not a dead benchmark."""
    if "snap" in _SNAPSHOT_CACHE:
        return _SNAPSHOT_CACHE["snap"]
    try:
        from . import programs  # noqa: F401  (registration side effect)

        result = analyze(
            get_specs(), contracts_dir=None, compile_collectives=False
        )
        snap = {
            "programs": {
                name: payload["fingerprint"]
                for name, payload in sorted(result.reports.items())
            },
            "findings": len(result.findings),
            "clean": result.clean,
            "error": None,
        }
    except Exception as exc:  # pragma: no cover - defensive
        snap = {
            "programs": {}, "findings": None, "clean": None,
            "error": f"{type(exc).__name__}: {exc}",
        }
    _SNAPSHOT_CACHE["snap"] = snap
    return snap
