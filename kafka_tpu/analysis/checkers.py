"""Contract checkers over traced programs + fingerprint manifests.

Four checkers walk the IR of every registered program
(:mod:`kafka_tpu.analysis.trace`):

- ``dtype`` — no f64/c128 aval anywhere in device code (catches computed
  dtypes the AST ``implicit-f64`` lint cannot see), plus the
  bf16-readiness rule: reduce/dot primitives consuming bf16 must produce
  f32 accumulators.  The rule is armed now so the planned mixed-precision
  PR (ROADMAP) inherits its gate instead of shipping one.
- ``transfer`` — no callback/debug primitives and no host-targeted
  ``device_put`` inside the traced body: the static twin of the runtime
  ``kafka_engine_device_reads_total == dispatches`` invariant.
- ``relayout`` — for programs registered ``relayout_clean``, no
  transpose/reshape touching a rank-3 (Jacobian-shaped) intermediate —
  the ``tests/test_solvers.py`` in-kernel jaxpr assertion generalised
  into a reusable checker.
- ``collective`` — for mesh programs, every collective op family in the
  compiled HLO must appear in the program's declared manifest; an
  unmanifested all-gather is called out as implicit full replication of
  a sharded operand.

Manifests: one JSON per program under ``contracts/`` records the
primitive/dtype census and a fingerprint hash.  ``compare_manifest``
turns any divergence into a ``drift`` finding (kafkalint-style:
regenerate deliberately with ``--update``, never silently).  Waivers
live inside each manifest as ``{"checker", "contains", "reason"}``
entries with stale-waiver semantics — a waiver matching nothing is
itself a finding, so the waiver set only shrinks.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

from .registry import ProgramSpec
from .trace import TracedProgram, iter_eqns

#: dtypes forbidden anywhere in a device program.
FORBIDDEN_DTYPES = ("float64", "complex128")

#: primitives whose presence in a jitted body is a host round-trip.
TRANSFER_PRIMITIVES = (
    "pure_callback", "io_callback", "debug_callback", "debug_print",
)

#: reduce/dot primitives the bf16 accumulate rule applies to.
REDUCE_DOT_PRIMITIVES = (
    "dot_general", "reduce_sum", "reduce_prod", "reduce_window_sum",
    "cumsum",
)


@dataclasses.dataclass(frozen=True, order=True)
class ContractFinding:
    """One violated contract on one program."""

    program: str
    checker: str    # dtype | transfer | relayout | collective | drift |
    #                 manifest | stale-waiver | trace
    message: str

    def format(self) -> str:
        return f"{self.program}: [{self.checker}] {self.message}"


# ---------------------------------------------------------------------------
# The four IR checkers.
# ---------------------------------------------------------------------------

def check_dtype(tp: TracedProgram) -> List[ContractFinding]:
    out: List[ContractFinding] = []
    for bad in FORBIDDEN_DTYPES:
        n = tp.dtypes.get(bad, 0)
        if n:
            culprit = _first_eqn_with_dtype(tp, bad)
            out.append(ContractFinding(
                program=tp.spec.name, checker="dtype",
                message=(
                    f"{bad} appears on {n} value(s) in the traced program"
                    f"{culprit} — device code is float32-only (the AST "
                    "implicit-f64 lint cannot see computed dtypes; this "
                    "checker can)"
                ),
            ))
    for eqn in iter_eqns(tp.closed.jaxpr):
        if eqn.primitive.name not in REDUCE_DOT_PRIMITIVES:
            continue
        in_bf16 = any(
            str(getattr(v.aval, "dtype", "")) == "bfloat16"
            for v in eqn.invars if hasattr(v, "aval")
        )
        out_bf16 = any(
            str(getattr(v.aval, "dtype", "")) == "bfloat16"
            for v in eqn.outvars
        )
        if in_bf16 and out_bf16:
            out.append(ContractFinding(
                program=tp.spec.name, checker="dtype",
                message=(
                    f"'{eqn.primitive.name}' consumes bfloat16 and "
                    "accumulates in bfloat16 — reduce/dot primitives on "
                    "bf16 storage must produce f32 accumulators "
                    "(preferred_element_type=float32); the bf16-readiness "
                    "gate for the mixed-precision arc"
                ),
            ))
    return out


def _first_eqn_with_dtype(tp: TracedProgram, dtype: str) -> str:
    for eqn in iter_eqns(tp.closed.jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None and str(getattr(aval, "dtype", "")) == dtype:
                shape = tuple(getattr(aval, "shape", ()))
                return (f" (first producer: '{eqn.primitive.name}' "
                        f"-> {dtype}{list(shape)})")
    return ""


def check_transfer(tp: TracedProgram) -> List[ContractFinding]:
    out: List[ContractFinding] = []
    counts: Dict[str, int] = {}
    host_puts = 0
    for eqn in iter_eqns(tp.closed.jaxpr):
        name = eqn.primitive.name
        if name in TRANSFER_PRIMITIVES:
            counts[name] = counts.get(name, 0) + 1
        elif name == "device_put" and _is_host_device_put(eqn):
            host_puts += 1
    for name in sorted(counts):
        out.append(ContractFinding(
            program=tp.spec.name, checker="transfer",
            message=(
                f"'{name}' primitive appears {counts[name]}x inside the "
                "traced body — a host round-trip per execution; the "
                "device program must stay transfer-free (one packed "
                "read per window, outside the jitted body)"
            ),
        ))
    if host_puts:
        out.append(ContractFinding(
            program=tp.spec.name, checker="transfer",
            message=(
                f"device_put with an explicit device/memory target "
                f"appears {host_puts}x inside the traced body — a "
                "forced placement (host staging) in device code; "
                "sharding constraints are fine, concrete-device puts "
                "are not"
            ),
        ))
    return out


def _is_host_device_put(eqn) -> bool:
    """Only flag device_put with a concrete placement target.  The
    benign trace-time form (constant promotion) carries
    ``devices=[None]``; in-program sharding constraints carry Sharding
    objects, which are layout hints, not transfers."""
    try:
        from jax.sharding import Sharding
    except Exception:                                # pragma: no cover
        Sharding = ()
    for dev in (eqn.params.get("devices") or ()):
        if dev is None or isinstance(dev, Sharding):
            continue
        return True
    return False


def check_relayout(tp: TracedProgram) -> List[ContractFinding]:
    if not tp.spec.relayout_clean:
        return []
    out: List[ContractFinding] = []
    n_transpose = n_reshape = 0
    example = ""
    for eqn in iter_eqns(tp.closed.jaxpr):
        name = eqn.primitive.name
        if name not in ("transpose", "reshape"):
            continue
        aval = getattr(eqn.invars[0], "aval", None)
        if aval is None or getattr(aval, "ndim", 0) < 3:
            continue
        if name == "transpose":
            n_transpose += 1
        else:
            n_reshape += 1
        if not example:
            shape = list(getattr(aval, "shape", ()))
            example = f" (e.g. '{name}' on {aval.dtype}{shape})"
    if n_transpose or n_reshape:
        out.append(ContractFinding(
            program=tp.spec.name, checker="relayout",
            message=(
                f"{n_transpose} transpose / {n_reshape} reshape on rank-3 "
                f"intermediates{example} in a program registered "
                "relayout_clean — a (B, n, p) Jacobian relayout is an "
                "extra HBM pass the in-kernel path exists to delete "
                "(jac_to_rows is the only sanctioned shim, and it lives "
                "outside relayout-clean programs)"
            ),
        ))
    return out


def check_collectives(tp: TracedProgram) -> List[ContractFinding]:
    if tp.collectives is None:
        return []
    out: List[ContractFinding] = []
    allowed = set(tp.spec.collectives)
    for op in sorted(tp.collectives):
        if op in allowed:
            continue
        hint = (
            " — an implicit FULL REPLICATION of a pixel-sharded operand "
            "(GSPMD gathered a shard because some op's sharding rule "
            "could not keep it partitioned)"
            if op == "all-gather" else
            " — a cross-device dependency the program's manifest does "
            "not declare"
        )
        out.append(ContractFinding(
            program=tp.spec.name, checker="collective",
            message=(
                f"compiled program contains {tp.collectives[op]}x "
                f"'{op}' not in its collectives manifest "
                f"{sorted(allowed) or '[]'}{hint}; either the sharding "
                "regressed or the manifest must be extended deliberately"
            ),
        ))
    return out


CHECKERS = (check_dtype, check_transfer, check_relayout, check_collectives)


def run_checkers(tp: TracedProgram) -> List[ContractFinding]:
    findings: List[ContractFinding] = []
    for checker in CHECKERS:
        findings.extend(checker(tp))
    return findings


# ---------------------------------------------------------------------------
# Fingerprints + manifests.
# ---------------------------------------------------------------------------

def fingerprint(tp: TracedProgram) -> str:
    """Deterministic 16-hex-digit digest of the trace-level shape of the
    program: primitive inventory + dtype census + transfer count.  Trace
    level on purpose — it is device-count independent and reproducible on
    any host, unlike compiled-HLO hashes."""
    transfer_count = sum(
        tp.primitives.get(p, 0) for p in TRANSFER_PRIMITIVES
    )
    payload = json.dumps(
        {
            "primitives": dict(sorted(tp.primitives.items())),
            "dtypes": dict(sorted(tp.dtypes.items())),
            "transfer_count": transfer_count,
        },
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def manifest_payload(tp: TracedProgram,
                     waivers: Optional[List[dict]] = None) -> dict:
    transfer_count = sum(
        tp.primitives.get(p, 0) for p in TRANSFER_PRIMITIVES
    )
    return {
        "program": tp.spec.name,
        "description": tp.spec.description,
        "fingerprint": fingerprint(tp),
        "eqns": tp.n_eqns,
        "primitives": dict(sorted(tp.primitives.items())),
        "dtypes": dict(sorted(tp.dtypes.items())),
        "transfer_count": transfer_count,
        "relayout_clean": tp.spec.relayout_clean,
        "mesh_devices": tp.mesh_devices,
        "collectives": (
            None if tp.collectives is None
            else dict(sorted(tp.collectives.items()))
        ),
        "collectives_manifest": sorted(tp.spec.collectives),
        "waivers": list(waivers or ()),
    }


def manifest_path(contracts_dir: str, name: str) -> str:
    return os.path.join(contracts_dir, f"{name}.json")


def load_manifest(contracts_dir: str, name: str) -> Optional[dict]:
    path = manifest_path(contracts_dir, name)
    if not os.path.isfile(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_manifest(contracts_dir: str, payload: dict) -> str:
    os.makedirs(contracts_dir, exist_ok=True)
    path = manifest_path(contracts_dir, payload["program"])
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def compare_manifest(tp: TracedProgram,
                     stored: Optional[dict]) -> List[ContractFinding]:
    """Drift findings between the fresh trace and the checked-in manifest
    (kafkalint-style: accept drift deliberately with ``--update``)."""
    name = tp.spec.name
    if stored is None:
        return [ContractFinding(
            program=name, checker="manifest",
            message=(
                "no checked-in contract manifest "
                f"(kafka_tpu/analysis/contracts/{name}.json) — run "
                "python -m tools.programlint --update to record the "
                "current fingerprint"
            ),
        )]
    out: List[ContractFinding] = []
    fp_new = fingerprint(tp)
    fp_old = stored.get("fingerprint")
    if fp_old != fp_new:
        out.append(ContractFinding(
            program=name, checker="drift",
            message=(
                f"trace fingerprint drifted {fp_old} -> {fp_new}"
                f"{_census_diff(stored.get('primitives') or {}, tp.primitives)} "
                "— the device program changed shape; review the diff and "
                "accept deliberately with python -m tools.programlint "
                "--update"
            ),
        ))
    old_coll = stored.get("collectives")
    if (old_coll is not None and tp.collectives is not None
            and dict(old_coll) != dict(tp.collectives)):
        out.append(ContractFinding(
            program=name, checker="drift",
            message=(
                f"collective inventory drifted {dict(old_coll)} -> "
                f"{dict(tp.collectives)} — the compiled partitioning "
                "changed; review and accept with --update"
            ),
        ))
    return out


def _census_diff(old: Dict[str, int], new: Dict[str, int],
                 limit: int = 6) -> str:
    changed = []
    for key in sorted(set(old) | set(new)):
        a, b = old.get(key, 0), new.get(key, 0)
        if a != b:
            changed.append(f"{key} {a}->{b}")
    if not changed:
        return ""
    shown = ", ".join(changed[:limit])
    more = f", +{len(changed) - limit} more" if len(changed) > limit else ""
    return f" (primitive deltas: {shown}{more})"


def apply_waivers(findings: List[ContractFinding], waivers: List[dict],
                  program: str) -> List[ContractFinding]:
    """Drop waived findings; report waivers that match nothing as
    ``stale-waiver`` findings (the manifest-embedded twin of kafkalint's
    stale-baseline semantics)."""
    hits = [0] * len(waivers)

    def waived(f: ContractFinding) -> bool:
        ok = False
        for i, w in enumerate(waivers):
            if (w.get("checker") == f.checker
                    and w.get("contains", "") in f.message):
                hits[i] += 1
                ok = True
        return ok

    kept = [f for f in findings if f.checker == "stale-waiver" or
            not waived(f)]
    for i, w in enumerate(waivers):
        if hits[i] == 0:
            kept.append(ContractFinding(
                program=program, checker="stale-waiver",
                message=(
                    f"waiver for [{w.get('checker')}] containing "
                    f"{w.get('contains', '')!r} matches no current "
                    "finding — remove it (reason was: "
                    f"{w.get('reason', 'none given')!r})"
                ),
            ))
    return kept


# ---------------------------------------------------------------------------
# The full analysis pass.
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class AnalysisResult:
    findings: List[ContractFinding]
    reports: Dict[str, dict]        # program -> fresh manifest payload
    updated: List[str]              # manifest paths written (--update)

    @property
    def clean(self) -> bool:
        return not self.findings


def analyze(specs, contracts_dir: Optional[str],
            update: bool = False,
            compile_collectives: bool = True) -> AnalysisResult:
    """Trace + check every spec; compare (or regenerate) manifests.

    ``contracts_dir=None`` skips manifest handling entirely (the fixture
    tests exercise the checkers in isolation that way).
    """
    from .trace import trace_program

    findings: List[ContractFinding] = []
    reports: Dict[str, dict] = {}
    updated: List[str] = []
    for spec in specs:
        try:
            tp = trace_program(
                spec, compile_collectives=compile_collectives
            )
        except Exception as exc:  # any builder failure becomes a finding
            findings.append(ContractFinding(
                program=spec.name, checker="trace",
                message=(
                    f"builder/trace failed: {type(exc).__name__}: "
                    f"{exc}"
                ),
            ))
            continue
        stored = (
            load_manifest(contracts_dir, spec.name)
            if contracts_dir else None
        )
        waivers = list((stored or {}).get("waivers") or ())
        payload = manifest_payload(tp, waivers=waivers)
        reports[spec.name] = payload
        prog_findings = run_checkers(tp)
        if contracts_dir:
            if update:
                updated.append(write_manifest(contracts_dir, payload))
            else:
                prog_findings.extend(compare_manifest(tp, stored))
        findings.extend(
            apply_waivers(prog_findings, waivers, spec.name)
        )
    return AnalysisResult(
        findings=sorted(findings), reports=reports, updated=updated
    )
