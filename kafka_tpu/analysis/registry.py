"""Device-program registry: canonical abstract specs for contract analysis.

Every entry point whose compiled form the repo's perf story depends on —
the per-date solve, the fused temporal scan, the smoother sweep, each
operator's linearize, the mesh-sharded step — is registered here with a
*builder* that reconstructs the callable plus a canonical abstract
argument tuple (``jax.ShapeDtypeStruct`` leaves, no concrete data, no
device).  ``tools/programlint.py`` traces each registered program with
``jax.make_jaxpr`` and verifies machine-checkable contracts over the IR
(:mod:`kafka_tpu.analysis.checkers`): dtype hygiene, no host transfers,
no Jacobian relayouts, and — for mesh programs — a manifest of permitted
collectives.

The registry is intentionally declarative and import-light: builders run
lazily at trace time, so importing this module (e.g. from kafkalint's
rule 21, which only reads ``COVERED_ENTRY_POINTS``) costs nothing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple


@dataclasses.dataclass
class BuiltProgram:
    """What a builder returns: the traceable callable and its canonical
    abstract arguments.  ``mesh_devices`` is the device count the builder's
    mesh actually spanned (0 = no mesh — the program is single-device and
    the collective checker does not apply)."""

    fn: Callable
    args: Tuple[Any, ...]
    mesh_devices: int = 0


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One registered device program.

    ``builder`` — zero-arg callable returning a :class:`BuiltProgram` (or
    a plain ``(fn, args)`` tuple).  Runs lazily at trace time.

    ``relayout_clean`` — this program promises NO transpose/reshape on
    rank-3 (Jacobian-shaped) intermediates; the relayout checker enforces
    it (the generalisation of the ``test_solvers.py`` in-kernel jaxpr
    assertion).

    ``collectives`` — the manifest of collective op families permitted in
    the compiled (GSPMD-partitioned) program; anything else is a finding.
    Only meaningful for mesh builders (``mesh_devices >= 2``).

    ``x64`` — trace under ``jax.experimental.enable_x64()``.  Production
    programs never set this (x64 stays off, f64 silently downcasts); the
    fixture specs use it so a seeded f64 upcast is *visible* to the dtype
    checker, and it arms the checker for any future x64-leak scenario.
    """

    name: str
    builder: Callable[[], Any]
    description: str = ""
    relayout_clean: bool = False
    collectives: Tuple[str, ...] = ()
    x64: bool = False

    def build(self) -> BuiltProgram:
        built = self.builder()
        if isinstance(built, BuiltProgram):
            return built
        fn, args = built
        return BuiltProgram(fn=fn, args=tuple(args))


#: name -> spec, in registration order (dicts preserve it).
REGISTRY: Dict[str, ProgramSpec] = {}


def register_program(name: str, *, description: str = "",
                     relayout_clean: bool = False,
                     collectives: Sequence[str] = (),
                     x64: bool = False,
                     registry: Optional[Dict[str, ProgramSpec]] = None):
    """Decorator registering a builder as a named program spec.

    ``registry`` defaults to the production :data:`REGISTRY`; fixture
    modules pass their own dict so seeded-violation specs never leak into
    the production analysis set.
    """
    target = REGISTRY if registry is None else registry

    def deco(builder: Callable[[], Any]) -> Callable[[], Any]:
        if name in target:
            raise ValueError(f"duplicate program name {name!r}")
        target[name] = ProgramSpec(
            name=name, builder=builder, description=description,
            relayout_clean=relayout_clean,
            collectives=tuple(collectives), x64=x64,
        )
        return builder

    return deco


def get_specs(names: Optional[Sequence[str]] = None,
              registry: Optional[Dict[str, ProgramSpec]] = None,
              ) -> Tuple[ProgramSpec, ...]:
    """The selected specs (all, in registration order, when ``names`` is
    None).  Unknown names raise ``KeyError`` with the known set."""
    reg = REGISTRY if registry is None else registry
    if names is None:
        return tuple(reg.values())
    unknown = [n for n in names if n not in reg]
    if unknown:
        raise KeyError(
            f"unknown program(s) {unknown}; known: {sorted(reg)}"
        )
    return tuple(reg[n] for n in names)
