"""Canonical abstract specs for every production device program.

Each builder reconstructs one real entry point exactly as the engine
dispatches it and pairs it with abstract ``ShapeDtypeStruct`` arguments
of canonical (small but structurally representative) shapes.  Tracing is
CPU-only abstract evaluation — no concrete data, no device transfers —
so the analysis runs anywhere, including in CI with no accelerator.

``COVERED_ENTRY_POINTS`` is the AST-readable twin of the registry:
kafkalint rule 21 (``unregistered-device-program``) parses this literal
and flags any jit/pjit/pallas_call/shard_map entry point in the device
packages whose def name is not listed here — registering a program and
naming its jitted def(s) below is the same act.  Keep the two in sync:
every name here must be reached by at least one registered builder.
"""

from __future__ import annotations

from .registry import BuiltProgram, register_program

#: jitted/pallas def names whose compiled bodies are traced by the
#: registered programs below (parsed by kafkalint rule 21 as a literal).
COVERED_ENTRY_POINTS = {
    # core/solvers.py — the per-date solve, its coalesced-serving twin
    # (vmap over a leading member axis) and the fused temporal scan.
    "_assimilate_date_impl",
    "_assimilate_batch_impl",
    "_assimilate_scan_impl",
    # core/pallas_solve.py — the packed solve and fused-update kernels
    # (traced inside the use_pallas date programs).
    "solve_rows",
    "_solve_kernel",
    "_fused_update_rows",
    "_fused_update_kernel",
    "_fused_gn_kernel",
    # smoother/rts_pass.py — the reverse RTS sweep.
    "_rts_sweep",
    # shard/step.py — the mesh-partitioned per-date step and forward.
    "_step",
    "_forward_apply",
}

#: canonical batch shapes: small enough to trace in <1 s each, large
#: enough that nothing degenerates (multi-block, multi-band, p > lanes).
N_PIX = 256
TIP_P = 7
TIP_BANDS = 2


def _sds(shape, dtype="float32"):
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def _tip_batch(n_pix=N_PIX, k_windows=None):
    """Abstract TIP problem: (obs BandBatch, x, p_inv) specs, optionally
    with a leading window axis on the observations."""
    from ..core.types import BandBatch

    lead = () if k_windows is None else (k_windows,)
    obs = BandBatch(
        y=_sds(lead + (TIP_BANDS, n_pix)),
        r_inv=_sds(lead + (TIP_BANDS, n_pix)),
        mask=_sds(lead + (TIP_BANDS, n_pix), "bool"),
    )
    return obs, _sds((n_pix, TIP_P)), _sds((n_pix, TIP_P, TIP_P))


def _date_program(solver_options):
    from ..core.solvers import assimilate_date_jit
    from ..obsops.twostream import TwoStreamOperator

    op = TwoStreamOperator()
    obs, x, p_inv = _tip_batch()

    def run(obs, x, p_inv):
        return assimilate_date_jit(
            op.linearize, obs, x, p_inv, None, dict(solver_options)
        )

    return run, (obs, x, p_inv)


@register_program(
    "date_twostream_xla",
    description="assimilate_date_jit, XLA path (two-stream TIP, "
                "out-of-kernel linearize + packed XLA solve)",
)
def _build_date_xla():
    return _date_program({"use_pallas": False, "max_iterations": 5})


@register_program(
    "date_twostream_inkernel",
    description="assimilate_date_jit, fused in-kernel path (whole GN "
                "loop VMEM-resident; the flagship perf program)",
    relayout_clean=True,
)
def _build_date_inkernel():
    return _date_program({
        "use_pallas": True, "inkernel_linearize": True,
        "min_iterations": 2, "max_iterations": 5,
    })


@register_program(
    "date_twostream_jac_to_rows",
    description="assimilate_date_jit, fused-update path through the "
                "sanctioned jac_to_rows relayout shim (out-of-kernel "
                "linearize feeding the Pallas solve)",
)
def _build_date_jac_to_rows():
    return _date_program({
        "use_pallas": True, "inkernel_linearize": False,
        "max_iterations": 5,
    })


@register_program(
    "date_batched_twostream_xla",
    description="assimilate_date_batch_jit: K=4 coalesced serve "
                "members (vmap over the leading member axis; each "
                "member's slice bit-identical to a solo date solve)",
)
def _build_date_batched_xla():
    from ..core.solvers import (
        assimilate_date_batch_jit, stack_solver_options,
    )
    from ..core.types import BandBatch
    from ..obsops.twostream import TwoStreamOperator

    k = 4
    op = TwoStreamOperator()
    obs = BandBatch(
        y=_sds((k, TIP_BANDS, N_PIX)),
        r_inv=_sds((k, TIP_BANDS, N_PIX)),
        mask=_sds((k, TIP_BANDS, N_PIX), "bool"),
    )
    x = _sds((k, N_PIX, TIP_P))
    p_inv = _sds((k, N_PIX, TIP_P, TIP_P))
    # Per-member numeric leaves stack to (K,) exactly as the serving
    # executor's stack_solver_options produces them.
    opts = stack_solver_options([
        {"use_pallas": False, "max_iterations": 5,
         "norm_denominator": float(N_PIX * (1 + i))}
        for i in range(k)
    ])

    def run(obs, x, p_inv):
        return assimilate_date_batch_jit(
            op.linearize, obs, x, p_inv, None, opts
        )

    return run, (obs, x, p_inv)


def _scan_program(solver_options, k_windows=3):
    from ..core.solvers import assimilate_windows_scan
    from ..obsops.twostream import TwoStreamOperator

    op = TwoStreamOperator()
    obs, x, p_inv = _tip_batch(k_windows=k_windows)
    prior_mean = _sds((N_PIX, TIP_P))
    prior_inv = _sds((N_PIX, TIP_P, TIP_P))

    def run(obs, x, p_inv, prior_mean, prior_inv):
        return assimilate_windows_scan(
            op.linearize, obs, x, p_inv,
            prior_mean=prior_mean, prior_inv=prior_inv,
            solver_options=dict(solver_options),
        )

    return run, (obs, x, p_inv, prior_mean, prior_inv)


@register_program(
    "windows_scan_twostream",
    description="assimilate_windows_scan, XLA path: K=3 advance+solve "
                "windows fused into one lax.scan program (prior-only "
                "advance, the engine's temporal-fusion dispatch)",
)
def _build_scan_xla():
    return _scan_program({"use_pallas": False, "max_iterations": 5})


@register_program(
    "windows_scan_twostream_inkernel",
    description="assimilate_windows_scan with the fused in-kernel solve "
                "inside each scan step",
    relayout_clean=True,
)
def _build_scan_inkernel():
    return _scan_program({
        "use_pallas": True, "inkernel_linearize": True,
        "min_iterations": 2, "max_iterations": 5,
    })


@register_program(
    "smoother_rts_sweep",
    description="the smoother's reverse lax.scan (_rts_sweep): fixed-"
                "interval RTS recursion over T=4 checkpoints",
)
def _build_rts_sweep():
    from ..smoother.rts_pass import _rts_sweep

    n, p, t = 64, TIP_P, 4
    args = (
        _sds((t - 1, n, p)), _sds((t - 1, n, p, p)),
        _sds((t - 1, n, p)), _sds((t - 1, n, p, p)),
        _sds((p, p)), _sds((n, p)), _sds((n, p, p)),
    )
    return _rts_sweep, args


# ---------------------------------------------------------------------------
# Operator linearizations: one program per operator family, tracing the
# exact ``linearize`` the solver jit-caches on.
# ---------------------------------------------------------------------------

@register_program(
    "linearize_twostream",
    description="TwoStreamOperator.linearize (2-band TIP, aux=None)",
)
def _build_lin_twostream():
    from ..obsops.twostream import TwoStreamOperator

    op = TwoStreamOperator()
    return (lambda x: op.linearize(None, x)), (_sds((N_PIX, TIP_P)),)


@register_program(
    "linearize_prosail",
    description="ProsailOperator.linearize (10-band S2 reflectance, "
                "scalar acquisition geometry aux)",
)
def _build_lin_prosail():
    from ..obsops.prosail import ProsailAux, ProsailOperator

    op = ProsailOperator()
    aux = ProsailAux(sza=_sds(()), vza=_sds(()), raa=_sds(()))
    return op.linearize, (aux, _sds((N_PIX, 10)))


@register_program(
    "linearize_gp_bank",
    description="GPBankOperator.linearize (banked GP emulators, leading "
                "band axis on every GPParams leaf)",
)
def _build_lin_gp_bank():
    from ..obsops.gp import GPBankOperator, GPParams

    m = 32  # inducing points per band
    op = GPBankOperator(n_params=TIP_P, n_bands=TIP_BANDS)
    aux = GPParams(
        x_train=_sds((TIP_BANDS, m, TIP_P)),
        alpha=_sds((TIP_BANDS, m)),
        log_lengthscales=_sds((TIP_BANDS, TIP_P)),
        log_amplitude=_sds((TIP_BANDS,)),
        y_mean=_sds((TIP_BANDS,)),
    )
    return op.linearize, (aux, _sds((N_PIX, TIP_P)))


@register_program(
    "linearize_mlp",
    description="MLPOperator.linearize (surrogate MLP, params via aux)",
)
def _build_lin_mlp():
    from ..obsops.mlp import MLPOperator

    hidden = 16
    op = MLPOperator(n_params=TIP_P, n_bands=3)
    aux = [
        {"w": _sds((TIP_P, hidden)), "b": _sds((hidden,))},
        {"w": _sds((hidden, 3)), "b": _sds((3,))},
    ]
    return op.linearize, (aux, _sds((N_PIX, TIP_P)))


@register_program(
    "linearize_wcm",
    description="WCMOperator.linearize (dual-pol water-cloud model, "
                "per-pixel incidence-angle aux)",
)
def _build_lin_wcm():
    from ..obsops.wcm import WCMAux, WCMOperator

    op = WCMOperator()
    aux = WCMAux(theta_deg=_sds((N_PIX,)))
    return op.linearize, (aux, _sds((N_PIX, op.n_params)))


@register_program(
    "linearize_joint_optical",
    description="ProsailJointOperator.linearize (11-param joint state, "
                "optical constraint)",
)
def _build_lin_joint_optical():
    from ..obsops.joint import ProsailJointOperator
    from ..obsops.prosail import ProsailAux

    op = ProsailJointOperator()
    aux = ProsailAux(sza=_sds(()), vza=_sds(()), raa=_sds(()))
    return op.linearize, (aux, _sds((N_PIX, op.n_params)))


@register_program(
    "linearize_joint_sar",
    description="WCMJointOperator.linearize (11-param joint state, SAR "
                "constraint through the transformed-LAI decode)",
)
def _build_lin_joint_sar():
    from ..obsops.joint import WCMJointOperator
    from ..obsops.wcm import WCMAux

    op = WCMJointOperator()
    aux = WCMAux(theta_deg=_sds((N_PIX,)))
    return op.linearize, (aux, _sds((N_PIX, op.n_params)))


# ---------------------------------------------------------------------------
# Mesh programs: lowered under the shard/mesh.py pixel mesh, with the
# compiled collective inventory checked against an explicit manifest.
# ---------------------------------------------------------------------------

@register_program(
    "sharded_step_tip",
    description="make_sharded_step: the mesh-partitioned per-date "
                "advance+solve program (pixels sharded, scalar "
                "convergence norm is the ONLY permitted collective)",
    collectives=("all-reduce",),
)
def _build_sharded_step():
    import jax

    from ..core.types import BandBatch
    from ..obsops.twostream import TwoStreamOperator
    from ..shard.mesh import make_pixel_mesh, pad_for_mesh
    from ..shard.step import make_sharded_step

    devices = jax.devices()
    mesh = make_pixel_mesh(devices)
    n = pad_for_mesh(N_PIX, mesh)
    op = TwoStreamOperator()
    step = make_sharded_step(
        op.linearize, mesh, solver_options={"max_iterations": 5},
        n_valid=N_PIX,
    )
    obs = BandBatch(
        y=_sds((TIP_BANDS, n)), r_inv=_sds((TIP_BANDS, n)),
        mask=_sds((TIP_BANDS, n), "bool"),
    )
    args = (
        obs, _sds((n, TIP_P)), _sds((n, TIP_P, TIP_P)),
        _sds((TIP_P, TIP_P)), _sds((TIP_P,)),
        _sds((n, TIP_P)), _sds((n, TIP_P, TIP_P)), None,
    )
    return BuiltProgram(fn=step, args=args, mesh_devices=len(devices))


@register_program(
    "sharded_forward_tip",
    description="make_sharded_forward: the mesh-partitioned batched "
                "forward (prediction path) — zero collectives permitted",
    collectives=(),
)
def _build_sharded_forward():
    import jax

    from ..obsops.twostream import TwoStreamOperator
    from ..shard.mesh import make_pixel_mesh, pad_for_mesh
    from ..shard.step import make_sharded_forward

    devices = jax.devices()
    mesh = make_pixel_mesh(devices)
    n = pad_for_mesh(N_PIX, mesh)
    op = TwoStreamOperator()
    fwd = make_sharded_forward(op.forward, mesh)
    return BuiltProgram(
        fn=fwd, args=(None, _sds((n, TIP_P))),
        mesh_devices=len(devices),
    )
