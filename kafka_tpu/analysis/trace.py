"""Abstract tracing of registered programs: jaxpr + (optionally) HLO.

Everything here is device-free: ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` specs performs abstract evaluation only, and the
collective inventory compiles for the CPU backend (GSPMD partitioning
happens at compile time regardless of backend, so all-gather/all-reduce
insertion is visible in the CPU executable's HLO text).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterator, Optional

from .registry import BuiltProgram, ProgramSpec

#: HLO op families counted as collectives (the -start forms cover async
#: lowering).  ``psum``/``ppermute`` lower to all-reduce/collective-permute.
COLLECTIVE_OPS = (
    "all-gather", "all-reduce", "all-to-all", "collective-permute",
    "reduce-scatter",
)


@dataclasses.dataclass
class TracedProgram:
    """One program's IR-level view: the ClosedJaxpr, a recursive primitive
    census, a dtype census over every aval the trace produced, and — for
    mesh programs — the compiled HLO's collective inventory."""

    spec: ProgramSpec
    closed: object                      # jax.core.ClosedJaxpr
    primitives: Dict[str, int]
    dtypes: Dict[str, int]
    n_eqns: int
    mesh_devices: int = 0
    collectives: Optional[Dict[str, int]] = None  # None = not compiled
    collectives_skipped_reason: Optional[str] = None


def iter_eqns(jaxpr) -> Iterator[object]:
    """Every equation in ``jaxpr`` and (recursively) in any sub-jaxpr
    carried by equation params — pjit bodies, scan/while/cond branches,
    custom_jvp/vjp call jaxprs, pallas kernels."""
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            yield from _iter_param(val)


def _iter_param(val) -> Iterator[object]:
    if hasattr(val, "jaxpr"):            # ClosedJaxpr
        yield from iter_eqns(val.jaxpr)
    elif hasattr(val, "eqns"):           # raw Jaxpr
        yield from iter_eqns(val)
    elif isinstance(val, (list, tuple)):
        for item in val:
            yield from _iter_param(item)
    elif isinstance(val, dict):
        for item in val.values():
            yield from _iter_param(item)


def _census(closed) -> Dict[str, int]:
    prims: Dict[str, int] = {}
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        prims[name] = prims.get(name, 0) + 1
    return prims


def _dtype_census(closed) -> Dict[str, int]:
    """Count avals by dtype: the top-level inputs plus every equation
    output, recursively — so a computed f64 (upcast mid-program) is
    counted even though no input or AST literal mentions it."""
    dtypes: Dict[str, int] = {}

    def add(aval) -> None:
        dt = getattr(aval, "dtype", None)
        if dt is None:
            return
        key = str(dt)
        dtypes[key] = dtypes.get(key, 0) + 1

    for var in closed.jaxpr.invars:
        add(var.aval)
    for eqn in iter_eqns(closed.jaxpr):
        for var in eqn.outvars:
            add(getattr(var, "aval", None))
    return dtypes


def trace_program(spec: ProgramSpec,
                  compile_collectives: bool = True) -> TracedProgram:
    """Build and abstractly trace one registered program.

    Raises whatever the builder/trace raises — callers wrap this in a
    per-program try/except and surface failures as findings rather than
    crashing the whole analysis run.
    """
    import jax

    built: BuiltProgram = spec.build()
    if spec.x64:
        with jax.experimental.enable_x64():
            closed = jax.make_jaxpr(built.fn)(*built.args)
    else:
        closed = jax.make_jaxpr(built.fn)(*built.args)

    collectives: Optional[Dict[str, int]] = None
    skipped: Optional[str] = None
    if built.mesh_devices >= 2:
        if compile_collectives:
            collectives = _collective_inventory(built)
        else:
            skipped = "collective compile disabled (--no-collectives)"
    elif built.mesh_devices == 1:
        skipped = (
            "single-device mesh: GSPMD inserts no collectives to inventory"
        )

    return TracedProgram(
        spec=spec,
        closed=closed,
        primitives=_census(closed),
        dtypes=_dtype_census(closed),
        n_eqns=sum(1 for _ in iter_eqns(closed.jaxpr)),
        mesh_devices=built.mesh_devices,
        collectives=collectives,
        collectives_skipped_reason=skipped,
    )


def _collective_inventory(built: BuiltProgram) -> Dict[str, int]:
    """Counts of collective HLO op families in the compiled program.

    Lowers ahead-of-time on the abstract args (a jitted-with-shardings
    callable has ``.lower``; anything else is wrapped in ``jax.jit``
    first) and greps the executable's HLO text — the one representation
    where GSPMD's inserted collectives are visible.
    """
    import jax

    fn = built.fn
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    hlo = fn.lower(*built.args).compile().as_text()
    out: Dict[str, int] = {}
    for op in COLLECTIVE_OPS:
        # Instruction applications read "... = <shape> all-reduce(...)"
        # (or the async "-start" form); the op name directly abuts the
        # operand parenthesis, which keeps shape strings and metadata out.
        n = len(re.findall(rf"(?<![\w-]){re.escape(op)}(?:-start)?\(", hlo))
        if n:
            out[op] = n
    return out
