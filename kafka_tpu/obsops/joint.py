"""Joint optical + SAR observation operators on one shared state.

The reference ships optical (PROSAIL emulators,
``/root/reference/kafka/inference/utils.py:181-219``) and SAR (Water-Cloud
Model, ``observation_operators/sar_forward_model.py``) operators but never
composes them — its drivers assimilate one sensor each.  These operators
close that gap: an 11-parameter joint state (the 10 transformed PROSAIL
parameters + volumetric soil moisture) that Sentinel-2 dates constrain
through the PROSAIL reflectance operator and Sentinel-1 dates constrain
through the WCM, so LAI is shared between the sensors and soil moisture
rides the SAR signal.

State layout (transformed space, matching ``obsops.prosail``):

    [0..9]  PROSAIL state (``PROSAIL_PARAMETER_LIST``), with slot 6 the
            exponentially transformed LAI: x6 = exp(-LAI/2)
    [10]    sm: volumetric soil moisture (m^3/m^3)
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from .prosail import ProsailAux, ProsailOperator
from .protocol import ObservationModel
from .wcm import WCMAux, WCM_PARAMETERS, wcm_sigma0

#: Transformed-LAI floor: exp(-10/2), i.e. LAI capped at 10 like the WCM
#: physical domain.
_TLAI_MIN = float(np.exp(-5.0))


def joint_state_bounds():
    """(lower, upper) for the 11-parameter joint state: PROSAIL bounds plus
    the WCM soil-moisture domain (0, 0.6]."""
    p_lo, p_hi = ProsailOperator.state_bounds
    lo = np.concatenate([p_lo, [1e-3]]).astype(np.float32)
    hi = np.concatenate([p_hi, [0.6]]).astype(np.float32)
    return lo, hi


class ProsailJointOperator(ObservationModel):
    """The PROSAIL S2 operator lifted onto the joint state: reads the first
    10 parameters, ignores soil moisture (zero Jacobian there, so SM keeps
    its prior/SAR-constrained value through optical dates)."""

    n_bands = 10
    n_params = 11
    state_bounds = joint_state_bounds()

    def __init__(self, hotspot: float = 0.01):
        self._prosail = ProsailOperator(hotspot=hotspot)

    def forward_pixel(self, aux: Optional[ProsailAux], x_pixel):
        return self._prosail.forward_pixel(aux, x_pixel[:10])


class WCMJointOperator(ObservationModel):
    """The dual-pol Water-Cloud Model on the joint state: the vegetation
    descriptor is the PHYSICAL LAI decoded from the transformed slot 6
    (LAI = -2 ln x6), soil moisture is slot 10.  Autodiff carries the
    chain rule through the decode, so SAR dates update the same
    transformed-LAI parameter the optical dates do."""

    n_params = 11
    state_bounds = joint_state_bounds()

    def __init__(self, polarisations=("VV", "VH")):
        self.polarisations = tuple(polarisations)
        for pol in self.polarisations:
            if pol not in WCM_PARAMETERS:
                raise ValueError(
                    f"polarisation {pol!r} has no WCM coefficient set "
                    "(VV and VH are supported)"
                )
        self.n_bands = len(self.polarisations)
        self._coeffs = np.array(
            [WCM_PARAMETERS[p] for p in self.polarisations], np.float32
        )

    def forward_pixel(self, aux: WCMAux, x_pixel):
        tlai = jnp.clip(x_pixel[6], _TLAI_MIN, 1.0)
        lai = -2.0 * jnp.log(tlai)
        sm = x_pixel[10]
        return jnp.stack(
            [
                wcm_sigma0(lai, sm, aux.theta_deg, tuple(c))
                for c in self._coeffs
            ]
        )
