"""MLP surrogate emulator — the neural alternative to the GP bank.

SURVEY.md §7 "hard parts" (a): reproducing pickled ``gp_emulator``
predictions may be impossible without the original artifacts; the listed
fallback is to *train a surrogate of the forward model and validate against
the emulator outputs*.  This module provides that: a small flax MLP trained
on samples of any forward function (PROSAIL tables, the two-stream model,
WCM, ...), used as an ``ObservationModel`` with autodiff Jacobians.  MLP
inference is pure matmul — the best-mapping operator class for the MXU, and
typically faster than the GP matvec for large inducing sets.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .protocol import ObservationModel


def _init_params(key, sizes: Sequence[int]):
    params = []
    for k_in, k_out in zip(sizes[:-1], sizes[1:]):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (k_in, k_out)) * jnp.sqrt(2.0 / k_in)
        params.append({"w": w, "b": jnp.zeros((k_out,))})
    return params


def mlp_apply(params, x):
    """Forward pass; ``x`` (..., k_in) -> (..., k_out). tanh hidden units
    keep the surrogate smooth (C-inf) so Jacobians/Hessians are well
    behaved for the Gauss-Newton loop."""
    h = x
    for layer in params[:-1]:
        h = jnp.tanh(h @ layer["w"] + layer["b"])
    out = h @ params[-1]["w"] + params[-1]["b"]
    return out


def fit_mlp(
    forward: Callable[[np.ndarray], np.ndarray],
    x_samples: np.ndarray,
    hidden: Sequence[int] = (64, 64),
    steps: int = 2000,
    lr: float = 1e-3,
    seed: int = 0,
):
    """Train a surrogate of ``forward`` on the sampled input set.

    ``forward`` maps (n, k_in) -> (n,) or (n, k_out).  Inputs/outputs are
    standardised internally; returns a params pytree for ``mlp_apply``
    (normalisation folded into the first/last layers so the artifact is a
    plain MLP).
    """
    import optax

    x = np.asarray(x_samples, np.float32)
    y = np.asarray(forward(x), np.float32)
    if y.ndim == 1:
        y = y[:, None]
    x_mu, x_sd = x.mean(0), x.std(0) + 1e-6
    y_mu, y_sd = y.mean(0), y.std(0) + 1e-6
    xn = jnp.asarray((x - x_mu) / x_sd)
    yn = jnp.asarray((y - y_mu) / y_sd)

    sizes = [x.shape[1], *hidden, y.shape[1]]
    params = _init_params(jax.random.PRNGKey(seed), sizes)
    opt = optax.adam(lr)
    state = opt.init(params)

    @jax.jit
    def step(params, state):  # kafkalint: disable=unregistered-device-program — offline training step
        def loss(p):
            return jnp.mean((mlp_apply(p, xn) - yn) ** 2)

        l, g = jax.value_and_grad(loss)(params)
        updates, state = opt.update(g, state)
        return optax.apply_updates(params, updates), state, l

    for _ in range(steps):
        params, state, l = step(params, state)

    # Fold input standardisation into layer 0 and output de-standardisation
    # into the last layer, so downstream use is a bare mlp_apply.
    p0 = params[0]
    w0 = p0["w"] / jnp.asarray(x_sd)[:, None]
    b0 = p0["b"] - jnp.asarray(x_mu / x_sd) @ p0["w"]
    params[0] = {"w": w0, "b": b0}
    pl = params[-1]
    wl = pl["w"] * jnp.asarray(y_sd)[None, :]
    bl = pl["b"] * jnp.asarray(y_sd) + jnp.asarray(y_mu)
    params[-1] = {"w": wl, "b": bl}
    return params, float(l)


class MLPOperator(ObservationModel):
    """Observation operator whose bands are the outputs of one MLP surrogate
    (params flow through ``aux`` as traced arrays)."""

    aux_per_pixel = False

    def __init__(self, n_params: int, n_bands: int, state_mapper=None):
        self.n_params = n_params
        self.n_bands = n_bands
        self.mapper = None if state_mapper is None else jnp.asarray(state_mapper)

    def forward_pixel(self, aux, x_pixel):
        sub = x_pixel if self.mapper is None else x_pixel[self.mapper]
        return mlp_apply(aux, sub)[: self.n_bands]
