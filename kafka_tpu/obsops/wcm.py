"""Water-Cloud Model (WCM) — the analytic SAR backscatter operator.

Same physics as the reference's ``sar_observation_operator``
(``/root/reference/kafka/observation_operators/sar_forward_model.py:13-106``):

    tau        = exp(-2 B V / cos(theta))
    sigma_veg  = A * V**E * cos(theta) * (1 - tau)
    sigma_soil = 10 ** ((C + D * SM) / 10)
    sigma_0    = sigma_veg + tau * sigma_soil

with the published per-polarisation fits for VV/VH (``:60-61``).  The
reference hand-codes the (LAI, SM) gradient (``:82-98``, with NaN patching);
here the gradient and Hessian come from autodiff of this forward function.

Differences from the reference, by design:
- incidence angle ``theta`` flows in through ``aux`` per pixel/date instead
  of the hard-coded 23 degrees (``:156``, marked TODO there);
- negative LAI/SM cannot raise inside jit, so inputs are clamped to a small
  positive epsilon (host-side validation available via ``validate_state``) —
  the reference raised ValueError (``:68-71``);
- the integer-division bug for Py3 (``:137-140``) has no equivalent here.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from .protocol import ObservationModel

# Published WCM fits (A, B, C, D, E) per polarisation, as in the reference.
WCM_PARAMETERS = {
    "VV": (0.0846, 0.0615, -14.8465, 15.907, 1.0),
    "VH": (0.0795, 0.1464, -14.8332, 15.907, 0.0),
}

_EPS = 1e-6


class WCMAux(NamedTuple):
    """Per-pixel auxiliary data: incidence angle in degrees (n_pix,)."""

    theta_deg: jnp.ndarray


def wcm_sigma0(v, sm, theta_deg, coeffs):
    """Backscatter (linear units, not dB) for vegetation descriptor ``v``
    (e.g. LAI) and soil moisture ``sm``."""
    a, b, c, d, e = coeffs
    mu = jnp.cos(jnp.deg2rad(theta_deg))
    v = jnp.maximum(v, _EPS)
    sm = jnp.maximum(sm, _EPS)
    tau = jnp.exp(-2.0 * b * v / mu)
    sigma_veg = a * jnp.power(v, e) * mu * (1.0 - tau)
    sigma_soil = 10.0 ** ((c + d * sm) / 10.0)
    return sigma_veg + tau * sigma_soil


class WCMOperator(ObservationModel):
    """Dual-polarisation (VV, VH) WCM on a state whose first two parameters
    are (vegetation descriptor, soil moisture) — the reference's state layout
    ``(LAI1, SM1, LAI2, SM2, ...)`` (``sar_forward_model.py:128-130``)."""

    def __init__(self, n_params: int = 2, v_index: int = 0, sm_index: int = 1,
                 polarisations=("VV", "VH")):
        self.n_params = n_params
        if n_params == 2 and (v_index, sm_index) == (0, 1):
            # physical domain: LAI in (0, 10], SM in (0, 0.6] m^3/m^3
            self.state_bounds = (
                np.array([1e-3, 1e-3], np.float32),
                np.array([10.0, 0.6], np.float32),
            )
        self.v_index = v_index
        self.sm_index = sm_index
        self.polarisations = tuple(polarisations)
        for pol in self.polarisations:
            if pol not in WCM_PARAMETERS:
                raise ValueError(
                    f"unsupported polarisation {pol!r}: WCM "
                    "coefficients are calibrated for VV and VH"
                )
        self.n_bands = len(self.polarisations)
        self._coeffs = np.array(
            [WCM_PARAMETERS[p] for p in self.polarisations], np.float32
        )

    def forward_pixel(self, aux: WCMAux, x_pixel):
        v = x_pixel[self.v_index]
        sm = x_pixel[self.sm_index]
        return jnp.stack(
            [
                wcm_sigma0(v, sm, aux.theta_deg, tuple(c))
                for c in self._coeffs
            ]
        )


def validate_state(x) -> None:
    """Host-side input validation mirroring the reference's eager checks
    (``sar_forward_model.py:68-71``): raises on non-positive LAI or SM."""
    x = np.asarray(x)
    if np.any(x[:, 0] <= 0.0):
        raise ValueError("Negative LAI!")
    if np.any(x[:, 1] <= 0.0):
        raise ValueError("Negative SM!")
    if np.any(~np.isfinite(x)):
        raise ValueError("Non-finite state!")
