"""JAX Gaussian-process emulator of expensive radiative-transfer models.

The reference runs pickled ``gp_emulator`` objects per band x geometry
(``/root/reference/kafka/input_output/Sentinel2_Observations.py:95-98,157-159``)
whose ``predict`` returns value + gradient and whose ``hessian`` feeds the
second-order correction (``kf_tools.py:28``).  Those pickles encode a GP
regression over PROSAIL training runs.  This module is the TPU-native
equivalent: an ARD-RBF GP whose predictive mean

    m(x*) = k(x*, X) @ alpha,   alpha = (K + sigma_n^2 I)^-1 y

is a pure JAX function — one matvec against the inducing set per pixel, MXU
friendly — with Jacobian/Hessian by autodiff instead of hand-derived kernel
derivatives.  ``GPEmulator.fit`` trains from (X, y) samples of any forward
model, replacing the unpicklable emulator files with a reproducible artifact
(hyperparameters + training set), saveable as ``.npz``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .protocol import ObservationModel


class GPParams(NamedTuple):
    """Everything the predictive mean needs; a pytree, so it can flow
    through ``aux`` as traced data (one compiled solve serves any
    band/geometry emulator of the same shapes)."""

    x_train: jnp.ndarray      # (m, k) inducing inputs
    alpha: jnp.ndarray        # (m,) precomputed (K + sig^2 I)^-1 y
    log_lengthscales: jnp.ndarray  # (k,)
    log_amplitude: jnp.ndarray     # ()
    y_mean: jnp.ndarray       # () training-target mean (centering)


def _kernel_row(params: GPParams, x_star: jnp.ndarray) -> jnp.ndarray:
    ell = jnp.exp(params.log_lengthscales)
    d = (params.x_train - x_star) / ell
    return jnp.exp(params.log_amplitude) * jnp.exp(-0.5 * jnp.sum(d * d, -1))


def gp_predict_pixel(params: GPParams, x_star: jnp.ndarray) -> jnp.ndarray:
    """Predictive mean for one pixel's (k,) input — scalar output."""
    return _kernel_row(params, x_star) @ params.alpha + params.y_mean


def fit_gp(
    x_train: np.ndarray,
    y_train: np.ndarray,
    lengthscales: Optional[np.ndarray] = None,
    amplitude: float = 1.0,
    noise: float = 1e-4,
    optimize: bool = False,
    steps: int = 200,
) -> GPParams:
    """Condition a GP on training samples.

    With ``optimize=True`` the (log) hyperparameters are tuned by Adam on
    the negative log marginal likelihood; otherwise lengthscales default to
    per-dimension input std (a solid heuristic for smooth RT models).
    """
    x_train = np.asarray(x_train, np.float32)
    y_train = np.asarray(y_train, np.float32)
    y_mean = float(y_train.mean())
    y_c = y_train - y_mean
    if lengthscales is None:
        lengthscales = x_train.std(0) + 1e-3

    log_ell = jnp.log(jnp.asarray(lengthscales, jnp.float32))
    log_amp = jnp.log(jnp.asarray(amplitude, jnp.float32))
    xt = jnp.asarray(x_train)
    yt = jnp.asarray(y_c)

    def gram(log_ell, log_amp):
        ell = jnp.exp(log_ell)
        z = xt / ell
        d2 = (
            jnp.sum(z * z, -1)[:, None]
            + jnp.sum(z * z, -1)[None, :]
            - 2.0 * z @ z.T
        )
        return jnp.exp(log_amp) * jnp.exp(-0.5 * jnp.maximum(d2, 0.0))

    if optimize:
        import optax

        # kafkalint: disable=unregistered-device-program — offline GP
        # hyperparameter fit, not a serving-engine device program
        def nll(p):
            k = gram(p["log_ell"], p["log_amp"])
            k = k + (noise + jnp.exp(p["log_noise"])) * jnp.eye(k.shape[0])
            chol = jnp.linalg.cholesky(k)
            w = jax.scipy.linalg.cho_solve((chol, True), yt)
            return 0.5 * yt @ w + jnp.sum(jnp.log(jnp.diagonal(chol)))

        params = {
            "log_ell": log_ell,
            "log_amp": log_amp,
            "log_noise": jnp.log(jnp.asarray(noise, jnp.float32)),
        }
        opt = optax.adam(1e-2)
        state = opt.init(params)
        grad_fn = jax.jit(jax.value_and_grad(nll))
        for _ in range(steps):
            _, g = grad_fn(params)
            updates, state = opt.update(g, state)
            params = optax.apply_updates(params, updates)
        log_ell, log_amp = params["log_ell"], params["log_amp"]
        noise = noise + float(np.exp(params["log_noise"]))

    k = gram(log_ell, log_amp) + noise * jnp.eye(x_train.shape[0])
    chol = jnp.linalg.cholesky(k)
    alpha = jax.scipy.linalg.cho_solve((chol, True), yt)
    return GPParams(
        x_train=xt,
        alpha=alpha,
        log_lengthscales=log_ell,
        log_amplitude=log_amp,
        y_mean=jnp.asarray(y_mean, jnp.float32),
    )


def save_gp(path: str, params: GPParams) -> None:
    np.savez(path, **{f: np.asarray(getattr(params, f)) for f in params._fields})


def load_gp(path: str) -> GPParams:
    data = np.load(path)
    return GPParams(**{f: jnp.asarray(data[f]) for f in GPParams._fields})


class GPBankOperator(ObservationModel):
    """Multi-band observation operator backed by one GP per band.

    ``aux`` carries a ``GPParams`` whose leaves are stacked over a leading
    band axis (all bands share shapes — same training-set size), so the
    operator is a single stable callable and per-date emulator selection
    (the reference picks a pickle per geometry,
    ``Sentinel2_Observations.py:133-145``) is just swapping traced arrays.

    Optional ``state_mappers`` (n_bands, k) gather a sub-state per band —
    the reference's ``state_mapper`` pattern for spectral parameters
    (``inference/utils.py:148-153``).
    """

    aux_per_pixel = False

    def __init__(self, n_params: int, n_bands: int, state_mappers=None):
        self.n_params = n_params
        self.n_bands = n_bands
        # numpy on purpose — see TwoStreamOperator.__init__: device-array
        # indices lower to slow dynamic gathers; host constants are static.
        self.mappers = (
            None if state_mappers is None else np.asarray(state_mappers)
        )

    def forward_pixel(self, aux: GPParams, x_pixel):
        # Shapes are static under trace: a bank whose band axis disagrees
        # with the operator must fail loudly here — JAX clamps
        # out-of-bounds indices, so leaf[b] past the end would silently
        # repeat the last band's prediction instead of erroring.
        n_in_bank = int(aux.x_train.shape[0])
        if n_in_bank != self.n_bands:
            raise ValueError(
                f"emulator bank carries {n_in_bank} band(s) but the "
                f"operator expects {self.n_bands}"
            )

        def one_band(b):
            params = jax.tree.map(lambda leaf: leaf[b], aux)
            sub = x_pixel if self.mappers is None else x_pixel[self.mappers[b]]
            return gp_predict_pixel(params, sub)

        return jnp.stack([one_band(b) for b in range(self.n_bands)])


def stack_gp_bank(per_band: list) -> GPParams:
    """Stack per-band GPParams into the banked layout used by
    ``GPBankOperator`` (leading band axis on every leaf)."""
    return GPParams(
        *[
            jnp.stack([jnp.asarray(getattr(p, f)) for p in per_band])
            for f in GPParams._fields
        ]
    )
