"""Differentiable two-stream canopy albedo operator (JRC-TIP style).

The reference's MODIS path inverts a two-stream radiative-transfer model
through pickled GP emulators of the JRC "Two-stream Inversion Package"
(state + band→parameter mapping at
``/root/reference/kafka/inference/utils.py:148-153``; prior at
``kf_tools.py:99-116``).  The pickles are not reproducible artifacts, so this
module provides the physics itself: a closed-form two-stream solution for
the bihemispherical reflectance (white-sky albedo) of a homogeneous canopy
over a reflecting soil, written in JAX — exactly differentiable, no emulator
required.  (A GP/MLP emulator of any forward model is still available in
``obsops/gp.py`` / ``obsops/mlp.py`` for operators without closed forms.)

State layout (the reference's 7-parameter TIP state, band mappers
``[0, 1, 6, 2]`` / ``[3, 4, 6, 5]``):

    [omega_vis, d_vis, a_soil_vis, omega_nir, d_nir, a_soil_nir, tlai]

where ``omega`` is the leaf single-scattering albedo, ``d`` a diffusion /
asymmetry factor, ``a_soil`` the background albedo, and
``tlai = exp(-LAI / 2)`` the transformed effective LAI
(``kf_tools.py:100-109``).

Physics: classic two-flux (Kubelka-Munk / Meador-Weaver family) solution.
With per-unit-LAI absorption ``1 - omega`` and backscatter fraction
``b = (1 - g) / 2`` (g = asymmetry derived from ``d``):

    alpha = 1 - omega * (1 - b)      # attenuation of a stream
    beta  = omega * b                # coupling between streams
    gamma = sqrt(alpha^2 - beta^2)
    r_inf = (alpha - gamma) / beta   # semi-infinite canopy albedo

and the finite-depth albedo over soil of albedo ``r_s`` follows from the
two-point boundary problem solved in closed form below.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .protocol import ObservationModel

_EPS = 1e-6

# TIP state slots, matching the reference band_selecta (kf_tools.py:19-23).
VIS_MAPPER = np.array([0, 1, 6, 2])
NIR_MAPPER = np.array([3, 4, 6, 5])


def tlai_to_lai(tlai):
    """Invert the TIP transform TLAI = exp(-LAI/2) (kf_tools.py:100-109)."""
    return -2.0 * jnp.log(jnp.clip(tlai, _EPS, 1.0 - _EPS))


def twostream_albedo(omega, d, soil_albedo, lai):
    """White-sky albedo of a homogeneous canopy over a Lambertian soil.

    Closed-form two-flux solution.  ``d`` is the TIP-style diffusion /
    asymmetry factor with 1.0 = isotropic scattering (the prior means are
    1.0 VIS / 0.7 NIR, ``kf_tools.py:110``): it maps to an effective
    asymmetry ``g = 1 - 1/d`` (d > 1 forward-scattering, d < 1 backward)
    and backscatter fraction ``b = (1 - g)/2``.  Fully differentiable; all
    inputs clamped to physical ranges so autodiff stays finite inside jit.
    """
    omega = jnp.clip(omega, _EPS, 1.0 - _EPS)
    g = jnp.clip(1.0 - 1.0 / jnp.maximum(d, 0.1), -0.95, 0.95)
    b = (1.0 - g) / 2.0
    soil = jnp.clip(soil_albedo, 0.0, 1.0)
    lai = jnp.maximum(lai, _EPS)

    alpha = 1.0 - omega * (1.0 - b)
    beta = omega * b
    gamma = jnp.sqrt(jnp.maximum(alpha**2 - beta**2, _EPS**2))
    r_inf = beta / (alpha + gamma)  # = (alpha - gamma)/beta, stable form

    # Downward/upward diffuse fluxes: A(z) = c1 e^{-g z} + c2 e^{+g z},
    # B(z) = r_inf c1 e^{-g z} + c2 / r_inf e^{+g z}; BCs A(0)=1,
    # B(L) = soil * A(L).  Solve for c1, c2; albedo = B(0).
    e_m = jnp.exp(-gamma * lai)
    # growing mode expressed via e_m to avoid overflow: e_p = 1/e_m
    # c2/c1 = e_m^2 * (r_inf - soil) / (soil - 1/r_inf)
    ratio = e_m**2 * (r_inf - soil) / (soil - 1.0 / r_inf)
    c1 = 1.0 / (1.0 + ratio)
    c2 = ratio * c1
    return r_inf * c1 + c2 / r_inf


class TwoStreamOperator(ObservationModel):
    """Two-band (VIS/NIR) two-stream albedo operator on the 7-param TIP
    state — the self-contained replacement for the reference's pickled
    per-band GP emulators in the MODIS/BHR pipeline."""

    n_bands = 2
    n_params = 7
    # Physical domain of [omega, d, soil] x 2 + tlai: albedos/ssa in (0, 1),
    # diffusion factor positive, transformed LAI in (0, 1).
    state_bounds = (
        np.array([1e-3, 0.1, 1e-3, 1e-3, 0.1, 1e-3, 5e-3], np.float32),
        np.array([0.999, 4.0, 0.999, 0.999, 4.0, 0.999, 0.999], np.float32),
    )

    def __init__(self):
        # numpy on purpose: a device-array index closed over in jit lowers
        # to a dynamic gather (~23 ms for 16k px on v5e via tunnel); a
        # host-constant index compiles to static slices (~0.03 ms).
        self._mappers = np.stack([VIS_MAPPER, NIR_MAPPER])

    def forward_band_pixel(self, aux, band: int, sub):
        """One band from its mapped 4-vector [omega, d, tlai, a_soil]."""
        omega, d, tlai, soil = sub[0], sub[1], sub[2], sub[3]
        return twostream_albedo(omega, d, soil, tlai_to_lai(tlai))

    def forward_pixel(self, aux, x_pixel):
        out = []
        for b in range(self.n_bands):
            sub = x_pixel[self._mappers[b]]
            out.append(self.forward_band_pixel(aux, b, sub))
        return jnp.stack(out)

    # ---- in-kernel linearisation (core.pallas_solve.fused_gn_rows) ----

    #: the two-stream forward is closed-form elementwise jnp — its
    #: value+Jacobian lowers inside a Pallas TPU kernel, so the whole
    #: Gauss-Newton loop can run VMEM-resident (no Jacobian relayout, no
    #: while_loop carry, no separate linearize program).
    inkernel_linearize = True

    def kernel_linearize_rows(self, x_rows):
        """Lane-row analytic value+Jacobian: tuple of p state lane
        vectors -> (h0 list (B), jac list-of-lists with jac[b][k] =
        dH0[b]/dx[k]) — ``jac_rows`` born directly in the fused kernel's
        row layout, never as a ``(B, n, p)`` tensor.

        Derivatives come from ``jax.jvp`` of the SAME
        ``twostream_albedo`` closed form the batched ``linearize`` path
        differentiates (one implementation of the physics to maintain);
        each band touches only its 4 mapped parameters, so 4 one-hot
        tangents per band cover the full Jacobian row block.
        """

        def band(omega, d, tlai, soil):
            return twostream_albedo(omega, d, soil, tlai_to_lai(tlai))

        zero = jnp.zeros_like(x_rows[0])
        h0_out, jac_out = [], []
        for b in range(self.n_bands):
            mapper = [int(i) for i in self._mappers[b]]
            sub = tuple(x_rows[i] for i in mapper)
            rows = [zero] * len(x_rows)
            val = None
            for k in range(len(sub)):
                tangents = tuple(
                    jnp.ones_like(s) if j == k else jnp.zeros_like(s)
                    for j, s in enumerate(sub)
                )
                val, dot = jax.jvp(band, sub, tangents)
                rows[mapper[k]] = dot
            h0_out.append(val)
            jac_out.append(rows)
        return h0_out, jac_out
