"""Observation-operator protocol.

The reference injects per-band factory functions producing ``(H0, sparse H)``
pairs around a linearisation point (signature at
``/root/reference/kafka/inference/utils.py:130-219``), with derivatives
supplied by pickled GP emulators or hand-coded gradients
(``sar_forward_model.py:82-98``).  Here an observation operator is a pure
differentiable JAX function of one pixel's state; Jacobians and Hessians come
from ``jax.jacfwd`` / ``jax.hessian``, batched over pixels with ``vmap`` —
no hand-coded derivatives anywhere, and the whole linearisation is traced
into the solver's XLA program.

Conventions
-----------
- ``forward_pixel(aux, x_pixel)`` maps a ``(p,)`` state to the ``(n_bands,)``
  predicted observations.  ``aux`` is a pytree of per-date operator data
  (angles, emulator weights...) whose array leaves either broadcast or carry
  a leading ``n_pix`` axis (per-pixel metadata such as SAR incidence angle).
- Operators are registered as *stable callables*: the solver jit-caches on
  the bound ``linearize`` method, with all per-date data flowing through
  ``aux`` as traced arguments.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import Linearization


def _aux_in_axes(aux: Any, n_pix: int):
    """vmap in_axes for an aux pytree: leaves with a leading n_pix axis are
    mapped, everything else is broadcast."""
    return jax.tree.map(
        lambda leaf: 0
        if (hasattr(leaf, "ndim") and leaf.ndim > 0 and leaf.shape[0] == n_pix)
        else None,
        aux,
    )


class ObservationModel:
    """Base class: subclasses implement ``forward_pixel``; ``forward``,
    ``linearize`` and ``hessian`` derive from it mechanically."""

    n_bands: int
    n_params: int
    #: Operators whose aux is shared across pixels (emulator weights etc.)
    #: set this False to disable the leading-axis auto-detection — a weight
    #: matrix whose first dim happens to equal n_pix must not be vmapped.
    aux_per_pixel: bool = True
    #: Optional (lower, upper) per-parameter physical domain; the solver
    #: projects every Gauss-Newton iterate into it (core.solvers).
    state_bounds = None
    #: Operators that implement ``kernel_linearize_rows`` set this True:
    #: the fused Pallas solve (``use_pallas``) then inlines the analytic
    #: value+Jacobian and runs the WHOLE Gauss-Newton loop VMEM-resident
    #: (``core.pallas_solve.fused_gn_rows``) — no ``(B, n, p)`` Jacobian
    #: tensor, no relayout, no while_loop carry crossing HBM.  Everything
    #: else (GP banks, PROSAIL, plain closures) keeps the out-of-kernel
    #: ``linearize`` path behind the same ``LinearizeFn`` protocol.
    inkernel_linearize: bool = False

    def forward_pixel(self, aux: Any, x_pixel: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def kernel_linearize_rows(self, x_rows):
        """Lane-row analytic value+Jacobian for the fused Pallas kernel.

        ``x_rows`` is a tuple of ``p`` state lane vectors (one per
        parameter, any common shape); returns ``(h0, jac)`` with ``h0`` a
        list of ``n_bands`` lane vectors and ``jac[b][k]`` the
        ``dH0[b]/dx[k]`` lane vector — already in the kernel's row
        layout.  Must be built from elementwise jnp ops only (it lowers
        inside a Pallas TPU kernel; no vmap, no gather, no reshape) and
        must match ``linearize`` to float32 reassociation tolerance —
        the parity tests pin both.  Only consulted when
        ``inkernel_linearize`` is True.
        """
        raise NotImplementedError

    def aux_in_axes(self, aux: Any, n_pix: int):
        if not self.aux_per_pixel:
            return jax.tree.map(lambda _: None, aux)
        return _aux_in_axes(aux, n_pix)

    # ---- batched derivations -------------------------------------------

    def forward(self, aux: Any, x: jnp.ndarray) -> jnp.ndarray:
        """(n_pix, p) -> (n_bands, n_pix) predicted observations."""
        n_pix = x.shape[0]
        h = jax.vmap(
            self.forward_pixel, in_axes=(self.aux_in_axes(aux, n_pix), 0)
        )(aux, x)
        return h.T

    def linearize(self, aux: Any, x: jnp.ndarray) -> Linearization:
        """(n_pix, p) -> Linearization(h0 (B, n_pix), jac (B, n_pix, p)).

        Value and Jacobian in one pass — the TPU replacement for the
        reference's ``gp.predict`` returning ``(H_, dH_)``
        (``inference/utils.py:87-90``).
        """
        n_pix = x.shape[0]
        axes = self.aux_in_axes(aux, n_pix)

        def value_and_jac(a, xi):
            h0 = self.forward_pixel(a, xi)
            jac = jax.jacfwd(lambda z: self.forward_pixel(a, z))(xi)
            return h0, jac

        h0, jac = jax.vmap(value_and_jac, in_axes=(axes, 0))(aux, x)
        return Linearization(h0=h0.T, jac=jnp.transpose(jac, (1, 0, 2)))

    def hessian(self, aux: Any, x: jnp.ndarray) -> jnp.ndarray:
        """(n_pix, p) -> (n_pix, n_bands, p, p) second derivatives, the
        equivalent of the emulators' ``gp.hessian`` (``kf_tools.py:28``)."""
        n_pix = x.shape[0]
        axes = self.aux_in_axes(aux, n_pix)
        return jax.vmap(
            lambda a, xi: jax.hessian(lambda z: self.forward_pixel(a, z))(xi),
            in_axes=(axes, 0),
        )(aux, x)


class BandView(ObservationModel):
    """A single-band view of a multi-band operator — the unit of the
    reference's legacy band-sequential assimilation
    (``linear_kf.py:325-425``: each band's posterior becomes the next
    band's prior).  A stable callable per (operator, band): the engine
    caches views so each band's jitted program compiles once.

    Known cost: the view evaluates the INNER operator's full multi-band
    forward and slices one output, so monolithic spectral operators
    (PROSAIL: one RT chain feeding all bands) pay ~n_bands of redundant
    work per band — n_bands^2 total vs the joint update.  That is the
    nature of the legacy mode (the reference's per-band loop re-ran its
    emulators the same way); per-band-separable operators
    (``MappedStateModel``) dead-code-eliminate cleanly."""

    def __init__(self, inner: ObservationModel, band: int):
        self.inner = inner
        self.band = int(band)
        self.n_bands = 1
        self.n_params = inner.n_params
        self.state_bounds = getattr(inner, "state_bounds", None)
        self.aux_per_pixel = getattr(inner, "aux_per_pixel", True)

    def forward_pixel(self, aux: Any, x_pixel: jnp.ndarray) -> jnp.ndarray:
        return self.inner.forward_pixel(aux, x_pixel)[
            self.band:self.band + 1
        ]

    def aux_in_axes(self, aux: Any, n_pix: int):
        return self.inner.aux_in_axes(aux, n_pix)


class MappedStateModel(ObservationModel):
    """Wraps a sub-state operator into the full state vector via per-band
    index mapping — the reference's ``state_mapper``/``band_selecta`` pattern
    (``inference/utils.py:148-153``, ``kf_tools.py:19-23``), where e.g. the
    VIS band reads params [0, 1, 6, 2] and NIR reads [3, 4, 6, 5] of a
    7-param state.

    ``inner.forward_pixel(aux, x_sub)`` must return a scalar (one band); this
    wrapper evaluates it once per band with that band's sub-state gather.
    """

    def __init__(self, inner, state_mappers, n_params: int):
        self.inner = inner
        # numpy on purpose — see TwoStreamOperator.__init__: device-array
        # indices lower to slow dynamic gathers; host constants are static.
        self.mappers = np.asarray(state_mappers)  # (n_bands, k)
        self.n_bands = int(self.mappers.shape[0])
        self.n_params = n_params

    def forward_pixel(self, aux: Any, x_pixel: jnp.ndarray) -> jnp.ndarray:
        def one_band(b):
            sub = x_pixel[self.mappers[b]]
            return self.inner.forward_band_pixel(aux, b, sub)

        return jnp.stack([one_band(b) for b in range(self.n_bands)])
