"""Identity / selection observation operators for linear testing.

The reference ships an identity operator used for linear sanity checks
(``/root/reference/kafka/inference/utils.py:119-126``).  ``IdentityOperator``
generalises it slightly: each band observes one chosen state parameter
directly (the plain identity is ``obs_indices = [0]`` on a 1-param state).
"""

from __future__ import annotations

import jax.numpy as jnp

from .protocol import ObservationModel


class IdentityOperator(ObservationModel):
    def __init__(self, n_params: int, obs_indices=(0,)):
        self.n_params = n_params
        self.obs_indices = jnp.asarray(obs_indices)
        self.n_bands = int(self.obs_indices.shape[0])

    def forward_pixel(self, aux, x_pixel):
        return x_pixel[self.obs_indices]
