"""Ingest the reference's ``gp_emulator`` pickle artifacts.

The reference ships its PROSAIL emulators as pickled dicts of
``gp_emulator.GaussianProcess`` objects, one file per viewing geometry,
keyed ``b"S2A_MSI_NN"`` per band and selected by filename-encoded angles
(``/root/reference/kafka/input_output/Sentinel2_Observations.py:157-184``,
``observations.py:281-286``).  This module converts those artifacts into
``GPParams`` pytrees — WITHOUT needing the ``gp_emulator`` package
installed — so a real emulator file drops straight into the S2 geometry
bank (``io.sentinel2.geometry_bank_aux_builder`` + ``GPBankOperator``).

Format mapping (the public ``gp_emulator`` GaussianProcess contract):

- ``inputs`` (M, D): the inducing/training inputs;
- ``targets`` (M,): raw training targets (no centering);
- ``theta`` (D+2,): log-hyperparameters ``[log w_1..log w_D,
  log sigma_f^2, log sigma_n^2]`` where ``w_d`` are INVERSE SQUARED
  length scales — its kernel is
  ``k(x, x') = e^{theta[D]} exp(-0.5 sum_d e^{theta[d]} (x_d-x'_d)^2)``;
- ``invQt`` (M,): the precomputed ``(K + sigma_n^2 I)^{-1} y`` weight
  vector its ``predict`` matvecs against.

Ours (``obsops.gp``) parameterises ``k = e^{log_amp}
exp(-0.5 sum ((x-x')/ell)^2)``, so ``log_ell_d = -theta[d]/2``,
``log_amp = theta[D]``, ``alpha = invQt`` (recomputed from the training
set when a pickle lacks it), ``y_mean = 0``.
"""

from __future__ import annotations

import glob
import io
import logging
import os
import pickle
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .gp import GPParams

LOG = logging.getLogger(__name__)

#: emulator band keys use the MSI band numbering of the reference's
#: ``emulator_band_map`` (``Sentinel2_Observations.py:171-182``).
EMULATOR_BAND_MAP = (2, 3, 4, 5, 6, 7, 8, 9, 12, 13)


class _StubUnpickled:
    """Attribute bag standing in for any class the pickle references —
    ``__setstate__``/``__reduce__`` state lands in ``__dict__``."""

    def __init__(self, *args, **kwargs):
        pass

    def __setstate__(self, state):
        if isinstance(state, dict):
            self.__dict__.update(state)
        elif isinstance(state, tuple):
            for part in state:
                if isinstance(part, dict):
                    self.__dict__.update(part)


class _EmulatorUnpickler(pickle.Unpickler):
    """Unpickler that resolves classes from the (absent) ``gp_emulator``
    package — and any other missing module — to attribute stubs, while
    letting numpy and the standard library load normally."""

    def find_class(self, module, name):
        try:
            return super().find_class(module, name)
        except (ImportError, AttributeError):
            LOG.debug("stubbing unpicklable class %s.%s", module, name)
            return type(name, (_StubUnpickled,), {})


def load_emulator_pickle(path: str) -> Any:
    """Unpickle a gp_emulator artifact without gp_emulator installed
    (latin1 encoding, matching the reference's py2->py3 load,
    ``Sentinel2_Observations.py:158-159``)."""
    with open(path, "rb") as f:
        data = f.read()
    return _EmulatorUnpickler(io.BytesIO(data),
                              encoding="latin1").load()


def gp_params_from_emulator(gp: Any) -> GPParams:
    """One ``gp_emulator.GaussianProcess`` (or stub) -> ``GPParams``."""
    import jax.numpy as jnp

    inputs = np.asarray(getattr(gp, "inputs"), np.float32)
    targets = np.asarray(getattr(gp, "targets"), np.float32).ravel()
    theta = np.asarray(getattr(gp, "theta"), np.float64).ravel()
    m, d = inputs.shape
    if theta.size < d + 1:
        raise ValueError(
            f"theta has {theta.size} entries for {d}-dim inputs; "
            "expected D+1 (no noise) or D+2"
        )
    log_ell = (-theta[:d] / 2.0).astype(np.float32)
    log_amp = np.float32(theta[d])
    noise = float(np.exp(theta[d + 1])) if theta.size > d + 1 else 1e-8

    alpha = getattr(gp, "invQt", None)
    if alpha is not None and np.asarray(alpha).size == m:
        alpha = np.asarray(alpha, np.float32).ravel()
    else:
        # Recompute (K + sigma_n^2 I)^-1 y from the training set with the
        # pickle's own hyperparameters (float64: K can be ill-conditioned
        # at small noise).
        w = np.exp(theta[:d])
        z = inputs.astype(np.float64) * np.sqrt(w)
        d2 = (
            (z * z).sum(1)[:, None] + (z * z).sum(1)[None, :]
            - 2.0 * z @ z.T
        )
        k = np.exp(float(theta[d])) * np.exp(-0.5 * np.maximum(d2, 0.0))
        k[np.diag_indices_from(k)] += max(noise, 1e-10)
        alpha = np.linalg.solve(k, targets.astype(np.float64)).astype(
            np.float32
        )
    return GPParams(
        x_train=jnp.asarray(inputs),
        alpha=jnp.asarray(alpha),
        log_lengthscales=jnp.asarray(log_ell),
        log_amplitude=jnp.asarray(log_amp),
        y_mean=jnp.zeros((), jnp.float32),
    )


def _normalise_band_key(key: Any) -> Optional[int]:
    """``b"S2A_MSI_02"``/"S2B_MSI_8"/plain int -> MSI band number."""
    if isinstance(key, (int, np.integer)):
        return int(key)
    text = key.decode("latin1") if isinstance(key, bytes) else str(key)
    m = re.search(r"(\d+)\s*$", text)
    return int(m.group(1)) if m else None


def _pad_inducing(params: List[GPParams]) -> List[GPParams]:
    """Pad inducing sets to a common size so per-band GPs stack into one
    banked pytree: padding rows get ``alpha = 0``, contributing exactly
    nothing to the predictive matvec."""
    import jax.numpy as jnp

    m_max = max(int(p.x_train.shape[0]) for p in params)
    out = []
    for p in params:
        m = int(p.x_train.shape[0])
        if m == m_max:
            out.append(p)
            continue
        pad = m_max - m
        out.append(p._replace(
            x_train=jnp.concatenate([
                p.x_train,
                jnp.zeros((pad, p.x_train.shape[1]), p.x_train.dtype),
            ]),
            alpha=jnp.concatenate([
                p.alpha, jnp.zeros((pad,), p.alpha.dtype)
            ]),
        ))
    return out


def load_emulator_bank_file(
    path: str,
    band_numbers: Tuple[int, ...] = EMULATOR_BAND_MAP,
) -> GPParams:
    """One per-geometry pickle (dict of per-band GPs) -> stacked
    ``GPParams`` with a leading band axis in ``band_numbers`` order —
    the aux pytree ``GPBankOperator`` consumes."""
    from .gp import stack_gp_bank

    raw = load_emulator_pickle(path)
    if not isinstance(raw, dict):
        # a single-GP pickle: treat as a one-band bank
        return stack_gp_bank([gp_params_from_emulator(raw)])
    by_band: Dict[int, Any] = {}
    for key, gp in raw.items():
        num = _normalise_band_key(key)
        if num is not None:
            by_band[num] = gp
    missing = [b for b in band_numbers if b not in by_band]
    if missing:
        raise KeyError(
            f"{path}: no emulator for MSI band(s) {missing}; "
            f"found {sorted(by_band)}"
        )
    params = [gp_params_from_emulator(by_band[b]) for b in band_numbers]
    return stack_gp_bank(_pad_inducing(params))


#: ``..._{vza}_{sza}_{raa}.pkl`` — the reference's filename-encoded
#: geometry grid (``Sentinel2_Observations.py:133-145``).
_GEOM_RE = re.compile(
    r"_(?P<vza>\d+(?:\.\d+)?)_(?P<sza>\d+(?:\.\d+)?)_"
    r"(?P<raa>\d+(?:\.\d+)?)\.[^.]+$"
)


def geometry_from_filename(path: str) -> Tuple[float, float, float]:
    """(sza, vza, raa) parsed from an emulator filename, using the
    reference's field convention: vza third-from-last, sza second-from-
    last, raa last (``Sentinel2_Observations.py:135-140``)."""
    m = _GEOM_RE.search(os.path.basename(path))
    if not m:
        raise ValueError(
            f"{path}: filename does not end in _vza_sza_raa.<ext>"
        )
    return (
        float(m.group("sza")), float(m.group("vza")), float(m.group("raa"))
    )


def save_bank_npz(path: str, params: GPParams) -> None:
    """Persist a stacked per-geometry bank as a plain ``.npz`` — the
    reproducible artifact replacing the reference's opaque pickles
    (loads ~instantly, no unpickling of foreign classes)."""
    np.savez(
        path,
        **{f: np.asarray(getattr(params, f)) for f in GPParams._fields},
    )


def load_bank_npz(path: str) -> GPParams:
    import jax.numpy as jnp

    data = np.load(path)
    return GPParams(
        **{f: jnp.asarray(data[f]) for f in GPParams._fields}
    )


def load_emulator_directory(
    folder: str,
    pattern: str = "*.pkl",
    band_numbers: Tuple[int, ...] = EMULATOR_BAND_MAP,
) -> Dict[Tuple[float, float, float], GPParams]:
    """A directory of per-geometry emulator files -> the ``banks`` dict
    of ``io.sentinel2.geometry_bank_aux_builder``: each date's scene
    angles then select the nearest converted bank, exactly like the
    reference's per-geometry unpickling — but as traced arrays through
    one compiled program.

    Accepts the reference's pickles AND this package's converted
    ``.npz`` banks; when both carry the same geometry the ``.npz`` wins
    (it IS the converted pickle, and loads without the per-band
    unpickle/recompute cost)."""
    banks: Dict[Tuple[float, float, float], GPParams] = {}
    pkl_paths = sorted(
        p for p in glob.glob(os.path.join(folder, pattern))
        if not p.endswith(".npz")
    )
    npz_paths = sorted(glob.glob(os.path.join(folder, "*.npz")))
    npz_keys = set()
    for path in npz_paths:
        try:
            key = geometry_from_filename(path)
        except ValueError:
            LOG.warning("skipping %s: no geometry in filename", path)
            continue
        banks[key] = load_bank_npz(path)
        npz_keys.add(key)
        LOG.info("loaded emulator bank %s -> geometry %s", path, key)
    for path in pkl_paths:
        try:
            key = geometry_from_filename(path)
        except ValueError:
            LOG.warning("skipping %s: no geometry in filename", path)
            continue
        if key in npz_keys:
            LOG.debug("%s: geometry %s already loaded from .npz", path,
                      key)
            continue
        banks[key] = load_emulator_bank_file(
            path, band_numbers=band_numbers
        )
        LOG.info("converted emulator bank %s -> geometry %s", path, key)
    if not banks:
        raise IOError(
            f"no emulator files ({pattern} or *.npz) in {folder}"
        )
    return banks
