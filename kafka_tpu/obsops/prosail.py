"""Differentiable PROSAIL-family canopy reflectance operator (JAX).

The reference's Sentinel-2 path inverts PROSAIL through pickled
per-band/per-geometry GP emulators
(``/root/reference/kafka/inference/utils.py:181-219``,
``Sentinel2_Observations.py:157-159``) on a 10-parameter transformed state
(``kafka_test_S2.py:136-137``):

    [n, cab, car, cbrown, cw, cm, lai, ala, bsoil, psoil]

with exponential transforms for the absorbing constituents and
``tlai = exp(-lai/2)`` (``kafka_test_S2.py:84-92``).  The pickles are not
reproducible artifacts, so this module provides the physics itself as a
pure JAX function — exactly differentiable, jit/vmap-native, no emulator
required (the GP/MLP machinery in ``obsops/gp.py``/``mlp.py`` remains
available to emulate *this* model or any external one).

Model structure (all closed-form, fully differentiable):

1. **Leaf optics — generalized plate model** (Allen/Stokes; the PROSPECT
   construction): per-layer absorption ``k`` from the constituent
   contents, elementary-layer transmissivity
   ``theta = (1-k)e^{-k} + k^2 E1(k)`` with the exponential integral
   ``E1`` via Abramowitz-Stegun approximations, Fresnel interface
   transmittances ``tav`` integrated numerically on the host (constants
   per band), and the Stokes N-layer system in its eigenvalue closed form.
2. **Canopy BRF — SAIL-family two-stream + single scattering**:
   Ross-Goudriaan G-functions from the average leaf angle, exact
   single-scattering term with a Kuusk-style hotspot factor, two-stream
   multiple scattering over a Lambertian soil, linear dry/wet soil mixing
   weighted by ``bsoil``/``psoil``.

Calibration status (tests/test_prosail_calibration.py):

- the **SAIL two-stream solution is exact**: ``sail_fluxes`` matches an
  independent float64 finite-difference boundary-value oracle of the same
  ODE system to <2e-3 across leaf/soil/LAI/LIDF regimes;
- the **plate model matches a float64 SciPy-``exp1`` oracle** to <2e-3
  (validating the branch-free E1 approximation under float32);
- the spectral inputs (``BAND_K``/``N_REFRACT``/soil) are generated in
  ``obsops.prospect_data`` from published fine-grid physical data
  (refractive-index curve, liquid-water absorption magnitudes, pigment
  band decompositions, dry-matter SWIR rise) band-averaged over
  flat-top approximations of the Sentinel-2A spectral response
  functions, and are regression-locked against QUANTITATIVE per-band
  canonical targets: fresh/dry/chlorotic leaf reflectance and dense-
  canopy BRF anchors per band, NIR chlorophyll transparency, and the
  945/2202 nm water-band magnitudes.  No PROSPECT-5 coefficient file
  ships in this environment (zero egress); ``prospect_data``'s anchors
  transcribe the published curves, and swapping in an exact table is a
  constant swap that touches no model code.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .protocol import ObservationModel

_EPS = 1e-6

# ---------------------------------------------------------------------------
# Per-band constants (10 bands: B02 B03 B04 B05 B06 B07 B08 B8A B09 B12),
# generated in ``obsops.prospect_data`` from published fine-grid spectra
# (refractive index curve, liquid-water absorption, pigment band
# decompositions, dry-matter SWIR rise) band-averaged over Gaussian
# approximations of the Sentinel-2A spectral response functions.
# ---------------------------------------------------------------------------

from .prospect_data import (  # noqa: E402  (constants, not code)
    BAND_K,
    BAND_WAVELENGTHS,
    N_REFRACT,
    SOIL_DRY,
    SOIL_WET,
)


def _tav_host(alpha_deg: float, n: np.ndarray) -> np.ndarray:
    """Average Fresnel transmittance of the air->leaf interface for
    radiation within a cone of half-angle ``alpha`` — PROSPECT's ``tav``,
    computed by direct numerical integration on the host (exact; the
    published closed form is an analytic antiderivative of this).  Only
    needed for per-band constants, never traced."""
    theta = np.linspace(0.0, np.deg2rad(alpha_deg), 512)[None, :]  # (1, t)
    # kafkalint: disable=implicit-f64 — host-only per-band constant, f64 is
    # the point of the exact integration (never traced)
    n = np.asarray(n, np.float64)[:, None]                         # (b, 1)
    sin_t = np.sin(theta)
    cos_t = np.cos(theta)
    sin_r = np.clip(sin_t / n, 0.0, 1.0)
    cos_r = np.sqrt(1.0 - sin_r**2)
    # Fresnel reflectances, unpolarised average, entering the denser medium
    rs = ((cos_t - n * cos_r) / (cos_t + n * cos_r)) ** 2
    rp = ((n * cos_t - cos_r) / (n * cos_t + cos_r)) ** 2
    t = 1.0 - 0.5 * (rs + rp)
    w = sin_t * cos_t
    return (t * w).sum(axis=1) / np.maximum(w.sum(), 1e-12)


_TAV40 = _tav_host(40.0, N_REFRACT)
_TAV90 = _tav_host(90.0, N_REFRACT)


def expint_e1(x):
    """Exponential integral E1(x) for x > 0 (Abramowitz & Stegun 5.1.53 /
    5.1.56), branch-free for jit."""
    x = jnp.maximum(x, 1e-8)
    # series for x <= 1
    a = jnp.array([-0.57721566, 0.99999193, -0.24991055,
                   0.05519968, -0.00976004, 0.00107857], jnp.float32)
    xs = jnp.minimum(x, 1.0)
    small = (
        a[0] + xs * (a[1] + xs * (a[2] + xs * (a[3] + xs * (a[4] + xs * a[5]))))
        - jnp.log(xs)
    )
    # rational for x >= 1
    xl = jnp.maximum(x, 1.0)
    num = xl * xl + 2.334733 * xl + 0.250621
    den = xl * xl + 3.330657 * xl + 1.681534
    large = jnp.exp(-xl) / xl * num / den
    return jnp.where(x <= 1.0, small, large)


def plate_model(k, tav_alpha, tav90, n, n_layers):
    """Leaf reflectance/transmittance from per-layer absorption ``k`` —
    the generalized plate model in its Stokes closed form (the PROSPECT
    construction).  All inputs broadcast per band."""
    k = jnp.maximum(k, _EPS)
    trans = (1.0 - k) * jnp.exp(-k) + k**2 * expint_e1(k)
    trans = jnp.clip(trans, _EPS, 1.0 - _EPS)

    t21 = tav90 / n**2
    r21 = 1.0 - t21
    r12 = 1.0 - tav90
    talf = tav_alpha
    ralf = 1.0 - talf
    denom = 1.0 - r21**2 * trans**2
    ta = talf * trans * t21 / denom
    ra = ralf + r21 * trans * ta
    t = tav90 * trans * t21 / denom
    r = r12 + r21 * trans * t

    # Stokes system for the remaining N-1 layers (eigenvalue form).
    t = jnp.clip(t, _EPS, 1.0 - _EPS)
    r = jnp.clip(r, _EPS, 1.0 - _EPS)
    d = jnp.sqrt(jnp.maximum(
        ((1.0 + r + t) * (1.0 + r - t) * (1.0 - r + t) * (1.0 - r - t)),
        _EPS**2,
    ))
    rq, tq = r**2, t**2
    a = (1.0 + rq - tq + d) / (2.0 * r)
    b = (1.0 - rq + tq + d) / (2.0 * t)
    m = jnp.maximum(n_layers - 1.0, _EPS)
    bnm1 = jnp.power(jnp.maximum(b, 1.0 + _EPS), m)
    bn2 = bnm1**2
    a2 = a**2
    denom2 = a2 * bn2 - 1.0
    rsub = a * (bn2 - 1.0) / denom2
    tsub = bnm1 * (a2 - 1.0) / denom2

    denom3 = 1.0 - rsub * r
    tran = ta * tsub / denom3
    refl = ra + ta * rsub * t / denom3
    return jnp.clip(refl, 0.0, 1.0), jnp.clip(tran, 0.0, 1.0)


def leaf_optics(n_layers, cab, car, cbrown, cw, cm):
    """(rho, tau) per band from the constituent contents."""
    kk = jnp.asarray(BAND_K, jnp.float32)
    contents = jnp.stack([cab, car, cbrown, cw, cm])
    k = (kk * contents[:, None]).sum(axis=0) / jnp.maximum(n_layers, 1.0)
    return plate_model(
        k,
        jnp.asarray(_TAV40, jnp.float32),
        jnp.asarray(_TAV90, jnp.float32),
        jnp.asarray(N_REFRACT, jnp.float32),
        n_layers,
    )


def g_function(theta, chi_l):
    """Ross-Goudriaan projection function G(theta) for a leaf angle
    distribution with Ross index ``chi_l`` (0 = spherical, +1 planophile,
    -1 erectophile)."""
    phi1 = 0.5 - 0.633 * chi_l - 0.33 * chi_l**2
    phi2 = 0.877 * (1.0 - 2.0 * phi1)
    return phi1 + phi2 * jnp.cos(theta)


def ala_to_chi(ala_deg):
    """Average leaf angle (deg) -> Ross-Goudriaan index.  Spherical LIDF
    has ALA ~ 57.3 deg <-> chi 0; planophile (horizontal) -> +1,
    erectophile (vertical) -> -1 (linear map, clipped to the valid
    Ross-Goudriaan range)."""
    return jnp.clip((57.3 - ala_deg) / 57.3, -0.4, 0.6)


def _fit_bf_polynomial() -> np.ndarray:
    """Host-side fit of ``bf = <cos^2 theta_l>`` as a cubic in the average
    leaf angle (degrees), over the ellipsoidal LIDF family (Campbell):

        g(theta; chi) ~ chi^3 sin(theta) / (cos^2 + chi^2 sin^2)^2

    The SAIL layer coefficients need the second LIDF moment (``bf`` in
    Verhoef's notation); parameterising it directly by ALA keeps the
    operator differentiable in the ``ala`` state without tracing the LIDF
    integral.  Exact for this family to the fit residual (<2e-3 over
    ALA in [15, 80] deg)."""
    trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy 1.x/2.x
    theta = np.linspace(1e-4, np.pi / 2 - 1e-4, 2000)
    chis = np.geomspace(0.08, 12.0, 200)
    alas, bfs = [], []
    for chi in chis:
        g = np.sin(theta) / (
            np.cos(theta) ** 2 + chi**2 * np.sin(theta) ** 2
        ) ** 2
        g /= trapezoid(g, theta)
        alas.append(np.rad2deg(trapezoid(theta * g, theta)))
        bfs.append(trapezoid(np.cos(theta) ** 2 * g, theta))
    return np.polyfit(np.asarray(alas), np.asarray(bfs), 3)


_BF_POLY = _fit_bf_polynomial()


def bf_from_ala(ala_deg):
    """Second LIDF moment <cos^2 theta_l> from the average leaf angle."""
    c = _BF_POLY
    a = jnp.clip(ala_deg, 15.0, 80.0)
    return jnp.clip(
        ((c[0] * a + c[1]) * a + c[2]) * a + c[3], 0.02, 0.98
    )


def _j_exp_integral(p, q, lai):
    """int_0^L e^{-p x} e^{-q x} dx = (1 - e^{-(p+q)L}) / (p+q), guarded
    (Verhoef's J2-style integral)."""
    s = p + q
    s = jnp.where(jnp.abs(s) < _EPS, _EPS, s)
    return (1.0 - jnp.exp(-s * lai)) / s


def sail_fluxes(rho_l, tau_l, soil, lai, ks, ko, bf):
    """Exact SAIL two-stream solution with the direct-beam source term.

    Solves the coupled diffuse-flux ODE system of the SAIL model
    analytically (eigenmodes e^{+-mx} + particular solution driven by the
    direct beam e^{-ks x}, soil boundary U(L) = rs (D(L) + tss)) and
    returns everything the BRF assembly needs.  Verhoef's closed-form
    rsd/tsd/rdo/tdo coefficients are this same construction; deriving it
    from the ODEs keeps every step checkable against a numerical
    boundary-value oracle (tests/test_prosail_calibration.py).

    Layer scattering coefficients from the LIDF second moment ``bf``
    (SUITS/Verhoef):

        sigb = ddb rho + ddf tau,  ddb = (1+bf)/2   (diffuse back)
        sb   = sdb rho + sdf tau,  sdb = (ks+bf)/2  (direct -> diffuse up)
        vb   = dob rho + dof tau,  dob = (ko+bf)/2  (diffuse -> view)
    """
    ddb, ddf = 0.5 * (1.0 + bf), 0.5 * (1.0 - bf)
    sdb, sdf = 0.5 * (ks + bf), 0.5 * (ks - bf)
    dob, dof = 0.5 * (ko + bf), 0.5 * (ko - bf)
    sigb = ddb * rho_l + ddf * tau_l
    sigf = ddf * rho_l + ddb * tau_l
    att = 1.0 - sigf
    # m -> 0 only for a perfectly conservative leaf (rho + tau = 1), where
    # the two exponential modes degenerate into secular (1, x) solutions.
    # Clamping m at 0.02 keeps the closed form well-conditioned and adds
    # <1e-3 error for any physical leaf (single-scatter albedo < 0.998).
    m = jnp.sqrt(jnp.maximum(att**2 - sigb**2, 4e-4))
    sb = sdb * rho_l + sdf * tau_l
    sf = sdf * rho_l + sdb * tau_l
    vb = dob * rho_l + dof * tau_l
    vf = dof * rho_l + dob * tau_l

    # ks = m is a removable resonance (the particular solution collides
    # with the decaying eigenmode; the true solution gains a secular
    # x e^{-mx} term).  Rather than special-casing, nudge ks off the
    # resonance and solve the ODE *exactly* for the nudged ks everywhere
    # (source, BCs, view integrals stay mutually consistent): the error is
    # |BRF(ks +- d) - BRF(ks)|, bounded by the solution's smoothness in
    # ks (<~2e-3 for d = 0.02; sdb/sdf keep the physical ks).  Resonance
    # only occurs for ks >~ 0.3, so det = ks^2 - m^2 stays >~ 0.012.
    d_res = 0.02
    diff = ks - m
    ks = jnp.where(
        jnp.abs(diff) < d_res,
        m + jnp.where(diff >= 0.0, d_res, -d_res),
        ks,
    )
    det = ks**2 - m**2
    # Particular solution  D_p = a e^{-ks x},  U_p = b e^{-ks x} of
    #   dD/dx = -att D + sigb U + sf Es,   dU/dx = att U - sigb D - sb Es
    # (x downward, Es = e^{-ks x}); Cramer on the 2x2 system whose rhs is
    # (sf, -sb): the beam feeds +sf into the downward equation and +sb
    # into the upward one.
    a_p = (-(att + ks) * sf - sigb * sb) / det
    b_p = (-(att - ks) * sb - sigb * sf) / det

    # Homogeneous modes: D ~ e^{-+mx}; U/D ratios rinf (decaying),
    # 1/rinf (growing).
    rinf = sigb / (att + m)
    tss = jnp.exp(-ks * lai)
    e_m = jnp.exp(-m * lai)

    # Boundary conditions: D(0) = 0;  U(L) = rs (D(L) + tss).
    #   A + B + a_p = 0
    #   A rinf e^{-mL} + B e^{+mL}/rinf + b_p tss
    #     = rs (A e^{-mL} + B e^{+mL} + a_p tss + tss)
    # Scale B by e^{+mL} (B' = B e^{mL}) so nothing overflows for large
    # m L: B = B' e^{-mL}.
    c11, c12 = 1.0, e_m
    c21 = (rinf - soil) * e_m
    c22 = 1.0 / rinf - soil
    r1 = -a_p
    r2 = (soil * (a_p + 1.0) - b_p) * tss
    det_bc = c11 * c22 - c12 * c21
    det_bc = jnp.where(jnp.abs(det_bc) < _EPS, _EPS, det_bc)
    aa = (r1 * c22 - c12 * r2) / det_bc
    bb_s = (c11 * r2 - c21 * r1) / det_bc   # scaled B'

    d_bottom = aa * e_m + bb_s + a_p * tss
    u_bottom = soil * (d_bottom + tss)

    # Directional radiance from leaf-scattered diffuse flux:
    #   int_0^L (vb U + vf D) e^{-ko x} dx
    # with U, D as sums of exponentials -> elementary integrals.  The
    # growing mode is integrated in its scaled form:
    #   B e^{+mx} e^{-ko x} = B' e^{-m(L-x)} e^{-ko x}; int_0^L =
    #   B' e^{-mL} (e^{(m-ko)L} - 1)/(m-ko)  ==  B' J(koL, mL) stable form.
    j_dec = _j_exp_integral(m, ko, lai)                   # decaying mode
    s_g = ko - m
    s_g = jnp.where(jnp.abs(s_g) < 1e-4, 1e-4, s_g)
    j_gro = (jnp.exp(-m * lai) - jnp.exp(-ko * lai)) / s_g  # growing mode
    j_par = _j_exp_integral(ks, ko, lai)                  # particular
    rad_leaf = (
        (vb * rinf + vf) * aa * j_dec
        + (vb / rinf + vf) * bb_s * j_gro
        + (vb * b_p + vf * a_p) * j_par
    )
    return {
        "rad_leaf": rad_leaf,
        "u_bottom": u_bottom,
        "d_bottom": d_bottom,
        "tss": tss,
        "rdd_top": aa * rinf + bb_s / rinf * e_m + b_p,  # diffuse albedo
        "m": m, "rinf": rinf, "a_p": a_p, "b_p": b_p,
        "aa": aa, "bb_scaled": bb_s,
        "sigb": sigb, "sigf": sigf, "sb": sb, "sf": sf,
        "vb": vb, "vf": vf,
    }


def canopy_brf(rho_l, tau_l, soil, lai, ala_deg, sza_deg, vza_deg, raa_deg,
               hotspot: float = 0.01):
    """Top-of-canopy bidirectional reflectance factor per band.

    SAIL decomposition with the diffuse part solved exactly:

    1. **single scattering** sun -> leaf -> view with a Kuusk-style
       hotspot gap correlation (bi-Lambertian area-scattering phase);
    2. **diffuse field** from the closed-form two-stream boundary-value
       solution (``sail_fluxes``): leaf-scattered diffuse radiance toward
       the viewer plus the soil-reflected diffuse flux escaping through
       the view-path gap fraction;
    3. **soil direct-direct** through the hotspot-correlated two-way gap
       probability.
    """
    ts = jnp.deg2rad(sza_deg)
    to = jnp.deg2rad(vza_deg)
    psi = jnp.deg2rad(raa_deg)
    mu_s = jnp.clip(jnp.cos(ts), 0.05, 1.0)
    mu_o = jnp.clip(jnp.cos(to), 0.05, 1.0)
    lai = jnp.maximum(lai, _EPS)

    chi = ala_to_chi(ala_deg)
    gs = g_function(ts, chi)
    go = g_function(to, chi)
    ks = gs / mu_s           # directional extinction coefficients
    ko = go / mu_o

    # Scattering phase: bi-Lambertian leaf, area-scattering approximation
    # (Ross): fraction of intercepted flux scattered sun->view.
    cos_scatter = (
        jnp.cos(ts) * jnp.cos(to) + jnp.sin(ts) * jnp.sin(to) * jnp.cos(psi)
    )
    w = rho_l + tau_l                              # single-scatter albedo
    gamma = 0.125 * (
        w * (1.0 + cos_scatter) + (rho_l - tau_l) * (1.0 - cos_scatter)
    )

    # Kuusk hotspot: correlation between sun and view gap fractions.
    delta = jnp.sqrt(
        jnp.maximum(
            jnp.tan(ts) ** 2 + jnp.tan(to) ** 2
            - 2.0 * jnp.tan(ts) * jnp.tan(to) * jnp.cos(psi),
            0.0,
        )
    )
    alpha_h = jnp.maximum(delta / jnp.maximum(hotspot, 1e-4), 1e-6)
    # overlap integral approximation (exponential form): full correlation
    # sqrt(ks ko) L in the exact backscatter direction, decaying with
    # angular distance from it.
    c_hs = jnp.sqrt(ks * ko) * lai * (1.0 - jnp.exp(-alpha_h)) / alpha_h
    # Single scattering over black soil with hotspot-corrected two-way
    # extinction: integral_0^L gamma e^{-(ks+ko) x + C(x)} dx, approximated
    # by deflating (ks+ko) with the correlation fraction f_hs.
    f_hs = c_hs / jnp.maximum((ks + ko) * lai, _EPS)
    k_two = (ks + ko) * (1.0 - f_hs)
    brf_ss = gamma * (1.0 - jnp.exp(-k_two * lai)) / jnp.maximum(k_two, _EPS)
    # correlated two-way soil transmittance (hotspot raises it above
    # tss * too)
    tau_sso = jnp.exp(-k_two * lai)

    # Exact diffuse field (two-stream BVP): leaf-scattered radiance toward
    # the viewer + soil-reflected diffuse escaping through view gaps.
    fx = sail_fluxes(rho_l, tau_l, soil, lai, ks, ko, bf_from_ala(ala_deg))
    tau_oo = jnp.exp(-ko * lai)
    brf_diffuse = fx["rad_leaf"] + fx["u_bottom"] * tau_oo \
        - soil * fx["tss"] * tau_oo
    # (the u_bottom term contains soil * tss * too already; subtract it and
    # add the hotspot-correlated version instead)
    brf_soil = soil * tau_sso

    brf = brf_ss + brf_diffuse + brf_soil
    return jnp.clip(brf, 0.0, 1.0)


class ProsailAux(NamedTuple):
    """Per-date acquisition geometry (degrees), broadcast or per pixel."""

    sza: jnp.ndarray
    vza: jnp.ndarray
    raa: jnp.ndarray


#: The 10-parameter transformed state of the reference S2 config
#: (``kafka_test_S2.py:136-137``).
PROSAIL_PARAMETER_LIST = (
    "n", "cab", "car", "cbrown", "cw", "cm", "lai", "ala", "bsoil", "psoil",
)


def inverse_transforms(x):
    """Transformed state -> physical PROSAIL quantities
    (``kafka_test_S2.py:84-92``: cab/car/cm/cw/lai live in exponential
    spaces, ala in [0,1] of 90 deg)."""
    # Leaf-structure N is carried directly in the state (the reference's
    # SAILPrior mean is 2.1, ``kafka_test_S2.py:84``) — identity transform,
    # physical plate-layer range [1, 3].
    n = jnp.clip(x[0], 1.0, 3.0)
    cab = -100.0 * jnp.log(jnp.clip(x[1], _EPS, 1.0 - _EPS))
    car = -100.0 * jnp.log(jnp.clip(x[2], _EPS, 1.0 - _EPS))
    cbrown = jnp.clip(x[3], 0.0, 1.0)
    cw = -(1.0 / 50.0) * jnp.log(jnp.clip(x[4], _EPS, 1.0 - _EPS))
    cm = -(1.0 / 100.0) * jnp.log(jnp.clip(x[5], _EPS, 1.0 - _EPS))
    lai = -2.0 * jnp.log(jnp.clip(x[6], _EPS, 1.0 - _EPS))
    ala = 90.0 * jnp.clip(x[7], 0.0, 1.0)
    bsoil = jnp.maximum(x[8], 0.0)
    psoil = jnp.clip(x[9], 0.0, 1.0)
    return n, cab, car, cbrown, cw, cm, lai, ala, bsoil, psoil


class ProsailOperator(ObservationModel):
    """10-band S2 reflectance operator on the transformed PROSAIL state —
    the self-contained, differentiable replacement for the reference's
    pickled PROSAIL emulators (``inference/utils.py:181-219``)."""

    n_bands = 10
    n_params = 10
    #: transformed-space domain: exponential-transform params in (0, 1),
    #: leaf-structure n carried directly in [1, 3], ala fraction in (0, 1),
    #: bsoil in (0, 2], psoil in (0, 1).
    state_bounds = (
        np.array([1.0, 5e-3, 5e-3, 0.0, 5e-3, 5e-3, 5e-3, 0.02, 0.0, 0.0],
                 np.float32),
        np.array([3.0, 0.999, 0.999, 1.0, 0.999, 0.999, 0.999, 0.98, 2.0,
                  1.0], np.float32),
    )

    def __init__(self, hotspot: float = 0.01):
        self.hotspot = float(hotspot)

    def forward_pixel(self, aux: Optional[ProsailAux], x_pixel):
        if aux is None:
            aux = ProsailAux(
                sza=jnp.asarray(30.0, jnp.float32),
                vza=jnp.asarray(0.0, jnp.float32),
                raa=jnp.asarray(0.0, jnp.float32),
            )
        n, cab, car, cbrown, cw, cm, lai, ala, bsoil, psoil = (
            inverse_transforms(x_pixel)
        )
        rho_l, tau_l = leaf_optics(n, cab, car, cbrown, cw, cm)
        soil = bsoil * (
            psoil * jnp.asarray(SOIL_DRY, jnp.float32)
            + (1.0 - psoil) * jnp.asarray(SOIL_WET, jnp.float32)
        )
        soil = jnp.clip(soil, 0.0, 1.0)
        return canopy_brf(
            rho_l, tau_l, soil, lai, ala, aux.sza, aux.vza, aux.raa,
            hotspot=self.hotspot,
        )
