"""Ross-Li BRDF kernels + the linear kernel-weights observation operator.

The reference's MOD09 path builds RossThick / LiSparse-Reciprocal kernel
values per pixel through the SIAC ``kernels.Kernels`` class
(``/root/reference/kafka/input_output/observations.py:141-143``: LiSparse,
RossThick, reciprocal, normalised, MODIS h/b and b/r) and carries them as
the observation operator for directional surface reflectance.  Here the
kernels are computed directly from the published MODIS BRDF/albedo model
(Lucht, Schaaf & Strahler 2000; the MCD43 ATBD) as pure JAX functions —
jit/vmap-friendly, usable both host-side when a reader prepares aux data
and device-side inside the solver's traced program.

Semi-empirical BRDF model per band:

    rho(sza, vza, raa) = f_iso + f_vol * K_vol + f_geo * K_geo

which is *linear* in the state (f_iso, f_vol, f_geo) — the TPU solver sees
a constant Jacobian ``[1, K_vol, K_geo]`` per band and the Gauss-Newton
loop converges in one iteration.

Angle convention: degrees at the public API (matching the reader rasters,
``observations.py:125-135`` divides the int16 HDF fields by 100 into
degrees); radians internally.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp
import numpy as np

from .protocol import ObservationModel

#: MODIS LiSparse crown shape: relative height h/b and shape b/r
#: (the ``MODISSPARSE=True`` constants: h/b = 2, b/r = 1).
HB_RATIO = 2.0
BR_RATIO = 1.0


def _phase_cos(cos_t1, sin_t1, cos_t2, sin_t2, cos_phi):
    """cos of the phase angle between the two directions."""
    return cos_t1 * cos_t2 + sin_t1 * sin_t2 * cos_phi


def ross_thick(sza_deg, vza_deg, raa_deg):
    """RossThick (volumetric) kernel, zero at nadir.

    K_vol = [(pi/2 - xi) cos xi + sin xi] / (cos sza + cos vza) - pi/4
    """
    t_s = jnp.deg2rad(sza_deg)
    t_v = jnp.deg2rad(vza_deg)
    phi = jnp.deg2rad(raa_deg)
    cos_xi = _phase_cos(
        jnp.cos(t_s), jnp.sin(t_s), jnp.cos(t_v), jnp.sin(t_v), jnp.cos(phi)
    )
    cos_xi = jnp.clip(cos_xi, -1.0, 1.0)
    xi = jnp.arccos(cos_xi)
    num = (jnp.pi / 2.0 - xi) * cos_xi + jnp.sin(xi)
    return num / (jnp.cos(t_s) + jnp.cos(t_v)) - jnp.pi / 4.0


def li_sparse_reciprocal(sza_deg, vza_deg, raa_deg,
                         hb: float = HB_RATIO, br: float = BR_RATIO):
    """LiSparse-Reciprocal (geometric-optical) kernel, zero at nadir.

    Standard MCD43 form with equivalent angles th' = arctan(br * tan th),
    overlap O from the cylinder-intersection term, and the reciprocal
    sec th_s' sec th_v' closure.
    """
    t_s = jnp.arctan(br * jnp.tan(jnp.deg2rad(sza_deg)))
    t_v = jnp.arctan(br * jnp.tan(jnp.deg2rad(vza_deg)))
    phi = jnp.deg2rad(raa_deg)
    cos_s, sin_s, tan_s = jnp.cos(t_s), jnp.sin(t_s), jnp.tan(t_s)
    cos_v, sin_v, tan_v = jnp.cos(t_v), jnp.sin(t_v), jnp.tan(t_v)
    cos_phi = jnp.cos(phi)
    cos_xi = jnp.clip(
        _phase_cos(cos_s, sin_s, cos_v, sin_v, cos_phi), -1.0, 1.0
    )
    sec_sum = 1.0 / cos_s + 1.0 / cos_v
    d2 = tan_s**2 + tan_v**2 - 2.0 * tan_s * tan_v * cos_phi
    # Guard the sqrt: d2 is >= 0 analytically but float rounding can dip
    # below, and sqrt(0) has an inf gradient XLA would propagate as NaN.
    d2 = jnp.maximum(d2, 0.0)
    cos_t = hb * jnp.sqrt(
        d2 + (tan_s * tan_v * jnp.sin(phi)) ** 2
    ) / sec_sum
    cos_t = jnp.clip(cos_t, -1.0, 1.0)
    t = jnp.arccos(cos_t)
    overlap = (1.0 / jnp.pi) * (t - jnp.sin(t) * cos_t) * sec_sum
    return overlap - sec_sum + 0.5 * (1.0 + cos_xi) / (cos_s * cos_v)


def ross_li_kernels(sza_deg, vza_deg, raa_deg):
    """(K_vol, K_geo) for arrays of angles in degrees — the TPU equivalent
    of constructing ``kernels.Kernels(vza, sza, raa, ...)`` per scene
    (``observations.py:141-143``)."""
    return (
        ross_thick(sza_deg, vza_deg, raa_deg),
        li_sparse_reciprocal(sza_deg, vza_deg, raa_deg),
    )


class KernelsAux(NamedTuple):
    """Per-pixel kernel values for one acquisition: each ``(n_pix,)`` (or
    scalar to broadcast a scene-constant geometry)."""

    k_vol: jnp.ndarray
    k_geo: jnp.ndarray


class KernelsOperator(ObservationModel):
    """Linear kernel-weights observation operator.

    State per pixel: ``(f_iso, f_vol, f_geo)`` per MODIS band, concatenated
    band-major — p = 3 * n_bands (21 for the 7 land bands).  Band b of the
    predicted reflectance reads only its own triplet:

        h_b = x[3b] + K_vol * x[3b+1] + K_geo * x[3b+2]

    This is the assimilation framing of the MCD43 kernel inversion: MOD09
    directional reflectances are the observations, kernel weights are the
    state, and the temporal filter replaces the 16-day window fit.  The
    reference reader hands the same information to the solver as the
    ``obs_op`` member of ``MOD09_data`` (``observations.py:145``).
    """

    def __init__(self, n_modis_bands: int = 7):
        self.n_bands = int(n_modis_bands)
        self.n_params = 3 * self.n_bands
        # Kernel weights can legitimately be slightly negative (f_geo often
        # is); bound loosely to keep Gauss-Newton iterates physical.
        lower = np.tile([-0.2, -1.0, -1.0], self.n_bands)
        upper = np.tile([1.2, 2.0, 2.0], self.n_bands)
        self.state_bounds = (
            jnp.asarray(lower, jnp.float32), jnp.asarray(upper, jnp.float32)
        )

    def forward_pixel(self, aux: Any, x_pixel: jnp.ndarray) -> jnp.ndarray:
        w = x_pixel.reshape(self.n_bands, 3)
        return w[:, 0] + aux.k_vol * w[:, 1] + aux.k_geo * w[:, 2]
