"""PROSPECT-grade spectral inputs for the PROSAIL operator, generated
from published physical anchor data and band-averaged over the Sentinel-2
spectral response functions.

The reference encodes real PROSPECT through pickled emulators
(``/root/reference/kafka/inference/utils.py:181-219``); no PROSPECT-5
coefficient table ships in this environment (zero egress, no ``prosail``
package), so this module reconstructs the spectral inputs on a fine
wavelength grid (400-2500 nm, 5 nm) from published physical data:

- **leaf refractive index** ``n(lambda)``: piecewise-linear through the
  anchor points of the PROSPECT refractive-index curve (monotone decline
  1.54 -> 1.33 across the domain);
- **liquid water absorption** ``k_w(lambda)`` [cm^-1]: anchored to the
  published pure-water absorption spectrum (Palmer & Williams 1974 /
  Kou et al. 1993 magnitudes: the 970/1200 nm weak bands, the 1450 and
  1940 nm strong bands, the 2200 nm shoulder);
- **in-vivo chlorophyll a+b specific absorption** [cm^2/ug]: Gaussian
  decomposition with the Soret (~435 nm) and red (~672 nm) bands plus
  the weak green-gap absorption, normalised so a canonical leaf
  (Cab=40 ug/cm^2) reproduces published green-leaf red/green
  reflectance;
- **carotenoid specific absorption** [cm^2/ug]: blue-only (400-520 nm)
  double-peak band;
- **brown pigment** (relative units): exponential decay from the blue,
  zero past ~900 nm;
- **dry matter specific absorption** [cm^2/g]: monotone SWIR rise with
  the cellulose/lignin magnitudes that make Cm=0.009 g/cm^2 matter at
  2200 nm;
- **soil reflectance**: bright dry-loam spectrum rising into the SWIR;
  wet variant darkened with water-band dips (the PROSAIL dry/wet mixing
  model).

Band constants are the SRF-weighted averages over **Gaussian
approximations of the Sentinel-2A response functions** (published centre
wavelengths and FWHM per band).  Everything is generated at import by
plain numpy (milliseconds); the generation is deterministic and the
per-band results are regression-locked by
``tests/test_prosail_calibration.py`` against quantitative canonical
targets (leaf-level and canopy-level).

Provenance honesty: the anchor tables below are transcriptions of
published curve shapes and magnitudes, not a shipped PROSPECT-5 data
file; the water spectrum and refractive index are the best-constrained
(physical measurements), the pigment decompositions are fits that
reproduce canonical leaf reflectance.  Swapping in an exact PROSPECT-5
table, should one become available, is a constant swap that touches no
model code (the arrays below keep the same shapes).
"""

from __future__ import annotations

import numpy as np

#: fine wavelength grid [nm]
WL = np.arange(400.0, 2501.0, 5.0)

# ---------------------------------------------------------------------------
# Sentinel-2A spectral response (Gaussian approximation: centre, FWHM, nm),
# reference band order B02..B8A, B09, B12
# (``Sentinel2_Observations.py:93-94``).
# ---------------------------------------------------------------------------
S2_BANDS = {
    "B02": (492.4, 66.0),
    "B03": (559.8, 36.0),
    "B04": (664.6, 31.0),
    "B05": (704.1, 16.0),
    "B06": (740.5, 15.0),
    "B07": (782.8, 20.0),
    "B08": (832.8, 106.0),
    "B8A": (864.7, 22.0),
    "B09": (945.1, 21.0),
    "B12": (2202.4, 175.0),
}
BAND_ORDER = list(S2_BANDS)


def _interp(anchors) -> np.ndarray:
    """Piecewise-linear spectrum through (wavelength, value) anchors."""
    pts = np.asarray(anchors, np.float64)
    return np.interp(WL, pts[:, 0], pts[:, 1])


def _gaussians(components) -> np.ndarray:
    """Sum of (amplitude, centre, sigma) Gaussians on the fine grid."""
    out = np.zeros_like(WL)
    for amp, centre, sigma in components:
        out += amp * np.exp(-0.5 * ((WL - centre) / sigma) ** 2)
    return out


# --- leaf refractive index -------------------------------------------------
N_SPECTRUM = _interp([
    (400, 1.540), (450, 1.535), (500, 1.525), (550, 1.515), (600, 1.505),
    (650, 1.495), (700, 1.485), (750, 1.475), (800, 1.465), (900, 1.455),
    (1000, 1.450), (1200, 1.440), (1400, 1.425), (1600, 1.415),
    (1800, 1.405), (2000, 1.395), (2200, 1.370), (2400, 1.340),
    (2500, 1.330),
])

# --- chlorophyll a+b, in vivo [cm^2/ug] ------------------------------------
K_CAB = _gaussians([
    (0.072, 435.0, 26.0),   # Soret band
    (0.034, 470.0, 22.0),   # Chl-b shoulder
    (0.013, 580.0, 80.0),   # green-gap base absorption
    (0.022, 630.0, 25.0),   # red shoulder
    (0.070, 672.0, 16.0),   # red peak
    (0.004, 710.0, 30.0),   # in-vivo red-edge wing (broadened red band)
])
# In-vivo chlorophyll absorption vanishes across the red edge; the
# taper ends before B07/B08 so the NIR plateau bands stay
# chlorophyll-transparent (their defining property).
K_CAB *= np.clip((765.0 - WL) / 30.0, 0.0, 1.0)

# --- carotenoids [cm^2/ug], blue only --------------------------------------
K_CAR = _gaussians([
    (0.022, 430.0, 30.0),
    (0.045, 452.0, 18.0),
    (0.040, 482.0, 18.0),
])
K_CAR[WL > 540.0] = 0.0

# --- brown pigment [relative] ----------------------------------------------
K_BROWN = np.where(
    WL < 900.0, 0.9 * np.exp(-(WL - 400.0) / 150.0), 0.0
)

# --- liquid water [cm^-1] --------------------------------------------------
K_WATER = _interp([
    (400, 0.0007), (600, 0.002), (700, 0.006), (800, 0.02), (900, 0.068),
    (940, 0.27), (960, 0.45), (980, 0.43), (1000, 0.36), (1100, 0.17),
    (1150, 0.80), (1200, 1.00), (1250, 0.85), (1300, 1.20), (1350, 3.0),
    (1400, 14.0), (1450, 29.0), (1500, 20.0), (1550, 10.0), (1600, 6.7),
    (1650, 5.6), (1700, 5.6), (1750, 6.0), (1800, 8.0), (1850, 15.0),
    (1900, 100.0), (1950, 125.0), (2000, 65.0), (2050, 40.0),
    (2100, 26.0), (2150, 24.0), (2200, 27.0), (2250, 31.0), (2300, 37.0),
    (2350, 44.0), (2400, 55.0), (2450, 70.0), (2500, 88.0),
])

# --- dry matter [cm^2/g] ---------------------------------------------------
# Magnitudes set so a fresh canonical leaf (Cw=0.0176 cm, Cm=0.009
# g/cm^2) keeps the published ~0.15 reflectance at 2200 nm (water
# dominates there; dry matter adds the cellulose/lignin floor that takes
# over when Cw drops).
K_DRY = _interp([
    (400, 3.0), (600, 1.5), (800, 1.0), (1000, 2.0), (1200, 4.0),
    (1400, 5.0), (1500, 6.0), (1700, 10.0), (1800, 11.0), (2000, 16.0),
    (2100, 19.0), (2200, 22.0), (2300, 28.0), (2400, 32.0), (2500, 35.0),
])

# --- soil spectra ----------------------------------------------------------
SOIL_DRY_SPECTRUM = _interp([
    (400, 0.06), (500, 0.09), (600, 0.14), (700, 0.18), (800, 0.22),
    (900, 0.25), (1000, 0.27), (1200, 0.31), (1400, 0.31), (1600, 0.35),
    (1800, 0.36), (2000, 0.33), (2200, 0.37), (2400, 0.33), (2500, 0.31),
])
SOIL_WET_SPECTRUM = _interp([
    (400, 0.035), (600, 0.075), (800, 0.12), (1000, 0.14), (1200, 0.16),
    (1400, 0.12), (1600, 0.17), (1800, 0.17), (2000, 0.12), (2200, 0.16),
    (2400, 0.12), (2500, 0.10),
])


def band_average(spectrum: np.ndarray) -> np.ndarray:
    """SRF-weighted average of a fine-grid spectrum over the 10 S2 bands.

    MSI response functions are near-rectangular (steep band edges), so
    the weight is a flat-top super-Gaussian ``exp(-0.5 x^8)`` with
    half-width FWHM/2 — a plain Gaussian's long tails would leak e.g.
    red-edge chlorophyll absorption into the (chlorophyll-transparent)
    broad B08 NIR band."""
    out = np.empty(len(BAND_ORDER))
    for i, name in enumerate(BAND_ORDER):
        centre, fwhm = S2_BANDS[name]
        x = (WL - centre) / (fwhm / 2.0)
        w = np.exp(-0.5 * x**8)
        out[i] = (w * spectrum).sum() / w.sum()
    return out


#: band centre wavelengths [nm], reference band order
BAND_WAVELENGTHS = np.array([S2_BANDS[b][0] for b in BAND_ORDER])

#: per-band leaf refractive index
N_REFRACT = band_average(N_SPECTRUM)

#: band-averaged specific absorption, rows = (cab, car, cbrown, cw, cm)
#: with the units of ``prosail.inverse_transforms`` outputs
#: (ug/cm^2, ug/cm^2, -, cm, g/cm^2)
BAND_K = np.stack([
    band_average(K_CAB),
    band_average(K_CAR),
    band_average(K_BROWN),
    band_average(K_WATER),
    band_average(K_DRY),
])

SOIL_DRY = band_average(SOIL_DRY_SPECTRUM)
SOIL_WET = band_average(SOIL_WET_SPECTRUM)
