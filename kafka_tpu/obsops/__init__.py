"""Observation operators: differentiable forward models H(x) with autodiff
linearisation (the reference's obs-op factories + emulators, re-designed)."""

from .protocol import MappedStateModel, ObservationModel
from .identity import IdentityOperator
from .wcm import WCMAux, WCMOperator, WCM_PARAMETERS, wcm_sigma0, validate_state
from .twostream import (
    NIR_MAPPER,
    VIS_MAPPER,
    TwoStreamOperator,
    tlai_to_lai,
    twostream_albedo,
)
from .kernels import (
    KernelsAux,
    KernelsOperator,
    li_sparse_reciprocal,
    ross_li_kernels,
    ross_thick,
)
from .gp import (
    GPBankOperator,
    GPParams,
    fit_gp,
    gp_predict_pixel,
    load_gp,
    save_gp,
    stack_gp_bank,
)
from .gp_import import (
    gp_params_from_emulator,
    load_emulator_bank_file,
    load_emulator_directory,
    load_emulator_pickle,
)
from .mlp import MLPOperator, fit_mlp, mlp_apply
from .joint import (
    ProsailJointOperator,
    WCMJointOperator,
    joint_state_bounds,
)
