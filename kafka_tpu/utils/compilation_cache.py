"""Persistent XLA compilation cache.

TPU compiles are expensive (~10 s for the per-date assimilation program,
and ~0.5 s even for trivial eager ops through a tunneled chip), and the
reference-scale workloads re-run the same programs across processes —
chunked drivers, restarts, repeated measurements.  Enabling JAX's
persistent compilation cache makes every compile after the first process
a disk hit.

Called by the CLI drivers, ``bench.py`` and the measurement harness; safe
to call multiple times.  Opt out with ``KAFKA_TPU_NO_COMPILE_CACHE=1`` or
redirect with ``KAFKA_TPU_COMPILE_CACHE_DIR``.
"""

from __future__ import annotations

import logging
import os

LOG = logging.getLogger(__name__)

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "kafka_tpu", "xla"
)


def enable_compilation_cache(cache_dir: str | None = None,
                             min_compile_time_s: float = 0.5) -> str | None:
    """Point JAX at a persistent on-disk compilation cache.

    ``min_compile_time_s`` lowers the persistence threshold for callers
    whose compiles are fast but still worth caching — the serving
    daemon's AOT bucket warm-up wants ZERO re-compiles on restart, so it
    passes 0 and eats the (harmless on matching hardware) XLA:CPU AOT
    load-time warnings.

    Returns the cache directory, or ``None`` when disabled (env opt-out
    or a JAX without the config knobs)."""
    if os.environ.get("KAFKA_TPU_NO_COMPILE_CACHE"):
        return None
    import jax

    path = (
        cache_dir
        or os.environ.get("KAFKA_TPU_COMPILE_CACHE_DIR")
        or _DEFAULT_DIR
    )
    # Scope by platform configuration WITHOUT initializing a backend
    # (jax.default_backend() would lock backend/distributed setup and
    # pay full device-client initialization even for --help): processes
    # pinned to CPU (tests) and processes with the device plugin
    # (drivers, bench) get separate caches, because XLA:CPU AOT
    # artifacts written under one configuration warn — and could
    # SIGILL — when loaded under another.
    scope = os.environ.get("JAX_PLATFORMS", "").strip() or "default"
    path = os.path.join(path, scope.replace(",", "-"))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # 0.5 s threshold: tunneled-TPU compiles (0.5 s even for trivial
        # eager ops, ~10 s for the solver programs) all cache; sub-100 ms
        # host-CPU compiles don't — XLA:CPU AOT entries are the ones that
        # warn about machine-feature mismatches at load time.
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except (AttributeError, ValueError, OSError) as e:
        LOG.info("compilation cache unavailable: %s", e)
        return None
    return path
