"""Profiling hooks (SURVEY.md §5: the reference has none — its nearest
thing is timestamped DEBUG logging, ``kafka_test.py:4-8``).

Two layers:

- :func:`trace` — a ``jax.profiler.trace`` context manager that captures a
  full XLA/TPU trace (HLO timelines, device occupancy) viewable in
  TensorBoard / Perfetto, no-op when no logdir is given.
- :func:`annotate` — named host-side phase annotations
  (``jax.profiler.TraceAnnotation``) so engine phases (advance /
  assimilate / dump) show up as labelled spans inside the trace.

Both degrade to no-ops if ``jax.profiler`` is unavailable so host-only
tools (readers, writers) can annotate unconditionally.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(logdir: Optional[str]) -> Iterator[None]:
    """Capture a JAX profiler trace into ``logdir`` (no-op if ``None`` or
    if ``jax.profiler`` is unavailable)."""
    if not logdir:
        yield
        return
    try:
        import jax.profiler
        # kafkalint: disable=raw-device-introspection — this IS one of
        # the two sanctioned wrappers (telemetry.perf drives managed
        # captures; this context manager is the CLI --profile-dir path)
        ctx = jax.profiler.trace(logdir)
    except (ImportError, AttributeError):
        # Profiler genuinely unavailable (no jax / stripped build) — a
        # host-only tool keeps working untraced.  Anything else (bad
        # logdir, a second trace already active) is a REAL failure the
        # caller asked for a trace and must hear about.
        yield
        return
    with ctx:
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label the enclosed host work as a named span in profiler traces."""
    try:
        import jax.profiler
        # kafkalint: disable=raw-device-introspection — phase labelling
        # only: annotations name spans inside a capture someone else
        # started, they never start/stop captures or read device state
        ctx = jax.profiler.TraceAnnotation(name)
    except (ImportError, AttributeError):
        # Same contract as trace(): only "profiler unavailable" degrades
        # to a no-op; real profiler failures surface.
        yield
        return
    with ctx:
        yield
