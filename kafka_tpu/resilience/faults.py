"""Deterministic fault injection — the chaos half of the resilience layer.

Production code marks its fragile operations with named *fault points*
(``faults.fault_point("prefetch.read_date", ...)``); this registry counts
every pass through each site and raises a scripted :class:`InjectedFault`
on exactly the call numbers a test (or a CLI chaos run) armed.  With
nothing armed a fault point is one module-global boolean read — safe on
hot paths.

In-repo sites:

================== ====================================================
``io.read_band``        GeoTIFF reads (``io.geotiff.read_geotiff`` /
                        ``read_geotiff_window``)
``prefetch.read_date``  one observation date's host-side read (prefetch
                        worker thread AND the synchronous
                        ``prefetch_depth=0`` path)
``scheduler.run_one``   one chunk execution attempt in
                        ``shard.scheduler.run_chunks`` and
                        ``shard.queue.run_queue``
``scheduler.claim``     one lease-claim attempt in the multi-host queue
                        (``shard.queue._try_claim`` — fresh claims and
                        reclaims both)
``scheduler.heartbeat`` one lease renewal on the queue worker's
                        background heartbeat thread
``scheduler.commit``    the ``.done`` commit of a queue-run chunk (fires
                        BEFORE ``mark_done``, so a transient commit
                        failure re-runs the chunk — the at-least-once
                        double-execution path)
``checkpoint.save``     one checkpoint shard write in
                        ``engine.checkpoint.Checkpointer.save``
``serve.admit``         one admission decision in
                        ``serve.service.AssimilationService.submit``
                        (an injected fault here sheds the request —
                        counted rejection, never a crashed daemon)
``serve.solve``         one request's incremental solve on the serving
                        worker (transient retries under the service
                        retry policy; poison answers an error response)
``serve.respond``       one atomic response write (a crash between
                        solve and respond is exactly what the request
                        journal's idempotent replay recovers)
``solver.pixel``        deterministic per-PIXEL corruption of the
                        Gauss-Newton linearisation (``h0`` forced NaN)
                        — the calls grammar addresses 0-based pixel
                        index ranges, not call numbers, and nothing is
                        raised: the armed pixels must come back
                        QA-quarantined through the solve-health path
                        (``core.solver_health``)
``obs.bias``            scripted ADDITIVE BIAS on observations — the
                        calls grammar addresses 1-based fetch-order
                        date numbers, and nothing is raised: the armed
                        dates' valid observations gain
                        ``telemetry.quality.OBS_BIAS_VALUE``, which the
                        quality ledger's drift sentinels must flag
                        (verdict flip + ``quality_drift`` event) while
                        unbiased dates stay bit-identical
``device.oom``          one window's solve dispatch in
                        ``engine.filter`` (unfused per-date AND fused
                        block paths) — stands in for XLA's
                        RESOURCE_EXHAUSTED; the flight recorder must
                        attach the devprof buffer census + kernel
                        table to the crash dump (``device_forensics``)
================== ====================================================

Scripting from tests::

    faults.script("prefetch.read_date", "2")        # 2nd call only
    faults.script("scheduler.run_one", "3", POISON)  # poison the 3rd
    faults.script("io.read_band", "2-4")             # calls 2..4
    faults.script("checkpoint.save", "5+")           # every call from 5
    ...
    faults.reset()

Scripting a CLI chaos run — the ``KAFKA_TPU_FAULTS`` env spec is
semicolon-separated ``<site>@<calls>[:<class>]`` items with the same
calls grammar (``N``, ``N-M``, ``N+``, ``*``) and class defaulting to
``transient``::

    KAFKA_TPU_FAULTS='prefetch.read_date@2;scheduler.run_one@3:poison' \
        python -m kafka_tpu.cli.run_synthetic --chunk-size 24 ...

Every fired fault lands in telemetry
(``kafka_resilience_faults_injected_total`` + a ``fault_injected``
event), so the forensic record of a chaos run names exactly what was
injected where.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Dict, List, Optional

from ..telemetry import get_registry
from .policy import FATAL, POISON, TRANSIENT

LOG = logging.getLogger(__name__)

ENV_VAR = "KAFKA_TPU_FAULTS"

_CLASSES = (TRANSIENT, POISON, FATAL)


class InjectedFault(RuntimeError):
    """A scripted failure.  Carries its failure class explicitly, so
    ``classify_failure`` routes it without heuristics."""

    def __init__(self, site: str, call_no: int, failure_class: str):
        super().__init__(
            f"injected {failure_class} fault at {site} (call #{call_no})"
        )
        self.site = site
        self.call_no = call_no
        self.kafka_failure_class = failure_class


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scripted failure window: calls ``first``..``last`` (1-based,
    inclusive; ``last=None`` = unbounded) at ``site`` raise with
    ``failure_class``."""

    site: str
    first: int
    last: Optional[int]
    failure_class: str = TRANSIENT

    def matches(self, call_no: int) -> bool:
        return self.first <= call_no and (
            self.last is None or call_no <= self.last
        )


_lock = threading.Lock()
_specs: Dict[str, List[FaultSpec]] = {}
_counts: Dict[str, int] = {}
_armed = False


def _parse_calls(text: str):
    text = text.strip()
    if text == "*":
        return 1, None
    if text.endswith("+"):
        return int(text[:-1]), None
    if "-" in text:
        lo, hi = text.split("-", 1)
        return int(lo), int(hi)
    n = int(text)
    return n, n


def parse_spec(text: str) -> List[FaultSpec]:
    """``KAFKA_TPU_FAULTS`` grammar -> specs (see module docstring)."""
    specs: List[FaultSpec] = []
    for item in text.split(";"):
        item = item.strip()
        if not item:
            continue
        if "@" not in item:
            raise ValueError(
                f"fault spec item {item!r}: expected "
                "'<site>@<calls>[:<class>]'"
            )
        site, rest = item.split("@", 1)
        calls, _, cls = rest.partition(":")
        cls = cls.strip() or TRANSIENT
        if cls not in _CLASSES:
            raise ValueError(
                f"fault spec item {item!r}: class {cls!r} not one of "
                f"{_CLASSES}"
            )
        first, last = _parse_calls(calls)
        specs.append(FaultSpec(
            site=site.strip(), first=first, last=last, failure_class=cls,
        ))
    return specs


def script(site: str, calls, failure_class: str = TRANSIENT) -> FaultSpec:
    """Arm one scripted failure.  ``calls`` uses the spec grammar
    (``"2"``, ``"2-4"``, ``"3+"``, ``"*"``) or is a plain int."""
    if failure_class not in _CLASSES:
        raise ValueError(f"failure_class {failure_class!r} not one of "
                         f"{_CLASSES}")
    first, last = _parse_calls(str(calls))
    spec = FaultSpec(site=site, first=first, last=last,
                     failure_class=failure_class)
    install([spec])
    return spec


def install(specs) -> None:
    """Arm a batch of :class:`FaultSpec` (additive)."""
    global _armed
    with _lock:
        for s in specs:
            _specs.setdefault(s.site, []).append(s)
        _armed = bool(_specs)


def install_from_env(environ=None) -> int:
    """Arm the ``KAFKA_TPU_FAULTS`` env spec (CLI chaos runs); returns
    how many spec items were installed (0 when the variable is unset)."""
    text = (environ if environ is not None else os.environ).get(ENV_VAR)
    if not text:
        return 0
    specs = parse_spec(text)
    install(specs)
    LOG.warning(
        "fault injection ARMED from %s: %d spec(s) — %s",
        ENV_VAR, len(specs), text,
    )
    return len(specs)


def reset() -> None:
    """Disarm everything and zero the per-site call counters."""
    global _armed
    with _lock:
        _specs.clear()
        _counts.clear()
        _armed = False


def active() -> bool:
    return _armed


def specs_for(site: str) -> List[FaultSpec]:
    """The armed specs for one site, without counting a call — for
    sites whose "calls" grammar addresses something other than call
    numbers (``solver.pixel`` reads its specs as pixel index ranges)."""
    with _lock:
        return list(_specs.get(site, ()))


def call_count(site: str) -> int:
    """How many times ``site``'s fault point has been passed (only
    counted while armed — an idle registry costs nothing)."""
    with _lock:
        return _counts.get(site, 0)


def fault_point(site: str, **context) -> None:
    """Declare a fragile operation.  No-op unless faults are armed; when
    a spec matches this site's current call number, raises the scripted
    :class:`InjectedFault` (and records it in telemetry first)."""
    if not _armed:
        return
    with _lock:
        n = _counts.get(site, 0) + 1
        _counts[site] = n
        spec = next(
            (s for s in _specs.get(site, ()) if s.matches(n)), None
        )
    if spec is None:
        return
    record_injection(
        site, call=n, failure_class=spec.failure_class,
        **{k: str(v) for k, v in context.items()},
    )
    raise InjectedFault(site, n, spec.failure_class)


def record_injection(site: str, **fields) -> None:
    """Land one fired fault in telemetry — the single registration site
    for the injected-faults counter.  Raising sites go through
    :func:`fault_point`; non-raising sites (``solver.pixel`` corrupts
    arrays instead of raising) call this directly."""
    reg = get_registry()
    reg.counter(
        "kafka_resilience_faults_injected_total",
        "scripted failures raised by the fault-injection harness, "
        "labelled by site",
    ).inc(site=site)
    reg.emit("fault_injected", site=site, **fields)
