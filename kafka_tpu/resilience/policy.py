"""Retry/backoff policies, failure classification and deadlines.

The reference delegated every fault-tolerance decision to dask.distributed
(``kafka_test_Py36.py:242-255``); the TPU-native replacement kept only the
``.done``-marker restart story, so until this layer existed a single
transient GeoTIFF read error killed an entire tile run.  This module is
the one place failure POLICY lives — the fragile layers (prefetch,
scheduler, checkpoint) stay mechanism-only and ask these helpers what to
do:

- :func:`classify_failure` sorts an exception into one of three classes:
  ``transient`` (worth retrying: network/file-system weather — OSError,
  TimeoutError, ConnectionError), ``poison`` (deterministic: the same
  input will fail the same way — ValueError, shape errors, any unknown
  exception) and ``fatal`` (the process itself is compromised —
  MemoryError, KeyboardInterrupt, SystemExit).  An exception can override
  the heuristic by carrying a ``kafka_failure_class`` attribute (the
  fault-injection harness uses exactly this hook).
- :class:`RetryPolicy` retries transient failures with exponential
  backoff.  ``jitter=0`` gives the jitter-free deterministic schedule the
  chaos tests pin; the ``sleep`` callable is injectable so tests never
  wait wall-clock time.  Every retry lands in the telemetry registry
  (``kafka_resilience_retries_total`` + ``retry``/``retry_exhausted``
  events) so a chaos run is fully forensic.
- :class:`Deadline` is a monotonic wall-clock budget for one call; the
  scheduler uses it to turn an over-deadline chunk into a quarantined
  chunk instead of a wedged run.

``time.sleep`` anywhere else in the production tree is a kafkalint
violation (rule ``ad-hoc-retry``): hand-rolled backoff loops must come
through here.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, Optional

from ..telemetry import get_registry

LOG = logging.getLogger(__name__)

#: failure classes (the vocabulary every resilience decision speaks).
TRANSIENT = "transient"
POISON = "poison"
FATAL = "fatal"

#: exit code for "the run completed but quarantined some work" — the
#: sysexits EX_TEMPFAIL convention, distinct from 0 (full success) and
#: 1 (hard failure) so schedulers/CI can trigger a targeted rerun.
EXIT_PARTIAL_SUCCESS = 75

_FATAL_TYPES = (MemoryError, KeyboardInterrupt, SystemExit, GeneratorExit)
#: OSError covers IOError, FileNotFoundError, ConnectionError,
#: InterruptedError, TimeoutError (3.10+) — the I/O weather class.
_TRANSIENT_TYPES = (OSError, TimeoutError, ConnectionError)


def classify_failure(exc: BaseException) -> str:
    """``transient`` / ``poison`` / ``fatal`` for one exception.

    An explicit ``kafka_failure_class`` attribute on the exception wins
    (injected faults and :class:`DeadlineExceeded` use it); otherwise
    I/O-flavoured errors are transient, process-compromising errors are
    fatal, and everything unknown is poison — retrying a deterministic
    failure only burns wall-clock and hides the bug.
    """
    explicit = getattr(exc, "kafka_failure_class", None)
    if explicit in (TRANSIENT, POISON, FATAL):
        return explicit
    if isinstance(exc, _FATAL_TYPES):
        return FATAL
    if isinstance(exc, _TRANSIENT_TYPES):
        return TRANSIENT
    return POISON


class DegradedDateError(RuntimeError):
    """An observation date whose read exhausted its transient-failure
    retries.  Raised by ``ObservationPrefetcher.get`` INSTEAD of the
    underlying error so the engine can consume the date as a missing
    observation (predict-only window) — the Kalman structure makes a
    dateless window a plain propagation step (PAPER.md §propagation)."""

    def __init__(self, date, cause: BaseException):
        super().__init__(
            f"observation read for {date} degraded after retries: "
            f"{cause!r}"
        )
        self.date = date
        self.cause = cause


class DeadlineExceeded(RuntimeError):
    """A per-call wall-clock budget ran out.  Classified poison, not
    transient: in-process the hung call cannot be killed, so retrying it
    would wedge the run again — the scheduler quarantines instead."""

    kafka_failure_class = POISON


class Deadline:
    """Monotonic wall-clock budget for one call."""

    def __init__(self, seconds: float):
        self.seconds = float(seconds)
        self._t0 = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def remaining(self) -> float:
        return self.seconds - self.elapsed()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "call") -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"{what} exceeded its {self.seconds:.1f}s deadline "
                f"(elapsed {self.elapsed():.1f}s)"
            )


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential-backoff retry for TRANSIENT failures.

    ``max_attempts`` counts the first try: ``max_attempts=3`` means one
    call plus up to two retries.  Delays follow ``base_delay *
    multiplier**k`` capped at ``max_delay``; ``jitter`` spreads each
    delay by a uniform ±fraction (0 = the deterministic schedule tests
    pin).  ``sleep`` is injectable so tests never wait wall-clock time.

    Poison/fatal failures are NEVER retried — they re-raise on the first
    attempt; a transient failure on the last attempt re-raises the
    ORIGINAL exception (callers classify it again to decide degradation
    vs abort).
    """

    max_attempts: int = 3
    base_delay: float = 0.5
    multiplier: float = 2.0
    max_delay: float = 30.0
    jitter: float = 0.0
    sleep: Callable[[float], None] = time.sleep

    def delay(self, failures: int) -> float:
        """Backoff before the retry following the Nth failure (1-based)."""
        d = min(self.base_delay * self.multiplier ** (failures - 1),
                self.max_delay)
        if self.jitter:
            d *= 1.0 + random.uniform(-self.jitter, self.jitter)
        return max(0.0, d)

    def schedule(self) -> list:
        """The full deterministic delay schedule (jitter applied per
        draw, so only meaningful with ``jitter=0`` — the test hook)."""
        return [self.delay(k) for k in range(1, self.max_attempts)]

    def call(self, fn: Callable, *args,
             site: str = "call",
             classify: Callable[[BaseException], str] = classify_failure,
             **kwargs):
        """Run ``fn`` under this policy.  ``site`` labels the telemetry
        (retry counter + events) so chaos forensics attribute every
        retry to its injection/failure point."""
        reg = get_registry()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except BaseException as exc:
                cls = classify(exc)
                if cls != TRANSIENT:
                    raise
                if attempt >= self.max_attempts:
                    reg.emit(
                        "retry_exhausted", site=site, attempts=attempt,
                        error=repr(exc)[:300],
                    )
                    LOG.warning(
                        "%s: transient failure persisted through %d "
                        "attempt(s): %r", site, attempt, exc,
                    )
                    raise
                d = self.delay(attempt)
                reg.counter(
                    "kafka_resilience_retries_total",
                    "transient failures retried under a RetryPolicy, "
                    "labelled by call site",
                ).inc(site=site)
                reg.emit(
                    "retry", site=site, attempt=attempt,
                    delay_s=round(d, 3), error=repr(exc)[:300],
                )
                LOG.warning(
                    "%s: transient failure on attempt %d/%d, retrying "
                    "in %.2fs: %r", site, attempt, self.max_attempts,
                    d, exc,
                )
                if d > 0:
                    self.sleep(d)


#: production default for host-side observation reads: three attempts,
#: 0.5s/2s backoff with ±10% jitter — generous enough for object-store
#: weather, bounded enough that a dead endpoint degrades in seconds.
DEFAULT_READ_POLICY = RetryPolicy(
    max_attempts=3, base_delay=0.5, multiplier=4.0, max_delay=8.0,
    jitter=0.1,
)
