"""Fault tolerance: retry/backoff policy, failure classification,
deadlines, and deterministic fault injection (BASELINE.md "Fault
tolerance").

Mechanism lives in the fragile layers (``engine.prefetch``,
``shard.scheduler``, ``engine.checkpoint``); POLICY lives here, so every
retry loop in the tree shares one backoff/classification vocabulary and
one telemetry surface — enforced statically by the kafkalint
``ad-hoc-retry`` rule.
"""

from . import faults  # noqa: F401
from .policy import (  # noqa: F401
    DEFAULT_READ_POLICY,
    EXIT_PARTIAL_SUCCESS,
    FATAL,
    POISON,
    TRANSIENT,
    Deadline,
    DeadlineExceeded,
    DegradedDateError,
    RetryPolicy,
    classify_failure,
)
