"""Multi-host tile scheduler — the dask-equivalent.

The reference farms independent 256x256 spatial chunks over a
dask.distributed cluster (``/root/reference/kafka_test_Py36.py:242-255``)
with fault tolerance delegated to dask and results written as per-chunk
prefixed GeoTIFFs (``:164-166``) so reruns are cheap.  The TPU-native
replacement:

- **within a host/slice**: chunks are just more pixels — the pixel mesh
  absorbs them (no scheduler needed);
- **across hosts**: a deterministic round-robin assignment of chunks by
  ``jax.process_index()`` (every process computes the same assignment, no
  coordinator, no message passing — the "zero collectives" structure of the
  problem extends to scheduling);
- **restartability**: a per-chunk ``.done`` marker next to the outputs.
  ``pending_chunks`` skips completed work, so a restarted job (or a
  replacement host) re-runs only what's missing — strictly better than the
  reference, which reruns every chunk the dead worker owned.  A chunk that
  dies mid-run leaves NO marker, so a replacement process re-runs exactly
  the missing chunks (tested in tests/test_shard.py).

``run_chunks`` records completion counters, per-chunk wall-time histograms
and straggler flags into the telemetry registry — the scheduler-level
slice of the observability layer (BASELINE.md "Observability").
"""

from __future__ import annotations

import json
import os
import statistics
import time
from dataclasses import dataclass
from typing import Callable, Iterable, List, Optional, Sequence

import jax

from ..io.tiling import Chunk
from ..telemetry import get_registry, tracing

#: a completed chunk is flagged a straggler when its wall time exceeds
#: this multiple of the median of the chunks completed before it (with at
#: least ``_STRAGGLER_MIN_SAMPLES`` priors) — the dask-dashboard signal
#: the reference lost when it dropped dask, now a counter + event.
STRAGGLER_FACTOR = 3.0
_STRAGGLER_MIN_SAMPLES = 3


@dataclass(frozen=True)
class ChunkAssignment:
    chunk: Chunk
    owner: int           # process index that runs it
    prefix: str          # output filename prefix (chunk-id trick,
    #                      kafka_test_Py36.py:164-166)


def assign_chunks(chunks: Sequence[Chunk],
                  num_processes: Optional[int] = None,
                  ) -> List[ChunkAssignment]:
    """Deterministic round-robin over hosts; identical on every process."""
    n = num_processes if num_processes is not None else jax.process_count()
    return [
        ChunkAssignment(chunk=c, owner=i % n, prefix=f"{c.chunk_no:04x}")
        for i, c in enumerate(chunks)
    ]


def marker_path(outdir: str, prefix: str) -> str:
    return os.path.join(outdir, f".chunk_{prefix}.done")


def mark_done(outdir: str, prefix: str, payload: Optional[dict] = None) -> None:
    with open(marker_path(outdir, prefix), "w") as f:
        json.dump({"finished": time.time(), **(payload or {})}, f)


def pending_chunks(assignments: Iterable[ChunkAssignment], outdir: str,
                   process_index: Optional[int] = None,
                   ) -> List[ChunkAssignment]:
    """This process's still-to-run chunks (restart-safe)."""
    me = process_index if process_index is not None else jax.process_index()
    return [
        a for a in assignments
        if a.owner == me and not os.path.exists(marker_path(outdir, a.prefix))
    ]


def run_chunks(
    chunks: Sequence[Chunk],
    run_one: Callable[[Chunk, str], None],
    outdir: str,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
) -> dict:
    """Execute ``run_one(chunk, prefix)`` for every pending chunk owned by
    this process.  The serial-loop / ``client.map`` duality of the reference
    (``kafka_test_S2.py:203-205`` vs ``kafka_test_Py36.py:254``) collapses
    into this one function: single-process runs own every chunk."""
    os.makedirs(outdir, exist_ok=True)
    assignments = assign_chunks(chunks, num_processes)
    todo = pending_chunks(assignments, outdir, process_index)
    stats = {"assigned": len([a for a in assignments if a.owner ==
                              (process_index if process_index is not None
                               else jax.process_index())]),
             "run": 0, "skipped": 0, "wall_s": 0.0}
    stats["skipped"] = stats["assigned"] - len(todo)
    reg = get_registry()
    m_done = reg.counter(
        "kafka_shard_chunks_completed_total",
        "chunks run to completion (.done marker written)",
    )
    m_wall = reg.histogram(
        "kafka_shard_chunk_seconds",
        "wall seconds per completed chunk",
    )
    m_pending = reg.gauge(
        "kafka_shard_chunks_pending",
        "this process's chunks still to run",
    )
    m_straggle = reg.counter(
        "kafka_shard_stragglers_total",
        "completed chunks slower than STRAGGLER_FACTOR x the median of "
        "prior completions",
    )
    m_pending.set(len(todo))
    walls: List[float] = []
    t0 = time.time()
    for a in todo:
        t_chunk = time.perf_counter()
        # chunk_id scopes every span/event recorded inside the chunk run
        # (engine phases, writes, reads) to this chunk's forensics.
        with tracing.push(chunk_id=a.prefix):
            run_one(a.chunk, a.prefix)
        t_end = time.perf_counter()
        wall = t_end - t_chunk
        # The chunk-level block lands on its own "scheduler" track, so
        # the timeline shows chunk boundaries above the engine phases.
        reg.trace.add_span(
            "chunk", t_chunk, t_end, lane="scheduler", cat="chunk",
            prefix=a.prefix, chunk=a.chunk.chunk_no,
        )
        mark_done(outdir, a.prefix, {"chunk": a.chunk.chunk_no,
                                     "wall_s": round(wall, 3)})
        stats["run"] += 1
        m_done.inc()
        m_wall.observe(wall)
        m_pending.set(len(todo) - stats["run"])
        if len(walls) >= _STRAGGLER_MIN_SAMPLES:
            median = statistics.median(walls)
            if wall > STRAGGLER_FACTOR * median:
                m_straggle.inc()
                reg.emit(
                    "straggler", prefix=a.prefix,
                    chunk=a.chunk.chunk_no, wall_s=round(wall, 3),
                    median_s=round(median, 3),
                )
        walls.append(wall)
        reg.emit(
            "chunk_done", prefix=a.prefix, chunk=a.chunk.chunk_no,
            wall_s=round(wall, 3),
        )
    stats["wall_s"] = time.time() - t0
    return stats
