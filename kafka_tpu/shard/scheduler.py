"""Multi-host tile scheduler — the dask-equivalent.

The reference farms independent 256x256 spatial chunks over a
dask.distributed cluster (``/root/reference/kafka_test_Py36.py:242-255``)
with fault tolerance delegated to dask and results written as per-chunk
prefixed GeoTIFFs (``:164-166``) so reruns are cheap.  The TPU-native
replacement:

- **within a host/slice**: chunks are just more pixels — the pixel mesh
  absorbs them (no scheduler needed);
- **across hosts**: a deterministic round-robin assignment of chunks by
  ``jax.process_index()`` (every process computes the same assignment, no
  coordinator, no message passing — the "zero collectives" structure of the
  problem extends to scheduling);
- **restartability**: a per-chunk ``.done`` marker next to the outputs
  (written atomically — tmp + ``os.replace`` — so a crash mid-write can
  never leave an empty marker that suppresses a rerun).
  ``pending_chunks`` skips completed work, so a restarted job (or a
  replacement host) re-runs only what's missing — strictly better than the
  reference, which reruns every chunk the dead worker owned.  A chunk that
  dies mid-run leaves NO marker, so a replacement process re-runs exactly
  the missing chunks (tested in tests/test_shard.py);
- **fault tolerance** (BASELINE.md "Fault tolerance"): ``run_chunks``
  optionally retries each chunk under a ``RetryPolicy`` (transient-class
  failures only), enforces a per-chunk wall-clock deadline, and — with
  ``quarantine=True`` — converts an exhausted/poison chunk into a
  ``.chunk_<prefix>.failed`` marker carrying the failure payload so the
  run CONTINUES and ``pending_chunks`` skips it on restart.  The nonzero
  ``failed`` count in the returned stats becomes the drivers'
  partial-success exit code.  The default (no policy, no quarantine)
  keeps the historical fail-fast behaviour;
- **self-healing across hosts** (``run_queue`` / ``shard.queue``): the
  static round-robin strands a dead host's chunks until a human
  restarts the job, so the queue mode replaces assignment with
  lease-based CLAIMING — atomic ``.chunk_<prefix>.lease`` markers with
  heartbeat deadlines, renewed from a background thread; any worker
  that finds an expired lease reclaims the chunk.  At-least-once
  execution made safe by the per-chunk-prefixed atomic outputs
  (a second completion overwrites with identical bytes; ``.done`` wins
  over any stale lease).  See BASELINE.md "Multi-host queue".

``run_chunks`` records completion counters, per-chunk wall-time histograms
and straggler flags into the telemetry registry — the scheduler-level
slice of the observability layer (BASELINE.md "Observability").
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import re
import statistics
import time
from dataclasses import dataclass
from typing import Callable, Iterable, List, Optional, Sequence

import jax

from ..io.tiling import Chunk
from ..resilience import (
    FATAL,
    Deadline,
    RetryPolicy,
    classify_failure,
    faults,
)
from ..telemetry import get_registry, stopwatch, tracing

LOG = logging.getLogger(__name__)

#: a completed chunk is flagged a straggler when its wall time exceeds
#: this multiple of the median of the chunks completed before it (with at
#: least ``_STRAGGLER_MIN_SAMPLES`` priors) — the dask-dashboard signal
#: the reference lost when it dropped dask, now a counter + event.
STRAGGLER_FACTOR = 3.0
_STRAGGLER_MIN_SAMPLES = 3


@dataclass(frozen=True)
class ChunkAssignment:
    chunk: Chunk
    owner: int           # process index that runs it
    prefix: str          # output filename prefix (chunk-id trick,
    #                      kafka_test_Py36.py:164-166)


def assign_chunks(chunks: Sequence[Chunk],
                  num_processes: Optional[int] = None,
                  ) -> List[ChunkAssignment]:
    """Deterministic round-robin over hosts; identical on every process."""
    n = num_processes if num_processes is not None else jax.process_count()
    return [
        ChunkAssignment(chunk=c, owner=i % n, prefix=f"{c.chunk_no:04x}")
        for i, c in enumerate(chunks)
    ]


def marker_path(outdir: str, prefix: str) -> str:
    return os.path.join(outdir, f".chunk_{prefix}.done")


def failed_marker_path(outdir: str, prefix: str) -> str:
    """Quarantine marker: this chunk exhausted its retries (or was
    poison) and the run continued without it.  Delete the marker to make
    a restart re-attempt the chunk."""
    return os.path.join(outdir, f".chunk_{prefix}.failed")


#: per-process tmp-name counter: together with the pid it makes every
#: writer's tmp unique, so two hosts racing on the SAME marker (lease
#: contention) can never interleave open/os.replace on one tmp file and
#: commit a torn payload.
_TMP_COUNTER = itertools.count()

#: tmp files left by a crash between open and os.replace — both the
#: legacy fixed ``.tmp`` suffix and the unique ``.tmp.<pid>.<n>`` form.
_TMP_RX = re.compile(r"\.tmp(\.\d+\.\d+)?$")


def _tmp_name(path: str) -> str:
    """A tmp name unique to this writer (pid + counter)."""
    return f"{path}.tmp.{os.getpid()}.{next(_TMP_COUNTER)}"


def _write_marker(path: str, payload: dict) -> None:
    """Atomic marker write: a crash mid-write must never leave an empty
    marker that suppresses a rerun (unique tmp + ``os.replace``, same
    pattern as ``engine.checkpoint``)."""
    tmp = _tmp_name(path)
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def sweep_stale_tmp(outdir: str, older_than_s: float = 60.0) -> List[str]:
    """Remove orphaned ``*.tmp`` marker/checkpoint files (recursive).

    A crash between ``open`` and ``os.replace`` leaks the tmp forever;
    this sweep runs on scheduler startup (``run_chunks`` / ``run_queue``)
    and clears them.  ``older_than_s`` protects writers that are mid-write
    RIGHT NOW on another host — a live atomic write completes in
    milliseconds, so anything older than a minute is a corpse."""
    removed: List[str] = []
    if not os.path.isdir(outdir):
        return removed
    now = time.time()
    reg = get_registry()
    for dirpath, _dirnames, filenames in os.walk(outdir):
        for fn in filenames:
            if not _TMP_RX.search(fn):
                continue
            path = os.path.join(dirpath, fn)
            try:
                if now - os.path.getmtime(path) < older_than_s:
                    continue
                os.unlink(path)
            except OSError:  # raced another sweeper, or vanished
                continue
            removed.append(path)
            reg.counter(
                "kafka_scheduler_stale_tmp_removed_total",
                "orphaned .tmp marker/checkpoint files removed by the "
                "startup sweep (crash between open and os.replace)",
            ).inc()
            reg.emit(
                "stale_tmp_removed",
                path=os.path.relpath(path, outdir),
            )
    return removed


def mark_done(outdir: str, prefix: str, payload: Optional[dict] = None) -> None:
    _write_marker(marker_path(outdir, prefix),
                  {"finished": time.time(), **(payload or {})})


def mark_failed(outdir: str, prefix: str,
                payload: Optional[dict] = None) -> None:
    _write_marker(failed_marker_path(outdir, prefix),
                  {"failed": time.time(), **(payload or {})})


def pending_chunks(assignments: Iterable[ChunkAssignment], outdir: str,
                   process_index: Optional[int] = None,
                   ) -> List[ChunkAssignment]:
    """This process's still-to-run chunks (restart-safe; quarantined
    chunks — ``.failed`` marker — are skipped too, so a restarted run
    doesn't immediately re-wedge on a known-bad chunk)."""
    me = process_index if process_index is not None else jax.process_index()
    return [
        a for a in assignments
        if a.owner == me
        and not os.path.exists(marker_path(outdir, a.prefix))
        and not os.path.exists(failed_marker_path(outdir, a.prefix))
    ]


def chunk_metrics(reg) -> dict:
    """The chunk-level metric vocabulary, registered at its ONE literal
    site (the metric-name lint requires exactly one registration site per
    name; ``run_chunks`` and ``queue.run_queue`` share these handles)."""
    return {
        "done": reg.counter(
            "kafka_shard_chunks_completed_total",
            "chunks run to completion (.done marker written)",
        ),
        "wall": reg.histogram(
            "kafka_shard_chunk_seconds",
            "wall seconds per completed chunk",
        ),
        "pending": reg.gauge(
            "kafka_shard_chunks_pending",
            "this process's chunks still to run",
        ),
        "stragglers": reg.counter(
            "kafka_shard_stragglers_total",
            "completed chunks slower than STRAGGLER_FACTOR x the median "
            "of prior completions",
        ),
        "failed": reg.counter(
            "kafka_shard_chunks_failed_total",
            "chunks quarantined after exhausting retries (.failed marker "
            "written, run continued)",
        ),
    }


def run_chunks(
    chunks: Sequence[Chunk],
    run_one: Callable[[Chunk, str], None],
    outdir: str,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    retry_policy: Optional[RetryPolicy] = None,
    quarantine: bool = False,
    chunk_deadline_s: Optional[float] = None,
) -> dict:
    """Execute ``run_one(chunk, prefix)`` for every pending chunk owned by
    this process.  The serial-loop / ``client.map`` duality of the reference
    (``kafka_test_S2.py:203-205`` vs ``kafka_test_Py36.py:254``) collapses
    into this one function: single-process runs own every chunk.

    Fault tolerance is opt-in and layered: ``retry_policy`` re-runs a
    chunk whose failure classifies TRANSIENT (backoff between attempts);
    ``chunk_deadline_s`` turns an over-budget attempt into a
    ``DeadlineExceeded`` (poison — a hung in-process ``run_one`` cannot
    be killed, so it is never retried; the subprocess chunk-worker path
    kills on its own timeout and surfaces here as a transient
    ``TimeoutError``); ``quarantine=True`` converts any non-FATAL failure
    that survives retries into a ``.chunk_<prefix>.failed`` marker +
    ``failed`` count instead of aborting the run.  Defaults preserve the
    historical fail-fast semantics exactly."""
    os.makedirs(outdir, exist_ok=True)
    sweep_stale_tmp(outdir)
    assignments = assign_chunks(chunks, num_processes)
    todo = pending_chunks(assignments, outdir, process_index)
    stats = {"assigned": len([a for a in assignments if a.owner ==
                              (process_index if process_index is not None
                               else jax.process_index())]),
             "run": 0, "skipped": 0, "failed": 0, "wall_s": 0.0}
    stats["skipped"] = stats["assigned"] - len(todo)
    reg = get_registry()
    metrics = chunk_metrics(reg)
    m_done, m_wall = metrics["done"], metrics["wall"]
    m_pending, m_failed = metrics["pending"], metrics["failed"]
    m_straggle = metrics["stragglers"]
    m_pending.set(len(todo))
    walls: List[float] = []
    t0 = time.time()
    for a in todo:
        sw_chunk = stopwatch()

        def attempt(a=a):
            deadline = Deadline(chunk_deadline_s) \
                if chunk_deadline_s else None
            faults.fault_point("scheduler.run_one", prefix=a.prefix)
            # chunk_id scopes every span/event recorded inside the chunk
            # run (engine phases, writes, reads) to this chunk's
            # forensics.
            with tracing.push(chunk_id=a.prefix):
                run_one(a.chunk, a.prefix)
            if deadline is not None:
                # In-process there is no way to kill a hung run_one; the
                # deadline is checked on completion and classifies
                # poison, so the chunk quarantines instead of retrying
                # into the same hang.
                deadline.check(f"chunk {a.prefix}")

        try:
            if retry_policy is not None:
                retry_policy.call(attempt, site="scheduler.run_one")
            else:
                attempt()
        except BaseException as exc:
            cls = classify_failure(exc)
            if cls == FATAL or not quarantine:
                raise
            stats["failed"] += 1
            mark_failed(outdir, a.prefix, {
                "chunk": a.chunk.chunk_no,
                "failure_class": cls,
                "error": repr(exc)[:500],
            })
            m_failed.inc()
            m_pending.set(len(todo) - stats["run"] - stats["failed"])
            reg.emit(
                "chunk_quarantined", prefix=a.prefix,
                chunk=a.chunk.chunk_no, failure_class=cls,
                error=repr(exc)[:300],
            )
            LOG.error(
                "chunk %s quarantined (%s): %r — run continues; delete "
                "%s to re-attempt it",
                a.prefix, cls, exc, failed_marker_path(outdir, a.prefix),
            )
            continue
        t_end = sw_chunk.now()
        wall = t_end - sw_chunk.t0
        # The chunk-level block lands on its own "scheduler" track, so
        # the timeline shows chunk boundaries above the engine phases.
        reg.trace.add_span(
            "chunk", sw_chunk.t0, t_end, lane="scheduler", cat="chunk",
            prefix=a.prefix, chunk=a.chunk.chunk_no,
        )
        mark_done(outdir, a.prefix, {"chunk": a.chunk.chunk_no,
                                     "wall_s": round(wall, 3)})
        stats["run"] += 1
        m_done.inc()
        m_wall.observe(wall)
        m_pending.set(len(todo) - stats["run"] - stats["failed"])
        if len(walls) >= _STRAGGLER_MIN_SAMPLES:
            median = statistics.median(walls)
            if wall > STRAGGLER_FACTOR * median:
                m_straggle.inc()
                reg.emit(
                    "straggler", prefix=a.prefix,
                    chunk=a.chunk.chunk_no, wall_s=round(wall, 3),
                    median_s=round(median, 3),
                )
        walls.append(wall)
        reg.emit(
            "chunk_done", prefix=a.prefix, chunk=a.chunk.chunk_no,
            wall_s=round(wall, 3),
        )
    stats["wall_s"] = time.time() - t0
    return stats


def run_queue(chunks: Sequence[Chunk], run_one: Callable[[Chunk, str], None],
              outdir: str, **kwargs) -> dict:
    """Self-healing multi-host execution: lease-based claiming over a
    shared filesystem queue instead of static assignment.  Thin
    delegation to :func:`kafka_tpu.shard.queue.run_queue` (lazy import —
    the queue module builds on this one)."""
    from .queue import run_queue as _run_queue

    return _run_queue(chunks, run_one, outdir, **kwargs)
