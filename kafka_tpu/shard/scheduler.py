"""Multi-host tile scheduler — the dask-equivalent.

The reference farms independent 256x256 spatial chunks over a
dask.distributed cluster (``/root/reference/kafka_test_Py36.py:242-255``)
with fault tolerance delegated to dask and results written as per-chunk
prefixed GeoTIFFs (``:164-166``) so reruns are cheap.  The TPU-native
replacement:

- **within a host/slice**: chunks are just more pixels — the pixel mesh
  absorbs them (no scheduler needed);
- **across hosts**: a deterministic round-robin assignment of chunks by
  ``jax.process_index()`` (every process computes the same assignment, no
  coordinator, no message passing — the "zero collectives" structure of the
  problem extends to scheduling);
- **restartability**: a per-chunk ``.done`` marker next to the outputs
  (written atomically — tmp + ``os.replace`` — so a crash mid-write can
  never leave an empty marker that suppresses a rerun).
  ``pending_chunks`` skips completed work, so a restarted job (or a
  replacement host) re-runs only what's missing — strictly better than the
  reference, which reruns every chunk the dead worker owned.  A chunk that
  dies mid-run leaves NO marker, so a replacement process re-runs exactly
  the missing chunks (tested in tests/test_shard.py);
- **fault tolerance** (BASELINE.md "Fault tolerance"): ``run_chunks``
  optionally retries each chunk under a ``RetryPolicy`` (transient-class
  failures only), enforces a per-chunk wall-clock deadline, and — with
  ``quarantine=True`` — converts an exhausted/poison chunk into a
  ``.chunk_<prefix>.failed`` marker carrying the failure payload so the
  run CONTINUES and ``pending_chunks`` skips it on restart.  The nonzero
  ``failed`` count in the returned stats becomes the drivers'
  partial-success exit code.  The default (no policy, no quarantine)
  keeps the historical fail-fast behaviour.

``run_chunks`` records completion counters, per-chunk wall-time histograms
and straggler flags into the telemetry registry — the scheduler-level
slice of the observability layer (BASELINE.md "Observability").
"""

from __future__ import annotations

import json
import logging
import os
import statistics
import time
from dataclasses import dataclass
from typing import Callable, Iterable, List, Optional, Sequence

import jax

from ..io.tiling import Chunk
from ..resilience import (
    FATAL,
    Deadline,
    RetryPolicy,
    classify_failure,
    faults,
)
from ..telemetry import get_registry, tracing

LOG = logging.getLogger(__name__)

#: a completed chunk is flagged a straggler when its wall time exceeds
#: this multiple of the median of the chunks completed before it (with at
#: least ``_STRAGGLER_MIN_SAMPLES`` priors) — the dask-dashboard signal
#: the reference lost when it dropped dask, now a counter + event.
STRAGGLER_FACTOR = 3.0
_STRAGGLER_MIN_SAMPLES = 3


@dataclass(frozen=True)
class ChunkAssignment:
    chunk: Chunk
    owner: int           # process index that runs it
    prefix: str          # output filename prefix (chunk-id trick,
    #                      kafka_test_Py36.py:164-166)


def assign_chunks(chunks: Sequence[Chunk],
                  num_processes: Optional[int] = None,
                  ) -> List[ChunkAssignment]:
    """Deterministic round-robin over hosts; identical on every process."""
    n = num_processes if num_processes is not None else jax.process_count()
    return [
        ChunkAssignment(chunk=c, owner=i % n, prefix=f"{c.chunk_no:04x}")
        for i, c in enumerate(chunks)
    ]


def marker_path(outdir: str, prefix: str) -> str:
    return os.path.join(outdir, f".chunk_{prefix}.done")


def failed_marker_path(outdir: str, prefix: str) -> str:
    """Quarantine marker: this chunk exhausted its retries (or was
    poison) and the run continued without it.  Delete the marker to make
    a restart re-attempt the chunk."""
    return os.path.join(outdir, f".chunk_{prefix}.failed")


def _write_marker(path: str, payload: dict) -> None:
    """Atomic marker write: a crash mid-write must never leave an empty
    marker that suppresses a rerun (tmp + ``os.replace``, same pattern
    as ``engine.checkpoint``)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def mark_done(outdir: str, prefix: str, payload: Optional[dict] = None) -> None:
    _write_marker(marker_path(outdir, prefix),
                  {"finished": time.time(), **(payload or {})})


def mark_failed(outdir: str, prefix: str,
                payload: Optional[dict] = None) -> None:
    _write_marker(failed_marker_path(outdir, prefix),
                  {"failed": time.time(), **(payload or {})})


def pending_chunks(assignments: Iterable[ChunkAssignment], outdir: str,
                   process_index: Optional[int] = None,
                   ) -> List[ChunkAssignment]:
    """This process's still-to-run chunks (restart-safe; quarantined
    chunks — ``.failed`` marker — are skipped too, so a restarted run
    doesn't immediately re-wedge on a known-bad chunk)."""
    me = process_index if process_index is not None else jax.process_index()
    return [
        a for a in assignments
        if a.owner == me
        and not os.path.exists(marker_path(outdir, a.prefix))
        and not os.path.exists(failed_marker_path(outdir, a.prefix))
    ]


def run_chunks(
    chunks: Sequence[Chunk],
    run_one: Callable[[Chunk, str], None],
    outdir: str,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    retry_policy: Optional[RetryPolicy] = None,
    quarantine: bool = False,
    chunk_deadline_s: Optional[float] = None,
) -> dict:
    """Execute ``run_one(chunk, prefix)`` for every pending chunk owned by
    this process.  The serial-loop / ``client.map`` duality of the reference
    (``kafka_test_S2.py:203-205`` vs ``kafka_test_Py36.py:254``) collapses
    into this one function: single-process runs own every chunk.

    Fault tolerance is opt-in and layered: ``retry_policy`` re-runs a
    chunk whose failure classifies TRANSIENT (backoff between attempts);
    ``chunk_deadline_s`` turns an over-budget attempt into a
    ``DeadlineExceeded`` (poison — a hung in-process ``run_one`` cannot
    be killed, so it is never retried; the subprocess chunk-worker path
    kills on its own timeout and surfaces here as a transient
    ``TimeoutError``); ``quarantine=True`` converts any non-FATAL failure
    that survives retries into a ``.chunk_<prefix>.failed`` marker +
    ``failed`` count instead of aborting the run.  Defaults preserve the
    historical fail-fast semantics exactly."""
    os.makedirs(outdir, exist_ok=True)
    assignments = assign_chunks(chunks, num_processes)
    todo = pending_chunks(assignments, outdir, process_index)
    stats = {"assigned": len([a for a in assignments if a.owner ==
                              (process_index if process_index is not None
                               else jax.process_index())]),
             "run": 0, "skipped": 0, "failed": 0, "wall_s": 0.0}
    stats["skipped"] = stats["assigned"] - len(todo)
    reg = get_registry()
    m_done = reg.counter(
        "kafka_shard_chunks_completed_total",
        "chunks run to completion (.done marker written)",
    )
    m_wall = reg.histogram(
        "kafka_shard_chunk_seconds",
        "wall seconds per completed chunk",
    )
    m_pending = reg.gauge(
        "kafka_shard_chunks_pending",
        "this process's chunks still to run",
    )
    m_straggle = reg.counter(
        "kafka_shard_stragglers_total",
        "completed chunks slower than STRAGGLER_FACTOR x the median of "
        "prior completions",
    )
    m_failed = reg.counter(
        "kafka_shard_chunks_failed_total",
        "chunks quarantined after exhausting retries (.failed marker "
        "written, run continued)",
    )
    m_pending.set(len(todo))
    walls: List[float] = []
    t0 = time.time()
    for a in todo:
        t_chunk = time.perf_counter()

        def attempt(a=a):
            deadline = Deadline(chunk_deadline_s) \
                if chunk_deadline_s else None
            faults.fault_point("scheduler.run_one", prefix=a.prefix)
            # chunk_id scopes every span/event recorded inside the chunk
            # run (engine phases, writes, reads) to this chunk's
            # forensics.
            with tracing.push(chunk_id=a.prefix):
                run_one(a.chunk, a.prefix)
            if deadline is not None:
                # In-process there is no way to kill a hung run_one; the
                # deadline is checked on completion and classifies
                # poison, so the chunk quarantines instead of retrying
                # into the same hang.
                deadline.check(f"chunk {a.prefix}")

        try:
            if retry_policy is not None:
                retry_policy.call(attempt, site="scheduler.run_one")
            else:
                attempt()
        except BaseException as exc:
            cls = classify_failure(exc)
            if cls == FATAL or not quarantine:
                raise
            stats["failed"] += 1
            mark_failed(outdir, a.prefix, {
                "chunk": a.chunk.chunk_no,
                "failure_class": cls,
                "error": repr(exc)[:500],
            })
            m_failed.inc()
            m_pending.set(len(todo) - stats["run"] - stats["failed"])
            reg.emit(
                "chunk_quarantined", prefix=a.prefix,
                chunk=a.chunk.chunk_no, failure_class=cls,
                error=repr(exc)[:300],
            )
            LOG.error(
                "chunk %s quarantined (%s): %r — run continues; delete "
                "%s to re-attempt it",
                a.prefix, cls, exc, failed_marker_path(outdir, a.prefix),
            )
            continue
        t_end = time.perf_counter()
        wall = t_end - t_chunk
        # The chunk-level block lands on its own "scheduler" track, so
        # the timeline shows chunk boundaries above the engine phases.
        reg.trace.add_span(
            "chunk", t_chunk, t_end, lane="scheduler", cat="chunk",
            prefix=a.prefix, chunk=a.chunk.chunk_no,
        )
        mark_done(outdir, a.prefix, {"chunk": a.chunk.chunk_no,
                                     "wall_s": round(wall, 3)})
        stats["run"] += 1
        m_done.inc()
        m_wall.observe(wall)
        m_pending.set(len(todo) - stats["run"] - stats["failed"])
        if len(walls) >= _STRAGGLER_MIN_SAMPLES:
            median = statistics.median(walls)
            if wall > STRAGGLER_FACTOR * median:
                m_straggle.inc()
                reg.emit(
                    "straggler", prefix=a.prefix,
                    chunk=a.chunk.chunk_no, wall_s=round(wall, 3),
                    median_s=round(median, 3),
                )
        walls.append(wall)
        reg.emit(
            "chunk_done", prefix=a.prefix, chunk=a.chunk.chunk_no,
            wall_s=round(wall, 3),
        )
    stats["wall_s"] = time.time() - t0
    return stats
