"""Self-healing multi-host chunk queue: lease-based claiming, heartbeats,
and crash-reclaim (BASELINE.md "Multi-host queue").

The static round-robin in ``scheduler.assign_chunks`` strands every chunk
a dead host owns until a human restarts the job — the reference leaned on
dask's scheduler to reassign them (``kafka_test_Py36.py:242-255``).  This
module is the coordinator-free replacement: the SHARED FILESYSTEM is the
queue, and the only protocol is three atomic marker files per chunk:

``.chunk_<prefix>.lease``
    claim marker.  Payload: owner id, hostname, pid, claim time, heartbeat
    ``deadline`` and the chunk's ``requeues`` count.  Created atomically
    (unique tmp + ``os.link``, which fails if a lease exists — the
    exclusive-create half of the protocol); RENEWED by the owner's
    background heartbeat thread (unique tmp + ``os.replace``) before the
    deadline passes.
``.chunk_<prefix>.done``
    commit marker (the existing restart-semantics marker).  ``.done`` WINS
    over any lease: a stale lease next to a ``.done`` is garbage and any
    scanner may remove it.
``.chunk_<prefix>.failed``
    quarantine marker (PR 6).  Honoured by every host: a poison chunk is
    never re-claimed.

**Reclaim.**  A worker that scans the outdir and finds a lease whose
heartbeat deadline has EXPIRED assumes the owner is dead and reclaims the
chunk: it atomically replaces the lease with its own (requeues + 1) and
re-runs the work.  This gives at-least-once execution; it is made SAFE by
the per-chunk-prefixed atomic outputs — if the "dead" owner was merely
slow, both complete and the second overwrites with identical bytes, and
``.done`` wins over any stale lease.  Clock skew between hosts eats into
the TTL margin, so ``lease_ttl_s`` should stay well above both the skew
bound and the heartbeat interval (default: TTL/3).

**Drain.**  SIGTERM requests a graceful drain: the worker finishes the
chunk it is running, commits it, releases any still-unstarted lease and
exits cleanly — remaining chunks stay PENDING for the next worker.  A
second SIGTERM falls through to the previous handler (the flight recorder
chains termination semantics).

Chaos hooks: ``scheduler.claim`` / ``scheduler.heartbeat`` /
``scheduler.commit`` fault points join ``scheduler.run_one`` in the
``faults`` registry, so the whole reclaim story is scriptable
deterministically on CPU (``KAFKA_TPU_FAULTS``).  Telemetry: live-lease /
active-worker gauges, ``kafka_scheduler_reclaims_total``, per-chunk
requeue counts, and ``chunk_claimed`` / ``chunk_reclaimed`` /
``lease_released`` events — ``trace.json`` shows the reclaim happening.

``tools/queue_status.py`` renders :func:`queue_status` for operators.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from ..io.tiling import Chunk
from ..resilience import (
    FATAL,
    TRANSIENT,
    Deadline,
    RetryPolicy,
    classify_failure,
    faults,
)
from ..telemetry import get_registry, stopwatch, tracing
from ..telemetry import live as live_telemetry
from .scheduler import (
    _write_marker,
    chunk_metrics,
    failed_marker_path,
    mark_done,
    mark_failed,
    marker_path,
    sweep_stale_tmp,
    _tmp_name,
)

LOG = logging.getLogger(__name__)

#: default heartbeat-lease time-to-live.  A worker that misses renewals
#: for this long is presumed dead and its chunk is reclaimed; renewals
#: run every TTL/3, so one missed beat never costs the lease.
DEFAULT_LEASE_TTL_S = 30.0

#: the queue's chunk universe, written once at startup so read-only
#: consumers (tools/queue_status.py) can count PENDING chunks — a chunk
#: nobody touched yet has no marker files at all.
MANIFEST_NAME = ".queue_manifest.json"

#: chunk states reported by :func:`scan_chunk` / :func:`queue_status`.
PENDING = "pending"
LEASED = "leased"
LEASE_EXPIRED = "lease_expired"
DONE = "done"
FAILED = "failed"


def lease_path(outdir: str, prefix: str) -> str:
    return os.path.join(outdir, f".chunk_{prefix}.lease")


def chunk_prefix(chunk: Chunk) -> str:
    """The output filename prefix (same chunk-id trick as
    ``assign_chunks``, ``kafka_test_Py36.py:164-166``)."""
    return f"{chunk.chunk_no:04x}"


def default_worker_id() -> str:
    return f"{socket.gethostname()}:{os.getpid()}"


def read_marker(path: str) -> Optional[dict]:
    """Tolerant marker read: ``None`` when the file is missing, ``{}``
    when it exists but is empty/corrupt (legacy pre-PR-6 payloads and
    torn pre-atomic writes must degrade, not crash the scan)."""
    try:
        with open(path) as f:
            payload = json.load(f)
        return payload if isinstance(payload, dict) else {}
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        return {}


def _lease_payload(prefix: str, owner: str, lease_ttl_s: float,
                   requeues: int, claimed: Optional[float] = None) -> dict:
    now = time.time()
    return {
        "prefix": prefix,
        "owner": owner,
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "claimed": round(claimed if claimed is not None else now, 6),
        "deadline": round(now + lease_ttl_s, 6),
        "requeues": int(requeues),
    }


def _try_claim(outdir: str, prefix: str, owner: str, lease_ttl_s: float,
               requeues: int = 0, reclaim: bool = False) -> Optional[dict]:
    """Atomically claim ``prefix``; returns the lease payload or ``None``
    when another worker won the race.

    Fresh claims use ``os.link`` (exclusive create: fails when a lease
    exists).  Reclaims use ``os.replace`` (the expired lease is
    overwritten in one step — no window with no lease on disk) and then
    verify ownership by re-reading: if a third worker replaced us in the
    gap, we lost and move on.
    """
    faults.fault_point("scheduler.claim", prefix=prefix, owner=owner)
    payload = _lease_payload(prefix, owner, lease_ttl_s, requeues)
    path = lease_path(outdir, prefix)
    tmp = _tmp_name(path)
    with open(tmp, "w") as f:
        json.dump(payload, f)
    if reclaim:
        os.replace(tmp, path)
        current = read_marker(path)
        if not current or current.get("owner") != owner:
            return None
        return payload
    try:
        os.link(tmp, path)
    except FileExistsError:
        return None
    finally:
        try:
            os.unlink(tmp)
        except OSError:  # already consumed by os.replace above
            pass
    return payload


def _renew_lease(outdir: str, payload: dict, lease_ttl_s: float) -> None:
    """Heartbeat: push the deadline out, keeping claim time + requeues."""
    fresh = _lease_payload(
        payload["prefix"], payload["owner"], lease_ttl_s,
        payload.get("requeues", 0), claimed=payload.get("claimed"),
    )
    path = lease_path(outdir, payload["prefix"])
    tmp = _tmp_name(path)
    with open(tmp, "w") as f:
        json.dump(fresh, f)
    os.replace(tmp, path)


def _release_lease(outdir: str, prefix: str, owner: str) -> bool:
    """Remove our own lease (commit, quarantine, or drain).  Only the
    current owner's lease is removed — a reclaimed-from-us lease belongs
    to its new owner now."""
    current = read_marker(lease_path(outdir, prefix))
    if current is None or (current and current.get("owner") != owner):
        return False
    try:
        os.unlink(lease_path(outdir, prefix))
    except OSError:
        return False
    return True


@dataclass(frozen=True)
class ChunkScan:
    """One chunk's queue state at scan time."""

    prefix: str
    state: str
    lease: Optional[dict] = None


def scan_chunk(outdir: str, prefix: str, now: Optional[float] = None,
               cleanup: bool = False) -> ChunkScan:
    """Classify one chunk.  ``.done`` wins over any lease (with
    ``cleanup=True`` the stale lease is removed on sight); a lease with a
    corrupt/absent deadline counts as expired — a torn lease must never
    wedge the queue."""
    now = time.time() if now is None else now
    if os.path.exists(marker_path(outdir, prefix)):
        if cleanup and os.path.exists(lease_path(outdir, prefix)):
            try:
                os.unlink(lease_path(outdir, prefix))
            except OSError:  # raced another cleaner — outcome identical
                pass
        return ChunkScan(prefix, DONE)
    if os.path.exists(failed_marker_path(outdir, prefix)):
        return ChunkScan(prefix, FAILED)
    lease = read_marker(lease_path(outdir, prefix))
    if lease is None:
        return ChunkScan(prefix, PENDING)
    deadline = lease.get("deadline")
    if not isinstance(deadline, (int, float)) or deadline <= now:
        return ChunkScan(prefix, LEASE_EXPIRED, lease)
    return ChunkScan(prefix, LEASED, lease)


def write_manifest(outdir: str, chunks: Sequence[Chunk]) -> str:
    """Persist the chunk universe (idempotent — every worker computes the
    same list, so the first atomic write wins and the rest skip)."""
    path = os.path.join(outdir, MANIFEST_NAME)
    if not os.path.exists(path):
        _write_marker(path, {
            "chunks": [
                {"prefix": chunk_prefix(c), **c._asdict()} for c in chunks
            ],
        })
    return path


def _discover_prefixes(outdir: str) -> List[str]:
    """Chunk prefixes visible from marker files alone (the no-manifest
    fallback: PENDING chunks are invisible without one)."""
    found = set()
    for name in os.listdir(outdir):
        if not name.startswith(".chunk_"):
            continue
        stem, _, suffix = name[len(".chunk_"):].rpartition(".")
        if suffix in ("done", "failed", "lease") and stem:
            found.add(stem)
    return sorted(found)


def queue_status(outdir: str, now: Optional[float] = None) -> dict:
    """Read-only snapshot of a queue outdir for operators and tests
    (rendered by ``tools/queue_status.py``).  Never mutates the queue."""
    now = time.time() if now is None else now
    manifest = read_marker(os.path.join(outdir, MANIFEST_NAME))
    if manifest and manifest.get("chunks"):
        prefixes = [c["prefix"] for c in manifest["chunks"]]
    else:
        manifest = None
        prefixes = _discover_prefixes(outdir)
    counts = {PENDING: 0, LEASED: 0, LEASE_EXPIRED: 0, DONE: 0, FAILED: 0}
    chunks: Dict[str, dict] = {}
    workers: Dict[str, dict] = {}
    for prefix in prefixes:
        s = scan_chunk(outdir, prefix, now=now)
        counts[s.state] += 1
        entry = {"state": s.state}
        if s.lease is not None:
            owner = str(s.lease.get("owner", "?"))
            entry["owner"] = owner
            entry["requeues"] = s.lease.get("requeues", 0)
            if isinstance(s.lease.get("deadline"), (int, float)):
                entry["deadline_in_s"] = round(s.lease["deadline"] - now, 3)
            w = workers.setdefault(
                owner, {"live": [], "expired": []}
            )
            w["live" if s.state == LEASED else "expired"].append(prefix)
        chunks[prefix] = entry
    return {
        "outdir": os.path.abspath(outdir),
        "manifest": manifest is not None,
        "n_chunks": len(prefixes),
        "counts": counts,
        "workers": workers,
        "chunks": chunks,
    }


# ---------------------------------------------------------------------------
# Heartbeat thread: renews the owner's current lease until stopped.
# ---------------------------------------------------------------------------

class _Heartbeat:
    """One background renewal thread per worker.  ``watch(payload)``
    points it at the lease just claimed; ``unwatch()`` after
    commit/quarantine.  A failed or lost renewal is recorded and survived
    — the queue's safety net for it is reclaim, not a crashed worker."""

    def __init__(self, outdir: str, owner: str, lease_ttl_s: float,
                 interval_s: Optional[float] = None):
        self._outdir = outdir
        self._owner = owner
        self._ttl = lease_ttl_s
        self._interval = interval_s if interval_s else lease_ttl_s / 3.0
        self._lock = threading.Lock()
        self._payload: Optional[dict] = None
        self._stop = threading.Event()
        self.lost = threading.Event()
        # Cross-thread trace propagation (PR 3 convention): capture the
        # constructing thread's context, re-install it on the worker.
        self._ctx = tracing.current_context()
        self._thread = threading.Thread(
            target=self._run, name="lease-heartbeat", daemon=True,
        )
        self._thread.start()

    def watch(self, payload: dict) -> None:
        with self._lock:
            self._payload = dict(payload)
        self.lost.clear()

    def unwatch(self) -> None:
        with self._lock:
            self._payload = None

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def _run(self) -> None:
        tracing.set_context(self._ctx)
        tracing.set_lane("scheduler")
        while not self._stop.wait(self._interval):
            self.beat()

    def beat(self) -> None:
        with self._lock:
            payload = self._payload
        if payload is None:
            return
        prefix = payload["prefix"]
        reg = get_registry()
        try:
            faults.fault_point(
                "scheduler.heartbeat", prefix=prefix, owner=self._owner,
            )
            current = read_marker(lease_path(self._outdir, prefix))
            if not current or current.get("owner") != self._owner:
                # Reclaimed from under us (we were presumed dead).  Keep
                # running: outputs are idempotent and .done wins — but
                # stop renewing and record the takeover.
                self.lost.set()
                self.unwatch()
                reg.emit(
                    "lease_lost", prefix=prefix, worker=self._owner,
                    holder=(current or {}).get("owner"),
                )
                return
            _renew_lease(self._outdir, payload, self._ttl)
        except Exception as exc:
            # A missed beat is survivable (the deadline has 3x headroom);
            # a crashed heartbeat thread is not — record and carry on.
            reg.emit(
                "heartbeat_failed", prefix=prefix, worker=self._owner,
                error=repr(exc)[:300],
            )


# ---------------------------------------------------------------------------
# SIGTERM drain.
# ---------------------------------------------------------------------------

def _install_drain(drain: threading.Event):
    """First SIGTERM sets the drain flag (finish current chunk, release
    unstarted leases, exit 0) and restores the PREVIOUS handler, so a
    second SIGTERM terminates through the normal chain (flight recorder
    included).  No-op off the main thread — signal.signal is
    main-thread-only."""
    import signal

    if threading.current_thread() is not threading.main_thread():
        return None
    prev = signal.getsignal(signal.SIGTERM)

    def handler(signum, frame):
        drain.set()
        get_registry().emit("worker_drain", signal="SIGTERM")
        signal.signal(signal.SIGTERM, prev or signal.SIG_DFL)

    signal.signal(signal.SIGTERM, handler)
    return prev


def _restore_drain(prev) -> None:
    import signal

    if prev is None:
        return
    try:
        signal.signal(signal.SIGTERM, prev)
    except ValueError:  # left the main thread since install — nothing held
        pass


# ---------------------------------------------------------------------------
# The worker loop.
# ---------------------------------------------------------------------------

def run_queue(
    chunks: Sequence[Chunk],
    run_one: Callable[[Chunk, str], None],
    outdir: str,
    lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
    worker_id: Optional[str] = None,
    heartbeat_interval_s: Optional[float] = None,
    poll_interval_s: Optional[float] = None,
    retry_policy: Optional[RetryPolicy] = None,
    quarantine: bool = False,
    chunk_deadline_s: Optional[float] = None,
    max_requeues: Optional[int] = None,
) -> dict:
    """Run this worker against the shared chunk queue until every chunk
    is ``.done``/``.failed`` (or a SIGTERM drain is requested).

    The self-healing replacement for ``run_chunks``'s static assignment:
    N workers pointed at one ``outdir`` cooperate with no coordinator —
    claims are atomic lease files, liveness is the heartbeat deadline,
    and a worker that dies mid-chunk has its lease EXPIRE and the chunk
    reclaimed by a survivor (at-least-once; safe because the per-chunk
    prefixed outputs are atomic and deterministic, and ``.done`` wins).

    PR 6 semantics compose unchanged: ``retry_policy`` re-runs transient
    chunk failures in place (the lease stays held, heartbeat running);
    ``quarantine=True`` converts exhausted/poison failures into the
    ``.chunk_<prefix>.failed`` marker all hosts honour;
    ``chunk_deadline_s`` classifies an over-budget chunk poison.
    ``max_requeues`` (with quarantine) bounds crash-loop reclaims: a
    chunk that keeps killing its workers is quarantined rather than
    reclaimed forever.

    Returns stats: ``{"worker", "total", "run", "reclaimed", "failed",
    "skipped", "claim_errors", "drained", "pending_at_exit", "wall_s"}``.
    """
    os.makedirs(outdir, exist_ok=True)
    sweep_stale_tmp(outdir)
    write_manifest(outdir, chunks)
    owner = worker_id or default_worker_id()
    # Queue-state export into the fleet plane: the live heartbeat
    # snapshot names the queue this worker serves, so
    # tools/fleet_status.py folds lease/chunk counts in with no extra
    # configuration, and liveness joins on the same host:pid worker id.
    live_telemetry.update_status(
        queue_outdir=os.path.abspath(outdir), worker_id=owner,
        lease_ttl_s=lease_ttl_s,
    )
    by_prefix = {chunk_prefix(c): c for c in chunks}
    prefixes = list(by_prefix)
    # Stable per-worker rotation: workers start their claim scan at
    # different offsets, so a fleet doesn't fight over chunk 1.
    if prefixes:
        offset = zlib.crc32(owner.encode()) % len(prefixes)
        prefixes = prefixes[offset:] + prefixes[:offset]
    poll = poll_interval_s if poll_interval_s else max(
        0.05, min(5.0, lease_ttl_s / 4.0)
    )

    reg = get_registry()
    metrics = chunk_metrics(reg)
    m_reclaims = reg.counter(
        "kafka_scheduler_reclaims_total",
        "expired leases reclaimed from presumed-dead workers",
    )
    m_requeues = reg.counter(
        "kafka_scheduler_chunk_requeues_total",
        "reclaim count per chunk (labelled by prefix) — how often this "
        "chunk's worker died or stalled before commit",
    )
    m_live = reg.gauge(
        "kafka_scheduler_leases_live",
        "live (unexpired) leases visible at the last queue scan",
    )
    m_workers = reg.gauge(
        "kafka_scheduler_workers_active",
        "distinct owners of live leases at the last queue scan",
    )

    stats = {
        "worker": owner, "total": len(chunks), "run": 0, "reclaimed": 0,
        "failed": 0, "skipped": 0, "claim_errors": 0, "drained": False,
        "pending_at_exit": 0, "wall_s": 0.0,
    }
    drain = threading.Event()
    prev_handler = _install_drain(drain)
    hb = _Heartbeat(outdir, owner, lease_ttl_s, heartbeat_interval_s)
    held: Optional[str] = None
    t0 = time.time()
    try:
        while not drain.is_set():
            now = time.time()
            scans = [scan_chunk(outdir, p, now=now, cleanup=True)
                     for p in prefixes]
            open_scans = [s for s in scans if s.state not in (DONE, FAILED)]
            live = [s for s in open_scans if s.state == LEASED]
            m_live.set(len(live))
            m_workers.set(len({
                str(s.lease.get("owner")) for s in live if s.lease
            }))
            metrics["pending"].set(len(open_scans))
            if not open_scans:
                break
            claimed_scan = None
            lease = None
            for s in open_scans:
                if s.state not in (PENDING, LEASE_EXPIRED) or drain.is_set():
                    continue
                requeues = 0
                if s.state == LEASE_EXPIRED:
                    requeues = int((s.lease or {}).get("requeues", 0)) + 1
                    if (quarantine and max_requeues is not None
                            and requeues > max_requeues):
                        # A chunk that keeps killing workers is poison
                        # for the whole fleet — quarantine it instead of
                        # reclaiming forever.
                        mark_failed(outdir, s.prefix, {
                            "chunk": by_prefix[s.prefix].chunk_no,
                            "failure_class": "poison",
                            "error": (
                                f"requeue budget exhausted "
                                f"({requeues - 1} reclaims > "
                                f"{max_requeues})"
                            ),
                        })
                        try:
                            # The dead owner's expired lease is garbage
                            # now — .failed wins; clear it directly.
                            os.unlink(lease_path(outdir, s.prefix))
                        except OSError:
                            pass
                        stats["failed"] += 1
                        metrics["failed"].inc()
                        reg.emit(
                            "chunk_quarantined", prefix=s.prefix,
                            chunk=by_prefix[s.prefix].chunk_no,
                            failure_class="poison",
                            error="requeue budget exhausted",
                        )
                        continue
                try:
                    lease = _try_claim(
                        outdir, s.prefix, owner, lease_ttl_s,
                        requeues=requeues,
                        reclaim=(s.state == LEASE_EXPIRED),
                    )
                except BaseException as exc:
                    if classify_failure(exc) != TRANSIENT:
                        raise
                    stats["claim_errors"] += 1
                    LOG.warning("claim of %s failed transiently: %r",
                                s.prefix, exc)
                    continue
                if lease is not None:
                    claimed_scan = s
                    break
            if claimed_scan is None:
                if drain.is_set():
                    break
                # Nothing claimable: others hold live leases.  Wake at
                # the earliest heartbeat deadline (reclaim opportunity)
                # or the poll interval, whichever is sooner.
                deadlines = [
                    s.lease["deadline"] for s in live
                    if isinstance((s.lease or {}).get("deadline"),
                                  (int, float))
                ]
                wait_s = poll
                if deadlines:
                    wait_s = min(poll, max(0.05, min(deadlines) - now))
                drain.wait(wait_s)
                continue

            prefix = claimed_scan.prefix
            chunk = by_prefix[prefix]
            reclaimed = claimed_scan.state == LEASE_EXPIRED
            if reclaimed:
                stats["reclaimed"] += 1
                m_reclaims.inc()
                m_requeues.inc(prefix=prefix)
                reg.emit(
                    "chunk_reclaimed", prefix=prefix,
                    chunk=chunk.chunk_no, worker=owner,
                    prev_owner=(claimed_scan.lease or {}).get("owner"),
                    requeues=lease["requeues"],
                )
            reg.emit(
                "chunk_claimed", prefix=prefix, chunk=chunk.chunk_no,
                worker=owner, reclaimed=reclaimed,
                requeues=lease["requeues"],
            )
            held = prefix
            hb.watch(lease)
            try:
                _run_claimed(
                    chunk, prefix, run_one, outdir, owner, stats, metrics,
                    retry_policy, quarantine, chunk_deadline_s, reg,
                )
            finally:
                hb.unwatch()
                if held is not None:
                    _release_lease(outdir, held, owner)
                    held = None
    finally:
        hb.stop()
        if held is not None and _release_lease(outdir, held, owner):
            reg.emit("lease_released", prefix=held, worker=owner,
                     reason="exit")
        _restore_drain(prev_handler)
        stats["drained"] = drain.is_set()
        now = time.time()
        still_open = [
            s for s in (scan_chunk(outdir, p, now=now) for p in prefixes)
            if s.state not in (DONE, FAILED)
        ]
        stats["pending_at_exit"] = len(still_open)
        stats["skipped"] = (stats["total"] - stats["run"]
                            - stats["failed"] - len(still_open))
        stats["wall_s"] = time.time() - t0
    return stats


def _run_claimed(chunk, prefix, run_one, outdir, owner, stats, metrics,
                 retry_policy, quarantine, chunk_deadline_s, reg) -> bool:
    """One claimed chunk through the PR 6 attempt machinery, ending in
    the atomic ``.done`` commit.  The ``scheduler.commit`` fault point
    sits INSIDE the attempt, before ``mark_done`` — a transient commit
    failure re-runs the whole chunk under the retry policy, which is
    exactly the at-least-once double-execution path the chaos tests pin
    (second completion overwrites with identical bytes)."""
    sw_chunk = stopwatch()

    def attempt():
        deadline = Deadline(chunk_deadline_s) if chunk_deadline_s else None
        faults.fault_point("scheduler.run_one", prefix=prefix)
        with tracing.push(chunk_id=prefix):
            run_one(chunk, prefix)
        if deadline is not None:
            deadline.check(f"chunk {prefix}")
        faults.fault_point("scheduler.commit", prefix=prefix)
        mark_done(outdir, prefix, {
            "chunk": chunk.chunk_no, "worker": owner,
            "wall_s": round(sw_chunk.elapsed(), 3),
        })

    try:
        if retry_policy is not None:
            retry_policy.call(attempt, site="scheduler.run_one")
        else:
            attempt()
    except BaseException as exc:
        cls = classify_failure(exc)
        if cls == FATAL or not quarantine:
            raise
        stats["failed"] += 1
        mark_failed(outdir, prefix, {
            "chunk": chunk.chunk_no,
            "failure_class": cls,
            "error": repr(exc)[:500],
            "worker": owner,
        })
        metrics["failed"].inc()
        reg.emit(
            "chunk_quarantined", prefix=prefix, chunk=chunk.chunk_no,
            failure_class=cls, error=repr(exc)[:300],
        )
        LOG.error(
            "chunk %s quarantined (%s): %r — queue continues; delete %s "
            "to re-attempt it",
            prefix, cls, exc, failed_marker_path(outdir, prefix),
        )
        return False
    t_end = sw_chunk.now()
    wall = t_end - sw_chunk.t0
    reg.trace.add_span(
        "chunk", sw_chunk.t0, t_end, lane="scheduler", cat="chunk",
        prefix=prefix, chunk=chunk.chunk_no,
    )
    stats["run"] += 1
    metrics["done"].inc()
    metrics["wall"].observe(wall)
    reg.emit(
        "chunk_done", prefix=prefix, chunk=chunk.chunk_no,
        wall_s=round(wall, 3),
    )
    return True
