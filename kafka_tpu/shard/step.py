"""The fully-sharded assimilation step: one XLA program per date.

Fuses the whole per-timestep pipeline — state propagation
(``kf_tools.py:136-353`` semantics), prior blending, and the multi-band
Gauss-Newton solve (``linear_kf.py:245-307``) — into ONE jitted program
partitioned over the pixel mesh axis.  GSPMD splits every batched kernel
across devices; because pixels never couple (SURVEY.md §2.3), the program
contains no collectives except the scalar convergence-norm ``psum`` inside
the while-loop, which rides ICI.

This is the multi-chip execution path: build the step once per operator
configuration, then feed it each date's band batch.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax

from jax.sharding import Mesh

from ..core import propagators as prop
from ..core.solvers import LinearizeFn, iterated_solve
from ..core.types import BandBatch, SolveDiagnostics
from .mesh import pixel_sharding, replicated


def make_sharded_step(
    linearize: LinearizeFn,
    mesh: Mesh,
    state_propagator: Optional[Callable] = None,
    use_prior: bool = True,
    solver_options: Optional[dict] = None,
    n_valid: Optional[int] = None,
):
    """Build the jitted, mesh-partitioned per-date step.

    Returned callable signature::

        step(bands, x_analysis, p_inv_analysis, m_matrix, q_diag,
             prior_mean, prior_inv, operator_params)
            -> (x_analysis, p_inv_analysis, diagnostics)

    ``prior_mean`` / ``prior_inv`` are ignored (pass anything) when
    ``use_prior=False``.  ``operator_params`` carries per-date operator data
    (angles, emulator weights) as a traced pytree.

    ``n_valid`` — number of real (unpadded) pixels in the batches this step
    will see.  With ``pad_for_mesh`` padding, the convergence norm must be
    normalised by the valid element count, not the padded one, or the
    tolerance loosens by n_pad/n_valid relative to the reference
    (``linear_kf.py:296``); same contract as the engine path
    (``engine/filter.py``).
    """
    opts = dict(solver_options or {})

    def _step(bands: BandBatch, x_analysis, p_inv_analysis, m_matrix,
              q_diag, prior_mean, prior_inv, operator_params):
        # --- advance (propagate_and_blend_prior, kf_tools.py:136-171) ---
        pm = prior_mean if use_prior else None
        pi = prior_inv if use_prior else None
        x_f, p_f, p_f_inv = prop.advance(
            x_analysis, None, p_inv_analysis, m_matrix, q_diag,
            prior_mean=pm, prior_cov_inverse=pi,
            state_propagator=state_propagator,
        )
        if x_f is None:  # no propagator, no prior: persistence forecast
            x_f, p_f_inv = x_analysis, p_inv_analysis
        elif p_f_inv is None:
            from ..core.linalg import spd_inverse_batched
            p_f_inv = spd_inverse_batched(p_f)
        # --- the multi-band Gauss-Newton solve -------------------------
        solve_opts = opts
        if n_valid is not None and "norm_denominator" not in opts:
            solve_opts = dict(
                opts, norm_denominator=float(n_valid * x_f.shape[1])
            )
        x_a, p_inv_a, diags = iterated_solve(
            linearize, bands, x_f, p_f_inv, operator_params, **solve_opts
        )
        return x_a, p_inv_a, diags

    px1 = pixel_sharding(mesh, 0, 2)     # (n_pix, p)
    px2 = pixel_sharding(mesh, 0, 3)     # (n_pix, p, p)
    bnd = pixel_sharding(mesh, 1, 2)     # (n_bands, n_pix)
    rep = replicated(mesh)
    band_sh = BandBatch(y=bnd, r_inv=bnd, mask=bnd)

    return jax.jit(
        _step,
        in_shardings=(band_sh, px1, px2, rep, rep, px1, px2, None),
        # Diagnostics: innovations/fwd are band-major pixel arrays, the
        # loop/telemetry scalars are replicated (chi2 is a tiny per-band
        # vector); the per-pixel converged mask (only present under that
        # convergence mode) rides the pixel axis.
        out_shardings=(
            px1, px2,
            SolveDiagnostics(
                innovations=bnd, fwd_modelled=bnd,
                n_iterations=rep, convergence_norm=rep,
                converged_mask=(
                    pixel_sharding(mesh, 0, 1)
                    if opts.get("per_pixel_convergence") else None
                ),
                chi2_per_band=rep, clipped_count=rep, nodata_count=rep,
            ),
        ),
    )


def make_sharded_forward(forward: Callable, mesh: Mesh):
    """Jit a plain batched forward model (``(aux, (n_pix, p)) -> (n_bands,
    n_pix)``) over the pixel mesh — the sharded inference/prediction path."""
    px1 = pixel_sharding(mesh, 0, 2)
    bnd = pixel_sharding(mesh, 1, 2)

    return jax.jit(
        functools.partial(_forward_apply, forward),
        in_shardings=(None, px1),
        out_shardings=bnd,
    )


def _forward_apply(forward, aux, x):
    return forward(aux, x)
