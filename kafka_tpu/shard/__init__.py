"""Device-mesh sharding + multi-host tile scheduling.

The TPU-native replacement for the reference's two distribution layers:
pixels within a chunk shard over the device mesh via GSPMD (``mesh``,
``step``), whole chunks/tiles distribute across hosts via a deterministic
work queue (``scheduler`` — the dask-equivalent of
``kafka_test_Py36.py:242-255``).
"""

from .mesh import (
    PIXEL_AXIS,
    initialize_distributed,
    make_pixel_mesh,
    pad_for_mesh,
    pixel_sharding,
    replicated,
    shard_bands,
    shard_state,
)
from .scheduler import (
    ChunkAssignment,
    assign_chunks,
    failed_marker_path,
    mark_done,
    mark_failed,
    pending_chunks,
    run_chunks,
)
from .step import make_sharded_forward, make_sharded_step

__all__ = [
    "PIXEL_AXIS",
    "initialize_distributed",
    "make_pixel_mesh",
    "pad_for_mesh",
    "pixel_sharding",
    "replicated",
    "shard_bands",
    "shard_state",
    "ChunkAssignment",
    "assign_chunks",
    "failed_marker_path",
    "mark_done",
    "mark_failed",
    "pending_chunks",
    "run_chunks",
    "make_sharded_forward",
    "make_sharded_step",
]
