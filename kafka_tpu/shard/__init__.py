"""Device-mesh sharding + multi-host tile scheduling.

The TPU-native replacement for the reference's two distribution layers:
pixels within a chunk shard over the device mesh via GSPMD (``mesh``,
``step``), whole chunks/tiles distribute across hosts via a deterministic
work queue (``scheduler`` — the dask-equivalent of
``kafka_test_Py36.py:242-255``) or, self-healingly, via the lease-based
shared chunk queue (``queue`` — claims, heartbeats and crash-reclaim, so
a dead host's chunks are picked up by survivors instead of stranding).
"""

from .mesh import (
    PIXEL_AXIS,
    initialize_distributed,
    make_pixel_mesh,
    pad_for_mesh,
    pixel_sharding,
    replicated,
    shard_bands,
    shard_state,
)
from .queue import (
    DEFAULT_LEASE_TTL_S,
    lease_path,
    queue_status,
    run_queue,
)
from .scheduler import (
    ChunkAssignment,
    assign_chunks,
    failed_marker_path,
    mark_done,
    mark_failed,
    pending_chunks,
    run_chunks,
    sweep_stale_tmp,
)
from .step import make_sharded_forward, make_sharded_step

__all__ = [
    "PIXEL_AXIS",
    "initialize_distributed",
    "make_pixel_mesh",
    "pad_for_mesh",
    "pixel_sharding",
    "replicated",
    "shard_bands",
    "shard_state",
    "ChunkAssignment",
    "DEFAULT_LEASE_TTL_S",
    "assign_chunks",
    "failed_marker_path",
    "lease_path",
    "mark_done",
    "mark_failed",
    "pending_chunks",
    "queue_status",
    "run_chunks",
    "run_queue",
    "sweep_stale_tmp",
    "make_sharded_forward",
    "make_sharded_step",
]
