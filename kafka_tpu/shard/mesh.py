"""Device mesh + pixel-axis sharding.

The reference's only intra-node parallel axis is the pixel batch: every
pixel's update is independent (SURVEY.md §2.3; proof that A is per-pixel
block-diagonal at ``/root/reference/kafka/inference/utils.py:193-215``).
The TPU mapping is therefore a 1-D device mesh with the pixel axis
partitioned across it — GSPMD splits every batched kernel with ZERO
collectives in the hot path (nothing couples across pixels; the only
reductions are the scalar convergence norm and diagnostics, which XLA
lowers to a cheap ``psum`` over ICI).

Multi-host: the same mesh spans hosts via ``jax.distributed.initialize``;
pixel shards ride ICI within a pod slice while whole tiles are distributed
across hosts by the scheduler (``shard.scheduler``) — the dask-equivalent
of ``kafka_test_Py36.py:242-255``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PIXEL_AXIS = "pixels"


def make_pixel_mesh(devices: Optional[Sequence[Any]] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name ``pixels``."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (PIXEL_AXIS,))


def pixel_sharding(mesh: Mesh, batch_axis: int = 0,
                   ndim: int = 2) -> NamedSharding:
    """NamedSharding partitioning axis ``batch_axis`` of an ``ndim``-array
    over the pixel mesh axis; all other axes replicated.

    State arrays are pixel-leading (``(n_pix, p)``, ``(n_pix, p, p)``:
    ``batch_axis=0``); band batches are band-leading (``(n_bands, n_pix)``:
    ``batch_axis=1``).
    """
    spec = [None] * ndim
    spec[batch_axis] = PIXEL_AXIS
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_state(mesh: Mesh, x, p_inv=None):
    """Device-put state arrays with the pixel axis partitioned."""
    x = jax.device_put(x, pixel_sharding(mesh, 0, np.ndim(x)))
    if p_inv is not None:
        p_inv = jax.device_put(p_inv, pixel_sharding(mesh, 0, np.ndim(p_inv)))
    return x, p_inv


def shard_bands(mesh: Mesh, bands):
    """Device-put a ``BandBatch`` (all fields ``(n_bands, n_pix)``) with the
    pixel axis partitioned."""
    sh = pixel_sharding(mesh, 1, 2)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), bands)


def pad_for_mesh(n: int, mesh: Mesh, lane: int = 128) -> int:
    """Smallest padded pixel count >= n that is divisible by the mesh size
    and keeps every shard lane-aligned (multiples of 128 for the TPU VPU
    lane dimension)."""
    n_dev = mesh.devices.size
    quantum = n_dev * lane
    return max(int(np.ceil(max(n, 1) / quantum)) * quantum, quantum)


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bring-up: ``jax.distributed.initialize`` (the replacement
    for the reference's dask ``Client('tcp://...')`` handshake,
    ``kafka_test_Py36.py:249``).

    With no arguments this defers to JAX's own pod auto-detection (the
    no-arg ``jax.distributed.initialize()`` contract); explicitly passing
    ``num_processes=1`` skips initialization for single-process runs.
    """
    if num_processes is not None and num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
