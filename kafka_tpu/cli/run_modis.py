"""MODIS BHR annual driver — serial information-filter configuration.

TPU-native equivalent of ``/root/reference/kafka_test.py:156-217``:
7-parameter TIP state, two-stream observation operator over MCD43
kernel-weight BHR, ``information_filter_lai`` propagation with
Q[TeLAI]=0.04, JRC prior for the initial state only, 16-day grid over a
year.  The whole tile runs as one chunk (the reference's serial driver);
use ``run_modis_distributed`` for the chunked variant.

Usage:
    python -m kafka_tpu.cli.run_modis --data-folder /path/mcd43 \
        --state-mask mask.tif --outdir /tmp/kafka_modis
"""

from __future__ import annotations

import argparse
import datetime
import json
import logging

from ..engine.config import RunConfig
from ..engine.priors import TIP_PARAMETER_LIST
from . import add_telemetry_arg, make_console
from .drivers import run_config


def default_config() -> RunConfig:
    """The reference's MODIS-annual constants (``kafka_test.py:156-217``)."""
    return RunConfig(
        parameter_list=TIP_PARAMETER_LIST,
        start=datetime.datetime(2017, 1, 1),
        end=datetime.datetime(2017, 12, 31),
        step_days=16,
        operator="twostream",
        propagator="information_filter_lai",
        prior=None,
        initial_prior="jrc",              # kafka_test.py:195-208
        q_diag=[0, 0, 0, 0, 0, 0, 0.04],  # Q[6::7]=0.04, kafka_test.py:207
        chunk_size=(2400, 2400),          # whole tile, one chunk
        observations="bhr",
        extra={"period": 16},
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default=None,
                    help="RunConfig JSON overriding the annual defaults")
    ap.add_argument("--data-folder", default=None)
    ap.add_argument("--state-mask", default=None)
    ap.add_argument("--outdir", default=None)
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )

    cfg = RunConfig.load(args.config) if args.config else default_config()
    if args.data_folder:
        cfg.data_folder = args.data_folder
    if args.state_mask:
        cfg.state_mask = args.state_mask
    if args.outdir:
        cfg.output_folder = args.outdir
    if args.telemetry_dir:
        cfg.telemetry_dir = args.telemetry_dir

    stats = run_config(cfg)
    print(json.dumps(stats))
    return stats


console = make_console(main)


if __name__ == "__main__":
    main()
