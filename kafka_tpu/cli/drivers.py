"""Shared driver harness: a ``RunConfig`` -> chunked assimilation run.

The reference repeats the same per-chunk wiring in each driver script —
sub-mask, reader, output-with-prefix, prior, ``LinearKalman``, ``run()``
(``/root/reference/kafka_test_S2.py:135-194``,
``kafka_test_Py36.py:147-187``).  Here that wiring lives once, driven by
the declarative ``RunConfig``, and chunk scheduling/restartability comes
from ``kafka_tpu.shard.run_chunks`` (the dask-equivalent, restart-safe).
"""

from __future__ import annotations

import json
import logging
import time
from typing import Callable, Optional

import numpy as np

from ..engine import KalmanFilter
from ..engine.config import RunConfig
from ..io import GeoTIFFOutput, read_geotiff
from ..io.tiling import chunk_geotransform, chunk_mask, get_chunks
from ..shard.scheduler import run_chunks

LOG = logging.getLogger(__name__)


def load_state_mask(cfg: RunConfig):
    """(mask bool array, GeoInfo) from the config's state-mask GeoTIFF."""
    if cfg.state_mask is None:
        raise ValueError("RunConfig.state_mask must point to a GeoTIFF")
    arr, info = read_geotiff(cfg.state_mask)
    return np.asarray(arr).astype(bool), info.geo


def _crs_parts(crs):
    """Split a reader's ``define_output`` CRS into (projection, epsg)."""
    if isinstance(crs, int):
        return "", crs
    return (crs or ""), None


def prosail_aux_builder(metadata, gather):
    """Scene angles -> ``ProsailAux`` (the per-date geometry the reference
    feeds through emulator selection, ``Sentinel2_Observations.py:148-159``)."""
    import jax.numpy as jnp

    from ..obsops.prosail import ProsailAux

    return ProsailAux(
        sza=jnp.asarray(metadata["sza"], jnp.float32),
        vza=jnp.asarray(metadata["vza"], jnp.float32),
        raa=jnp.asarray(metadata["vaa"] - metadata["saa"], jnp.float32),
    )


def run_one_chunk(
    cfg: RunConfig,
    chunk,
    prefix: str,
    full_mask: np.ndarray,
    geo,
    aux_builder: Optional[Callable] = None,
    operator=None,
) -> Optional[dict]:
    """One chunk's full assimilation: reader, prior, filter, outputs.

    Returns a summary dict, or None when the chunk's mask is empty (the
    reference's mask-nonempty guard, ``kafka_test_Py36.py:155-157``).

    ``operator`` should be the ONE instance shared across chunks: the
    jitted per-date solver is cache-keyed on the operator's bound
    ``linearize``, so a fresh instance per chunk would recompile the
    whole program for every chunk.
    """
    sub_mask = chunk_mask(full_mask, chunk)
    if not sub_mask.any():
        return None
    if operator is None:
        operator = cfg.make_operator()
    gt = chunk_geotransform(geo.geotransform, chunk)
    obs = cfg.make_observations(
        operator, state_geo=(gt, geo.epsg), aux_builder=aux_builder
    )
    if hasattr(obs, "apply_roi"):
        # Native-grid reader (MODIS family): window to the chunk instead of
        # warping — the reference's per-chunk apply_roi
        # (``kafka_test_Py36.py:162``).
        obs.apply_roi(
            chunk.x0, chunk.y0,
            chunk.x0 + chunk.nx_valid, chunk.y0 + chunk.ny_valid,
        )
    crs, out_gt = obs.define_output()
    projection, epsg = _crs_parts(crs)
    output = GeoTIFFOutput(
        cfg.parameter_list, out_gt, projection,
        folder=cfg.output_folder, prefix=prefix, epsg=epsg,
        async_writes=True, wire_dtype=cfg.wire_dtype,
    )
    prior = cfg.make_prior()
    kf = KalmanFilter(
        obs, output, sub_mask, cfg.parameter_list,
        state_propagation=cfg.make_propagator(),
        prior=prior,
        pad_multiple=cfg.pad_multiple,
        solver_options=cfg.solver_options,
        hessian_correction=cfg.hessian_correction,
        prefetch_depth=cfg.prefetch_depth,
        scan_window=cfg.scan_window,
    )
    kf.set_trajectory_model()
    q = cfg.q_diag if cfg.q_diag is not None else np.zeros(cfg.n_params)
    kf.set_trajectory_uncertainty(np.asarray(q, np.float32))
    init_prior = cfg.make_initial_prior()
    if init_prior is None:
        raise ValueError(
            "RunConfig needs `prior` or `initial_prior` for the start state"
        )
    x0, p_inv0 = init_prior.process_prior(None, kf.gather)
    grid = cfg.time_grid()
    checkpointer = None
    advance_first = False
    if cfg.checkpoint_folder:
        from ..engine.checkpoint import Checkpointer

        checkpointer = Checkpointer(
            cfg.checkpoint_folder, prefix=f"{prefix}_",
            n_shards=int(cfg.extra.get("checkpoint_shards", 1)),
        )
        grid, seed = checkpointer.resume_time_grid(grid)
        if seed is not None:
            x0, p_inv0 = seed
            advance_first = True
            LOG.info(
                "chunk %s: resuming from checkpoint at %s (%d steps left)",
                prefix, grid[0], len(grid) - 1,
            )
    t0 = time.time()
    kf.run(grid, x0, None, p_inv0, checkpointer=checkpointer,
           advance_first=advance_first)
    output.close()
    return {
        "prefix": prefix,
        "n_pixels": int(kf.gather.n_valid),
        "n_dates_assimilated": len(kf.diagnostics_log),
        "wall_s": round(time.time() - t0, 3),
    }


def run_config(
    cfg: RunConfig,
    aux_builder: Optional[Callable] = None,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
) -> dict:
    """Chunked run over the whole state mask — the ``__main__`` of every
    reference driver, including the dask fan-out (serial loop and
    distributed execution are the same code path here;
    ``kafka_test_S2.py:196-205`` vs ``kafka_test_Py36.py:242-255``)."""
    from ..utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    full_mask, geo = load_state_mask(cfg)
    ny, nx = full_mask.shape
    chunks = list(get_chunks(nx, ny, tuple(cfg.chunk_size)))
    summaries = []
    # One operator for ALL chunks — keeps the jitted solver's compile
    # cache warm across the chunk loop (see run_one_chunk).
    operator = cfg.make_operator()

    def run_one(chunk, prefix):
        s = run_one_chunk(
            cfg, chunk, prefix, full_mask, geo, aux_builder,
            operator=operator,
        )
        if s is not None:
            summaries.append(s)
            LOG.info("chunk %s: %s", prefix, json.dumps(s))

    stats = run_chunks(
        chunks, run_one, cfg.output_folder,
        num_processes=num_processes, process_index=process_index,
    )
    stats["chunks_with_pixels"] = len(summaries)
    stats["pixels"] = int(sum(s["n_pixels"] for s in summaries))
    stats["dates_assimilated"] = int(
        sum(s["n_dates_assimilated"] for s in summaries)
    )
    return stats
