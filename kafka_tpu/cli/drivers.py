"""Shared driver harness: a ``RunConfig`` -> chunked assimilation run.

The reference repeats the same per-chunk wiring in each driver script —
sub-mask, reader, output-with-prefix, prior, ``LinearKalman``, ``run()``
(``/root/reference/kafka_test_S2.py:135-194``,
``kafka_test_Py36.py:147-187``).  Here that wiring lives once, driven by
the declarative ``RunConfig``, and chunk scheduling/restartability comes
from ``kafka_tpu.shard.run_chunks`` (the dask-equivalent, restart-safe).
"""

from __future__ import annotations

import functools
import json
import logging
import os
import time
from typing import Callable, Optional

import numpy as np

from ..engine import KalmanFilter
from ..engine.config import RunConfig
from ..io import GeoTIFFOutput, read_geotiff
from ..io.tiling import chunk_geotransform, chunk_mask, get_chunks
from ..shard.scheduler import run_chunks

LOG = logging.getLogger(__name__)


def load_state_mask(cfg: RunConfig):
    """(mask bool array, GeoInfo) from the config's state-mask GeoTIFF."""
    if cfg.state_mask is None:
        raise ValueError("RunConfig.state_mask must point to a GeoTIFF")
    arr, info = read_geotiff(cfg.state_mask)
    return np.asarray(arr).astype(bool), info.geo


def _crs_parts(crs):
    """Split a reader's ``define_output`` CRS into (projection, epsg)."""
    if isinstance(crs, int):
        return "", crs
    return (crs or ""), None


def prosail_aux_builder(metadata, gather):
    """Scene angles -> ``ProsailAux`` (the per-date geometry the reference
    feeds through emulator selection, ``Sentinel2_Observations.py:148-159``)."""
    import jax.numpy as jnp

    from ..obsops.prosail import ProsailAux

    return ProsailAux(
        sza=jnp.asarray(metadata["sza"], jnp.float32),
        vza=jnp.asarray(metadata["vza"], jnp.float32),
        raa=jnp.asarray(metadata["vaa"] - metadata["saa"], jnp.float32),
    )


def make_run_mesh(cfg: RunConfig):
    """The chunk-level pixel mesh per ``RunConfig.device_mesh``: all LOCAL
    devices (the ICI axis — chips of this host's slice), or None.  Chunks
    stay the DCN/process axis via the scheduler."""
    mode = getattr(cfg, "device_mesh", "auto")
    if mode not in ("auto", "local", "none"):
        raise ValueError(
            f"device_mesh={mode!r}: expected 'auto', 'local' or 'none'"
        )
    if mode == "none":
        return None
    import jax

    devices = jax.local_devices()
    if mode == "auto" and len(devices) < 2:
        return None
    from ..shard.mesh import make_pixel_mesh

    return make_pixel_mesh(devices)


def run_one_chunk(
    cfg: RunConfig,
    chunk,
    prefix: str,
    full_mask: np.ndarray,
    geo,
    aux_builder: Optional[Callable] = None,
    operator=None,
) -> Optional[dict]:
    """One chunk's full assimilation: reader, prior, filter, outputs.

    Returns a summary dict, or None when the chunk's mask is empty (the
    reference's mask-nonempty guard, ``kafka_test_Py36.py:155-157``).

    ``operator`` should be the ONE instance shared across chunks: the
    jitted per-date solver is cache-keyed on the operator's bound
    ``linearize``, so a fresh instance per chunk would recompile the
    whole program for every chunk.
    """
    sub_mask = chunk_mask(full_mask, chunk)
    if not sub_mask.any():
        return None
    if operator is None:
        operator = cfg.make_operator()
    gt = chunk_geotransform(geo.geotransform, chunk)
    obs = cfg.make_observations(
        operator, state_geo=(gt, geo.epsg), aux_builder=aux_builder
    )
    if hasattr(obs, "apply_roi"):
        # Native-grid reader (MODIS family): window to the chunk instead of
        # warping — the reference's per-chunk apply_roi
        # (``kafka_test_Py36.py:162``).
        obs.apply_roi(
            chunk.x0, chunk.y0,
            chunk.x0 + chunk.nx_valid, chunk.y0 + chunk.ny_valid,
        )
    crs, out_gt = obs.define_output()
    projection, epsg = _crs_parts(crs)
    output = GeoTIFFOutput(
        cfg.parameter_list, out_gt, projection,
        folder=cfg.output_folder, prefix=prefix, epsg=epsg,
        async_writes=True, wire_dtype=cfg.wire_dtype,
    )
    prior = cfg.make_prior()
    kf = KalmanFilter(
        obs, output, sub_mask, cfg.parameter_list,
        state_propagation=cfg.make_propagator(),
        prior=prior,
        pad_multiple=cfg.pad_multiple,
        # Production defaults applied (use_pallas flips on for
        # parity-tested operators once the healthy-window bench artifact
        # exists — engine/config.py: resolved_solver_options).
        solver_options=cfg.resolved_solver_options(),
        hessian_correction=cfg.hessian_correction,
        prefetch_depth=cfg.prefetch_depth,
        prefetch_workers=cfg.prefetch_workers,
        scan_window=cfg.scan_window,
        mesh=make_run_mesh(cfg),
        checkpoint_every_n=cfg.checkpoint_every_n,
        band_sequential=cfg.band_sequential,
    )
    kf.set_trajectory_model()
    q = cfg.q_diag if cfg.q_diag is not None else np.zeros(cfg.n_params)
    kf.set_trajectory_uncertainty(np.asarray(q, np.float32))
    init_prior = cfg.make_initial_prior()
    if init_prior is None:
        raise ValueError(
            "RunConfig needs `prior` or `initial_prior` for the start state"
        )
    x0, p_inv0 = init_prior.process_prior(None, kf.gather)
    grid = cfg.time_grid()
    checkpointer = None
    advance_first = False
    if cfg.checkpoint_folder:
        from ..engine.checkpoint import Checkpointer

        checkpointer = Checkpointer(
            cfg.checkpoint_folder, prefix=f"{prefix}_",
            n_shards=int(cfg.extra.get("checkpoint_shards", 1)),
        )
        grid, seed = checkpointer.resume_time_grid(grid)
        if seed is not None:
            x0, p_inv0 = seed
            advance_first = True
            LOG.info(
                "chunk %s: resuming from checkpoint at %s (%d steps left)",
                prefix, grid[0], len(grid) - 1,
            )
    t0 = time.time()
    try:
        kf.run(grid, x0, None, p_inv0, checkpointer=checkpointer,
               advance_first=advance_first)
    except BaseException:
        # Tear the async writer down on failure too — an abandoned worker
        # thread (and any device arrays in its queue) would outlive the
        # failed attempt and eat into a retry's device memory.
        try:
            output.close()
        except Exception as close_exc:
            LOG.warning(
                "output teardown after a failed run also failed "
                "(original error propagates): %s", close_exc,
            )
        raise
    output.close()
    return {
        "prefix": prefix,
        "n_pixels": int(kf.gather.n_valid),
        "n_dates_assimilated": len(kf.diagnostics_log),
        "wall_s": round(time.time() - t0, 3),
    }


def _is_oom(exc: BaseException) -> bool:
    text = str(exc)
    return "RESOURCE_EXHAUSTED" in text or "ResourceExhausted" in text


def split_chunk(chunk) -> list:
    """Quarter a chunk (2x2, odd sizes rounded up in the first half)."""
    from ..io.tiling import Chunk

    hx = (chunk.nx_valid + 1) // 2
    hy = (chunk.ny_valid + 1) // 2
    subs = []
    for y0, ny in ((chunk.y0, hy), (chunk.y0 + hy, chunk.ny_valid - hy)):
        for x0, nx in ((chunk.x0, hx), (chunk.x0 + hx, chunk.nx_valid - hx)):
            if nx > 0 and ny > 0:
                subs.append(Chunk(x0, y0, nx, ny, chunk.chunk_no))
    return subs


@functools.lru_cache(maxsize=4)
def _emulator_banks(folder: str):
    """Converted per-geometry emulator banks, loaded once per process
    (every chunk shares them; the jitted program is keyed on the
    operator, only the bank arrays change per date).

    When ``folder`` holds raw pickles, the converted banks are written
    to a ``.kafka_tpu_banks/`` cache next to them (best-effort): fresh
    worker processes — every chunk after a device OOM runs in one —
    then load the .npz cache instead of re-paying the full unpickle +
    per-band alpha recompute per process."""
    import glob as _glob

    from ..obsops.gp_import import (
        load_emulator_directory, save_bank_npz,
    )

    cache = os.path.join(folder, ".kafka_tpu_banks")
    if _glob.glob(os.path.join(cache, "*.npz")):
        return load_emulator_directory(cache)
    banks = load_emulator_directory(folder)
    had_pickles = bool(_glob.glob(os.path.join(folder, "*.pkl")))
    if had_pickles and not _glob.glob(os.path.join(folder, "*.npz")):
        try:
            os.makedirs(cache, exist_ok=True)
            for (sza, vza, raa), bank in banks.items():
                save_bank_npz(
                    os.path.join(
                        cache, f"bank_{vza:g}_{sza:g}_{raa:g}.npz"
                    ),
                    bank,
                )
            LOG.info("cached %d converted emulator bank(s) in %s",
                     len(banks), cache)
        except OSError as exc:
            LOG.warning("could not cache converted banks in %s: %s",
                        cache, exc)
    return banks


@functools.lru_cache(maxsize=4)
def _gp_bank_builder(folder: str) -> Callable:
    from ..io.sentinel2 import geometry_bank_aux_builder

    return geometry_bank_aux_builder(_emulator_banks(folder))


def gp_bank_aux_builder(cfg: RunConfig) -> Callable:
    """Per-date geometry -> converted emulator bank (the reference's
    per-geometry unpickling, ``Sentinel2_Observations.py:157-159``).
    Cached per folder so repeated resolution returns the SAME callable —
    the OOM-recovery identity check relies on it."""
    return _gp_bank_builder(cfg.extra["emulator_folder"])


#: aux builders reconstructible by name in a fresh worker process.
def resolve_aux_builder(cfg: RunConfig) -> Optional[Callable]:
    # The joint S2+S1 configuration feeds the same scene-angle builder to
    # its Sentinel-2 side (run_joint.py).
    if cfg.operator in ("prosail", "prosail_joint"):
        return prosail_aux_builder
    if cfg.operator == "gp_bank":
        return gp_bank_aux_builder(cfg)
    return None


def _remove_outputs(cfg, patterns) -> None:
    """Delete output rasters matching ``patterns`` in the run's output
    folder — the split/success paths use this to guarantee that exactly
    one generation of files covers any pixel."""
    if not getattr(cfg, "output_folder", None):
        return
    import glob as _glob

    for pattern in patterns:
        for stale in _glob.glob(os.path.join(cfg.output_folder, pattern)):
            LOG.info("removing stale output %s", stale)
            os.unlink(stale)


#: set once this process's device client has thrown RESOURCE_EXHAUSTED:
#: after that, EVERY allocation in this process fails (measured on the
#: tunneled TPU runtime — even 1 MB), so all further chunk work must run
#: in fresh subprocesses.
_DEVICE_POISONED = False


def _run_chunk_subprocess(cfg: RunConfig, chunk, prefix: str):
    """Run one chunk in a fresh interpreter (fresh device client).

    Returns ``(exit_code, summary_or_None)``."""
    import subprocess
    import sys
    import tempfile

    from .chunk_worker import OOM_EXIT_CODE  # noqa: F401 (doc link)

    with tempfile.NamedTemporaryFile(
        "w", suffix=".json", delete=False
    ) as f:
        f.write(cfg.to_json())
        cfg_path = f.name
    # Generous hang guard: a wedged device client (a known failure mode
    # of this runtime after OOM) must surface as a failed worker, not
    # block the scheduler forever.  Far above any measured chunk time
    # (largest observed: ~7 min for an annual 1.2M-px chunk);
    # overridable per run via extra["chunk_worker_timeout"].
    timeout_s = float(
        (getattr(cfg, "extra", None) or {}).get(
            "chunk_worker_timeout", 4 * 3600
        )
    )
    # Hand the run id down so the worker's spans/crash dumps correlate
    # with this scheduler's trace (kafka_tpu.telemetry.tracing).
    env = dict(os.environ)
    from ..telemetry import tracing

    ctx = tracing.current_context()
    if ctx is not None:
        env["KAFKA_TPU_RUN_ID"] = ctx.run_id
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "kafka_tpu.cli.chunk_worker",
             cfg_path, str(chunk.x0), str(chunk.y0),
             str(chunk.nx_valid), str(chunk.ny_valid),
             str(chunk.chunk_no), prefix],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired:
        LOG.error(
            "chunk worker %s exceeded %.0f s and was killed",
            prefix, timeout_s,
        )
        return 124, None
    finally:
        os.unlink(cfg_path)
    summary = None
    if proc.returncode == 0:
        parsed = False
        for line in reversed(proc.stdout.strip().splitlines() or [""]):
            try:
                summary = json.loads(line)
                parsed = True
                break
            except json.JSONDecodeError:
                continue
        if not parsed:
            # rc 0 contractually prints one JSON line; a silent None here
            # would undercount run stats while the outputs exist on disk.
            LOG.error(
                "chunk worker %s exited 0 without a summary JSON line "
                "(stdout: %r)", prefix, proc.stdout[-300:],
            )
    else:
        LOG.warning(
            "chunk worker %s rc=%d: %s", prefix, proc.returncode,
            proc.stderr.strip()[-500:],
        )
    return proc.returncode, summary


def run_one_chunk_resilient(
    cfg: RunConfig,
    chunk,
    prefix: str,
    full_mask: np.ndarray,
    geo,
    aux_builder: Optional[Callable] = None,
    operator=None,
    max_splits: int = 2,
) -> Optional[dict]:
    """``run_one_chunk`` with device-OOM recovery.

    A RESOURCE_EXHAUSTED poisons this process's device client permanently
    (see ``_DEVICE_POISONED``), so recovery is process-based: after the
    first OOM, every chunk — the failed one and all that follow — runs in
    a fresh subprocess (``cli.chunk_worker``); a chunk whose working set
    genuinely exceeds HBM OOMs in its own process too and is split into
    four quarter chunks (recursively, up to ``max_splits`` levels), each
    with a suffixed output prefix.  Chunk sizing stops being a hard
    failure mode: the configured size is a hint, oversize chunks degrade
    into more files instead of a crash.  Non-OOM errors propagate.

    The subprocess path needs the aux builder reconstructible by name
    (``resolve_aux_builder``); runs with a custom injected builder fail
    loudly rather than silently dropping it.
    """
    global _DEVICE_POISONED
    from .chunk_worker import OOM_EXIT_CODE

    if not _DEVICE_POISONED:
        try:
            result = run_one_chunk(
                cfg, chunk, prefix, full_mask, geo, aux_builder,
                operator=operator,
            )
            _remove_outputs(cfg, [f"*_{prefix}-[abcd]*.tif"])
            return result
        except Exception as exc:  # noqa: BLE001 — filtered to OOM below
            if not _is_oom(exc):
                raise
            _DEVICE_POISONED = True
            LOG.warning(
                "chunk %s (%dx%d px) exhausted device memory; this "
                "process's device client is no longer usable — running "
                "remaining work in fresh subprocesses",
                prefix, chunk.nx_valid, chunk.ny_valid,
            )
    if aux_builder is not None and \
            aux_builder is not resolve_aux_builder(cfg):
        raise RuntimeError(
            "device OOM recovery needs a subprocess, but the injected "
            "aux_builder cannot be reconstructed there; re-run with "
            "smaller chunk_size"
        )
    rc, summary = _run_chunk_subprocess(cfg, chunk, prefix)
    if rc == 0:
        # Symmetric to the pre-split cleanup: a full-chunk success must
        # remove quarter outputs left by an earlier crashed split of the
        # same chunk, or mosaics double-read those pixels.
        _remove_outputs(cfg, [f"*_{prefix}-[abcd]*.tif"])
        return summary
    if rc == 124:
        # The worker was killed by the hang guard: transient-class by
        # construction (TimeoutError), so a scheduler-level RetryPolicy
        # re-attempts it — the kill already freed the wedged process.
        raise TimeoutError(
            f"chunk worker for {prefix} exceeded its wall-clock "
            "timeout and was killed"
        )
    if rc != OOM_EXIT_CODE:
        raise RuntimeError(
            f"chunk worker for {prefix} failed (rc={rc})"
        )
    if max_splits <= 0 or min(chunk.nx_valid, chunk.ny_valid) < 2:
        raise RuntimeError(
            f"chunk {prefix} exceeds device memory even at "
            f"{chunk.nx_valid}x{chunk.ny_valid} px (split limit reached)"
        )
    LOG.warning(
        "chunk %s (%dx%d px) exceeds device memory; splitting 2x2",
        prefix, chunk.nx_valid, chunk.ny_valid,
    )
    # The failed full-chunk attempts may have flushed partial rasters
    # under this prefix before dying; remove them so the quarter outputs
    # are the only files for these pixels (a downstream mosaic globbing
    # the prefix must not double-read stale data).
    _remove_outputs(cfg, [f"*_{prefix}.tif", f"*_{prefix}_unc.tif"])
    merged = {
        "prefix": prefix, "n_pixels": 0, "n_dates_assimilated": 0,
        "wall_s": 0.0, "oom_split": True,
    }
    any_ran = False
    for tag, sub in zip("abcd", split_chunk(chunk)):
        # Dash separator: a bare hex append would collide with larger
        # runs' chunk ids (prefix '1000' + 'a' == chunk '1000a'), and the
        # success-path cleanup glob could then delete a sibling chunk's
        # outputs.
        s = run_one_chunk_resilient(
            cfg, sub, f"{prefix}-{tag}", full_mask, geo, aux_builder,
            operator=operator, max_splits=max_splits - 1,
        )
        if s is not None:
            any_ran = True
            merged["n_pixels"] += s.get("n_pixels", 0)
            merged["n_dates_assimilated"] = max(
                merged["n_dates_assimilated"],
                s.get("n_dates_assimilated", 0),
            )
            merged["wall_s"] += s.get("wall_s", 0.0)
    return merged if any_ran else None


def run_config(
    cfg: RunConfig,
    aux_builder: Optional[Callable] = None,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    queue: bool = False,
    lease_ttl_s: Optional[float] = None,
) -> dict:
    """Chunked run over the whole state mask — the ``__main__`` of every
    reference driver, including the dask fan-out (serial loop and
    distributed execution are the same code path here;
    ``kafka_test_S2.py:196-205`` vs ``kafka_test_Py36.py:242-255``).

    ``queue=True`` replaces the static round-robin with the self-healing
    lease-based chunk queue (``shard.run_queue``): this process becomes
    one worker claiming from the shared ``output_folder`` queue, a dying
    worker's chunks are reclaimed by survivors after ``lease_ttl_s``,
    and ``num_processes``/``process_index`` are irrelevant — the queue
    needs no assignment (BASELINE.md "Multi-host queue")."""
    from ..resilience import RetryPolicy, faults
    from ..telemetry import (
        configure, flight_recorder, get_registry, live, slo,
        install_compile_listeners, tracing,
    )
    from ..utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    install_compile_listeners()
    if cfg.telemetry_dir:
        configure(cfg.telemetry_dir)
    # Chaos-run hook: KAFKA_TPU_FAULTS scripts deterministic failures at
    # the registered fault points (BASELINE.md "Fault tolerance").
    faults.install_from_env()
    # Crash forensics: unhandled exceptions, SIGTERM/SIGINT and unhealthy
    # probe verdicts dump crash_<ts>.json into the telemetry directory
    # (no-op without one — see telemetry.flight_recorder).
    recorder = flight_recorder.install(cfg.telemetry_dir)
    full_mask, geo = load_state_mask(cfg)
    ny, nx = full_mask.shape
    chunks = list(get_chunks(nx, ny, tuple(cfg.chunk_size)))
    # Keyed by prefix, not appended: queue-mode at-least-once execution
    # (commit retries, reclaims) may run a chunk twice.
    summaries = {}
    # One operator for ALL chunks — keeps the jitted solver's compile
    # cache warm across the chunk loop (see run_one_chunk).
    operator = cfg.make_operator()

    def run_one(chunk, prefix):
        s = run_one_chunk_resilient(
            cfg, chunk, prefix, full_mask, geo, aux_builder,
            operator=operator,
        )
        if s is not None:
            summaries[prefix] = s
            LOG.info("chunk %s: %s", prefix, json.dumps(s))

    # Fault-tolerance knobs ride RunConfig.extra["fault_tolerance"]:
    # {"chunk_attempts": 3, "backoff_s": 2.0, "quarantine": true,
    #  "chunk_deadline_s": 3600}.  Defaults keep fail-fast semantics.
    ft = dict((getattr(cfg, "extra", None) or {})
              .get("fault_tolerance") or {})
    attempts = int(ft.get("chunk_attempts", 1))
    retry_policy = RetryPolicy(
        max_attempts=attempts,
        base_delay=float(ft.get("backoff_s", 2.0)),
        multiplier=float(ft.get("backoff_multiplier", 2.0)),
        jitter=float(ft.get("jitter", 0.1)),
    ) if attempts > 1 else None
    deadline_s = ft.get("chunk_deadline_s")
    # One trace context for the whole run: chunk/window ids are pushed
    # below it, and the recorder guard dumps on the way out of a failure.
    with tracing.push(run_id=tracing.new_run_id()), recorder:
        # Fleet plane heartbeat: live_<host>_<pid>.json refreshed in the
        # background for operators watching mid-run (no-op without a
        # telemetry dir; the stop writes the clean-shutdown snapshot).
        live.start_publisher(role="queue_worker" if queue else "engine")
        # SLO evaluator (telemetry.slo): solver/quality/perf burn over
        # this run's registry, serving /alertz and alerts.jsonl.
        slo.start_engine()
        try:
            if queue:
                from ..shard.queue import DEFAULT_LEASE_TTL_S, run_queue

                stats = run_queue(
                    chunks, run_one, cfg.output_folder,
                    lease_ttl_s=(lease_ttl_s if lease_ttl_s
                                 else DEFAULT_LEASE_TTL_S),
                    retry_policy=retry_policy,
                    quarantine=bool(ft.get("quarantine", True)),
                    chunk_deadline_s=(
                        float(deadline_s) if deadline_s is not None
                        else None
                    ),
                    max_requeues=ft.get("max_requeues"),
                )
            else:
                stats = run_chunks(
                    chunks, run_one, cfg.output_folder,
                    num_processes=num_processes,
                    process_index=process_index,
                    retry_policy=retry_policy,
                    quarantine=bool(ft.get("quarantine", False)),
                    chunk_deadline_s=(
                        float(deadline_s) if deadline_s is not None
                        else None
                    ),
                )
        finally:
            slo.stop_engine()
            live.stop_publisher()
    stats["chunks_with_pixels"] = len(summaries)
    stats["pixels"] = int(
        sum(s["n_pixels"] for s in summaries.values())
    )
    stats["dates_assimilated"] = int(
        sum(s["n_dates_assimilated"] for s in summaries.values())
    )
    reg = get_registry()
    reg.emit("run_done", **stats)
    # Snapshot the run's metrics + trace timeline (no-op when no
    # telemetry_dir configured).
    reg.dump()
    return stats
