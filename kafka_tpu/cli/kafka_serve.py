"""``kafka-serve`` — the resident assimilation-as-a-service daemon.

Serves observation-date requests against warm per-tile filter state
(see BASELINE.md "Serving"): clients drop ``{"tile", "date"}`` JSON
files into ``<root>/inbox/`` (atomic rename; ``serve.submit_request``
does it for you) and read ``<root>/responses/<request_id>.json``.  A
new observation date costs only the grid windows after the tile's
newest checkpoint — an incremental predict/correct, not a full-series
rerun.

Robustness surface:

- admission control + load shedding against the bounded queue and the
  engine telemetry gauges (``--max-queue``, ``--max-writer-backlog``,
  ``--shed-unhealthy``): overload answers fast rejections;
- per-request deadlines (``--deadline-s``): expired requests are
  cancelled and counted, never silently dropped;
- SIGTERM = graceful drain (finish in-flight, reject new, exit 0);
  SIGKILL = crash, recovered on restart by replaying ``requests.jsonl``
  idempotently from the warm checkpoints;
- chaos-scriptable via ``KAFKA_TPU_FAULTS`` at the ``serve.admit`` /
  ``serve.solve`` / ``serve.respond`` fault points;
- bounded telemetry for a long-lived process (events.jsonl rotation,
  capped crash dumps).

This driver serves SYNTHETIC tiles (the chaos/bench harness, like
``run_synthetic``); production sources plug into the same
``AssimilationService`` programmatically with real ``TileSpec``s.

Usage:
    kafka-serve --root /tmp/serve --tiles 2 --operator identity &
    python -m tools.loadgen --root /tmp/serve --requests 64
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from . import add_telemetry_arg, make_console


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", required=True,
                    help="serve root (inbox/, responses/, requests.jsonl,"
                         " ckpt_<tile>/ live here)")
    ap.add_argument("--tiles", type=int, default=1,
                    help="number of synthetic tiles to serve "
                         "(tile0..tileN-1)")
    ap.add_argument("--ckpt-root", default=None,
                    help="directory holding the ckpt_<tile>/ checkpoint "
                         "sets (default: --root).  Replicas of an "
                         "elastic fleet SHARE this root so re-routing a "
                         "tile to another replica resumes it warm — "
                         "the checkpoint set is the canonical state")
    ap.add_argument("--operator", default="identity",
                    choices=("identity", "twostream", "wcm"))
    ap.add_argument("--ny", type=int, default=20)
    ap.add_argument("--nx", type=int, default=20)
    ap.add_argument("--days", type=int, default=16)
    ap.add_argument("--step", type=int, default=4,
                    help="time-grid step in days")
    ap.add_argument("--obs-every", type=int, default=2,
                    help="observation cadence in days")
    ap.add_argument("--scan-window", type=int, default=1,
                    help="temporal fusion window (1 = unfused, the "
                         "bit-exact serving configuration)")
    ap.add_argument("--batch-window-ms", type=float, default=5.0,
                    help="admission micro-window: hold a dequeued "
                         "request up to this long while shape-"
                         "compatible peers arrive, then serve the "
                         "group as ONE coalesced device launch "
                         "(bit-identical to sequential serving; "
                         "0 disables)")
    ap.add_argument("--max-batch", type=int, default=8,
                    help="coalesced-launch member cap")
    ap.add_argument("--compile-cache-dir", default=None,
                    help="persistent XLA compilation cache directory "
                         "(default: <root>/.xla_cache — per serve "
                         "root, so a restart finds its own programs)")
    ap.add_argument("--aot-buckets", default="1",
                    help="comma-separated batch sizes to AOT-compile "
                         "per shape bucket at startup (lower+compile "
                         "before the first request; with a warm "
                         "--compile-cache-dir the restart pays zero "
                         "compiles)")
    ap.add_argument("--no-aot", action="store_true",
                    help="skip the startup AOT bucket warm-up "
                         "(first requests pay the compiles)")
    ap.add_argument("--max-queue", type=int, default=16,
                    help="admission bound on the request queue; beyond "
                         "it requests are shed with reason queue_full")
    ap.add_argument("--max-writer-backlog", type=int, default=256,
                    help="shed when the async writer backlog gauge "
                         "exceeds this (0 disables)")
    ap.add_argument("--max-prefetch-depth", type=int, default=256,
                    help="shed when the prefetch queue-depth gauge "
                         "exceeds this (0 disables)")
    ap.add_argument("--no-shed-unhealthy", action="store_true",
                    help="keep admitting while the health probe verdict "
                         "is off-band")
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="default per-request wall-clock budget; "
                         "expired requests are cancelled and counted")
    ap.add_argument("--poll-interval-s", type=float, default=0.05,
                    help="inbox scan cadence")
    ap.add_argument("--exit-when-idle", action="store_true",
                    help="exit 0 once the journal is replayed and the "
                         "inbox/queue stay empty for --idle-grace-s "
                         "(one-shot recovery / batch mode)")
    ap.add_argument("--idle-grace-s", type=float, default=1.0)
    ap.add_argument("--events-rotate-mb", type=float, default=32.0,
                    help="rotate events.jsonl past this size "
                         "(keep-N segments; a daemon cannot afford "
                         "unbounded telemetry)")
    ap.add_argument("--events-keep", type=int, default=3,
                    help="rotated events.jsonl segments kept")
    ap.add_argument("--journal-rotate-mb", type=float, default=64.0,
                    help="compact requests.jsonl past this size: "
                         "answered-and-checkpointed entries rotate into "
                         "size-capped segments (0 disables; a resident "
                         "daemon cannot afford an unbounded journal)")
    ap.add_argument("--journal-keep", type=int, default=3,
                    help="rotated requests.jsonl segments kept")
    ap.add_argument("--http-port", type=int, default=0,
                    help="live metrics endpoint port (/metrics "
                         "Prometheus text, /healthz, /statusz with "
                         "sessions + queue depth + crash index; "
                         "0 = disabled)")
    ap.add_argument("--live-interval-s", type=float, default=None,
                    help="live_<host>_<pid>.json heartbeat cadence "
                         "(default 2s or $KAFKA_TPU_LIVE_INTERVAL_S)")
    ap.add_argument("--fleet-dir", default=None,
                    help="telemetry root holding the fleet's live "
                         "snapshots; the daemon refreshes the "
                         "kafka_fleet_dead_hosts gauge from it")
    ap.add_argument("--max-dead-hosts", type=int, default=None,
                    help="shed requests (reason fleet_degraded) while "
                         "the fleet view counts more dead hosts than "
                         "this (needs --fleet-dir)")
    ap.add_argument("--shed-quality-drift", action="store_true",
                    help="shed requests (reason quality_degraded) "
                         "while any quality drift sentinel is alarming "
                         "(kafka_quality_drift_active > 0); default "
                         "serves degraded answers labelled via the "
                         "response's quality field")
    ap.add_argument("--shed-slo", action="store_true",
                    help="shed requests (reason slo_burn) while any "
                         "PAGE-severity SLO alert is firing "
                         "(kafka_slo_alerts_firing, telemetry.slo); "
                         "default keeps admitting and lets the alert "
                         "page the operator")
    ap.add_argument("--slo-fast-window-s", type=float, default=None,
                    help="SLO fast (paging) burn-rate window "
                         "(default 300s)")
    ap.add_argument("--slo-slow-window-s", type=float, default=None,
                    help="SLO slow (warning) burn-rate window "
                         "(default 3600s)")
    ap.add_argument("--slo-interval-s", type=float, default=None,
                    help="SLO evaluation cadence (default 5s)")
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    return ap


def main(argv=None):
    from ..utils.compilation_cache import enable_compilation_cache

    args = build_parser().parse_args(argv)
    # Per-root cache by default: a daemon restart re-lowers the exact
    # same bucket programs, so every AOT compile after the first start
    # is a disk hit (min_compile_time_s=0 persists even the fast ones —
    # zero-miss restart is the contract, see BASELINE.md).
    enable_compilation_cache(
        cache_dir=(args.compile_cache_dir
                   or os.path.join(args.root, ".xla_cache")),
        min_compile_time_s=0.0,
    )
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )
    from ..telemetry import (
        configure, flight_recorder, get_registry,
        install_compile_listeners, tracing,
    )

    install_compile_listeners()
    if args.telemetry_dir:
        configure(
            args.telemetry_dir,
            events_rotate_bytes=int(args.events_rotate_mb * 1024 * 1024),
            events_keep=args.events_keep,
        )
    recorder = flight_recorder.install(args.telemetry_dir)
    from ..resilience import faults
    from ..serve import (
        AdmissionPolicy, AssimilationService, ServeDaemon, TileSession,
        make_synthetic_tile,
    )

    faults.install_from_env()
    os.makedirs(args.root, exist_ok=True)
    ckpt_root = args.ckpt_root or args.root
    sessions = {}
    for i in range(max(1, args.tiles)):
        name = f"tile{i}"
        spec = make_synthetic_tile(
            name, ckpt_dir=os.path.join(ckpt_root, f"ckpt_{name}"),
            operator=args.operator, ny=args.ny, nx=args.nx,
            days=args.days, step_days=args.step,
            obs_every=args.obs_every, scan_window=args.scan_window,
            seed=i,
        )
        sessions[name] = TileSession(spec)
    policy = AdmissionPolicy(
        max_queue_depth=args.max_queue,
        max_prefetch_queue_depth=(
            args.max_prefetch_depth if args.max_prefetch_depth > 0
            else None
        ),
        max_writer_backlog=(
            args.max_writer_backlog if args.max_writer_backlog > 0
            else None
        ),
        shed_when_unhealthy=not args.no_shed_unhealthy,
        max_dead_hosts=args.max_dead_hosts,
        shed_on_quality_drift=args.shed_quality_drift,
        shed_on_slo=args.shed_slo,
    )
    # AOT bucket warm-up: lower+compile every resident shape bucket
    # (solo program plus each --aot-buckets batch size) BEFORE the
    # daemon admits a request.  With a warm --compile-cache-dir the
    # whole pass is disk hits — the first request after a restart
    # never pays a compile (asserted in tests: zero
    # kafka_compile_cache_misses_total for declared buckets).
    from ..serve import batch as serve_batch

    aot_manifest = None
    if not args.no_aot:
        sizes = tuple(
            int(s) for s in str(args.aot_buckets).split(",") if s.strip()
        ) or (1,)
        aot_manifest = serve_batch.aot_compile_buckets(
            sessions, batch_sizes=sizes
        )
    service = AssimilationService(
        sessions, args.root, policy=policy,
        default_deadline_s=args.deadline_s,
        journal_rotate_bytes=(
            int(args.journal_rotate_mb * 1024 * 1024)
            if args.journal_rotate_mb > 0 else None
        ),
        journal_keep=args.journal_keep,
        batch_window_ms=args.batch_window_ms,
        max_batch=args.max_batch,
    )
    daemon = ServeDaemon(
        service, args.root,
        poll_interval_s=args.poll_interval_s,
        exit_when_idle=args.exit_when_idle,
        idle_grace_s=args.idle_grace_s,
        fleet_dir=args.fleet_dir,
    )

    def statusz():
        # The /statusz page's daemon-specific facts (read-only; handler
        # threads must never block on the serve path).
        return {
            "serve_root": os.path.abspath(args.root),
            "sessions": {
                name: {"serves": sess.serves}
                for name, sess in service.sessions.items()
            },
            "queue_depth": service.pending(),
            "draining": service.draining,
            "fleet_dir": args.fleet_dir,
            "serve_aot_buckets": aot_manifest,
        }

    from ..telemetry import live, slo
    from ..telemetry.httpd import maybe_start

    reg = get_registry()
    slo_kwargs = {
        k: v for k, v in (
            ("fast_window_s", args.slo_fast_window_s),
            ("slow_window_s", args.slo_slow_window_s),
            ("interval_s", args.slo_interval_s),
        ) if v is not None
    }
    with tracing.push(run_id=tracing.new_run_id()), recorder:
        # Fleet plane: heartbeat snapshots + the optional live HTTP
        # endpoint, up for exactly as long as the daemon serves.
        live.update_status(serve_root=os.path.abspath(args.root),
                           tiles=sorted(sessions))
        live.start_publisher(role="serve",
                             interval_s=args.live_interval_s)
        # SLO evaluator (telemetry.slo): burn-rate alerting over the
        # daemon's own registry, serving /alertz and the slo_burn
        # shed signal for exactly as long as the daemon serves.
        slo.start_engine(**slo_kwargs)
        httpd = maybe_start(args.http_port, status_provider=statusz,
                            role="serve")
        try:
            summary = daemon.run()
        finally:
            slo.stop_engine()
            live.stop_publisher()
            if httpd is not None:
                httpd.close()
    # Request-level errors completed the run but lost work — surface the
    # partial-success exit code the other drivers use.
    summary["failed"] = summary["errors"]
    summary["http_port"] = None if httpd is None else httpd.port
    summary["telemetry_dir"] = reg.dump()
    print(json.dumps(summary))
    return summary


console = make_console(main)


if __name__ == "__main__":
    sys.exit(console())
