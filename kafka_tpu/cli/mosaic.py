"""Mosaic per-chunk outputs into single-tile rasters.

A chunked/distributed run (and the OOM splitter) writes one GeoTIFF per
parameter per timestep PER CHUNK PREFIX — the reference leaves its users
with exactly the same pile of prefixed files (``hex(chunk)`` prefixes,
``/root/reference/kafka_test_Py36.py:164-166``) and no tool.  This one
stitches them: chunk placement comes from each file's own geotransform
relative to the mosaic grid, so quarters from an OOM split and whole
chunks compose identically.

Usage:
    python -m kafka_tpu.cli.mosaic <folder> [--param lai ...]
        [--date A2017183 ...] [--include-unc] [--outdir <folder>]

Without ``--param``/``--date`` every parameter and timestep discovered in
the folder is mosaicked.  Output naming: ``{param}_{date}[_unc].tif`` in
``--outdir`` (default ``<folder>/mosaic``).
"""

from __future__ import annotations

import argparse
import glob
import json
import logging
import os
import re
from collections import defaultdict
from typing import Dict, List, Tuple

import numpy as np

from ..io.geotiff import GeoInfo, read_geotiff, read_info, write_geotiff
from . import make_console

LOG = logging.getLogger(__name__)

#: ``{param}_{A%Y%j}_{prefix}[_unc].tif`` — prefix is the chunk id with
#: optional ``-a``..``-d`` quarter suffixes from OOM splits (the dash
#: separator keeps hex chunk ids unambiguous: chunk ``1000a`` vs split
#: quarter ``1000-a``; recursive splits nest as ``-a-c``...).
_NAME = re.compile(
    r"^(?P<param>.+)_(?P<date>A\d{7})_(?P<prefix>[0-9a-fx]+(?:-[abcd])*)"
    r"(?P<unc>_unc)?\.tif$"
)


def discover(folder: str) -> Dict[Tuple[str, str, bool], List[str]]:
    """Group chunk files by (param, date, is_unc)."""
    groups: Dict[Tuple[str, str, bool], List[str]] = defaultdict(list)
    for path in sorted(glob.glob(os.path.join(folder, "*.tif"))):
        m = _NAME.match(os.path.basename(path))
        if m:
            groups[(
                m.group("param"), m.group("date"), bool(m.group("unc"))
            )].append(path)
    return dict(groups)


def mosaic_files(files: List[str], out_path: str,
                 like=None) -> Tuple[int, int]:
    """Stitch chunk rasters into one grid by their geotransforms.

    All inputs must share resolution and CRS (they come from one run).

    ``like`` — optional raster (typically the run's state mask) whose
    grid becomes the mosaic grid.  Without it the extent is the bounding
    box of the files present, which SHRINKS when edge chunks had empty
    masks and wrote nothing; with it the product always aligns with the
    full tile, and the coverage check becomes exact: a warning fires
    only where the like-raster has VALID (non-zero) pixels that no chunk
    file covers — genuinely missing data, not benign empty chunks.

    Returns the mosaic (height, width)."""
    infos = [read_info(f) for f in files]
    gts = [i.geo.geotransform for i in infos]
    rx, ry = gts[0][1], gts[0][5]

    def crs_key(geo: GeoInfo):
        # EPSG is authoritative when present; projection-name strings
        # are a fallback (files from one run may carry one or the other).
        return geo.epsg if geo.epsg else geo.projection

    crs0 = crs_key(infos[0].geo)
    for f, info, gt in zip(files, infos, gts):
        if (gt[1], gt[5]) != (rx, ry):
            raise ValueError(
                f"{f}: resolution {(gt[1], gt[5])} != {(rx, ry)}"
            )
        if crs_key(info.geo) != crs0:
            raise ValueError(
                f"{f}: CRS {crs_key(info.geo)!r} != {crs0!r} — "
                "mixed-projection chunks cannot share a grid"
            )
    like_arr = None
    if like is not None:
        # ``like`` may be a path or a preloaded (array, TiffInfo) pair
        # (main() reads the raster once for all output groups).
        if isinstance(like, str):
            like_arr, like_info = read_geotiff(like)
        else:
            like_arr, like_info = like
        lgt = like_info.geo.geotransform
        if (lgt[1], lgt[5]) != (rx, ry):
            raise ValueError(
                f"--like: resolution {(lgt[1], lgt[5])} != "
                f"chunk resolution {(rx, ry)}"
            )
        if crs_key(like_info.geo) != crs0:
            raise ValueError(
                f"--like: CRS {crs_key(like_info.geo)!r} != chunk CRS "
                f"{crs0!r} — offsets computed across projections would "
                "be meaningless"
            )
        x0, y0 = lgt[0], lgt[3]
        width, height = like_info.width, like_info.height
    else:
        x0 = min(gt[0] for gt in gts)
        y0 = (max(gt[3] for gt in gts) if ry < 0
              else min(gt[3] for gt in gts))
        width = height = None
    cols = [int(round((gt[0] - x0) / rx)) for gt in gts]
    rows = [int(round((gt[3] - y0) / ry)) for gt in gts]
    if width is None:
        width = max(c + i.width for c, i in zip(cols, infos))
        height = max(r + i.height for r, i in zip(rows, infos))
    out = np.zeros((height, width), np.float32)
    covered = np.zeros((height, width), bool)
    overlap_px = 0
    for path, info, r, c in zip(files, infos, rows, cols):
        if r < 0 or c < 0 or r + info.height > height \
                or c + info.width > width:
            raise ValueError(
                f"{path} lies outside the mosaic grid "
                f"(offset {r},{c}, size {info.height}x{info.width} in "
                f"{height}x{width})"
            )
        arr, _ = read_geotiff(path)
        region = covered[r:r + info.height, c:c + info.width]
        overlap_px += int(region.sum())
        out[r:r + info.height, c:c + info.width] = arr
        region[...] = True
    if overlap_px:
        # Duplicate coverage means conflicting generations of files for
        # the same pixels (e.g. a stale whole-chunk raster next to its
        # OOM-split quarters): last writer wins in the product, which is
        # never the silent outcome the user wants.
        LOG.warning(
            "%s: %d px covered by more than one chunk file — stale and "
            "fresh chunk generations may be mixed (last file wins)",
            out_path, overlap_px,
        )
    if like_arr is not None:
        missing = int(((like_arr != 0) & ~covered).sum())
        if missing:
            LOG.warning(
                "%s: %d valid pixels of the --like raster are covered "
                "by no chunk file — missing or half-written chunks; "
                "those pixels are zero",
                out_path, missing,
            )
    elif not covered.all():
        # Without an authoritative grid this is only a hint: chunks whose
        # state mask was empty legitimately wrote no file.
        LOG.info(
            "%s: chunk files cover %d of %d px (empty-mask chunks are a "
            "benign cause; pass --like <state_mask> for an exact check)",
            out_path, int(covered.sum()), height * width,
        )
    geo = GeoInfo(
        geotransform=(x0, rx, gts[0][2], y0, gts[0][4], ry),
        projection=infos[0].geo.projection,
        epsg=infos[0].geo.epsg,
    )
    write_geotiff(out_path, out, geo)
    return height, width


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("folder")
    ap.add_argument("--param", action="append", default=None)
    ap.add_argument("--date", action="append", default=None)
    ap.add_argument("--include-unc", action="store_true")
    ap.add_argument("--like", default=None,
                    help="raster (e.g. the state mask) defining the "
                         "mosaic grid and enabling an exact coverage "
                         "check")
    ap.add_argument("--outdir", default=None)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )
    outdir = args.outdir or os.path.join(args.folder, "mosaic")
    os.makedirs(outdir, exist_ok=True)

    groups = discover(args.folder)
    if not groups:
        raise SystemExit(f"no chunk outputs found in {args.folder}")
    like = read_geotiff(args.like) if args.like else None
    written = []
    for (param, date, unc), files in sorted(groups.items()):
        if args.param and param not in args.param:
            continue
        if args.date and date not in args.date:
            continue
        if unc and not args.include_unc:
            continue
        name = f"{param}_{date}{'_unc' if unc else ''}.tif"
        out_path = os.path.join(outdir, name)
        h, w = mosaic_files(files, out_path, like=like)
        LOG.info("%s: %d chunks -> %dx%d", name, len(files), h, w)
        written.append({"file": name, "chunks": len(files),
                        "shape": [h, w]})
    print(json.dumps({"outdir": outdir, "mosaics": written}))
    return written


console = make_console(main)


if __name__ == "__main__":
    main()
