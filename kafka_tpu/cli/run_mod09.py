"""MOD09 directional-reflectance driver — kernel-weight retrieval.

The observation path the reference sketches but never wires into a driver
(``/root/reference/kafka/input_output/observations.py:89-147``): MOD09GA
clear-sky directional reflectances assimilated into a per-pixel, per-band
Ross-Li kernel-weight state (21 parameters) with the linear
``KernelsOperator`` — the MCD43 kernel inversion recast as a temporal
filter.  Information-filter propagation accumulates angular sampling
across dates (the temporal replacement for MCD43's 16-day window fit);
the weak kernel prior seeds the initial state only.

Usage:
    python -m kafka_tpu.cli.run_mod09 --data-folder /path/mod09 \
        --state-mask mask.tif --outdir /tmp/kafka_mod09
"""

from __future__ import annotations

import argparse
import datetime
import json
import logging

from ..engine.config import RunConfig
from ..engine.priors import KERNEL_PARAMETER_LIST
from . import add_telemetry_arg, make_console
from .drivers import run_config


def default_config() -> RunConfig:
    return RunConfig(
        parameter_list=KERNEL_PARAMETER_LIST,
        start=datetime.datetime(2017, 6, 1),
        end=datetime.datetime(2017, 6, 30),
        step_days=1,
        operator="kernels",
        propagator="information_filter",
        prior=None,
        initial_prior="kernels",
        q_diag=[0.0] * 21,
        chunk_size=(256, 256),    # kafka_test_Py36.py:241 chunking
        observations="mod09",
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default=None,
                    help="RunConfig JSON overriding the defaults")
    ap.add_argument("--data-folder", default=None)
    ap.add_argument("--state-mask", default=None)
    ap.add_argument("--outdir", default=None)
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )

    cfg = RunConfig.load(args.config) if args.config else default_config()
    if args.data_folder:
        cfg.data_folder = args.data_folder
    if args.state_mask:
        cfg.state_mask = args.state_mask
    if args.outdir:
        cfg.output_folder = args.outdir
    if args.telemetry_dir:
        cfg.telemetry_dir = args.telemetry_dir

    stats = run_config(cfg)
    print(json.dumps(stats))
    return stats


console = make_console(main)


if __name__ == "__main__":
    main()
