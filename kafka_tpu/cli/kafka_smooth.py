"""Offline reanalysis driver: RTS-smooth a run's checkpoint chain.

Walks a completed (or merely resumable) forward run's checkpoint folder
newest -> oldest, runs the fixed-interval RTS backward pass
(``kafka_tpu.smoother``), and writes the smoothed product alongside the
filter's: ``{param}_{A%Y%j}_smoothed.tif`` + ``..._smoothed_unc.tif``
per date, plus the smoother's QA band
(``solver_qa_{A%Y%j}_smoothed.tif``) and ``smoothed`` quality-ledger
records (``quality_report`` scores the passes separately).

Usage:
    python -m kafka_tpu.cli.kafka_smooth --ckpt-dir /tmp/out/ckpt \
        --outdir /tmp/out --operator identity --ny 204 --nx 235

The chain must store the analysis in information form (every checkpoint
the engine writes does).  Checkpoints carrying the forecast sidecar
smooth exactly; pre-sidecar sets fall back to re-deriving the forecast
through ``--propagator``/``--q`` — pass the forward run's configuration
for an exact fallback.  The mask/grid arguments must reproduce the
forward run's (same ``--mask`` or ``--ny/--nx``), or the chain's pixel
rows will not scatter back onto the raster.

The summary JSON includes a ``x_sha256`` per date — the digest the
``smoothed=true`` serve path also reports, so offline and served
reanalysis are comparable bit-for-bit.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

import numpy as np

from ..core import (
    propagate_information_filter,
    propagate_information_filter_approx,
    propagate_standard_kalman,
)
from ..engine import Checkpointer, make_pixel_gather
from ..engine.priors import TIP_PARAMETER_LIST
from ..io import GeoTIFFOutput, read_geotiff
from ..smoother import SmootherError, smooth_checkpoints, state_sha256
from ..testing.fixtures import DEFAULT_GEO, make_pivot_mask
from . import add_telemetry_arg, make_console

#: parameter names per operator, matching ``run_synthetic``'s problems.
_OPERATOR_PARAMS = {
    "identity": ("a", "b"),
    "twostream": TIP_PARAMETER_LIST,
    "wcm": ("lai", "sm"),
}

_PROPAGATORS = {
    "information": propagate_information_filter,
    "approx": propagate_information_filter_approx,
    "standard": propagate_standard_kalman,
}


def main(argv=None):
    from ..utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt-dir", required=True,
                    help="the forward run's checkpoint folder")
    ap.add_argument("--ckpt-prefix", default="",
                    help="checkpoint filename prefix (chunked runs)")
    ap.add_argument("--shards", type=int, default=1,
                    help="the forward run's checkpoint shard count")
    ap.add_argument("--outdir", default=None,
                    help="write *_smoothed.tif products here (omit for "
                         "a summary-only pass)")
    ap.add_argument("--operator", default="identity",
                    choices=sorted(_OPERATOR_PARAMS),
                    help="names the output parameters like run_synthetic")
    ap.add_argument("--params", default=None,
                    help="comma-separated parameter names (overrides "
                         "--operator)")
    ap.add_argument("--mask", default=None,
                    help="GeoTIFF state mask of the forward run "
                         "(default: generated pivots)")
    ap.add_argument("--ny", type=int, default=204)
    ap.add_argument("--nx", type=int, default=235)
    ap.add_argument("--propagator", default="information",
                    choices=sorted(_PROPAGATORS),
                    help="fallback propagator for sidecar-less "
                         "checkpoints (match the forward run)")
    ap.add_argument("--q", type=float, default=1e-3,
                    help="fallback trajectory uncertainty diagonal "
                         "(match the forward run)")
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )
    from ..telemetry import (
        configure, flight_recorder, get_registry, quality,
        install_compile_listeners, tracing,
    )

    install_compile_listeners()
    if args.telemetry_dir:
        configure(args.telemetry_dir)
    recorder = flight_recorder.install(args.telemetry_dir)

    ck = Checkpointer(args.ckpt_dir, prefix=args.ckpt_prefix,
                      n_shards=max(1, args.shards))
    t0 = time.time()
    with tracing.push(run_id=tracing.new_run_id()), recorder:
        try:
            result = smooth_checkpoints(
                ck, q_diag=np.float32(args.q),
                state_propagator=_PROPAGATORS[args.propagator],
            )
        except SmootherError as exc:
            print(f"kafka-smooth: {exc}", file=sys.stderr)
            return {"failed": 1, "error": str(exc)}

        t_total, n_pix, p = result.x_smoothed.shape
        if args.params:
            params = tuple(s for s in args.params.split(",") if s)
        else:
            params = tuple(_OPERATOR_PARAMS[args.operator])[:p]
        if len(params) != p:
            print(
                f"kafka-smooth: chain stores {p} parameters but "
                f"{len(params)} names were given ({params})",
                file=sys.stderr,
            )
            return {"failed": 1, "error": "parameter-count mismatch"}

        reg = get_registry()
        ledger = quality.get_ledger(reg)
        prefix = args.ckpt_prefix.rstrip("_") or None
        dates = {}
        for t, ts in enumerate(result.timesteps):
            dates[ts.isoformat()] = {
                "x_sha256": state_sha256(result.x_smoothed[t]),
                "sigma_shrink": [
                    round(v, 6) for v in result.sigma_shrink(t)
                ],
                "rederived": ts in result.rederived,
            }
            ledger.record_smoothed(
                ts.date().isoformat(), result.sigma_shrink(t),
                n_valid=n_pix, prefix=prefix,
            )

        written = 0
        if args.outdir:
            written = _write_outputs(args, result, params, prefix)

        summary = {
            "windows": t_total,
            "n_pixels": n_pix,
            "rederived": len(result.rederived),
            "skipped": len(result.skipped),
            "dates": dates,
            "outputs_written": written,
            "outdir": args.outdir,
            "wall_s": round(time.time() - t0, 3),
        }
        reg.emit(
            "smooth_done", windows=t_total, rederived=len(result.rederived),
            skipped=len(result.skipped), outputs_written=written,
        )
        summary["telemetry_dir"] = reg.dump()
    print(json.dumps(summary))
    return summary


def _write_outputs(args, result, params, prefix) -> int:
    """Scatter the smoothed planes back onto the forward run's raster
    grid and write the ``*_smoothed.tif`` product set."""
    if args.mask:
        mask_arr, info = read_geotiff(args.mask)
        mask = mask_arr.astype(bool)
        geo = info.geo
    else:
        mask = make_pivot_mask(args.ny, args.nx)
        geo = DEFAULT_GEO
    gather = make_pixel_gather(mask)
    n_pix = result.x_smoothed.shape[1]
    if gather.n_pad != n_pix:
        raise SystemExit(
            f"kafka-smooth: mask yields {gather.n_pad} padded pixels "
            f"but the chain stores {n_pix} — pass the forward run's "
            "--mask/--ny/--nx"
        )
    out_prefix = f"{prefix}_smoothed" if prefix else "smoothed"
    os.makedirs(args.outdir, exist_ok=True)
    output = GeoTIFFOutput(
        params, geo.geotransform, geo.projection, args.outdir,
        prefix=out_prefix, epsg=geo.epsg, async_writes=True,
    )
    try:
        for t, ts in enumerate(result.timesteps):
            output.dump_data(ts, result.x_smoothed[t],
                             result.p_inv_diag[t], gather, params)
            output.dump_qa(ts, result.qa[t], gather)
    finally:
        output.close()
    return len([
        f for f in os.listdir(args.outdir)
        if f.endswith("_smoothed.tif") or f.endswith("_smoothed_unc.tif")
    ])


console = make_console(main)


if __name__ == "__main__":
    sys.exit(console())
