"""Convert the reference's gp_emulator pickle artifacts into .npz banks.

Reference users carry directories of per-geometry emulator pickles
(``prosail_..._{vza}_{sza}_{raa}.pkl`` — dicts of per-band
``gp_emulator.GaussianProcess`` objects,
``/root/reference/kafka/input_output/Sentinel2_Observations.py:133-159``).
This tool converts them once into plain ``.npz`` banks (stacked
``GPParams``, no foreign classes, instant loads); ``kafka-tpu-s2
--emulators <folder>`` then runs the S2 assimilation through those
emulators exactly as the reference would — no PROSAIL physics operator
involved.

Usage:
    kafka-tpu-import-emulators /path/emulator_pickles /path/banks_out
"""

from __future__ import annotations

import argparse
import glob
import logging
import os

from . import make_console

LOG = logging.getLogger(__name__)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("src", help="directory of gp_emulator pickles")
    ap.add_argument("dst", help="output directory for .npz banks")
    ap.add_argument("--pattern", default="*.pkl")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )
    from ..obsops.gp_import import (
        geometry_from_filename,
        load_emulator_bank_file,
        save_bank_npz,
    )

    os.makedirs(args.dst, exist_ok=True)
    n_done = 0
    for path in sorted(
        glob.glob(os.path.join(args.src, args.pattern))
    ):
        try:
            sza, vza, raa = geometry_from_filename(path)
        except ValueError:
            LOG.warning("skipping %s: no _vza_sza_raa geometry in name",
                        path)
            continue
        bank = load_emulator_bank_file(path)
        base = os.path.splitext(os.path.basename(path))[0]
        out = os.path.join(args.dst, f"{base}.npz")
        save_bank_npz(out, bank)
        LOG.info("%s -> %s (sza=%g vza=%g raa=%g)", path, out, sza, vza,
                 raa)
        n_done += 1
    if n_done == 0:
        raise SystemExit(
            f"no emulator pickles matching {args.pattern} in {args.src}"
        )
    print(f"converted {n_done} emulator bank(s) into {args.dst}")
    return 0


console = make_console(main)

if __name__ == "__main__":
    raise SystemExit(main())
