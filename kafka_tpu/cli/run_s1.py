"""Sentinel-1 SAR-only assimilation driver (Water-Cloud Model).

The reference ships the analytic WCM operator and an S1 sigma0 reader but
never wires them into a driver (``/root/reference/kafka/
observation_operators/sar_forward_model.py``,
``input_output/Sentinel1_Observations.py`` — both unused by the three
shipped scripts).  This driver completes that path: a 2-parameter
(LAI, soil moisture) state retrieved from dual-pol VV/VH backscatter time
series with the per-pixel incidence angle the reference left as a TODO,
information-filter propagation between acquisitions.

Usage:
    python -m kafka_tpu.cli.run_s1 --data-folder /path/s1_ncs \
        --state-mask mask.tif --outdir /tmp/kafka_s1
"""

from __future__ import annotations

import argparse
import datetime
import json
import logging

from ..engine.config import RunConfig
from ..engine.priors import WCM_PARAMETER_LIST
from . import add_telemetry_arg, make_console
from .drivers import run_config


def default_config() -> RunConfig:
    """SAR-only defaults: 2-param WCM state, broad prior seeding the
    initial state, information filter carrying it between acquisitions
    (soil moisture decorrelates fast — larger Q)."""
    return RunConfig(
        parameter_list=WCM_PARAMETER_LIST,
        start=datetime.datetime(2017, 7, 1),
        end=datetime.datetime(2017, 7, 31),
        step_days=3,
        operator="wcm",
        propagator="information_filter",
        prior=None,
        initial_prior="wcm",
        q_diag=[5e-3, 2e-2],
        chunk_size=(256, 256),
        observations="sentinel1",
    )


def _enl_arg(text: str):
    """'auto' or a positive look count — ENL <= 0 would silently
    zero-weight every observation (sigma -> inf)."""
    if text == "auto":
        return "auto"
    try:
        value = float(text)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--enl must be 'auto' or a number, got {text!r}"
        )
    if value <= 0:
        raise argparse.ArgumentTypeError(
            f"--enl must be positive, got {value}"
        )
    return value


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default=None,
                    help="RunConfig JSON overriding the defaults")
    ap.add_argument("--data-folder", default=None, help="S1 NetCDF folder")
    ap.add_argument("--state-mask", default=None)
    ap.add_argument("--outdir", default=None)
    ap.add_argument("--enl", default=None, type=_enl_arg,
                    help="equivalent number of looks for speckle-"
                         "statistics uncertainty: a number, 'auto' "
                         "(estimate per scene from homogeneous-block "
                         "statistics), or omit for the file attribute / "
                         "5%% relative placeholder")
    ap.add_argument("--noise-floor", type=float, default=None,
                    help="noise-equivalent sigma0 (linear power) added "
                         "in quadrature to the speckle term")
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )

    cfg = RunConfig.load(args.config) if args.config else default_config()
    if args.data_folder:
        cfg.data_folder = args.data_folder
    if args.state_mask:
        cfg.state_mask = args.state_mask
    if args.outdir:
        cfg.output_folder = args.outdir
    if args.enl is not None:
        cfg.extra["s1_enl"] = args.enl
    if args.noise_floor is not None:
        cfg.extra["s1_noise_floor"] = args.noise_floor
    if args.telemetry_dir:
        cfg.telemetry_dir = args.telemetry_dir

    stats = run_config(cfg)
    print(json.dumps(stats))
    return stats


console = make_console(main)


if __name__ == "__main__":
    main()
