"""Single-chunk subprocess worker for device-OOM recovery.

On this class of TPU runtime, one RESOURCE_EXHAUSTED poisons the process's
device client permanently (every later allocation fails, even 1 MB —
measured), so OOM recovery cannot happen in-process: the failed chunk's
quarters must run in fresh processes with their own clients.  This module
is that fresh process: it runs exactly one chunk via ``run_one_chunk``
and reports the summary as one JSON line on stdout.

Exit codes: 0 success (JSON on stdout; ``null`` for an empty-mask chunk),
17 device OOM (the parent splits and retries), anything else = real error
(propagated by the parent).

Usage (emitted by ``run_one_chunk_resilient`` — not user-facing):
    python -m kafka_tpu.cli.chunk_worker <config.json> <x0> <y0> \
        <nx_valid> <ny_valid> <chunk_no> <prefix>
"""

from __future__ import annotations

import json
import os
import sys

OOM_EXIT_CODE = 17


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    cfg_path, x0, y0, nx, ny, chunk_no, prefix = argv
    from ..engine.config import RunConfig
    from ..io.tiling import Chunk
    from ..telemetry import (
        configure, flight_recorder, get_registry,
        install_compile_listeners, tracing,
    )
    from .drivers import (
        _is_oom,
        load_state_mask,
        resolve_aux_builder,
        run_one_chunk,
    )
    from ..utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    install_compile_listeners()
    # Chaos specs propagate into chunk workers through the environment,
    # so a KAFKA_TPU_FAULTS run exercises the subprocess path too (call
    # counters are per-process — spec call numbers are worker-local).
    from ..resilience import faults

    faults.install_from_env()
    cfg = RunConfig.load(cfg_path)
    # Per-chunk telemetry subdirectory: this fresh process must not
    # interleave its events/trace with the parent scheduler's files.
    tel_dir = None
    if cfg.telemetry_dir:
        tel_dir = os.path.join(cfg.telemetry_dir, f"chunk_{prefix}")
        configure(tel_dir)
    recorder = flight_recorder.install(tel_dir)
    chunk = Chunk(int(x0), int(y0), int(nx), int(ny), int(chunk_no))
    full_mask, geo = load_state_mask(cfg)
    # new_run_id() picks up KAFKA_TPU_RUN_ID from the parent scheduler,
    # so this worker's spans and crash dumps correlate with its trace.
    with tracing.push(run_id=tracing.new_run_id(), chunk_id=prefix):
        try:
            with recorder:
                summary = run_one_chunk(
                    cfg, chunk, prefix, full_mask, geo,
                    resolve_aux_builder(cfg),
                )
        except Exception as exc:  # noqa: BLE001 — classified for parent
            if _is_oom(exc):
                print(str(exc)[:500], file=sys.stderr)
                return OOM_EXIT_CODE
            raise
    get_registry().dump()
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
