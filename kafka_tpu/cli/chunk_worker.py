"""Chunk worker subprocess: one chunk (OOM recovery) or a whole queue.

**Single-chunk mode** (positional args — emitted by
``run_one_chunk_resilient``, not user-facing): on this class of TPU
runtime, one RESOURCE_EXHAUSTED poisons the process's device client
permanently (every later allocation fails, even 1 MB — measured), so OOM
recovery cannot happen in-process: the failed chunk's quarters must run
in fresh processes with their own clients.  This module is that fresh
process: it runs exactly one chunk via ``run_one_chunk`` and reports the
summary as one JSON line on stdout.

Exit codes: 0 success (JSON on stdout; ``null`` for an empty-mask chunk),
17 device OOM (the parent splits and retries), anything else = real error
(propagated by the parent).

    python -m kafka_tpu.cli.chunk_worker <config.json> <x0> <y0> \
        <nx_valid> <ny_valid> <chunk_no> <prefix>

**Queue mode** (``--queue`` — the ROADMAP's "per-host worker over a
shared chunk queue"): the process becomes one self-healing worker
claiming chunks from the config's ``output_folder`` via lease files
(``shard.run_queue`` — BASELINE.md "Multi-host queue").  Run one per
host against a shared filesystem; a worker that dies has its chunks
reclaimed by the survivors.  ``--num-workers N`` spawns a local
N-process fleet from this one command:

    python -m kafka_tpu.cli.chunk_worker --queue config.json \
        --lease-ttl-s 30 --num-workers 4

Queue-mode exit codes: 0 all chunks done (or a clean SIGTERM drain), 75
when chunks were quarantined (partial success — rerun after fixing).
"""

from __future__ import annotations

import json
import os
import sys

OOM_EXIT_CODE = 17


def _queue_main(argv) -> int:
    """``--queue`` worker mode (see module docstring)."""
    import argparse
    import subprocess

    ap = argparse.ArgumentParser(
        prog="chunk_worker --queue",
        description="self-healing queue worker over a RunConfig",
    )
    ap.add_argument("config", help="RunConfig JSON")
    ap.add_argument("--lease-ttl-s", type=float, default=None,
                    help="heartbeat-lease TTL; a worker silent this long "
                         "is presumed dead and its chunk is reclaimed")
    ap.add_argument("--num-workers", type=int, default=1,
                    help="local fleet size (N>1 spawns N single-worker "
                         "subprocesses of this command and waits)")
    ap.add_argument("--http-port", type=int, default=0,
                    help="live metrics endpoint port (/metrics /healthz "
                         "/statusz; 0 = disabled; a local fleet gives "
                         "worker i port+i)")
    args = ap.parse_args(argv)

    if args.num_workers > 1:
        cmd = [sys.executable, "-m", "kafka_tpu.cli.chunk_worker",
               "--queue", args.config, "--num-workers", "1"]
        if args.lease_ttl_s is not None:
            cmd += ["--lease-ttl-s", str(args.lease_ttl_s)]
        env = dict(os.environ)
        # All workers join one trace: new_run_id() picks this up.
        env.setdefault("KAFKA_TPU_RUN_ID", os.urandom(6).hex())
        procs = []
        for i in range(args.num_workers):
            worker_cmd = list(cmd)
            if args.http_port:
                # One endpoint per worker process.
                worker_cmd += ["--http-port", str(args.http_port + i)]
            procs.append(subprocess.Popen(worker_cmd, env=env))
        rcs = [p.wait() for p in procs]
        hard = [rc for rc in rcs if rc not in (0, 75)]
        if hard:
            return hard[0]
        return 75 if 75 in rcs else 0

    from ..engine.config import RunConfig
    from ..telemetry.httpd import maybe_start
    from .drivers import resolve_aux_builder, run_config

    cfg = RunConfig.load(args.config)
    httpd = maybe_start(args.http_port, role="queue_worker")
    try:
        stats = run_config(
            cfg, resolve_aux_builder(cfg), queue=True,
            lease_ttl_s=args.lease_ttl_s,
        )
    finally:
        if httpd is not None:
            httpd.close()
    print(json.dumps(stats))
    if stats.get("failed"):
        from ..resilience import EXIT_PARTIAL_SUCCESS

        return EXIT_PARTIAL_SUCCESS
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--queue" in argv:
        argv.remove("--queue")
        return _queue_main(argv)
    cfg_path, x0, y0, nx, ny, chunk_no, prefix = argv
    from ..engine.config import RunConfig
    from ..io.tiling import Chunk
    from ..telemetry import (
        configure, flight_recorder, get_registry, live, slo,
        install_compile_listeners, tracing,
    )
    from .drivers import (
        _is_oom,
        load_state_mask,
        resolve_aux_builder,
        run_one_chunk,
    )
    from ..utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    install_compile_listeners()
    # Chaos specs propagate into chunk workers through the environment,
    # so a KAFKA_TPU_FAULTS run exercises the subprocess path too (call
    # counters are per-process — spec call numbers are worker-local).
    from ..resilience import faults

    faults.install_from_env()
    cfg = RunConfig.load(cfg_path)
    # Per-chunk telemetry subdirectory: this fresh process must not
    # interleave its events/trace with the parent scheduler's files.
    tel_dir = None
    if cfg.telemetry_dir:
        tel_dir = os.path.join(cfg.telemetry_dir, f"chunk_{prefix}")
        configure(tel_dir)
    recorder = flight_recorder.install(tel_dir)
    chunk = Chunk(int(x0), int(y0), int(nx), int(ny), int(chunk_no))
    full_mask, geo = load_state_mask(cfg)
    # new_run_id() picks up KAFKA_TPU_RUN_ID from the parent scheduler,
    # so this worker's spans and crash dumps correlate with its trace.
    with tracing.push(run_id=tracing.new_run_id(), chunk_id=prefix):
        live.start_publisher(role="chunk_worker")
        # SLO evaluator (telemetry.slo): solver/quality burn over this
        # worker's registry, alerts.jsonl next to its chunk telemetry.
        slo.start_engine()
        try:
            with recorder:
                summary = run_one_chunk(
                    cfg, chunk, prefix, full_mask, geo,
                    resolve_aux_builder(cfg),
                )
        except Exception as exc:  # noqa: BLE001 — classified for parent
            if _is_oom(exc):
                print(str(exc)[:500], file=sys.stderr)
                return OOM_EXIT_CODE
            raise
        finally:
            slo.stop_engine()
            live.stop_publisher()
    get_registry().dump()
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
