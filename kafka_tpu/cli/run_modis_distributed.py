"""MODIS BHR distributed driver — chunked prior-blend configuration.

TPU-native equivalent of ``/root/reference/kafka_test_Py36.py:147-255``:
the same MODIS BHR pipeline chunked 256x256 over the tile, prior-only
advance (``state_propagation=None`` + JRC prior, Q[TeLAI]=0.025), each
chunk an independent restartable unit with prefixed outputs.  Where the
reference fans chunks over a dask cluster, here ``shard.run_chunks``
round-robins them over ``jax.distributed`` processes — run one process per
host (``--num-processes``/``--process-index`` for external launchers) and
each executes only its own pending chunks, with ``.done`` markers making
restarts cheap.

Usage:
    python -m kafka_tpu.cli.run_modis_distributed --data-folder /path/mcd43 \
        --state-mask mask.tif --outdir /tmp/kafka_modis_dist
"""

from __future__ import annotations

import argparse
import datetime
import json
import logging

from ..engine.config import RunConfig
from ..engine.priors import TIP_PARAMETER_LIST
from . import add_telemetry_arg, make_console
from .drivers import run_config


def default_config() -> RunConfig:
    """The reference's distributed-MODIS constants
    (``kafka_test_Py36.py:159-255``)."""
    return RunConfig(
        parameter_list=TIP_PARAMETER_LIST,
        start=datetime.datetime(2017, 1, 1),
        end=datetime.datetime(2017, 12, 31),
        step_days=16,
        operator="twostream",
        propagator="none",
        prior="jrc",                       # prior-only advance, :173-177
        q_diag=[0, 0, 0, 0, 0, 0, 0.025],  # Q[6::7]=0.025, :180-181
        chunk_size=(256, 256),             # kafka_test_Py36.py:241
        observations="bhr",
        extra={"period": 16},
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default=None,
                    help="RunConfig JSON overriding the defaults")
    ap.add_argument("--data-folder", default=None)
    ap.add_argument("--state-mask", default=None)
    ap.add_argument("--outdir", default=None)
    ap.add_argument("--num-processes", type=int, default=None,
                    help="override jax.process_count() for the round-robin")
    ap.add_argument("--process-index", type=int, default=None,
                    help="override jax.process_index()")
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )

    cfg = RunConfig.load(args.config) if args.config else default_config()
    if args.data_folder:
        cfg.data_folder = args.data_folder
    if args.state_mask:
        cfg.state_mask = args.state_mask
    if args.outdir:
        cfg.output_folder = args.outdir
    if args.telemetry_dir:
        cfg.telemetry_dir = args.telemetry_dir

    stats = run_config(
        cfg,
        num_processes=args.num_processes,
        process_index=args.process_index,
    )
    print(json.dumps(stats))
    return stats


console = make_console(main)


if __name__ == "__main__":
    main()
