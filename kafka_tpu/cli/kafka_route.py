"""``kafka-route`` — the consistent-hash front door of a kafka-serve fleet.

Partitions the tile keyspace across N ``kafka-serve`` replicas with a
stable consistent-hash ring (``serve.router``): clients drop
``{"tile", "date"}`` JSON files into the ROUTER's ``<root>/inbox/`` and
read the ROUTER's ``<root>/responses/<request_id>.json`` — one serving
surface, N daemons behind it.  Every admitted request is journaled
before it is forwarded (a router crash replays unanswered requests on
restart), and because the replicas share a checkpoint root
(``kafka-serve --ckpt-root``), re-routing a tile to another replica is
warm-state migration for free: the new owner resumes from the bytes
the old owner checkpointed.

Fleet awareness (``--fleet-dir``, the PR 10 live-snapshot root shared
by the replicas' ``--telemetry-dir``): a replica whose heartbeat goes
stale without a clean-shutdown marker is flagged dead within one
heartbeat TTL — its ring segments rebalance to the survivors and its
in-flight requests are re-forwarded; a replica shedding ``queue_full``
is deprioritised instead of hammered.  Replicas join/leave a RUNNING
router via ``--replicas-file`` (a ``{"rid": "root"}`` JSON re-read on
mtime change).

Usage:
    kafka-serve --root /tmp/rep0 --ckpt-root /tmp/ckpt \\
        --telemetry-dir /tmp/fleet/rep0 &
    kafka-serve --root /tmp/rep1 --ckpt-root /tmp/ckpt \\
        --telemetry-dir /tmp/fleet/rep1 &
    kafka-route --root /tmp/front --replicas rep0=/tmp/rep0,rep1=/tmp/rep1 \\
        --fleet-dir /tmp/fleet &
    python -m tools.loadgen --root /tmp/front --requests 64
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from . import add_telemetry_arg, make_console


def parse_replicas(text: str) -> dict:
    """``rid=path,rid=path`` (or bare paths, auto-named rep0..N-1) into
    the ``{replica_id: serve_root}`` map."""
    out = {}
    for i, part in enumerate(p.strip() for p in text.split(",")):
        if not part:
            continue
        if "=" in part:
            rid, _, root = part.partition("=")
        else:
            rid, root = f"rep{i}", part
        out[rid.strip()] = root.strip()
    return out


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", required=True,
                    help="router root (inbox/, responses/, "
                         "requests.jsonl live here)")
    ap.add_argument("--replicas", default=None,
                    help="comma-separated rid=serve_root pairs (or bare "
                         "serve roots, auto-named rep0..N-1)")
    ap.add_argument("--replicas-file", default=None,
                    help='{"rid": "serve_root"} JSON, re-read when its '
                         "mtime changes — replicas join/leave a running "
                         "router without a restart")
    ap.add_argument("--fleet-dir", default=None,
                    help="telemetry root holding the replicas' live "
                         "snapshots; dead/shedding replicas are "
                         "detected from it")
    ap.add_argument("--ttl-s", type=float, default=None,
                    help="heartbeat staleness beyond which a replica is "
                         "dead (default: 3x each snapshot's own publish "
                         "interval)")
    ap.add_argument("--refresh-s", type=float, default=1.0,
                    help="fleet-view refresh cadence")
    ap.add_argument("--max-queue-depth", type=int, default=None,
                    help="deprioritise replicas whose live queue-depth "
                         "gauge is at or past this bound")
    ap.add_argument("--retry-after-s", type=float, default=0.5,
                    help="backoff hint on router-level rejections")
    ap.add_argument("--shed-slo", action="store_true",
                    help="shed new submissions (reason slo_burn) while "
                         "any PAGE-severity SLO alert fires on the "
                         "router's registry (telemetry.slo)")
    ap.add_argument("--poll-interval-s", type=float, default=0.05,
                    help="inbox/response scan cadence")
    ap.add_argument("--exit-when-idle", action="store_true",
                    help="exit 0 once the journal is replayed and the "
                         "inbox/in-flight set stay empty for "
                         "--idle-grace-s")
    ap.add_argument("--idle-grace-s", type=float, default=1.0)
    ap.add_argument("--http-port", type=int, default=0,
                    help="live metrics endpoint port (/metrics, "
                         "/healthz, /statusz with the router view; "
                         "0 = disabled)")
    ap.add_argument("--live-interval-s", type=float, default=None,
                    help="live_<host>_<pid>.json heartbeat cadence")
    ap.add_argument("--compile-cache-dir", default=None,
                    help="persistent XLA compilation cache directory "
                         "(default: <root>/.xla_cache; the router "
                         "itself compiles nothing, but keeping the "
                         "flag uniform lets one wrapper script "
                         "configure the whole fleet)")
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )
    from ..utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache(
        cache_dir=(args.compile_cache_dir
                   or os.path.join(args.root, ".xla_cache")),
    )
    if not args.replicas and not args.replicas_file:
        print("kafka-route: need --replicas and/or --replicas-file",
              file=sys.stderr)
        raise SystemExit(2)
    from ..resilience import faults
    from ..serve.router import RoutePolicy, TileRouter
    from ..telemetry import (
        configure, flight_recorder, get_registry, live, slo, tracing,
    )
    from ..telemetry.httpd import maybe_start

    if args.telemetry_dir:
        configure(args.telemetry_dir)
    recorder = flight_recorder.install(args.telemetry_dir)
    faults.install_from_env()
    os.makedirs(args.root, exist_ok=True)
    replicas = parse_replicas(args.replicas) if args.replicas else {}
    if args.replicas_file and os.path.exists(args.replicas_file):
        with open(args.replicas_file) as f:
            replicas.update(json.load(f))
    policy = RoutePolicy(
        refresh_s=args.refresh_s,
        ttl_s=args.ttl_s,
        max_queue_depth=args.max_queue_depth,
        retry_after_s=args.retry_after_s,
        shed_on_slo=args.shed_slo,
    )
    router = TileRouter(
        replicas, args.root,
        fleet_dir=args.fleet_dir,
        policy=policy,
        poll_interval_s=args.poll_interval_s,
        exit_when_idle=args.exit_when_idle,
        idle_grace_s=args.idle_grace_s,
        replicas_file=args.replicas_file,
    )
    reg = get_registry()
    with tracing.push(run_id=tracing.new_run_id()), recorder:
        live.update_status(router_root=os.path.abspath(args.root))
        live.start_publisher(role="route",
                             interval_s=args.live_interval_s)
        # SLO evaluator over the router's registry: availability here
        # means the whole fleet behind the front door (the router's
        # latency/rejection counters are client-visible totals).
        slo.start_engine()
        httpd = maybe_start(args.http_port,
                            status_provider=router.status,
                            role="route")
        try:
            summary = router.run()
        finally:
            slo.stop_engine()
            live.stop_publisher()
            if httpd is not None:
                httpd.close()
    summary["failed"] = 0
    summary["http_port"] = None if httpd is None else httpd.port
    summary["telemetry_dir"] = reg.dump()
    print(json.dumps(summary))
    return summary


console = make_console(main)


if __name__ == "__main__":
    sys.exit(console())
