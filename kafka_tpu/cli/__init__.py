"""CLI drivers and tools."""

from __future__ import annotations


def add_telemetry_arg(ap) -> None:
    """The drivers' shared ``--telemetry-dir`` flag (observability layer,
    BASELINE.md "Observability"): events stream to ``events.jsonl`` in the
    directory during the run; ``metrics.prom`` (Prometheus text format)
    and ``metrics.json`` snapshots are written at run end."""
    ap.add_argument(
        "--telemetry-dir", default=None,
        help="export run telemetry into this directory (events.jsonl "
             "streamed; metrics.prom/metrics.json written at run end)",
    )


def make_console(main_fn):
    """Wrap a driver ``main`` (which returns a result object for
    programmatic callers) into a console-script entry point.

    Exit codes: 0 on full success; ``EXIT_PARTIAL_SUCCESS`` (75,
    sysexits EX_TEMPFAIL) when the run COMPLETED but quarantined chunks
    — the result dict carries a nonzero ``"failed"`` — so a scheduler/CI
    can distinguish "rerun the quarantined pieces" from a hard failure
    (which still raises and exits nonzero the usual way)."""

    def console():
        result = main_fn()
        if isinstance(result, dict) and result.get("failed"):
            from ..resilience import EXIT_PARTIAL_SUCCESS

            return EXIT_PARTIAL_SUCCESS
        return 0

    return console
