"""CLI drivers and tools."""

from __future__ import annotations


def make_console(main_fn):
    """Wrap a driver ``main`` (which returns a result object for
    programmatic callers) into a console-script entry point whose return
    value ``sys.exit`` treats as success."""

    def console():
        main_fn()
        return 0

    return console
