"""CLI drivers and tools."""

from __future__ import annotations


def add_telemetry_arg(ap) -> None:
    """The drivers' shared ``--telemetry-dir`` flag (observability layer,
    BASELINE.md "Observability"): events stream to ``events.jsonl`` in the
    directory during the run; ``metrics.prom`` (Prometheus text format)
    and ``metrics.json`` snapshots are written at run end."""
    ap.add_argument(
        "--telemetry-dir", default=None,
        help="export run telemetry into this directory (events.jsonl "
             "streamed; metrics.prom/metrics.json written at run end)",
    )


def make_console(main_fn):
    """Wrap a driver ``main`` (which returns a result object for
    programmatic callers) into a console-script entry point whose return
    value ``sys.exit`` treats as success."""

    def console():
        main_fn()
        return 0

    return console
