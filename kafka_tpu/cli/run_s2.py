"""Sentinel-2 PROSAIL driver — the Barrax configuration.

TPU-native equivalent of ``/root/reference/kafka_test_S2.py:135-205``:
10-parameter PROSAIL state, SAIL prior, prior-only advance (zero Q),
2-day time grid, 128x128 chunks over the pivot-field state mask, per-chunk
prefixed GeoTIFF outputs.  All knobs come from a ``RunConfig`` (the config
layer the reference lacks); pass ``--config run.json`` to override any of
them.

Usage:
    python -m kafka_tpu.cli.run_s2 --data-folder /path/s2_tree \
        --state-mask pivots.tif --outdir /tmp/kafka_s2
"""

from __future__ import annotations

import argparse
import datetime
import json
import logging

from ..engine.config import RunConfig
from ..engine.priors import PROSAIL_PARAMETER_LIST
from . import add_telemetry_arg, make_console
from .drivers import resolve_aux_builder, run_config


def default_config() -> RunConfig:
    """The reference's S2-Barrax constants (``kafka_test_S2.py:135-205``)."""
    return RunConfig(
        parameter_list=PROSAIL_PARAMETER_LIST,
        start=datetime.datetime(2017, 7, 3),
        end=datetime.datetime(2017, 7, 11),
        step_days=2,
        operator="prosail",
        propagator="none",
        prior="sail",
        q_diag=None,                      # Q = 0 (kafka_test_S2.py:185-187)
        chunk_size=(128, 128),
        observations="sentinel2",
        solver_options={"relaxation": 0.7},
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default=None,
                    help="RunConfig JSON overriding the Barrax defaults")
    ap.add_argument("--data-folder", default=None)
    ap.add_argument("--state-mask", default=None)
    ap.add_argument("--outdir", default=None)
    ap.add_argument("--emulators", default=None,
                    help="directory of gp_emulator pickles or converted "
                         ".npz banks (kafka-tpu-import-emulators): runs "
                         "the assimilation through the reference's "
                         "emulator artifacts instead of the built-in "
                         "PROSAIL physics operator")
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )

    cfg = RunConfig.load(args.config) if args.config else default_config()
    if args.data_folder:
        cfg.data_folder = args.data_folder
    if args.state_mask:
        cfg.state_mask = args.state_mask
    if args.outdir:
        cfg.output_folder = args.outdir
    if args.telemetry_dir:
        cfg.telemetry_dir = args.telemetry_dir
    if args.emulators:
        cfg.operator = "gp_bank"
        cfg.extra["emulator_folder"] = args.emulators

    stats = run_config(cfg, aux_builder=resolve_aux_builder(cfg))
    print(json.dumps(stats))
    return stats


console = make_console(main)


if __name__ == "__main__":
    main()
