"""Joint Sentinel-2 optical + Sentinel-1 SAR assimilation driver.

The multi-sensor configuration the reference never shipped: its SAR
Water-Cloud operator exists (``/root/reference/kafka/observation_operators/
sar_forward_model.py``) but no driver composes it with the optical path.
Here both sensors constrain ONE 11-parameter state (the 10 transformed
PROSAIL parameters + volumetric soil moisture, ``obsops.joint``): S2 dates
update the full optical state through PROSAIL, S1 dates update LAI and
soil moisture through the WCM — the merged date stream is assimilated
in time order by the same filter.

Usage:
    python -m kafka_tpu.cli.run_joint --data-folder /path/s2_tree \
        --s1-folder /path/s1_ncs --state-mask mask.tif --outdir /tmp/joint
"""

from __future__ import annotations

import argparse
import datetime
import json
import logging

from ..engine.config import RunConfig
from ..engine.priors import JOINT_PARAMETER_LIST
from . import add_telemetry_arg, make_console
from .drivers import prosail_aux_builder, run_config


def default_config() -> RunConfig:
    """S2-Barrax constants extended with the SAR stream: same grid and
    chunking as the S2 driver (``kafka_test_S2.py:135-205``), 11-parameter
    joint state.

    Unlike the S2 driver's prior-only advance (which RESETS the state to
    the prior every grid step, ``kf_tools.py:155-158`` semantics — fine
    when one sensor observes every window, fatal when sensors alternate),
    the joint config propagates information through time: the joint prior
    seeds the initial state only, and the information filter carries each
    sensor's constraint forward with a small model error Q, so SAR-derived
    soil moisture survives optical-only windows and vice versa (the
    MODIS-serial pattern, ``kafka_test.py:195-208``)."""
    return RunConfig(
        parameter_list=JOINT_PARAMETER_LIST,
        start=datetime.datetime(2017, 7, 3),
        end=datetime.datetime(2017, 7, 11),
        step_days=2,
        operator="prosail_joint",
        propagator="information_filter",
        prior=None,
        initial_prior="joint",
        # Small per-step model error; soil moisture decorrelates faster
        # than canopy structure, so its Q is an order larger.
        q_diag=[1e-3] * 10 + [1e-2],
        chunk_size=(128, 128),
        observations="joint",
        solver_options={"relaxation": 0.7},
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default=None,
                    help="RunConfig JSON overriding the defaults")
    ap.add_argument("--data-folder", default=None, help="S2 granule tree")
    ap.add_argument("--s1-folder", default=None, help="S1 NetCDF folder")
    ap.add_argument("--state-mask", default=None)
    ap.add_argument("--outdir", default=None)
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )

    cfg = RunConfig.load(args.config) if args.config else default_config()
    if args.data_folder:
        cfg.data_folder = args.data_folder
    if args.s1_folder:
        cfg.extra["s1_folder"] = args.s1_folder
    if args.state_mask:
        cfg.state_mask = args.state_mask
    if args.outdir:
        cfg.output_folder = args.outdir
    if args.telemetry_dir:
        cfg.telemetry_dir = args.telemetry_dir
    if "s1_folder" not in cfg.extra:
        ap.error("--s1-folder (or extra.s1_folder in --config) is required")

    stats = run_config(cfg, aux_builder=prosail_aux_builder)
    print(json.dumps(stats))
    return stats


console = make_console(main)


if __name__ == "__main__":
    main()
