"""End-to-end synthetic driver.

Runs the complete pipeline — mask, prior, operator, multi-date filter run,
GeoTIFF outputs — on generated data, no external rasters or emulators.  The
structural equivalent of the reference's driver scripts
(``/root/reference/kafka_test_S2.py:135-205``) with the identity/two-stream/
WCM operators standing in for the data-dependent emulator paths.

Usage:
    python -m kafka_tpu.cli.run_synthetic --operator twostream \
        --outdir /tmp/kafka_out --days 16 --step 4
"""

from __future__ import annotations

import argparse
import datetime
import json
import logging
import os
import time

import numpy as np

from ..core import propagate_information_filter
from ..core.propagators import PixelPrior
from ..engine import Checkpointer, FixedGaussianPrior, KalmanFilter
from ..engine.priors import TIP_PARAMETER_LIST, jrc_prior
from ..io import GeoTIFFOutput, read_geotiff
from ..obsops import IdentityOperator, TwoStreamOperator, WCMAux, WCMOperator
from ..testing.fixtures import DEFAULT_GEO, make_pivot_mask
from ..testing.synthetic import SyntheticObservations
from . import add_telemetry_arg, make_console

import jax.numpy as jnp


def build_operator(name: str, gather):
    if name == "identity":
        op = IdentityOperator(n_params=2, obs_indices=(0, 1))
        params = ("a", "b")
        prior = FixedGaussianPrior(
            _iso_prior(2, 0.5, 0.4), params
        )
        truth_val = np.array([0.3, 0.7], np.float32)
        aux_fn = None
        sigma = 0.02
    elif name == "twostream":
        op = TwoStreamOperator()
        params = TIP_PARAMETER_LIST
        prior = jrc_prior()
        truth_val = np.asarray(prior.prior.mean).copy()
        truth_val[6] = 0.5  # TLAI target
        aux_fn = None
        sigma = 0.002
    elif name == "wcm":
        op = WCMOperator()
        params = ("lai", "sm")
        prior = FixedGaussianPrior(
            _mean_prior(np.array([1.5, 0.25], np.float32),
                        np.array([1.0, 0.2], np.float32)),
            params,
        )
        truth_val = np.array([2.2, 0.32], np.float32)
        aux_fn = lambda date, g: WCMAux(
            theta_deg=jnp.full((g.n_pad,), 23.0, jnp.float32)
        )
        sigma = 0.002
    else:
        raise SystemExit(f"unknown operator {name!r}")
    return op, params, prior, truth_val, aux_fn, sigma


def _iso_prior(p, mean, sigma):
    cov = np.diag(np.full(p, sigma**2)).astype(np.float32)
    return PixelPrior(
        mean=jnp.full((p,), mean, jnp.float32), cov=jnp.asarray(cov),
        inv_cov=jnp.asarray(np.linalg.inv(cov)),
    )


def _mean_prior(mean, sigma):
    cov = np.diag(sigma**2).astype(np.float32)
    return PixelPrior(
        mean=jnp.asarray(mean, jnp.float32), cov=jnp.asarray(cov),
        inv_cov=jnp.asarray(np.linalg.inv(cov)),
    )


def main(argv=None):
    from ..utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--operator", default="twostream",
                    choices=("identity", "twostream", "wcm"))
    ap.add_argument("--outdir", default="/tmp/kafka_tpu_synthetic")
    ap.add_argument("--mask", default=None,
                    help="GeoTIFF state mask (default: generated pivots)")
    ap.add_argument("--ny", type=int, default=204)
    ap.add_argument("--nx", type=int, default=235)
    ap.add_argument("--days", type=int, default=16)
    ap.add_argument("--step", type=int, default=4,
                    help="time-grid step in days")
    ap.add_argument("--obs-every", type=int, default=2,
                    help="observation cadence in days")
    ap.add_argument("--checkpoint", action="store_true")
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )
    from ..telemetry import (
        configure, flight_recorder, get_registry,
        install_compile_listeners, tracing,
    )

    install_compile_listeners()
    if args.telemetry_dir:
        configure(args.telemetry_dir)
    recorder = flight_recorder.install(args.telemetry_dir)
    if args.mask:
        mask_arr, info = read_geotiff(args.mask)
        mask = mask_arr.astype(bool)
        geo = info.geo
    else:
        mask = make_pivot_mask(args.ny, args.nx)
        geo = DEFAULT_GEO

    os.makedirs(args.outdir, exist_ok=True)
    base = datetime.datetime(2017, 7, 1)
    obs_dates = [
        base + datetime.timedelta(days=d)
        for d in range(1, args.days, args.obs_every)
    ]
    time_grid = [
        base + datetime.timedelta(days=d)
        for d in range(0, args.days + args.step, args.step)
    ]

    op, params, prior, truth_val, aux_fn, sigma = build_operator(
        args.operator, None
    )
    truth = np.broadcast_to(
        truth_val, mask.shape + (len(truth_val),)
    ).astype(np.float32)
    observations = SyntheticObservations(
        dates=obs_dates, operator=op,
        truth_fn=lambda date: truth, sigma=sigma, aux_fn=aux_fn,
        mask_prob=0.1,
    )
    output = GeoTIFFOutput(
        params, geo.geotransform, geo.projection, args.outdir,
        epsg=geo.epsg, async_writes=True,
    )
    kf = KalmanFilter(
        observations, output, mask, params,
        state_propagation=propagate_information_filter,
        prior=None,
        solver_options={"relaxation": 0.5},
    )
    kf.set_trajectory_model()
    kf.set_trajectory_uncertainty(np.full(len(params), 1e-3, np.float32))
    x0, p_inv0 = prior.process_prior(None, kf.gather)

    ck = Checkpointer(os.path.join(args.outdir, "ckpt")) \
        if args.checkpoint else None
    t0 = time.time()
    # One trace context for the run; the recorder guard turns a mid-run
    # death into a crash_<ts>.json next to the other telemetry artifacts.
    with tracing.push(run_id=tracing.new_run_id()), recorder:
        kf.run(time_grid, x0, None, p_inv0, checkpointer=ck)
    output.close()
    wall = time.time() - t0

    n_outputs = len([f for f in os.listdir(args.outdir)
                     if f.endswith(".tif")])
    n_steps = len(time_grid) - 1
    summary = {
        "operator": args.operator,
        "n_pixels": int(kf.gather.n_valid),
        "n_dates": len(obs_dates),
        "n_timesteps": n_steps,
        "wall_s": round(wall, 3),
        "pixel_steps_per_s": round(
            kf.gather.n_valid * len(obs_dates) / wall, 1
        ),
        "outputs_written": n_outputs,
        "outdir": args.outdir,
        "mean_iterations": round(
            float(np.mean([d["n_iterations"]
                           for d in kf.diagnostics_log] or [0])), 2
        ),
    }
    reg = get_registry()
    reg.emit("run_done", **{k: v for k, v in summary.items()})
    summary["telemetry_dir"] = reg.dump()
    print(json.dumps(summary))
    return summary


console = make_console(main)


if __name__ == "__main__":
    main()
