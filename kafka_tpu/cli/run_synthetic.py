"""End-to-end synthetic driver.

Runs the complete pipeline — mask, prior, operator, multi-date filter run,
GeoTIFF outputs — on generated data, no external rasters or emulators.  The
structural equivalent of the reference's driver scripts
(``/root/reference/kafka_test_S2.py:135-205``) with the identity/two-stream/
WCM operators standing in for the data-dependent emulator paths.

Usage:
    python -m kafka_tpu.cli.run_synthetic --operator twostream \
        --outdir /tmp/kafka_out --days 16 --step 4

``--chunk-size N`` routes the run through the restart-safe chunk
scheduler (``shard.run_chunks``) with quarantine enabled — one
KalmanFilter per NxN chunk, prefixed outputs, per-chunk retry — which
makes this driver the fault-tolerance chaos harness: script failures
with ``KAFKA_TPU_FAULTS`` (see ``kafka_tpu.resilience.faults``) and the
run completes with exit code 75 (partial success) when chunks were
quarantined, while unaffected chunks produce bit-identical outputs.

``--queue`` upgrades chunked mode to the self-healing lease-based queue
(``shard.run_queue``): workers claim chunks via heartbeat leases and
reclaim a dead worker's expired leases, so a SIGKILLed worker's chunks
are finished by the survivors.  ``--num-workers N`` makes a local
N-process fleet out of this one command (the chaos recipe in BASELINE.md
"Multi-host queue"); SIGTERM drains a worker gracefully (finish current
chunk, release leases, exit 0).
"""

from __future__ import annotations

import argparse
import datetime
import json
import logging
import os
import sys
import time

import numpy as np

from ..core import propagate_information_filter
from ..core.propagators import PixelPrior
from ..engine import Checkpointer, FixedGaussianPrior, KalmanFilter
from ..engine.priors import TIP_PARAMETER_LIST, jrc_prior
from ..io import GeoTIFFOutput, read_geotiff
from ..obsops import IdentityOperator, TwoStreamOperator, WCMAux, WCMOperator
from ..testing.fixtures import DEFAULT_GEO, make_pivot_mask
from ..testing.synthetic import SyntheticObservations
from . import add_telemetry_arg, make_console

import jax.numpy as jnp


def build_operator(name: str, gather):
    if name == "identity":
        op = IdentityOperator(n_params=2, obs_indices=(0, 1))
        params = ("a", "b")
        prior = FixedGaussianPrior(
            _iso_prior(2, 0.5, 0.4), params
        )
        truth_val = np.array([0.3, 0.7], np.float32)
        aux_fn = None
        sigma = 0.02
    elif name == "twostream":
        op = TwoStreamOperator()
        params = TIP_PARAMETER_LIST
        prior = jrc_prior()
        truth_val = np.asarray(prior.prior.mean).copy()
        truth_val[6] = 0.5  # TLAI target
        aux_fn = None
        sigma = 0.002
    elif name == "wcm":
        op = WCMOperator()
        params = ("lai", "sm")
        prior = FixedGaussianPrior(
            _mean_prior(np.array([1.5, 0.25], np.float32),
                        np.array([1.0, 0.2], np.float32)),
            params,
        )
        truth_val = np.array([2.2, 0.32], np.float32)
        aux_fn = lambda date, g: WCMAux(
            theta_deg=jnp.full((g.n_pad,), 23.0, jnp.float32)
        )
        sigma = 0.002
    else:
        raise SystemExit(f"unknown operator {name!r}")
    return op, params, prior, truth_val, aux_fn, sigma


def _iso_prior(p, mean, sigma):
    cov = np.diag(np.full(p, sigma**2)).astype(np.float32)
    return PixelPrior(
        mean=jnp.full((p,), mean, jnp.float32), cov=jnp.asarray(cov),
        inv_cov=jnp.asarray(np.linalg.inv(cov)),
    )


def _mean_prior(mean, sigma):
    cov = np.diag(sigma**2).astype(np.float32)
    return PixelPrior(
        mean=jnp.asarray(mean, jnp.float32), cov=jnp.asarray(cov),
        inv_cov=jnp.asarray(np.linalg.inv(cov)),
    )


def main(argv=None):
    from ..utils.compilation_cache import enable_compilation_cache

    raw_argv = list(sys.argv[1:] if argv is None else argv)
    enable_compilation_cache()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--operator", default="twostream",
                    choices=("identity", "twostream", "wcm"))
    ap.add_argument("--outdir", default="/tmp/kafka_tpu_synthetic")
    ap.add_argument("--mask", default=None,
                    help="GeoTIFF state mask (default: generated pivots)")
    ap.add_argument("--ny", type=int, default=204)
    ap.add_argument("--nx", type=int, default=235)
    ap.add_argument("--days", type=int, default=16)
    ap.add_argument("--step", type=int, default=4,
                    help="time-grid step in days")
    ap.add_argument("--obs-every", type=int, default=2,
                    help="observation cadence in days")
    ap.add_argument("--checkpoint", action="store_true")
    ap.add_argument("--chunk-size", type=int, default=0,
                    help="run as NxN chunks through the restart-safe "
                         "scheduler with quarantine on (0 = one run)")
    ap.add_argument("--queue", action="store_true",
                    help="claim chunks from the self-healing lease-based "
                         "queue (shard.run_queue) instead of static "
                         "assignment; requires --chunk-size")
    ap.add_argument("--lease-ttl-s", type=float, default=None,
                    help="queue-mode heartbeat-lease TTL; a worker "
                         "silent this long is presumed dead and its "
                         "chunk is reclaimed")
    ap.add_argument("--num-workers", type=int, default=1,
                    help="queue-mode local fleet size: N>1 spawns N "
                         "single-worker subprocesses of this command "
                         "over one shared queue and waits")
    ap.add_argument("--chunk-attempts", type=int, default=2,
                    help="attempts per chunk under the scheduler retry "
                         "policy (chunked mode)")
    ap.add_argument("--chunk-deadline-s", type=float, default=None,
                    help="per-chunk wall-clock deadline; over-budget "
                         "chunks are quarantined (chunked mode)")
    ap.add_argument("--read-attempts", type=int, default=3,
                    help="attempts per observation read before the date "
                         "degrades to predict-only")
    ap.add_argument("--retry-delay-s", type=float, default=0.25,
                    help="base backoff delay for read/chunk retries "
                         "(deterministic, jitter-free schedule)")
    ap.add_argument("--max-degraded-dates", type=int, default=8,
                    help="degraded-date budget per filter run before "
                         "aborting")
    ap.add_argument("--http-port", type=int, default=0,
                    help="live metrics endpoint port (/metrics /healthz "
                         "/statusz /profilez; 0 = disabled; fleet mode "
                         "gives worker i port+i)")
    ap.add_argument("--profile-windows", type=int, default=0,
                    help="capture a jax.profiler trace of the first N "
                         "assimilated windows into <telemetry-dir>/"
                         "profile (0 = off; one capture at a time)")
    add_telemetry_arg(ap)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )
    if args.queue and args.chunk_size <= 0:
        raise SystemExit("--queue requires --chunk-size")
    if args.queue and args.num_workers > 1:
        # Local fleet: the parent only spawns + waits + summarises; the
        # children are plain single-worker copies of this command over
        # the one shared filesystem queue.
        return _run_fleet(args, raw_argv)
    from ..telemetry import (
        configure, flight_recorder, get_registry, live, perf, slo,
        install_compile_listeners, tracing,
    )
    from ..telemetry.httpd import maybe_start

    install_compile_listeners()
    if args.telemetry_dir:
        configure(args.telemetry_dir)
    recorder = flight_recorder.install(args.telemetry_dir)
    httpd = maybe_start(args.http_port, role="engine")
    if args.profile_windows > 0:
        # Windowed profiler capture (telemetry.perf): starts now, stops
        # itself after N assimilated windows; the finally below is the
        # safety net for runs shorter than N.
        perf.start_windowed_capture(
            args.profile_windows,
            os.path.join(args.telemetry_dir or args.outdir, "profile"),
        )
    from ..resilience import RetryPolicy, faults

    # Chaos hook: KAFKA_TPU_FAULTS scripts deterministic failures at the
    # registered fault points (BASELINE.md "Fault tolerance").
    faults.install_from_env()
    read_policy = RetryPolicy(
        max_attempts=max(1, args.read_attempts),
        base_delay=args.retry_delay_s, multiplier=2.0, jitter=0.0,
    )
    if args.mask:
        mask_arr, info = read_geotiff(args.mask)
        mask = mask_arr.astype(bool)
        geo = info.geo
    else:
        mask = make_pivot_mask(args.ny, args.nx)
        geo = DEFAULT_GEO

    os.makedirs(args.outdir, exist_ok=True)
    base = datetime.datetime(2017, 7, 1)
    obs_dates = [
        base + datetime.timedelta(days=d)
        for d in range(1, args.days, args.obs_every)
    ]
    time_grid = [
        base + datetime.timedelta(days=d)
        for d in range(0, args.days + args.step, args.step)
    ]

    op, params, prior, truth_val, aux_fn, sigma = build_operator(
        args.operator, None
    )
    truth = np.broadcast_to(
        truth_val, mask.shape + (len(truth_val),)
    ).astype(np.float32)

    t0 = time.time()
    # One trace context for the run; the recorder guard turns a mid-run
    # death into a crash_<ts>.json next to the other telemetry artifacts.
    with tracing.push(run_id=tracing.new_run_id()), recorder:
        # Fleet-plane heartbeat (live_<host>_<pid>.json; no-op without
        # --telemetry-dir).  The queue chaos tests watch these files.
        live.start_publisher(
            role="queue_worker" if args.queue else "engine"
        )
        # SLO evaluator (telemetry.slo): solver/quality/perf burn over
        # this run's registry, serving /alertz and alerts.jsonl.
        slo.start_engine()
        try:
            if args.chunk_size > 0:
                summary = _run_chunked(
                    args, mask, geo, op, params, prior, truth, aux_fn,
                    sigma, obs_dates, time_grid, read_policy,
                )
            else:
                summary = _run_single(
                    args, mask, geo, op, params, prior, truth, aux_fn,
                    sigma, obs_dates, time_grid, read_policy,
                )
        finally:
            perf.stop_windowed_capture()
            slo.stop_engine()
            live.stop_publisher()
            if httpd is not None:
                httpd.close()
    wall = time.time() - t0

    summary["operator"] = args.operator
    summary["n_dates"] = len(obs_dates)
    summary["n_timesteps"] = len(time_grid) - 1
    summary["wall_s"] = round(wall, 3)
    summary["pixel_steps_per_s"] = round(
        summary["n_pixels"] * len(obs_dates) / wall, 1
    )
    summary["outputs_written"] = len(
        [f for f in os.listdir(args.outdir) if f.endswith(".tif")]
    )
    summary["outdir"] = args.outdir
    reg = get_registry()
    reg.emit("run_done", **{k: v for k, v in summary.items()})
    summary["telemetry_dir"] = reg.dump()
    print(json.dumps(summary))
    return summary


def _make_filter(args, sub_mask, output, op, params, obs, read_policy):
    kf = KalmanFilter(
        obs, output, sub_mask, params,
        state_propagation=propagate_information_filter,
        prior=None,
        solver_options={"relaxation": 0.5},
        read_retry_policy=read_policy,
        max_degraded_dates=args.max_degraded_dates,
    )
    kf.set_trajectory_model()
    kf.set_trajectory_uncertainty(np.full(len(params), 1e-3, np.float32))
    return kf


def _run_single(args, mask, geo, op, params, prior, truth, aux_fn,
                sigma, obs_dates, time_grid, read_policy) -> dict:
    observations = SyntheticObservations(
        dates=obs_dates, operator=op,
        truth_fn=lambda date: truth, sigma=sigma, aux_fn=aux_fn,
        mask_prob=0.1,
    )
    output = GeoTIFFOutput(
        params, geo.geotransform, geo.projection, args.outdir,
        epsg=geo.epsg, async_writes=True,
    )
    kf = _make_filter(args, mask, output, op, params, observations,
                      read_policy)
    x0, p_inv0 = prior.process_prior(None, kf.gather)
    ck = Checkpointer(os.path.join(args.outdir, "ckpt")) \
        if args.checkpoint else None
    kf.run(time_grid, x0, None, p_inv0, checkpointer=ck)
    output.close()
    return {
        "n_pixels": int(kf.gather.n_valid),
        "mean_iterations": round(
            float(np.mean([d["n_iterations"]
                           for d in kf.diagnostics_log] or [0])), 2
        ),
    }


def _run_chunked(args, mask, geo, op, params, prior, truth, aux_fn,
                 sigma, obs_dates, time_grid, read_policy) -> dict:
    """The chunk-scheduler path: one filter per NxN chunk with prefixed
    outputs, per-chunk retry and quarantine — the synthetic chaos
    harness for the fault-tolerance layer (exit code 75 when chunks end
    up quarantined; see module docstring)."""
    from ..io.tiling import chunk_geotransform, chunk_mask, get_chunks
    from ..resilience import RetryPolicy
    from ..shard.scheduler import run_chunks

    ny, nx = mask.shape
    chunks = list(get_chunks(nx, ny, (args.chunk_size, args.chunk_size)))
    # Keyed by prefix, not appended: at-least-once execution (queue-mode
    # commit retries, reclaimed chunks) may run a chunk twice.
    summaries = {}

    def run_one(chunk, prefix):
        sub_mask = chunk_mask(mask, chunk)
        if not sub_mask.any():
            return
        sub_truth = np.ascontiguousarray(
            truth[chunk.y0:chunk.y0 + chunk.ny_valid,
                  chunk.x0:chunk.x0 + chunk.nx_valid]
        )
        obs = SyntheticObservations(
            dates=obs_dates, operator=op,
            truth_fn=lambda date: sub_truth, sigma=sigma, aux_fn=aux_fn,
            mask_prob=0.1,
        )
        output = GeoTIFFOutput(
            params, chunk_geotransform(geo.geotransform, chunk),
            geo.projection, args.outdir, prefix=prefix, epsg=geo.epsg,
            async_writes=True,
        )
        kf = _make_filter(args, sub_mask, output, op, params, obs,
                          read_policy)
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        ck = Checkpointer(
            os.path.join(args.outdir, "ckpt"), prefix=f"{prefix}_"
        ) if args.checkpoint else None
        try:
            kf.run(time_grid, x0, None, p_inv0, checkpointer=ck)
        except BaseException:
            # A failed attempt must not leak the async writer thread
            # into the retry (same teardown contract as the drivers).
            output.close()
            raise
        output.close()
        summaries[prefix] = {
            "prefix": prefix, "n_pixels": int(kf.gather.n_valid),
        }

    policy = RetryPolicy(
        max_attempts=max(1, args.chunk_attempts),
        base_delay=args.retry_delay_s, multiplier=2.0, jitter=0.0,
    ) if args.chunk_attempts > 1 else None
    if args.queue:
        from ..shard.queue import DEFAULT_LEASE_TTL_S, run_queue

        stats = run_queue(
            chunks, run_one, args.outdir,
            lease_ttl_s=(args.lease_ttl_s if args.lease_ttl_s
                         else DEFAULT_LEASE_TTL_S),
            retry_policy=policy, quarantine=True,
            chunk_deadline_s=args.chunk_deadline_s,
        )
        return {
            "mode": "queue",
            "worker": stats["worker"],
            "chunks_total": stats["total"],
            "chunks_run": stats["run"],
            "reclaimed": stats["reclaimed"],
            "skipped": stats["skipped"],
            "failed": stats["failed"],
            "drained": stats["drained"],
            "pending": stats["pending_at_exit"],
            "n_pixels": int(sum(s["n_pixels"] for s in summaries.values())),
        }
    stats = run_chunks(
        chunks, run_one, args.outdir, num_processes=1, process_index=0,
        retry_policy=policy, quarantine=True,
        chunk_deadline_s=args.chunk_deadline_s,
    )
    return {
        "mode": "chunked",
        "chunks_assigned": stats["assigned"],
        "chunks_run": stats["run"],
        "skipped": stats["skipped"],
        "failed": stats["failed"],
        "n_pixels": int(sum(s["n_pixels"] for s in summaries.values())),
    }


def _strip_flag(argv, name, has_value=True):
    """Remove ``name <v>`` / ``name=<v>`` occurrences from an argv list."""
    out, skip = [], False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok == name:
            skip = has_value
            continue
        if tok.startswith(name + "="):
            continue
        out.append(tok)
    return out


def _run_fleet(args, raw_argv) -> dict:
    """``--queue --num-workers N``: the one-command local fleet.  Spawns
    N single-worker copies of this command over the shared queue in
    ``--outdir``, waits, and summarises the queue's final state — the
    chaos recipe from BASELINE.md "Multi-host queue" (SIGKILL a worker
    mid-run and the survivors reclaim its chunks)."""
    import subprocess

    from ..shard.queue import queue_status

    child_argv = raw_argv
    for flag in ("--num-workers", "--telemetry-dir", "--http-port"):
        child_argv = _strip_flag(child_argv, flag)
    env = dict(os.environ)
    # One run id for the whole fleet: every worker's spans/events join
    # one trace (tracing.new_run_id reads this).
    env.setdefault("KAFKA_TPU_RUN_ID", os.urandom(6).hex())
    procs = []
    for i in range(args.num_workers):
        cmd = [sys.executable, "-m", "kafka_tpu.cli.run_synthetic",
               *child_argv, "--num-workers", "1"]
        if args.telemetry_dir:
            cmd += ["--telemetry-dir",
                    os.path.join(args.telemetry_dir, f"worker_{i}")]
        if args.http_port:
            # One endpoint per worker process: ports cannot be shared.
            cmd += ["--http-port", str(args.http_port + i)]
        procs.append(subprocess.Popen(cmd, env=env,
                                      stdout=subprocess.DEVNULL))
    rcs = [p.wait() for p in procs]
    hard = [rc for rc in rcs if rc not in (0, 75)]
    if hard:
        raise RuntimeError(
            f"queue worker hard-failed (rc={hard[0]}; all: {rcs})"
        )
    status = queue_status(args.outdir)
    summary = {
        "mode": "queue-fleet",
        "num_workers": args.num_workers,
        "chunks_total": status["n_chunks"],
        "done": status["counts"]["done"],
        "failed": status["counts"]["failed"],
        "pending": status["counts"]["pending"],
        "worker_rcs": rcs,
        "outdir": args.outdir,
    }
    print(json.dumps(summary))
    return summary


console = make_console(main)


if __name__ == "__main__":
    sys.exit(console())
