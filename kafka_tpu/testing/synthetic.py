"""Synthetic observation source + in-memory output sink.

The reference sketched both and finished neither: ``BHRObservationsTest``
computes band data but returns nothing
(``/root/reference/kafka/input_output/observations.py:313-334``) and
``KafkaOutputMemory`` is duplicated across all three drivers
(``kafka_test.py:135-145`` etc.).  SURVEY.md §4 calls for finishing them so
a full ``run()`` is testable without rasters — this module does that.
"""

from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.types import BandBatch
from ..engine.protocols import DateObservation
from ..engine.state import PixelGather
from ..obsops.protocol import ObservationModel


class SyntheticObservations:
    """Generates observations by running a forward operator on a known
    ground-truth state trajectory + noise, with random masking.

    ``truth_fn(date) -> (ny, nx, p)`` raster of true states; observations
    are ``operator.forward(aux, truth)`` + N(0, sigma^2), inverse-variance
    ``1/sigma^2`` (the readers' convention,
    ``Sentinel2_Observations.py:174-179``).
    """

    def __init__(
        self,
        dates: Sequence[datetime.datetime],
        operator: ObservationModel,
        truth_fn,
        sigma: float = 0.01,
        aux_fn=None,
        mask_prob: float = 0.1,
        seed: int = 0,
    ):
        self._dates = list(dates)
        self.operator = operator
        self.truth_fn = truth_fn
        self.sigma = sigma
        self.aux_fn = aux_fn or (lambda date, gather: None)
        self.mask_prob = mask_prob
        self.seed = seed
        self.bands_per_observation = {
            d: operator.n_bands for d in self._dates
        }

    @property
    def dates(self):
        return self._dates

    def get_observations(self, date, gather: PixelGather) -> DateObservation:
        truth = self.truth_fn(date)  # (ny, nx, p)
        x_true = jnp.asarray(gather.gather(truth), jnp.float32)
        aux = self.aux_fn(date, gather)
        y_clean = np.asarray(self.operator.forward(aux, x_true))
        # Per-date seeding: the same date always yields the same draw, so a
        # resumed run sees identical observations to the original.
        rng = np.random.default_rng((self.seed, date.toordinal()))
        noise = rng.normal(0.0, self.sigma, y_clean.shape)
        y = (y_clean + noise).astype(np.float32)
        mask = rng.uniform(size=y.shape) > self.mask_prob
        mask &= gather.valid[None, :]
        r_inv = np.where(mask, 1.0 / self.sigma**2, 0.0).astype(np.float32)
        bands = BandBatch(
            y=jnp.asarray(np.where(mask, y, 0.0)),
            r_inv=jnp.asarray(r_inv),
            mask=jnp.asarray(mask),
        )
        return DateObservation(bands=bands, operator=self.operator, aux=aux)


def make_tip_problem(n_pix: int, seed: int = 0, sigma: float = 0.005,
                     mask_prob: float = 0.1):
    """Standard synthetic TIP/two-stream assimilation problem used by the
    sharding tests, ``bench.py`` and ``__graft_entry__.py``: truth drawn
    around the TIP prior, two-stream forward + noise, random masking.

    Returns ``(operator, bands, x0, p_inv0)`` with ``x0``/``p_inv0`` the
    broadcast TIP prior (the forecast for a first-timestep assimilation).
    """
    from ..core.propagators import broadcast_prior, tip_prior
    from ..obsops.twostream import TwoStreamOperator

    op = TwoStreamOperator()
    rng = np.random.default_rng(seed)
    x0, p_inv0 = broadcast_prior(tip_prior(), n_pix)
    truth = np.clip(
        np.asarray(x0) + rng.normal(0, 0.05, (n_pix, op.n_params)),
        0.05, 0.95,
    ).astype(np.float32)
    y = np.array(op.forward(None, jnp.asarray(truth)))
    y += rng.normal(0, sigma, y.shape)
    mask = rng.uniform(size=y.shape) > mask_prob
    r_inv = np.where(mask, 1.0 / sigma**2, 0.0).astype(np.float32)
    bands = BandBatch(
        y=jnp.asarray(np.where(mask, y, 0.0).astype(np.float32)),
        r_inv=jnp.asarray(r_inv),
        mask=jnp.asarray(mask),
    )
    return op, bands, x0, p_inv0


class MemoryOutput:
    """In-memory output sink (the finished ``KafkaOutputMemory``): stores
    per-parameter mean and sigma rasters keyed by timestep."""

    def __init__(self):
        self.output: Dict[datetime.datetime, Dict[str, np.ndarray]] = {}

    def dump_data(self, timestep, x, p_inv_diag, gather: PixelGather,
                  parameter_list) -> None:
        sol = {}
        for ii, param in enumerate(parameter_list):
            sol[param] = gather.scatter(np.asarray(x)[:, ii])
            if p_inv_diag is not None:
                sigma = 1.0 / np.sqrt(
                    np.maximum(np.asarray(p_inv_diag)[:, ii], 1e-30)
                )
                sol[param + "_unc"] = gather.scatter(
                    sigma.astype(np.float32)
                )
        self.output[timestep] = sol
