"""Synthetic observation source + in-memory output sink.

The reference sketched both and finished neither: ``BHRObservationsTest``
computes band data but returns nothing
(``/root/reference/kafka/input_output/observations.py:313-334``) and
``KafkaOutputMemory`` is duplicated across all three drivers
(``kafka_test.py:135-145`` etc.).  SURVEY.md §4 calls for finishing them so
a full ``run()`` is testable without rasters — this module does that.
"""

from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.types import BandBatch
from ..engine.protocols import DateObservation
from ..engine.state import PixelGather
from ..obsops.protocol import ObservationModel


class SyntheticObservations:
    """Generates observations by running a forward operator on a known
    ground-truth state trajectory + noise, with random masking.

    ``truth_fn(date) -> (ny, nx, p)`` raster of true states; observations
    are ``operator.forward(aux, truth)`` + N(0, sigma^2), inverse-variance
    ``1/sigma^2`` (the readers' convention,
    ``Sentinel2_Observations.py:174-179``).
    """

    def __init__(
        self,
        dates: Sequence[datetime.datetime],
        operator: ObservationModel,
        truth_fn,
        sigma: float = 0.01,
        aux_fn=None,
        mask_prob: float = 0.1,
        seed: int = 0,
    ):
        self._dates = list(dates)
        self.operator = operator
        self.truth_fn = truth_fn
        self.sigma = sigma
        self.aux_fn = aux_fn or (lambda date, gather: None)
        self.mask_prob = mask_prob
        self.seed = seed
        self.bands_per_observation = {
            d: operator.n_bands for d in self._dates
        }

    @property
    def dates(self):
        return self._dates

    def get_observations(self, date, gather: PixelGather) -> DateObservation:
        truth = self.truth_fn(date)  # (ny, nx, p)
        x_true = jnp.asarray(gather.gather(truth), jnp.float32)
        aux = self.aux_fn(date, gather)
        y_clean = np.asarray(self.operator.forward(aux, x_true))
        # Per-date seeding: the same date always yields the same draw, so a
        # resumed run sees identical observations to the original.
        rng = np.random.default_rng((self.seed, date.toordinal()))
        noise = rng.normal(0.0, self.sigma, y_clean.shape)
        y = (y_clean + noise).astype(np.float32)
        mask = rng.uniform(size=y.shape) > self.mask_prob
        mask &= gather.valid[None, :]
        r_inv = np.where(mask, 1.0 / self.sigma**2, 0.0).astype(np.float32)
        bands = BandBatch(
            y=jnp.asarray(np.where(mask, y, 0.0)),
            r_inv=jnp.asarray(r_inv),
            mask=jnp.asarray(mask),
        )
        return DateObservation(bands=bands, operator=self.operator, aux=aux)


def make_tip_problem(n_pix: int, seed: int = 0, sigma: float = 0.005,
                     mask_prob: float = 0.1, host: bool = False):
    """Standard synthetic TIP/two-stream assimilation problem used by the
    sharding tests, ``bench.py`` and ``__graft_entry__.py``: truth drawn
    around the TIP prior, two-stream forward + noise, random masking.

    Returns ``(operator, bands, x0, p_inv0)`` with ``x0``/``p_inv0`` the
    broadcast TIP prior (the forecast for a first-timestep assimilation).

    Constructed host-side on purpose: on a tunneled TPU client the first
    device->host copy permanently degrades every subsequent dispatch
    (~13 ms/execution, measured), so benchmark problem setup must never
    read back from the default device.  The synthetic forward runs on the
    host CPU backend; only host->device transfers touch the accelerator.
    """
    import jax

    from ..core.propagators import tip_prior_arrays
    from ..obsops.twostream import TwoStreamOperator

    op = TwoStreamOperator()
    rng = np.random.default_rng(seed)
    mean_h, _, inv_h = tip_prior_arrays()
    truth = np.clip(
        mean_h + rng.normal(0, 0.05, (n_pix, op.n_params)),
        0.05, 0.95,
    ).astype(np.float32)
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        cpu = None
    with jax.default_device(cpu):
        y = np.array(op.forward(None, jax.device_put(truth, cpu)))
    y += rng.normal(0, sigma, y.shape)
    mask = rng.uniform(size=y.shape) > mask_prob
    r_inv = np.where(mask, 1.0 / sigma**2, 0.0).astype(np.float32)
    y_masked = np.where(mask, y, 0.0).astype(np.float32)
    if host:
        # Pure-numpy variant (identical draws): for CPU-baseline consumers
        # that must not touch the accelerator at all.
        bands = BandBatch(y=y_masked, r_inv=r_inv, mask=mask)
        x0_h = np.broadcast_to(mean_h, (n_pix, op.n_params)).copy()
        p_inv0_h = np.broadcast_to(
            inv_h, (n_pix, op.n_params, op.n_params)
        ).copy()
        return op, bands, x0_h, p_inv0_h
    bands = BandBatch(
        y=jnp.asarray(y_masked),
        r_inv=jnp.asarray(r_inv),
        mask=jnp.asarray(mask),
    )
    x0 = jnp.broadcast_to(
        jnp.asarray(mean_h), (n_pix, op.n_params)
    )
    p_inv0 = jnp.broadcast_to(
        jnp.asarray(inv_h), (n_pix, op.n_params, op.n_params)
    )
    return op, bands, x0, p_inv0


def run_tip_engine(
    mesh=None,
    scan_window: int = 1,
    obs_days: Sequence[int] = (1, 3, 5, 7),
    grid_days: Sequence[int] = (0, 2, 4, 6, 8),
    mesh_lane: int = 8,
    ny: int = 12,
    nx: int = 14,
    pad_multiple: int = 128,
):
    """A complete (tiny) TIP assimilation through the PRODUCTION engine —
    ``KalmanFilter.run`` with prior-only advance, prefetch, optional
    temporal fusion and optional mesh sharding.  Shared by the engine-mesh
    parity tests and ``__graft_entry__.dryrun_multichip`` so the dryrun
    exercises exactly the code path the drivers run.

    Returns ``(kf, out, x_analysis, p_inv_analysis)``.  Observation draws
    are keyed on (seed, date): two calls see identical data, so a sharded
    and an unsharded run are directly comparable — PROVIDED both see the
    same padded batch size (noise/mask draws have shape (n_bands, n_pad)).
    When comparing against a mesh run whose device count does not divide
    ``pad_multiple``, pass the mesh run's effective padding here:
    ``np.lcm(128, n_devices * mesh_lane)``.
    """
    import jax.numpy as jnp

    from ..core.propagators import PixelPrior
    from ..engine import FixedGaussianPrior, KalmanFilter
    from ..engine.priors import TIP_PARAMETER_LIST, jrc_prior
    from ..obsops import TwoStreamOperator

    def day(i):
        return datetime.datetime(2021, 3, 1) + datetime.timedelta(days=i)

    yy, xx = np.mgrid[:ny, :nx]
    mask = (yy - ny / 2) ** 2 + (xx - nx / 2) ** 2 < (min(ny, nx) / 2.4) ** 2
    op = TwoStreamOperator()
    truth = np.broadcast_to(
        np.asarray(jrc_prior().prior.mean), mask.shape + (7,)
    ).copy()
    truth[..., 6] = 0.45
    obs = SyntheticObservations(
        dates=[day(i) for i in obs_days],
        operator=op,
        truth_fn=lambda date: truth,
        sigma=0.001,
        mask_prob=0.05,
    )
    out = MemoryOutput()
    base = jrc_prior()
    mean = np.asarray(base.prior.mean)
    sigma = np.full(7, 0.01, np.float32)
    sigma[6] = 0.5
    cov = np.diag(sigma**2).astype(np.float32)
    prior = FixedGaussianPrior(
        PixelPrior(
            mean=jnp.asarray(mean), cov=jnp.asarray(cov),
            inv_cov=jnp.asarray(np.linalg.inv(cov)),
        ),
        TIP_PARAMETER_LIST,
    )
    kf = KalmanFilter(
        obs, out, mask, TIP_PARAMETER_LIST,
        state_propagation=None, prior=prior, pad_multiple=pad_multiple,
        solver_options={"relaxation": 0.7, "max_iterations": 40},
        scan_window=scan_window, prefetch_depth=2,
        mesh=mesh, mesh_lane=mesh_lane,
    )
    kf.set_trajectory_uncertainty(np.zeros(7))
    x0, p_inv0 = prior.process_prior(None, kf.gather)
    grid = [day(i) for i in grid_days]
    x_a, _, p_inv_a = kf.run(grid, x0, None, p_inv0)
    return kf, out, x_a, p_inv_a


class MemoryOutput:
    """In-memory output sink (the finished ``KafkaOutputMemory``): stores
    per-parameter mean and sigma rasters keyed by timestep."""

    def __init__(self):
        self.output: Dict[datetime.datetime, Dict[str, np.ndarray]] = {}

    def dump_data(self, timestep, x, p_inv_diag, gather: PixelGather,
                  parameter_list) -> None:
        sol = self.output.setdefault(timestep, {})
        for ii, param in enumerate(parameter_list):
            sol[param] = gather.scatter(np.asarray(x)[:, ii])
            if p_inv_diag is not None:
                sigma = 1.0 / np.sqrt(
                    np.maximum(np.asarray(p_inv_diag)[:, ii], 1e-30)
                )
                sol[param + "_unc"] = gather.scatter(
                    sigma.astype(np.float32)
                )

    def dump_qa(self, timestep, verdicts, gather: PixelGather) -> None:
        """Per-pixel solve-health QA bitmask raster (the in-memory
        equivalent of GeoTIFFOutput's ``solver_qa`` band)."""
        self.output.setdefault(timestep, {})["solver_qa"] = \
            gather.scatter(np.asarray(verdicts).astype(np.uint8))
