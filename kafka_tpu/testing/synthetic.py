"""Synthetic observation source + in-memory output sink.

The reference sketched both and finished neither: ``BHRObservationsTest``
computes band data but returns nothing
(``/root/reference/kafka/input_output/observations.py:313-334``) and
``KafkaOutputMemory`` is duplicated across all three drivers
(``kafka_test.py:135-145`` etc.).  SURVEY.md §4 calls for finishing them so
a full ``run()`` is testable without rasters — this module does that.
"""

from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.types import BandBatch
from ..engine.protocols import DateObservation
from ..engine.state import PixelGather
from ..obsops.protocol import ObservationModel


class SyntheticObservations:
    """Generates observations by running a forward operator on a known
    ground-truth state trajectory + noise, with random masking.

    ``truth_fn(date) -> (ny, nx, p)`` raster of true states; observations
    are ``operator.forward(aux, truth)`` + N(0, sigma^2), inverse-variance
    ``1/sigma^2`` (the readers' convention,
    ``Sentinel2_Observations.py:174-179``).
    """

    def __init__(
        self,
        dates: Sequence[datetime.datetime],
        operator: ObservationModel,
        truth_fn,
        sigma: float = 0.01,
        aux_fn=None,
        mask_prob: float = 0.1,
        seed: int = 0,
    ):
        self._dates = list(dates)
        self.operator = operator
        self.truth_fn = truth_fn
        self.sigma = sigma
        self.aux_fn = aux_fn or (lambda date, gather: None)
        self.mask_prob = mask_prob
        self.seed = seed
        self.bands_per_observation = {
            d: operator.n_bands for d in self._dates
        }

    @property
    def dates(self):
        return self._dates

    def get_observations(self, date, gather: PixelGather) -> DateObservation:
        truth = self.truth_fn(date)  # (ny, nx, p)
        x_true = jnp.asarray(gather.gather(truth), jnp.float32)
        aux = self.aux_fn(date, gather)
        y_clean = np.asarray(self.operator.forward(aux, x_true))
        # Per-date seeding: the same date always yields the same draw, so a
        # resumed run sees identical observations to the original.
        rng = np.random.default_rng((self.seed, date.toordinal()))
        noise = rng.normal(0.0, self.sigma, y_clean.shape)
        y = (y_clean + noise).astype(np.float32)
        mask = rng.uniform(size=y.shape) > self.mask_prob
        mask &= gather.valid[None, :]
        r_inv = np.where(mask, 1.0 / self.sigma**2, 0.0).astype(np.float32)
        bands = BandBatch(
            y=jnp.asarray(np.where(mask, y, 0.0)),
            r_inv=jnp.asarray(r_inv),
            mask=jnp.asarray(mask),
        )
        return DateObservation(bands=bands, operator=self.operator, aux=aux)


def make_tip_problem(n_pix: int, seed: int = 0, sigma: float = 0.005,
                     mask_prob: float = 0.1, host: bool = False):
    """Standard synthetic TIP/two-stream assimilation problem used by the
    sharding tests, ``bench.py`` and ``__graft_entry__.py``: truth drawn
    around the TIP prior, two-stream forward + noise, random masking.

    Returns ``(operator, bands, x0, p_inv0)`` with ``x0``/``p_inv0`` the
    broadcast TIP prior (the forecast for a first-timestep assimilation).

    Constructed host-side on purpose: on a tunneled TPU client the first
    device->host copy permanently degrades every subsequent dispatch
    (~13 ms/execution, measured), so benchmark problem setup must never
    read back from the default device.  The synthetic forward runs on the
    host CPU backend; only host->device transfers touch the accelerator.
    """
    import jax

    from ..core.propagators import tip_prior_arrays
    from ..obsops.twostream import TwoStreamOperator

    op = TwoStreamOperator()
    rng = np.random.default_rng(seed)
    mean_h, _, inv_h = tip_prior_arrays()
    truth = np.clip(
        mean_h + rng.normal(0, 0.05, (n_pix, op.n_params)),
        0.05, 0.95,
    ).astype(np.float32)
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        cpu = None
    with jax.default_device(cpu):
        y = np.array(op.forward(None, jax.device_put(truth, cpu)))
    y += rng.normal(0, sigma, y.shape)
    mask = rng.uniform(size=y.shape) > mask_prob
    r_inv = np.where(mask, 1.0 / sigma**2, 0.0).astype(np.float32)
    y_masked = np.where(mask, y, 0.0).astype(np.float32)
    if host:
        # Pure-numpy variant (identical draws): for CPU-baseline consumers
        # that must not touch the accelerator at all.
        bands = BandBatch(y=y_masked, r_inv=r_inv, mask=mask)
        x0_h = np.broadcast_to(mean_h, (n_pix, op.n_params)).copy()
        p_inv0_h = np.broadcast_to(
            inv_h, (n_pix, op.n_params, op.n_params)
        ).copy()
        return op, bands, x0_h, p_inv0_h
    bands = BandBatch(
        y=jnp.asarray(y_masked),
        r_inv=jnp.asarray(r_inv),
        mask=jnp.asarray(mask),
    )
    x0 = jnp.broadcast_to(
        jnp.asarray(mean_h), (n_pix, op.n_params)
    )
    p_inv0 = jnp.broadcast_to(
        jnp.asarray(inv_h), (n_pix, op.n_params, op.n_params)
    )
    return op, bands, x0, p_inv0


class MemoryOutput:
    """In-memory output sink (the finished ``KafkaOutputMemory``): stores
    per-parameter mean and sigma rasters keyed by timestep."""

    def __init__(self):
        self.output: Dict[datetime.datetime, Dict[str, np.ndarray]] = {}

    def dump_data(self, timestep, x, p_inv_diag, gather: PixelGather,
                  parameter_list) -> None:
        sol = {}
        for ii, param in enumerate(parameter_list):
            sol[param] = gather.scatter(np.asarray(x)[:, ii])
            if p_inv_diag is not None:
                sigma = 1.0 / np.sqrt(
                    np.maximum(np.asarray(p_inv_diag)[:, ii], 1e-30)
                )
                sol[param + "_unc"] = gather.scatter(
                    sigma.astype(np.float32)
                )
        self.output[timestep] = sol
