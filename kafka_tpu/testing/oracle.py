"""NumPy/SciPy oracle of the reference solver path.

A faithful re-implementation (NOT a copy) of the reference's sparse
block-diagonal math, used for two things:

1. numerical parity tests of the batched JAX kernels (the reference's own
   unit tests were broken at import — SURVEY.md §4 — so these oracles are the
   executable spec), and
2. the measured CPU baseline for ``bench.py`` — the reference publishes no
   numbers (SURVEY.md §6), so the baseline protocol is to *measure* this
   SuperLU path and compare pixels/sec.

Formulas mirrored:
 - normal equations + splu solve: ``/root/reference/kafka/inference/solvers.py:100-145``
 - relinearisation shift: ``solvers.py:95``
 - convergence loop: ``linear_kf.py:245-307`` (tol 1e-3, min 2, bail >25)
 - information propagation: ``kf_tools.py:208-245`` and ``:247-289``
 - prior blending: ``kf_tools.py:75-96``
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import numpy as np
import scipy.sparse as sp
import scipy.sparse.linalg as spl


def build_sparse_h(jac: np.ndarray) -> sp.csr_matrix:
    """Pack a batched Jacobian (n_pix, p) for one band into the reference's
    sparse layout: row i touches columns [i*p, (i+1)*p)
    (``inference/utils.py:193-215``)."""
    n_pix, p = jac.shape
    rows = np.repeat(np.arange(n_pix), p)
    cols = np.arange(n_pix * p)
    return sp.csr_matrix(
        (jac.ravel(), (rows, cols)), shape=(n_pix, n_pix * p)
    )


def block_diag_dense(blocks: np.ndarray) -> sp.csr_matrix:
    """(n_pix, p, p) -> sparse block-diagonal, reference state layout."""
    return sp.block_diag(list(blocks), format="csc")


def sparse_multiband_solve(
    h0_b: Sequence[np.ndarray],
    jac_b: Sequence[np.ndarray],
    y_b: Sequence[np.ndarray],
    r_inv_b: Sequence[np.ndarray],
    mask_b: Sequence[np.ndarray],
    x_lin: np.ndarray,
    x_forecast: np.ndarray,
    p_inv_blocks: np.ndarray,
) -> Tuple[np.ndarray, sp.spmatrix]:
    """One linearised multiband update via sparse splu, mirroring
    ``variational_kalman_multiband`` (``solvers.py:100-145``).

    All band inputs are per-pixel dense arrays; the masked-obs convention is
    the reference's: ``y`` is zeroed where masked and the uncertainty row is
    zeroed before inversion, so masked rows have R^-1 = 0 contribution.
    Returns the flat interleaved analysis state and the sparse Hessian A.
    """
    x_forecast = np.asarray(x_forecast).ravel()
    h_rows, y_rows, r_rows = [], [], []
    x_lin_flat = np.asarray(x_lin).ravel()
    for h0, jac, y, r_inv, mask in zip(h0_b, jac_b, y_b, r_inv_b, mask_b):
        h_sp = build_sparse_h(jac)
        y_shift = np.where(mask, y, 0.0) + h_sp.dot(x_lin_flat) - h0
        h_rows.append(h_sp)
        y_rows.append(y_shift)
        r_rows.append(np.where(mask, r_inv, 0.0))
    big_h = sp.vstack(h_rows).tocsr()
    big_r = sp.diags(np.hstack(r_rows))
    big_y = np.hstack(y_rows)
    p_inv = block_diag_dense(p_inv_blocks)
    a = (big_h.T.dot(big_r).dot(big_h) + p_inv).astype(np.float32)
    b = (
        big_h.T.dot(big_r).dot(big_y) + p_inv.dot(x_forecast)
    ).astype(np.float32)
    lu = spl.splu(a.tocsc())
    x = lu.solve(b)
    return x, a


def iterated_sparse_solve(
    linearize: Callable[[np.ndarray], Tuple[List[np.ndarray], List[np.ndarray]]],
    y_b: Sequence[np.ndarray],
    r_inv_b: Sequence[np.ndarray],
    mask_b: Sequence[np.ndarray],
    x_forecast: np.ndarray,
    p_inv_blocks: np.ndarray,
    tol: float = 1e-3,
    min_iterations: int = 2,
    max_iterations: int = 25,
) -> Tuple[np.ndarray, sp.spmatrix, int]:
    """The reference's Gauss-Newton loop (``linear_kf.py:245-307``) around
    the sparse solve.  ``linearize(x)`` returns per-band ``(h0_b, jac_b)``
    evaluated on the (n_pix, p) state."""
    n_params = p_inv_blocks.shape[-1]
    x_prev = x_forecast.ravel().copy()
    n_iter = 1
    while True:
        h0_b, jac_b = linearize(x_prev.reshape(-1, n_params))
        x_new, a = sparse_multiband_solve(
            h0_b, jac_b, y_b, r_inv_b, mask_b,
            x_prev.reshape(-1, n_params), x_forecast, p_inv_blocks,
        )
        norm = np.linalg.norm(x_new - x_prev) / float(len(x_new))
        if (norm < tol and n_iter >= min_iterations) or n_iter > max_iterations:
            return x_new, a, n_iter
        x_prev = x_new.copy()
        n_iter += 1


def propagate_information_filter_np(p_inv_blocks: np.ndarray,
                                    q_diag: np.ndarray) -> np.ndarray:
    """Exact information propagation oracle (``kf_tools.py:208-245``):
    solve ``(I + P_inv Q) X = P_inv`` blockwise with dense LAPACK."""
    out = np.empty_like(p_inv_blocks)
    p = p_inv_blocks.shape[-1]
    q = np.diag(np.broadcast_to(q_diag, (p,)))
    for i, blk in enumerate(p_inv_blocks):
        out[i] = np.linalg.solve(np.eye(p) + blk @ q, blk)
    return out


def blend_prior_np(prior_mean, prior_inv_blocks, x_forecast, p_inv_blocks):
    """Prior blending oracle preserving the reference's operand pairing
    (``kf_tools.py:89-94``)."""
    a = block_diag_dense(p_inv_blocks + prior_inv_blocks)
    b = (
        block_diag_dense(p_inv_blocks).dot(prior_mean.ravel())
        + block_diag_dense(prior_inv_blocks).dot(x_forecast.ravel())
    ).astype(np.float32)
    lu = spl.splu(a.tocsc())
    return lu.solve(b), a


def rts_smoother_np(
    x_analysis: np.ndarray,
    p_analysis_inverse: np.ndarray,
    x_forecast: np.ndarray,
    p_forecast_inverse: np.ndarray,
    m_matrix: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Dense float64 fixed-interval RTS smoother oracle, per pixel.

    Textbook covariance-form backward recursion over T filter steps:
    ``G(t) = P_a(t) M^T P_f(t+1)^-1``,
    ``x_s(t) = x_a(t) + G(t)(x_s(t+1) - x_f(t+1))``,
    ``P_s(t) = P_a(t) + G(t)(P_s(t+1) - P_f(t+1))G(t)^T``,
    anchored at ``x_s(T-1) = x_a(T-1)``.  Inputs are stacked
    ``(T, n, p)`` / ``(T, n, p, p)`` in INFORMATION form (what the
    checkpoint chain stores); ``x_forecast``/``p_forecast_inverse`` hold
    the forecast AT each step (index 0 is unused by the recursion).
    Returns ``(x_smoothed, p_smoothed)`` stacked the same way — the
    executable spec the jitted ``smoother.rts_pass`` sweep is pinned
    against in the linear regime.
    """
    t_total, n_pix, p = x_analysis.shape
    x_s = np.empty((t_total, n_pix, p), np.float64)
    p_s = np.empty((t_total, n_pix, p, p), np.float64)
    m = np.asarray(m_matrix, np.float64)
    p_a = np.linalg.inv(np.asarray(p_analysis_inverse, np.float64))
    p_f = np.linalg.inv(np.asarray(p_forecast_inverse, np.float64))
    x_s[-1] = x_analysis[-1]
    p_s[-1] = p_a[-1]
    for t in range(t_total - 2, -1, -1):
        for i in range(n_pix):
            gain = p_a[t, i] @ m.T @ np.linalg.inv(p_f[t + 1, i])
            x_s[t, i] = x_analysis[t, i] + gain @ (
                x_s[t + 1, i] - x_forecast[t + 1, i]
            )
            p_s[t, i] = p_a[t, i] + gain @ (
                p_s[t + 1, i] - p_f[t + 1, i]
            ) @ gain.T
    return x_s, p_s
