"""Shipped micro-raster fixtures (the Barrax-mask pattern, SURVEY.md §4).

The reference ships ``Barrax_pivots.tif`` — a 235x204 uint8 mask of five
centre-pivot irrigation fields on a 10 m UTM grid — as its only raster
fixture.  ``make_pivot_mask`` generates the same *kind* of artifact
procedurally (circular pivot fields on a UTM grid) so tests and demos need
no binary blobs in the repo.
"""

from __future__ import annotations

import numpy as np

from ..io.geotiff import GeoInfo, write_geotiff

# A Barrax-like footprint: 10 m pixels, UTM zone 30N.
DEFAULT_GEO = GeoInfo(
    geotransform=(576000.0, 10.0, 0.0, 4325000.0, 0.0, -10.0),
    projection="WGS 84 / UTM zone 30N",
    epsg=32630,
)


def make_pivot_mask(ny: int = 204, nx: int = 235, n_pivots: int = 5,
                    seed: int = 0) -> np.ndarray:
    """Boolean mask of circular 'pivot fields' scattered over the scene."""
    rng = np.random.default_rng(seed)
    mask = np.zeros((ny, nx), bool)
    yy, xx = np.mgrid[:ny, :nx]
    for _ in range(n_pivots):
        r = rng.integers(min(ny, nx) // 12, min(ny, nx) // 6)
        cy = rng.integers(r, ny - r)
        cx = rng.integers(r, nx - r)
        mask |= (yy - cy) ** 2 + (xx - cx) ** 2 < r**2
    return mask


def write_pivot_mask(path: str, ny: int = 204, nx: int = 235,
                     n_pivots: int = 5, seed: int = 0) -> np.ndarray:
    mask = make_pivot_mask(ny, nx, n_pivots, seed)
    write_geotiff(path, mask.astype(np.uint8), DEFAULT_GEO)
    return mask


_S2_METADATA_XML = """<?xml version="1.0"?>
<granule><Geometric_Info><Tile_Angles>
  <Mean_Sun_Angle>
    <ZENITH_ANGLE>{sza}</ZENITH_ANGLE><AZIMUTH_ANGLE>{saa}</AZIMUTH_ANGLE>
  </Mean_Sun_Angle>
  <Mean_Viewing_Incidence_Angle_List>
    <Mean_Viewing_Incidence_Angle bandId="0">
      <ZENITH_ANGLE>{vza}</ZENITH_ANGLE><AZIMUTH_ANGLE>{vaa}</AZIMUTH_ANGLE>
    </Mean_Viewing_Incidence_Angle>
  </Mean_Viewing_Incidence_Angle_List>
</Tile_Angles></Geometric_Info></granule>
"""


def make_s2_granule_tree(
    root: str,
    dates,
    truth_state=None,
    ny: int = 64,
    nx: int = 64,
    geo: GeoInfo = DEFAULT_GEO,
    noise: float = 0.0,
    seed: int = 0,
    angles=(30.5, 150.0, 5.0, 100.0),
    dtype=np.float32,
):
    """Write a Sentinel-2 granule tree (``YYYY/MM/DD/granule/``) whose
    10-band reflectances are the PROSAIL forward model evaluated at
    ``truth_state`` — physically consistent data for end-to-end driver
    tests, replacing the private ``/data/nemesis`` trees of the reference
    (``kafka_test_S2.py:151``).  Returns the truth state used.

    ``dtype=np.uint16`` writes DN bands as real S2 L2A products are
    encoded (half the bytes of float32) — use for at-scale benchmarks."""
    import datetime as _dt
    import os

    import jax.numpy as jnp
    import numpy as np

    from ..obsops.prosail import ProsailAux, ProsailOperator

    rng = np.random.default_rng(seed)
    op = ProsailOperator()
    if truth_state is None:
        from ..engine.priors import sail_prior

        truth_state = np.asarray(sail_prior().prior.mean).copy()
        truth_state[6] = np.exp(-3.0 / 2.0)  # LAI 3
    truth_state = np.asarray(truth_state, np.float32)
    sza, saa, vza, vaa = angles
    aux = ProsailAux(
        sza=jnp.asarray(sza), vza=jnp.asarray(vza),
        raa=jnp.asarray(vaa - saa),
    )
    brf = np.asarray(op.forward(aux, jnp.asarray(truth_state)[None, :]))
    brf = brf[:, 0]  # (10,)
    from ..io.sentinel2 import BAND_MAP

    for date in dates:
        gran = os.path.join(
            root, f"{date.year}", f"{date.month}", f"{date.day}",
            "S2_SYNTH_GRANULE",
        )
        os.makedirs(gran, exist_ok=True)
        for bi, b in enumerate(BAND_MAP):
            field = np.full((ny, nx), brf[bi], np.float32)
            if noise > 0:
                field = field + rng.normal(
                    0, noise, field.shape
                ).astype(np.float32)
            dn = np.clip(field, 1e-4, 1.0) * 10000.0
            if np.dtype(dtype).kind == "u":
                dn = np.round(dn)
            write_geotiff(
                os.path.join(gran, f"B{b}_sur.tif"),
                dn.astype(dtype), geo,
                predictor=2 if np.dtype(dtype).kind in "ui" else 1,
            )
        write_geotiff(
            os.path.join(gran, "synth_aot.tif"),
            np.ones((ny, nx), np.float32), geo,
        )
        with open(os.path.join(gran, "metadata.xml"), "w") as f:
            f.write(_S2_METADATA_XML.format(sza=sza, saa=saa, vza=vza,
                                            vaa=vaa))
    return truth_state


def make_mod09_granules(
    dirpath: str,
    dates,
    truth_weights=None,
    ny: int = 32,
    nx: int = 32,
    geo: GeoInfo = DEFAULT_GEO,
    noise: float = 0.0,
    seed: int = 0,
    angles=None,
):
    """Write MOD09GA-style granule directories whose 7-band reflectances
    are the Ross-Li kernel model evaluated at ``truth_weights`` under each
    date's geometry — the physically consistent stand-in for real HDF4
    granules (``/root/reference/kafka/input_output/observations.py:89-147``).

    ``ny, nx`` is the 1 km grid; reflectance rasters are written at the
    2x 500 m resolution.  ``angles`` maps each date to
    ``(sza, saa, vza, vaa)`` degrees (a default sweep is used when None).
    Returns the ``(21,)`` truth kernel-weight state.
    """
    import os

    import numpy as np

    from ..obsops.kernels import ross_li_kernels

    rng = np.random.default_rng(seed)
    if truth_weights is None:
        # Plausible MODIS land-band weights: moderate iso, smaller vol/geo.
        iso = np.array([0.05, 0.3, 0.04, 0.06, 0.25, 0.2, 0.1])
        truth_weights = np.stack(
            [iso, 0.4 * iso, 0.15 * iso], axis=1
        ).reshape(-1)
    truth_weights = np.asarray(truth_weights, np.float32)
    w = truth_weights.reshape(7, 3)
    for di, date in enumerate(dates):
        if angles is not None:
            sza, saa, vza, vaa = angles[di]
        else:  # sweep geometry so the kernel weights are identifiable
            sza, saa = 25.0 + 3.0 * di, 140.0
            vza, vaa = 10.0 + 5.0 * (di % 4), 140.0 + 30.0 * (di % 3)
        gran = os.path.join(dirpath, f"MOD09GA.A{date.strftime('%Y%j')}")
        os.makedirs(gran, exist_ok=True)
        k_vol, k_geo = ross_li_kernels(sza, vza, vaa - saa)
        k_vol, k_geo = float(k_vol), float(k_geo)
        for band in range(7):
            refl = w[band, 0] + k_vol * w[band, 1] + k_geo * w[band, 2]
            field = np.full((2 * ny, 2 * nx), refl, np.float32)
            if noise > 0:
                field = field + rng.normal(0, noise, field.shape)
            write_geotiff(
                os.path.join(gran, f"sur_refl_b{band + 1:02d}.tif"),
                np.clip(field * 10000.0, 1.0, 16000.0).astype(np.int16),
                geo,
            )
        write_geotiff(  # QA word 8 = clear sky, no shadow, land
            os.path.join(gran, "state_1km.tif"),
            np.full((ny, nx), 8, np.uint16), geo,
        )
        for name, deg in (
            ("SolarZenith_1", sza), ("SolarAzimuth_1", saa),
            ("SensorZenith_1", vza), ("SensorAzimuth_1", vaa),
        ):
            write_geotiff(
                os.path.join(gran, name + ".tif"),
                np.full((ny, nx), round(deg * 100), np.int16), geo,
            )
    return truth_weights


def make_synergy_series(
    dirpath: str,
    dates,
    truth_bhr=None,
    ny: int = 32,
    nx: int = 32,
    geo: GeoInfo = DEFAULT_GEO,
    kernel_unc: float = 0.005,
    stem: str = "SYN.h17v05",
):
    """Write a Synergy kernel-weight series (per-band weights + unc + mask
    GeoTIFFs, the ``observations.py:150-170`` file layout) whose per-band
    white-sky albedo equals ``truth_bhr`` (7,).  Returns ``truth_bhr``."""
    import os

    import numpy as np

    if truth_bhr is None:
        truth_bhr = np.array([0.05, 0.3, 0.04, 0.06, 0.25, 0.2, 0.1])
    truth_bhr = np.asarray(truth_bhr, np.float64)
    os.makedirs(dirpath, exist_ok=True)
    for date in dates:
        base = os.path.join(dirpath, f"{stem}.A{date.strftime('%Y%j')}")
        for band in range(7):
            k = np.zeros((ny, nx, 3), np.float32)
            k[..., 0] = truth_bhr[band]  # iso-only => kernels . to_BHR = iso
            u = np.full((ny, nx, 3), kernel_unc, np.float32)
            write_geotiff(f"{base}_b{band}_kernel_weights.tif", k, geo)
            write_geotiff(f"{base}_b{band}_kernel_unc.tif", u, geo)
        write_geotiff(
            f"{base}_mask.tif", np.ones((ny, nx), np.uint8), geo
        )
    return truth_bhr


def make_mcd43_series(
    dirpath: str,
    dates,
    truth_state=None,
    ny: int = 64,
    nx: int = 64,
    geo: GeoInfo = DEFAULT_GEO,
    noise: float = 0.0,
    seed: int = 0,
):
    """Write an MCD43 kernel-weight series whose BHR equals the two-stream
    forward model at ``truth_state`` (iso weight = albedo, vol/geo zero, so
    ``kernels . to_BHR`` reproduces it exactly).  Returns the truth state."""
    import os

    import jax.numpy as jnp
    import numpy as np

    from ..obsops.twostream import TwoStreamOperator

    rng = np.random.default_rng(seed)
    op = TwoStreamOperator()
    if truth_state is None:
        from ..core.propagators import tip_prior

        truth_state = np.asarray(tip_prior().mean).copy()
        truth_state[6] = 0.5
    truth_state = np.asarray(truth_state, np.float32)
    albedo = np.asarray(
        op.forward(None, jnp.asarray(truth_state)[None, :])
    )[:, 0]  # (2,): vis, nir
    for date in dates:
        stem = os.path.join(dirpath, f"MCD43_A{date.strftime('%Y%j')}")
        for bi, band in enumerate(("vis", "nir")):
            k = np.zeros((ny, nx, 3), np.float32)
            k[..., 0] = albedo[bi]
            if noise > 0:
                k[..., 0] += rng.normal(0, noise, (ny, nx))
            qa = np.zeros((ny, nx), np.uint8)
            write_geotiff(f"{stem}_{band}_kernels.tif", k, geo)
            write_geotiff(f"{stem}_{band}_qa.tif", qa, geo)
    return truth_state


def make_s1_series(
    dirpath: str,
    dates,
    truth_lai: float = 3.0,
    truth_sm: float = 0.3,
    ny: int = 64,
    nx: int = 64,
    geo: GeoInfo = DEFAULT_GEO,
    theta_deg: float = 35.0,
    noise: float = 0.0,
    seed: int = 0,
):
    """Write a folder of preprocessed Sentinel-1 sigma0 NetCDFs whose VV/VH
    backscatter is the Water-Cloud Model evaluated at (``truth_lai``,
    ``truth_sm``) — physically consistent SAR data for joint-assimilation
    tests (file naming/contract of ``io.sentinel1.S1Observations``)."""
    import os

    import h5py
    import jax.numpy as jnp
    import numpy as np

    from ..obsops.wcm import WCM_PARAMETERS, wcm_sigma0

    os.makedirs(dirpath, exist_ok=True)
    rng = np.random.default_rng(seed)
    s0 = {
        pol: float(wcm_sigma0(
            jnp.asarray(truth_lai), jnp.asarray(truth_sm),
            jnp.asarray(theta_deg), WCM_PARAMETERS[pol],
        ))
        for pol in ("VV", "VH")
    }
    for date in dates:
        name = f"S1A_IW_GRDH_1SDV_pre_{date.strftime('%Y%m%dT%H%M%S')}_x_y.nc"
        with h5py.File(os.path.join(dirpath, name), "w") as f:
            f.attrs["geotransform"] = np.asarray(geo.geotransform, np.float64)
            f.attrs["epsg"] = np.int64(geo.epsg or 32630)
            for pol in ("VV", "VH"):
                field = np.full((ny, nx), s0[pol], np.float32)
                if noise > 0:
                    field = field * (
                        1.0 + rng.normal(0, noise, field.shape)
                    ).astype(np.float32)
                f.create_dataset(f"sigma0_{pol}", data=field)
            f.create_dataset(
                "theta", data=np.full((ny, nx), theta_deg, np.float32)
            )
    return s0
