"""Shipped micro-raster fixtures (the Barrax-mask pattern, SURVEY.md §4).

The reference ships ``Barrax_pivots.tif`` — a 235x204 uint8 mask of five
centre-pivot irrigation fields on a 10 m UTM grid — as its only raster
fixture.  ``make_pivot_mask`` generates the same *kind* of artifact
procedurally (circular pivot fields on a UTM grid) so tests and demos need
no binary blobs in the repo.
"""

from __future__ import annotations

import numpy as np

from ..io.geotiff import GeoInfo, write_geotiff

# A Barrax-like footprint: 10 m pixels, UTM zone 30N.
DEFAULT_GEO = GeoInfo(
    geotransform=(576000.0, 10.0, 0.0, 4325000.0, 0.0, -10.0),
    projection="WGS 84 / UTM zone 30N",
    epsg=32630,
)


def make_pivot_mask(ny: int = 204, nx: int = 235, n_pivots: int = 5,
                    seed: int = 0) -> np.ndarray:
    """Boolean mask of circular 'pivot fields' scattered over the scene."""
    rng = np.random.default_rng(seed)
    mask = np.zeros((ny, nx), bool)
    yy, xx = np.mgrid[:ny, :nx]
    for _ in range(n_pivots):
        r = rng.integers(min(ny, nx) // 12, min(ny, nx) // 6)
        cy = rng.integers(r, ny - r)
        cx = rng.integers(r, nx - r)
        mask |= (yy - cy) ** 2 + (xx - cx) ** 2 < r**2
    return mask


def write_pivot_mask(path: str, ny: int = 204, nx: int = 235,
                     n_pivots: int = 5, seed: int = 0) -> np.ndarray:
    mask = make_pivot_mask(ny, nx, n_pivots, seed)
    write_geotiff(path, mask.astype(np.uint8), DEFAULT_GEO)
    return mask
