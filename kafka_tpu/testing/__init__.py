"""Test/bench support: NumPy oracles of the reference math, synthetic
observation sources, and in-memory sinks."""

from . import oracle
from .synthetic import MemoryOutput, SyntheticObservations
