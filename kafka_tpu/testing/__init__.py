"""Test/bench support: NumPy oracles of the reference math and synthetic
data generators."""

from . import oracle
