"""Multi-process worker for distributed-backend tests.

Run as ``python -m kafka_tpu.testing.multiprocess_worker`` in N coordinated
processes.  Exercises the real multi-host bring-up path end to end — the
thing the reference only ever does against a live dask scheduler
(``/root/reference/kafka_test_Py36.py:249-255``) and which round 1 only
faked with a patched ``process_index``:

1. ``jax.distributed.initialize`` against a localhost coordinator
   (``shard.mesh.initialize_distributed``);
2. a global device mesh spanning both processes with a real cross-process
   collective (``psum`` of per-shard sums must equal the global sum);
3. ``shard.scheduler.run_chunks`` with the true ``jax.process_index()``,
   writing per-chunk outputs + ``.done`` markers into a shared directory.

Each process writes ``result_<pid>.json`` with everything the parent test
asserts on.  Exit code 0 only if all local checks pass.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)  # host:port
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--outdir", required=True)
    ap.add_argument("--devices-per-process", type=int, default=2)
    args = ap.parse_args(argv)

    # Platform must be pinned before JAX initialises.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count="
        f"{args.devices_per_process}"
    ).strip()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kafka_tpu.io.tiling import get_chunks
    from kafka_tpu.shard.mesh import initialize_distributed, make_pixel_mesh
    from kafka_tpu.shard.scheduler import run_chunks

    initialize_distributed(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.process_count() == args.num_processes, jax.process_count()
    me = jax.process_index()
    assert me == args.process_id, (me, args.process_id)

    # --- cross-process collective over the global mesh -----------------
    n_global = args.num_processes * args.devices_per_process
    assert len(jax.devices()) == n_global, len(jax.devices())
    mesh = make_pixel_mesh()  # 1-D mesh over ALL global devices
    n_pix = n_global * 8
    sharding = NamedSharding(mesh, P("pixels"))
    # Each process materialises only its addressable shards.
    global_x = jax.make_array_from_callback(
        (n_pix,), sharding,
        lambda idx: np.arange(n_pix, dtype=np.float32)[idx],
    )

    @jax.jit
    def global_sum(v):
        return jnp.sum(v)  # GSPMD inserts the cross-process reduction

    total = float(global_sum(global_x))
    expect = float(n_pix * (n_pix - 1) / 2)
    assert total == expect, (total, expect)

    # --- chunk scheduler with the real process_index -------------------
    chunks = list(get_chunks(64, 64, (32, 32)))  # 4 chunks
    ran = []

    def run_one(chunk, prefix):
        ran.append(prefix)
        with open(os.path.join(args.outdir, f"out_{prefix}.json"), "w") as f:
            json.dump({"chunk": chunk.chunk_no, "process": me}, f)

    stats = run_chunks(chunks, run_one, args.outdir)

    with open(os.path.join(args.outdir, f"result_{me}.json"), "w") as f:
        json.dump({
            "process_index": me,
            "process_count": jax.process_count(),
            "global_devices": len(jax.devices()),
            "local_devices": len(jax.local_devices()),
            "collective_sum": total,
            "collective_expected": expect,
            "chunks_run": sorted(ran),
            "stats": stats,
        }, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
