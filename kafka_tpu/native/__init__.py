"""Native (C++) runtime components, loaded via ctypes.

``rasterkit``: thread-pooled TIFF tile codec (zlib inflate/deflate +
predictor), the GDAL-stack replacement for the raster hot path.  Built on
demand with the bundled Makefile; all callers fall back to pure Python when
no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "librasterkit.so")

_DEFAULT_THREADS = min(16, os.cpu_count() or 1)


def ensure_built(quiet: bool = True) -> bool:
    """Compile librasterkit.so if missing.  Returns True when available."""
    if os.path.exists(_SO):
        return True
    try:
        subprocess.run(
            ["make", "-C", _DIR],
            check=True,
            capture_output=quiet,
        )
    except (OSError, subprocess.SubprocessError):
        # No make / no compiler / build failure: expected on minimal
        # hosts — every caller falls back to the pure-Python codec.
        return False
    return os.path.exists(_SO)


class RasterKit:
    """ctypes wrapper over librasterkit with list-of-bytes interfaces."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.rk_inflate_batch.restype = ctypes.c_int
        lib.rk_inflate_batch.argtypes = [
            ctypes.c_int64, ctypes.POINTER(u8p),
            ctypes.POINTER(ctypes.c_int64), u8p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        lib.rk_deflate_batch.restype = ctypes.c_int
        lib.rk_deflate_batch.argtypes = [
            ctypes.c_int64, ctypes.POINTER(u8p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, u8p,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        # LZW/fp3 entry points are round-3 additions: a stale pre-built
        # .so may lack them — degrade to the Python paths, don't die.
        self.has_lzw = hasattr(lib, "rk_lzw_inflate_batch")
        if self.has_lzw:
            lib.rk_lzw_inflate_batch.restype = ctypes.c_int
            lib.rk_lzw_inflate_batch.argtypes = [
                ctypes.c_int64, ctypes.POINTER(u8p),
                ctypes.POINTER(ctypes.c_int64), u8p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ]
        self.has_lzw_enc = hasattr(lib, "rk_lzw_deflate_batch")
        if self.has_lzw_enc:
            lib.rk_lzw_deflate_batch.restype = ctypes.c_int
            lib.rk_lzw_deflate_batch.argtypes = [
                ctypes.c_int64, ctypes.POINTER(u8p),
                ctypes.POINTER(ctypes.c_int64), u8p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ]
        self.has_fp3 = hasattr(lib, "rk_decode_fp3_batch")
        if not self.has_fp3:
            return
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.rk_decode_fp3_batch.restype = ctypes.c_int
        lib.rk_decode_fp3_batch.argtypes = [
            ctypes.c_int64, ctypes.POINTER(u8p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, f32p, ctypes.c_int64,
            ctypes.c_int,
        ]
        lib.rk_encode_fp3_batch.restype = ctypes.c_int
        lib.rk_encode_fp3_batch.argtypes = [
            ctypes.c_int64, f32p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]

    def _run_bytes_batch(self, segments: Sequence[bytes], stride: int,
                         entry, errmsg: str, n_threads: int,
                         allow_empty: bool = False,
                         extra_args: tuple = ()) -> List[bytes]:
        """Shared bytes-in/bytes-out batch epilogue: marshal segments,
        allocate the strided output, run ``entry``, raise on nonzero rc,
        slice per-item results.  ``extra_args`` are inserted after the
        sizes argument (the deflate entry's ``level``)."""
        n, bufs, ptrs, sizes = self._in_arrays(segments, allow_empty)
        if n == 0:
            return []
        out = ctypes.create_string_buffer(n * stride)
        out_sizes = (ctypes.c_int64 * n)()
        u8p = ctypes.POINTER(ctypes.c_uint8)
        rc = entry(
            n, ptrs, sizes, *extra_args, ctypes.cast(out, u8p), stride,
            out_sizes, n_threads,
        )
        if rc != 0:
            raise ValueError("%s (code %d)" % (errmsg, rc))
        raw = out.raw  # single copy; .raw copies the whole buffer
        return [
            raw[i * stride: i * stride + out_sizes[i]] for i in range(n)
        ]

    def lzw_inflate_many(self, segments: Sequence[bytes],
                         expected_size: int,
                         n_threads: int = _DEFAULT_THREADS
                         ) -> List[bytes]:
        """Batch TIFF-LZW decode on the worker pool (~60x the Python
        decoder per tile, times the pool width)."""
        return self._run_bytes_batch(
            segments, int(expected_size) + 16,
            self._lib.rk_lzw_inflate_batch,
            "TIFF LZW decode failed", n_threads, allow_empty=True,
        )

    def lzw_deflate_many(self, segments: Sequence[bytes],
                         n_threads: int = _DEFAULT_THREADS
                         ) -> List[bytes]:
        """Batch TIFF-LZW encode on the worker pool — bit-identical
        streams to the Python ``lzw_encode`` (same width/clear policy),
        ~4000x faster per tile."""
        if not segments:
            return []
        # Worst case: ~12 bits/code, one code per input byte, plus
        # clear/EOI overhead.
        stride = 2 * max(len(s) for s in segments) + 64
        return self._run_bytes_batch(
            segments, stride, self._lib.rk_lzw_deflate_batch,
            "TIFF LZW encode failed", n_threads, allow_empty=True,
        )

    def decode_fp3_many(self, segments: Sequence[bytes], rows: int,
                        cols: int, nb: int, compressed: bool,
                        n_threads: int = _DEFAULT_THREADS):
        """Fused float32 predictor-3 tile decode: (optional) inflate +
        fpAcc + byte unshuffle per tile, parallel over tiles.  Empty
        segments decode to zero tiles.  Returns a (n, rows, cols, nb)
        float32 array."""
        import numpy as np

        n = len(segments)
        out = np.zeros((n, rows, cols, nb), np.float32)
        if n == 0:
            return out
        n, bufs, ptrs, sizes = self._in_arrays(segments,
                                               allow_empty=True)
        stride = rows * cols * nb
        rc = self._lib.rk_decode_fp3_batch(
            n, ptrs, sizes, rows, cols, nb, int(bool(compressed)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            stride, n_threads,
        )
        if rc != 0:
            raise ValueError(
                "fp3 tile decode failed with zlib code %d" % rc
            )
        return out

    def encode_fp3_many(self, tiles, level: int = 1,
                        n_threads: int = _DEFAULT_THREADS) -> List[bytes]:
        """Fused float32 predictor-3 tile encode: fpDiff + deflate per
        tile, parallel over tiles.  ``tiles`` is a contiguous
        (n, rows, cols, nb) float32 array; returns the n compressed
        segments."""
        import numpy as np

        tiles = np.ascontiguousarray(tiles, np.float32)
        n, rows, cols, nb = tiles.shape
        if n == 0:
            return []
        rawbytes = rows * cols * nb * 4
        stride = rawbytes + rawbytes // 1000 + 64
        out = ctypes.create_string_buffer(n * stride)
        out_sizes = (ctypes.c_int64 * n)()
        u8p = ctypes.POINTER(ctypes.c_uint8)
        rc = self._lib.rk_encode_fp3_batch(
            n, tiles.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rows * cols * nb, rows, cols, nb, int(level),
            ctypes.cast(out, u8p), stride, out_sizes, n_threads,
        )
        if rc != 0:
            raise ValueError(
                "fp3 tile encode failed with zlib code %d" % rc
            )
        raw = out.raw
        return [
            raw[i * stride: i * stride + out_sizes[i]] for i in range(n)
        ]

    @staticmethod
    def _in_arrays(segments: Sequence[bytes], allow_empty: bool = False):
        n = len(segments)
        if allow_empty:
            # create_string_buffer needs size >= 1; empty segments are
            # signalled by size 0 and never dereferenced natively.
            bufs = [
                ctypes.create_string_buffer(s if s else b"\x00",
                                            max(len(s), 1))
                for s in segments
            ]
        else:
            bufs = [
                ctypes.create_string_buffer(s, len(s)) for s in segments
            ]
        u8p = ctypes.POINTER(ctypes.c_uint8)
        ptrs = (u8p * n)(
            *[ctypes.cast(b, u8p) for b in bufs]
        )
        sizes = (ctypes.c_int64 * n)(*[len(s) for s in segments])
        return n, bufs, ptrs, sizes

    def inflate_many(self, segments: Sequence[bytes],
                     expected_size: int,
                     n_threads: int = _DEFAULT_THREADS) -> List[bytes]:
        return self._run_bytes_batch(
            segments, int(expected_size), self._lib.rk_inflate_batch,
            "zlib inflate failed", n_threads,
        )

    def deflate_many(self, segments: Sequence[bytes], level: int = 6,
                     n_threads: int = _DEFAULT_THREADS) -> List[bytes]:
        if not segments:
            return []
        max_in = max(len(s) for s in segments)
        # zlib worst case: input + input/1000 + 64
        stride = max_in + max_in // 1000 + 64
        return self._run_bytes_batch(
            segments, stride, self._lib.rk_deflate_batch,
            "zlib deflate failed", n_threads, extra_args=(level,),
        )


_loaded: Optional[RasterKit] = None


def load_library() -> Optional[RasterKit]:
    """Load (building if needed) the native codec; None if unavailable."""
    global _loaded
    if _loaded is None:
        if ensure_built():
            _loaded = RasterKit(ctypes.CDLL(_SO))
        else:
            _loaded = False  # type: ignore[assignment]
    return _loaded or None
