"""Native (C++) runtime components, loaded via ctypes.

``rasterkit``: thread-pooled TIFF tile codec (zlib inflate/deflate +
predictor), the GDAL-stack replacement for the raster hot path.  Built on
demand with the bundled Makefile; all callers fall back to pure Python when
no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "librasterkit.so")

_DEFAULT_THREADS = min(16, os.cpu_count() or 1)


def ensure_built(quiet: bool = True) -> bool:
    """Compile librasterkit.so if missing.  Returns True when available."""
    if os.path.exists(_SO):
        return True
    try:
        subprocess.run(
            ["make", "-C", _DIR],
            check=True,
            capture_output=quiet,
        )
    except Exception:
        return False
    return os.path.exists(_SO)


class RasterKit:
    """ctypes wrapper over librasterkit with list-of-bytes interfaces."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.rk_inflate_batch.restype = ctypes.c_int
        lib.rk_inflate_batch.argtypes = [
            ctypes.c_int64, ctypes.POINTER(u8p),
            ctypes.POINTER(ctypes.c_int64), u8p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        lib.rk_deflate_batch.restype = ctypes.c_int
        lib.rk_deflate_batch.argtypes = [
            ctypes.c_int64, ctypes.POINTER(u8p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, u8p,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]

    @staticmethod
    def _in_arrays(segments: Sequence[bytes]):
        n = len(segments)
        bufs = [ctypes.create_string_buffer(s, len(s)) for s in segments]
        u8p = ctypes.POINTER(ctypes.c_uint8)
        ptrs = (u8p * n)(
            *[ctypes.cast(b, u8p) for b in bufs]
        )
        sizes = (ctypes.c_int64 * n)(*[len(s) for s in segments])
        return n, bufs, ptrs, sizes

    def inflate_many(self, segments: Sequence[bytes],
                     expected_size: int,
                     n_threads: int = _DEFAULT_THREADS) -> List[bytes]:
        n, bufs, ptrs, sizes = self._in_arrays(segments)
        if n == 0:
            return []
        stride = int(expected_size)
        out = ctypes.create_string_buffer(n * stride)
        out_sizes = (ctypes.c_int64 * n)()
        u8p = ctypes.POINTER(ctypes.c_uint8)
        rc = self._lib.rk_inflate_batch(
            n, ptrs, sizes, ctypes.cast(out, u8p), stride, out_sizes,
            n_threads,
        )
        if rc != 0:
            raise ValueError("zlib inflate failed with code %d" % rc)
        raw = out.raw  # single copy; .raw copies the whole buffer per access
        return [
            raw[i * stride: i * stride + out_sizes[i]] for i in range(n)
        ]

    def deflate_many(self, segments: Sequence[bytes], level: int = 6,
                     n_threads: int = _DEFAULT_THREADS) -> List[bytes]:
        n, bufs, ptrs, sizes = self._in_arrays(segments)
        if n == 0:
            return []
        max_in = max(len(s) for s in segments)
        # zlib worst case: input + input/1000 + 64
        stride = max_in + max_in // 1000 + 64
        out = ctypes.create_string_buffer(n * stride)
        out_sizes = (ctypes.c_int64 * n)()
        u8p = ctypes.POINTER(ctypes.c_uint8)
        rc = self._lib.rk_deflate_batch(
            n, ptrs, sizes, level, ctypes.cast(out, u8p), stride,
            out_sizes, n_threads,
        )
        if rc != 0:
            raise ValueError("zlib deflate failed with code %d" % rc)
        raw = out.raw  # single copy; .raw copies the whole buffer per access
        return [
            raw[i * stride: i * stride + out_sizes[i]] for i in range(n)
        ]


_loaded: Optional[RasterKit] = None


def load_library() -> Optional[RasterKit]:
    """Load (building if needed) the native codec; None if unavailable."""
    global _loaded
    if _loaded is None:
        if ensure_built():
            _loaded = RasterKit(ctypes.CDLL(_SO))
        else:
            _loaded = False  # type: ignore[assignment]
    return _loaded or None
